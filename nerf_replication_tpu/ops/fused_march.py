"""Fused ray-march mega-kernel: DDA + sampling (+ MLP + compositing).

The staged packed pipeline (renderer/packed_march.py) round-trips every
ray through HBM between stages: XLA-side coarse DDA → a materialized
``[N, K_c·r]`` candidate stream → ONE global stable sort → masked Pallas
MLP → segmented compositing. The VDB / hierarchical-ray-traversal line
(arXiv 2404.10272) shows the win comes from keeping the whole ray alive
in one kernel, and NerfAcc (arXiv 2305.04966) shows early termination is
most valuable when it short-circuits BEFORE samples are materialized —
exactly what a staged pipeline cannot do. This module fuses the march so
each program instance owns a BLOCK of rays end to end:

* **Stage (a), ``march_rays_fused``** — partial fusion: coarse DDA +
  fine gather in one kernel emitting a compacted per-ray sample stream
  ``(t, voxel, valid) [B, K]``. No global sort (compaction is per-ray,
  via the repo's broadcast-compare one-hot rank trick — no argsort, no
  scatter), no ``[N, K_c·r]`` HBM intermediate (candidates live only in
  the block's scratch). The MLP + log-space compositing still run
  outside, so ANY encoder family (hashgrid included) can ride it.
* **Stage (b), ``march_rays_fused_full``** — full fusion: the block body
  additionally frequency-encodes the surviving samples, streams them
  through the fused-MLP tile machinery (ops/fused_mlp.py
  ``_forward_tile`` — same canonical flattened-weight order), and
  composites transmittance in-kernel with early-ray-termination: the
  K-slot stream is walked in tiles of ``k_tile`` samples and a whole
  tile's matmul chain is skipped once every ray in the block has
  ``T < eps``. Frequency-encoder families only (``fused_spec_for``
  gates, same contract as the fused trunk); forward-only by design —
  it serves the eval/serve surfaces, training keeps the staged path.

Both stages keep the ``(params, rays, grid, bbox)`` executable
signature: the coarse pyramid level is derived in-graph from the fine
grid (occupancy.coarse_from_grid), so serve bucket×tier families, AOT
registrations, and the NGP carved phase adopt the kernel by flipping
``MarchOptions.march_fused`` — nothing about donation or warm-up
changes.

**Dispatch and the Mosaic gather caveat.** The block body
(:func:`_dda_block`) is ONE jnp function executed two ways: as the
production path, ``lax.map`` over ray blocks — a single fused XLA
program per block that already realizes the memory win (no global sort,
no cross-block intermediates) — and as a Pallas kernel
(``force_pallas=True``, tier-1-covered via ``interpret=``) whose TPU
lowering is staged behind the recorded Mosaic negative on in-kernel
gathers (models/encoding/pallas_hash.py): the DDA's grid lookups are
exactly such gathers, so Mosaic lowering stays off until that
restriction lifts. Sharing one body makes kernel-vs-reference parity
bitwise BY CONSTRUCTION — the tests pin it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..renderer.occupancy import (
    PYRAMID_FACTORS,
    coarse_from_grid,
    world_to_voxel,
)
from .fused_mlp import _forward_tile, _interpret, _pad_cols, _rup


def _use_pallas(force) -> bool:
    # Production dispatch is the XLA block-fused path on every platform;
    # the Pallas expression of the same body is opt-in (tests run it in
    # interpret mode) until Mosaic accepts the DDA's in-kernel gathers —
    # see the module docstring.
    return bool(force)


@dataclass(frozen=True)
class _FusedStatics:
    """Trace-time constants of one fused-march configuration."""

    resolution: int   # fine grid R
    rc: int           # coarse grid R_c (in-graph pyramid level)
    factor: int       # fine→coarse index divisor (PYRAMID_FACTORS[-1])
    r: int            # fine steps per coarse block (options.coarse_block)
    s_c: int          # coarse blocks per ray
    k_c: int          # kept-interval budget per ray
    n_steps: int      # fine march steps (S)
    k_sel: int        # per-ray sample-slot budget K = min(max_samples, C)
    compact: bool     # K < C: second per-ray compaction runs
    step: float
    near: float
    far: float
    clip: bool        # per-ray bbox-span quadrature (clip_bbox)
    threshold: float
    white_bkgd: bool

    @property
    def c_total(self) -> int:
        return self.k_c * self.r


def _statics_for(grid_res: int, rc: int, near: float, far: float,
                 options) -> _FusedStatics:
    from ..renderer.packed_march import hierarchical_caps

    if options.coarse_block <= 0:
        raise ValueError(
            "march_fused requires march_coarse_block > 0 — the fused "
            "kernel's traversal IS the hierarchical coarse DDA; a flat "
            "fused sweep would silently march every position and "
            "invalidate any A/B labeled with the fused knob"
        )
    r = options.coarse_block
    n_steps = max(math.ceil((far - near) / options.step_size - 1e-9), 1)
    s_c, k_c = hierarchical_caps(n_steps, options)
    c_total = k_c * r
    k_sel = min(options.max_samples, c_total)
    return _FusedStatics(
        resolution=int(grid_res), rc=int(rc),
        factor=PYRAMID_FACTORS[-1], r=r, s_c=s_c, k_c=k_c,
        n_steps=n_steps, k_sel=k_sel, compact=k_sel < c_total,
        step=float(options.step_size), near=float(near), far=float(far),
        clip=bool(options.clip_bbox),
        threshold=float(options.transmittance_threshold),
        white_bkgd=bool(options.white_bkgd),
    )


def _rank_compact(occ, n_slots: int, *payloads):
    """First-``n_slots`` occupied entries per row, in order, no argsort.

    ``occ [B, S]`` bool → ``(valid [B, n_slots], gathered payloads)``
    via the Mosaic-friendly broadcast-compare idiom: the exclusive rank
    ``cumsum(occ) − occ`` names each occupied entry's destination slot,
    a one-hot ``rank == slot`` compare selects it, and a reduce-sum
    extracts the payload — pure elementwise + reductions, the op mix the
    fused kernels already rely on (no sort, no scatter, no gather)."""
    occ_i = occ.astype(jnp.int32)
    rank = jnp.cumsum(occ_i, axis=-1) - occ_i  # exclusive prefix
    slots = jnp.arange(n_slots, dtype=jnp.int32)
    sel = occ[:, :, None] & (rank[:, :, None] == slots[None, None, :])
    valid = jnp.any(sel, axis=1)  # [B, n_slots]
    outs = tuple(
        jnp.sum(jnp.where(sel, p[:, :, None], 0), axis=1).astype(p.dtype)
        for p in payloads
    )
    return (valid,) + outs


def _dda_block(st: _FusedStatics, rays_blk, grid_flat, coarse_flat, bbox):
    """The shared block body: coarse DDA → interval compaction → fine
    gather → per-ray sample-slot compaction, all in one program's scratch.

    ``rays_blk [B, 6]``, flattened bool-as-int8 fine/coarse grids, bbox
    [2, 3]. Returns ``(t_sel [B, K] f32, valid [B, K] bool, flat_sel
    [B, K] i32 fine voxel ids, n_occ [B] i32, n_blk [B] i32 occupied
    coarse blocks, dist [B] f32 per-sample quadrature width)``.

    Every float expression matches renderer/packed_march.py's
    ``_hierarchical_sweep`` operation-for-operation (same world→voxel
    math at the same march positions), so the admitted candidate set —
    and with generous budgets the composited image — is parity-exact
    against the staged pipeline."""
    f32 = jnp.float32
    o, d = rays_blk[:, 0:3], rays_blk[:, 3:6]
    b = rays_blk.shape[0]

    if st.clip:
        # slab spans, exactly packed_march._ray_bbox_spans
        inv = 1.0 / jnp.where(jnp.abs(d) < 1e-12, 1e-12, d)
        t_lo = (bbox[0] - o) * inv
        t_hi = (bbox[1] - o) * inv
        tmin = jnp.max(jnp.minimum(t_lo, t_hi), axis=-1)
        tmax = jnp.min(jnp.maximum(t_lo, t_hi), axis=-1)
        t0 = jnp.clip(tmin, st.near, st.far)
        t1 = jnp.maximum(jnp.clip(tmax, st.near, st.far), t0)
        step_r = (t1 - t0) / st.n_steps  # [B]
    else:
        t0 = jnp.full((b,), st.near, f32)
        step_r = jnp.full((b,), st.step, f32)

    # coarse DDA: classify every padded march position's PARENT pyramid
    # cell (fine_vox // factor — index space, preserving the superset
    # invariant the parity contract rests on)
    s_pad = st.s_c * st.r
    s_idx = jnp.arange(s_pad, dtype=f32)
    if st.clip:
        ts = t0[:, None] + s_idx[None, :] * step_r[:, None]  # [B, S_pad]
    else:
        ts = st.near + s_idx * st.step
        ts = jnp.broadcast_to(ts, (b, s_pad))
    pts = o[:, None, :] + d[:, None, :] * ts[..., None]
    vox = world_to_voxel(pts, bbox, st.resolution)  # [B, S_pad, 3]
    cvox = vox // st.factor
    cflat = (cvox[..., 0] * st.rc + cvox[..., 1]) * st.rc + cvox[..., 2]
    coarse_occ = jnp.take(coarse_flat, cflat) > 0  # [B, S_pad]
    real = jnp.sum(d * d, axis=-1) > 0.0  # padding rays drop out
    in_range = jnp.arange(s_pad) < st.n_steps
    coarse_occ = coarse_occ & real[:, None] & in_range[None, :]
    if st.clip:
        coarse_occ = coarse_occ & (step_r > 0)[:, None]

    block_occ = coarse_occ.reshape(b, st.s_c, st.r).any(-1)  # [B, S_c]
    n_blk = jnp.sum(block_occ, axis=-1).astype(jnp.int32)

    # interval compaction: first K_c occupied blocks per ray, march order
    s_blocks = jnp.broadcast_to(
        jnp.arange(st.s_c, dtype=jnp.int32), (b, st.s_c)
    )
    bvalid, border = _rank_compact(block_occ, st.k_c, s_blocks)

    # fine gather at the C = K_c·r candidate positions of kept intervals
    s_f = border[..., None] * st.r + jnp.arange(st.r, dtype=jnp.int32)
    s_f = s_f.reshape(b, st.c_total)  # [B, C]
    cand = jnp.broadcast_to(
        bvalid[..., None], (b, st.k_c, st.r)
    ).reshape(b, st.c_total) & (s_f < st.n_steps)
    t_cand = t0[:, None] + s_f.astype(f32) * step_r[:, None]
    pts_c = o[:, None, :] + d[:, None, :] * t_cand[..., None]
    vox_c = world_to_voxel(pts_c, bbox, st.resolution)
    flat_c = (
        vox_c[..., 0] * st.resolution + vox_c[..., 1]
    ) * st.resolution + vox_c[..., 2]
    occ_c = (jnp.take(grid_flat, flat_c) > 0) & cand  # [B, C]
    n_occ = jnp.sum(occ_c, axis=-1).astype(jnp.int32)

    if st.compact:
        # second per-ray compaction: first K occupied samples per ray.
        # Statically skipped when K ≥ C (the serving configurations size
        # max_samples ≥ K_c·r, so the [B, C, K] one-hot never builds).
        valid, t_sel, flat_sel = _rank_compact(
            occ_c, st.k_sel, t_cand, flat_c
        )
    else:
        valid, t_sel, flat_sel = occ_c, t_cand, flat_c

    dist = step_r * jnp.sqrt(jnp.sum(d * d, axis=-1))  # [B] ‖d‖-scaled δ
    return t_sel, valid, flat_sel.astype(jnp.int32), n_occ, n_blk, dist


def _dda_kernel(st, rays_ref, grid_ref, coarse_ref, bbox_ref,
                t_ref, val_ref, flat_ref, nocc_ref, nblk_ref, dist_ref):
    t_sel, valid, flat_sel, n_occ, n_blk, dist = _dda_block(
        st, rays_ref[...], grid_ref[...].reshape(-1),
        coarse_ref[...].reshape(-1), bbox_ref[...],
    )
    t_ref[...] = t_sel
    val_ref[...] = valid.astype(jnp.float32)
    flat_ref[...] = flat_sel
    nocc_ref[...] = n_occ[:, None]
    nblk_ref[...] = n_blk[:, None]
    dist_ref[...] = dist[:, None]


def _dda_pallas(st: _FusedStatics, blk: int, rays_p, grid_flat,
                coarse_flat, bbox):
    n_pad = rays_p.shape[0]
    k = st.k_sel
    grid2 = grid_flat.reshape(1, -1)
    coarse2 = coarse_flat.reshape(1, -1)
    full = [
        pl.BlockSpec(grid2.shape, lambda i: (0, 0)),
        pl.BlockSpec(coarse2.shape, lambda i: (0, 0)),
        pl.BlockSpec(bbox.shape, lambda i: (0, 0)),
    ]
    outs = pl.pallas_call(
        partial(_dda_kernel, st),
        grid=(n_pad // blk,),
        in_specs=[pl.BlockSpec((blk, 6), lambda i: (i, 0))] + full,
        out_specs=[
            pl.BlockSpec((blk, k), lambda i: (i, 0)),
            pl.BlockSpec((blk, k), lambda i: (i, 0)),
            pl.BlockSpec((blk, k), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, k), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(rays_p, grid2, coarse2, bbox)
    t_sel, val_f, flat_sel, nocc, nblk, dist = outs
    return (t_sel, val_f > 0.0, flat_sel,
            nocc[:, 0], nblk[:, 0], dist[:, 0])


def fused_dda_gather(rays, near: float, far: float, grid, bbox, options,
                     force_pallas=None) -> dict:
    """Stage (a) traversal: one fused DDA + fine gather over ray blocks.

    Returns the compacted per-ray sample stream ``{t_sel [N, K], valid
    [N, K] bool, flat_sel [N, K] i32, n_occ [N], n_blk [N], dist [N]}``
    plus the statics under ``"statics"``. The peak intermediate is the
    [N, K] output stream itself — the staged path's [N, K_c·r] candidate
    arrays and [N·C] global sort keys never materialize."""
    coarse = coarse_from_grid(grid, PYRAMID_FACTORS[-1])
    st = _statics_for(grid.shape[0], coarse.shape[0], near, far, options)
    if rays.shape[-1] > 6:
        raise ValueError(
            "the fused march only supports static [N, 6] rays, got "
            f"{rays.shape[-1]} columns — time-conditioned scenes must use "
            "the chunked volume renderer"
        )
    grid_flat = grid.reshape(-1).astype(jnp.int8)
    coarse_flat = coarse.reshape(-1).astype(jnp.int8)
    bbox = jnp.asarray(bbox, jnp.float32)

    n = rays.shape[0]
    blk = min(int(options.fused_block), max(n, 1))
    n_pad = _rup(n, blk)
    rays_p = jnp.pad(rays, ((0, n_pad - n), (0, 0)))  # zero rays: unreal

    if _use_pallas(force_pallas):
        t_sel, valid, flat_sel, n_occ, n_blk, dist = _dda_pallas(
            st, blk, rays_p, grid_flat, coarse_flat, bbox
        )
    else:
        outs = jax.lax.map(
            lambda rb: _dda_block(st, rb, grid_flat, coarse_flat, bbox),
            rays_p.reshape(n_pad // blk, blk, 6),
        )
        t_sel, valid, flat_sel, n_occ, n_blk, dist = tuple(
            a.reshape((n_pad,) + a.shape[2:]) for a in outs
        )
    return {
        "t_sel": t_sel[:n], "valid": valid[:n], "flat_sel": flat_sel[:n],
        "n_occ": n_occ[:n], "n_blk": n_blk[:n], "dist": dist[:n],
        "statics": st,
    }


def _march_stats(st: _FusedStatics, n_rays: int, n_occ, n_blk) -> dict:
    """Traversal telemetry shared by both stages (packed-march keys)."""
    total_occ = jnp.sum(n_occ)
    dropped = jnp.sum(jnp.maximum(n_occ - st.k_sel, 0))
    return {
        "overflow_frac": (
            dropped.astype(jnp.float32)
            / jnp.maximum(total_occ, 1).astype(jnp.float32)
        ),
        "march_candidates": jnp.float32(n_rays * st.c_total),
        "march_samples_out": total_occ.astype(jnp.float32),
        "march_coarse_occ": (
            jnp.sum(n_blk).astype(jnp.float32) / float(n_rays * st.s_c)
        ),
    }


def march_rays_fused(
    apply_fn,
    rays: jax.Array,
    near: float,
    far: float,
    grid: jax.Array,
    bbox: jax.Array,
    options,
    return_samples: bool = False,
    force_pallas=None,
) -> dict:
    """Stage (a) renderer: fused DDA+gather → masked MLP → per-ray
    log-space compositing with ERT. Output contract matches
    ``march_rays_packed`` (maps, per-ray ``truncated``, ``overflow_frac``,
    march telemetry keys), so every routing site swaps it in unchanged.

    Truncation is PER-RAY again (like accelerated.py): a ray loses
    samples when its occupied count exceeds the K slot budget or its
    occupied coarse blocks exceed K_c — there is no global stream to
    overflow. ``apply_fn``s advertising ``supports_valid_mask`` get the
    per-slot occupancy bit streamed into the MLP kernel; unlike the
    sorted packed stream the invalid slots are interleaved, so tile-skip
    recovers less — the full-fusion stage is where dead slots stop
    costing MXU work."""
    dda = fused_dda_gather(rays, near, far, grid, bbox, options,
                           force_pallas=force_pallas)
    st: _FusedStatics = dda["statics"]
    t_sel, valid, dist = dda["t_sel"], dda["valid"], dda["dist"]
    n, k = t_sel.shape
    rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
    vf = valid.astype(jnp.float32)

    pts = rays_o[:, None, :] + rays_d[:, None, :] * t_sel[..., None]
    norm = jnp.linalg.norm(rays_d, axis=-1, keepdims=True)
    viewdirs = rays_d / jnp.maximum(norm, 1e-12)  # padding rays: finite
    if getattr(apply_fn, "supports_valid_mask", False):
        raw = apply_fn(pts, viewdirs, "fine", valid=vf)
    else:
        raw = apply_fn(pts, viewdirs, "fine")  # [N, K, 4]

    rgb = jax.nn.sigmoid(raw[..., :3])
    sigma = jax.nn.relu(raw[..., 3])
    # per-ray log-space compositing: 1 − α = exp(−σδ) makes transmittance
    # an exclusive cumsum — exactly the packed composite minus the global
    # segment bookkeeping
    tau = sigma * dist[:, None] * vf  # [N, K]
    c = jnp.cumsum(tau, axis=-1)
    trans = jnp.exp(-(c - tau))  # T BEFORE each sample
    alpha = 1.0 - jnp.exp(-tau)
    weights = trans * alpha * (trans >= st.threshold)

    rgb_map = jnp.sum(weights[..., None] * rgb, axis=-2)
    depth_map = jnp.sum(weights * t_sel, axis=-1)
    acc_map = jnp.sum(weights, axis=-1)
    if st.white_bkgd:
        rgb_map = rgb_map + (1.0 - acc_map[..., None])

    still_alive = jnp.exp(-c[:, -1]) >= st.threshold
    lost = (dda["n_occ"] > st.k_sel) | (dda["n_blk"] > st.k_c)
    out = {
        "rgb_map_f": rgb_map,
        "depth_map_f": depth_map,
        "acc_map_f": acc_map,
        "truncated": lost & still_alive,
    }
    out.update(_march_stats(st, n, dda["n_occ"], dda["n_blk"]))
    if return_samples:
        # flat [N·K] arrays, the packed march's sample-stream layout
        out["sample_flat"] = jax.lax.stop_gradient(
            dda["flat_sel"].reshape(-1)
        )
        out["sample_sigma"] = jax.lax.stop_gradient(sigma.reshape(-1))
        out["sample_valid"] = vf.reshape(-1)
    return out


def _full_block(st: _FusedStatics, spec, xyz_encoder, dir_encoder,
                k_tile: int, rays_blk, grid_flat, coarse_flat, bbox,
                flat_ws):
    """Stage (b) block body: DDA → encode → fused-MLP tiles → in-kernel
    compositing with early ray termination.

    The K-slot stream is walked in python-static tiles of ``k_tile``
    samples; each tile runs ops/fused_mlp.py's ``_forward_tile`` on
    ``[B·k_tile]`` rows (the same canonical weight chain as the fused
    trunk) and folds its weights into carried per-ray accumulators. ERT
    is two-level: per-sample weights are zeroed the moment transmittance
    crosses the threshold (bitwise the staged semantics), and a whole
    tile's encode+matmul chain is skipped via ``lax.cond`` once EVERY
    ray in the block is dead — sound because τ ≥ 0 means transmittance
    never recovers, so the skipped tiles' weights are zero by algebra."""
    t_sel, valid, _flat, n_occ, n_blk, dist = _dda_block(
        st, rays_blk, grid_flat, coarse_flat, bbox
    )
    b, k = t_sel.shape
    o, d = rays_blk[:, 0:3], rays_blk[:, 3:6]
    vf = valid.astype(jnp.float32)

    k_pad = _rup(k, k_tile)
    if k_pad != k:  # pad slots are invalid ⇒ contribute exactly zero
        t_sel = jnp.pad(t_sel, ((0, 0), (0, k_pad - k)))
        vf = jnp.pad(vf, ((0, 0), (0, k_pad - k)))

    pts = o[:, None, :] + d[:, None, :] * t_sel[..., None]
    norm = jnp.linalg.norm(d, axis=-1, keepdims=True)
    viewdirs = d / jnp.maximum(norm, 1e-12)
    d_enc = _pad_cols(
        jnp.asarray(dir_encoder(viewdirs), jnp.float32), spec.c_views_pad
    )  # [B, c_views_pad] — one encode per ray, broadcast per sample

    rgb_acc = jnp.zeros((b, 3), jnp.float32)
    depth_acc = jnp.zeros((b,), jnp.float32)
    acc_acc = jnp.zeros((b,), jnp.float32)
    c_prev = jnp.zeros((b,), jnp.float32)  # Σ τ marched so far, per ray
    ws = list(flat_ws)

    for j in range(k_pad // k_tile):
        sl = slice(j * k_tile, (j + 1) * k_tile)
        pts_j, vf_j, t_j = pts[:, sl, :], vf[:, sl], t_sel[:, sl]
        carry = (rgb_acc, depth_acc, acc_acc, c_prev)

        def _run(carry, pts_j=pts_j, vf_j=vf_j, t_j=t_j):
            rgb_acc, depth_acc, acc_acc, c_prev = carry
            x = _pad_cols(
                jnp.asarray(xyz_encoder(pts_j), jnp.float32), spec.c_in_pad
            ).reshape(b * k_tile, spec.c_in_pad)
            v = jnp.broadcast_to(
                d_enc[:, None, :], (b, k_tile, spec.c_views_pad)
            ).reshape(b * k_tile, spec.c_views_pad)
            raw8, _ = _forward_tile(spec, x, v, ws)
            raw = raw8.reshape(b, k_tile, 8)
            sigma = jax.nn.relu(raw[..., 3]) * vf_j
            rgb_j = jax.nn.sigmoid(raw[..., :3])
            tau = sigma * dist[:, None]
            cj = jnp.cumsum(tau, axis=-1)
            trans = jnp.exp(-(c_prev[:, None] + (cj - tau)))
            alpha = 1.0 - jnp.exp(-tau)
            w = trans * alpha * (trans >= st.threshold)
            return (
                rgb_acc + jnp.sum(w[..., None] * rgb_j, axis=-2),
                depth_acc + jnp.sum(w * t_j, axis=-1),
                acc_acc + jnp.sum(w, axis=-1),
                c_prev + cj[:, -1],
            )

        def _skip(carry):
            return carry

        any_alive = jnp.any(jnp.exp(-c_prev) >= st.threshold)
        rgb_acc, depth_acc, acc_acc, c_prev = jax.lax.cond(
            any_alive, _run, _skip, carry
        )

    if st.white_bkgd:
        rgb_acc = rgb_acc + (1.0 - acc_acc[..., None])
    still_alive = jnp.exp(-c_prev) >= st.threshold
    return rgb_acc, depth_acc, acc_acc, still_alive, n_occ, n_blk


def _full_kernel(conv, n_ws, rays_ref, grid_ref, coarse_ref, bbox_ref,
                 *rest):
    ws = rest[:n_ws]
    consts = rest[n_ws:-6]
    rgb_ref, depth_ref, acc_ref, alive_ref, nocc_ref, nblk_ref = rest[-6:]
    rgb, depth, acc, alive, n_occ, n_blk = conv(
        rays_ref[...], grid_ref[...].reshape(-1),
        coarse_ref[...].reshape(-1), bbox_ref[...],
        [w[...] for w in ws], [c[...] for c in consts],
    )
    rgb_ref[...] = rgb
    depth_ref[...] = depth[:, None]
    acc_ref[...] = acc[:, None]
    alive_ref[...] = alive.astype(jnp.float32)[:, None]
    nocc_ref[...] = n_occ[:, None]
    nblk_ref[...] = n_blk[:, None]


def _full_pallas(body, blk: int, rays_p, grid_flat, coarse_flat, bbox,
                 flat_ws):
    n_pad = rays_p.shape[0]
    grid2 = grid_flat.reshape(1, -1)
    coarse2 = coarse_flat.reshape(1, -1)
    # the encoders close over trace-time arrays (the frequency bands),
    # which a Pallas kernel cannot capture — trace the body once outside
    # the kernel, hoist the jaxpr's array constants into explicit kernel
    # operands, and replay the jaxpr inside with the loaded values
    closed = jax.make_jaxpr(
        lambda rb, gf, cf, bb, ws: body(rb, gf, cf, bb, tuple(ws))
    )(rays_p[:blk], grid_flat, coarse_flat, bbox, list(flat_ws))
    consts = tuple(jnp.asarray(c) for c in closed.consts)

    def conv(rb, gf, cf, bb, ws, cs):
        return tuple(
            jax.core.eval_jaxpr(closed.jaxpr, cs, rb, gf, cf, bb, *ws)
        )
    full = [
        pl.BlockSpec(grid2.shape, lambda i: (0, 0)),
        pl.BlockSpec(coarse2.shape, lambda i: (0, 0)),
        pl.BlockSpec(bbox.shape, lambda i: (0, 0)),
    ] + [
        pl.BlockSpec(w.shape, lambda i, nd=w.ndim: (0,) * nd)
        for w in list(flat_ws) + list(consts)
    ]
    col = lambda i: (i, 0)  # noqa: E731
    outs = pl.pallas_call(
        partial(_full_kernel, conv, len(flat_ws)),
        grid=(n_pad // blk,),
        in_specs=[pl.BlockSpec((blk, 6), col)] + full,
        out_specs=[
            pl.BlockSpec((blk, 3), col),
            pl.BlockSpec((blk, 1), col),
            pl.BlockSpec((blk, 1), col),
            pl.BlockSpec((blk, 1), col),
            pl.BlockSpec((blk, 1), col),
            pl.BlockSpec((blk, 1), col),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 3), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(rays_p, grid2, coarse2, bbox, *flat_ws, *consts)
    rgb, depth, acc, alive, nocc, nblk = outs
    return (rgb, depth[:, 0], acc[:, 0], alive[:, 0] > 0.0,
            nocc[:, 0], nblk[:, 0])


def march_rays_fused_full(
    spec,
    xyz_encoder,
    dir_encoder,
    branch: dict,
    rays: jax.Array,
    near: float,
    far: float,
    grid: jax.Array,
    bbox: jax.Array,
    options,
    k_tile: int | None = None,
    force_pallas=None,
) -> dict:
    """Stage (b) renderer: the whole march — DDA, sampling, frequency
    encoding, MLP trunk, compositing — in one block-fused program.

    ``spec``/``xyz_encoder``/``dir_encoder`` come from
    ``fused_spec_for(network)`` + the network's parameter-free encoders
    (build-time; unsupported families refuse there, loudly);
    ``branch = params["params"][model]`` is traced, flattened to the
    canonical kernel order inside the executable — the ``(params, rays,
    grid, bbox)`` signature is unchanged. Forward-only: eval and serve
    surfaces; training keeps the staged path (the compositing VJP would
    need the bwd tile machinery threaded through the carried state).

    ``k_tile`` sets samples per MLP tile (default sizes B·k_tile ≈ the
    fused trunk's 512-row tile); smaller tiles terminate earlier, larger
    ones amortize the weight stream."""
    coarse = coarse_from_grid(grid, PYRAMID_FACTORS[-1])
    st = _statics_for(grid.shape[0], coarse.shape[0], near, far, options)
    if rays.shape[-1] > 6:
        raise ValueError(
            "the fused march only supports static [N, 6] rays, got "
            f"{rays.shape[-1]} columns — time-conditioned scenes must use "
            "the chunked volume renderer"
        )
    grid_flat = grid.reshape(-1).astype(jnp.int8)
    coarse_flat = coarse.reshape(-1).astype(jnp.int8)
    bbox = jnp.asarray(bbox, jnp.float32)
    flat_ws = tuple(spec.flatten_params(branch))

    n = rays.shape[0]
    blk = min(int(options.fused_block), max(n, 1))
    n_pad = _rup(n, blk)
    rays_p = jnp.pad(rays, ((0, n_pad - n), (0, 0)))
    kt = int(k_tile) if k_tile else max(1, 512 // blk)

    body = partial(_full_block, st, spec, xyz_encoder, dir_encoder, kt)
    if _use_pallas(force_pallas):
        rgb, depth, acc, alive, n_occ, n_blk = _full_pallas(
            body, blk, rays_p, grid_flat, coarse_flat, bbox, flat_ws
        )
    else:
        outs = jax.lax.map(
            lambda rb: body(rb, grid_flat, coarse_flat, bbox, flat_ws),
            rays_p.reshape(n_pad // blk, blk, 6),
        )
        rgb, depth, acc, alive, n_occ, n_blk = tuple(
            a.reshape((n_pad,) + a.shape[2:]) for a in outs
        )
    rgb, depth, acc = rgb[:n], depth[:n], acc[:n]
    alive, n_occ, n_blk = alive[:n], n_occ[:n], n_blk[:n]

    lost = (n_occ > st.k_sel) | (n_blk > st.k_c)
    out = {
        "rgb_map_f": rgb,
        "depth_map_f": depth,
        "acc_map_f": acc,
        "truncated": lost & alive,
    }
    out.update(_march_stats(st, n, n_occ, n_blk))
    return out
