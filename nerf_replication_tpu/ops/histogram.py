"""Scatter-free segment reduction: dense bucket sums via sort + cumsum +
merge-extraction.

The capability seat of the reference's atomicAdd embedding backward
(src/models/encoding/hashencoder/src/hashencoder.cu:254-267): summing R
update rows into T table buckets. The XLA TPU scatter-add lowering runs
~23M rows/s serialized (BENCH_PRIMITIVES.jsonl: duplicate, sorted, AND
unique-index scatters all measure the same) — at the hash-encoder's
~1e8 rows/step that alone is seconds per step. This formulation uses only
primitives the chip runs at hundreds of M rows/s:

1. ``lax.sort`` rows by bucket id (payload = row position).
2. one wide gather to reorder the update rows, then a running ``cumsum``.
3. a second sort MERGES bucket sentinels into the sorted id stream, and a
   third (one-bit-key compaction) sort reads off each bucket's end
   position — giving, per bucket, the prefix-sum range that covers
   exactly its rows. Dense output = two wide gathers and a subtract.

Cost: ~(2R + 2(R+T)) sorted rows + 2 wide gathers + cumsum. Zero scatters
anywhere. Accumulation is f32; worst-case error of the prefix-sum
difference is ~eps * |prefix| (tested at 1e8-row-like magnitudes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def indexed_row_sum(idx: jax.Array, rows: jax.Array, num_buckets: int):
    """Return ``out[b] = sum(rows[i] for i where idx[i] == b)``.

    ``idx``: [R] int32 in [0, num_buckets). ``rows``: [R, W] (any float
    dtype; accumulated in f32). Returns [num_buckets, W] f32 — with zero
    scatter ops in the lowered program.
    """
    r = int(idx.shape[0])
    t = int(num_buckets)
    idx = idx.astype(jnp.int32)

    # 1. order rows by bucket id
    sk, order = lax.sort((idx, jnp.arange(r, dtype=jnp.int32)), num_keys=1)
    rows_s = jnp.take(rows.astype(jnp.float32), order, axis=0)
    cs = jnp.cumsum(rows_s, axis=0)
    csp = jnp.concatenate([jnp.zeros((1, rows.shape[1]), jnp.float32), cs])

    # 2. merge bucket sentinels behind their rows (tag orders equal keys)
    keys2 = jnp.concatenate([sk, jnp.arange(t, dtype=jnp.int32)])
    tags = jnp.concatenate(
        [jnp.zeros((r,), jnp.int8), jnp.ones((t,), jnp.int8)]
    )
    _, mt = lax.sort((keys2, tags), num_keys=2)

    # 3. sentinel positions, in bucket order, via stable 1-bit-key sort;
    # sentinel b sits at merged position hi(b) + b, hi(b) = #rows with
    # idx <= b
    pos = jnp.arange(r + t, dtype=jnp.int32)
    _, cpos = lax.sort(((1 - mt).astype(jnp.int32), pos), num_keys=2)
    hi = cpos[:t] - jnp.arange(t, dtype=jnp.int32)
    hi_prev = jnp.concatenate([jnp.zeros((1,), hi.dtype), hi[:-1]])
    return jnp.take(csp, hi, axis=0) - jnp.take(csp, hi_prev, axis=0)
