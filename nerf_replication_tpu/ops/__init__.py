"""TPU-native data-movement ops built from the primitives this chip runs
fast (measured, BENCH_PRIMITIVES.jsonl): sort ~330M rows/s, cumsum ~420M
rows/s, row gather ~120-170M rows/s — versus scatter-add at ~23M rows/s
regardless of sorted/unique hints. Everything here is scatter-free."""

from .histogram import indexed_row_sum

__all__ = ["indexed_row_sum"]
