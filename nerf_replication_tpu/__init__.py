"""nerf_replication_tpu — a TPU-native NeRF training & rendering framework.

A brand-new JAX/XLA/Pallas implementation with the capability surface of the
PyTorch reference `echo636/nerf-replication` (see SURVEY.md): a config-driven
plugin registry selecting dataset / network / renderer / loss / evaluator
modules per task from YAML, a Blender-synthetic ray pipeline, coarse+fine NeRF
MLPs with pluggable encoders (frequency + multiresolution hash grid), a
jittable volume renderer, an occupancy-grid accelerated ray marcher, and a
data/tensor-parallel training loop over a `jax.sharding.Mesh`.

Nothing here is a port: the compute path is pure functional JAX designed for
XLA's compilation model (static shapes, `lax` control flow, MXU-sized
matmuls), and parallelism rides XLA collectives over ICI/DCN rather than
NCCL/DDP.
"""

__version__ = "0.1.0"
