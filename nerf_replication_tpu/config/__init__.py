from .node import ConfigNode
from .config import (
    cfg_from_args,
    default_cfg,
    make_cfg,
    make_parser,
    parse_cfg,
)

__all__ = [
    "ConfigNode",
    "cfg_from_args",
    "default_cfg",
    "make_cfg",
    "make_parser",
    "parse_cfg",
]
