"""Attribute-style nested configuration node.

Plays the role of the reference's vendored yacs (`src/config/yacs.py`): a dict
subclass with attribute access, deep typed merges, immutability, and YAML
round-tripping. Written from scratch and intentionally small — the semantics we
preserve are the ones the reference's configs actually rely on:

* nested attribute access (``cfg.train.lr``),
* deep merge where dicts merge recursively and scalars overwrite,
* type coercion on merge (int→float promotion, str↔number rejection),
* ``merge_from_list`` for trailing CLI ``opts`` overrides,
* ``freeze()`` so a config can be treated as jit-static.

New keys are allowed by default (the reference sets ``new_allowed`` implicitly
by merging arbitrary YAML into the template defaults).
"""

from __future__ import annotations

import copy
from typing import Any, Iterable

import yaml

_FROZEN = "__frozen__"


class ConfigNode(dict):
    """A dict with attribute access and controlled deep merging."""

    def __init__(self, init: dict | None = None):
        super().__init__()
        object.__setattr__(self, _FROZEN, False)
        if init:
            for k, v in init.items():
                self[k] = _wrap(v)

    # -- attribute protocol -------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError:
            raise AttributeError(
                f"Config has no key {name!r}; available: {sorted(self.keys())}"
            ) from None

    def __setattr__(self, name: str, value: Any) -> None:
        if self.is_frozen():
            raise AttributeError(f"Cannot set {name!r} on a frozen ConfigNode")
        self[name] = _wrap(value)

    def __setitem__(self, key, value):
        if self.is_frozen():
            raise AttributeError(f"Cannot set {key!r} on a frozen ConfigNode")
        super().__setitem__(key, _wrap(value))

    def __delattr__(self, name: str) -> None:
        del self[name]

    def __delitem__(self, key) -> None:
        if self.is_frozen():
            raise AttributeError(f"Cannot delete {key!r} on a frozen ConfigNode")
        super().__delitem__(key)

    # dict's C-level mutators bypass __setitem__; route them through it so
    # freeze and _wrap hold for every mutation path.
    def update(self, other=(), **kw):  # type: ignore[override]
        items = other.items() if isinstance(other, dict) else other
        for k, v in items:
            self[k] = v
        for k, v in kw.items():
            self[k] = v

    def setdefault(self, key, default=None):  # type: ignore[override]
        if key not in self:
            self[key] = default
        return self[key]

    def pop(self, key, *default):  # type: ignore[override]
        if self.is_frozen():
            raise AttributeError(f"Cannot pop {key!r} from a frozen ConfigNode")
        return super().pop(key, *default)

    def popitem(self):  # type: ignore[override]
        if self.is_frozen():
            raise AttributeError("Cannot popitem from a frozen ConfigNode")
        return super().popitem()

    def clear(self):  # type: ignore[override]
        if self.is_frozen():
            raise AttributeError("Cannot clear a frozen ConfigNode")
        super().clear()

    # -- freeze -------------------------------------------------------------
    def is_frozen(self) -> bool:
        return object.__getattribute__(self, _FROZEN)

    def freeze(self) -> "ConfigNode":
        object.__setattr__(self, _FROZEN, True)
        for v in self.values():
            if isinstance(v, ConfigNode):
                v.freeze()
        return self

    def defrost(self) -> "ConfigNode":
        object.__setattr__(self, _FROZEN, False)
        for v in self.values():
            if isinstance(v, ConfigNode):
                v.defrost()
        return self

    # -- merge --------------------------------------------------------------
    def merge(self, other: dict) -> "ConfigNode":
        """Deep-merge ``other`` into self (other wins)."""
        if self.is_frozen():
            raise AttributeError("Cannot merge into a frozen ConfigNode")
        for key, new in other.items():
            if (
                key in self
                and isinstance(self[key], ConfigNode)
                and isinstance(new, dict)
            ):
                self[key].merge(new)
            elif key in self:
                self[key] = _coerce(new, self[key], key)
            else:
                self[key] = _wrap(copy.deepcopy(new))
        return self

    def merge_from_file(self, path: str) -> "ConfigNode":
        with open(path, "r") as f:
            data = yaml.safe_load(f) or {}
        return self.merge(data)

    def merge_from_list(self, opts: Iterable[Any]) -> "ConfigNode":
        """Merge ``[key1, v1, key2, v2, ...]`` with dotted keys.

        Values may be python-literal strings (``"1e-3"``, ``"[1,2]"``,
        ``"True"``) which are YAML-parsed, mirroring the reference CLI
        ``opts`` behavior.
        """
        opts = list(opts)
        if len(opts) % 2 != 0:
            raise ValueError(f"opts must be key/value pairs, got {opts}")
        for key, raw in zip(opts[0::2], opts[1::2]):
            value = _parse_literal(raw) if isinstance(raw, str) else raw
            node = self
            parts = str(key).split(".")
            for i, p in enumerate(parts[:-1]):
                if p in node and not isinstance(node[p], ConfigNode):
                    raise TypeError(
                        f"Key {'.'.join(parts[: i + 1])!r} is a scalar; cannot "
                        f"descend into it for override {key!r}"
                    )
                if p not in node:
                    node[p] = ConfigNode()
                node = node[p]
            leaf = parts[-1]
            if leaf in node:
                node[leaf] = _coerce(value, node[leaf], key)
            else:
                node[leaf] = _wrap(value)
        return self

    # -- export -------------------------------------------------------------
    def to_dict(self) -> dict:
        out = {}
        for k, v in self.items():
            out[k] = v.to_dict() if isinstance(v, ConfigNode) else copy.deepcopy(v)
        return out

    def clone(self) -> "ConfigNode":
        return ConfigNode(self.to_dict())

    def dump(self) -> str:
        return yaml.safe_dump(self.to_dict(), sort_keys=True)

    def __deepcopy__(self, memo):
        return ConfigNode(self.to_dict())

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ConfigNode({dict.__repr__(self)})"


def _parse_literal(raw: str) -> Any:
    """Parse a CLI value string: YAML first, then bare-float forms like 1e-3
    that YAML 1.1 treats as strings."""
    value = yaml.safe_load(raw)
    if isinstance(value, str):
        try:
            return float(value)
        except ValueError:
            return value
    return value


def _wrap(value: Any) -> Any:
    if isinstance(value, ConfigNode):
        return value
    if isinstance(value, dict):
        return ConfigNode(value)
    return value


_NUMERIC = (int, float)


def _coerce(new: Any, old: Any, key: str) -> Any:
    """Type-checked replacement mirroring yacs' merge coercion rules."""
    if old is None or new is None:
        return _wrap(new)
    if isinstance(old, ConfigNode) and isinstance(new, dict):
        node = old.clone()
        node.merge(new)
        return node
    # Replacing a whole subtree with a scalar (or vice versa) is an error.
    if isinstance(old, ConfigNode) != isinstance(new, dict):
        raise TypeError(
            f"Cannot merge {type(new).__name__} into {type(old).__name__} "
            f"for key {key!r}"
        )
    if isinstance(old, bool) != isinstance(new, bool):
        # bool is an int subclass; 1 -> True style coercion is allowed only
        # when the template value is bool (the reference's `white_bkgd: 1`).
        if isinstance(old, bool) and isinstance(new, int):
            return bool(new)
        raise TypeError(f"Cannot merge bool/non-bool for key {key!r}")
    if isinstance(old, _NUMERIC) and isinstance(new, _NUMERIC):
        if isinstance(old, float):
            return float(new)  # int → float promotion
        if isinstance(new, float):
            raise TypeError(
                f"Cannot merge float {new!r} into int-typed key {key!r}"
            )
        return new
    if type(old) is not type(new) and not (
        isinstance(old, (list, tuple)) and isinstance(new, (list, tuple))
    ):
        raise TypeError(
            f"Type mismatch for key {key!r}: {type(old).__name__} vs "
            f"{type(new).__name__}"
        )
    return _wrap(copy.deepcopy(new))
