"""Config construction: defaults, YAML inheritance, CLI overrides, dir layout.

Capability parity with the reference's `src/config/config.py:134-209` (global
yacs ``cfg`` + argparse built at import time), redesigned functionally: nothing
is constructed at import time, and the returned config is **frozen** so its
values can be safely closed over as jit-static constants (SURVEY.md §7 "Hard
parts": cfg keys read inside the reference's render loop become trace-time
constants here).

Schema compatibility: the YAML keys are the reference's — ``task_arg``,
``network.nerf.{W,D,skips}``, ``train.scheduler``, ``*_module`` plugin keys,
``parent_cfg`` recursive inheritance (config.py:177-188), trailing CLI ``opts``
with the ``other_opts`` sentinel cutoff (config.py:190-194), and the derived
``{task}/{scene}/{exp_name}`` output-dir layout (config.py:161-170) — so the
reference's configs port over unchanged.
"""

from __future__ import annotations

import argparse
import os
import subprocess
from typing import Sequence

import yaml

from .node import ConfigNode


def default_cfg() -> ConfigNode:
    """Template defaults covering the live capability surface (SURVEY.md §2.1)."""
    cfg = ConfigNode()

    # experiment identity / layout
    cfg.task = ""
    cfg.scene = "default"
    cfg.exp_name = "default"
    cfg.exp_name_tag = ""
    cfg.save_tag = "default"
    # accepted for config parity; device selection is JAX's
    cfg.gpus = [0]  # graftlint: ok(config-key: parity-only, never read)
    cfg.resume = True
    cfg.pretrain = ""
    # one seed feeds every stream: param init (utils/setup.py), the train
    # base key (train/trainer.py, train/ngp.py), host RNG pinning
    # (fix_random), and dataset generation (datasets/__init__.py)
    cfg.seed = 0
    # fix_random pins the host-side RNGs (random/np.random — dataset
    # generation, procedural scenes); the device path is already
    # deterministic via explicit key threading. ≙ reference train.py:25-28.
    cfg.fix_random = False
    # NaN-anomaly switch: ≙ reference train.py:23's always-on
    # set_detect_anomaly, opt-in here because jax_debug_nans disables jit
    # caching benefits on the hot path.
    cfg.debug_nans = False
    cfg.skip_eval = False
    # the reference evaluator always dumps per-view pred/gt PNGs
    # (src/evaluators/nerf.py:29-38)
    cfg.save_result = True
    cfg.clear_result = False

    # plugin registry keys — resolved through nerf_replication_tpu.registry
    # (the dataset pair is read via a computed f"{split}_dataset_module"
    # key in datasets/__init__.py, invisible to static key tracking)
    cfg.train_dataset_module = "nerf_replication_tpu.datasets.blender"  # graftlint: ok(config-key: read via computed key)
    cfg.test_dataset_module = "nerf_replication_tpu.datasets.blender"  # graftlint: ok(config-key: read via computed key)
    cfg.network_module = "nerf_replication_tpu.models.nerf.network"
    cfg.renderer_module = "nerf_replication_tpu.renderer.volume"
    cfg.loss_module = "nerf_replication_tpu.train.loss"
    cfg.evaluator_module = "nerf_replication_tpu.evaluators.nerf"

    # epoch cadence (reference config.py:77-81; log_interval only via YAML there)
    cfg.ep_iter = -1
    cfg.save_ep = 100000
    cfg.save_latest_ep = 1
    cfg.eval_ep = 1
    cfg.log_interval = 20

    cfg.task_arg = ConfigNode()

    cfg.train = ConfigNode(
        {
            "epoch": 10000,
            "batch_size": 4,
            "num_workers": 0,
            "collator": "default",
            "batch_sampler": "default",
            "sampler_meta": {},
            "shuffle": True,
            "optim": "adam",
            "lr": 5e-4,
            "eps": 1e-8,
            "weight_decay": 0.0,
            "scheduler": {
                "type": "multi_step",
                "milestones": [80, 120, 200, 240],
                "gamma": 0.5,
            },
            # observability: capture a jax.profiler xplane trace around
            # exactly [start_step, start_step + num_steps) of the hot loop
            # (obs/profiling.ProfileWindow; dir defaults to
            # <record_dir>/profile). start_step -1 = disabled.
            "profile": {"start_step": -1, "num_steps": 0, "dir": ""},
        }
    )
    cfg.test = ConfigNode(
        {
            "batch_size": 1,
            "epoch": -1,
            "collator": "default",
            "batch_sampler": "default",
            "sampler_meta": {},
        }
    )
    # eval-render routing (renderer/gate.py, render_video.py): sharded
    # sends full-image renders sequence-parallel over the mesh's data axis
    # (a pod must not render 800² images on the chief chip alone);
    # whole_img is the evaluator's full-image-metrics switch
    cfg.eval = ConfigNode({"sharded": False, "whole_img": False})

    # output roots (specialized by parse_cfg into per-experiment dirs)
    cfg.trained_model_dir = "data/trained_model"
    cfg.trained_config_dir = "data/trained_config"
    cfg.record_dir = "data/record"
    cfg.result_dir = "data/result"

    # mesh extraction (reference config.py:11-12)
    cfg.level = 32.0
    cfg.resolution = 256

    # parallelism — TPU-native addition (SURVEY.md §2.3): axis sizes for the
    # device mesh. -1 on the data axis means "all remaining devices".
    cfg.parallel = ConfigNode(
        {
            "data_axis": -1,
            "model_axis": 1,
            "mesh_axes": ["data", "model"],
            "multihost": False,
        }
    )

    # precision knobs (TPU-native: bfloat16 compute, f32 params/accumulation)
    cfg.precision = ConfigNode({"compute_dtype": "float32", "param_dtype": "float32"})

    # learned sampling (renderer/sampling.py, models/proposal.py,
    # docs/sampling.md): mode "proposal" replaces the coarse pass with a
    # small density-only MLP — S_p = n_proposal stratified proposal-net
    # samples resampled (inverse-CDF over the proposal weight histogram)
    # into S_f = n_fine fine-network points, the proposal supervised by
    # the interlevel weight-bound loss (loss_mult) next to the photometric
    # loss. anneal_iters blends the resampling PDF from uniform to the
    # proposal histogram over early training; net sizes the proposal MLP.
    cfg.sampling = ConfigNode(
        {
            "mode": "coarse_fine",   # "proposal" enables the resampler
            "n_proposal": 64,        # S_p proposal-MLP samples per ray
            "n_fine": 32,            # S_f fine-MLP samples per ray
            "anneal_iters": 1000,    # uniform->sharp PDF anneal horizon
            "loss_mult": 1.0,        # interlevel loss weight
            "net": {"D": 2, "W": 64, "freq": 5},  # proposal MLP size
        }
    )

    # render-serving engine (nerf_replication_tpu/serve, docs/serving.md):
    # shape buckets are ray-chunk sizes arbitrary request shapes pad into
    # (each rounded up to a multiple of the render chunk size), so a mixed
    # request stream never retraces; the micro-batcher coalesces pending
    # requests until max_batch_rays or max_delay_ms, whichever first; under
    # backlog, shed_queue_depths are the queue depths (requests still
    # waiting) that activate degradation tiers 1..4
    # (bf16 / reduced_k / coarse / half_res)
    cfg.serve = ConfigNode(
        {
            "buckets": [4096, 16384],
            "max_batch_rays": 16384,
            "max_delay_ms": 5.0,
            "request_timeout_s": 30.0,
            "cache_entries": 64,     # pose->image LRU slots (0 disables)
            "pose_decimals": 3,      # camera-pose quantization for cache keys
            "warmup": True,          # pre-compile every (bucket, tier) pair
            "shed_queue_depths": [4, 8, 16, 32],
        }
    )

    # multi-scene serving fleet (nerf_replication_tpu/fleet, docs/fleet.md):
    # a manifest (or scan_dir) names the scenes; the residency manager
    # keeps an LRU of device-resident scenes under hbm_budget_mb — sized
    # from real leaf nbytes — with pinned leases for in-flight batches and
    # async host->device prefetch. Both discovery knobs empty = classic
    # single-scene serving; default_scene is the request alias for the
    # engine's own checkpoint.
    cfg.fleet = ConfigNode(
        {
            "manifest": "",             # scene manifest JSON (docs/fleet.md)
            "scan_dir": "",             # or: discover scenes by directory scan
            "store_dir": "",            # or: sharded SceneStore root (index.json)
            "hbm_budget_mb": 256.0,     # resident-scene byte budget
            "prefetch": True,           # background h2d on first sight
            "verify_checksums": True,   # tree-sha256 gate on scene checkpoints
            "default_scene": "default",  # alias for the engine's own scene
            # tiered residency ladder (fleet/ladder.py): > 0 keeps a
            # host-RAM staging tier so HBM eviction demotes instead of
            # dropping (re-promotion = device_put, no disk/checksum walk);
            # TTLs expire idle bytes at each tier (0 = never)
            "staging_mb": 0.0,          # host-RAM staging budget (0 = off)
            "staging_ttl_s": 0.0,       # staged-copy expiry
            "resident_ttl_s": 0.0,      # idle HBM-resident demotion
            # per-tenant QoS (fleet/qos.py): token-bucket admission (429
            # TenantQuotaError past the rate), weighted fair batch cuts,
            # and per-tenant breakers; tenants maps name -> {rate, burst,
            # weight} overrides of the defaults
            "qos": {
                "enabled": False,
                "default_rate": 200.0,   # sustained requests/s per tenant
                "default_burst": 50.0,   # bucket capacity (burst headroom)
                "default_weight": 1.0,   # fair-batching share
                "tenants": {},
            },
        }
    )

    # AOT compile registry (nerf_replication_tpu/compile, docs/compilation.md):
    # aot routes every registered jitted entrypoint through
    # lower().compile() up front on host threads (overlapping dataset /
    # checkpoint I/O) instead of building on first dispatch; artifacts
    # additionally serializes picklable executables (serve buckets, NGP
    # eval renders) to dir — "" anchors <repo>/data/jax_cache/aot — so a
    # second process deserializes instead of compiling at all
    cfg.compile = ConfigNode(
        {
            "aot": True,
            "artifacts": True,
            "dir": "",
        }
    )

    # resilience knobs (nerf_replication_tpu/resil, docs/robustness.md):
    # bounded-backoff retry on resumable load paths, the finite-loss guard
    # + divergence rollback budget, SIGTERM preemption flush, and the
    # serve dispatch circuit breaker
    cfg.resil = ConfigNode(
        {
            "retry_attempts": 3,       # tries per load before giving up
            "retry_base_s": 0.05,      # first backoff; doubles per retry
            "retry_max_s": 2.0,        # backoff ceiling
            "finite_guard": True,      # raise on non-finite host loss
            "max_rollbacks": 2,        # divergence rollbacks before abort
            "preempt_sigterm": True,   # SIGTERM -> checkpoint flush + exit
            "breaker_threshold": 5,    # consecutive dispatch failures to open
            "breaker_cooldown_s": 5.0,  # open -> half_open probe delay
        }
    )

    # observability knobs (nerf_replication_tpu/obs, docs/observability.md):
    # request-scoped span tracing, the crash flight recorder's ring size,
    # and the latency target /healthz's SLO view is computed against
    cfg.obs = ConfigNode(
        {
            "trace": True,           # span tracing on the serve path
            "trace_ring": 256,       # flight recorder span-ring capacity
            "flight_dir": "",        # "" -> record_dir (flight_<reason>.json)
            "slo_target_ms": 100.0,  # /healthz SLO attainment target
            # multi-window multi-burn-rate alerting (obs/alerts.py), the
            # incident correlator, and the capacity ledger — serve.py
            # wires all three when enabled
            "alerts": {
                "enabled": True,
                "slo_objective": 0.99,    # latency-SLO attainment objective
                "deny_objective": 0.99,   # tenant-admission objective
                "fast_burn": 14.4,        # page threshold (x budget)
                "slow_burn": 6.0,         # ticket threshold (x budget)
                "fast_short_s": 300.0,    # page windows: 5m AND 1h
                "fast_long_s": 3600.0,
                "slow_short_s": 1800.0,   # ticket windows: 30m AND 6h
                "slow_long_s": 21600.0,
                "clear_hold_s": 60.0,     # hysteresis before an alert clears
                "orphan_grace_s": 30.0,   # span-parent arrival grace
                "orphan_rate_max": 0.05,  # orphan-span rate ticket threshold
                "thrash_per_min_max": 6.0,  # demote+repromote churn/min
                "view_window_s": 300.0,   # /healthz windowed SLO view
                "capacity_every_s": 30.0,  # capacity_snapshot cadence
            },
        }
    )

    # replica scale-out knobs (nerf_replication_tpu/scale, docs/scaleout.md):
    # mesh-sharded dispatch over the data axis, the front-door router, and
    # the supervisor's closed loop on SLO attainment / per-tenant deny rate
    cfg.scale = ConfigNode(
        {
            "enabled": False,          # supervisor loop on/off
            "min_replicas": 1,
            "max_replicas": 4,
            "out_below": 0.90,         # attainment below -> miss window
            "in_above": 0.98,          # attainment at/above -> good window
            "deny_above": 0.05,        # tenant deny rate above -> miss
            "out_windows": 2,          # consecutive misses before scale-out
            "in_windows": 5,           # consecutive goods before scale-in
            "cooldown_out_s": 30.0,
            "cooldown_in_s": 120.0,
            "heartbeat_interval_s": 2.0,
            "heartbeat_timeout_s": 10.0,  # missed beats before dead
            "drain_timeout_s": 60.0,
            # "off" = plain jit; "auto" = shard chunks over the data mesh
            # when >1 device; "force" = mesh even on one device (the
            # CPU parity-test configuration)
            "mesh": "off",
            # 2-D serving mesh [data, model] (None -> all devices on
            # data). model > 1 = model-parallel serving: params shard by
            # parallel/sharding rules (hash tables row-sharded, MLP
            # width column-parallel), so each device holds ~1/model of
            # the scene — scenes bigger than one chip's HBM budget
            # become servable (docs/scaleout.md "Model-parallel serving")
            "mesh_shape": None,
            # scene placement planner (scale/placement.py): which replica
            # holds which scene. Disabled -> the router's passive
            # affinity/least-loaded dispatch is bitwise unchanged.
            "placement": {
                "enabled": False,
                "hot_width": 2,            # replicas per hot scene (R)
                "max_width": 4,            # replication-width ceiling
                "hot_rps": 0.5,            # requests/s at/above -> hot
                "width_rps": 2.0,          # extra replica per this much heat
                "hbm_budget_bytes": 0,     # 0 -> per-replica ladder budget
                "staging_budget_bytes": 0,  # 0 -> ladder staging budget
                "replan_every_s": 10.0,    # supervisor replan cadence
                "max_moves_per_step": 4,   # move-execution rate limit
            },
        }
    )

    return cfg


def _load_yaml(path: str) -> dict:
    with open(path, "r") as f:
        return yaml.safe_load(f) or {}


def _merge_with_parents(cfg: ConfigNode, cfg_file: str, _depth: int = 0) -> None:
    """Recursive ``parent_cfg`` inheritance (reference config.py:177-188)."""
    if _depth > 16:
        raise RecursionError(f"parent_cfg chain too deep at {cfg_file}")
    data = _load_yaml(cfg_file)
    parent = data.pop("parent_cfg", None)
    if parent is not None:
        if not os.path.isabs(parent):
            # Parents are repo-root-relative in the reference; also try
            # relative to the child file and to this repo's root so configs
            # resolve regardless of cwd.
            repo_root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            for base in (
                os.getcwd(),
                os.path.dirname(os.path.abspath(cfg_file)),
                repo_root,
            ):
                cand = os.path.join(base, parent)
                if os.path.exists(cand):
                    parent = cand
                    break
        _merge_with_parents(cfg, parent, _depth + 1)
    cfg.merge(data)


def _git_describe(args_: Sequence[str]) -> str:
    try:
        out = subprocess.run(
            ["git", *args_], capture_output=True, text=True, timeout=5
        )
        return out.stdout.strip()
    # graftlint: ok(swallow: best-effort git metadata; empty value lands in run_meta)
    except Exception:
        return ""


def parse_cfg(cfg: ConfigNode, slurm_local_rank: int = 0) -> None:
    """Derive experiment name templates and output dirs (config.py:134-175)."""
    if not cfg.task:
        raise ValueError("task must be specified")

    if cfg.exp_name_tag:
        cfg.exp_name = f"{cfg.exp_name}_{cfg.exp_name_tag}"
    if "gitbranch" in cfg.exp_name:
        branch = _git_describe(["describe", "--all"])
        cfg.exp_name = cfg.exp_name.replace("gitbranch", branch[6:] or "nobranch")
    if "gitcommit" in cfg.exp_name:
        commit = _git_describe(["describe", "--tags", "--always"])
        cfg.exp_name = cfg.exp_name.replace("gitcommit", commit or "nocommit")

    exp = os.path.join(cfg.task, cfg.scene, cfg.exp_name)
    cfg.trained_model_dir = os.path.join(cfg.trained_model_dir, exp)
    cfg.trained_config_dir = os.path.join(cfg.trained_config_dir, exp)
    cfg.record_dir = os.path.join(cfg.record_dir, exp)
    cfg.result_dir = os.path.join(cfg.result_dir, exp, cfg.save_tag)
    # set for reference parity; runtime rank checks use jax.process_index()
    cfg.local_rank = slurm_local_rank  # graftlint: ok(config-key: parity-only, never read)


def make_cfg(
    cfg_file: str,
    opts: Sequence[str] = (),
    freeze: bool = True,
    default_task: str = "",
    local_rank: int = 0,
) -> ConfigNode:
    """Build a config: defaults ← parent chain ← cfg_file ← CLI opts."""
    cfg = default_cfg()
    _merge_with_parents(cfg, cfg_file)
    opts = list(opts)
    if "other_opts" in opts:  # reference's sentinel cutoff (config.py:190-194)
        opts = opts[: opts.index("other_opts")]
    cfg.merge_from_list(opts)
    if not cfg.task and default_task:
        cfg.task = default_task
    parse_cfg(cfg, slurm_local_rank=local_rank)
    if freeze:
        cfg.freeze()
    return cfg


def make_parser() -> argparse.ArgumentParser:
    """CLI surface shared by train.py / run.py (reference config.py:199-205)."""
    parser = argparse.ArgumentParser(description="nerf_replication_tpu")
    parser.add_argument("--cfg_file", default="configs/nerf/lego.yaml", type=str)
    parser.add_argument("--test", action="store_true", default=False)
    parser.add_argument("--type", type=str, default="")
    parser.add_argument("--local_rank", type=int, default=0)
    parser.add_argument("opts", default=None, nargs=argparse.REMAINDER)
    return parser


def cfg_from_args(args: argparse.Namespace, freeze: bool = True) -> ConfigNode:
    # `--type X` runs work with task-less configs (reference config.py:207-208
    # sets task="run" before the merge for exactly this case).
    return make_cfg(
        args.cfg_file,
        args.opts or (),
        freeze=freeze,
        default_task="run" if getattr(args, "type", "") else "",
        local_rank=getattr(args, "local_rank", 0),
    )
