"""Real-capture dataset: a single colmap2nerf ``transforms.json`` → ray bank.

Closes the capture→train loop the reference only gestures at: its vendored
converter (reference scripts/colmap2nerf.py:332-440) writes instant-ngp-style
transforms that nothing in its own tree consumes. Here
``scripts/colmap2nerf.py`` output is directly trainable:

* ONE ``transforms.json`` for the whole capture (no ``transforms_{split}``
  files): the split is derived by holdout — every ``test_hold``-th frame is
  test, the rest train (the LLFF ``llffhold=8`` convention).
* calibrated intrinsics ``fl_x/fl_y/cx/cy`` + ``w/h`` at the top level
  (or per frame — per-frame keys win when present), instead of
  ``camera_angle_x``; principal point and anisotropic focal are honored in
  ray generation (rays.py:get_rays_np).
* ``file_path`` entries carry their real extension and are used verbatim
  (blender.py appends ``.png`` — real captures are usually jpg).
* optional **NDC rays** for forward-facing captures (``ndc: true``):
  origins moved to the near plane and projected so t∈[0,1] sweeps
  near→infinity (rays.py:ndc_rays_np); ray-space near/far become 0/1
  regardless of cfg, matching the original NeRF's LLFF treatment.

Lens distortion (k1/k2/p1/p2) is recorded by the converter but NOT applied
here — matching instant-ngp's loader behavior of treating mildly-distorted
captures as pinhole unless images are pre-undistorted (colmap's
``image_undistorter`` is the supported path for heavy distortion).

Same contract as datasets.blender.Dataset: ``ray_bank()`` for on-device
sampling, ``image_batch(i)`` for eval, registry-loadable via
``train_dataset_module: nerf_replication_tpu.datasets.real``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

import numpy as np

from .bank import RayBankDataset
from .blender import _load_image, _resize_area, _to_rgba_uint8
from .rays import get_rays_np, ndc_rays_np


@dataclass
class Dataset(RayBankDataset):
    data_root: str
    scene: str = ""
    split: str = "train"
    transforms: str = "transforms.json"
    test_hold: int = 8
    input_ratio: float = 1.0
    ndc: bool = False
    near: float = 2.0
    far: float = 6.0

    H: int = field(init=False)
    W: int = field(init=False)
    focal: float = field(init=False)
    rays: np.ndarray = field(init=False)
    rgbs: np.ndarray = field(init=False)
    poses: np.ndarray = field(init=False)
    n_images: int = field(init=False)

    def _transforms_path(self) -> str:
        cands = [
            os.path.join(self.data_root, self.scene, self.transforms),
            os.path.join(self.data_root, self.transforms),
        ]
        for c in cands:
            if os.path.exists(c):
                return c
        raise FileNotFoundError(
            f"no {self.transforms} under {self.data_root!r} "
            f"(scene={self.scene!r}); run scripts/colmap2nerf.py first"
        )

    def __post_init__(self):
        path = self._transforms_path()
        base = os.path.dirname(path)
        with open(path, "r") as f:
            meta = json.load(f)

        frames = meta["frames"]
        hold = max(int(self.test_hold), 1)
        test_idx = set(range(0, len(frames), hold))
        if self.split == "train":
            frames = [f for i, f in enumerate(frames) if i not in test_idx]
        else:
            frames = [f for i, f in enumerate(frames) if i in test_idx]
        if not frames:
            raise ValueError(
                f"split={self.split!r} with test_hold={hold} selected no "
                f"frames out of {len(meta['frames'])}"
            )

        W0 = int(meta.get("w", 0)) or None
        H0 = int(meta.get("h", 0)) or None

        images, pose_list, ray_o, ray_d = [], [], [], []
        bank_focal = None
        for frame in frames:
            fp = frame["file_path"]
            root, ext = os.path.splitext(fp)
            if not ext:  # blender-style extensionless path
                fp = fp + ".png"
            img = _to_rgba_uint8(_load_image(os.path.join(base, fp)))
            h, w = img.shape[:2]
            if H0 is None:
                H0, W0 = h, w
            H = int(H0 * self.input_ratio)
            W = int(W0 * self.input_ratio)

            # Intrinsics carry their provenance's pixel units: per-frame keys
            # are in THIS frame's native (h, w) pixels, capture-level keys in
            # the top-level (meta h/w) pixels. Each is scaled by its own
            # units→bank factor, so a second camera's frames (different
            # native size, per-frame intrinsics) and capture-level fallbacks
            # both land in bank pixels.
            sx_f, sy_f = W / w, H / h
            sx_c, sy_c = W / W0, H / H0

            def intr(key, s_frame, s_cap, default=None):
                if key in frame:
                    return float(frame[key]) * s_frame
                if key in meta:
                    return float(meta[key]) * s_cap
                if default is None:
                    raise KeyError(f"transforms.json lacks intrinsic {key!r}")
                return default  # already bank-scale

            fl_x = intr("fl_x", sx_f, sx_c)
            fl_y = intr("fl_y", sy_f, sy_c, default=fl_x)
            cx = intr("cx", sx_f, sx_c, default=0.5 * W)
            cy = intr("cy", sy_f, sy_c, default=0.5 * H)
            if bank_focal is None:
                bank_focal = fl_x

            if (h, w) != (H, W):
                img = _resize_area(img, W, H)
            c2w = np.asarray(frame["transform_matrix"], dtype=np.float32)
            o, d = get_rays_np(H, W, fl_x, c2w, fl_y=fl_y, cx=cx, cy=cy)
            if self.ndc:
                # NDC wants the pre-projection focals of THIS capture
                o, d = ndc_rays_np(H, W, fl_x, 1.0, o, d, fl_y=fl_y)
            ray_o.append(o.reshape(-1, 3))
            ray_d.append(d.reshape(-1, 3))
            images.append(img)
            pose_list.append(c2w)

        self.H, self.W = int(H0 * self.input_ratio), int(W0 * self.input_ratio)
        # bank-scale focal of the first loaded frame (consistent with the
        # rays regardless of which camera that frame came from)
        self.focal = float(bank_focal)
        self.poses = np.stack(pose_list, 0)
        self.n_images = len(frames)
        if self.ndc:
            self.near, self.far = 0.0, 1.0

        rgba = np.stack(images, 0).astype(np.float32) / 255.0
        # white-background compositing, as the blender bank builder does
        rgb = rgba[..., :3] * rgba[..., 3:4] + (1.0 - rgba[..., 3:4])
        self.rays = np.concatenate(
            [np.concatenate(ray_o, 0), np.concatenate(ray_d, 0)], axis=-1
        ).astype(np.float32)
        self.rgbs = rgb.reshape(-1, 3).astype(np.float32)

    @classmethod
    def from_cfg(cls, cfg, split: str) -> "Dataset":
        node = cfg.train_dataset if split == "train" else cfg.test_dataset
        ndc = bool(node.get("ndc", cfg.task_arg.get("ndc", False)))
        if ndc:
            near = float(cfg.task_arg.get("near", 2.0))
            far = float(cfg.task_arg.get("far", 6.0))
            if near != 0.0 or far != 1.0:
                # the Trainer samples cfg.task_arg bounds — a mismatch would
                # silently place every sample outside the NDC [0,1] frustum
                raise ValueError(
                    "ndc=true requires task_arg.near: 0.0 and task_arg.far: "
                    f"1.0 (got near={near}, far={far}); see "
                    "configs/real/capture_ndc.yaml"
                )
        return cls(
            data_root=node.data_root,
            scene=cfg.scene,
            split=node.get("split", split),
            transforms=node.get("transforms", "transforms.json"),
            test_hold=int(node.get("test_hold", 8)),
            input_ratio=float(node.get("input_ratio", 1.0)),
            ndc=ndc,
            near=float(cfg.task_arg.get("near", 2.0)),
            far=float(cfg.task_arg.get("far", 6.0)),
        )

    # ray_bank/precrop_index_pool/__len__/image_batch/__getitem__: RayBankDataset
