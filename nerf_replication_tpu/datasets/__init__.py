"""Dataset factory: resolves the ``*_dataset_module`` plugin key.

Parity with the reference's `make_data_loader` (src/datasets/make_dataset.py:
73-100); the returned object is a Dataset exposing the ray-bank/TPU contract
rather than a torch DataLoader (see datasets.blender module docstring).
"""

from __future__ import annotations

from ..registry import load_attr


def make_dataset(cfg, split: str = "train"):
    key = "train_dataset_module" if split == "train" else "test_dataset_module"
    dataset_cls = load_attr(cfg[key], "Dataset")
    return dataset_cls.from_cfg(cfg, split)


from . import rays, sampling  # noqa: E402,F401  (re-export submodules)
