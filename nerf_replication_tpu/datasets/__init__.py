"""Dataset factory: resolves the ``*_dataset_module`` plugin key.

Parity with the reference's `make_data_loader` (src/datasets/make_dataset.py:
73-100). Two data paths exist by design (SURVEY.md §7):

* :func:`make_dataset` — the TPU hot path: a Dataset exposing ``ray_bank()``
  for on-device sampling inside the jitted step. No loader object.
* :func:`make_data_loader` — the host-side loader contract for the CLI/debug
  workflow and image-shaped tasks: sampler selection (random/sequential/
  distributed), batch-sampler selection (``default``/``image_size`` via
  ``cfg.train.batch_sampler`` + ``sampler_meta``), ``ep_iter`` iteration
  capping, a named-collator registry, and thread prefetch honoring
  ``num_workers`` (threads, not processes — the work is NumPy slicing, and
  fork-per-batch is the overhead the reference pays 0.2 s/iter for).
"""

from __future__ import annotations

from ..registry import load_attr


def make_dataset(cfg, split: str = "train"):
    key = "train_dataset_module" if split == "train" else "test_dataset_module"
    dataset_cls = load_attr(cfg[key], "Dataset")
    return dataset_cls.from_cfg(cfg, split)


class DataLoader:
    """Minimal iterable: batch sampler → __getitem__ → collate, with an
    optional ``num_workers``-thread prefetch pipeline.

    Batch entries are passed to ``dataset[entry]`` verbatim — plain indices
    from the default sampler, ``(index, h, w)`` tuples from the image_size
    sampler (the reference's dataset-side resize contract).
    """

    def __init__(self, dataset, batch_sampler, collate, num_workers: int = 0):
        self.dataset = dataset
        self.batch_sampler = batch_sampler
        self.collate = collate
        self.num_workers = int(num_workers)

    def _load(self, batch):
        return self.collate([self.dataset[entry] for entry in batch])

    def __iter__(self):
        if self.num_workers <= 0:
            for batch in self.batch_sampler:
                yield self._load(batch)
            return
        # bounded prefetch: at most num_workers+1 submitted batches in the
        # window — Executor.map would submit the WHOLE sampler eagerly and
        # buffer every finished batch regardless of consumer speed (OOM on
        # long full-image iterations)
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(self.num_workers) as pool:
            window: deque = deque()
            it = iter(self.batch_sampler)
            try:
                for batch in it:
                    window.append(pool.submit(self._load, batch))
                    # pop only past num_workers in-flight: popping at == would
                    # make depth-1 prefetch a no-op (block on the batch just
                    # submitted, nothing loading while the consumer computes)
                    if len(window) > self.num_workers:
                        yield window.popleft().result()
                while window:
                    yield window.popleft().result()
            finally:
                for fut in window:
                    fut.cancel()

    def __len__(self):
        return len(self.batch_sampler)


def make_data_loader(cfg, split: str = "train", is_distributed: bool = False,
                     max_iter: int = -1):
    """Reference-shaped loader factory (make_dataset.py:73-100)."""
    import jax

    from .collate import make_collator
    from .samplers import (
        BatchSampler,
        DistributedSampler,
        ImageSizeBatchSampler,
        IterationBasedBatchSampler,
        RandomSampler,
        SequentialSampler,
    )

    dataset = make_dataset(cfg, split)
    node = cfg.train if split == "train" else cfg.test
    n = dataset.n_images if hasattr(dataset, "n_images") else len(dataset)

    shuffle = bool(node.get("shuffle", split == "train"))
    seed = int(cfg.get("seed", 0))
    if is_distributed:
        sampler = DistributedSampler(
            n, jax.process_index(), jax.process_count(), seed=seed,
            shuffle=shuffle,
        )
    elif shuffle:
        sampler = RandomSampler(n, seed=seed)
    else:
        sampler = SequentialSampler(n)

    batch_size = int(node.get("batch_size", 1))
    kind = str(node.get("batch_sampler", "default"))
    if kind == "image_size":
        meta = node.get("sampler_meta", {}) or {}
        batch_sampler = ImageSizeBatchSampler(
            sampler, batch_size,
            min_hw=tuple(meta.get("min_hw", (256, 256))),
            max_hw=tuple(meta.get("max_hw", (480, 640))),
            divisor=int(meta.get("strides", 32)),
            seed=seed,
        )
    else:
        batch_sampler = BatchSampler(sampler, batch_size)

    if max_iter == -1:
        ep_iter = int(cfg.get("ep_iter", -1))
        max_iter = ep_iter if split == "train" else -1
    if max_iter > 0:
        batch_sampler = IterationBasedBatchSampler(batch_sampler, max_iter)

    return DataLoader(
        dataset, batch_sampler, make_collator(cfg, split),
        num_workers=int(node.get("num_workers", 0)),
    )


from . import rays, sampling  # noqa: E402,F401  (re-export submodules)
