"""Named dataset-path catalog.

Capability parity with the reference's `src/datasets/dataset_catalog.py:1-64`
(a registry mapping dataset names to argument dicts, imported by its
evaluator factory). Entries carry the data_root/split/scene arguments a
``Dataset.from_cfg`` would otherwise read from YAML.
"""

from __future__ import annotations


class DatasetCatalog:
    dataset_attrs: dict[str, dict] = {
        "BlenderTrain": {
            "data_root": "data/nerf_synthetic",
            "split": "train",
        },
        "BlenderTest": {
            "data_root": "data/nerf_synthetic",
            "split": "test",
        },
    }

    @classmethod
    def get(cls, name: str) -> dict:
        return dict(cls.dataset_attrs[name])

    @classmethod
    def register(cls, name: str, attrs: dict) -> None:
        cls.dataset_attrs[name] = dict(attrs)
