"""Light-stage / ZJU-MoCap multi-view capture dataset, TPU-native.

Capability parity with the reference's `src/datasets/light_stage.py:10-237`
(the last §2.4 component): a calibrated multi-camera rig captured over time —
``annots.npy`` with per-camera ``K/D/R/T`` and per-frame image lists,
foreground (human) masks, per-frame SMPL vertices defining a moving bbox,
and rays that carry a per-frame latent index for time-conditioned encoders
(models/encoding/dynamic.py consumes ``(x, y, z, t)``).

TPU-first redesign of the sampling path: the reference draws each batch on
the host with rejection sampling — 50% foreground rays (random pixels until
enough land on ``mask==1``) and 50% background rays (pixels inside the
projected world-bbox hull), per ``__getitem__`` call, every step
(light_stage.py:174-206). Here both candidate sets are enumerated ONCE into
a device-resident two-segment ray bank — all foreground pixels, then
background pixels resampled to the same count — so the jitted trainer's
uniform bank draw yields the same 50/50 fg/bg mixture in expectation with
zero per-step host work and no rejection loops.

Matches the reference's mask handling: a ``border``-px erode/dilate band
around the silhouette is marked ambiguous (value 100) and excluded from the
foreground set (light_stage.py:110-116); masked-out pixels are zeroed in the
image (light_stage.py:151). Undistortion and ``input_ratio`` resize follow
light_stage.py:128-150. T is stored in millimetres in ZJU annots and is
scaled by 1/1000 (light_stage.py:163).

Rays are ``[N, 7]``: origin, direction, latent (time) index. The static
renderer slices columns 0:6 and ignores the t column; dynamic-encoder tasks
read it. Scalar near/far for the volume renderer are derived from the world
bbox: min/max camera-to-bbox-corner distance with a safety margin (the
reference leaves per-ray box intersection to its human-NeRF renderers; this
dataset is not wired into its NeRF path either — SURVEY.md §2.4).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np


def _bbox_corners(wbbox: np.ndarray) -> np.ndarray:
    """[8, 3] corner points of a [lo(3), hi(3)] world bbox."""
    lo, hi = wbbox[:3], wbbox[3:6]
    return np.array(
        [[x, y, z] for x in (lo[0], hi[0]) for y in (lo[1], hi[1])
         for z in (lo[2], hi[2])]
    )


def _erode_dilate_band(msk: np.ndarray, border: int = 5) -> np.ndarray:
    """Mark the ±border boundary band of a binary mask with 100
    (light_stage.py:110-116's cv2 erode/dilate)."""
    import cv2

    kernel = np.ones((border, border), np.uint8)
    eroded = cv2.erode(msk.copy(), kernel)
    dilated = cv2.dilate(msk.copy(), kernel)
    out = msk.copy()
    out[(dilated - eroded) == 1] = 100
    return out


def _project_bbox_hull_mask(wbbox: np.ndarray, K: np.ndarray,
                            ext: np.ndarray, H: int, W: int) -> np.ndarray:
    """Binary mask of the world-bbox's projected convex hull
    (base_utils.get_bound_2d_mask's role in light_stage.py:183-186)."""
    import cv2

    corners = _bbox_corners(wbbox)
    cam = corners @ ext[:3, :3].T + ext[:3, 3]
    # guard: corners behind the camera would project nonsensically
    cam[:, 2] = np.maximum(cam[:, 2], 1e-6)
    pix = cam @ K.T
    pix = (pix[:, :2] / pix[:, 2:3]).astype(np.int32)
    mask = np.zeros((H, W), np.uint8)
    hull = cv2.convexHull(pix.reshape(-1, 1, 2))
    cv2.fillConvexPoly(mask, hull, 1)
    return mask


@dataclass
class Dataset:
    """One split of a light-stage capture rooted at ``data_root``."""

    data_root: str
    split: str = "train"
    cameras: tuple = (0, -1, 1)   # [start, end, skip] over the rig
    frames: tuple = (0, -1, 1)    # [start, end, skip] over time
    input_ratio: float = 1.0
    mask_border: int = 5
    scene: str = ""               # registry/catalog compatibility; unused

    H: int = field(init=False)
    W: int = field(init=False)
    near: float = field(init=False)
    far: float = field(init=False)
    wbbox: np.ndarray = field(init=False)

    def __post_init__(self):
        annots = np.load(
            os.path.join(self.data_root, "annots.npy"), allow_pickle=True
        ).item()
        self.cams = annots["cams"]
        n_cams = len(self.cams["K"])
        c0, c1, cs = self.cameras
        c1 = n_cams if c1 == -1 else c1
        self.camera_ids = list(range(n_cams))[c0:c1:cs]

        n_frames = len(annots["ims"])
        f0, f1, fs = self.frames
        f1 = n_frames if f1 == -1 else f1
        self.frame_ids = list(range(n_frames))[f0:f1:fs]
        # latent index = position within the selected frame range, so frame
        # subsampling still yields dense [0, T) indices for latent tables
        self._latent = {f: i for i, f in enumerate(self.frame_ids)}

        # per-frame SMPL-vertex bbox (±5 cm), world bbox over all frames
        # (light_stage.py:65-89)
        bboxes = []
        for f in self.frame_ids:
            verts = np.load(
                os.path.join(self.data_root, "new_vertices", f"{f}.npy")
            )
            bboxes.append(
                np.concatenate([verts.min(0) - 0.05, verts.max(0) + 0.05])
            )
        bboxes = np.stack(bboxes)
        self.wbbox = np.concatenate(
            [bboxes[:, :3].min(0), bboxes[:, 3:6].max(0)]
        ).astype(np.float32)

        self.items = [
            {"frame": f, "camera": c,
             "path": annots["ims"][f]["ims"][c]}
            for f in self.frame_ids
            for c in self.camera_ids
        ]

        self._load_all()
        self._derive_bounds()

    # ---- image/mask loading ------------------------------------------------
    def _mask_path(self, rel: str) -> str:
        """mask_cihp > mask > images→mask substitution, all with .png
        (light_stage.py:94-103's fallback chain, on capture-relative
        paths)."""
        stem = os.path.splitext(rel)[0] + ".png"
        for cand in (
            os.path.join(self.data_root, "mask_cihp", stem),
            os.path.join(self.data_root, "mask", stem),
            os.path.join(self.data_root, stem.replace("images", "mask")),
        ):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(f"no mask found for {rel}")

    def _read_item(self, item):
        import cv2
        from PIL import Image

        img = np.asarray(
            Image.open(os.path.join(self.data_root, item["path"])).convert(
                "RGB"
            ),
            dtype=np.float32,
        ) / 255.0
        H, W = img.shape[:2]
        with Image.open(self._mask_path(item["path"])) as m:
            msk = np.asarray(m)
        if msk.ndim == 3:
            msk = msk[..., 0]
        msk = (msk != 0).astype(np.uint8)
        msk = cv2.resize(msk, (W, H), interpolation=cv2.INTER_NEAREST)

        K = np.array(self.cams["K"][item["camera"]], np.float64).copy()
        D = np.array(self.cams["D"][item["camera"]], np.float64)
        img = cv2.undistort(img, K, D)
        msk = cv2.undistort(msk, K, D)

        if self.input_ratio != 1.0:
            img = cv2.resize(img, None, fx=self.input_ratio,
                             fy=self.input_ratio,
                             interpolation=cv2.INTER_AREA)
            msk = cv2.resize(msk, None, fx=self.input_ratio,
                             fy=self.input_ratio,
                             interpolation=cv2.INTER_NEAREST)
            K[:2] *= self.input_ratio

        img = img.copy()
        img[msk == 0] = 0.0
        msk = _erode_dilate_band(msk, self.mask_border)

        R = np.array(self.cams["R"][item["camera"]], np.float64)
        T = np.array(self.cams["T"][item["camera"]], np.float64).reshape(3, 1)
        T = T / 1000.0  # ZJU annots store millimetres (light_stage.py:163)
        ext = np.concatenate([R, T], axis=1)  # world→camera [3,4]
        return img, msk, K, ext

    def _rays_for(self, K, ext, ys, xs, latent: int):
        """[len(ys), 7] world rays through pixel centers + latent column
        (light_stage.py:208-219's pixel→camera→world chain)."""
        c2w = np.eye(4)
        c2w[:3] = ext
        c2w = np.linalg.inv(c2w)
        d_pix = np.stack([xs, ys, np.ones_like(xs)], -1).astype(np.float64)
        d = d_pix @ np.linalg.inv(K).T @ c2w[:3, :3].T
        d = d / np.linalg.norm(d, axis=-1, keepdims=True)
        o = np.broadcast_to(c2w[:3, 3], d.shape)
        t = np.full((len(d_pix), 1), float(latent))
        return np.concatenate([o, d, t], -1).astype(np.float32)

    def _load_all(self):
        self.n_images = len(self.items)
        if self.split != "train":
            # eval items load LAZILY in image_batch — a real rig (20+ cams ×
            # hundreds of frames at megapixel res) cannot hold every decoded
            # image + [H·W, 7] ray grid in host RAM at once. Read one item
            # here only to publish the H/W contract attributes.
            img, _, _, _ = self._read_item(self.items[0])
            self.H, self.W = img.shape[:2]
            return

        fg_rays, fg_rgbs, bg_rays, bg_rgbs = [], [], [], []
        rng = np.random.default_rng(0)
        for item in self.items:
            img, msk, K, ext = self._read_item(item)
            H, W = img.shape[:2]
            latent = self._latent[item["frame"]]

            ys, xs = np.nonzero(msk == 1)  # interior fg, band excluded
            fg_rays.append(self._rays_for(K, ext, ys, xs, latent))
            fg_rgbs.append(img[ys, xs])

            hull = _project_bbox_hull_mask(self.wbbox, K, ext, H, W)
            ys_b, xs_b = np.nonzero(hull == 1)
            bg_rays.append(self._rays_for(K, ext, ys_b, xs_b, latent))
            bg_rgbs.append(img[ys_b, xs_b])

        fg_r = np.concatenate(fg_rays)
        fg_c = np.concatenate(fg_rgbs)
        bg_r = np.concatenate(bg_rays)
        bg_c = np.concatenate(bg_rgbs)
        # two equal segments ⇒ uniform sampling is 50/50 fg/bg in
        # expectation (the reference's fg_num = N_rays // 2)
        idx = rng.integers(0, len(bg_r), size=len(fg_r))
        self.rays = np.concatenate([fg_r, bg_r[idx]])
        self.rgbs = np.concatenate([fg_c, bg_c[idx]]).astype(np.float32)
        self.H, self.W = H, W

    def _derive_bounds(self):
        """Scalar near/far from camera-to-bbox-corner distances."""
        corners = _bbox_corners(self.wbbox)
        dists = []
        for c in self.camera_ids:
            R = np.array(self.cams["R"][c], np.float64)
            T = np.array(self.cams["T"][c], np.float64).reshape(3) / 1000.0
            center = -R.T @ T
            dists.append(np.linalg.norm(corners - center, axis=-1))
        dists = np.stack(dists)
        self.near = float(max(dists.min() * 0.8, 0.05))
        self.far = float(dists.max() * 1.2)

    # ---- framework contract ------------------------------------------------
    def ray_bank(self):
        return self.rays, self.rgbs

    def __len__(self) -> int:
        if self.split == "train":
            return 1_000_000
        return len(self.items)

    def image_batch(self, index: int) -> dict:
        """One whole eval image, loaded and ray-gridded on demand (a rig's
        full eval split does not fit in host RAM precomputed). Follows the
        shared RayBankDataset contract (bank.py:image_batch — ``rgbs``,
        ``i``, ``meta: {H, W}``) so the evaluators consume it unchanged;
        ``mask``/``wbounds`` ride along for mask-aware extensions."""
        item = self.items[index]
        img, msk, K, ext = self._read_item(item)
        H, W = img.shape[:2]
        ys, xs = np.mgrid[0:H, 0:W].astype(np.float64)
        rays = self._rays_for(
            K, ext, ys.ravel(), xs.ravel(), self._latent[item["frame"]]
        )
        return {
            "rays": rays,
            "rgbs": img.reshape(-1, 3),
            "near": np.float32(self.near),
            "far": np.float32(self.far),
            "i": index,
            "meta": {"H": H, "W": W},
            "mask": msk,
            "wbounds": self.wbbox,
        }

    @classmethod
    def from_cfg(cls, cfg, split: str) -> "Dataset":
        node = cfg.train_dataset if split == "train" else cfg.test_dataset
        return cls(
            data_root=node.data_root,
            split=node.get("split", split),
            cameras=tuple(node.get("cameras", [0, -1, 1])),
            frames=tuple(node.get("frames", [0, -1, 1])),
            input_ratio=float(node.get("input_ratio", 1.0)),
            scene=cfg.get("scene", ""),
        )


def make_dataset(cfg, split: str) -> Dataset:
    return Dataset.from_cfg(cfg, split)
