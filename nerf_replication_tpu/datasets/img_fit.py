"""img_fit dataset: 2-D image-regression warm-up task.

Capability parity with the reference's `src/datasets/img_fit/synthetic.py`
(which ships broken — it imports the nonexistent ``lib.utils``/``lib.config``,
SURVEY.md §2.1): load ONE view of a Blender-format scene, build the (u, v)
pixel-coordinate grid normalized to [0, 1], and serve random (uv → rgb)
batches for training / the whole image for eval.

TPU data path: :meth:`ray_bank` exposes the flat (uv, rgb) arrays so the
generic trainer samples batches on device, exactly like the NeRF ray bank
(the "rays" slot of the batch dict carries uv here).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

import numpy as np


def _uv_grid(h: int, w: int) -> np.ndarray:
    """[h·w, 2] normalized (u, v) pixel coordinates in [0, 1] — one
    construction for the native grid and the resized tuple-index path."""
    X, Y = np.meshgrid(np.arange(w), np.arange(h))
    u = X.astype(np.float32) / max(w - 1, 1)
    v = Y.astype(np.float32) / max(h - 1, 1)
    return np.stack([u, v], -1).reshape(-1, 2)


@dataclass
class Dataset:
    data_root: str
    scene: str
    split: str = "train"
    view: int = 0
    input_ratio: float = 1.0

    img: np.ndarray = field(init=False)  # [H, W, 3]
    uv: np.ndarray = field(init=False)  # [H*W, 2]
    H: int = field(init=False)
    W: int = field(init=False)
    batch_size: int = 8192

    def __post_init__(self):
        scene_dir = os.path.join(self.data_root, self.scene)
        with open(os.path.join(scene_dir, "transforms_train.json")) as f:
            meta = json.load(f)
        frame = meta["frames"][self.view]
        rel = frame["file_path"]
        rel = rel[2:] if rel.startswith("./") else rel

        import imageio.v2 as imageio

        img = np.asarray(imageio.imread(os.path.join(scene_dir, rel + ".png")))
        img = (img / 255.0).astype(np.float32)
        if img.shape[-1] == 4:
            img = img[..., :3] * img[..., 3:] + (1.0 - img[..., 3:])
        if self.input_ratio != 1.0:
            import cv2

            img = cv2.resize(
                img, None, fx=self.input_ratio, fy=self.input_ratio,
                interpolation=cv2.INTER_AREA,
            )
        self.img = img.astype(np.float32)
        self.H, self.W = img.shape[:2]
        self.uv = _uv_grid(self.H, self.W)

    @classmethod
    def from_cfg(cls, cfg, split: str) -> "Dataset":
        node = cfg.train_dataset if split == "train" else cfg.test_dataset
        ds = cls(
            data_root=node.data_root,
            scene=cfg.scene,
            split=node.get("split", split),
            view=int(node.get("view", 0)),
            input_ratio=float(node.get("input_ratio", 1.0)),
        )
        ds.batch_size = int(cfg.task_arg.get("N_pixels", 8192))
        return ds

    # ---- TPU data path ----------------------------------------------------
    def ray_bank(self):
        """(uv [N, 2], rgb [N, 3]) — the generic trainer's bank contract."""
        return self.uv, self.img.reshape(-1, 3)

    # ---- loader contract --------------------------------------------------
    def __len__(self) -> int:
        return 1  # one image (synthetic.py:53-55)

    def image_batch(self, index: int = 0) -> dict:
        return {
            "uv": self.uv,
            "rays": self.uv,  # generic-trainer alias
            "rgb": self.img.reshape(-1, 3),
            "rgbs": self.img.reshape(-1, 3),
            "near": np.float32(0.0),
            "far": np.float32(1.0),
            "i": index,
            "meta": {"H": self.H, "W": self.W},
        }

    def __getitem__(self, index) -> dict:
        if isinstance(index, tuple):
            # ImageSizeBatchSampler contract (reference samplers.py:10-47 via
            # the light-stage datasets): the sampler hands (index, h, w) and
            # the dataset resizes its item — scale augmentation for the 2-D
            # regression task, with TPU-friendly bucketed (h, w).
            index, h, w = index
            import cv2

            img = cv2.resize(
                self.img, (w, h), interpolation=cv2.INTER_AREA
            ).reshape(-1, 3)
            uv = _uv_grid(h, w)
            ids = np.random.choice(
                h * w, min(self.batch_size, h * w), replace=False
            )
            return {"uv": uv[ids], "rgb": img[ids], "meta": {"H": h, "W": w}}
        if self.split == "train":
            ids = np.random.choice(len(self.uv), self.batch_size, replace=False)
            return {
                "uv": self.uv[ids],
                "rgb": self.img.reshape(-1, 3)[ids],
                "meta": {"H": self.H, "W": self.W},
            }
        return self.image_batch(index)
