"""Named-collator registry (reference src/datasets/collate_batch.py:4-12).

The reference keeps an (empty) dict of task-name → collator falling back to
``default_collate``; this is that extension seam with a working default:
stack NumPy leaves, pass ``meta`` dicts and scalars through untouched (the
``meta`` device-transfer exemption, data_utils.py:566-567).
"""

from __future__ import annotations

import numpy as np

_collators: dict = {}


def register_collator(name: str):
    def deco(fn):
        _collators[name] = fn
        return fn

    return deco


def default_collate(items: list):
    """List of per-item dicts → one batch dict with stacked array leaves."""
    if not items:
        return {}
    first = items[0]
    if not isinstance(first, dict):
        return np.stack([np.asarray(x) for x in items], 0)
    out = {}
    for key in first:
        vals = [it[key] for it in items]
        if key == "meta" or isinstance(first[key], dict):
            # ALWAYS a list — a batch-size-dependent type fork (dict when 1,
            # list when >1) makes consumers fragile
            out[key] = vals
        elif np.isscalar(first[key]) or getattr(first[key], "ndim", 1) == 0:
            out[key] = np.asarray(vals)
        else:
            out[key] = np.stack([np.asarray(v) for v in vals], 0)
    return out


def make_collator(cfg, split: str = "train"):
    node = cfg.train if split == "train" else cfg.test
    name = str(node.get("collator", "default"))
    if name in ("", "default"):
        return default_collate
    if name not in _collators:
        raise KeyError(
            f"unknown collator {name!r}; registered: {sorted(_collators)}"
        )
    return _collators[name]
