"""Host-side batch samplers — the reference's sampler stack for the CLI/debug
data path (src/datasets/samplers.py:10-131).

The TRAINING hot path does not use these: ray batches are drawn on device
inside the jitted step (datasets/sampling.py), which subsumes
DistributedSampler semantics via per-(step, process) RNG streams. These
samplers exist for the host-side loader contract (`run.py --type dataset`,
custom tasks iterating images rather than rays) and for schema parity:
``cfg.train.batch_sampler`` / ``sampler_meta`` select them by name.

TPU note on ImageSizeBatchSampler: the reference draws a CONTINUOUS random
(H, W) per batch (samplers.py:10-47) — unbounded shape diversity, which on
TPU would recompile per novel shape. The same knobs here quantize to a
SMALL STATIC BUCKET SET (strides of ``divisor``, default 32), so a run
compiles at most ``n_buckets²`` variants; the paper-equivalent augmentation
survives with bounded compilation.
"""

from __future__ import annotations

import numpy as np


class SequentialSampler:
    def __init__(self, n: int):
        self.n = n

    def __iter__(self):
        return iter(range(self.n))

    def __len__(self):
        return self.n


class RandomSampler:
    """Epoch-seeded permutation (≙ torch RandomSampler + the reference's
    wall-clock worker seeding made deterministic)."""

    def __init__(self, n: int, seed: int = 0):
        self.n = n
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        rng = np.random.default_rng((self.seed, self.epoch))
        return iter(rng.permutation(self.n).tolist())

    def __len__(self):
        return self.n


class DistributedSampler(RandomSampler):
    """Epoch-seeded permutation, padded to divisibility, rank-sliced
    (reference samplers.py:75-131)."""

    def __init__(self, n: int, rank: int, world: int, seed: int = 0,
                 shuffle: bool = True):
        super().__init__(n, seed)
        self.rank, self.world, self.shuffle = rank, world, shuffle

    def __iter__(self):
        rng = np.random.default_rng((self.seed, self.epoch))
        order = rng.permutation(self.n) if self.shuffle else np.arange(self.n)
        total = -(-self.n // self.world) * self.world
        # pad by tiling: a single slice under-pads when world > 2·n (e.g.
        # 1 image on 4 processes needs 3 repeats), leaving high ranks with
        # short/empty slices while __len__ still promises ceil(n/world)
        reps = -(-total // self.n)
        order = np.tile(order, reps)[:total]
        return iter(order[self.rank : total : self.world].tolist())

    def __len__(self):
        return -(-self.n // self.world)


class BatchSampler:
    """Group a sampler's indices into fixed-size batches."""

    def __init__(self, sampler, batch_size: int, drop_last: bool = False):
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)


class ImageSizeBatchSampler:
    """Batches of ``(index, h, w)`` tuples with a random bucketed size per
    batch (reference samplers.py:10-47: min/max/strides from
    ``sampler_meta``).

    The reference contract: the DATASET's ``__getitem__`` receives the
    ``(index, h, w)`` tuple and resizes its item — tasks opting into this
    sampler must accept tuple indices (the template's light-stage datasets
    do; the ray-bank datasets don't, and configuring them together is a
    loud TypeError, not a silent no-op). Sizes are multiples of ``divisor``
    so the shape set is static — TPU-compilable augmentation (see module
    docstring). The RNG stream is instance state, so successive epochs draw
    fresh sizes.
    """

    def __init__(self, sampler, batch_size: int, drop_last: bool = False,
                 min_hw=(256, 256), max_hw=(480, 640), divisor: int = 32,
                 seed: int = 0):
        self.sampler = sampler  # exposed: IterationBased re-seeds epochs here
        self.inner = BatchSampler(sampler, batch_size, drop_last)
        self.min_hw, self.max_hw, self.divisor = min_hw, max_hw, divisor
        self._rng = np.random.default_rng(seed)

    def _buckets(self, lo: int, hi: int):
        q = self.divisor
        return list(range((lo + q - 1) // q * q, hi // q * q + 1, q)) or [lo]

    def __iter__(self):
        hs = self._buckets(self.min_hw[0], self.max_hw[0])
        ws = self._buckets(self.min_hw[1], self.max_hw[1])
        for batch in self.inner:
            h, w = int(self._rng.choice(hs)), int(self._rng.choice(ws))
            yield [(idx, h, w) for idx in batch]

    def __len__(self):
        return len(self.inner)


class IterationBasedBatchSampler:
    """Re-yield batches from a batch sampler until exactly ``num_iterations``
    have been produced (reference samplers.py:50-72 — the ``ep_iter``
    mechanism)."""

    def __init__(self, batch_sampler, num_iterations: int, start_iter: int = 0):
        self.batch_sampler = batch_sampler
        self.num_iterations = num_iterations
        self.start_iter = start_iter

    def __iter__(self):
        it = self.start_iter
        epoch = 0
        while it < self.num_iterations:
            sampler = getattr(self.batch_sampler, "sampler", None)
            if hasattr(sampler, "set_epoch"):
                sampler.set_epoch(epoch)
            epoch += 1
            produced = False
            for batch in self.batch_sampler:
                produced = True
                if it >= self.num_iterations:
                    return
                it += 1
                yield batch
            if not produced:
                raise ValueError(
                    "inner batch sampler yielded no batches (empty dataset "
                    "or empty rank slice?) — refusing to spin forever"
                )

    def __len__(self):
        return self.num_iterations - self.start_iter
