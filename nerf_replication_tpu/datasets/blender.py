"""Blender-synthetic (NeRF) dataset: transforms_{split}.json + PNG frames.

Capability parity with the reference's `src/datasets/nerf/blender.py:33-166`,
redesigned for the TPU data path (SURVEY.md §7): instead of a torch DataLoader
feeding per-item random rays, the dataset precomputes the full ray bank as
flat NumPy arrays (the reference does the same precompute, blender.py:105-108)
and exposes:

* :meth:`ray_bank` — ``(rays [N,6], rgbs [N,3])`` host arrays that the trainer
  moves to device once; per-step random batches are then drawn *inside* the
  jitted train step (no host↔device traffic in the hot loop), and
* test-time :meth:`image_batch` — one whole image's rays + ``H/W/focal`` meta,
  matching the reference's test ``__getitem__`` contract (blender.py:132-139).

Also implements precrop center-cropping warm-up (``precrop_iters`` /
``precrop_frac``), which the reference configures but never reads
(SURVEY.md §2.5).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

import numpy as np

from .bank import RayBankDataset
from .rays import focal_from_fov, get_rays_np


def _load_image(path: str) -> np.ndarray:
    import imageio.v2 as imageio

    return np.asarray(imageio.imread(path))


def _resize_area(img: np.ndarray, W: int, H: int) -> np.ndarray:
    import cv2

    return cv2.resize(img, (W, H), interpolation=cv2.INTER_AREA)


def _to_rgba_uint8(img: np.ndarray) -> np.ndarray:
    """Normalize decoded PNGs to uint8 RGBA: 16-bit/float depths rescaled
    (the reference's bare /255 mishandles those), gray/LA expanded, RGB given
    opaque alpha — so frames with mixed channel counts stack uniformly."""
    if img.dtype == np.uint16:
        img = (img >> 8).astype(np.uint8)
    elif np.issubdtype(img.dtype, np.floating):
        img = (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)
    elif img.dtype != np.uint8:
        raise ValueError(f"unsupported image dtype {img.dtype}")

    if img.ndim == 2:  # grayscale
        img = np.repeat(img[..., None], 3, axis=-1)
    if img.shape[-1] == 2:  # luminance + alpha
        img = np.concatenate([np.repeat(img[..., :1], 3, axis=-1),
                              img[..., 1:]], axis=-1)
    if img.shape[-1] == 3:  # opaque alpha: composite is then a no-op
        img = np.concatenate(
            [img, np.full_like(img[..., :1], 255)], axis=-1
        )
    if img.shape[-1] != 4:
        raise ValueError(f"unsupported channel count {img.shape[-1]}")
    return img


@dataclass
class Dataset(RayBankDataset):
    """One split of a Blender-format scene, fully materialized in host RAM."""

    data_root: str
    scene: str
    split: str = "train"
    input_ratio: float = 1.0
    cams: tuple | list | None = None
    H: int = 800
    W: int = 800
    near: float = 2.0
    far: float = 6.0

    # populated by __post_init__
    focal: float = field(init=False)
    rays: np.ndarray = field(init=False)  # [N_total, 6]
    rgbs: np.ndarray = field(init=False)  # [N_total, 3]
    poses: np.ndarray = field(init=False)  # [n_images, 4, 4]
    n_images: int = field(init=False)

    def __post_init__(self):
        path = os.path.join(self.data_root, self.scene, f"transforms_{self.split}.json")
        with open(path, "r") as f:
            meta = json.load(f)

        frames = meta["frames"]
        if self.cams is not None:
            start, stop, step = self.cams
            if stop == -1:
                stop = len(frames)
            frames = frames[start:stop:step]
        if not frames:
            raise ValueError(f"cams={self.cams} selected no frames from {path}")

        H_orig, W_orig = self.H, self.W
        self.H = int(H_orig * self.input_ratio)
        self.W = int(W_orig * self.input_ratio)
        self.focal = focal_from_fov(W_orig, float(meta["camera_angle_x"])) * (
            self.input_ratio
        )

        raw_images, pose_list = [], []
        for frame in frames:
            img_path = os.path.join(
                self.data_root, self.scene, frame["file_path"] + ".png"
            )
            img = _to_rgba_uint8(_load_image(img_path))
            if img.shape[:2] != (H_orig, W_orig):
                # the reference trusts cfg H/W and would silently build rays
                # with the wrong focal/slicing on a mismatched capture —
                # fail loudly instead (SURVEY.md §2.5 spirit). Checked
                # BEFORE the input_ratio resize, which would otherwise
                # coerce any capture (even aspect-distorting) into shape.
                raise ValueError(
                    f"{img_path}: image is {img.shape[1]}x{img.shape[0]} but "
                    f"the config expects {W_orig}x{H_orig} "
                    f"(train/test_dataset.H/W) — set H/W to the capture "
                    "resolution"
                )
            if self.input_ratio != 1.0:
                # uint8 INTER_AREA downscale, as the reference does before
                # the /255 float conversion (blender.py:86-87)
                img = _resize_area(img, self.W, self.H)
            raw_images.append(img)
            pose_list.append(
                np.asarray(frame["transform_matrix"], dtype=np.float32)
            )
        self.poses = np.stack(pose_list, axis=0)
        self.n_images = len(pose_list)

        # one bank builder for every path (C++ multithreaded when available,
        # NumPy otherwise): pinhole rays + RGBA→white compositing
        from ..native import build_ray_bank

        self.rays, self.rgbs = build_ray_bank(
            self.poses, np.stack(raw_images, 0), self.focal
        )

    @classmethod
    def from_cfg(cls, cfg, split: str) -> "Dataset":
        """Build from the reference-schema config (train_dataset/test_dataset)."""
        node = cfg.train_dataset if split == "train" else cfg.test_dataset
        return cls(
            data_root=node.data_root,
            scene=cfg.scene,
            split=node.get("split", split),
            input_ratio=float(node.get("input_ratio", 1.0)),
            cams=node.get("cams", None),
            H=int(node.get("H", 800)),
            W=int(node.get("W", 800)),
            near=float(cfg.task_arg.near),
            far=float(cfg.task_arg.far),
        )

    # ray_bank/precrop_index_pool/__len__/image_batch/__getitem__: RayBankDataset
