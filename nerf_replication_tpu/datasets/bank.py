"""Shared ray-bank dataset contract.

Every image-collection dataset here (blender, real captures) materializes the
same four host arrays — ``rays [N,6]``, ``rgbs [N,3]``, ``poses``, and the
``H/W/focal/near/far`` scalars — and then exposes one identical surface:
``ray_bank()`` for on-device sampling, ``precrop_index_pool()`` for the
center-crop warm-up, the reference's test ``__getitem__`` contract
(blender.py:124-139 in the reference), and the nominal 1M-epoch train length
(reference blender.py:163). That surface lives here once so task datasets
only implement loading.
"""

from __future__ import annotations

import numpy as np


class RayBankDataset:
    """Mixin over (rays, rgbs, H, W, focal, near, far, n_images, split)."""

    # subclasses populate these in __post_init__
    rays: np.ndarray
    rgbs: np.ndarray
    H: int
    W: int
    focal: float
    near: float
    far: float
    n_images: int
    split: str

    # ---- TPU data path ----------------------------------------------------
    def ray_bank(self):
        """Flat ``(rays, rgbs)`` host arrays for on-device batch sampling."""
        return self.rays, self.rgbs

    def precrop_index_pool(self, precrop_frac: float) -> np.ndarray:
        """Flat ray indices inside the center crop of every image
        (precrop_frac of H and W, as in the original NeRF's warm-up)."""
        H, W, n = self.H, self.W, self.n_images
        dH = int(H // 2 * precrop_frac)
        dW = int(W // 2 * precrop_frac)
        rows = np.arange(H // 2 - dH, H // 2 + dH)
        cols = np.arange(W // 2 - dW, W // 2 + dW)
        rr, cc = np.meshgrid(rows, cols, indexing="ij")
        per_image = (rr * W + cc).reshape(-1)
        offsets = np.arange(n, dtype=np.int64)[:, None] * (H * W)
        return (offsets + per_image[None, :]).reshape(-1)

    # ---- test-split contract ----------------------------------------------
    def __len__(self) -> int:
        if self.split == "train":
            return 1_000_000  # nominal epoch length (reference blender.py:163)
        return self.n_images

    def image_batch(self, index: int) -> dict:
        """One whole image's rays (the reference's test ``__getitem__``)."""
        n_pix = self.H * self.W
        sl = slice(index * n_pix, (index + 1) * n_pix)
        return {
            "rays": self.rays[sl],
            "rgbs": self.rgbs[sl],
            "near": np.float32(self.near),
            "far": np.float32(self.far),
            "i": index,
            "meta": {"H": self.H, "W": self.W, "focal": self.focal},
        }

    def __getitem__(self, index: int) -> dict:
        if self.split == "train":
            # Host-side random batch (used by the smoke CLI; the trainer's hot
            # path samples on device instead).
            idx = np.random.randint(0, self.rays.shape[0], size=(1024,))
            return {
                "rays": self.rays[idx],
                "rgbs": self.rgbs[idx],
                "near": np.float32(self.near),
                "far": np.float32(self.far),
                "i": index,
            }
        return self.image_batch(index)
