"""On-device ray-batch sampling — the TPU-native replacement for the torch
DataLoader + sampler stack (SURVEY.md §2.1 "Data-loader factory", §2.3).

The reference's `DistributedSampler` shards a permutation across ranks with
epoch seeding (samplers.py:75-131); on TPU each process draws independent
random ray batches from the device-resident ray bank, with the RNG key folded
over (step, process_index) so streams are disjoint and deterministic — the
sampler semantics the reference actually needs (SURVEY.md §2.3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_step_key(base_key: jax.Array, step, process_index: int = 0) -> jax.Array:
    """Deterministic per-(step, process) RNG stream."""
    key = jax.random.fold_in(base_key, jnp.asarray(step, jnp.uint32))
    if process_index:
        key = jax.random.fold_in(key, process_index)
    return key


def sample_rays(
    key: jax.Array,
    rays: jax.Array,
    rgbs: jax.Array,
    n_rays: int,
    index_pool: jax.Array | None = None,
):
    """Draw ``n_rays`` random rays from the bank (jit-safe, static n_rays).

    ``index_pool`` restricts sampling to a subset of flat ray indices (used
    for precrop warm-up). Mirrors blender.py:124-131's uniform-with-replacement
    draw.
    """
    if index_pool is None:
        idx = jax.random.randint(key, (n_rays,), 0, rays.shape[0])
    else:
        pool_draw = jax.random.randint(key, (n_rays,), 0, index_pool.shape[0])
        idx = index_pool[pool_draw]
    return jnp.take(rays, idx, axis=0), jnp.take(rgbs, idx, axis=0)
