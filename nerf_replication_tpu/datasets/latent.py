"""Latent-vector dataset: (x, y) feature pairs from a single ``.npy``.

Capability parity with the reference's `src/datasets/latent.py:9-23` (an
extension-surface dataset from the parent template): the scene's ``.npy``
holds rows of concatenated features split as x = [:1] ⊕ [1:32],
y = [32:160] ⊕ [160:].
"""

from __future__ import annotations

import os

import numpy as np


class Dataset:
    def __init__(self, data_root: str, scene: str, split: str = "train",
                 batch_size: int = 1024):
        self.data = np.load(os.path.join(data_root, scene + ".npy"))
        self.split = split
        self.batch_size = batch_size

    @classmethod
    def from_cfg(cls, cfg, split: str) -> "Dataset":
        node = cfg.train_dataset if split == "train" else cfg.test_dataset
        return cls(
            data_root=node.data_root,
            scene=cfg.scene,
            split=node.get("split", split),
            batch_size=int(cfg.task_arg.get("N_rays", 1024)),
        )

    def ray_bank(self):
        """(x [N, 32], y [N, rest]) — generic-trainer bank contract."""
        return (
            self.data[:, :32].astype(np.float32),
            self.data[:, 32:].astype(np.float32),
        )

    def __getitem__(self, index: int):
        x_1, x_2 = self.data[:, :1], self.data[:, 1:32]
        y_1, y_2 = self.data[:, 32:32 + 128], self.data[:, 32 + 128:]
        return x_1, x_2, y_1, y_2

    def __len__(self) -> int:
        return len(self.data)
