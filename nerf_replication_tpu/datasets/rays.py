"""Pinhole-camera ray generation and camera-path helpers.

Capability parity with the reference's `src/datasets/nerf/blender.py:13-32`
(`get_rays`) and `render_video.py:10-19` (`pose_spherical`), as pure NumPy/JAX
functions. Convention is the Blender/NeRF one: camera looks down -z, x right,
y up; `c2w` is a 3x4 or 4x4 camera-to-world matrix.
"""

from __future__ import annotations

import numpy as np


def get_rays_np(
    H: int,
    W: int,
    focal: float,
    c2w: np.ndarray,
    fl_y: float | None = None,
    cx: float | None = None,
    cy: float | None = None,
):
    """Ray origins/directions for every pixel of an HxW pinhole image.

    Returns ``(rays_o, rays_d)`` each ``[H, W, 3]`` float32. Directions are
    *not* normalized (matching the reference; `raw2outputs` multiplies sample
    distances by ``|d|``, volume_renderer.py:27).

    The Blender-synthetic case passes only ``focal`` (principal point at the
    image center, square pixels). Real captures carry calibrated
    ``fl_x/fl_y/cx/cy`` (colmap2nerf output) — pass them for off-center /
    anisotropic intrinsics.
    """
    c2w = np.asarray(c2w, dtype=np.float32)
    fl_y = focal if fl_y is None else fl_y
    cx = 0.5 * W if cx is None else cx
    cy = 0.5 * H if cy is None else cy
    i, j = np.meshgrid(
        np.arange(W, dtype=np.float32), np.arange(H, dtype=np.float32), indexing="xy"
    )
    dirs = np.stack(
        [(i - cx) / focal, -(j - cy) / fl_y, -np.ones_like(i)], axis=-1
    )
    rays_d = dirs @ c2w[:3, :3].T
    rays_o = np.broadcast_to(c2w[:3, 3], rays_d.shape).copy()
    return rays_o.astype(np.float32), rays_d.astype(np.float32)


def ndc_rays_np(
    H: int, W: int, focal: float, near: float,
    rays_o: np.ndarray, rays_d: np.ndarray,
    fl_y: float | None = None,
):
    """Shift rays into normalized device coordinates (forward-facing scenes).

    The original NeRF's LLFF treatment (Mildenhall et al. 2020, appendix C):
    move origins to the near plane, then apply the perspective projection so
    the frustum maps to the [-1,1] cube and sampling t∈[0,1] sweeps
    near→infinity. The reference names this capability in BASELINE.json
    ("LLFF forward-facing, NDC rays") but never implements it.
    """
    fl_y = focal if fl_y is None else fl_y  # anisotropic pixels: y uses fl_y
    o, d = rays_o.astype(np.float64), rays_d.astype(np.float64)
    # shift each origin onto the z = -near plane
    t = -(near + o[..., 2]) / d[..., 2]
    o = o + t[..., None] * d

    o0 = -focal / (0.5 * W) * o[..., 0] / o[..., 2]
    o1 = -fl_y / (0.5 * H) * o[..., 1] / o[..., 2]
    o2 = 1.0 + 2.0 * near / o[..., 2]
    d0 = -focal / (0.5 * W) * (d[..., 0] / d[..., 2] - o[..., 0] / o[..., 2])
    d1 = -fl_y / (0.5 * H) * (d[..., 1] / d[..., 2] - o[..., 1] / o[..., 2])
    d2 = -2.0 * near / o[..., 2]

    rays_o = np.stack([o0, o1, o2], axis=-1).astype(np.float32)
    rays_d = np.stack([d0, d1, d2], axis=-1).astype(np.float32)
    return rays_o, rays_d


def trans_t(t: float) -> np.ndarray:
    m = np.eye(4, dtype=np.float32)
    m[2, 3] = t
    return m


def rot_phi(phi: float) -> np.ndarray:
    c, s = np.cos(phi), np.sin(phi)
    m = np.eye(4, dtype=np.float32)
    m[1, 1], m[1, 2] = c, -s
    m[2, 1], m[2, 2] = s, c
    return m


def rot_theta(th: float) -> np.ndarray:
    c, s = np.cos(th), np.sin(th)
    m = np.eye(4, dtype=np.float32)
    m[0, 0], m[0, 2] = c, -s
    m[2, 0], m[2, 2] = s, c
    return m


def pose_spherical(theta_deg: float, phi_deg: float, radius: float) -> np.ndarray:
    """Camera-to-world for a camera on a sphere looking at the origin
    (render_video.py:10-19 semantics: translate, tilt phi, spin theta, flip x/z
    into the Blender world frame)."""
    c2w = trans_t(radius)
    c2w = rot_phi(phi_deg / 180.0 * np.pi) @ c2w
    c2w = rot_theta(theta_deg / 180.0 * np.pi) @ c2w
    flip = np.array(
        [[-1, 0, 0, 0], [0, 0, 1, 0], [0, 1, 0, 0], [0, 0, 0, 1]], dtype=np.float32
    )
    return flip @ c2w


def focal_from_fov(W: int, camera_angle_x: float) -> float:
    """focal = 0.5 * W / tan(0.5 * fov_x)  (blender.py:71-75)."""
    return 0.5 * W / float(np.tan(0.5 * camera_angle_x))
