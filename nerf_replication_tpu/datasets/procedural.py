"""Procedural Blender-format scene generator.

The reference assumes the NeRF synthetic dataset has been downloaded
(`scripts/download_blender.sh`); no data ships with either repo. This module
writes a *valid* Blender-format scene (transforms_{split}.json + RGBA PNGs) by
analytically ray-tracing a small solid scene, giving tests, demos, and
quality benchmarks a learnable ground truth without any download.

The scene: a diffuse unit-ish sphere (normal-colored) plus an axis-aligned
cube, on a transparent background — exercising the RGBA→white compositing,
near/far bounds, and view-dependence of the real pipeline.
"""

from __future__ import annotations

import json
import os

import numpy as np

from .rays import get_rays_np, pose_spherical

CAMERA_ANGLE_X = 0.6911112070083618  # Blender synthetic default fov


def _intersect_sphere(rays_o, rays_d, center, radius):
    """Smallest positive t of ray/sphere hit, inf where missed."""
    oc = rays_o - center
    b = np.sum(oc * rays_d, -1)
    a = np.sum(rays_d * rays_d, -1)
    c = np.sum(oc * oc, -1) - radius**2
    disc = b * b - a * c
    hit = disc > 0
    sq = np.sqrt(np.maximum(disc, 0.0))
    t0 = (-b - sq) / a
    t1 = (-b + sq) / a
    t = np.where(t0 > 1e-3, t0, t1)
    return np.where(hit & (t > 1e-3), t, np.inf)


def _intersect_box(rays_o, rays_d, lo, hi):
    """Slab-method ray/AABB intersection; inf where missed."""
    inv = 1.0 / np.where(np.abs(rays_d) < 1e-9, 1e-9, rays_d)
    t_lo = (lo - rays_o) * inv
    t_hi = (hi - rays_o) * inv
    t_near = np.max(np.minimum(t_lo, t_hi), -1)
    t_far = np.min(np.maximum(t_lo, t_hi), -1)
    hit = (t_far > np.maximum(t_near, 1e-3))
    t = np.where(t_near > 1e-3, t_near, t_far)
    return np.where(hit & (t > 1e-3), t, np.inf)


SPHERE_C = np.array([0.35, 0.0, 0.25], dtype=np.float32)
SPHERE_R = 0.55
BOX_LO = np.array([-0.9, -0.5, -0.5], dtype=np.float32)
BOX_HI = np.array([-0.1, 0.3, 0.3], dtype=np.float32)
LIGHT_DIR = np.array([0.4, 0.35, 0.85], dtype=np.float32) / np.linalg.norm(
    [0.4, 0.35, 0.85]
)


def render_view(H: int, W: int, focal: float, c2w: np.ndarray) -> np.ndarray:
    """Analytic RGBA render of the scene from one camera. [H, W, 4] uint8."""
    rays_o, rays_d = get_rays_np(H, W, focal, c2w)
    o = rays_o.reshape(-1, 3)
    d = rays_d.reshape(-1, 3)

    t_s = _intersect_sphere(o, d, SPHERE_C, SPHERE_R)
    t_b = _intersect_box(o, d, BOX_LO, BOX_HI)
    t = np.minimum(t_s, t_b)
    hit = np.isfinite(t)
    which_sphere = hit & (t_s <= t_b)

    p = o + np.where(hit, t, 0.0)[:, None] * d
    # normals
    n_sphere = (p - SPHERE_C) / SPHERE_R
    center_box = (BOX_LO + BOX_HI) / 2
    half = (BOX_HI - BOX_LO) / 2
    rel = (p - center_box) / half
    axis = np.argmax(np.abs(rel), -1)
    n_box = np.zeros_like(p)
    n_box[np.arange(len(p)), axis] = np.sign(
        rel[np.arange(len(p)), axis]
    )
    n = np.where(which_sphere[:, None], n_sphere, n_box)

    lambert = np.clip(np.sum(n * LIGHT_DIR, -1), 0.0, 1.0)[:, None]
    albedo_sphere = 0.5 * (n_sphere + 1.0)
    albedo_box = np.broadcast_to(
        np.array([0.9, 0.35, 0.2], dtype=np.float32), p.shape
    )
    albedo = np.where(which_sphere[:, None], albedo_sphere, albedo_box)
    rgb = albedo * (0.25 + 0.75 * lambert)

    rgba = np.zeros((H * W, 4), dtype=np.float32)
    rgba[:, :3] = np.where(hit[:, None], rgb, 0.0)
    rgba[:, 3] = hit.astype(np.float32)
    return (np.clip(rgba.reshape(H, W, 4), 0, 1) * 255).astype(np.uint8)


def generate_scene(
    root: str,
    scene: str = "procedural",
    H: int = 64,
    W: int = 64,
    n_train: int = 20,
    n_test: int = 4,
    radius: float = 4.0,
    seed: int = 0,
) -> str:
    """Write a Blender-format scene dir; returns its path."""
    import imageio.v2 as imageio

    rng = np.random.default_rng(seed)
    scene_dir = os.path.join(root, scene)
    focal = 0.5 * W / np.tan(0.5 * CAMERA_ANGLE_X)

    for split, n in (("train", n_train), ("val", n_test), ("test", n_test)):
        frames = []
        img_dir = os.path.join(scene_dir, split)
        os.makedirs(img_dir, exist_ok=True)
        for k in range(n):
            if split == "train":
                theta = float(rng.uniform(-180, 180))
                phi = float(rng.uniform(-60, -10))
            else:
                theta = -180.0 + 360.0 * k / max(n, 1)
                phi = -30.0
            c2w = pose_spherical(theta, phi, radius)
            img = render_view(H, W, focal, c2w)
            rel = f"./{split}/r_{k}"
            imageio.imwrite(os.path.join(scene_dir, rel + ".png"), img)
            frames.append(
                {"file_path": rel, "transform_matrix": c2w.tolist()}
            )
        with open(
            os.path.join(scene_dir, f"transforms_{split}.json"), "w"
        ) as f:
            json.dump({"camera_angle_x": CAMERA_ANGLE_X, "frames": frames}, f)
    return scene_dir
