"""Procedural Blender-format scene generator.

The reference assumes the NeRF synthetic dataset has been downloaded
(`scripts/download_blender.sh`); no data ships with either repo. This module
writes a *valid* Blender-format scene (transforms_{split}.json + RGBA PNGs) by
analytically ray-tracing a small solid scene, giving tests, demos, and
quality benchmarks a learnable ground truth without any download.

The scene: a diffuse unit-ish sphere (normal-colored) plus an axis-aligned
cube, on a transparent background — exercising the RGBA→white compositing,
near/far bounds, and view-dependence of the real pipeline.
"""

from __future__ import annotations

import json
import os

import numpy as np

from .rays import get_rays_np, pose_spherical

CAMERA_ANGLE_X = 0.6911112070083618  # Blender synthetic default fov


def _intersect_sphere(rays_o, rays_d, center, radius):
    """Smallest positive t of ray/sphere hit, inf where missed."""
    oc = rays_o - center
    b = np.sum(oc * rays_d, -1)
    a = np.sum(rays_d * rays_d, -1)
    c = np.sum(oc * oc, -1) - radius**2
    disc = b * b - a * c
    hit = disc > 0
    sq = np.sqrt(np.maximum(disc, 0.0))
    t0 = (-b - sq) / a
    t1 = (-b + sq) / a
    t = np.where(t0 > 1e-3, t0, t1)
    return np.where(hit & (t > 1e-3), t, np.inf)


def _intersect_box(rays_o, rays_d, lo, hi):
    """Slab-method ray/AABB intersection; inf where missed."""
    inv = 1.0 / np.where(np.abs(rays_d) < 1e-9, 1e-9, rays_d)
    t_lo = (lo - rays_o) * inv
    t_hi = (hi - rays_o) * inv
    t_near = np.max(np.minimum(t_lo, t_hi), -1)
    t_far = np.min(np.maximum(t_lo, t_hi), -1)
    hit = (t_far > np.maximum(t_near, 1e-3))
    t = np.where(t_near > 1e-3, t_near, t_far)
    return np.where(hit & (t > 1e-3), t, np.inf)


def _intersect_cylinder_z(rays_o, rays_d, cx, cy, radius, z0, z1):
    """Smallest positive t of a ray vs a z-aligned finite cylinder."""
    ox = rays_o[:, 0] - cx
    oy = rays_o[:, 1] - cy
    dx, dy = rays_d[:, 0], rays_d[:, 1]
    a = dx * dx + dy * dy
    b = ox * dx + oy * dy
    c = ox * ox + oy * oy - radius * radius
    disc = b * b - a * c
    hit = (disc > 0) & (a > 1e-12)
    sq = np.sqrt(np.maximum(disc, 0.0))
    a_safe = np.where(a > 1e-12, a, 1.0)
    best = np.full(len(rays_o), np.inf)
    for t in ((-b - sq) / a_safe, (-b + sq) / a_safe):
        z = rays_o[:, 2] + t * rays_d[:, 2]
        ok = hit & (t > 1e-3) & (z >= z0) & (z <= z1)
        best = np.where(ok & (t < best), t, best)
    return best


SPHERE_C = np.array([0.35, 0.0, 0.25], dtype=np.float32)
SPHERE_R = 0.55
BOX_LO = np.array([-0.9, -0.5, -0.5], dtype=np.float32)
BOX_HI = np.array([-0.1, 0.3, 0.3], dtype=np.float32)
LIGHT_DIR = np.array([0.4, 0.35, 0.85], dtype=np.float32) / np.linalg.norm(
    [0.4, 0.35, 0.85]
)

# "hard" variant: a fence of THIN vertical cylinders (diameter 0.03 —
# the width of ~1.3 cells of a 128³ grid over the ±1.5 bbox, the classic
# occupancy-carving failure shape) in FRONT of the textured solids, so
# carving the space between the bars without eating the bars is required
# to render the scene (cf. the lego grille, the reference's own scene).
HARD_FENCE_X = np.linspace(-0.9, 0.9, 7, dtype=np.float32)
HARD_FENCE_Y = 0.8
HARD_FENCE_R = 0.015
HARD_FENCE_Z = (-0.6, 0.6)
HARD_CHECKER_FREQ = 24.0  # albedo cycles/unit — sub-voxel color detail


def render_view(
    H: int, W: int, focal: float, c2w: np.ndarray, variant: str = "plain"
) -> np.ndarray:
    """Analytic RGBA render of the scene from one camera. [H, W, 4] uint8.

    ``variant="hard"`` adds the thin-cylinder fence and swaps the solid
    albedos for a high-frequency 3D checker — deliberately adversarial
    geometry for grid carving and encoder resolution (VERDICT r4 #6: show
    the 30 dB crossing is not an artifact of easy geometry).
    """
    rays_o, rays_d = get_rays_np(H, W, focal, c2w)
    o = rays_o.reshape(-1, 3)
    d = rays_d.reshape(-1, 3)
    hard = variant == "hard"

    t_s = _intersect_sphere(o, d, SPHERE_C, SPHERE_R)
    t_b = _intersect_box(o, d, BOX_LO, BOX_HI)
    t = np.minimum(t_s, t_b)
    which = np.where(t_s <= t_b, 0, 1)  # 0 sphere, 1 box
    if hard:
        for cx in HARD_FENCE_X:
            t_c = _intersect_cylinder_z(
                o, d, cx, HARD_FENCE_Y, HARD_FENCE_R, *HARD_FENCE_Z
            )
            which = np.where(t_c < t, 2, which)
            t = np.minimum(t, t_c)
    hit = np.isfinite(t)
    which = np.where(hit, which, -1)

    p = o + np.where(hit, t, 0.0)[:, None] * d
    # normals
    n_sphere = (p - SPHERE_C) / SPHERE_R
    center_box = (BOX_LO + BOX_HI) / 2
    half = (BOX_HI - BOX_LO) / 2
    rel = (p - center_box) / half
    axis = np.argmax(np.abs(rel), -1)
    n_box = np.zeros_like(p)
    n_box[np.arange(len(p)), axis] = np.sign(
        rel[np.arange(len(p)), axis]
    )
    n = np.where((which == 0)[:, None], n_sphere, n_box)
    if hard:
        # cylinder normal: radial in xy from the nearest fence bar
        dx = p[:, 0:1] - HARD_FENCE_X[None, :]
        nearest = np.argmin(np.abs(dx), axis=-1)
        cx = HARD_FENCE_X[nearest]
        n_cyl = np.stack(
            [p[:, 0] - cx, p[:, 1] - HARD_FENCE_Y, np.zeros(len(p))], -1
        )
        n_cyl /= np.maximum(
            np.linalg.norm(n_cyl, axis=-1, keepdims=True), 1e-9
        )
        n = np.where((which == 2)[:, None], n_cyl, n)

    lambert = np.clip(np.sum(n * LIGHT_DIR, -1), 0.0, 1.0)[:, None]
    albedo_sphere = 0.5 * (n_sphere + 1.0)
    albedo_box = np.broadcast_to(
        np.array([0.9, 0.35, 0.2], dtype=np.float32), p.shape
    )
    if hard:
        # 3D checker at sub-voxel frequency: the encoder must resolve
        # color flips every ~0.04 units (< one 128³ grid cell)
        checker = (
            np.floor(p[:, 0] * HARD_CHECKER_FREQ)
            + np.floor(p[:, 1] * HARD_CHECKER_FREQ)
            + np.floor(p[:, 2] * HARD_CHECKER_FREQ)
        ) % 2.0
        albedo_sphere = np.where(
            checker[:, None] > 0.5,
            np.array([0.95, 0.95, 0.1], np.float32),
            np.array([0.1, 0.2, 0.9], np.float32),
        )
        albedo_box = np.where(
            checker[:, None] > 0.5,
            np.array([0.9, 0.35, 0.2], np.float32),
            np.array([0.15, 0.8, 0.5], np.float32),
        )
    albedo = np.where((which == 0)[:, None], albedo_sphere, albedo_box)
    if hard:
        albedo = np.where(
            (which == 2)[:, None],
            np.array([0.85, 0.85, 0.9], np.float32),
            albedo,
        )
    rgb = albedo * (0.25 + 0.75 * lambert)

    rgba = np.zeros((H * W, 4), dtype=np.float32)
    rgba[:, :3] = np.where(hit[:, None], rgb, 0.0)
    rgba[:, 3] = hit.astype(np.float32)
    return (np.clip(rgba.reshape(H, W, 4), 0, 1) * 255).astype(np.uint8)


def generate_scene(
    root: str,
    scene: str = "procedural",
    H: int = 64,
    W: int = 64,
    n_train: int = 20,
    n_test: int = 4,
    radius: float = 4.0,
    seed: int = 0,
) -> str:
    """Write a Blender-format scene dir; returns its path."""
    import imageio.v2 as imageio

    rng = np.random.default_rng(seed)
    scene_dir = os.path.join(root, scene)
    focal = 0.5 * W / np.tan(0.5 * CAMERA_ANGLE_X)
    # scene names containing "hard" get the adversarial variant (thin
    # fence + sub-voxel checker albedo)
    variant = "hard" if "hard" in scene else "plain"

    for split, n in (("train", n_train), ("val", n_test), ("test", n_test)):
        frames = []
        img_dir = os.path.join(scene_dir, split)
        os.makedirs(img_dir, exist_ok=True)
        for k in range(n):
            if split == "train":
                theta = float(rng.uniform(-180, 180))
                phi = float(rng.uniform(-60, -10))
            else:
                theta = -180.0 + 360.0 * k / max(n, 1)
                phi = -30.0
            c2w = pose_spherical(theta, phi, radius)
            img = render_view(H, W, focal, c2w, variant=variant)
            rel = f"./{split}/r_{k}"
            imageio.imwrite(os.path.join(scene_dir, rel + ".png"), img)
            frames.append(
                {"file_path": rel, "transform_matrix": c2w.tolist()}
            )
        with open(
            os.path.join(scene_dir, f"transforms_{split}.json"), "w"
        ) as f:
            json.dump({"camera_angle_x": CAMERA_ANGLE_X, "frames": frames}, f)
    return scene_dir


def generate_light_stage_capture(
    root: str,
    n_cams: int = 4,
    n_frames: int = 3,
    H: int = 48,
    W: int = 48,
    rig_radius: float = 3.0,
    seed: int = 0,
) -> str:
    """Write a synthetic light-stage capture (the air-gapped stand-in for
    ZJU-MoCap, mirroring annots.npy's schema — ref light_stage.py:18-28):
    ``annots.npy`` with per-camera K/D/R/T (T in millimetres, as shipped) and
    per-frame image lists, ``images/`` + ``mask/`` trees, and per-frame
    ``new_vertices/{f}.npy`` surface samples of the moving subject (a sphere
    drifting over time). Returns ``root``.
    """
    from PIL import Image

    rng = np.random.default_rng(seed)
    os.makedirs(os.path.join(root, "new_vertices"), exist_ok=True)

    focal = 1.2 * W
    K = np.array([[focal, 0, W / 2], [0, focal, H / 2], [0, 0, 1]], np.float64)

    # ring of inward-looking cameras (world→camera R, T)
    cams = {"K": [], "D": [], "R": [], "T": []}
    exts = []
    for c in range(n_cams):
        ang = 2 * np.pi * c / n_cams
        center = np.array(
            [rig_radius * np.cos(ang), rig_radius * np.sin(ang), 0.3]
        )
        fwd = -center / np.linalg.norm(center)          # camera +z looks in
        right = np.cross(np.array([0.0, 0.0, 1.0]), fwd)
        right /= np.linalg.norm(right)
        down = np.cross(fwd, right)
        R = np.stack([right, down, fwd])                 # rows: cam axes
        T = (-R @ center).reshape(3, 1)
        cams["K"].append(K.tolist())
        cams["D"].append(np.zeros((5, 1)).tolist())
        cams["R"].append(R.tolist())
        cams["T"].append((T * 1000.0).tolist())          # millimetres
        exts.append((R, T.reshape(3)))

    sphere_r = 0.5
    ims = []
    for f in range(n_frames):
        center = np.array([0.3 * np.sin(f), 0.3 * np.cos(f), 0.1 * f])
        # fibonacci-sphere surface samples as the frame's "SMPL vertices"
        i = np.arange(256, dtype=np.float64)
        phi = np.arccos(1 - 2 * (i + 0.5) / 256)
        theta = np.pi * (1 + 5**0.5) * i
        verts = center + sphere_r * np.stack(
            [np.sin(phi) * np.cos(theta), np.sin(phi) * np.sin(theta),
             np.cos(phi)], -1
        )
        np.save(os.path.join(root, "new_vertices", f"{f}.npy"), verts)

        frame_paths = []
        for c, (R, T) in enumerate(exts):
            ys, xs = np.mgrid[0:H, 0:W].astype(np.float64)
            d_cam = np.stack([xs, ys, np.ones_like(xs)], -1) @ np.linalg.inv(K).T
            d = d_cam @ R        # R^T from the right
            d /= np.linalg.norm(d, axis=-1, keepdims=True)
            o = (-R.T @ T).reshape(1, 1, 3)
            t = _intersect_sphere(
                o.reshape(-1, 3).repeat(H * W, 0).reshape(H, W, 3), d,
                center, sphere_r,
            )
            hit = np.isfinite(t)
            p = o + np.where(hit, t, 0.0)[..., None] * d
            n = (p - center) / sphere_r
            rgb = np.where(
                hit[..., None], 0.5 * (n + 1.0), 0.0
            )
            img_rel = os.path.join("images", f"cam{c}", f"{f:04d}.jpg")
            msk_rel = os.path.join("mask", f"cam{c}", f"{f:04d}.png")
            os.makedirs(os.path.dirname(os.path.join(root, img_rel)),
                        exist_ok=True)
            os.makedirs(os.path.dirname(os.path.join(root, msk_rel)),
                        exist_ok=True)
            Image.fromarray(
                (np.clip(rgb, 0, 1) * 255).astype(np.uint8)
            ).save(os.path.join(root, img_rel), quality=95)
            Image.fromarray(
                (hit * 255).astype(np.uint8)
            ).save(os.path.join(root, msk_rel))
            frame_paths.append(img_rel)
        ims.append({"ims": frame_paths})

    np.save(
        os.path.join(root, "annots.npy"),
        np.array({"cams": cams, "ims": ims}, dtype=object),
    )
    return root


def ensure_scene(root: str, scene: str = "procedural", H: int = 64,
                 W: int = 64, n_train: int = 20, n_test: int = 4) -> str:
    """Generate the scene unless an up-to-date one already exists.

    A dir left by an earlier run at a different resolution or view count
    would silently train on the wrong scene (or trip the dataset's
    capture-size guard) — verify the first train image's size and both
    splits' frame counts, and regenerate on any mismatch.
    """
    scene_dir = os.path.join(root, scene)
    tjson = os.path.join(scene_dir, "transforms_train.json")
    stale = not os.path.exists(tjson)
    if not stale:
        from PIL import Image

        def frames(split):
            p = os.path.join(scene_dir, f"transforms_{split}.json")
            if not os.path.exists(p):
                return -1
            with open(p) as f:
                return len(json.load(f).get("frames", []))

        first = os.path.join(scene_dir, "train", "r_0.png")
        if (not os.path.exists(first) or frames("train") != n_train
                or frames("test") != n_test):
            stale = True
        else:
            with Image.open(first) as im:
                stale = im.size != (W, H)
        if stale:
            import shutil

            shutil.rmtree(scene_dir)
            print(f"scene at {scene_dir} is stale; regenerating", flush=True)
    if stale:
        print(f"generating {n_train}-view {H}x{W} scene …", flush=True)
        generate_scene(root, scene=scene, H=H, W=W, n_train=n_train,
                       n_test=n_test)
    return scene_dir
