"""Native (C++) runtime components, bound via ctypes.

The compute path is JAX/XLA; the host runtime around it is native where the
reference's is (SURVEY.md: torch's C++ DataLoader machinery + hashencoder
JIT build, src/models/encoding/hashencoder/backend.py:6-16). The library is
compiled on first use with g++ into a per-version cache dir; every entry
point has a NumPy fallback, so the package works on machines without a
toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "raybank.cpp")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_LIB_FAILED = False


def _build_dir() -> str:
    import platform

    tag = f"cpy{sys.version_info.major}{sys.version_info.minor}-{platform.machine()}"
    d = os.environ.get(
        "NERF_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "nerf_replication_tpu", tag),
    )
    os.makedirs(d, exist_ok=True)
    return d


def _compile() -> str | None:
    out = os.path.join(_build_dir(), "libraybank.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(_SRC):
        return out
    # portable flags only (no -march=native): the cache dir may be a home
    # share mounted across heterogeneous pod hosts
    tmp = f"{out}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        # graftlint: ok(blocking-under-lock: the module lock exists to make g++ invocations process-wide single-flight; first-touch only, cached .so afterwards)
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            return None
        os.replace(tmp, out)  # atomic: concurrent builders race benignly
    except (OSError, subprocess.TimeoutExpired):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return out


def get_lib() -> ctypes.CDLL | None:
    """The compiled library, or None when unavailable (fallback mode)."""
    global _LIB, _LIB_FAILED
    with _LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        path = _compile()
        if path is None:
            _LIB_FAILED = True
            return None
        lib = ctypes.CDLL(path)
        lib.build_ray_bank.argtypes = [
            ctypes.POINTER(ctypes.c_float),   # poses
            ctypes.POINTER(ctypes.c_uint8),   # images
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,  # n,H,W,C
            ctypes.c_float,                   # focal
            ctypes.c_int,                     # n_threads
            ctypes.POINTER(ctypes.c_float),   # rays_out
            ctypes.POINTER(ctypes.c_float),   # rgbs_out
        ]
        lib.build_ray_bank.restype = None
        _LIB = lib
        return _LIB


def native_available() -> bool:
    return get_lib() is not None


def build_ray_bank(
    poses: np.ndarray,   # [n, 4, 4] float32 c2w
    images: np.ndarray,  # [n, H, W, C] uint8 (C in {3, 4})
    focal: float,
    n_threads: int | None = None,
):
    """(rays [n·H·W, 6], rgbs [n·H·W, 3]) — native when possible, NumPy
    fallback otherwise. Identical math to datasets.rays.get_rays_np +
    white-compositing."""
    n, H, W, C = images.shape
    if C not in (3, 4):
        raise ValueError(
            f"build_ray_bank needs RGB or RGBA frames, got {C} channels"
        )
    lib = get_lib()
    if lib is not None:
        poses_c = np.ascontiguousarray(poses, np.float32)
        images_c = np.ascontiguousarray(images, np.uint8)
        rays = np.empty((n * H * W, 6), np.float32)
        rgbs = np.empty((n * H * W, 3), np.float32)
        if n_threads is None:
            n_threads = min(os.cpu_count() or 1, 8)
        lib.build_ray_bank(
            poses_c.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            images_c.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            n, H, W, C, float(focal), int(n_threads),
            rays.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rgbs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return rays, rgbs
    return _build_ray_bank_numpy(poses, images, focal)


def _build_ray_bank_numpy(poses, images, focal):
    from ..datasets.rays import get_rays_np

    n, H, W, C = images.shape
    rays_list, rgb_list = [], []
    for f in range(n):
        rays_o, rays_d = get_rays_np(H, W, focal, poses[f])
        rays_list.append(
            np.concatenate([rays_o, rays_d], -1).reshape(-1, 6)
        )
        img = images[f].astype(np.float32) / 255.0
        if C == 4:
            img = img[..., :3] * img[..., 3:] + (1.0 - img[..., 3:])
        rgb_list.append(img[..., :3].reshape(-1, 3).astype(np.float32))
    return (
        np.concatenate(rays_list, 0).astype(np.float32),
        np.concatenate(rgb_list, 0),
    )
