// Native ray-bank builder: the host-side data pipeline as C++.
//
// The TPU-native seat of the reference's native data-path components (its
// torch DataLoader C++ machinery + the per-pixel Python loops in
// src/datasets/nerf/blender.py:77-108): for every frame, generate pinhole
// rays (blender.py:13-32 math), composite RGBA onto white (blender.py:92-93),
// and write the flat [N,6]/[N,3] banks the trainer uploads to device once.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image). Work is
// sharded across std::thread by frame; each frame's pixels are an
// independent, cache-friendly sweep.

#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

// rays for one frame: dirs = K^-1 pixel dirs rotated by c2w; origin = c2w[:,3]
// (blender.py:13-32: x right, y up, camera looks down -z, dirs unnormalized)
void frame_rays(const float* c2w /*[16] row-major 4x4*/, int H, int W,
                float focal, float* rays /*[H*W*6]*/) {
  const float cx = 0.5f * static_cast<float>(W);
  const float cy = 0.5f * static_cast<float>(H);
  const float r00 = c2w[0], r01 = c2w[1], r02 = c2w[2], tx = c2w[3];
  const float r10 = c2w[4], r11 = c2w[5], r12 = c2w[6], ty = c2w[7];
  const float r20 = c2w[8], r21 = c2w[9], r22 = c2w[10], tz = c2w[11];
  for (int j = 0; j < H; ++j) {
    const float dy = -(static_cast<float>(j) - cy) / focal;
    float* row = rays + static_cast<size_t>(j) * W * 6;
    for (int i = 0; i < W; ++i) {
      const float dx = (static_cast<float>(i) - cx) / focal;
      // dir = R @ [dx, dy, -1]
      const float wx = r00 * dx + r01 * dy - r02;
      const float wy = r10 * dx + r11 * dy - r12;
      const float wz = r20 * dx + r21 * dy - r22;
      float* p = row + static_cast<size_t>(i) * 6;
      p[0] = tx;
      p[1] = ty;
      p[2] = tz;
      p[3] = wx;
      p[4] = wy;
      p[5] = wz;
    }
  }
}

// RGBA uint8 -> float rgb composited onto white (blender.py:92-93);
// 3-channel input is a plain [0,1] scale.
void frame_rgbs(const uint8_t* img, int n_pixels, int channels,
                float* rgbs /*[n_pixels*3]*/) {
  constexpr float kInv255 = 1.0f / 255.0f;
  if (channels == 4) {
    for (int p = 0; p < n_pixels; ++p) {
      const uint8_t* px = img + static_cast<size_t>(p) * 4;
      const float a = static_cast<float>(px[3]) * kInv255;
      float* out = rgbs + static_cast<size_t>(p) * 3;
      for (int c = 0; c < 3; ++c) {
        const float v = static_cast<float>(px[c]) * kInv255;
        out[c] = v * a + (1.0f - a);
      }
    }
  } else {
    for (int p = 0; p < n_pixels; ++p) {
      const uint8_t* px = img + static_cast<size_t>(p) * channels;
      float* out = rgbs + static_cast<size_t>(p) * 3;
      for (int c = 0; c < 3; ++c) {
        out[c] = static_cast<float>(px[c]) * kInv255;
      }
    }
  }
}

}  // namespace

extern "C" {

// poses: [n_images, 16] row-major c2w; images: [n_images, H*W*channels] u8;
// rays_out: [n_images*H*W, 6]; rgbs_out: [n_images*H*W, 3].
void build_ray_bank(const float* poses, const uint8_t* images, int n_images,
                    int H, int W, int channels, float focal, int n_threads,
                    float* rays_out, float* rgbs_out) {
  const size_t n_pixels = static_cast<size_t>(H) * W;
  auto work = [&](int begin, int end) {
    for (int f = begin; f < end; ++f) {
      frame_rays(poses + static_cast<size_t>(f) * 16, H, W, focal,
                 rays_out + static_cast<size_t>(f) * n_pixels * 6);
      frame_rgbs(images + static_cast<size_t>(f) * n_pixels * channels,
                 static_cast<int>(n_pixels), channels,
                 rgbs_out + static_cast<size_t>(f) * n_pixels * 3);
    }
  };
  if (n_threads <= 1 || n_images <= 1) {
    work(0, n_images);
    return;
  }
  const int workers = n_threads < n_images ? n_threads : n_images;
  std::vector<std::thread> threads;
  threads.reserve(workers);
  const int per = (n_images + workers - 1) / workers;
  for (int t = 0; t < workers; ++t) {
    const int begin = t * per;
    const int end = begin + per < n_images ? begin + per : n_images;
    if (begin >= end) break;
    threads.emplace_back(work, begin, end);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
