"""AOT compile + warm-start subsystem (docs/compilation.md).

Three layers kill the warmup tax the round-5 bench measured (177 s of a
420 s window on compile + grid warm-up):

* :mod:`.registry` — every jitted entrypoint registers its abstract
  signature and is lowered+compiled up front, concurrently on host
  threads, instead of lazily on first dispatch.
* :mod:`.artifacts` — AOT executables serialize to a repo-anchored store
  keyed by (name, signature, jax version, backend, config hash); a second
  process deserializes and performs zero builds.
* NGP/eval warm-start lives with its state: the live occupancy grid rides
  in the checkpoint bundle and the trainer's phase counters in a sidecar
  (train/checkpoint.py, train/ngp.py), so a resumed run re-enters the
  carved phase directly.
"""

from .artifacts import (
    artifact_key,
    artifact_path,
    default_artifact_dir,
    load_artifact,
    save_artifact,
)
from .registry import (
    AOTRegistry,
    PrecompiledFn,
    abstract_like,
    artifact_census,
    registry_from_cfg,
)

__all__ = [
    "AOTRegistry",
    "PrecompiledFn",
    "abstract_like",
    "artifact_census",
    "artifact_key",
    "artifact_path",
    "default_artifact_dir",
    "load_artifact",
    "registry_from_cfg",
    "save_artifact",
]
