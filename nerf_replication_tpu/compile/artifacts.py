"""Serialized AOT executables: the disk half of the warm-start story.

An AOT-compiled executable (``jax.jit(...).lower().compile()``) can be
serialized via ``jax.experimental.serialize_executable`` and reloaded by a
later process, skipping tracing AND compilation entirely — measured on this
backend, a deserialized executable dispatches with zero builds
(CompileTracker count 0). Artifacts are keyed by everything that could
invalidate them:

* the entry's registered name (program identity),
* the abstract input signature (shapes/dtypes/pytree structure),
* ``jax.__version__`` and the backend platform (an XLA upgrade or a
  cpu→tpu move must miss, never deserialize a stale executable),
* an optional extra tag (the run's config hash).

Not every executable serializes: the payload pickles the input/output
pytree *treedefs*, and optax optimizer states close over local functions
that cannot pickle. Executables materialized FROM the persistent XLA
compilation cache are a subtler failure — they serialize without error
but the payload omits their jitted symbols, so every later deserialize
fails with "Symbols not found". ``save_artifact`` round-trip-verifies the
payload before writing and degrades to "no artifact" on any failure (the
persistent XLA compilation cache still makes the rebuild cheap) —
serve-engine executables compiled fresh, whose trees are plain dicts,
round-trip fine and are the case the zero-build serve restart depends on.

Layout: one ``<key>.aot`` file per artifact under the artifact dir
(default: ``<repo>/data/jax_cache/aot``, riding next to the persistent XLA
cache from ``utils/platform.enable_compilation_cache``). Writes are atomic
(tmp + ``os.replace``) so a killed process can never leave a torn artifact
for the next one to trip over.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import sys

from ..obs.emit import get_emitter
from ..resil import (
    fault_point,
    report,
    verify_checksum,
    with_retry,
    write_checksum,
)


def default_artifact_dir() -> str:
    """Repo-anchored artifact dir (next to the persistent XLA cache)."""
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo_root, "data", "jax_cache", "aot")


def _signature(tree) -> str:
    """Stable fingerprint of a pytree of abstract values (or arrays).

    Mesh-sharded leaves (model-parallel serving) fold their partition
    spec + mesh shape into the fingerprint: the same shapes lowered
    under a different sharding are a DIFFERENT executable, and the two
    must never collide in the artifact store. Unsharded/single-device
    leaves contribute nothing extra, so every pre-sharding key is
    unchanged (warm restarts across this change still hit)."""
    import jax
    from jax.sharding import NamedSharding

    leaves, treedef = jax.tree.flatten(tree)
    parts = [str(treedef)]
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        sig = f"{shape}:{dtype}"
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            sig += f":{sharding.spec}@{dict(sharding.mesh.shape)}"
        parts.append(sig)
    return "|".join(parts)


def artifact_key(name: str, abstract_args, extra: str = "") -> str:
    """Cache key for one registered entrypoint (see module docstring)."""
    import jax

    backend = "unknown"
    try:
        backend = jax.default_backend()
    # graftlint: ok(swallow: backend probe; key falls back to 'unknown')
    except Exception:
        pass
    payload = "\x1f".join(
        [name, _signature(abstract_args), jax.__version__, backend, extra]
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


def artifact_path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, f"{key}.aot")


def _skip(name: str, key: str, reason: str) -> None:
    """A serialization skip is a visible event, not a silent degrade: one
    ``compile`` row (phase "aot") + a one-line warning."""
    get_emitter().emit(
        "compile", name=name or key, n_compiles=0, wall_s=0.0,
        phase="aot", skipped_reason=reason[:200],
    )
    print(
        f"warning: aot artifact skipped for {name or key}: {reason[:120]}",
        file=sys.stderr,
    )


def save_artifact(cache_dir: str, key: str, compiled, name: str = "") -> bool:
    """Persist one compiled executable (+ its checksum sidecar); False
    when it cannot serialize (unpicklable treedefs, backend without
    executable serialization) or cannot be written."""
    try:
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree))
        # round-trip gate: an executable the persistent XLA compilation
        # cache materialized (rather than compiled fresh) serializes a
        # payload whose jitted symbols are not embedded — it pickles fine
        # but every later deserialize fails with "Symbols not found".
        # Verifying here keeps poisoned blobs off disk entirely, so a warm
        # restart can trust any artifact that exists.
        serialize_executable.deserialize_and_load(payload, in_tree, out_tree)
    # graftlint: ok(swallow: _skip emits the compile row with skipped_reason)
    except Exception as exc:
        _skip(name, key, f"unserializable: {type(exc).__name__}: {exc}")
        return False
    try:
        os.makedirs(cache_dir, exist_ok=True)
        path = artifact_path(cache_dir, key)
        fault_point("artifact.save", path=path)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        write_checksum(path)
        return True
    except OSError as exc:  # read-only FS etc.: degrade to no artifact
        _skip(name, key, f"io: {exc}")
        return False


def load_artifact(cache_dir: str, key: str):
    """Deserialize one executable, or None (missing/stale/torn artifact —
    every failure mode degrades to the normal compile path). Transient
    read errors retry with backoff; a checksum mismatch (truncated .aot)
    is reported and degrades to the lazy build, never loads garbage."""
    path = artifact_path(cache_dir, key)
    if not os.path.exists(path):
        return None

    def _read():
        fault_point("artifact.load", path=path)
        with open(path, "rb") as f:
            return f.read()

    try:
        blob = with_retry(_read, point="artifact.load")
    except OSError:
        return None  # retries exhausted: the normal compile path runs
    if verify_checksum(path) is False:
        report("artifact.load", "checksum", path=path)
        return None
    try:
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = pickle.loads(blob)
        return serialize_executable.deserialize_and_load(
            payload, in_tree, out_tree
        )
    except Exception as exc:
        report("artifact.load", "torn", path=path,
               detail=f"{type(exc).__name__}")
        return None
