"""AOT compile registry: every jitted entrypoint built up front, not on
first dispatch.

The round-5 numbers put the bottleneck at the *ramp*, not the hot loop:
177 s of a 420 s NGP bench window went to compile + warm-up, and every
lazily-built executable (train step, scan burst, NGP warm/march variants,
eval render, serve buckets) pays its compile exactly where it hurts — on
the first dispatch, serially, inside the timed window. The registry
inverts that:

* callers :meth:`~AOTRegistry.register` each jitted entrypoint with its
  **abstract** input signature (``abstract_like`` of the real call args),
* :meth:`~AOTRegistry.compile_all` lowers + compiles every entry
  concurrently on host threads (XLA compilation releases the GIL), so
  compiles overlap dataset loading / checkpoint I/O / each other,
* :meth:`~AOTRegistry.take` hands the caller a :class:`PrecompiledFn` —
  blocking only on *that* entry — which the trainer/engine installs in
  place of the lazy ``jax.jit`` wrapper. A ``PrecompiledFn`` can never
  retrace (it IS one executable) and reports a constant lowering-cache
  size, so CompileTracker counts zero builds on dispatch; the build that
  DID happen is accounted exactly once at compile time via
  ``CompileTracker.note_compile`` (one ``compile`` telemetry row).

Entries registered with ``serialize=True`` additionally round-trip through
the artifact store (:mod:`.artifacts`): a later process deserializes the
executable from disk and performs **zero** builds — the serve engine's
warm restart. Entries whose trees cannot pickle (optax states) skip the
disk leg silently; the persistent XLA cache still covers them.

Every failure mode degrades to the lazy path: a registration whose
lower/compile raises records the error and ``take`` returns None, so the
caller's ordinary ``jax.jit`` build runs instead — the registry can make
startup faster, never break it.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from .artifacts import (
    artifact_key,
    default_artifact_dir,
    load_artifact,
    save_artifact,
)


def abstract_like(tree):
    """Pytree of ShapeDtypeStructs matching ``tree``'s arrays — the
    abstract signature ``register`` wants, derived from the exact objects
    the caller will later pass so the compiled executable always
    structure-matches the dispatch."""
    import jax
    import jax.numpy as jnp

    def _abstract(a):
        if isinstance(a, jax.ShapeDtypeStruct):
            return a  # already abstract (mixed trees are fine)
        # carry the leaf's sharding: a mesh-sharded state/bank must lower
        # to the layout the real dispatch will pass, or the compiled
        # executable rejects its own inputs
        sharding = getattr(a, "sharding", None)
        return jax.ShapeDtypeStruct(
            jnp.shape(a), jnp.result_type(a), sharding=sharding
        )

    return jax.tree.map(_abstract, tree)


class PrecompiledFn:
    """Drop-in callable around one AOT-compiled (or deserialized)
    executable. ``_cache_size`` is the CompileTracker probe: a constant 0
    tells the tracker no lowering cache can ever grow here — dispatching a
    precompiled executable is never a build."""

    __slots__ = ("name", "fn", "source", "build_s")

    def __init__(self, name: str, fn, source: str, build_s: float = 0.0):
        self.name = name
        self.fn = fn
        self.source = source  # "compiled" | "disk"
        self.build_s = build_s

    def _cache_size(self) -> int:
        return 0

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)


@dataclass
class _Entry:
    name: str
    jitted: object  # the jax.jit-wrapped callable to lower
    abstract_args: tuple
    serialize: bool = False
    result: PrecompiledFn | None = None
    error: str | None = None
    wall_s: float = 0.0
    future: object = field(default=None, repr=False)


class AOTRegistry:
    """Named AOT entrypoints for one process (see module docstring).

    ``tracker`` (a CompileTracker) receives one ``note_compile`` per entry
    actually built — disk-loaded entries count zero, which is exactly the
    invariant the cold-start bench asserts.
    """

    def __init__(self, cache_dir: str | None = None, config_hash: str = "",
                 tracker=None, enabled: bool = True,
                 artifacts: bool = True):
        self.cache_dir = cache_dir or default_artifact_dir()
        self.config_hash = config_hash
        self.tracker = tracker
        self.enabled = enabled
        self.artifacts = artifacts
        self._entries: dict[str, _Entry] = {}
        self._pool: ThreadPoolExecutor | None = None

    # -- registration --------------------------------------------------------

    def register(self, name: str, jitted, abstract_args: tuple,
                 serialize: bool = False) -> None:
        """Declare one jitted entrypoint. Re-registering a name replaces
        the entry (a rebuilt step fn with new static config)."""
        self._entries[name] = _Entry(
            name=name, jitted=jitted, abstract_args=tuple(abstract_args),
            serialize=serialize,
        )

    def names(self) -> list[str]:
        return list(self._entries)

    # -- compilation ---------------------------------------------------------

    def _build_one(self, entry: _Entry) -> None:
        t0 = time.perf_counter()
        key = artifact_key(entry.name, entry.abstract_args,
                           extra=self.config_hash)
        if entry.serialize and self.artifacts:
            loaded = load_artifact(self.cache_dir, key)
            if loaded is not None:
                entry.wall_s = time.perf_counter() - t0
                entry.result = PrecompiledFn(
                    entry.name, loaded, "disk", entry.wall_s
                )
                return
        try:
            compiled = entry.jitted.lower(*entry.abstract_args).compile()
        # graftlint: ok(swallow: degrades to lazy build; entry.error reaches the compile summary)
        except Exception as exc:
            entry.error = f"{type(exc).__name__}: {exc}"
            return
        entry.wall_s = time.perf_counter() - t0
        entry.result = PrecompiledFn(
            entry.name, compiled, "compiled", entry.wall_s
        )
        if self.tracker is not None:
            self.tracker.note_compile(entry.name, entry.wall_s)
        if entry.serialize and self.artifacts:
            save_artifact(self.cache_dir, key, compiled, name=entry.name)

    def compile_all(self, wait: bool = True,
                    max_workers: int | None = None) -> None:
        """Lower + compile every pending entry on host threads.

        ``wait=False`` returns immediately with compiles in flight —
        callers overlap dataset loading / checkpoint I/O and pick results
        up per-entry via :meth:`take` (which blocks only on its entry)."""
        if not self.enabled:
            return
        pending = [
            e for e in self._entries.values()
            if e.result is None and e.error is None and e.future is None
        ]
        if not pending:
            return
        if max_workers is None:
            max_workers = min(len(pending), max(2, (os.cpu_count() or 4) // 2))
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="aot-compile"
            )
        for entry in pending:
            entry.future = self._pool.submit(self._build_one, entry)
        if wait:
            self.wait()

    def wait(self) -> None:
        """Block until every in-flight compile has landed."""
        for entry in self._entries.values():
            if entry.future is not None:
                entry.future.result()
                entry.future = None

    def take(self, name: str) -> PrecompiledFn | None:
        """The precompiled executable for ``name`` (blocking on its
        in-flight compile), or None — unknown name, disabled registry, or
        a failed build — in which case the caller's lazy path runs."""
        if not self.enabled:
            return None
        entry = self._entries.get(name)
        if entry is None:
            return None
        if entry.future is not None:
            entry.future.result()
            entry.future = None
        return entry.result

    # -- introspection -------------------------------------------------------

    def summary(self) -> dict:
        """Inventory for telemetry/stats: per-source counts + build wall."""
        sources: dict[str, int] = {}
        errors = []
        wall = 0.0
        for e in self._entries.values():
            if e.result is not None:
                sources[e.result.source] = sources.get(e.result.source, 0) + 1
                wall += e.wall_s
            elif e.error is not None:
                errors.append(e.name)
        return {
            "entries": len(self._entries),
            "sources": sources,
            "wall_s": round(wall, 3),
            "errors": errors,
        }

    def warm_source(self) -> str:
        """``disk`` when every resolved entry deserialized from an
        artifact (the zero-build restart), else ``compiled``."""
        resolved = [
            e.result.source for e in self._entries.values()
            if e.result is not None
        ]
        if resolved and all(s == "disk" for s in resolved):
            return "disk"
        return "compiled"


def artifact_census(cache_dir: str | None = None) -> dict:
    """Inventory of the shared artifact dir: what a FRESH replica would
    warm from. ``scale/``'s replica spawn path reports this before boot
    (n_artifacts > 0 predicts a ``warm_source == "disk"`` start) and
    serve_bench asserts on it when proving the zero-build restart."""
    cache_dir = cache_dir or default_artifact_dir()
    if not os.path.isdir(cache_dir):
        return {"dir": cache_dir, "n_artifacts": 0, "bytes": 0}
    names = [n for n in sorted(os.listdir(cache_dir)) if n.endswith(".aot")]
    total = 0
    for n in names:
        try:
            total += os.path.getsize(os.path.join(cache_dir, n))
        except OSError:
            pass
    return {"dir": cache_dir, "n_artifacts": len(names), "bytes": total}


def registry_from_cfg(cfg, tracker=None) -> AOTRegistry | None:
    """The config-gated registry (``cfg.compile``): None when AOT is
    switched off, so call sites keep their lazy-jit behavior untouched."""
    from ..obs.emit import config_hash

    c = cfg.get("compile", {})
    if not bool(c.get("aot", True)):
        return None
    return AOTRegistry(
        cache_dir=c.get("dir", "") or None,
        config_hash=config_hash(cfg),
        tracker=tracker,
        artifacts=bool(c.get("artifacts", True)),
    )
