"""Density-only proposal MLP — the learned sampler's network half.

A deliberately small frequency-encoded MLP (default D=2, W=64, 5 bands vs
the main trunk's D=8, W=256, 10 bands) whose ONLY job is to produce a
per-sample raw density the resampler (renderer/sampling.py) turns into a
weight histogram. Per NerfAcc (arXiv 2305.04966) / NeuSample (arXiv
2111.15552): sample placement needs far less capacity than appearance, so
S_p evaluations of this net + S_f of the fine net undercut the reference's
S_c coarse + (S_c + S_f) fine sweeps at matched PSNR.

It rides inside ``models.nerf.network.Network`` as a third branch
(``model="proposal"``, params under the same tree as coarse/fine), so
checkpointing, donation, AOT registration, scene-compat checks, and the
serve engine's bf16 clone all work unchanged.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class ProposalMLP(nn.Module):
    """[..., S, d] points → [..., S, 1] raw density (pre-relu σ).

    Encoding is inline frequency (log-spaced bands, include-input — the
    freq.py formula) over whatever point dimensionality arrives (3-D
    static or 4-D time-conditioned), so the branch needs no encoder
    plumbing. No view dependence: density is direction-free by
    construction, like the main trunk's ``alpha_linear`` head.
    """

    D: int = 2
    W: int = 64
    n_freqs: int = 5
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pts: jax.Array) -> jax.Array:
        if self.n_freqs > 0:
            bands = 2.0 ** jnp.arange(self.n_freqs, dtype=jnp.float32)
            xb = pts[..., None, :] * bands[:, None]
            enc = jnp.stack([jnp.sin(xb), jnp.cos(xb)], axis=-2)
            h = jnp.concatenate(
                [pts, enc.reshape(*pts.shape[:-1], -1)], axis=-1
            )
        else:
            h = pts
        h = h.astype(self.compute_dtype)
        for i in range(self.D):
            h = nn.relu(
                nn.Dense(
                    self.W,
                    dtype=self.compute_dtype,
                    param_dtype=self.param_dtype,
                    name=f"prop_linear_{i}",
                )(h)
            )
        # density head in f32 for numerically stable compositing, matching
        # the main trunk's alpha_linear convention (network.py:172-177)
        return nn.Dense(
            1, param_dtype=self.param_dtype, name="sigma_linear"
        )(h.astype(jnp.float32))
