"""img_fit network: freq-encoded (u, v) → MLP → sigmoid rgb.

Capability parity with the reference's `src/models/img_fit/network.py:8-55`:
a D-layer W-wide ReLU backbone over the uv positional encoding with a
sigmoid rgb head. No chunking loop (network.py:40-49) — memory capping at
eval time is ImgFitRenderer's `lax.map`.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..encoding import get_encoder


class Network(nn.Module):
    D: int = 4
    W: int = 128
    uv_encoder: Callable = None

    @nn.compact
    def __call__(self, uv: jax.Array) -> jax.Array:
        """[..., 2] → [..., 3] rgb in (0, 1)."""
        h = self.uv_encoder(uv)
        for i in range(self.D):
            h = nn.relu(nn.Dense(self.W, name=f"backbone_{i}")(h))
        return jax.nn.sigmoid(nn.Dense(3, name="rgb")(h))


def make_network(cfg) -> Network:
    uv_enc, _ = get_encoder(cfg.network.uv_encoder)
    return Network(
        D=int(cfg.network.D),
        W=int(cfg.network.W),
        uv_encoder=uv_enc,
    )


def init_params(network: Network, key: jax.Array):
    return network.init(key, jnp.zeros((2, 2), jnp.float32))
