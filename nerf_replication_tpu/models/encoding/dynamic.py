"""Time-conditioned encoder family (dynamic-NeRF capability surface).

Capability parity with the reference's dynamic encoder variants
(src/models/encoding/hashencoder/hashgrid.py:241-427 and
src/models/encoding/dnerf.py:12-104): per-frame latent codes, 4-D hash,
basis-grid mixtures, and deformation fields warping points into a canonical
hash grid. All take ``[..., 4]`` inputs ``(x, y, z, t)`` with ``t`` a frame
index in ``[0, num_frames)``.

Jit-compatibility redesign: the reference branches on ``t[0] != 0`` at
runtime (hashgrid.py:276, 380) — host control flow on device data. Here the
"frame 0 is canonical/undeformed" rule is a per-point ``where`` mask, which
is shape-static, differentiable, and strictly generalizes the reference's
whole-batch assumption.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from .freq import frequency_encoder
from .hashgrid import HashGridEncoder, normalize_bbox as _normalize_xyz
from .triplane import _PLANES


def _hash_out_dim(hash_kwargs: dict | None) -> int:
    kw = hash_kwargs or {}
    return int(kw.get("num_levels", 16)) * int(kw.get("level_dim", 2))


class HashLatentEncoder(nn.Module):
    """hash(xyz) ⊕ per-frame latent code (hashgrid.py:289-303)."""

    num_frames: int
    latent_dim: int = 32
    bbox: tuple | None = None
    hash_kwargs: dict | None = None

    def setup(self):
        self.hash = HashGridEncoder(bbox=self.bbox, **(self.hash_kwargs or {}))
        self.latent_t = self.param(
            "latent_t",
            lambda key, shape: jax.random.uniform(
                key, shape, jnp.float32, -1e-4, 1e-4
            ),
            (self.num_frames, self.latent_dim),
        )

    @property
    def out_dim(self) -> int:
        return _hash_out_dim(self.hash_kwargs) + self.latent_dim

    def __call__(self, xyzt: jax.Array) -> jax.Array:
        feat = self.hash(xyzt[..., :3])
        t_idx = jnp.clip(
            xyzt[..., 3].astype(jnp.int32), 0, self.num_frames - 1
        )
        return jnp.concatenate([feat, self.latent_t[t_idx]], axis=-1)


class HashEncoder4d(nn.Module):
    """4-D hash over (xyz normalized, t/num_frames) (hashgrid.py:306-318)."""

    num_frames: int
    bbox: tuple
    hash_kwargs: dict | None = None

    def setup(self):
        kwargs = dict(self.hash_kwargs or {})
        kwargs["input_dim"] = 4
        self.hash = HashGridEncoder(**kwargs)

    @property
    def out_dim(self) -> int:
        return _hash_out_dim(self.hash_kwargs)

    def __call__(self, xyzt: jax.Array) -> jax.Array:
        xyz = _normalize_xyz(xyzt[..., :3], self.bbox)
        t = xyzt[..., 3:] / self.num_frames
        return self.hash(jnp.concatenate([xyz, t], axis=-1))


class HashCoefEncoder(nn.Module):
    """Mixture of basis hash grids with (x,t)-dependent softmax coefficients
    (hashgrid.py:321-351): 6 3-D basis grids + a 4-D hash → 64 → 6 coef head."""

    num_frames: int
    bbox: tuple
    basis_num: int = 6
    hash_kwargs: dict | None = None

    def setup(self):
        kwargs = dict(self.hash_kwargs or {})
        self.basis = [
            HashGridEncoder(**kwargs, name=f"basis_{i}")
            for i in range(self.basis_num)
        ]
        coef_kwargs = dict(kwargs)
        coef_kwargs["input_dim"], coef_kwargs["log2_hashmap_size"] = 4, 20
        self.coefs = HashGridEncoder(**coef_kwargs, name="coefs")
        self.coef_hidden = nn.Dense(64)
        self.coef_out = nn.Dense(self.basis_num)

    @property
    def out_dim(self) -> int:
        return _hash_out_dim(self.hash_kwargs)

    def __call__(self, xyzt: jax.Array) -> jax.Array:
        xyz = _normalize_xyz(xyzt[..., :3], self.bbox)
        xyzt_n = jnp.concatenate(
            [xyz, xyzt[..., 3:] / self.num_frames], axis=-1
        )
        h = nn.relu(self.coef_hidden(self.coefs(xyzt_n)))
        coefs = jax.nn.softmax(self.coef_out(h), axis=-1)
        embs = jnp.stack([b(xyz) for b in self.basis], axis=-2)  # [..., B, F]
        return jnp.sum(embs * coefs[..., None], axis=-2)


class DeformationMLP(nn.Module):
    """MLP time-warp field (dnerf.py:12-104 capability): freq-embedded
    (x, t) → displacement Δx, zero at frame 0."""

    depth: int = 8
    width: int = 128
    xyz_freq: int = 10
    t_freq: int = 4

    @nn.compact
    def __call__(self, xyz: jax.Array, t: jax.Array) -> jax.Array:
        embed_x, _ = frequency_encoder(3, self.xyz_freq)
        embed_t, _ = frequency_encoder(1, self.t_freq)
        h = jnp.concatenate([embed_x(xyz), embed_t(t)], axis=-1)
        inp = h
        for i in range(self.depth):
            h = nn.relu(nn.Dense(self.width, name=f"linear_{i}")(h))
            if i == self.depth // 2:
                h = jnp.concatenate([inp, h], axis=-1)
        delta = nn.Dense(3, name="delta")(h)
        # canonical frame: no deformation at t == 0
        return jnp.where(t == 0.0, 0.0, delta)


class DNeRFEncoder(nn.Module):
    """Deformation-MLP → canonical encoder composition (the reference's
    `dnerf` encoder type). Canonical space is a hash grid here; frame 0 maps
    through unwarped."""

    num_frames: int
    bbox: tuple
    hash_kwargs: dict | None = None
    depth: int = 8
    width: int = 128

    def setup(self):
        self.warp = DeformationMLP(depth=self.depth, width=self.width)
        self.hash = HashGridEncoder(**(self.hash_kwargs or {}))

    @property
    def out_dim(self) -> int:
        return _hash_out_dim(self.hash_kwargs)

    def __call__(self, xyzt: jax.Array) -> jax.Array:
        xyz = _normalize_xyz(xyzt[..., :3], self.bbox)
        t = xyzt[..., 3:] / max(self.num_frames - 1, 1)
        delta = self.warp(xyz, t)
        return self.hash(jnp.clip(xyz + delta, 0.0, 1.0))


class Motion2dEncoder(nn.Module):
    """Tri-plane of 2-D hash grids warped by a sigmoid displacement MLP
    (hashgrid.py:241-287): xyz' = clip(x + 2·mlp(x,t) − 1, 0, 1), identity
    at frame 0."""

    num_frames: int
    bbox: tuple
    mlp_depth: int = 8
    mlp_width: int = 128
    hash_kwargs: dict | None = None

    def setup(self):
        kwargs = dict(self.hash_kwargs or {})
        kwargs["input_dim"] = 2
        self.planes = [
            HashGridEncoder(**kwargs, name=f"plane_{a}{b}") for a, b in _PLANES
        ]
        self.mlp_layers = [
            nn.Dense(self.mlp_width, name=f"mlp_{i}")
            for i in range(self.mlp_depth - 1)
        ]
        self.mlp_out = nn.Dense(3, name="mlp_out")

    @property
    def out_dim(self) -> int:
        return 3 * _hash_out_dim(self.hash_kwargs)

    def __call__(self, xyzt: jax.Array) -> jax.Array:
        xyz = _normalize_xyz(xyzt[..., :3], self.bbox)
        t = xyzt[..., 3:] / max(self.num_frames - 1, 1)
        h = jnp.concatenate([xyz, t], axis=-1)
        for layer in self.mlp_layers:
            h = nn.relu(layer(h))
        delta = jax.nn.sigmoid(self.mlp_out(h))
        warped = jnp.clip(xyz + 2.0 * delta - 1.0, 0.0, 1.0)
        xyz_eff = jnp.where(t == 0.0, xyz, warped)
        feats = [
            plane(xyz_eff[..., (a, b)])
            for plane, (a, b) in zip(self.planes, _PLANES)
        ]
        return jnp.concatenate(feats, axis=-1)


class DNeRFNGPEncoder(nn.Module):
    """Hash grid + factorized (coordinate, time) deformation field with a
    temporal TV regularizer (hashgrid.py:354-427): per axis i, three
    [F, T, R] feature planes sampled at (x_i, t); their per-axis products sum
    to the displacement component Δx_i."""

    num_frames: int
    bbox: tuple
    feat_dim: int = 64
    feat_res: int = 256
    hash_kwargs: dict | None = None

    def setup(self):
        self.hash = HashGridEncoder(**(self.hash_kwargs or {}))
        self.feat = self.param(
            "feat",
            lambda key, shape: 0.1 * jax.random.normal(key, shape, jnp.float32),
            # [axis i of Δx, the 3 factor planes, F, T, R]
            (3, 3, self.feat_dim, self.num_frames, self.feat_res),
        )

    @property
    def out_dim(self) -> int:
        return _hash_out_dim(self.hash_kwargs)

    def _sample_plane(self, plane: jax.Array, u: jax.Array, v: jax.Array):
        """Bilinear sample of [F, T, R] at (u∈[0,1]→T axis, v∈[0,1]→R axis)
        with align_corners=True semantics (hashgrid.py:403)."""
        T, R = plane.shape[-2], plane.shape[-1]
        tu = jnp.clip(u, 0.0, 1.0) * (T - 1)
        rv = jnp.clip(v, 0.0, 1.0) * (R - 1)
        t0 = jnp.clip(jnp.floor(tu).astype(jnp.int32), 0, T - 2)
        r0 = jnp.clip(jnp.floor(rv).astype(jnp.int32), 0, R - 2)
        ft, fr = tu - t0, rv - r0
        p00 = plane[:, t0, r0]
        p01 = plane[:, t0, r0 + 1]
        p10 = plane[:, t0 + 1, r0]
        p11 = plane[:, t0 + 1, r0 + 1]
        return (
            p00 * (1 - ft) * (1 - fr)
            + p01 * (1 - ft) * fr
            + p10 * ft * (1 - fr)
            + p11 * ft * fr
        )  # [F, ...]

    def compute_delta(self, xyz_n: jax.Array, t_n: jax.Array) -> jax.Array:
        """Δxyz [..., 3] from normalized coords and t ∈ [0, 1]."""
        deltas = []
        for i in range(3):
            prod = None
            for j in range(3):
                s = self._sample_plane(
                    self.feat[i, j], t_n[..., 0], xyz_n[..., j]
                )  # [F, ...]
                prod = s if prod is None else prod * s
            deltas.append(jnp.sum(prod, axis=0))
        return jnp.stack(deltas, axis=-1)

    def __call__(self, xyzt: jax.Array) -> jax.Array:
        xyz = _normalize_xyz(xyzt[..., :3], self.bbox)
        t = xyzt[..., 3:] / max(self.num_frames - 1, 1)
        delta = jnp.where(t == 0.0, 0.0, self.compute_delta(xyz, t))
        return self.hash(jnp.clip(xyz + delta, 0.0, 1.0))

    def tv_loss(self, xyzt: jax.Array) -> jax.Array:
        """Temporal smoothness: ‖Δ(t) − Δ(t−1)‖² (‖Δ(0)‖² at frame 0)
        (hashgrid.py:410-427)."""
        xyz = _normalize_xyz(xyzt[..., :3], self.bbox)
        t_idx = xyzt[..., 3:]
        denom = max(self.num_frames - 1, 1)
        d_now = self.compute_delta(xyz, t_idx / denom)
        d_prev = self.compute_delta(xyz, (t_idx - 1.0) / denom)
        sq = jnp.where(
            t_idx == 0.0,
            jnp.sum(d_now**2, axis=-1, keepdims=True),
            jnp.sum((d_now - d_prev) ** 2, axis=-1, keepdims=True),
        )
        return jnp.mean(sq)
