"""Cell-packed multiresolution hash grid — the TPU-native redesign of the
instant-ngp encoder.

Same capability seat as the reference's CUDA hash encoder
(src/models/encoding/hashencoder/src/hashencoder.cu:99-196 forward,
254-267 atomicAdd backward), but re-laid-out for what this chip actually
runs fast (BENCH_PRIMITIVES.jsonl): row gathers cost ~6-9 ns per ROW
almost independent of row width, and scatter-add is ~23M rows/s. The
classic corner-shared layout needs 2^D narrow gathers per (point, level)
forward and 2^D scatter rows backward — measured 6-10 s/step at 4096 rays
(PERF.md round 3). This layout changes both:

* **One wide gather per (point, level).** Each table row packs ALL 2^D
  corner values of one CELL (row width 2^D*C floats, 64 B at C=2). The
  trilinear blend then happens in registers. Forward cost drops 8x by
  construction: 2^D-fewer gather rows at near-constant per-row cost.
* **Scatter-free backward.** The table cotangent is per-level
  ``ops.indexed_row_sum`` (sort + cumsum + merge-extraction) over [N, 8C]
  update rows — built only from sort/cumsum/gather, the primitives the
  chip runs at 300-400M rows/s.

The trade (documented, deliberate): cells do NOT share corner entries
with their neighbours, so the interpolated field is piecewise-trilinear
with discontinuities at cell faces — the same *kind* of artifact as
instant-ngp's hash collisions, which its MLP demonstrably learns around;
tests/test_packed_hash.py pins a procedural-scene PSNR within tolerance
of the corner-shared encoder, and QUALITY trails carry the measured
quality side by side. Param budget per level matches the reference rule
(min(2^log2_hashmap_size, full grid) entries of C floats): a bucket holds
2^D entries, so levels get min(2^log2/2^D, n_cells^D) buckets.
"""

from __future__ import annotations

import numpy as np

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops import indexed_row_sum
from .hashgrid import _PRIMES, normalize_bbox


def packed_level_geometry(
    input_dim: int,
    num_levels: int,
    per_level_scale: float,
    base_resolution: int,
    log2_hashmap_size: int,
):
    """Static per-level constants for the cell-packed layout.

    Returns (offsets [L+1], scales [L], n_cells [L], use_hash [L]):
    bucket offsets into the flat table, the float grid scale, cells per
    dim, and whether the level hashes its cell id (static per level, as in
    hashgrid.level_geometry / hashencoder.cu:56-74).
    """
    corners = 1 << input_dim
    bucket_cap = max(2**log2_hashmap_size // corners, 1)
    offsets, scales, n_cells_l, use_hash = [0], [], [], []
    s = float(per_level_scale)
    for lvl in range(num_levels):
        scale = 2.0 ** (lvl * np.log2(s)) * base_resolution - 1.0
        # pos = x*scale + 0.5 in [0.5, scale+0.5] => cell ids 0..ceil(scale)
        n_cells = int(np.ceil(scale)) + 1
        dense_buckets = n_cells**input_dim
        hashed = dense_buckets > bucket_cap
        if hashed:
            buckets = max(int(bucket_cap / 8) * 8, 8)
        else:
            # round UP: rounding down would alias the top row-major cells
            # onto buckets 0..k via the modulo — a silent collision on a
            # level the layout promises is collision-free
            buckets = max(-(-dense_buckets // 8) * 8, 8)
        offsets.append(offsets[-1] + buckets)
        scales.append(float(scale))
        n_cells_l.append(n_cells)
        use_hash.append(hashed)
    return offsets, scales, n_cells_l, use_hash


def _cell_index(cell, n_cells: int, buckets: int, hashed: bool):
    """Bucket id of a cell (pre-offset); dense row-major or XOR-prime hash
    (hashencoder.cu:56-74 semantics on cells instead of corners)."""
    d = cell.shape[-1]
    if not hashed:
        stride = 1
        index = jnp.zeros(cell.shape[:-1], jnp.uint32)
        for dd in range(d):
            index = index + cell[..., dd].astype(jnp.uint32) * jnp.uint32(stride)
            stride *= n_cells
    else:
        index = jnp.zeros(cell.shape[:-1], jnp.uint32)
        for dd in range(d):
            index = index ^ (
                cell[..., dd].astype(jnp.uint32) * jnp.uint32(_PRIMES[dd])
            )
    return (index % jnp.uint32(buckets)).astype(jnp.int32)


def _cells_and_weights(x, scale: float, input_dim: int):
    """cell ids [N, D] int32, corner weights [N, 2^D], frac [N, D]."""
    pos = x * scale + 0.5
    cell = jnp.floor(pos)
    frac = pos - cell
    cell = cell.astype(jnp.int32)
    w_cols = []
    for bits in range(1 << input_dim):
        w = jnp.ones(x.shape[:-1], x.dtype)
        for dd in range(input_dim):
            w = w * (frac[..., dd] if (bits >> dd) & 1 else 1.0 - frac[..., dd])
        w_cols.append(w)
    return cell, jnp.stack(w_cols, axis=-1), frac


def packed_hash_encode(
    x: jax.Array,  # [..., D] in [0, 1]
    table: jax.Array,  # [total_buckets, 2^D * C]
    input_dim: int,
    num_levels: int,
    per_level_scale: float,
    base_resolution: int,
    log2_hashmap_size: int,
    gather_dtype: str = "float32",
) -> jax.Array:
    """[..., D] -> [..., L*C]; pure function of (x, table). Plain-autodiff
    variant (its backward scatters) — production goes through
    :func:`packed_hash_encode_vjp`.

    ``gather_dtype="bfloat16"`` casts the table ONCE per call and gathers
    half-width rows (measured +45% row rate at width 16 — the gather cost
    is per-row but drops when rows pack into fewer tiles). Positions,
    weights, and the blend stay f32; the f32 master param and its f32
    cotangent are untouched (the cast is inside the traced fn).
    """
    offsets, scales, n_cells_l, use_hash = packed_level_geometry(
        input_dim, num_levels, per_level_scale, base_resolution,
        log2_hashmap_size,
    )
    corners = 1 << input_dim
    c = table.shape[-1] // corners
    tab_g = table.astype(jnp.dtype(gather_dtype))
    batch_shape = x.shape[:-1]
    if len(batch_shape) != 1:
        x = x.reshape(-1, input_dim)
    n = x.shape[0]
    outs = []
    for lvl in range(num_levels):
        cell, w, _ = _cells_and_weights(x, scales[lvl], input_dim)
        idx = _cell_index(
            cell, n_cells_l[lvl], offsets[lvl + 1] - offsets[lvl],
            use_hash[lvl],
        )
        row = jnp.take(tab_g, idx + offsets[lvl], axis=0)  # [N, 2^D*C]
        vals = row.reshape(n, corners, c)
        outs.append(jnp.sum(w[..., None] * vals.astype(w.dtype), axis=1))
    out = jnp.concatenate(outs, axis=-1)
    if len(batch_shape) != 1:
        out = out.reshape(*batch_shape, out.shape[-1])
    return out


def packed_hash_encode_vjp(
    x: jax.Array,
    table: jax.Array,
    input_dim: int,
    num_levels: int,
    per_level_scale: float,
    base_resolution: int,
    log2_hashmap_size: int,
    gather_dtype: str = "float32",
) -> jax.Array:
    """packed_hash_encode with the scatter-free custom backward.

    dtable: per-level ``indexed_row_sum`` over [N, 2^D*C] update rows
    (outer(corner weights, level cotangent)) — sorts and gathers only,
    accumulated in f32 regardless of ``gather_dtype``.
    dx: exact, via the corner-weight derivative against re-gathered rows;
    when x carries no gradient path (rays are data), XLA DCE prunes it.
    """
    static = (input_dim, num_levels, per_level_scale, base_resolution,
              log2_hashmap_size, gather_dtype)

    @jax.custom_vjp
    def encode(x, table):
        return packed_hash_encode(x, table, *static)

    def fwd(x, table):
        return encode(x, table), (x, table)

    def bwd(res, g):
        x, table = res
        offsets, scales, n_cells_l, use_hash = packed_level_geometry(
            *static[:5]
        )
        corners = 1 << input_dim
        c = table.shape[-1] // corners
        batch_shape = x.shape[:-1]
        if len(batch_shape) != 1:
            x_flat = x.reshape(-1, input_dim)
            g_flat = g.reshape(-1, g.shape[-1])
        else:
            x_flat, g_flat = x, g
        n = x_flat.shape[0]
        g_flat = g_flat.astype(jnp.float32)

        grad_slices = []
        # per-dim accumulators, stacked at the end (an .at[:, d].add here
        # would lower to the very scatter this backward exists to avoid)
        dx_cols = [jnp.zeros((n,), jnp.float32) for _ in range(input_dim)]
        for lvl in range(num_levels):
            buckets = offsets[lvl + 1] - offsets[lvl]
            cell, w, frac = _cells_and_weights(
                x_flat.astype(jnp.float32), scales[lvl], input_dim
            )
            idx = _cell_index(cell, n_cells_l[lvl], buckets, use_hash[lvl])
            g_lvl = g_flat[:, lvl * c:(lvl + 1) * c]  # [N, C]
            # dtable rows: outer(w, g_lvl) -> [N, 2^D * C]
            upd = (w[:, :, None] * g_lvl[:, None, :]).reshape(n, corners * c)
            grad_slices.append(indexed_row_sum(idx, upd, int(buckets)))

            # dx: d(out_l)/dx_d = scale * sum_b sign_b(d) *
            #     prod_{d' != d} wfac_b(d') * <row_b, g_lvl>
            row = jnp.take(
                table.astype(jnp.dtype(gather_dtype)),
                idx + offsets[lvl], axis=0,
            )
            vals = row.reshape(n, corners, c).astype(jnp.float32)
            rowg = jnp.sum(vals * g_lvl[:, None, :], axis=-1)  # [N, 2^D]
            for dd in range(input_dim):
                acc = jnp.zeros((n,), jnp.float32)
                for bits in range(corners):
                    f = jnp.ones((n,), jnp.float32)
                    for d2 in range(input_dim):
                        if d2 == dd:
                            continue
                        f = f * (frac[..., d2] if (bits >> d2) & 1
                                 else 1.0 - frac[..., d2])
                    sign = 1.0 if (bits >> dd) & 1 else -1.0
                    acc = acc + sign * f * rowg[:, bits]
                dx_cols[dd] = dx_cols[dd] + scales[lvl] * acc

        dx = jnp.stack(dx_cols, axis=-1)
        dtable = jnp.concatenate(grad_slices, axis=0).astype(table.dtype)
        dx = dx.astype(x.dtype)
        if len(batch_shape) != 1:
            dx = dx.reshape(*batch_shape, input_dim)
        return dx, dtable

    encode.defvjp(fwd, bwd)
    return encode(x, table)


class PackedHashGridEncoder(nn.Module):
    """Flax module owning the cell-packed embedding table (uniform +-1e-4
    init, matching hashgrid.py:184-186's convention), world-bounds
    normalization to [0, 1]. Drop-in for HashGridEncoder: same config
    knobs, same out_dim."""

    input_dim: int = 3
    num_levels: int = 16
    level_dim: int = 2
    per_level_scale: float = 2.0
    base_resolution: int = 16
    log2_hashmap_size: int = 19
    desired_resolution: int = -1
    bbox: tuple | None = None
    custom_bwd: bool = True  # scatter-free VJP (the point of this layout)
    gather_dtype: str = "float32"  # "bfloat16": half-width gather rows

    @property
    def scale_factor(self) -> float:
        if self.desired_resolution != -1:
            return float(
                2.0
                ** (
                    np.log2(self.desired_resolution / self.base_resolution)
                    / (self.num_levels - 1)
                )
            )
        return float(self.per_level_scale)

    @property
    def out_dim(self) -> int:
        return self.num_levels * self.level_dim

    @property
    def n_buckets(self) -> int:
        offsets, _, _, _ = packed_level_geometry(
            self.input_dim, self.num_levels, self.scale_factor,
            self.base_resolution, self.log2_hashmap_size,
        )
        return offsets[-1]

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        table = self.param(
            "embeddings",
            lambda key, shape: jax.random.uniform(
                key, shape, jnp.float32, -1e-4, 1e-4
            ),
            (self.n_buckets, (1 << self.input_dim) * self.level_dim),
        )
        if self.bbox is not None:
            x = normalize_bbox(x, self.bbox)
        else:
            x = jnp.clip(x, 0.0, 1.0)
        encode = (packed_hash_encode_vjp if self.custom_bwd
                  else packed_hash_encode)
        return encode(
            x,
            table,
            self.input_dim,
            self.num_levels,
            self.scale_factor,
            self.base_resolution,
            self.log2_hashmap_size,
            self.gather_dtype,
        )

    @classmethod
    def from_cfg(cls, enc_cfg, precision=None) -> "PackedHashGridEncoder":
        bbox = enc_cfg.get("bbox", None)
        if bbox is None:
            raise ValueError(
                "hashgrid_packed encoder config needs "
                "'bbox: [[lo...],[hi...]]' world bounds for [0,1] "
                "normalization"
            )
        # gather rows stay f32 unless pinned explicitly: the chip's gather
        # cost is per-ROW, nearly width-independent (BENCH_PRIMITIVES), so
        # half-width bf16 rows buy nothing and the per-step table cast
        # measurably costs ~10% (BENCH_SWEEP_HASH round 4: 10.2k vs 11.3k
        # rays/s at 4096). ``precision`` is accepted for future dtype-aware
        # layouts but deliberately not consulted here.
        del precision
        gather_dtype = str(enc_cfg.get("gather_dtype", "float32"))
        return cls(
            input_dim=int(enc_cfg.get("input_dim", 3)),
            num_levels=int(enc_cfg.get("num_levels", 16)),
            level_dim=int(enc_cfg.get("level_dim", 2)),
            per_level_scale=float(enc_cfg.get("per_level_scale", 2.0)),
            base_resolution=int(enc_cfg.get("base_resolution", 16)),
            log2_hashmap_size=int(enc_cfg.get("log2_hashmap_size", 19)),
            desired_resolution=int(enc_cfg.get("desired_resolution", -1)),
            bbox=tuple(map(tuple, bbox)),
            custom_bwd=bool(enc_cfg.get("custom_bwd", True)),
            gather_dtype=gather_dtype,
        )
