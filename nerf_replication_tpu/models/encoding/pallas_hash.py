"""Pallas TPU kernel for the multiresolution hash-grid forward pass.

The CUDA reference implements the encoder as hand-written kernels
(src/models/encoding/hashencoder/src/hashencoder.cu:99-196 forward,
254-267 backward). This is the TPU counterpart of the *forward* gather +
D-linear MAC, built per SURVEY.md §7 step 8, with the design constraints
Mosaic imposes:

* **Layout.** A level's table slice is packed ``[R, 128·C]``: entry ``e``,
  feature ``c`` live at row ``e // 128``, lane ``c·128 + e % 128``. The raw
  ``[entries, C]`` layout (C=2) would waste 64× VMEM to lane padding; packed,
  a full 2^19-entry slice is 4 MB and fits VMEM beside the point block.
* **Grid.** ``(L, N/blk)`` — one program interpolates one level for one
  point block. Per-level scalars (scale, resolution, hashed?, slice size)
  ride in via ``PrefetchScalarGridSpec`` so the kernel body is one traced
  program for all levels; the dense/hash decision is a ``jnp.where`` select
  on uint32 index math, not control flow.
* **Gather.** The 2^D corner loop is a static Python loop of row+lane
  gathers from the VMEM-resident slice. Random gather is the op TPUs are
  weakest at — whether this beats XLA's own gather lowering is an empirical
  question, which is exactly why both paths exist behind one dispatch
  (``use_pallas``) and one oracle test. The backward pass is NOT a kernel:
  differentiating the pure-XLA formulation yields a segment-sum scatter-add,
  the TPU-idiomatic equivalent of the CUDA ``atomicAdd`` backward
  (SURVEY.md §2.2) — so the custom_vjp pairs the Pallas forward with the
  XLA backward.

Correctness: validated against ``hash_encode`` (the pure-XLA oracle) in
``tests/test_pallas_hash.py`` under interpret mode on CPU.

**Measured verdict (round 3, TPU v5 lite — PERF.md):** Mosaic rejects the
in-kernel row gather at lowering time ("Shape mismatch in input, indices
and output"; eval_shape tracing is clean, so it is the backend, not the
wrapper). ``hash_encode`` is therefore the production path; this kernel is
retained as the interpret-tested reference design and the recorded
negative result for in-kernel gathers on this Mosaic version.

**Round-4 closure (FINAL — VERDICT r3 #7).** Pinned negative:

* Stack: jax/jaxlib 0.9.0, libtpu via the axon terminal (v5e target).
  Rejected op: the in-kernel vector gather ``table_slice[idx_vec]``
  (rows from a VMEM-resident [R, 128·C] block addressed by a computed
  uint32 vector) — Mosaic lowering fails with
  ``ValueError: Shape mismatch in input, indices and output`` while
  interpret mode and trace-time eval_shape both pass (BENCH_HASH.jsonl
  pallas rows).
* The remaining in-Mosaic alternatives are serialized by construction
  (per-row ``dynamic_slice`` in a ``fori_loop``, or per-row async-copy
  DMA): both issue one row per loop iteration, which cannot beat the
  ~6-9 ns/row the XLA gather path already achieves
  (BENCH_PRIMITIVES.jsonl, forced-sync harness) — the wall is the
  hardware's dynamic-address issue rate, not XLA's lowering.
* Round 4 made the question moot for production: the cell-packed layout
  (packed_hash.py) reduced the encoder to ONE wide gather per (point,
  level) with a sort-based scatter-free backward; the encoder is no
  longer the step's bottleneck at any measured shape. NOTE: the round-3
  "xla" timing rows in BENCH_HASH.jsonl predate the forced-sync timing
  harness and are elision-corrupted (a 99 MB-gradient fwd+bwd "in
  25 us"); the trustworthy encoder rates live in BENCH_PRIMITIVES.jsonl
  and the full-step BENCH_SWEEP_HASH.jsonl rows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .hashgrid import _PRIMES, hash_encode, level_geometry

_LANES = 128


def pack_table(
    table: jax.Array,  # [total_entries, C]
    offsets,  # [L+1] python ints
) -> jax.Array:
    """Repack the flat table into ``[L, R_max, 128·C]`` level pages.

    Rows beyond a level's slice are zero — harmless, since index math never
    reaches them (indices are mod slice size).
    """
    num_levels = len(offsets) - 1
    c = table.shape[-1]
    s_max = max(offsets[lvl + 1] - offsets[lvl] for lvl in range(num_levels))
    r_max = -(-s_max // _LANES)
    pages = []
    for lvl in range(num_levels):
        sl = table[offsets[lvl] : offsets[lvl + 1]]
        pad = r_max * _LANES - sl.shape[0]
        sl = jnp.pad(sl, ((0, pad), (0, 0)))
        # [R·128, C] -> [R, 128, C] -> [R, C, 128] -> [R, C·128]
        page = sl.reshape(r_max, _LANES, c).transpose(0, 2, 1)
        pages.append(page.reshape(r_max, c * _LANES))
    return jnp.stack(pages)


def _hash_kernel(
    scales_ref,  # f32[L]   scalar-prefetch
    resolutions_ref,  # i32[L]
    hashed_ref,  # i32[L]  (0/1)
    sizes_ref,  # i32[L]  slice entry counts
    x_ref,  # [blk, D] VMEM
    page_ref,  # [R, C·128] VMEM — this level's packed slice
    out_ref,  # [C, blk] VMEM — this level's features for this block
    *,
    input_dim: int,
    level_dim: int,
):
    lvl = pl.program_id(0)
    scale = scales_ref[lvl]
    resolution = resolutions_ref[lvl]
    hashed = hashed_ref[lvl]
    hashmap_size = sizes_ref[lvl].astype(jnp.uint32)

    x = x_ref[:]  # [blk, D]
    pos = x * scale + 0.5  # cu:109
    pos_grid = jnp.floor(pos)
    frac = pos - pos_grid
    pos_grid = pos_grid.astype(jnp.int32)

    d = input_dim
    c = level_dim
    page = page_ref[:]  # [R, C·128]

    acc = jnp.zeros((c, x.shape[0]), jnp.float32)
    for corner_bits in range(1 << d):
        # per-dim scalar offsets (Python ints — a vector constant would be
        # a captured const, which pallas_call rejects)
        sel = [(corner_bits >> dd) & 1 for dd in range(d)]

        # dense row-major and XOR-prime hash, both computed per-dim, select
        # by the prefetched per-level flag (static-shape, no branching)
        dense = jnp.zeros(x.shape[:1], jnp.uint32)
        stride = jnp.uint32(1)
        hashv = jnp.zeros(x.shape[:1], jnp.uint32)
        w = jnp.ones(x.shape[0], jnp.float32)
        for dd in range(d):
            corner_d = (pos_grid[..., dd] + sel[dd]).astype(jnp.uint32)
            dense = dense + corner_d * stride
            stride = stride * (resolution.astype(jnp.uint32) + 1)
            hashv = hashv ^ (corner_d * jnp.uint32(_PRIMES[dd]))
            w = w * (frac[..., dd] if sel[dd] else 1.0 - frac[..., dd])
        index = jnp.where(hashed == 1, hashv, dense) % hashmap_size

        row = (index // _LANES).astype(jnp.int32)  # [blk]
        lane = (index % _LANES).astype(jnp.int32)  # [blk]
        rows = jnp.take(page, row, axis=0)  # [blk, C·128]
        for cc in range(c):
            vals = jnp.take_along_axis(
                rows, (lane + cc * _LANES)[:, None], axis=1
            )[:, 0]
            acc = acc.at[cc, :].add(w * vals)
    out_ref[:] = acc


def pallas_hash_encode(
    x: jax.Array,  # [N, D] in [0, 1]
    table: jax.Array,  # [total_entries, C]
    input_dim: int,
    num_levels: int,
    per_level_scale: float,
    base_resolution: int,
    log2_hashmap_size: int,
    block_size: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Forward-only Pallas path; same contract as ``hash_encode``."""
    offsets, scales, resolutions, use_hash = level_geometry(
        input_dim, num_levels, per_level_scale, base_resolution,
        log2_hashmap_size,
    )
    n = x.shape[0]
    blk = min(block_size, n)
    n_blocks = -(-n // blk)
    pad = n_blocks * blk - n
    x_p = jnp.pad(x, ((0, pad), (0, 0)))

    pages = pack_table(table, offsets)  # [L, R, C·128]
    c = table.shape[-1]
    r_max = pages.shape[1]

    sizes = np.asarray(
        [offsets[lvl + 1] - offsets[lvl] for lvl in range(num_levels)],
        np.int32,
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(num_levels, n_blocks),
        in_specs=[
            pl.BlockSpec((blk, input_dim), lambda l, b, *_: (b, 0)),
            pl.BlockSpec((1, r_max, c * _LANES), lambda l, b, *_: (l, 0, 0)),
        ],
        # [C, blk] feature-major output block: lanes carry points (tiling-
        # friendly); the host-side transpose below restores [N, L·C]
        out_specs=pl.BlockSpec((1, c, blk), lambda l, b, *_: (l, 0, b)),
    )

    kernel = functools.partial(
        _squeeze_page_kernel, input_dim=input_dim, level_dim=c
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (num_levels, c, n_blocks * blk), jnp.float32
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        jnp.asarray(scales, jnp.float32),
        jnp.asarray(resolutions, jnp.int32),
        jnp.asarray(use_hash, jnp.int32),
        jnp.asarray(sizes),
        x_p.astype(jnp.float32),
        pages.astype(jnp.float32),
    )
    # [L, C, N] -> [N, L·C] matching hash_encode's per-level concat
    out = jnp.transpose(out, (2, 0, 1)).reshape(
        n_blocks * blk, num_levels * c
    )
    return out[:n]


def _squeeze_page_kernel(
    scales_ref, resolutions_ref, hashed_ref, sizes_ref,
    x_ref, page_ref, out_ref, *, input_dim: int, level_dim: int,
):
    """Adapter: drop the leading level axis the BlockSpecs carry."""
    _hash_kernel(
        scales_ref, resolutions_ref, hashed_ref, sizes_ref,
        x_ref, page_ref.at[0], out_ref.at[0],
        input_dim=input_dim, level_dim=level_dim,
    )


# -- custom_vjp: Pallas forward + XLA segment-sum backward -------------------


def make_hash_encode_fn(
    input_dim: int,
    num_levels: int,
    per_level_scale: float,
    base_resolution: int,
    log2_hashmap_size: int,
    use_pallas: bool = False,
    interpret: bool = False,
):
    """Return ``encode(x, table) -> [N, L·C]``.

    ``use_pallas=False`` → the pure-XLA formulation (oracle; also its own
    backward). ``use_pallas=True`` → Pallas forward, with the backward taken
    from the XLA formulation's VJP (gather-transpose = scatter-add =
    segment-sum; writing that as a second kernel would re-implement what XLA
    already lowers idiomatically, cu:254-267 ≙ segment_sum).
    """
    static = dict(
        input_dim=input_dim,
        num_levels=num_levels,
        per_level_scale=per_level_scale,
        base_resolution=base_resolution,
        log2_hashmap_size=log2_hashmap_size,
    )

    def xla_encode(x, table):
        return hash_encode(x, table, **static)

    if not use_pallas:
        return xla_encode

    @jax.custom_vjp
    def encode(x, table):
        return pallas_hash_encode(x, table, interpret=interpret, **static)

    def fwd(x, table):
        return encode(x, table), (x, table)

    def bwd(res, g):
        x, table = res
        _, vjp = jax.vjp(xla_encode, x, table)
        return vjp(g)

    encode.defvjp(fwd, bwd)
    return encode
