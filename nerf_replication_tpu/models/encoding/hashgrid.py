"""Multiresolution hash-grid encoder (instant-ngp style), TPU-native.

Capability parity with the reference's native CUDA component — the
hash-encoder kernels (src/models/encoding/hashencoder/src/hashencoder.cu:
99-196) and their module wrapper (src/models/encoding/hashencoder/
hashgrid.py:121-227) — re-derived from the math rather than translated:

* **Level geometry** (hashencoder.cu:101-102, hashgrid.py:167-177):
  ``scale_l = 2^(l·S)·H − 1`` with ``S = log2(per_level_scale)``,
  ``resolution_l = ceil(scale_l) + 1``; table slice per level holds
  ``min(2^log2_hashmap_size, (ceil(H·s^l)+1)^D)`` entries rounded down to a
  multiple of 8.
* **Indexing** (hashencoder.cu:56-74): dense row-major over voxel corners
  while the level fits its table slice, otherwise the XOR-prime
  ``fast_hash`` — whether a level hashes is STATIC (a compile-time constant
  per level), so XLA sees no data-dependent branching.
* **Interpolation** (hashencoder.cu:116-149): D-linear blend of the 2^D
  corners; the corner loop is a static Python loop of 2^D fused
  gather+multiply-accumulate steps over all levels at once.

The backward pass needs no hand-written kernel: differentiating the gather
yields a scatter-add, which XLA lowers to the TPU-idiomatic segment-sum —
the role of the CUDA ``atomicAdd`` backward (hashencoder.cu:254-267,
SURVEY.md §2.2). Forward+backward are one fused jitted program.
"""

from __future__ import annotations

import numpy as np

import flax.linen as nn
import jax
import jax.numpy as jnp

# fast_hash primes (hashencoder.cu:43); 1 for dim 0 keeps memory coherence
_PRIMES = (1, 19349663, 83492791, 25165843, 6291469, 12582917, 3145739)


def normalize_bbox(x: jax.Array, bbox) -> jax.Array:
    """Clip to world bounds and scale into [0, 1] by the LARGEST extent
    (hashgrid.py:191-199's wbounds normalization) — the one convention every
    encoder in the family shares."""
    lo = jnp.asarray(bbox[0], x.dtype)
    hi = jnp.asarray(bbox[1], x.dtype)
    return (jnp.clip(x, lo, hi) - lo) / (jnp.max(hi - lo) + 1e-6)


def level_geometry(
    input_dim: int,
    num_levels: int,
    per_level_scale: float,
    base_resolution: int,
    log2_hashmap_size: int,
):
    """Static per-level constants.

    Returns (offsets [L+1], scales [L], resolutions [L], use_hash [L]):
    table-slice offsets in entries, the float grid scale, the corner-grid
    resolution, and whether the level indexes by hash (static!).
    """
    max_params = 2**log2_hashmap_size
    offsets, scales, resolutions, use_hash = [0], [], [], []
    s = float(per_level_scale)
    for lvl in range(num_levels):
        # allocation-side resolution (hashgrid.py:167-171)
        res_alloc = int(np.ceil(base_resolution * s**lvl))
        params_in_level = min(max_params, (res_alloc + 1) ** input_dim)
        params_in_level = int(params_in_level / 8) * 8
        offsets.append(offsets[-1] + params_in_level)

        # kernel-side geometry (hashencoder.cu:101-102)
        scale = 2.0 ** (lvl * np.log2(s)) * base_resolution - 1.0
        resolution = int(np.ceil(scale)) + 1
        scales.append(float(scale))
        resolutions.append(resolution)
        # dense iff the full corner grid fits the slice (cu:61-68 semantics)
        use_hash.append((resolution + 1) ** input_dim > params_in_level)
    return offsets, scales, resolutions, use_hash


def _corner_index(
    corner: jax.Array,  # [..., D] int32 corner coords of one level
    resolution: int,
    hashmap_size: int,
    hashed: bool,
) -> jax.Array:
    """Table index of a corner (pre-offset), static dense/hash selection."""
    d = corner.shape[-1]
    if not hashed:
        # row-major: sum_d corner_d * (resolution+1)^d  (cu:61-65)
        stride = 1
        index = jnp.zeros(corner.shape[:-1], jnp.uint32)
        for dd in range(d):
            index = index + corner[..., dd].astype(jnp.uint32) * jnp.uint32(stride)
            stride *= resolution + 1
    else:
        index = jnp.zeros(corner.shape[:-1], jnp.uint32)
        for dd in range(d):
            index = index ^ (
                corner[..., dd].astype(jnp.uint32) * jnp.uint32(_PRIMES[dd])
            )
    return (index % jnp.uint32(hashmap_size)).astype(jnp.int32)


def hash_encode(
    x: jax.Array,  # [..., D] in [0, 1]
    table: jax.Array,  # [total_entries, C]
    input_dim: int,
    num_levels: int,
    per_level_scale: float,
    base_resolution: int,
    log2_hashmap_size: int,
) -> jax.Array:
    """[..., D] → [..., L·C]; pure function of (x, table)."""
    offsets, scales, resolutions, use_hash = level_geometry(
        input_dim, num_levels, per_level_scale, base_resolution,
        log2_hashmap_size,
    )
    d = input_dim
    # flatten leading batch dims around the gather: renderer batches arrive
    # [rays, samples, D], and gather/scatter with two batch dims lowers far
    # worse on TPU than the flat [N, D] shape the encoder microbench runs
    # (PERF.md round 3: 1.4 G points/s flat vs a ~50x-slower training step)
    batch_shape = x.shape[:-1]
    if len(batch_shape) != 1:
        x = x.reshape(-1, d)
    outs = []
    for lvl in range(num_levels):
        scale = scales[lvl]
        pos = x * scale + 0.5  # cu:109
        pos_grid = jnp.floor(pos)
        frac = pos - pos_grid
        pos_grid = pos_grid.astype(jnp.int32)

        acc = None
        for corner_bits in range(1 << d):
            sel = [(corner_bits >> dd) & 1 for dd in range(d)]
            corner = pos_grid + jnp.asarray(sel, jnp.int32)
            w = jnp.ones(x.shape[:-1], x.dtype)
            for dd in range(d):
                w = w * (frac[..., dd] if sel[dd] else 1.0 - frac[..., dd])
            idx = _corner_index(
                corner,
                resolutions[lvl],
                offsets[lvl + 1] - offsets[lvl],
                use_hash[lvl],
            )
            vals = jnp.take(table, idx + offsets[lvl], axis=0)
            contrib = w[..., None] * vals
            acc = contrib if acc is None else acc + contrib
        outs.append(acc)
    out = jnp.concatenate(outs, axis=-1)
    if len(batch_shape) != 1:
        out = out.reshape(*batch_shape, out.shape[-1])
    return out


def _encode_with_per_level_bwd(
    x: jax.Array,
    table: jax.Array,
    input_dim: int,
    num_levels: int,
    per_level_scale: float,
    base_resolution: int,
    log2_hashmap_size: int,
) -> jax.Array:
    """hash_encode with a hand-written backward that scatters per level.

    Autodiff of ``hash_encode`` emits the table cotangent as L·2^D
    scatter-adds against the ONE concatenated [total_entries, C] operand;
    XLA's TPU lowering of those turns the train step into a modeled
    24.7 TB/step memory-traffic program (PERF.md round 3, f2 cost
    analysis) — ~400-650 rays/s where the chip's scatter-add tops out at
    ~23M rows/s however it is hinted (BENCH_PRIMITIVES.jsonl). This VJP
    recomputes the (cheap, vectorized) index and weight math in the
    backward and reduces each level's rows with the scatter-free sorted
    histogram (ops.indexed_row_sum: sort + cumsum + merge-extraction).
    The x cotangent is taken through autodiff of the table-frozen
    forward — that path is gathers only, no scatters.

    Replaces the atomic-add backward of the reference's CUDA kernel
    (hashencoder.cu:254-267) with sort-rate segment sums — the same
    capability, lowered TPU-idiomatically.
    """
    static = (input_dim, num_levels, per_level_scale, base_resolution,
              log2_hashmap_size)

    @jax.custom_vjp
    def encode(x, table):
        return hash_encode(x, table, *static)

    def fwd(x, table):
        return encode(x, table), (x, table)

    def bwd(res, g):
        x, table = res
        batch_shape = x.shape[:-1]
        if len(batch_shape) != 1:
            x_flat = x.reshape(-1, input_dim)
            g_flat = g.reshape(-1, g.shape[-1])
        else:
            x_flat, g_flat = x, g
        offsets, scales, resolutions, use_hash = level_geometry(*static)
        c = table.shape[-1]

        # dx through the frozen-table forward: gathers only
        _, vjp_x = jax.vjp(lambda x_: hash_encode(x_, table, *static), x)
        (dx,) = vjp_x(g)

        # dtable per level: recompute idx/w (cheap vector math), then the
        # scatter-FREE sorted histogram (ops.indexed_row_sum): measured on
        # this chip, EVERY scatter-add variant — duplicate, sorted, unique —
        # lowers to ~23M rows/s (BENCH_PRIMITIVES.jsonl), while sort /
        # cumsum / gather run at 120-420M rows/s; the histogram is built
        # from only the fast three
        grad_slices = []
        for lvl in range(num_levels):
            pos = x_flat * scales[lvl] + 0.5
            pos_grid = jnp.floor(pos)
            frac = pos - pos_grid
            pos_grid = pos_grid.astype(jnp.int32)
            g_lvl = g_flat[:, lvl * c:(lvl + 1) * c]
            n_entries = offsets[lvl + 1] - offsets[lvl]
            idx_cols, upd_cols = [], []
            for corner_bits in range(1 << input_dim):
                sel = [(corner_bits >> dd) & 1 for dd in range(input_dim)]
                corner = pos_grid + jnp.asarray(sel, jnp.int32)
                w = jnp.ones(x_flat.shape[:-1], x_flat.dtype)
                for dd in range(input_dim):
                    w = w * (frac[..., dd] if sel[dd] else 1.0 - frac[..., dd])
                idx_cols.append(_corner_index(
                    corner, resolutions[lvl], n_entries, use_hash[lvl]
                ))
                upd_cols.append(w[:, None] * g_lvl)
            idx_lvl = jnp.concatenate(idx_cols, axis=0)
            upd_lvl = jnp.concatenate(upd_cols, axis=0)
            from ...ops import indexed_row_sum

            grad_slices.append(
                indexed_row_sum(idx_lvl, upd_lvl, int(n_entries))
                .astype(table.dtype)
            )
        return dx, jnp.concatenate(grad_slices, axis=0)

    encode.defvjp(fwd, bwd)
    return encode(x, table)


class HashGridEncoder(nn.Module):
    """Flax module owning the embedding table (uniform ±1e-4 init,
    hashgrid.py:184-186), with world-bounds normalization to [0, 1]
    (hashgrid.py:191-199)."""

    input_dim: int = 3
    num_levels: int = 16
    level_dim: int = 2
    per_level_scale: float = 2.0
    base_resolution: int = 16
    log2_hashmap_size: int = 19
    desired_resolution: int = -1
    bbox: tuple | None = None  # ((lo,)*D, (hi,)*D) world bounds
    # scatter-free sorted VJP by default: the autodiff backward's scatter
    # lowering is ~23M rows/s on this chip (PERF.md round 4)
    custom_bwd: bool = True

    @property
    def scale_factor(self) -> float:
        if self.desired_resolution != -1:
            # finest-level resolution overrides the scale (hashgrid.py:137-140)
            return float(
                2.0
                ** (
                    np.log2(self.desired_resolution / self.base_resolution)
                    / (self.num_levels - 1)
                )
            )
        return float(self.per_level_scale)

    @property
    def out_dim(self) -> int:
        return self.num_levels * self.level_dim

    @property
    def n_entries(self) -> int:
        offsets, _, _, _ = level_geometry(
            self.input_dim, self.num_levels, self.scale_factor,
            self.base_resolution, self.log2_hashmap_size,
        )
        return offsets[-1]

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        table = self.param(
            "embeddings",
            lambda key, shape: jax.random.uniform(
                key, shape, jnp.float32, -1e-4, 1e-4
            ),
            (self.n_entries, self.level_dim),
        )
        if self.bbox is not None:
            x = normalize_bbox(x, self.bbox)
        else:
            # callers must pre-normalize; clip so out-of-range coords can't
            # wrap through uint32 into scrambled (but finite) table indices
            x = jnp.clip(x, 0.0, 1.0)
        encode = (_encode_with_per_level_bwd if self.custom_bwd
                  else hash_encode)
        return encode(
            x,
            table,
            self.input_dim,
            self.num_levels,
            self.scale_factor,
            self.base_resolution,
            self.log2_hashmap_size,
        )

    @classmethod
    def from_cfg(cls, enc_cfg) -> "HashGridEncoder":
        bbox = enc_cfg.get("bbox", None)
        if bbox is None:
            # config-driven world-coordinate encoders must declare bounds
            # (the reference crashes on wbounds=None too, hashgrid.py:193)
            raise ValueError(
                "hashgrid encoder config needs 'bbox: [[lo...],[hi...]]' "
                "world bounds for [0,1] normalization"
            )
        return cls(
            input_dim=int(enc_cfg.get("input_dim", 3)),
            num_levels=int(enc_cfg.get("num_levels", 16)),
            level_dim=int(enc_cfg.get("level_dim", 2)),
            per_level_scale=float(enc_cfg.get("per_level_scale", 2.0)),
            base_resolution=int(enc_cfg.get("base_resolution", 16)),
            log2_hashmap_size=int(enc_cfg.get("log2_hashmap_size", 19)),
            desired_resolution=int(enc_cfg.get("desired_resolution", -1)),
            bbox=tuple(map(tuple, bbox)) if bbox is not None else None,
            custom_bwd=bool(enc_cfg.get("custom_bwd", True)),
        )
