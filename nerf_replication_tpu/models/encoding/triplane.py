"""Tri-plane encoder: three axis-aligned 2-D hash grids.

Capability parity with the reference's CUDA ``TriPlane``
(src/models/encoding/hashencoder/hashgrid.py:222-238): the xy/yz/xz
projections of a 3-D point each go through an independent 2-D multiresolution
hash encoder and the three feature vectors concatenate. (The reference also
carries a dense-`grid_sample` torch variant, src/models/encoding/
triplane.py:8-103 — same capability, one implementation here.)
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from .hashgrid import HashGridEncoder, normalize_bbox

_PLANES = ((0, 1), (1, 2), (0, 2))  # xy, yz, xz (hashgrid.py:231-236)


class TriPlaneEncoder(nn.Module):
    """[..., 3] → [..., 3·L·C]."""

    num_levels: int = 16
    level_dim: int = 2
    per_level_scale: float = 2.0
    base_resolution: int = 16
    log2_hashmap_size: int = 19
    desired_resolution: int = -1
    bbox: tuple | None = None

    def setup(self):
        kwargs = dict(
            input_dim=2,
            num_levels=self.num_levels,
            level_dim=self.level_dim,
            per_level_scale=self.per_level_scale,
            base_resolution=self.base_resolution,
            log2_hashmap_size=self.log2_hashmap_size,
            desired_resolution=self.desired_resolution,
        )
        self.planes = [
            HashGridEncoder(**kwargs, name=f"plane_{a}{b}") for a, b in _PLANES
        ]

    @property
    def out_dim(self) -> int:
        return 3 * self.num_levels * self.level_dim

    def __call__(self, x: jax.Array) -> jax.Array:
        if self.bbox is not None:
            x = normalize_bbox(x, self.bbox)
        feats = [
            plane(x[..., (a, b)]) for plane, (a, b) in zip(self.planes, _PLANES)
        ]
        return jnp.concatenate(feats, axis=-1)

    @classmethod
    def from_cfg(cls, enc_cfg) -> "TriPlaneEncoder":
        bbox = enc_cfg.get("bbox", None)
        return cls(
            num_levels=int(enc_cfg.get("num_levels", 16)),
            level_dim=int(enc_cfg.get("level_dim", 2)),
            per_level_scale=float(enc_cfg.get("per_level_scale", 2.0)),
            base_resolution=int(enc_cfg.get("base_resolution", 16)),
            log2_hashmap_size=int(enc_cfg.get("log2_hashmap_size", 19)),
            desired_resolution=int(enc_cfg.get("desired_resolution", -1)),
            bbox=tuple(map(tuple, bbox)) if bbox is not None else None,
        )
