"""NeRF frequency (positional) encoding as a pure vectorized JAX function.

Parity with the reference's closure-based encoder (src/models/encoding/
freq.py:2-33): output is ``[x, sin(2^0 x), cos(2^0 x), ..., sin(2^{L-1} x),
cos(2^{L-1} x)]`` with log-spaced bands, giving ``d*(1+2L)`` features. Unlike
the reference's per-band Python loop of lambdas, this is one broadcasted
sin/cos over a precomputed frequency vector — a single fused VPU op under XLA.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def frequency_encoder(
    input_dim: int,
    n_freqs: int,
    include_input: bool = True,
    log_sampling: bool = True,
):
    """Returns ``(encode_fn, out_dim)``; ``encode_fn`` maps [..., d] → [..., out]."""
    if n_freqs <= 0:
        return (lambda x: x), input_dim

    if log_sampling:
        freq_bands = 2.0 ** np.linspace(0.0, n_freqs - 1, n_freqs)
    else:
        freq_bands = np.linspace(1.0, 2.0 ** (n_freqs - 1), n_freqs)
    freq_bands = jnp.asarray(freq_bands, dtype=jnp.float32)

    out_dim = input_dim * (2 * n_freqs + (1 if include_input else 0))

    def encode(x):
        # [..., d] -> [..., L, d] scaled by each band
        xb = x[..., None, :] * freq_bands[:, None]
        # interleave (sin_f, cos_f) per band to match the reference's
        # per-frequency [sin, cos] ordering, then flatten bands.
        enc = jnp.stack([jnp.sin(xb), jnp.cos(xb)], axis=-2)  # [..., L, 2, d]
        enc = enc.reshape(*x.shape[:-1], -1)
        if include_input:
            enc = jnp.concatenate([x, enc], axis=-1)
        return enc

    return encode, out_dim
