"""Encoder dispatch — parity with the reference's `get_encoder`
(src/models/encoding/__init__.py:6-86), which keys 14 encoder types off
``cfg.type``.

Param-free encoders (frequency) return ``(callable, out_dim)``. Parametric
encoders (hash grids, triplanes, deformation fields) return
``(flax_module, out_dim)`` — the network binds them as submodules so their
tables train with the MLP. Types not yet implemented raise with a clear
message naming the round they're planned for.
"""

from __future__ import annotations

from .freq import frequency_encoder


def get_encoder(enc_cfg):
    """``enc_cfg`` is a config node with at least ``type`` and ``input_dim``."""
    enc_type = enc_cfg.type

    if enc_type == "frequency":
        return frequency_encoder(
            input_dim=int(enc_cfg.input_dim),
            n_freqs=int(enc_cfg.freq),
            include_input=True,
            log_sampling=True,
        )

    if enc_type in ("hashgrid", "cuda_hashgrid", "grid_hash"):
        from .hashgrid import HashGridEncoder

        module = HashGridEncoder.from_cfg(enc_cfg)
        return module, module.out_dim

    if enc_type in ("triplane", "cuda_triplane"):
        from .triplane import TriPlaneEncoder

        module = TriPlaneEncoder.from_cfg(enc_cfg)
        return module, module.out_dim

    raise NotImplementedError(
        f"Encoder type {enc_type!r} is not implemented yet "
        f"(reference parity list: frequency, hashgrid, triplane, dnerf & "
        f"variants; see SURVEY.md §2.2)"
    )
