"""Encoder dispatch — parity with the reference's `get_encoder`
(src/models/encoding/__init__.py:6-86), which keys 14 encoder types off
``cfg.type``.

Param-free encoders (frequency) return ``(callable, out_dim)``. Parametric
encoders (hash grids, triplanes, deformation fields) return
``(flax_module, out_dim)`` — the network binds them as submodules so their
tables train with the MLP. Types not yet implemented raise with a clear
message naming the round they're planned for.
"""

from __future__ import annotations

from .freq import frequency_encoder


def get_encoder(enc_cfg, precision=None):
    """``enc_cfg`` is a config node with at least ``type`` and ``input_dim``.
    ``precision`` (cfg.precision) lets dtype-aware encoders follow the
    compute dtype (the packed hash grid gathers half-width rows under
    bf16)."""
    enc_type = enc_cfg.type

    if enc_type == "frequency":
        return frequency_encoder(
            input_dim=int(enc_cfg.input_dim),
            n_freqs=int(enc_cfg.freq),
            include_input=True,
            log_sampling=True,
        )

    if enc_type in ("hashgrid", "cuda_hashgrid", "grid_hash"):
        from .hashgrid import HashGridEncoder

        module = HashGridEncoder.from_cfg(enc_cfg)
        return module, module.out_dim

    if enc_type == "hashgrid_packed":
        # TPU-native cell-packed layout: one wide gather per (point, level)
        # and a scatter-free sorted backward (see packed_hash.py)
        from .packed_hash import PackedHashGridEncoder

        module = PackedHashGridEncoder.from_cfg(enc_cfg, precision)
        return module, module.out_dim

    if enc_type in ("triplane", "cuda_triplane"):
        from .triplane import TriPlaneEncoder

        module = TriPlaneEncoder.from_cfg(enc_cfg)
        return module, module.out_dim

    if enc_type in _DYNAMIC_TYPES:
        module = _make_dynamic(enc_type, enc_cfg)
        return module, module.out_dim

    raise NotImplementedError(
        f"Encoder type {enc_type!r} is not implemented "
        f"(supported: frequency, hashgrid, triplane + dynamic family "
        f"{sorted(_DYNAMIC_TYPES)}; see SURVEY.md §2.2)"
    )


# time-conditioned family — reference type names from
# src/models/encoding/__init__.py:6-86 map onto our dynamic modules
_DYNAMIC_TYPES = {
    "cuda_hashgrid_latent": "HashLatentEncoder",
    "cuda_hashgrid_4d": "HashEncoder4d",
    "cuda_hashgrid_coef": "HashCoefEncoder",
    "cuda_motion2d": "Motion2dEncoder",
    "dnerf": "DNeRFEncoder",
    "cuda_dnerf_ngp_tensorf": "DNeRFNGPEncoder",
    "dnerf_ngp_tensorf": "DNeRFNGPEncoder",
    "dnerf_ngp_mlp": "DNeRFEncoder",
    "dnerf_mlp_tensorf": "DNeRFNGPEncoder",
}

# note: input_dim deliberately NOT forwarded — each dynamic class owns its
# hash input rank (3-D xyz canonical space; HashEncoder4d/Motion2d override)
_HASH_KEYS = (
    "num_levels", "level_dim", "per_level_scale",
    "base_resolution", "log2_hashmap_size", "desired_resolution",
)


def _make_dynamic(enc_type, enc_cfg):
    from . import dynamic

    cls = getattr(dynamic, _DYNAMIC_TYPES[enc_type])
    hash_kwargs = {k: enc_cfg[k] for k in _HASH_KEYS if k in enc_cfg}
    kwargs = dict(
        num_frames=int(enc_cfg.get("num_frames", 1)),
        bbox=tuple(map(tuple, enc_cfg.bbox)) if "bbox" in enc_cfg else None,
        hash_kwargs=hash_kwargs,
    )
    if kwargs["bbox"] is None:
        # dynamic encoders normalize with explicit world bounds
        kwargs["bbox"] = ((-1.5, -1.5, -1.5), (1.5, 1.5, 1.5))
    return cls(**kwargs)
