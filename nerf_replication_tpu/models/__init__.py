"""Network factory: resolves the ``network_module`` plugin key
(parity: src/models/make_network.py:4-8)."""

from __future__ import annotations

from ..registry import load_attr


def make_network(cfg):
    factory = load_attr(cfg.network_module, "make_network", "Network")
    return factory(cfg)
