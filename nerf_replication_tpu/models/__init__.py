"""Network factory: resolves the ``network_module`` plugin key
(parity: src/models/make_network.py:4-8)."""

from __future__ import annotations

from ..registry import load_attr


def make_network(cfg):
    factory = load_attr(cfg.network_module, "make_network", "Network")
    return factory(cfg)


def init_params_for(cfg):
    """The network plugin's ``init_params`` (task networks have different
    input signatures); defaults to the NeRF one. Shared by training and the
    eval bootstrap so both resolve identically."""
    try:
        return load_attr(cfg.network_module, "init_params")
    except (AttributeError, ImportError):
        from .nerf.network import init_params

        return init_params
