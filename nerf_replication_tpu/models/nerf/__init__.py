from .network import Network, NeRFMLP, init_params, make_network

__all__ = ["Network", "NeRFMLP", "init_params", "make_network"]
