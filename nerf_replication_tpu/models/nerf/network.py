"""Coarse+fine NeRF network as Flax modules.

Capability parity with the reference's `src/models/nerf/network.py:9-192`:
the original-paper MLP (D=8, W=256, skip connection re-injecting the embedded
position at layer `skips`, separate density head, and a viewdirs branch:
feature(W) ⊕ dir-embedding → W/2 → rgb), with coarse and fine instances owned
by one `Network` module.

TPU-native differences:
* No `batchify` chunking loop (network.py:161-169) — point batches go through
  as single ``[N, C] @ [C, W]`` matmuls sized for the MXU; memory capping at
  eval time is the renderer's job (`lax.map` over ray chunks).
* Optional bfloat16 compute with float32 params (``cfg.precision``): matmuls
  hit the MXU at 2× rate while the density/color heads and compositing stay
  in float32.
* Model selection ("coarse"/"fine") is a trace-time constant, so each variant
  compiles to its own fused executable.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..encoding import get_encoder


class SplitDense(nn.Module):
    """``Dense(features)`` over ``concat([x1, x2], -1)`` WITHOUT the concat.

    Param name/shape/init are identical to ``nn.Dense`` on the concatenated
    input (kernel ``[c1+c2, features]``, lecun_normal; zero bias), so
    checkpoints and the Keras import are byte-compatible — but the forward
    is ``x1 @ k[:c1] + x2 @ k[c1:] + b``: the ``[N, S, c1+c2]`` concat
    buffer (and its cotangent in the backward) never exists. PERF.md f3:
    the flagship step is HBM-bound and the 262k-ray OOM dump showed XLA
    materializing exactly these buffers at 9 GB scale.
    """

    features: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x1: jax.Array, x2: jax.Array) -> jax.Array:
        c1, c2 = x1.shape[-1], x2.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (c1 + c2, self.features), self.param_dtype,
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(),
            (self.features,), self.param_dtype,
        )
        k = kernel.astype(self.dtype)
        return (
            x1.astype(self.dtype) @ k[:c1]
            + x2.astype(self.dtype) @ k[c1:]
            + bias.astype(self.dtype)
        )


class NeRFMLP(nn.Module):
    """The original-paper NeRF MLP over pre-embedded inputs."""

    D: int = 8
    W: int = 256
    input_ch: int = 63
    input_ch_views: int = 27
    skips: Sequence[int] = (4,)
    use_viewdirs: bool = True
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # scan the uniform W->W trunk runs with stacked params instead of
    # unrolling them: the fully-unrolled coarse+fine fwd+bwd graph is what
    # made 65k-ray remote compiles exceed 15 min (PERF.md round 3).
    # OPT-IN because it changes the param tree (runs store one stacked
    # "trunk_scan_<start>" param instead of per-layer pts_linear_i; use
    # checkpoint.replace_param_prefix-style surgery to convert bundles).
    scan_trunk: bool = False

    def _uniform_runs(self):
        """Maximal consecutive runs of W->W trunk layers (layer i is
        uniform iff i>0 and its input is the previous layer's W-wide
        output, i.e. (i-1) is not a skip layer)."""
        runs, start = [], None
        for i in range(1, self.D):
            if (i - 1) not in self.skips:
                if start is None:
                    start = i
            else:
                if start is not None:
                    runs.append((start, i - start))
                start = None
        if start is not None:
            runs.append((start, self.D - start))
        return runs

    @nn.compact
    def __call__(self, embedded: jax.Array) -> jax.Array:
        """[..., input_ch + input_ch_views] → [..., 4] raw (r, g, b, sigma)."""
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats,
            dtype=self.compute_dtype,
            param_dtype=self.param_dtype,
            name=name,
        )
        split_dense = lambda feats, name: SplitDense(  # noqa: E731
            feats,
            dtype=self.compute_dtype,
            param_dtype=self.param_dtype,
            name=name,
        )
        input_pts = embedded[..., : self.input_ch]
        input_views = embedded[..., self.input_ch :]

        scanned = {}  # layer index -> (run_start, run_len) for run heads
        if self.scan_trunk:
            scanned = {start: (start, length)
                       for start, length in self._uniform_runs()}

        h = input_pts.astype(self.compute_dtype)
        pending_skip = None  # re-injected input feeding the NEXT layer
        i = 0
        while i < self.D:
            if i in scanned and pending_skip is None:
                start, length = scanned[i]
                kernel = self.param(
                    f"trunk_scan_{start}",
                    # batch_axis=0: per-LAYER lecun fan (fan_in = W), same
                    # distribution each slice as an unrolled nn.Dense
                    nn.initializers.variance_scaling(
                        1.0, "fan_in", "truncated_normal", batch_axis=(0,)
                    ),
                    (length, self.W, self.W), self.param_dtype,
                )
                bias = self.param(
                    f"trunk_scan_{start}_bias", nn.initializers.zeros_init(),
                    (length, self.W), self.param_dtype,
                )
                cd = self.compute_dtype

                def body(carry, kb):
                    k, b = kb
                    return nn.relu(
                        carry @ k.astype(cd) + b.astype(cd)
                    ), None

                h, _ = jax.lax.scan(body, h, (kernel, bias))
                last = start + length - 1
                if last in self.skips:
                    pending_skip = input_pts.astype(self.compute_dtype)
                i = start + length
                continue
            if pending_skip is not None:
                h = split_dense(self.W, f"pts_linear_{i}")(pending_skip, h)
                pending_skip = None
            else:
                h = dense(self.W, f"pts_linear_{i}")(h)
            h = nn.relu(h)
            if i in self.skips:
                pending_skip = input_pts.astype(self.compute_dtype)
            i += 1
        if pending_skip is not None:
            # skip at the last trunk layer: the heads genuinely consume the
            # concatenated width — materialize only in that config
            h = jnp.concatenate([pending_skip, h], axis=-1)

        if self.use_viewdirs:
            # density head reads the trunk directly (network.py:60);
            # keep heads in f32 for numerically stable compositing.
            alpha = nn.Dense(1, param_dtype=self.param_dtype, name="alpha_linear")(
                h.astype(jnp.float32)
            )
            feature = dense(self.W, "feature_linear")(h)
            h = nn.relu(
                split_dense(self.W // 2, "views_linear_0")(
                    feature, input_views.astype(self.compute_dtype)
                )
            )
            rgb = nn.Dense(3, param_dtype=self.param_dtype, name="rgb_linear")(
                h.astype(jnp.float32)
            )
            return jnp.concatenate([rgb, alpha], axis=-1)

        out = nn.Dense(4, param_dtype=self.param_dtype, name="output_linear")(
            h.astype(jnp.float32)
        )
        return out


class Network(nn.Module):
    """Coarse + fine NeRF pair behind one apply, with pluggable encoders
    (parity: reference `Network`, network.py:126-192).

    ``proposal_cfg`` (a static ``(D, W, n_freqs)`` tuple, None = absent)
    adds the learned-sampling density branch (models/proposal.py) as a
    third model under the SAME apply — ``model="proposal"`` returns raw
    [..., S, 1] σ. One params tree for all branches keeps checkpoints,
    donation, AOT signatures, and the serve engine's scene-compat checks
    structurally unchanged."""

    D: int = 8
    W: int = 256
    skips: Sequence[int] = (4,)
    use_viewdirs: bool = True
    xyz_encoder: Callable = None
    dir_encoder: Callable = None
    input_ch: int = 63
    input_ch_views: int = 27
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    scan_trunk: bool = False
    proposal_cfg: tuple | None = None

    def setup(self):
        kwargs = dict(
            D=self.D,
            W=self.W,
            input_ch=self.input_ch,
            input_ch_views=self.input_ch_views if self.use_viewdirs else 0,
            skips=tuple(self.skips),
            use_viewdirs=self.use_viewdirs,
            compute_dtype=self.compute_dtype,
            param_dtype=self.param_dtype,
            scan_trunk=self.scan_trunk,
        )
        self.coarse = NeRFMLP(**kwargs, name="coarse")
        self.fine = NeRFMLP(**kwargs, name="fine")
        if self.proposal_cfg is not None:
            from ..proposal import ProposalMLP

            d_p, w_p, f_p = self.proposal_cfg
            self.proposal = ProposalMLP(
                D=d_p, W=w_p, n_freqs=f_p,
                compute_dtype=self.compute_dtype,
                param_dtype=self.param_dtype,
                name="proposal",
            )

    def __call__(self, pts: jax.Array, viewdirs: jax.Array | None, model: str = "coarse"):
        """``pts [..., S, 3]``, ``viewdirs [..., 3]`` → raw ``[..., S, 4]``
        (or ``[..., S, 1]`` raw σ for ``model="proposal"``).

        ``model`` must be a static string ("coarse" | "fine" |
        "proposal")."""
        if model == "proposal":
            # density-only branch: its own inline frequency encoding (far
            # fewer bands than the main xyz_encoder), no view conditioning
            return self.proposal(pts)
        embedded = self.xyz_encoder(pts)
        if self.use_viewdirs:
            dirs = jnp.broadcast_to(
                viewdirs[..., None, :], pts.shape[:-1] + (viewdirs.shape[-1],)
            )
            embedded = jnp.concatenate(
                [embedded, self.dir_encoder(dirs)], axis=-1
            )
        mlp = self.fine if model == "fine" else self.coarse
        return mlp(embedded)


def make_network(cfg) -> Network:
    """Build the Network module from the reference-schema config."""
    xyz_enc, input_ch = get_encoder(
        cfg.network.xyz_encoder, cfg.get("precision", {})
    )
    use_viewdirs = bool(cfg.task_arg.use_viewdirs)
    if use_viewdirs:
        dir_enc, input_ch_views = get_encoder(cfg.network.dir_encoder)
    else:
        dir_enc, input_ch_views = None, 0
    prec = cfg.get("precision", {})
    # learned sampling (cfg.sampling, docs/sampling.md): proposal mode
    # grows the density-only branch; its params ride the same tree, so a
    # proposal-trained checkpoint IS a normal checkpoint with one more
    # top-level branch
    samp = cfg.get("sampling", {})
    proposal_cfg = None
    if str(samp.get("mode", "coarse_fine")) == "proposal":
        net = samp.get("net", {})
        proposal_cfg = (
            int(net.get("D", 2)), int(net.get("W", 64)),
            int(net.get("freq", 5)),
        )
    return Network(
        D=int(cfg.network.nerf.D),
        W=int(cfg.network.nerf.W),
        skips=tuple(cfg.network.nerf.skips),
        use_viewdirs=use_viewdirs,
        xyz_encoder=xyz_enc,
        dir_encoder=dir_enc,
        input_ch=input_ch,
        input_ch_views=input_ch_views,
        compute_dtype=jnp.dtype(prec.get("compute_dtype", "float32")),
        param_dtype=jnp.dtype(prec.get("param_dtype", "float32")),
        scan_trunk=bool(cfg.network.nerf.get("scan_trunk", False)),
        proposal_cfg=proposal_cfg,
    )


def load_weights_from_keras(params, weights, model: str = "coarse"):
    """Import an original-NeRF Keras checkpoint (the flat weight list the
    reference consumes, network.py:76-123) into one MLP branch.

    The Keras list interleaves [kernel, bias] per layer: D trunk layers,
    then feature_linear (index 2D), views_linear (2D+2), rgb_linear (2D+4),
    alpha_linear (2D+6). Keras kernels are stored ``[in, out]`` — exactly
    Flax's layout, so unlike the torch reference no transpose is needed.
    Returns a NEW params pytree; requires use_viewdirs layout.
    """
    import numpy as np

    branch = dict(params["params"][model])
    d = sum(1 for k in branch if k.startswith("pts_linear_"))

    def pair(name, idx):
        kernel = np.asarray(weights[idx])
        bias = np.asarray(weights[idx + 1]).reshape(-1)
        have = branch[name]["kernel"].shape
        if tuple(kernel.shape) != tuple(have):
            raise ValueError(
                f"{name}: keras weight {kernel.shape} != param {have} "
                "(check D/W/skips match the checkpoint)"
            )
        return {"kernel": jnp.asarray(kernel), "bias": jnp.asarray(bias)}

    for i in range(d):
        branch[f"pts_linear_{i}"] = pair(f"pts_linear_{i}", 2 * i)
    branch["feature_linear"] = pair("feature_linear", 2 * d)
    branch["views_linear_0"] = pair("views_linear_0", 2 * d + 2)
    branch["rgb_linear"] = pair("rgb_linear", 2 * d + 4)
    branch["alpha_linear"] = pair("alpha_linear", 2 * d + 6)

    new_params = dict(params["params"])
    new_params[model] = branch
    return {"params": new_params}


def init_params(network: Network, key: jax.Array):
    """Initialize both MLPs' parameters with dummy point/dir batches.

    Coarse and fine are independent parameter sets (network.py:141-159), so
    each branch inits from its own key; the two single-branch variable trees
    merge disjointly (parametric encoder subtrees, when present, are shared
    and taken from the last init)."""
    k_coarse, k_fine = jax.random.split(key)
    pts = jnp.zeros((2, 4, 3), jnp.float32)
    dirs = jnp.zeros((2, 3), jnp.float32) if network.use_viewdirs else None
    params_c = network.init(k_coarse, pts, dirs, model="coarse")
    params_f = network.init(k_fine, pts, dirs, model="fine")
    merged = {**params_c["params"], **params_f["params"]}
    if getattr(network, "proposal_cfg", None) is not None:
        # the proposal branch draws from its own fold of the fine key so
        # coarse/fine init streams are bitwise-unchanged from 2-branch runs
        k_prop = jax.random.fold_in(k_fine, 1)
        params_p = network.init(k_prop, pts, dirs, model="proposal")
        merged = {**merged, **params_p["params"]}
    return {"params": merged}
