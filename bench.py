"""Headline benchmark: training ray throughput on the flagship lego model.

Runs the full jitted train step (on-device ray sampling → coarse+fine NeRF
render at 64+128 samples/ray, the reference's per-ray work — configs/nerf/
lego.yaml:20-23 ≙ reference lego.yaml — → MSE → grads → clip(40) → adam) on
one chip and reports rays/second.

Baseline: the reference trains 1024 rays/iter at a measured mean 0.222 s/iter
(/root/reference/log.txt; BASELINE.md) ≈ 4612 rays/s on its CUDA GPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

import jax
import jax.numpy as jnp

_REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_RAYS_PER_SEC = 1024 / 0.222  # reference log.txt mean iter time


def _ngp_companion(path=None):
    """Best occupancy-accelerated-training measurement on record, or None.

    The headline metric is the flagship (reference-parity) step; the NGP
    trainer (train/ngp.py) is this framework's fastest path and lives in
    BENCH_NGP.jsonl. Surfacing its best row here keeps the driver's
    one-line record pointing at both numbers. Quality floor 25 dB keeps
    warm-up-only / compile-window arms (occupancy 1.0, single-digit
    PSNR — see PERF.md "disowned rows") out of the companion slot.
    """
    best = None
    try:
        with open(path or os.path.join(_REPO, "BENCH_NGP.jsonl")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if not str(rec.get("arm", "")).startswith("ngp"):
                    continue
                rate = rec.get("rays_per_sec")
                if not isinstance(rate, (int, float)):
                    continue
                if not (isinstance(rec.get("psnr"), (int, float))
                        and rec["psnr"] >= 25.0):
                    continue
                if best is None or rate > best["rays_per_sec"]:
                    best = {
                        k: rec.get(k)
                        for k in (
                            "arm", "rays_per_sec", "carved_rays_per_sec",
                            "psnr", "ssim", "n_rays", "config", "opts", "ts",
                        )
                        if rec.get(k) is not None
                    }
    except Exception:
        # never let the companion break the driver's one-line contract
        # (it is also emitted from the failure path)
        pass
    return best

def main():
    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.models.nerf.network import make_network
    from nerf_replication_tpu.train.loss import make_loss
    from nerf_replication_tpu.train.trainer import Trainer, make_train_state

    # escape hatch for CI/smoke runs on machines whose sitecustomize pins
    # the platform (env alone is beaten — see utils/platform.py)
    forced = os.environ.get("BENCH_FORCE_PLATFORM", "")
    if forced:
        from nerf_replication_tpu.utils.platform import force_platform

        force_platform(forced)
    else:
        # after an HBM-OOM storm the axon terminal restarts itself and can
        # take minutes to answer again (docs/operations.md: wedges last
        # minutes to HOURS) — the default budget is 6 probes with
        # exponential backoff (~20 min), env-tunable via BENCH_INIT_*.
        # Deliberately NOT setup_backend(): that helper hard-exits on
        # failure, and bench must instead catch the error below to emit
        # its JSON failure record (the driver's one-line contract) before
        # its own os._exit.
        from nerf_replication_tpu.utils.platform import (
            init_backend_with_retry,
        )

        init_backend_with_retry()  # budget via BENCH_INIT_* env vars

    # Measured-best defaults: scripts/tpu_battery.sh promotes the winning
    # sweep point into BENCH_DEFAULTS.json so the driver's plain
    # `python bench.py` (no envs) runs the best known config. Envs still win.
    defaults = {"n_rays": 4096, "steps": 50, "config": "lego.yaml",
                "dtype": "bfloat16", "remat": "false", "grad_accum": 1}
    try:
        with open(os.path.join(_REPO, "BENCH_DEFAULTS.json")) as f:
            defaults.update(json.load(f))
    except (OSError, ValueError):
        pass

    from nerf_replication_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache()

    n_rays = int(os.environ.get("BENCH_N_RAYS", defaults["n_rays"]))
    n_steps = int(os.environ.get("BENCH_STEPS", defaults["steps"]))
    config = os.environ.get("BENCH_CONFIG", defaults["config"])
    # promoted free-form cfg overrides (e.g. the fused trunk) — env wins;
    # defaults' opts were measured on defaults' CONFIG and must not leak
    # onto a different BENCH_CONFIG (a fused-trunk override would be
    # rejected outright by the hash-encoder configs)
    opts = os.environ.get("BENCH_OPTS")
    if opts is None:
        opts = (
            defaults.get("opts", "")
            if config == defaults.get("config")
            else ""
        )

    cfg = make_cfg(
        os.path.join(_REPO, "configs", "nerf", config),
        [
            "task_arg.N_rays", str(n_rays),
            "task_arg.precrop_iters", "0",
            # TPU-native default: bf16 MXU matmuls, f32 params/heads/compositing
            "precision.compute_dtype",
            os.environ.get("BENCH_DTYPE", defaults["dtype"]),
            "task_arg.remat",
            os.environ.get("BENCH_REMAT", str(defaults["remat"]).lower()),
            # K optimizer steps per device dispatch (lax.scan) — the lever
            # for the latency-bound small-batch regime (PERF.md)
            "task_arg.scan_steps",
            os.environ.get("BENCH_SCAN_STEPS", str(defaults.get("scan_steps", 1))),
            # microbatch accumulation — promoted with the winning sweep
            # point (a promoted accum row must replay WITH accumulation)
            "task_arg.grad_accum",
            os.environ.get(
                "BENCH_GRAD_ACCUM", str(defaults.get("grad_accum", 1))
            ),
            # space-separated trailing cfg overrides, e.g.
            # BENCH_OPTS="network.xyz_encoder.custom_bwd true"
            *opts.split(),
        ],
    )
    network = make_network(cfg)
    loss = make_loss(cfg, network)
    trainer = Trainer(cfg, network, loss)

    key = jax.random.PRNGKey(0)
    k_init, k_bank, base_key = jax.random.split(key, 3)
    state, _ = make_train_state(cfg, network, k_init)

    # synthetic ray bank: throughput is content-independent, so the bench
    # needs no dataset download (rays point at the scene volume).
    n_bank = 1 << 20
    k1, k2, k3 = jax.random.split(k_bank, 3)
    origins = jax.random.normal(k1, (n_bank, 3)) * 0.5 + jnp.asarray(
        [0.0, 0.0, -4.0]
    )
    dirs = jax.random.normal(k2, (n_bank, 3))
    dirs = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)
    bank_rays = jnp.concatenate([origins, dirs], axis=-1).astype(jnp.float32)
    bank_rgbs = jax.random.uniform(k3, (n_bank, 3), jnp.float32)

    # scan_steps>1: K steps per dispatch; n_steps rounds UP to whole bursts
    scan_k = trainer.scan_steps
    n_bursts = max(1, -(-n_steps // scan_k))
    n_steps = n_bursts * scan_k

    # warmup: compile + 3 bursts
    state, stats = trainer.multi_step(state, bank_rays, bank_rgbs, base_key)
    for _ in range(3):
        state, stats = trainer.multi_step(state, bank_rays, bank_rgbs, base_key)
    jax.block_until_ready(stats)

    t0 = time.perf_counter()
    for _ in range(n_bursts):
        state, stats = trainer.multi_step(state, bank_rays, bank_rgbs, base_key)
    jax.block_until_ready(stats)
    dt = time.perf_counter() - t0

    rays_per_sec = n_rays * n_steps / dt

    # closed-form MFU estimate (PERF.md arithmetic): per-point MLP cost =
    # 2·params; a train step touches N_samples coarse + (N_samples +
    # N_importance) fine points per ray, ×3 for fwd+bwd. Encoder tables are
    # excluded (gathers, not MXU FLOPs).
    import numpy as np

    def _mlp_params(tree) -> int:
        return sum(
            int(np.prod(np.shape(leaf)))
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
            if "embeddings" not in str(path)
        )

    n_coarse = int(cfg.task_arg.N_samples)
    n_importance = int(cfg.task_arg.get("N_importance", 0))
    p_coarse = _mlp_params(state.params.get("coarse", {}))
    p_fine = _mlp_params(state.params.get("fine", {}))
    # the fine network only runs when hierarchical sampling is on
    fine_term = p_fine * (n_coarse + n_importance) if n_importance > 0 else 0
    flops_per_ray = 3.0 * 2.0 * (p_coarse * n_coarse + fine_term)
    # v5e peak: 197 TFLOP/s bf16; fp32 runs the MXU at ~half rate
    dtype = str(cfg.precision.compute_dtype)
    default_peak = 197e12 if dtype == "bfloat16" else 98.5e12
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", default_peak))
    mfu = rays_per_sec * flops_per_ray / peak if flops_per_ray else None

    # sweep subprocesses append this line verbatim to BENCH_SWEEP*.jsonl;
    # the companion snapshot belongs only in the driver-facing record
    ngp_best = (
        None
        if os.environ.get("BENCH_NO_COMPANION") == "1"
        else _ngp_companion()
    )
    print(
        json.dumps(
            {
                "metric": "train_rays_per_sec",
                "value": round(rays_per_sec, 1),
                "unit": "rays/s",
                "vs_baseline": round(rays_per_sec / BASELINE_RAYS_PER_SEC, 2),
                "mfu": round(mfu, 4) if mfu is not None else None,
                "gflops_per_ray": round(flops_per_ray / 1e9, 3),
                "dtype": dtype,
                "peak_flops": peak,
                "n_rays": n_rays,
                "scan_steps": scan_k,
                "grad_accum": int(cfg.task_arg.get("grad_accum", 1)),
                "remat": bool(cfg.task_arg.get("remat", False)),
                # free-form label (e.g. BENCH_TAG=steady_state) for sweep
                # rows that supersede compile-window measurements
                "config": config,
                "ts": round(time.time(), 1),
                **(
                    {"tag": os.environ["BENCH_TAG"]}
                    if os.environ.get("BENCH_TAG")
                    else {}
                ),
                **({"opts": opts} if opts else {}),
                # best occupancy-accelerated-training number on record —
                # the framework's fastest training path (train/ngp.py),
                # measured with held-out PSNR in the same file
                **(
                    {"ngp_training_best": ngp_best}
                    if ngp_best is not None
                    else {}
                ),
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:
        # The driver records exactly one JSON line; on unrecoverable failure
        # emit a diagnostic line (value null) so the record is actionable
        # rather than an opaque non-zero exit.
        traceback.print_exc()
        # make the failure record actionable: point at the best current
        # measurement instead of leaving a bare null (round-2's record was
        # an opaque failure while the real numbers sat in the sweep files).
        # Prefer the config this run was benchmarking; fall back to any.
        best_known = None
        try:
            import glob

            from nerf_replication_tpu.utils.sweeps import best_point

            paths = glob.glob(os.path.join(_REPO, "BENCH_SWEEP*.jsonl"))
            cfg_name = os.environ.get("BENCH_CONFIG", "lego.yaml")
            rec = best_point(paths, config=cfg_name) or best_point(paths)
            if rec is not None:
                best_known = {
                    k: rec.get(k)
                    for k in ("value", "n_rays", "dtype", "remat")
                }
                best_known["scan_steps"] = rec.get("scan_steps", 1)
                best_known["grad_accum"] = rec.get("grad_accum", 1)
                best_known["config"] = rec.get("config", "lego.yaml")
                if rec.get("opts"):
                    # the overrides that DEFINE the point — without them
                    # the quoted number is not reproducible
                    best_known["opts"] = rec["opts"]
        except Exception:
            pass
        print(
            json.dumps(
                {
                    "metric": "train_rays_per_sec",
                    "value": None,
                    "unit": "rays/s",
                    "vs_baseline": None,
                    "error": f"{type(exc).__name__}: {exc}",
                    # partial probe history (utils/platform attaches it to
                    # the init error) — a failed record must still show
                    # what was tried and when, not just an opaque message
                    "init_trail": getattr(exc, "trail", None),
                    "best_known_measurement": best_known,
                    # a wedge-null record must still carry the round's
                    # best NGP-training number (same slot and same
                    # sweep-subprocess gate as the success path; the
                    # helper swallows its own errors)
                    "ngp_training_best": (
                        None
                        if os.environ.get("BENCH_NO_COMPANION") == "1"
                        else _ngp_companion()
                    ),
                }
            )
        )
        sys.stdout.flush()
        sys.stderr.flush()
        # hard exit: a watchdogged init thread may be wedged in C++ backend
        # code; normal interpreter shutdown could block behind it
        os._exit(1)
