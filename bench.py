"""Headline benchmark: training ray throughput on the flagship lego model.

Runs the full jitted train step (on-device ray sampling → coarse+fine NeRF
render at 64+128 samples/ray, the reference's per-ray work — configs/nerf/
lego.yaml:20-23 ≙ reference lego.yaml — → MSE → grads → clip(40) → adam) on
one chip and reports rays/second.

Baseline: the reference trains 1024 rays/iter at a measured mean 0.222 s/iter
(/root/reference/log.txt; BASELINE.md) ≈ 4612 rays/s on its CUDA GPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp

_REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_RAYS_PER_SEC = 1024 / 0.222  # reference log.txt mean iter time


def main():
    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.models.nerf.network import make_network
    from nerf_replication_tpu.train.loss import make_loss
    from nerf_replication_tpu.train.trainer import Trainer, make_train_state

    n_rays = int(os.environ.get("BENCH_N_RAYS", 4096))
    n_steps = int(os.environ.get("BENCH_STEPS", 50))

    cfg = make_cfg(
        os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        [
            "task_arg.N_rays", str(n_rays),
            "task_arg.precrop_iters", "0",
            # TPU-native precision: bf16 MXU matmuls, f32 params/heads/compositing
            "precision.compute_dtype", "bfloat16",
            "task_arg.remat", os.environ.get("BENCH_REMAT", "false"),
        ],
    )
    network = make_network(cfg)
    loss = make_loss(cfg, network)
    trainer = Trainer(cfg, network, loss)

    key = jax.random.PRNGKey(0)
    k_init, k_bank, base_key = jax.random.split(key, 3)
    state, _ = make_train_state(cfg, network, k_init)

    # synthetic ray bank: throughput is content-independent, so the bench
    # needs no dataset download (rays point at the scene volume).
    n_bank = 1 << 20
    k1, k2, k3 = jax.random.split(k_bank, 3)
    origins = jax.random.normal(k1, (n_bank, 3)) * 0.5 + jnp.asarray(
        [0.0, 0.0, -4.0]
    )
    dirs = jax.random.normal(k2, (n_bank, 3))
    dirs = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)
    bank_rays = jnp.concatenate([origins, dirs], axis=-1).astype(jnp.float32)
    bank_rgbs = jax.random.uniform(k3, (n_bank, 3), jnp.float32)

    # warmup: compile + 3 steps
    state, stats = trainer.step(state, bank_rays, bank_rgbs, base_key)
    for _ in range(3):
        state, stats = trainer.step(state, bank_rays, bank_rgbs, base_key)
    jax.block_until_ready(stats)

    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, stats = trainer.step(state, bank_rays, bank_rgbs, base_key)
    jax.block_until_ready(stats)
    dt = time.perf_counter() - t0

    rays_per_sec = n_rays * n_steps / dt
    print(
        json.dumps(
            {
                "metric": "train_rays_per_sec",
                "value": round(rays_per_sec, 1),
                "unit": "rays/s",
                "vs_baseline": round(rays_per_sec / BASELINE_RAYS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
