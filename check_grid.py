"""Sanity-check a baked occupancy grid.

Parity with the reference's `check_grid.py:15-79`: assert dtype/shape, print
the occupancy percentage, optionally scatter-plot the occupied voxels in 3-D.

    python check_grid.py --cfg_file configs/nerf/lego.yaml [--visualize]
"""

from __future__ import annotations


def main():
    from nerf_replication_tpu.config import make_parser
    from nerf_replication_tpu.renderer.occupancy import (
        default_grid_path,
        load_occupancy_grid,
        occupancy_stats,
    )

    parser = make_parser()
    parser.add_argument("--visualize", action="store_true", default=False)
    args = parser.parse_args()

    path = default_grid_path(args.cfg_file)
    grid, bbox = load_occupancy_grid(path)
    stats = occupancy_stats(grid)
    print(f"grid: {path}")
    print(f"shape: {stats['shape']}  dtype: {grid.dtype}")
    print(
        f"occupied: {stats['occupied']}/{stats['total']} "
        f"({stats['occupancy_pct']:.2f}%)"
    )
    print(f"bbox: {bbox.tolist()}")

    if args.visualize:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        import numpy as np

        xs, ys, zs = np.nonzero(grid)
        # subsample for plot responsiveness
        if xs.size > 20000:
            sel = np.random.default_rng(0).choice(xs.size, 20000, replace=False)
            xs, ys, zs = xs[sel], ys[sel], zs[sel]
        fig = plt.figure(figsize=(8, 8))
        ax = fig.add_subplot(111, projection="3d")
        ax.scatter(xs, ys, zs, s=0.5)
        out = path.replace(".npz", "_vis.png")
        fig.savefig(out, dpi=120)
        print(f"visualization saved to {out}")


if __name__ == "__main__":
    main()
