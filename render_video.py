"""Render a 360° spiral video of the trained scene.

Parity with the reference's `render_video.py:14-74`: cameras on a spherical
spiral (θ sweeping 360°, φ=-30°, r=4) for 240 frames, each rendered through
the full coarse+fine pipeline and written to an mp4 at 30 fps. The
occupancy-accelerated renderer is used when a baked grid exists.

    python render_video.py --cfg_file configs/nerf/lego.yaml
"""

from __future__ import annotations

import os

import numpy as np

N_FRAMES = 240  # render_video.py:39-43
FPS = 30
PHI_DEG = -30.0
RADIUS = 4.0


def spiral_frames(renderer, params, H, W, focal, near, far, n_frames=N_FRAMES,
                  phi_deg=PHI_DEG, radius=RADIUS, progress=True,
                  render_fn=None, engine=None):
    """Render the 360° spiral as a list of uint8 [H, W, 3] frames.

    ``engine`` routes every frame through a warm serve-engine session
    (nerf_replication_tpu/serve): all frames reuse the same bucketed
    executable and per-frame ``serve_request`` telemetry is emitted.
    ``render_fn`` instead overrides the per-frame renderer (the shared
    gate's sequence-parallel path on a pod); with neither, the
    occupancy-accelerated single-device march."""
    from nerf_replication_tpu.datasets.rays import get_rays_np, pose_spherical

    if render_fn is None:
        render_fn = renderer.render_accelerated
    thetas = np.linspace(-180.0, 180.0, n_frames, endpoint=False)
    if progress:
        from tqdm import tqdm

        thetas = tqdm(thetas, desc="Rendering video")
    frames = []
    for theta in thetas:
        c2w = pose_spherical(float(theta), phi_deg, radius)
        if engine is not None:
            image, _ = engine.render_view(c2w, H, W, focal)
            frames.append(image)
            continue
        rays_o, rays_d = get_rays_np(H, W, focal, c2w)
        rays = np.concatenate([rays_o, rays_d], -1).reshape(-1, 6)
        batch = {"rays": rays, "near": np.float32(near), "far": np.float32(far)}
        out = render_fn(params, batch)
        key = "rgb_map_f" if "rgb_map_f" in out else "rgb_map_c"
        rgb = np.clip(np.asarray(out[key]).reshape(H, W, 3), 0.0, 1.0)
        frames.append((rgb * 255).astype(np.uint8))
    renderer.report_truncation()
    return frames


def render_360_video(cfg, args=None):
    import time

    import jax

    from nerf_replication_tpu.datasets import make_dataset
    from nerf_replication_tpu.obs import init_run
    from nerf_replication_tpu.renderer import make_renderer
    from nerf_replication_tpu.renderer.occupancy import default_grid_path
    from nerf_replication_tpu.utils.setup import load_trained_network

    network, params, _ = load_trained_network(cfg)
    renderer = make_renderer(cfg, network)
    use_grid = bool(
        cfg.task_arg.get("accelerated_renderer", False)
    ) and args is not None
    if use_grid:
        renderer.load_occupancy_grid(default_grid_path(args.cfg_file))

    test_ds = make_dataset(cfg, "test")
    n_frames = int(cfg.task_arg.get("video_frames", N_FRAMES))
    sharded = (
        bool(cfg.get("eval", {}).get("sharded", False))
        and jax.device_count() > 1
    )

    emitter = init_run(cfg, component="render_video")
    engine = render_fn = None
    if sharded:
        # pods render through the shared sequence-parallel gate; the serve
        # engine is a single-device surface (renderer/gate.py)
        from nerf_replication_tpu.renderer.gate import full_image_render_fn

        render_fn = full_image_render_fn(
            cfg, network, renderer, test_ds, use_grid=use_grid
        )
    else:
        # serve-engine session: one warm bucketed executable renders every
        # spiral frame; compile rows + per-frame serve_request telemetry
        # land in the run's stream (emitter is live BEFORE warm-up so the
        # warm-up compiles are on the record)
        from nerf_replication_tpu.serve import RenderEngine

        engine = RenderEngine(
            cfg, network, params,
            near=test_ds.near, far=test_ds.far,
            grid=renderer.occupancy_grid if use_grid else None,
            bbox=renderer.grid_bbox if use_grid else None,
            warmup_families=("full",),  # the spiral never serves degraded
        )
    t0 = time.perf_counter()
    frames = spiral_frames(
        renderer, params, test_ds.H, test_ds.W, test_ds.focal,
        test_ds.near, test_ds.far,
        n_frames=n_frames,
        render_fn=render_fn,
        engine=engine,
    )
    wall = time.perf_counter() - t0
    fps = len(frames) / wall if wall else 0.0
    if engine is not None:
        stats = engine.stats()
        print(
            f"rendered {len(frames)} frames at {fps:.2f} fps through "
            f"{len(stats['compiles'])} warm executables "
            f"({stats['total_compiles']} compiles total, "
            f"{stats['n_truncated']} truncated rays)"
        )
    emitter.emit(
        "eval",
        prefix="video",
        metrics={},
        n_images=len(frames),
        mean_net_time_s=wall / len(frames) if frames else 0.0,
        fps=fps,
    )
    emitter.close()
    os.makedirs(cfg.result_dir, exist_ok=True)
    out_path = _write_video(os.path.join(cfg.result_dir, "video"), frames)
    print(f"video saved to {out_path}")
    return out_path


def _write_video(base_path: str, frames: list[np.ndarray]) -> str:
    """mp4 via OpenCV; animated GIF fallback when no mp4 codec is present."""
    try:
        import cv2

        path = base_path + ".mp4"
        h, w = frames[0].shape[:2]
        writer = cv2.VideoWriter(
            path, cv2.VideoWriter_fourcc(*"mp4v"), FPS, (w, h)
        )
        if writer.isOpened():
            for f in frames:
                writer.write(cv2.cvtColor(f, cv2.COLOR_RGB2BGR))
            writer.release()
            return path
        writer.release()
    except Exception:
        pass
    import imageio.v2 as imageio

    path = base_path + ".gif"
    # imageio >= 2.28 (Pillow plugin) interprets GIF frame duration in
    # MILLIseconds; older releases used seconds. Dispatch on version so the
    # fallback GIF actually plays at FPS either way.
    ver = tuple(int(v) for v in imageio.__version__.split(".")[:2])
    duration = 1000.0 / FPS if ver >= (2, 28) else 1.0 / FPS
    imageio.mimsave(path, frames, duration=duration, loop=0)
    return path


def main():
    from nerf_replication_tpu.config import cfg_from_args, make_parser

    args = make_parser().parse_args()
    cfg = cfg_from_args(args)
    from nerf_replication_tpu.utils.setup import configure_runtime

    configure_runtime(cfg)
    render_360_video(cfg, args)


if __name__ == "__main__":
    main()
