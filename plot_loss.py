"""Plot training curves from a captured console log.

Parity with the reference's `plot_loss.py:7-134`: regex-parse the console
lines (``eta: .. epoch: .. step: .. loss..``, plus validation ``PSNR``/``SSIM``
summaries) into a table and write a 3-panel figure (total loss, PSNR, SSIM).
Our recorder emits the same line format, so this works on either framework's
logs.

    python plot_loss.py --log_file data/record/train.log --out curves.png
    python plot_loss.py --log_file QUALITY.jsonl --out curves.png
"""

from __future__ import annotations

import argparse
import re

LINE_RE = re.compile(r"step:\s*(\d+)")
KV_RE = re.compile(r"([A-Za-z_][A-Za-z0-9_]*):\s*([-+0-9.eE]+)")
VAL_PSNR_RE = re.compile(r"(?:Average PSNR|psnr):\s*([-+0-9.eE]+)", re.IGNORECASE)
VAL_SSIM_RE = re.compile(r"(?:Average SSIM|ssim):\s*([-+0-9.eE]+)", re.IGNORECASE)


def parse_log_file(path: str):
    """Returns (train_rows, val_rows): per-step loss stats and validation
    metric samples (indexed by the last seen train step)."""
    train, val = [], []
    last_step = 0
    with open(path, "r", errors="replace") as f:
        for line in f:
            m = LINE_RE.search(line)
            if m and "eta:" in line:
                row = {k: float(v) for k, v in KV_RE.findall(line)}
                row["step"] = int(m.group(1))
                last_step = row["step"]
                train.append(row)
                continue
            pm, sm = VAL_PSNR_RE.search(line), VAL_SSIM_RE.search(line)
            if pm or sm:
                row = {
                    "step": last_step,
                    "psnr": float(pm.group(1)) if pm else None,
                    "ssim": float(sm.group(1)) if sm else None,
                }
                # the reference prints PSNR and SSIM of one validation on
                # SEPARATE lines — merge them into one sample instead of
                # double-counting the eval (round-3 advisor finding)
                if (
                    val
                    and val[-1]["step"] == last_step
                    and all(
                        val[-1][k] is None or row[k] is None
                        for k in ("psnr", "ssim")
                    )
                ):
                    for k in ("psnr", "ssim"):
                        if row[k] is not None:
                            val[-1][k] = row[k]
                else:
                    val.append(row)
    return train, val


def parse_quality_jsonl(path: str):
    """Returns (train_rows, val_rows) from a QUALITY*.jsonl trace
    (scripts/quality_run.py): each eval record carries step/loss/psnr/ssim;
    run-header lines (no ``step``) are skipped."""
    import json

    train, val = [], []
    with open(path, "r", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if "step" not in r:
                continue
            if "loss" in r:
                train.append(
                    {"step": int(r["step"]), "loss": float(r["loss"])}
                )
            if r.get("psnr") is not None or r.get("ssim") is not None:
                val.append({"step": int(r["step"]), "psnr": r.get("psnr"),
                            "ssim": r.get("ssim")})
    return train, val


def plot_metrics(train, val, out_path: str):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 3, figsize=(16, 4))

    steps = [r["step"] for r in train]
    loss_key = next(
        (k for k in ("total_loss", "loss", "loss_f", "loss_c")
         if train and k in train[0]),
        None,
    )
    if loss_key:
        axes[0].plot(steps, [r[loss_key] for r in train], lw=0.7)
        axes[0].set_yscale("log")
    axes[0].set_title(f"train {loss_key or 'loss'}")
    axes[0].set_xlabel("step")

    vp = [(r["step"], r["psnr"]) for r in val if r.get("psnr") is not None]
    if vp:
        axes[1].plot(*zip(*vp), marker="o", ms=2)
    axes[1].set_title("val PSNR (dB)")
    axes[1].set_xlabel("step")

    vs = [(r["step"], r["ssim"]) for r in val if r.get("ssim") is not None]
    if vs:
        axes[2].plot(*zip(*vs), marker="o", ms=2)
    axes[2].set_title("val SSIM")
    axes[2].set_xlabel("step")

    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    return out_path


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--log_file", required=True,
                        help="console log, or a QUALITY*.jsonl trace")
    parser.add_argument("--out", default="curves.png")
    args = parser.parse_args()
    if args.log_file.endswith(".jsonl"):
        train, val = parse_quality_jsonl(args.log_file)
    else:
        train, val = parse_log_file(args.log_file)
    print(f"parsed {len(train)} train lines, {len(val)} val samples")
    out = plot_metrics(train, val, args.out)
    print(f"figure saved to {out}")


if __name__ == "__main__":
    main()
