#!/bin/bash
# Round-5 follow-up C — redo of battery_r5_resume stage 3c, which hit
# its 1800 s timeout before writing a row: the combined-lever arm
# (bbox clip + update_every 64) overflowed the packed EVAL stream at
# val-render time (68% -> cap 512 -> 36% -> cap 1024), and each
# escalation recompiled the eval executable.  Fix here: preset the
# eval cap at 1024 so the render compiles once, and give the stage the
# budget the escalation trail actually needed.
#
# Run AFTER battery_r5_resume.sh finishes (monoclient tunnel).
set -u
cd "$(dirname "$0")/.."
mkdir -p data/logs
log() { echo "[batteryR5c $(date +%H:%M:%S)] $*"; }
export BENCH_INIT_TOTAL_S=${BENCH_INIT_TOTAL_S:-420}

log "stage 3c-redo: packed + bbox-clip + slow refresh, eval cap preset"
timeout 2700 python scripts/bench_ngp.py --seconds 420 \
  --config lego_hash_packed.yaml --arms ngp_packed \
  --out BENCH_NGP.jsonl task_arg.render_step_size 0.015 \
  task_arg.max_march_samples 64 task_arg.scan_steps 8 \
  task_arg.march_clip_bbox true task_arg.ngp_grid_update_every 64 \
  task_arg.ngp_packed_cap_avg_eval 1024 \
  2>data/logs/r5c_ngp_clip.err | tail -2

log "stage 3-redo: refresh lever alone (update_every 64, no clip)"
timeout 2700 python scripts/bench_ngp.py --seconds 420 \
  --config lego_hash_packed.yaml --arms ngp_packed \
  --out BENCH_NGP.jsonl task_arg.render_step_size 0.01 \
  task_arg.max_march_samples 64 task_arg.scan_steps 8 \
  task_arg.ngp_grid_update_every 64 \
  task_arg.ngp_packed_cap_avg_eval 1024 \
  2>data/logs/r5c_ngp_refresh.err | tail -2

log "battery r5c done"
