"""Primitive bench round 2: gather-width scaling + sort scaling.

Round 1 (BENCH_PRIMITIVES.jsonl) convicted BOTH sides of the hash step:
scatter-add is ~22M rows/s regardless of sorted/unique hints, and narrow
[T,2] row gather is 119M rows/s (~1 GB/s effective). The redesign rests on
two open questions this round answers:

1. Does gather throughput scale with ROW WIDTH? (decides the cell-packed
   wide-row table layout: 1 gather x 64 B beats 8 gathers x 8 B only if
   per-row cost is ~flat in width)
2. Does lax.gather with a (2,2,2,C) WINDOW over a dense 3-D grid lower
   well? (decides the dense-level trilinear formulation)
3. Does the variadic sort stay fast at 8-16M rows with f32 payloads?
   (decides the sort-based scatter-free backward)
4. How fast is the full merge-extraction composite (sort + cumsum +
   position-merge, zero scatters) at real scale?

    python scripts/bench_primitives2.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    p.add_argument("--out", default="")
    p.add_argument("--small", action="store_true",
                   help="tiny shapes for CPU smoke")
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import (
        enable_compilation_cache,
        setup_backend,
    )

    setup_backend(args.force_platform)
    enable_compilation_cache()

    import jax
    import jax.numpy as jnp
    from jax import lax

    K = args.iters
    SMALL = args.small
    sink = open(args.out, "a") if args.out else None

    def emit(rec):
        line = json.dumps(rec)
        print(line, flush=True)
        if sink:
            sink.write(line + "\n")
            sink.flush()

    def _sync(x):
        """Force completion: device_get of a scalar CANNOT return before the
        producing computation finishes. On the axon tunnel,
        ``block_until_ready`` returns early for sub-ms programs (measured:
        a 67 MB gather "completing" in 24 us), so timing must end on a
        host copy, not on a ready-event."""
        leaf = jax.tree_util.tree_leaves(x)[0]
        return float(jnp.ravel(leaf)[0])

    def run(name, body, carry, unit_count, unit, extra=None):
        @jax.jit
        def prog(c):
            return lax.fori_loop(0, K, body, c)

        try:
            out = prog(carry)
            _sync(out)
            t0 = time.perf_counter()
            out = prog(out)
            _sync(out)
            dt = (time.perf_counter() - t0) / K
            rec = {"stage": name, "s_per_iter": round(dt, 6),
                   "rate_per_s": round(unit_count / dt, 1), "unit": unit,
                   "iters": K, "ts": int(time.time())}
            if extra:
                rec.update(extra)
            emit(rec)
        except Exception as exc:
            emit({"stage": name, "error": str(exc).splitlines()[0][:160]})

    key0 = jax.random.PRNGKey(0)

    def fresh_idx(i, hi, n):
        return jax.random.randint(jax.random.fold_in(key0, i), (n,), 0, hi)

    # ---- 1. gather rate vs row width (same total rows) -------------------
    T = 4096 if SMALL else 524288
    R = 65536 if SMALL else 2 * 1024 * 1024
    for W in (2, 8, 16, 32, 128):
        def gather_w(i, acc, W=W):
            idx = fresh_idx(i, T, R)
            vals = jnp.take(acc, idx, axis=0)
            return acc.at[0].add(jnp.sum(vals, axis=0) * 1e-9)

        run(f"gather_rows_w{W}", gather_w, jnp.ones((T, W)), R, "rows",
            {"rows": R, "table": T, "width": W,
             "gbps": None})

    # ---- 2. windowed gather: (2,2,2,C) trilinear neighborhoods -----------
    G = 16 if SMALL else 128  # dense grid resolution
    C = 2
    NPTS = 16384 if SMALL else 1024 * 1024

    def window_gather(i, acc):
        k = jax.random.fold_in(key0, i)
        pos = jax.random.randint(k, (NPTS, 3), 0, G - 1)
        dnums = lax.GatherDimensionNumbers(
            offset_dims=(1, 2, 3, 4),
            collapsed_slice_dims=(),
            start_index_map=(0, 1, 2),
        )
        win = lax.gather(
            acc, pos, dnums, slice_sizes=(2, 2, 2, C),
            mode=lax.GatherScatterMode.CLIP,
        )  # [NPTS, 2,2,2,C]
        return acc.at[0, 0, 0].add(
            jnp.sum(win, axis=(0, 1, 2, 3)) * 1e-9
        )

    run("gather_window_2x2x2", window_gather,
        jnp.ones((G, G, G, C)), NPTS, "windows",
        {"points": NPTS, "grid": G, "window_floats": 8 * C})

    # ---- 2b. same neighborhood via 8 separate narrow gathers (control) ---
    def corner_gathers(i, acc):
        k = jax.random.fold_in(key0, i)
        pos = jax.random.randint(k, (NPTS, 3), 0, G - 1)
        flat = acc.reshape(-1, C)
        total = jnp.zeros((C,))
        for bits in range(8):
            sel = jnp.asarray([(bits >> d) & 1 for d in range(3)])
            cp = pos + sel
            fi = (cp[:, 0] * G + cp[:, 1]) * G + cp[:, 2]
            total = total + jnp.sum(jnp.take(flat, fi, axis=0), axis=0)
        return acc.at[0, 0, 0].add(total * 1e-9)

    run("gather_8corners_narrow", corner_gathers,
        jnp.ones((G, G, G, C)), NPTS * 8, "rows",
        {"points": NPTS, "grid": G})

    # ---- 3. variadic sort scaling with f32 payloads ----------------------
    for RS in ((1 << 17,) if SMALL else (1 << 23, 1 << 24)):
        def sort_payload(i, acc, RS=RS):
            keys = fresh_idx(i, 1 << 19, RS) + acc[0].astype(jnp.int32)
            u0 = jnp.full((RS,), 1e-6, jnp.float32)
            u1 = jnp.full((RS,), 2e-6, jnp.float32)
            sk, s0, s1 = lax.sort((keys, u0, u1), num_keys=1)
            return acc.at[0].set((sk[0] % 7).astype(jnp.float32)
                                 + s0[0] + s1[0])

        run(f"sort_i32_2f32_R{RS}", sort_payload, jnp.zeros((1,)),
            RS, "rows", {"rows": RS})

    # ---- 4. full merge-extraction composite at real scale ----------------
    # rows -> sorted -> cumsum -> dense [T_total, C] grad, ZERO scatters:
    #   positions of arange(T) inside the sorted idx stream come from a
    #   second sort over (idx rows) ++ (entry sentinels), and the dense
    #   grad is csum[hi] - csum[lo] gathered per entry.
    RT = 1 << 17 if SMALL else 1 << 23          # update rows
    TT = 1 << 14 if SMALL else 12 * 1024 * 1024  # total table entries

    def merge_extract(i, acc):
        idx = fresh_idx(i, TT, RT)
        u0 = jnp.full((RT,), 1e-6, jnp.float32) + acc[0, 0] * 1e-12
        u1 = jnp.full((RT,), 2e-6, jnp.float32)
        # sort rows by entry id, payloads ride the sort
        sk, s0, s1 = lax.sort((idx, u0, u1), num_keys=1)
        cs0 = jnp.cumsum(s0)
        cs1 = jnp.cumsum(s1)
        # merge positions: entries (key=e, tag=1) vs rows (key=idx, tag=0);
        # after the sort, entry e sits at position hi(e) + e
        keys2 = jnp.concatenate([sk, jnp.arange(TT, dtype=jnp.int32)])
        tags = jnp.concatenate([
            jnp.zeros((RT,), jnp.int8), jnp.ones((TT,), jnp.int8)
        ])
        mk, mt = lax.sort((keys2, tags), num_keys=2)  # rows before entries
        # positions of the entry sentinels, in entry order, via compaction
        # sort: flagged first, stable by position
        pos = jnp.arange(RT + TT, dtype=jnp.int32)
        ck, cpos = lax.sort(((1 - mt).astype(jnp.int32), pos), num_keys=2)
        hi = cpos[:TT] - jnp.arange(TT, dtype=jnp.int32)  # rows <= e
        csp0 = jnp.concatenate([jnp.zeros((1,), cs0.dtype), cs0])
        csp1 = jnp.concatenate([jnp.zeros((1,), cs1.dtype), cs1])
        hi_prev = jnp.concatenate([jnp.zeros((1,), hi.dtype), hi[:-1]])
        g0 = jnp.take(csp0, hi) - jnp.take(csp0, hi_prev)
        g1 = jnp.take(csp1, hi) - jnp.take(csp1, hi_prev)
        return acc.at[:, 0].add(g0 * 1e-9).at[:, 1].add(g1 * 1e-9)

    run("merge_extract_full", merge_extract, jnp.zeros((TT, C)),
        RT, "rows", {"rows": RT, "table": TT})

    # ---- 5. one-hot gather via MXU for a SMALL dense level ---------------
    T0 = 512 if SMALL else 4920
    R0 = 16384 if SMALL else 1 << 21

    def mxu_gather(i, acc):
        idx = fresh_idx(i, T0, R0)
        oh = (idx[:, None] == jnp.arange(T0)[None, :]).astype(jnp.bfloat16)
        vals = oh @ acc.astype(jnp.bfloat16)  # [R0, C]
        return acc.at[0].add(jnp.sum(vals, axis=0).astype(jnp.float32)
                             * 1e-9)

    run("mxu_onehot_gather_T4920", mxu_gather, jnp.ones((T0, C)),
        R0, "rows", {"rows": R0, "table": T0})

    if sink:
        sink.close()


if __name__ == "__main__":
    main()
