#!/bin/bash
# Round-5 battery D — the stages battery_r5_resume.sh did not land.
#
# What happened to the resume battery (2026-08-01 evening): stage 2r
# landed the packed-march A/B (41.2k carved rays/s @ 32.4 dB), then
# stage 3c's outer `timeout` killed it mid-eval-recompile (the val
# render was escalating the packed eval cap, one recompile per
# doubling) — and a killed in-flight compile wedges the tunnel
# (docs/operations.md).  The battery had its watch loop only at the
# START, so stages 5/6/3 burned their 420 s init budgets against the
# dead tunnel and 3b was mid-burn when killed.
#
# Two structural fixes here:
#   * `gate` — the two-good-probes watch loop runs before EVERY
#     stage, so a wedge (including one caused by our own previous
#     stage's timeout kill) costs waiting, not stages.
#   * every ngp_packed arm presets ngp_packed_cap_avg_eval=1024 (the
#     value stage 3c's escalation trail ended at) so the eval render
#     compiles exactly once.
set -u
cd "$(dirname "$0")/.."
mkdir -p data/logs
log() { echo "[batteryR5d $(date +%H:%M:%S)] $*"; }
export BENCH_INIT_TOTAL_S=${BENCH_INIT_TOTAL_S:-420}

probe() {
  timeout 90 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform in ("tpu", "axon")
import jax.numpy as jnp
jnp.arange(8).sum().block_until_ready()
EOF
}

gate() {
  local good=0
  log "gate: waiting for two good probes 60 s apart"
  until [ "$good" -ge 2 ]; do
    if probe; then
      good=$((good + 1))
      log "gate: probe ok ($good/2)"
      [ "$good" -lt 2 ] && sleep 60
    else
      good=0
      log "gate: probe failed; sleeping 120 s"
      sleep 120
    fi
  done
  log "gate: tunnel usable"
}

NGP_OPTS="task_arg.render_step_size 0.01 task_arg.max_march_samples 64 \
task_arg.scan_steps 8"
CAP="task_arg.ngp_packed_cap_avg_eval 1024"

gate
log "stage 5: NGP H=400 quality trail (decoupled eval budget, packed)"
timeout 3600 python scripts/quality_run.py --minutes 25 --H 400 \
  --config lego_hash_packed.yaml --out_prefix QUALITY_NGP_R5 \
  --tag q_ngp_r5 task_arg.ngp_training true \
  task_arg.ngp_packed_march true $NGP_OPTS $CAP \
  2>data/logs/r5_quality_ngp.err | tail -6

gate
log "stage 6: std quality trail + eval-fps shootout (lego.yaml)"
timeout 2700 python scripts/quality_run.py --minutes 15 --H 400 \
  --config lego.yaml --out_prefix QUALITY_R5 --tag q_std_r5 \
  2>data/logs/r5_quality_std.err | tail -8

gate
log "stage 3c-redo: packed + bbox-clip + slow refresh, eval cap preset"
timeout 2700 python scripts/bench_ngp.py --seconds 420 \
  --config lego_hash_packed.yaml --arms ngp_packed \
  --out BENCH_NGP.jsonl task_arg.render_step_size 0.015 \
  task_arg.max_march_samples 64 task_arg.scan_steps 8 \
  task_arg.march_clip_bbox true task_arg.ngp_grid_update_every 64 \
  $CAP 2>data/logs/r5c_ngp_clip.err | tail -2

gate
log "stage 3b: NGP-step cost analysis (validates the PERF.md roofline)"
for MODE in "" "task_arg.ngp_packed_march true"; do
  BENCH_OPTS="task_arg.render_step_size 0.01 task_arg.max_march_samples 64 $MODE" \
  timeout 2400 python scripts/profile_step.py --ngp --n_rays 4096 \
    --remat false --config lego_hash_packed.yaml --steps 20 \
    2>data/logs/r5_ngp_profile.err | tee -a PROFILE_STEP.jsonl | tail -2
done

gate
log "stage B: fused at scale (16k/scan8, 65k/scan1 — std OOMs at 65k)"
FUSED="network.nerf.fused_trunk true network.nerf.fused_tile 512"
for shape in "16384 8" "65536 1"; do
  set -- $shape
  BENCH_N_RAYS=$1 BENCH_SCAN_STEPS=$2 BENCH_NO_COMPANION=1 \
  BENCH_OPTS="$FUSED" \
  timeout 2400 python bench.py 2>data/logs/r5b_fused_$1.err \
    | tee -a BENCH_SWEEP_FUSED.jsonl | tail -1
done

gate
log "stage C: fused tile axis (256; 1024 retries the VMEM OOM w/ raised limit)"
for t in 256 1024; do
  BENCH_NO_COMPANION=1 \
  BENCH_OPTS="network.nerf.fused_trunk true network.nerf.fused_tile $t" \
  timeout 1800 python bench.py 2>data/logs/r5b_fused_t$t.err \
    | tee -a BENCH_SWEEP_FUSED.jsonl | tail -1
done
python scripts/promote_bench_defaults.py BENCH_SWEEP*.jsonl \
  --config lego.yaml || true

gate
log "stage A: fused-step XLA bytes/flops (did the traffic go away?)"
BENCH_OPTS="$FUSED" timeout 1800 python scripts/profile_step.py \
  --n_rays 4096 --remat false --config lego.yaml --steps 20 \
  2>data/logs/r5b_profile_fused.err | tee -a PROFILE_STEP.jsonl | tail -2

gate
log "stage D: packed-NGP steady state at 8k/16k rays (600 s/arm)"
for nr in 8192 16384; do
  timeout 2400 python scripts/bench_ngp.py --seconds 600 --n_rays $nr \
    --config lego_hash_packed.yaml --arms ngp_packed \
    --out BENCH_NGP.jsonl task_arg.render_step_size 0.015 \
    task_arg.max_march_samples 64 task_arg.scan_steps 8 \
    task_arg.march_clip_bbox true task_arg.ngp_grid_update_every 64 \
    $CAP 2>data/logs/r5b_ngp_$nr.err | tail -2
done

gate
log "stage 4b: packed-hash steady-state scale rows (4k/8k/16k, accum)"
BENCH_TAG=steady_state timeout 5400 python scripts/bench_sweep.py \
  --rays 4096 8192 16384 --dtypes bfloat16 --remat false \
  --scan_steps 8 --grad_accum 1 4 --steps 40 --point_timeout 1800 \
  --config lego_hash_packed.yaml --out BENCH_SWEEP_HASH.jsonl \
  2>data/logs/r5_sweep_hash.err | tail -8

gate
log "stage 4a: flagship steady-state scale rows (8k/16k/65k)"
BENCH_TAG=steady_state BENCH_OPTS="network.nerf.scan_trunk true" \
timeout 7200 python scripts/bench_sweep.py \
  --rays 8192 16384 65536 --dtypes bfloat16 --remat false \
  --scan_steps 8 --grad_accum 1 8 --steps 40 --point_timeout 2400 \
  --out BENCH_SWEEP.jsonl 2>data/logs/r5_sweep_flagship.err | tail -8

gate
log "stage 7: hard-scene trail (thin fence + checker)"
timeout 2700 python scripts/quality_run.py --minutes 15 --H 400 \
  --scene procedural_hard --config lego_hash_packed.yaml \
  --out_prefix QUALITY_HARD --tag q_hard_r5 \
  task_arg.ngp_training true task_arg.ngp_packed_march true $NGP_OPTS \
  $CAP 2>data/logs/r5_quality_hard.err | tail -6

gate
log "stage 3: packed refresh lever alone (update_every 64, no clip)"
timeout 2700 python scripts/bench_ngp.py --seconds 420 \
  --config lego_hash_packed.yaml --arms ngp_packed \
  --out BENCH_NGP.jsonl $NGP_OPTS task_arg.ngp_grid_update_every 64 \
  $CAP 2>data/logs/r5_ngp_refresh.err | tail -2

log "battery r5d done"
