"""NGP-vs-standard training benchmark: throughput and quality per second.

Two arms on the same procedural scene and the same hash-grid config
(configs/nerf/lego_hash.yaml):

* ``std`` — the flagship coarse+fine hierarchical trainer (train/trainer.py)
* ``ngp`` — occupancy-accelerated training (train/ngp.py): live-grid march,
  fine network only

Each arm trains under a fixed wall-clock budget, then evaluates PSNR on held
-out views through its own eval path. Appends one JSON line per arm to the
--out file: {"arm", "rays_per_sec", "steps", "psnr", "ssim", "occupancy"
(ngp only), "t_s", "config", "ts"}.

    python scripts/bench_ngp.py --seconds 120 [--H 200] [--n_rays 4096]
        [--force_platform cpu] [--out BENCH_NGP.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--seconds", type=float, default=120.0,
                   help="train budget per arm (excludes compile + eval)")
    p.add_argument("--H", type=int, default=200)
    p.add_argument("--views", type=int, default=60)
    p.add_argument("--test_views", type=int, default=2)
    p.add_argument("--n_rays", type=int, default=4096)
    p.add_argument("--eval_cap", type=int, default=1024,
                   help="preset packed-eval stream cap (samples/ray avg) for "
                        "the ngp arms — set from telemetry history so eval "
                        "never escalate-recompiles mid-bench (stage-3c trail "
                        "settled at 1024)")
    p.add_argument("--scene_root", default="data/bench_ngp_scene")
    p.add_argument("--arms", nargs="+", default=["std", "ngp"])
    p.add_argument("--config", default="lego_hash.yaml",
                   help="config under configs/nerf/ for both arms")
    p.add_argument("--out", default="BENCH_NGP.jsonl")
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    p.add_argument("opts", nargs="*", default=[])
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import setup_backend

    setup_backend(args.force_platform)

    from nerf_replication_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache()

    import jax

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.datasets import make_dataset
    from nerf_replication_tpu.datasets.procedural import ensure_scene
    from nerf_replication_tpu.evaluators import make_evaluator
    from nerf_replication_tpu.models import make_network

    scene = "procedural"
    ensure_scene(args.scene_root, scene=scene, H=args.H, W=args.H,
                 n_train=args.views, n_test=args.test_views)

    def build_cfg(extra):
        return make_cfg(
            os.path.join(_REPO, "configs", "nerf", args.config),
            [
                "scene", scene,
                "train_dataset.data_root", args.scene_root,
                "test_dataset.data_root", args.scene_root,
                "train_dataset.H", str(args.H), "train_dataset.W", str(args.H),
                "test_dataset.H", str(args.H), "test_dataset.W", str(args.H),
                "test_dataset.cams", "[0, -1, 1]",
                "task_arg.N_rays", str(args.n_rays),
                "task_arg.precrop_iters", "0",
                *extra,
                *args.opts,
            ],
        )

    # rows go through the telemetry append helper (crash-safe single-line
    # writes) and are shape-checked against the bench schema before they
    # land — a drifted row fails the bench loudly, not the report later
    from nerf_replication_tpu.obs import append_jsonl, validate_bench_row

    for arm in args.arms:
        if arm == "ngp":
            cfg = build_cfg((
                "task_arg.ngp_training", "true",
                "task_arg.ngp_grid_res", "128",
                "task_arg.ngp_packed_cap_avg_eval", str(args.eval_cap),
            ))
        elif arm == "ngp_packed":
            # globally-packed sample stream (renderer/packed_march.py):
            # encoder/MLP run only on occupied samples compacted across
            # rays — the round-5 throughput lever over the [N, K] march
            cfg = build_cfg((
                "task_arg.ngp_training", "true",
                "task_arg.ngp_grid_res", "128",
                "task_arg.ngp_packed_march", "true",
                "task_arg.ngp_packed_cap_avg_eval", str(args.eval_cap),
            ))
        else:
            cfg = build_cfg(())
        network = make_network(cfg)
        evaluator = make_evaluator(cfg)
        train_ds = make_dataset(cfg, "train")
        test_ds = make_dataset(cfg, "test")
        bank = tuple(jax.device_put(a) for a in train_ds.ray_bank())
        key = jax.random.PRNGKey(1)

        if arm.startswith("ngp"):
            from nerf_replication_tpu.train.ngp import make_ngp_trainer

            trainer = make_ngp_trainer(cfg, network)
            state, _ = trainer.make_state(jax.random.PRNGKey(0))
        else:
            from nerf_replication_tpu.train import make_loss, make_train_state
            from nerf_replication_tpu.train.trainer import Trainer

            loss = make_loss(cfg, network)
            trainer = Trainer(cfg, network, loss, evaluator)
            state, _ = make_train_state(cfg, network, jax.random.PRNGKey(0))

        # compile outside the timed window
        state, stats = trainer.step(state, bank[0], bank[1], key)
        jax.block_until_ready(stats)

        steps = 0
        t0 = time.time()
        phase_switch = None  # (steps, t) when NGP leaves warmup
        while time.time() - t0 < args.seconds:
            it = 0
            while it < 20:
                state, stats = trainer.multi_step(
                    state, bank[0], bank[1], key
                )
                if arm.startswith("ngp"):
                    k = trainer.last_burst_steps
                    if phase_switch is None and not trainer.last_burst_warm:
                        phase_switch = (steps + it, time.time() - t0)
                else:
                    k = trainer.scan_steps
                it += k
            jax.block_until_ready(stats)
            steps += it
        dt = time.time() - t0

        if arm.startswith("ngp"):
            result = trainer.val(
                state, test_ds, evaluator, max_images=args.test_views
            )
        else:
            result = trainer.val(
                state, epoch=steps, test_dataset=test_ds,
                max_images=args.test_views,
            )

        rec = {
            "arm": arm,
            "rays_per_sec": round(steps * args.n_rays / dt, 1),
            "steps": steps,
            "t_s": round(dt, 1),
            "psnr": round(float(result.get("psnr", 0.0)), 3),
            "ssim": round(float(result.get("ssim", 0.0)), 4),
            "config": args.config,
            "n_rays": args.n_rays,
            "ts": round(time.time(), 1),
            # provenance: the march/trainer overrides this arm ran with
            # (the r4 A/B's step-0.01/K-64 settings were only recoverable
            # from shell history)
            **({"opts": " ".join(args.opts)} if args.opts else {}),
        }
        if arm.startswith("ngp"):
            rec["occupancy"] = round(float(stats["occupancy"]), 4)
            rec["truncated_frac"] = round(float(stats["truncated_frac"]), 4)
            if "overflow_frac" in stats:
                rec["overflow_frac"] = round(
                    float(stats["overflow_frac"]), 4
                )
            # train-batch psnr: the val render is blind while the grid is
            # dense (K-budget truncation renders ~background), so this is
            # the only honest learning signal during warmup
            rec["train_psnr"] = round(float(stats["psnr"]), 3)
            if phase_switch is not None:
                # throughput of the CARVED phase alone — the steady-state
                # number the warmup amortizes into on longer runs
                s_sw, t_sw = phase_switch
                if dt > t_sw:
                    rec["warmup_steps_run"] = s_sw
                    rec["warmup_t_s"] = round(t_sw, 1)
                    rec["carved_rays_per_sec"] = round(
                        (steps - s_sw) * args.n_rays / (dt - t_sw), 1
                    )
        errors = validate_bench_row(rec)
        if errors:
            raise SystemExit(f"bench row failed schema check: {errors}")
        print(json.dumps(rec), flush=True)
        append_jsonl(args.out, rec)


if __name__ == "__main__":
    main()
