#!/bin/bash
# Round-4 chip battery (run with the host core IDLE — concurrent CPU load
# inflates dispatch-thread timings 3x, PERF.md round 4):
#   1. flagship headline (lego.yaml, BENCH_DEFAULTS scan-burst shape) with
#      the SplitDense concat-split in — vs round 3's 48.5k rays/s.
#   2. the 65,536-ray compile frontier with scan_trunk (remat off/on) —
#      VERDICT r3 #4's missing BENCH_SWEEP.jsonl rows.
#   3. flagship profile (bytes/step) after the concat-split — vs f3's
#      48.2 GiB/step.
set -u
cd "$(dirname "$0")/.."
log() { echo "[batteryR4 $(date +%H:%M:%S)] $*"; }

log "stage 1: flagship headline"
timeout 1800 python bench.py 2>&1 | grep -vE "WARNING|^E[0-9]" | tail -3

log "stage 2: 65k compile frontier (scan_trunk)"
BENCH_OPTS="network.nerf.scan_trunk true" \
timeout 3600 python scripts/bench_sweep.py \
  --rays 65536 --dtypes bfloat16 --remat false true --scan_steps 1 \
  --steps 10 --point_timeout 2400 --out BENCH_SWEEP.jsonl \
  2>&1 | grep -vE "WARNING|^E[0-9]" | tail -6

log "stage 3: flagship profile (bytes/step after concat-split)"
timeout 1800 python scripts/profile_step.py --n_rays 4096 --remat false \
  2>&1 | grep -vE "WARNING|^E[0-9]" | tail -6

log "battery r4 done"
