#!/bin/bash
# Staged TPU measurement battery (VERDICT r2 #4: the committed outage machinery).
#
# The axon tunnel on this machine is MONOCLIENT (one process may hold it),
# wedges for hours after HBM-OOM compile storms, and can FLAP — answer one
# probe then wedge again. This script encodes that operational knowledge:
#
#   * watch:   probe every 5 min (budget: WATCH_PROBES, default 60 ≈ 5 h);
#              require TWO consecutive good probes 60 s apart before
#              declaring a window (a single probe is not a usable window)
#   * battery: run the measurement stages SERIALLY, each with its own
#              timeout and its own incremental output file, ordered by
#              VALUE: the quality artifact first (the single most important
#              output, on the only shape proven to compile here), then the
#              sweeps whose cold compiles are long and can wedge the tunnel
#
# Usage:   bash scripts/tpu_battery.sh            # watch, then full battery
#          WATCH_PROBES=0 bash scripts/tpu_battery.sh   # skip watch, run now
#
# Stages (each standalone-rerunnable):
#   1. quality run (35 min, chip)      -> QUALITY.jsonl/md + grid + video
#   1b. scan-burst sweep @4096         -> BENCH_SWEEP.jsonl (cheap compiles)
#   2. remat sweep 16k/64k bf16        -> BENCH_SWEEP_REMAT.jsonl
#      + promote best point            -> BENCH_DEFAULTS.json (bench.py reads)
#   3. lego_hash sweep                 -> BENCH_SWEEP_HASH.jsonl
#      (not promoted: the driver headline stays on lego.yaml so vs_baseline
#      remains apples-to-apples with the reference's big-MLP number)
#   4. hash shootout XLA vs Pallas     -> BENCH_HASH.jsonl
#   5. profile step (top-op table)     -> PROFILE_STEP.jsonl
#   6. scale check 800x800             -> SCALE_CHECK.jsonl
#
# NEVER pkill by pattern on this box — kill by exact PID only (the driver's
# own command line matches almost any pattern).
set -u
cd "$(dirname "$0")/.."

WATCH_PROBES=${WATCH_PROBES:-60}
PROBE_SLEEP=${PROBE_SLEEP:-300}
log() { echo "[battery $(date +%H:%M:%S)] $*"; }

# The watch loop below already gates every stage on a CONFIRMED-up tunnel,
# so stages must not ride out a wedge with the ~20-min default init budget
# (utils/platform.py): a mid-battery wedge should fail the stage loudly
# INSIDE its outer `timeout` (smallest stage budget: 1200 s) and let the
# next stage's init re-probe. With the per-stage BENCH_INIT_DELAY_S=30
# overrides below, 420 s admits 2 full probes (fail ≈ 270 s in).
export BENCH_INIT_TOTAL_S=${BENCH_INIT_TOTAL_S:-420}

probe_once() {
  timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

if [ "$WATCH_PROBES" -gt 0 ]; then
  up=0
  for i in $(seq 1 "$WATCH_PROBES"); do
    if probe_once; then
      log "probe $i: UP — confirming (tunnel can flap)"
      sleep 60
      if probe_once; then
        log "probe $i: CONFIRMED up"
        up=1
        break
      fi
      log "probe $i: flapped back down"
    else
      log "probe $i: down"
    fi
    sleep "$PROBE_SLEEP"
  done
  if [ "$up" -ne 1 ]; then
    log "tunnel never confirmed up within the watch budget; exiting"
    exit 1
  fi
fi

log "=== stage 1: quality run (chip, 35 min) — the #1 artifact, so it goes first ==="
# 4096 rays / no remat: the one shape PROVEN to compile through this tunnel
# (round 2's headline). Round-3 lesson: a 16k-ray remat graph took >15 min of
# remote compile and the point timeout killed it — never put an unproven
# compile in front of the quality artifact.
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 \
timeout 5400 python scripts/quality_run.py --minutes 35 --H 400 --views 100 \
  --test_views 4 --n_rays 4096 --eval_every_s 120 \
  --target_psnr 21.55 2>&1 | tail -40

log "=== stage 1b: scan-burst sweep on the proven 4096-ray shape ==="
# K optimizer steps per device dispatch (task_arg.scan_steps, lax.scan)
# directly attacks the measured latency bound at 4096 rays (16.9% MFU,
# ~40 sequential small matmuls/step); this shape's compile is known-cheap,
# so it's the lowest-risk shot at a big headline jump.
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 5400 python scripts/bench_sweep.py \
  --rays 4096 --dtypes bfloat16 --remat false --scan_steps 8 32 --steps 96 \
  --point_timeout 1800 --out BENCH_SWEEP.jsonl
python scripts/promote_bench_defaults.py \
  BENCH_SWEEP.jsonl BENCH_SWEEP_REMAT.jsonl --config lego.yaml

log "=== stage 2: remat sweep (big-MLP headline) ==="
# point_timeout must cover a cold 15-20 min remote compile (measured r3);
# a killed compile caches nothing and the work is lost.
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 9000 python scripts/bench_sweep.py \
  --rays 16384 65536 --dtypes bfloat16 --remat true --steps 30 \
  --point_timeout 2400 --out BENCH_SWEEP_REMAT.jsonl
python scripts/promote_bench_defaults.py \
  BENCH_SWEEP_REMAT.jsonl BENCH_SWEEP.jsonl --config lego.yaml

log "=== stage 3: lego_hash sweep (the 1M rays/s config) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 7500 python scripts/bench_sweep.py \
  --config lego_hash.yaml --rays 65536 262144 --dtypes bfloat16 \
  --remat true --steps 30 --point_timeout 2400 --out BENCH_SWEEP_HASH.jsonl

mkdir -p data/logs
log "=== stage 3b: NGP-vs-standard training bench ==="
timeout 1800 python scripts/bench_ngp.py --seconds 120 \
  --out BENCH_NGP.jsonl precision.compute_dtype bfloat16 \
  2>data/logs/bench_ngp.err | tee -a data/logs/bench_ngp.out

log "=== stage 4: hash shootout (XLA vs Pallas) ==="
timeout 1500 python scripts/bench_hash.py 2>data/logs/bench_hash.err \
  | tee -a BENCH_HASH.jsonl

log "=== stage 5: profile step ==="
timeout 1200 python scripts/profile_step.py --n_rays 65536 --remat true \
  2>data/logs/profile_step.err | tee -a PROFILE_STEP.jsonl

log "=== stage 6: scale check 800x800 ==="
timeout 1800 python scripts/scale_check.py --H 800 \
  2>data/logs/scale_check.err | tee -a SCALE_CHECK.jsonl

log "=== battery done ==="
