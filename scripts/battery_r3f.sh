#!/bin/bash
# Round-3 battery, stage F: the honest-timing re-run + the fix candidate.
#
#   f1. bench_hash_step with per-call input variation (the first c0 run's
#       argument-stationary loops produced physically impossible timings —
#       see _timed's docstring) + the enc3 sorted-segment-sum probe
#   f2. in-context A/B: bench.py on lego_hash with and without
#       network.xyz_encoder.custom_bwd (the per-level sorted-segment VJP)
set -u
cd "$(dirname "$0")/.."
log() { echo "[batteryF $(date +%H:%M:%S)] $*"; }

WAIT_PID=${WAIT_PID:-}
if [ -n "$WAIT_PID" ]; then
  log "waiting for battery pid $WAIT_PID to release the tunnel"
  while kill -0 "$WAIT_PID" 2>/dev/null; do sleep 60; done
  log "pid $WAIT_PID gone; waiting 120 s for the tunnel to settle"
  sleep 120
fi

log "=== F1: trisection re-run with varied inputs (honest timings) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 5400 python scripts/bench_hash_step.py \
  --n_rays 4096 --steps 10 | tee -a BENCH_HASH_STEP.jsonl

log "=== F2a: lego_hash step, plain autodiff backward (control) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 BENCH_CONFIG=lego_hash.yaml \
  BENCH_N_RAYS=4096 BENCH_STEPS=30 BENCH_SCAN_STEPS=1 timeout 3600 \
  python bench.py | tee -a BENCH_SWEEP_HASH.jsonl

log "=== F2b: lego_hash step, custom_bwd (per-level sorted segment_sum) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 BENCH_CONFIG=lego_hash.yaml \
  BENCH_N_RAYS=4096 BENCH_STEPS=30 BENCH_SCAN_STEPS=1 \
  BENCH_OPTS="network.xyz_encoder.custom_bwd true" timeout 3600 \
  python bench.py | tee -a BENCH_SWEEP_HASH.jsonl

log "=== battery F done ==="
