#!/bin/bash
# Round-3 battery, stage D: reference-style eval timing at real scale
# (SURVEY.md §3.2 / VERDICT r2 #8's fps half). Requires battery C's c2 to
# have trained+saved the 800x800 quality checkpoint and baked its grid.
# Runs `run.py --type evaluate` on chip — per-image net_time + fps with the
# first image excluded (ref run.py:73-87) — through BOTH render paths.
set -u
cd "$(dirname "$0")/.."
log() { echo "[batteryD $(date +%H:%M:%S)] $*"; }

WAIT_PID=${WAIT_PID:-}
if [ -n "$WAIT_PID" ]; then
  log "waiting for battery pid $WAIT_PID to release the tunnel"
  while kill -0 "$WAIT_PID" 2>/dev/null; do sleep 60; done
  log "pid $WAIT_PID gone; waiting 120 s for the tunnel to settle"
  sleep 120
fi

H=${H:-800}
TAG="quality_lego_${H}"
MODEL_DIR="data/trained_model/nerf/procedural/${TAG}"
SCENE="data/quality_scene_h${H}_v50_t2"
if [ ! -d "$MODEL_DIR" ]; then
  log "no checkpoint at $MODEL_DIR (c2 did not finish?); skipping"
  exit 0
fi

# array, not a string: unquoted [0,-1,1] in a flat string is a glob that
# could match a single-char filename and silently corrupt the override
OPTS=(scene procedural exp_name "${TAG}"
  train_dataset.data_root "${SCENE}" test_dataset.data_root "${SCENE}"
  train_dataset.H "${H}" train_dataset.W "${H}"
  test_dataset.H "${H}" test_dataset.W "${H}"
  test_dataset.cams "[0,-1,1]")

log "=== d1: evaluate 800x800, vanilla chunked renderer ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 2400 python run.py \
  --type evaluate --cfg_file configs/nerf/lego.yaml "${OPTS[@]}" \
  task_arg.accelerated_renderer false 2>&1 | tail -15

# the accelerated path loads logs/<cfg>/occupancy_grid.npz (reference
# artifact layout); c2's bake lands next to the checkpoint. Fail d2 loudly
# when the grid is missing — run.py would silently fall back to the
# vanilla path and the d1-vs-d2 comparison would be meaningless.
if [ ! -f "${MODEL_DIR}/occupancy_grid.npz" ]; then
  log "d2 SKIPPED: no baked grid at ${MODEL_DIR}/occupancy_grid.npz"
  exit 0
fi
mkdir -p logs/lego
cp "${MODEL_DIR}/occupancy_grid.npz" logs/lego/occupancy_grid.npz

log "=== d2: evaluate 800x800, occupancy-accelerated marcher ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 2400 python run.py \
  --type evaluate --cfg_file configs/nerf/lego.yaml "${OPTS[@]}" \
  task_arg.accelerated_renderer true 2>&1 | tail -15

log "=== battery D done ==="
