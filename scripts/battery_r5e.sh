#!/bin/bash
# Round-5 battery E — deadline-aware remainder of battery_r5d.sh.
#
# Context: the 19:54 wedge outlasted every earlier one; the round (and
# the driver's own `python bench.py`) ends shortly after 07:00, and a
# battery stage still holding the monoclient tunnel then — or a hard
# kill landing mid-remote-compile just before — would take the
# driver's round-end record down with it.  So every stage here:
#   * waits for the two-good-probes gate,
#   * STARTS only if its estimated duration + 10 min margin fits
#     before the 06:35 UTC deadline,
#   * runs under a kill timer capped at the REMAINING headroom (see
#     `budget` — a stage that overruns its estimate still can't hold
#     the tunnel past the deadline),
# and whatever time remains at the end goes to one plain warm-cache
# `python bench.py` replay (the driver-verifiable headline); the
# BENCH_DEFAULTS re-promotion over the freshest sweep rows runs from an
# EXIT trap, so no wedged gate can skip it.
set -u
cd "$(dirname "$0")/.."
mkdir -p data/logs
log() { echo "[batteryR5e $(date -u +%H:%M:%S)] $*"; }
export BENCH_INIT_TOTAL_S=${BENCH_INIT_TOTAL_S:-420}

DEADLINE=$(date -u -d "06:35" +%s)
[ "$DEADLINE" -le "$(date -u +%s)" ] && DEADLINE=$((DEADLINE + 86400))

probe() {
  timeout 90 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform in ("tpu", "axon")
import jax.numpy as jnp
jnp.arange(8).sum().block_until_ready()
EOF
}

gate() {
  local good=0
  log "gate: waiting for two good probes 60 s apart"
  until [ "$good" -ge 2 ]; do
    if [ "$(date -u +%s)" -ge "$DEADLINE" ]; then
      log "gate: deadline passed while waiting; exiting"
      exit 0
    fi
    if probe; then
      good=$((good + 1))
      log "gate: probe ok ($good/2)"
      [ "$good" -lt 2 ] && sleep 60
    else
      good=0
      log "gate: probe failed; sleeping 120 s"
      sleep 120
    fi
  done
  log "gate: tunnel usable"
}

# fits <est_minutes> -> 0 if the stage can start now and finish (plus a
# 10-min margin) before the deadline
fits() {
  local need=$(( ($1 + 10) * 60 ))
  [ $(( $(date -u +%s) + need )) -le "$DEADLINE" ]
}

# budget <ceiling_minutes> -> the stage's `timeout` seconds: its own
# ceiling, capped at whatever actually remains before the deadline minus
# the 10-min margin.  `fits` only checks the ESTIMATE at start time — a
# stage that overruns its estimate would otherwise hold the tunnel
# straight through the deadline on its fixed kill timer.
budget() {
  local s=$(( $1 * 60 ))
  local cap=$(( DEADLINE - $(date -u +%s) - 600 ))
  [ "$cap" -lt "$s" ] && s=$cap
  [ "$s" -lt 60 ] && s=60
  echo "$s"
}

# The BENCH_DEFAULTS re-promotion is purely local (reads sweep rows,
# no chip) — run it on EVERY exit so a wedged tunnel parking a gate at
# the deadline can't skip it.
trap 'python scripts/promote_bench_defaults.py BENCH_SWEEP*.jsonl \
  --config lego.yaml || true' EXIT

NGP_OPTS="task_arg.render_step_size 0.01 task_arg.max_march_samples 64 \
task_arg.scan_steps 8"
CAP="task_arg.ngp_packed_cap_avg_eval 1024"

gate
if fits 45; then
  log "stage 5: NGP H=400 quality trail (decoupled eval budget, packed)"
  timeout $(budget 60) python scripts/quality_run.py --minutes 25 --H 400 \
    --config lego_hash_packed.yaml --out_prefix QUALITY_NGP_R5 \
    --tag q_ngp_r5 task_arg.ngp_training true \
    task_arg.ngp_packed_march true $NGP_OPTS $CAP \
    2>data/logs/r5_quality_ngp.err | tail -6
else log "skip stage 5 (needs 45 min)"; fi

gate
if fits 30; then
  log "stage 6: std quality trail + eval-fps shootout (lego.yaml)"
  timeout $(budget 45) python scripts/quality_run.py --minutes 15 --H 400 \
    --config lego.yaml --out_prefix QUALITY_R5 --tag q_std_r5 \
    2>data/logs/r5_quality_std.err | tail -8
else log "skip stage 6 (needs 30 min)"; fi

gate
if fits 20; then
  log "stage 3c-redo: packed + bbox-clip + slow refresh, eval cap preset"
  timeout $(budget 45) python scripts/bench_ngp.py --seconds 420 \
    --config lego_hash_packed.yaml --arms ngp_packed \
    --out BENCH_NGP.jsonl task_arg.render_step_size 0.015 \
    task_arg.max_march_samples 64 task_arg.scan_steps 8 \
    task_arg.march_clip_bbox true task_arg.ngp_grid_update_every 64 \
    $CAP 2>data/logs/r5c_ngp_clip.err | tail -2
else log "skip stage 3c (needs 20 min)"; fi

gate
if fits 40; then
  log "stage D: packed-NGP steady state at 8k rays (600 s)"
  timeout $(budget 40) python scripts/bench_ngp.py --seconds 600 --n_rays 8192 \
    --config lego_hash_packed.yaml --arms ngp_packed \
    --out BENCH_NGP.jsonl task_arg.render_step_size 0.015 \
    task_arg.max_march_samples 64 task_arg.scan_steps 8 \
    task_arg.march_clip_bbox true task_arg.ngp_grid_update_every 64 \
    $CAP 2>data/logs/r5b_ngp_8192.err | tail -2
else log "skip stage D (needs 40 min)"; fi

gate
if fits 25; then
  log "stage 3b: NGP-step cost analysis (validates the PERF.md roofline)"
  for MODE in "" "task_arg.ngp_packed_march true"; do
    BENCH_OPTS="task_arg.render_step_size 0.01 task_arg.max_march_samples 64 $MODE" \
    timeout $(budget 25) python scripts/profile_step.py --ngp --n_rays 4096 \
      --remat false --config lego_hash_packed.yaml --steps 20 \
      2>>data/logs/r5_ngp_profile.err | tee -a PROFILE_STEP.jsonl | tail -2
  done
else log "skip stage 3b (needs 25 min)"; fi

gate
if fits 35; then
  log "stage B/C: fused 16k/scan8 + tile-1024 VMEM retry"
  FUSED="network.nerf.fused_trunk true network.nerf.fused_tile 512"
  BENCH_N_RAYS=16384 BENCH_SCAN_STEPS=8 BENCH_NO_COMPANION=1 \
  BENCH_OPTS="$FUSED" \
  timeout $(budget 30) python bench.py 2>data/logs/r5b_fused_16384.err \
    | tee -a BENCH_SWEEP_FUSED.jsonl | tail -1
  BENCH_NO_COMPANION=1 \
  BENCH_OPTS="network.nerf.fused_trunk true network.nerf.fused_tile 1024" \
  timeout $(budget 25) python bench.py 2>data/logs/r5b_fused_t1024.err \
    | tee -a BENCH_SWEEP_FUSED.jsonl | tail -1
else log "skip stage B/C (needs 35 min)"; fi

gate
if fits 25; then
  log "stage 7: hard-scene trail (thin fence + checker)"
  timeout $(budget 25) python scripts/quality_run.py --minutes 12 --H 400 \
    --scene procedural_hard --config lego_hash_packed.yaml \
    --out_prefix QUALITY_HARD --tag q_hard_r5 \
    task_arg.ngp_training true task_arg.ngp_packed_march true $NGP_OPTS \
    $CAP 2>data/logs/r5_quality_hard.err | tail -6
else log "skip stage 7 (needs 25 min)"; fi

# Closing move: one driver-identical warm replay (the freshest
# BENCH_DEFAULTS promotion now runs from the EXIT trap, gate or no gate).
gate
if fits 2; then
  log "closing: warm-cache driver replay (python bench.py)"
  timeout $(budget 20) python bench.py 2>data/logs/r5e_replay.err | tail -1
fi
log "battery r5e done"
