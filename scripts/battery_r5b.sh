#!/bin/bash
# Round-5 follow-up battery — runs AFTER battery_r5.sh. Two questions the
# first battery left open:
#
#   A. Did the fused Pallas trunk actually cut the step's HBM bytes?
#      Stage 1b measured only +1.9% at the headline shape (48.6k vs
#      47.7k), far off the modeled 65-100k. profile_step's XLA
#      flops/bytes accounting on the FUSED step tells us whether the
#      traffic went away (=> the step is bound elsewhere, attack that)
#      or didn't (=> the kernel's sequential-grid dW accumulation or
#      f32 weight streams eat the win).
#   B. Where does fused win at SCALE? The std 65k-ray no-remat step
#      OOMs HBM (24.6G, BENCH_SWEEP ts 1785518936); the fused forward
#      saves only [M,4] + inputs, so 65k/scan-1 should now fit without
#      remat — and big batches amortize the dispatch floor that caps
#      the 4k-ray shape.
#
# Plus the packed-NGP scale rows the r4 verdict disowned as
# compile-window artifacts: steady-state 8k/16k with enough budget to
# carve (warmup is a fixed step count, so bigger batches need more
# wall).
set -u
cd "$(dirname "$0")/.."
mkdir -p data/logs
log() { echo "[batteryR5b $(date +%H:%M:%S)] $*"; }
export BENCH_INIT_TOTAL_S=${BENCH_INIT_TOTAL_S:-420}

FUSED="network.nerf.fused_trunk true network.nerf.fused_tile 512"

log "stage A: fused-step XLA bytes/flops (did the traffic go away?)"
BENCH_OPTS="$FUSED" timeout 1800 python scripts/profile_step.py \
  --n_rays 4096 --remat false --config lego.yaml --steps 20 \
  2>data/logs/r5b_profile_fused.err | tee -a PROFILE_STEP.jsonl | tail -2

log "stage B: fused at scale (16k/scan8, 65k/scan1 — std OOMs here)"
for shape in "16384 8" "65536 1"; do
  set -- $shape
  BENCH_N_RAYS=$1 BENCH_SCAN_STEPS=$2 BENCH_OPTS="$FUSED" \
  timeout 2400 python bench.py 2>data/logs/r5b_fused_$1.err \
    | tee -a BENCH_SWEEP_FUSED.jsonl | tail -1
done

log "stage C: fused tile axis at the headline shape (256; 1024 retry)"
# 1024 re-tries the recorded scoped-VMEM OOM with the raised Mosaic
# vmem_limit_bytes (ops/fused_mlp.py _mosaic_kwargs) — doubling the tile
# halves the kernel's per-tile weight-stream HBM term.
for t in 256 1024; do
  BENCH_OPTS="network.nerf.fused_trunk true network.nerf.fused_tile $t" \
  timeout 1800 python bench.py 2>data/logs/r5b_fused_t$t.err \
    | tee -a BENCH_SWEEP_FUSED.jsonl | tail -1
done

log "stage D: packed-NGP steady state at 8k/16k rays (600 s/arm)"
for nr in 8192 16384; do
  timeout 2400 python scripts/bench_ngp.py --seconds 600 --n_rays $nr \
    --config lego_hash_packed.yaml --arms ngp_packed \
    --out BENCH_NGP.jsonl task_arg.render_step_size 0.015 \
    task_arg.max_march_samples 64 task_arg.scan_steps 8 \
    task_arg.march_clip_bbox true task_arg.ngp_grid_update_every 64 \
    2>data/logs/r5b_ngp_$nr.err | tail -2
done

log "stage E: re-promote the winning point for the driver's bench"
python scripts/promote_bench_defaults.py BENCH_SWEEP*.jsonl \
  --config lego.yaml || true

log "battery r5b done"
