"""Chaos runner: drive a real train or serve loop under a seeded FaultPlan.

Boots the same self-contained tiny scene the benches use (procedural
scene, randomly-initialized network — no downloads), installs a
deterministic :class:`~nerf_replication_tpu.resil.FaultPlan`, runs the
REAL production loop (``train.fit`` or the engine + micro-batcher stack),
and then summarizes the run's ``fault``/``retry``/``breaker`` telemetry
next to its recovery status. The same ``--seed`` + ``--fault`` specs
always produce the same failure schedule, so a chaos reproduction is a
command line, not a war story.

    python scripts/chaos_run.py train --fault checkpoint.save:io_error
    python scripts/chaos_run.py train --fault train.loss:nan_loss:30 \
        --epochs 3
    python scripts/chaos_run.py serve --fault serve.flush:kill:4 \
        --fault occupancy.load:truncate --requests 40
    python scripts/chaos_run.py serve --scenes 3 \
        --fault fleet.load:io_error:2
    python scripts/chaos_run.py serve --scenes 3 \
        --fault fleet.load:truncate:3:1
    python scripts/chaos_run.py serve --scenes 3 --tenants 3 \
        --fault fleet.load:truncate:3:1
    python scripts/chaos_run.py serve --replicas 3 --requests 48
    python scripts/chaos_run.py serve --replicas 2 --processes

``--replicas N`` serves the stream through the scale-out front door
(nerf_replication_tpu/scale): N in-process replicas behind the router,
a supervisor holding the fleet at N. Halfway through the stream one
replica's batcher is killed WITHOUT its registry entry knowing — the
crashed-process shape. Recovery then ALSO requires the router to fail
the next submit over to a survivor (the caller sees one submit, not
the crash), the supervisor's next pass to replace the dead replica
1:1, every post-kill request to complete, drain-before-retire at
teardown to fail zero in-flight requests, and the whole episode to
trigger zero recompiles (the replacement warms from the shared
engine).

``--replicas N --processes`` runs the same kill against the REAL
multi-process shape (scale/launcher.py + scale/placement.py): the
launcher spawns N ``serve.py`` children warm from one shared artifact
dir, routed traffic heats a scene until the placement plan replicates
it ``hot_width``-wide, then a hot-scene child is SIGKILLed at the OS
level with its registry entry still saying ready. Recovery then ALSO
requires the launcher's 1:1 respawn, the replan restoring the hot
width, and zero compiles across every child (all-disk warm starts).

``--scenes N`` puts the serve mode behind a multi-scene fleet
(nerf_replication_tpu/fleet) with an HBM budget of about half the
scenes, so the request stream churns eviction/reload while faults land
on the ``fleet.load`` point: an injected ``io_error`` must be absorbed
by the retry ladder, while a ``truncate`` (torn checkpoint, caught by
the tree checksum) must fail ONLY that scene's requests — every other
scene keeps serving and the run still counts as recovered.

``--tenants N`` adds the QoS control plane (fleet/qos.py): one ``hot``
tenant floods a deliberately tiny token bucket while ``N-1`` quiet
tenants trickle under a generous one. Recovery then ALSO requires the
blast radius to hold: the hot tenant must actually be throttled (429s +
a ``flight_tenant_throttled.json`` naming it), every quiet tenant must
keep getting full-tier responses (zero quiet sheds, zero quiet denies),
and — combined with a torn-scene fault — the scene-error flight dump
must name the injected fault next to the throttle dump.

Fault spec grammar: ``point:kind[:after[:times]]`` — inject ``kind`` at
``point`` after letting ``after`` hits through, on up to ``times`` hits
(``-1`` = every hit). Points/kinds: ``resil.FAULT_POINTS`` /
``resil.FAULT_KINDS`` (catalog: docs/robustness.md).

Serve mode runs with span tracing on and a resil flight recorder
installed (resil/flight.py): a scenario that opens the circuit breaker
or crashes the batcher worker must leave a ``flight_<reason>.json``
post-mortem in the workdir's record dir whose event ring names the
injected fault — ``check_flight`` asserts it, and a missing or
cause-less dump fails the run.

Every injected-fault scenario also self-documents as an INCIDENT
(obs/incidents.py): the correlator opens on the first injected fault
row, assembles the fault/retry/breaker/residency timeline around it,
and the harness force-resolves it once the recovery checks pass.
``check_incidents`` then fails the run unless a schema-valid RESOLVED
``incident_*.json`` names the injected fault point — and a clean run
(no ``--fault`` specs, no deliberate tenant flood) must leave ZERO
incident files. ``--alerts`` adds a chaos-scaled burn-rate alert
engine (obs/alerts.py, second-scale windows) and requires the breaker
scenario to PAGE (``breaker_open``, severity page) on the fast window
while the circuit is open and to clear after recovery:

    python scripts/chaos_run.py serve --alerts \\
        --fault serve.flush:io_error:2:5 --requests 40

Exit code 0 = the run RECOVERED (it completed, no retry ladder was
exhausted, and — serve — the steady-state stream triggered zero
recompiles) AND every required flight dump exists and names its fault
AND the incident (and, with ``--alerts``, paging) contract above held.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

NEAR, FAR = 2.0, 6.0


def parse_fault(spec: str):
    """``point:kind[:after[:times]]`` → FaultSpec kwargs."""
    parts = spec.split(":")
    if len(parts) < 2 or len(parts) > 4:
        raise argparse.ArgumentTypeError(
            f"bad fault spec {spec!r} (want point:kind[:after[:times]])"
        )
    kw = {"point": parts[0], "kind": parts[1]}
    if len(parts) > 2:
        kw["after"] = int(parts[2])
    if len(parts) > 3:
        times = int(parts[3])
        kw["times"] = None if times < 0 else times
    return kw


def _tiny_cfg(scene_root: str, workdir: str, extra=()):
    from nerf_replication_tpu.config import make_cfg

    return make_cfg(
        os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        [
            "scene", "procedural",
            "exp_name", "chaos",
            "train_dataset.data_root", scene_root,
            "test_dataset.data_root", scene_root,
            "train_dataset.H", "16", "train_dataset.W", "16",
            "test_dataset.H", "16", "test_dataset.W", "16",
            "task_arg.N_rays", "128",
            "task_arg.N_samples", "24",
            "task_arg.N_importance", "24",
            "task_arg.chunk_size", "256",
            "task_arg.precrop_iters", "0",
            "network.nerf.W", "64",
            "network.nerf.D", "3",
            "network.nerf.skips", "[1]",
            "network.xyz_encoder.freq", "6",
            "network.dir_encoder.freq", "2",
            "ep_iter", "25",
            "trained_model_dir", os.path.join(workdir, "trained"),
            "record_dir", os.path.join(workdir, "record"),
            *extra,
        ],
    )


def _scene(workdir: str) -> str:
    from nerf_replication_tpu.datasets.procedural import generate_scene

    root = os.path.join(workdir, "scene")
    if not os.path.exists(os.path.join(root, "transforms_train.json")):
        generate_scene(root, scene="procedural", H=16, W=16,
                       n_train=6, n_test=2)
    return root


def _ops_attach(record_dir: str, with_alerts: bool = False):
    """PR 16 ops wiring for one chaos run: an incident correlator that
    opens on every injected fault row (each scenario self-documents as
    ``incident_*.json``) and — under ``--alerts`` — a chaos-scaled
    burn-rate AlertEngine whose breaker_open page is asserted by
    ``check_alerts``."""
    from nerf_replication_tpu.obs import (
        AlertEngine,
        AlertOptions,
        IncidentManager,
    )
    from nerf_replication_tpu.resil.flight import add_dump_listener

    incidents = IncidentManager(record_dir, open_on_fault=True).attach()
    add_dump_listener(incidents.on_flight_dump)
    alerts = None
    if with_alerts:
        # second-scale windows so a ~10s chaos stream spans many of
        # them; clear_hold 0 lets the page resolve the moment the
        # breaker re-closes; the generous latency target keeps CPU
        # render time from paging over the breaker signal under test
        alerts = AlertEngine(AlertOptions(
            fast_short_s=1.0, fast_long_s=5.0,
            slow_short_s=2.0, slow_long_s=10.0,
            clear_hold_s=0.0,
        ), slo_target_s=30.0).attach()
        alerts.add_listener(incidents.on_alert)
    return incidents, alerts


def _ops_finish(incidents, alerts) -> dict:
    """Final alert pass + force-resolve + detach; the outcome blocks."""
    from nerf_replication_tpu.resil.flight import remove_dump_listener

    if alerts is not None:
        # the breaker re-closed during the stream's recovery tail; give
        # the engine a bounded window to observe that and clear the page
        deadline = time.monotonic() + 3.0
        while True:
            alerts.evaluate()
            if "breaker_open" not in alerts.active() \
                    or time.monotonic() >= deadline:
                break
            time.sleep(0.1)
    forced = incidents.resolve_open("chaos recovery checks passed")
    remove_dump_listener(incidents.on_flight_dump)
    incidents.detach()
    out: dict = {"incidents": {
        "n_incidents": len(incidents.incidents),
        "n_resolved": sum(1 for i in incidents.incidents
                          if i["status"] == "resolved"),
        "force_resolved": forced,
        "fault_points": sorted({p for i in incidents.incidents
                                for p in i["fault_points"]}),
        "paths": [i["path"] for i in incidents.incidents],
    }}
    if alerts is not None:
        alerts.remove_listener(incidents.on_alert)
        alerts.detach()
        out["alerts"] = {
            "transitions": [{"name": t["name"], "state": t["state"],
                             "severity": t["severity"]}
                            for t in alerts.transitions],
            "alert_seconds": {k: round(v, 3)
                              for k, v in alerts.alert_seconds.items()},
            "still_firing": alerts.active(),
        }
    return out


def run_train(args, plan) -> dict:
    """fit() on the tiny scene under the plan; survives injected faults
    the library is supposed to absorb, reports the ones it isn't."""
    from nerf_replication_tpu.resil import (
        DivergenceError,
        SimulatedKill,
        injecting,
    )
    from nerf_replication_tpu.train import fit

    cfg = _tiny_cfg(
        _scene(args.workdir), args.workdir,
        ["train.epoch", str(args.epochs),
         "save_ep", "1",
         "skip_eval", "True",
         "log_interval", "5"],
    )
    outcome = {"mode": "train", "completed": False, "died": None}
    # each injected fault must end the run with a resolved
    # incident_*.json naming it — check_incidents() asserts it
    incidents, _ = _ops_attach(os.path.join(args.workdir, "record"))
    t0 = time.perf_counter()
    with injecting(plan):
        try:
            fit(cfg)
            outcome["completed"] = True
        except SimulatedKill as k:
            outcome["died"] = f"SimulatedKill({k})"
        except DivergenceError as err:
            outcome["died"] = f"DivergenceError(step={err.step})"
    outcome["wall_s"] = round(time.perf_counter() - t0, 2)
    outcome["telemetry"] = os.path.join(str(cfg.record_dir),
                                        "telemetry.jsonl")
    outcome.update(_ops_finish(incidents, None))
    return outcome


def _build_chaos_fleet(engine, args):
    """N scenes with REAL checkpoint dirs (dummy blob + tree checksum —
    a target for truncate/io_error faults) over an in-memory loader,
    budgeted to ~half the fleet so the stream churns eviction/reload."""
    import numpy as np

    import jax

    from nerf_replication_tpu.fleet import (
        ResidencyManager,
        SceneData,
        SceneRecord,
        SceneRegistry,
    )
    from nerf_replication_tpu.resil import write_tree_checksum

    scene_ids = [f"scene{i:02d}" for i in range(args.scenes)]
    datas, records = {}, []
    for i, sid in enumerate(scene_ids):
        ckpt = os.path.join(args.workdir, "fleet", sid)
        os.makedirs(os.path.join(ckpt, "latest"), exist_ok=True)
        with open(os.path.join(ckpt, "latest", "params.bin"), "wb") as fh:
            fh.write(os.urandom(4096))
        write_tree_checksum(ckpt)
        perturbed = jax.tree.map(
            lambda a, s=1.0 + 0.01 * (i + 1): np.asarray(a) * np.float32(s),
            engine.params,
        )
        datas[sid] = SceneData(scene_id=sid, params=perturbed,
                               grid=np.asarray(engine.grid),
                               bbox=np.asarray(engine.bbox),
                               near=NEAR, far=FAR)
        records.append(SceneRecord(scene_id=sid, checkpoint=ckpt))
    one = (sum(leaf.nbytes for leaf in jax.tree.leaves(engine.params))
           + engine.grid.nbytes + engine.bbox.nbytes)
    residency = ResidencyManager(
        SceneRegistry(records), lambda rec: datas[rec.scene_id],
        budget_bytes=int(one * max(1.5, args.scenes / 2.0)),
        # no background prefetch: the fault schedule stays a pure
        # function of the (deterministic) request-loop hit order
        prefetch=False,
        retry_kw={"attempts": 3, "base_s": 0.01, "max_s": 0.05},
    )
    engine.attach_fleet(residency)
    return residency, scene_ids


def run_serve(args, plan) -> dict:
    """Engine + micro-batcher under the plan: the worker watchdog and the
    breaker must keep the stream flowing with zero steady recompiles."""
    import numpy as np

    import jax

    from nerf_replication_tpu.models import init_params_for, make_network
    from nerf_replication_tpu.obs import init_run
    from nerf_replication_tpu.resil import (
        BreakerOpenError,
        CircuitBreaker,
        injecting,
    )
    from nerf_replication_tpu.serve import (
        MicroBatcher,
        RenderEngine,
        ServeTimeoutError,
    )

    scene_root = _scene(args.workdir)
    cfg = _tiny_cfg(
        scene_root, args.workdir,
        ["task_arg.render_step_size", "0.25",
         "task_arg.max_march_samples", "16",
         "task_arg.march_chunk_size", "64",
         "serve.buckets", "[128, 256]",
         "serve.max_batch_rays", "256",
         "serve.max_delay_ms", "5.0",
         "serve.request_timeout_s", "10.0",
         "serve.shed_queue_depths", "[8, 16, 32, 64]"]
        # --alerts: a 1s probe delay so the breaker re-closes (and the
        # page clears) WITHIN the chaos stream rather than after it
        + (["resil.breaker_cooldown_s", "1.0"] if args.alerts else []),
    )
    telem = os.path.join(args.workdir, "record", "telemetry.jsonl")
    init_run(cfg, component="serve", path=telem)
    # span tracing + flight recorder: the chaos run must leave a
    # post-mortem on breaker-open / watchdog crash — check_flight() in
    # main() asserts the dump exists and names the injected fault
    from nerf_replication_tpu.obs import configure_tracing
    from nerf_replication_tpu.resil import (
        FlightRecorder,
        install_flight_recorder,
        uninstall_flight_recorder,
    )

    flight_dir = os.path.join(args.workdir, "record")
    configure_tracing(enabled=True)
    install_flight_recorder(FlightRecorder(flight_dir))
    incidents, alerts = _ops_attach(flight_dir, with_alerts=args.alerts)
    network = make_network(cfg)
    params = init_params_for(cfg)(network, jax.random.PRNGKey(0))
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)
    grid = np.zeros((16, 16, 16), bool)
    grid[4:12, 4:12, 4:12] = True
    engine = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                          grid=grid, bbox=bbox)

    # multi-tenant mode: a 'hot' tenant on a deliberately starved bucket
    # next to quiet tenants on a generous one — the flood must be
    # absorbed at ADMISSION (429 + throttle dump), never as queue
    # pressure that sheds the quiet tenants' batches
    from nerf_replication_tpu.fleet.qos import (
        QosController,
        TenantPolicy,
        TenantQuotaError,
    )

    qos = quiet_ids = None
    if args.tenants > 0:
        quiet_ids = [f"quiet{i:02d}" for i in range(max(1, args.tenants - 1))]
        qos = QosController(
            # hot's bucket is starved enough that ANY realistic loop
            # cadence floods it (the loop submits hot 3x per cycle)
            [TenantPolicy("hot", rate=2.0, burst=2.0)]
            + [TenantPolicy(q, rate=2000.0, burst=256.0)
               for q in quiet_ids],
            dump_after_denies=4,
        )
    batcher = MicroBatcher(engine, breaker=CircuitBreaker.from_cfg(cfg),
                           qos=qos)

    from nerf_replication_tpu.fleet import SceneError

    residency = scene_ids = None
    if args.scenes > 0:
        residency, scene_ids = _build_chaos_fleet(engine, args)

    rng = np.random.default_rng(args.seed)
    steady_base = engine.tracker.total_compiles()
    ok = rejected = failed = scene_failed = 0
    ok_by_scene: dict = {}
    ok_by_tenant: dict = {}
    denied_by_tenant: dict = {}
    quiet_shed = quiet_denied = 0
    t0 = time.perf_counter()
    with injecting(plan):
        for i in range(args.requests):
            n = int(rng.integers(32, 257))
            d = np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.15, (n, 3))
            rays = np.concatenate(
                [np.tile([0.0, 0.0, 4.0], (n, 1)), d], -1
            ).astype(np.float32)
            # runs of 4 same-scene requests cycling the fleet: residency
            # churn under fault, not one scene absorbing every hit
            scene = scene_ids[(i // 4) % len(scene_ids)] if scene_ids \
                else None
            # 3:1 hot:quiet mix — the hot tenant submits at loop speed
            # (far past its starved quota) while quiet tenants rotate
            tenant = None
            if qos is not None:
                tenant = ("hot" if i % 4 != 3
                          else quiet_ids[(i // 4) % len(quiet_ids)])
            try:
                out = batcher.submit(rays, NEAR, FAR, scene=scene,
                                     tenant=tenant).result(timeout=30.0)
                ok += 1
                if scene is not None:
                    ok_by_scene[scene] = ok_by_scene.get(scene, 0) + 1
                if tenant is not None:
                    ok_by_tenant[tenant] = ok_by_tenant.get(tenant, 0) + 1
                    if tenant != "hot" and out.get("tier") != "full":
                        quiet_shed += 1
            except BreakerOpenError:
                rejected += 1
                time.sleep(0.05)
            except TenantQuotaError as err:
                # admission-level throttle (the typed 429): scoped to the
                # offending tenant, never queue pressure for the others
                denied_by_tenant[err.tenant] = \
                    denied_by_tenant.get(err.tenant, 0) + 1
                if err.tenant != "hot":
                    quiet_denied += 1
            except SceneError:
                # scene-scoped failure (torn/unloadable): 503 for THAT
                # scene only — the stream itself keeps flowing
                scene_failed += 1
            except (ServeTimeoutError, TimeoutError, RuntimeError, OSError):
                # the batcher scatters the original dispatch exception onto
                # the futures: RuntimeError for a crashed worker, OSError
                # for an injected/organic I/O failure
                failed += 1
            if alerts is not None:
                # per-request evaluation: the page must fire WHILE the
                # breaker is open, not be reconstructed afterwards
                alerts.evaluate()
    wall = time.perf_counter() - t0
    health = batcher.health()
    batcher.close(drain=False)
    ops = _ops_finish(incidents, alerts)
    uninstall_flight_recorder()
    configure_tracing(enabled=False)
    out = {
        "mode": "serve",
        "completed": True,
        "died": None,
        "wall_s": round(wall, 2),
        "n_ok": ok,
        "n_rejected_503": rejected,
        "n_failed": failed,
        "worker_restarts": health["worker_restarts"],
        "breaker": health["breaker"],
        "recompiles_steady": engine.tracker.total_compiles() - steady_base,
        "telemetry": telem,
    }
    if residency is not None:
        stats = residency.stats()
        out["n_scene_failed"] = scene_failed
        out["ok_by_scene"] = ok_by_scene
        out["scenes_still_serving"] = sum(1 for v in ok_by_scene.values()
                                          if v > 0)
        out["fleet"] = {
            "n_scenes": len(scene_ids),
            "evictions": stats["evictions"],
            "cold_loads": stats["cold_loads"],
            "load_errors": stats["load_errors"],
            "overloads": stats["overloads"],
        }
    if qos is not None:
        quiet_served = sum(1 for q in quiet_ids
                           if ok_by_tenant.get(q, 0) > 0)
        out["qos"] = {
            "ok_by_tenant": ok_by_tenant,
            "denied_by_tenant": denied_by_tenant,
            "hot_denied": denied_by_tenant.get("hot", 0),
            "quiet_denied": quiet_denied,
            "quiet_shed": quiet_shed,
            "quiet_tenants": len(quiet_ids),
            "quiet_tenants_served": quiet_served,
        }
    out.update(ops)
    out["flight_dumps"] = _scan_flight_dumps(flight_dir)
    return out


def run_serve_replicas(args, plan) -> dict:
    """Kill-a-replica chaos behind the scale/ front door.

    N replicas (own micro-batchers, one shared warm engine) serve an
    open stream through the router when one replica's batcher dies
    mid-load WITHOUT its registry entry knowing (the crashed-process
    shape: state still says ready). The run recovers iff the router
    fails the next submit over to a survivor (marking the liar dead),
    the supervisor replaces it 1:1 outside any cooldown, every
    post-kill request completes within the SLO bound, drain-before-
    retire at teardown fails zero in-flight requests, and the whole
    episode triggers zero recompiles."""
    import numpy as np

    import jax

    from nerf_replication_tpu.models import init_params_for, make_network
    from nerf_replication_tpu.obs import configure_tracing, init_run
    from nerf_replication_tpu.resil import (
        FlightRecorder,
        injecting,
        install_flight_recorder,
        uninstall_flight_recorder,
    )
    from nerf_replication_tpu.scale import (
        InProcessReplica,
        NoReplicaAvailableError,
        ReplicaState,
        Router,
        ScaleOptions,
        Supervisor,
    )
    from nerf_replication_tpu.serve import (
        MicroBatcher,
        RenderEngine,
        ServeTimeoutError,
    )

    scene_root = _scene(args.workdir)
    cfg = _tiny_cfg(
        scene_root, args.workdir,
        ["task_arg.render_step_size", "0.25",
         "task_arg.max_march_samples", "16",
         "task_arg.march_chunk_size", "64",
         "serve.buckets", "[128, 256]",
         "serve.max_batch_rays", "256",
         "serve.max_delay_ms", "5.0",
         "serve.request_timeout_s", "10.0"],
    )
    telem = os.path.join(args.workdir, "record", "telemetry.jsonl")
    init_run(cfg, component="serve", path=telem)
    flight_dir = os.path.join(args.workdir, "record")
    configure_tracing(enabled=True)
    install_flight_recorder(FlightRecorder(flight_dir))
    incidents, alerts = _ops_attach(flight_dir, with_alerts=args.alerts)
    network = make_network(cfg)
    params = init_params_for(cfg)(network, jax.random.PRNGKey(0))
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)
    grid = np.zeros((16, 16, 16), bool)
    grid[4:12, 4:12, 4:12] = True
    # ONE warm engine shared by every replica: chaos prices the control
    # plane (failover/replace), not warm-start economics — serve_bench
    # --replicas owns that measurement
    engine = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                          grid=grid, bbox=bbox)
    fleet: list = []

    def spawn(i: int):
        r = InProcessReplica(f"replica{i}", engine, MicroBatcher(engine))
        fleet.append(r)
        return r

    n = max(2, args.replicas)
    router = Router(heartbeat_timeout_s=5.0)
    sup = Supervisor(router, spawn, options=ScaleOptions(
        min_replicas=n, max_replicas=n, cooldown_out_s=1e9,
        cooldown_in_s=1e9))
    sup.ensure_min()

    rng = np.random.default_rng(args.seed)
    steady_base = engine.tracker.total_compiles()
    kill_at = args.requests // 2
    killed = None
    ok = failed = shed = post_kill_failed = 0
    lats_after: list = []
    t0_run = time.perf_counter()
    with injecting(plan):
        for i in range(args.requests):
            if i == kill_at:
                victim = next(r for r in fleet
                              if r.state == ReplicaState.READY)
                # the crashed-process shape: the batcher dies (queued
                # futures fail NOW), the registry still says ready
                victim.batcher.close(drain=False)
                killed = victim.replica_id
                print(f"chaos: killed {killed} at request {i}")
            if killed and i == kill_at + 4:
                # the supervisor's periodic pass: sweep + 1:1 replace
                sup.replace_dead()
            n_rays = int(rng.integers(32, 257))
            d = np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.15, (n_rays, 3))
            rays = np.concatenate(
                [np.tile([0.0, 0.0, 4.0], (n_rays, 1)), d], -1
            ).astype(np.float32)
            t0 = time.perf_counter()
            try:
                router.submit(rays, NEAR, FAR).result(timeout=30.0)
                ok += 1
                if killed is not None:
                    lats_after.append(time.perf_counter() - t0)
            except NoReplicaAvailableError:
                shed += 1
            except (ServeTimeoutError, TimeoutError, RuntimeError, OSError):
                failed += 1
                if killed is not None:
                    post_kill_failed += 1
            if alerts is not None:
                alerts.evaluate()
    wall = time.perf_counter() - t0_run
    drain_failures = 0
    for r in fleet:
        if r.state in (ReplicaState.STARTING, ReplicaState.READY):
            drain_failures += r.drain(timeout_s=30.0)
    ops = _ops_finish(incidents, alerts)
    uninstall_flight_recorder()
    configure_tracing(enabled=False)
    p95_after = None
    if lats_after:
        lat_sorted = sorted(lats_after)
        p95_after = lat_sorted[min(len(lat_sorted) - 1,
                                   int(0.95 * len(lat_sorted)))]
    out = {
        "mode": "serve",
        "completed": True,
        "died": None,
        "wall_s": round(wall, 2),
        "n_ok": ok,
        "n_rejected_503": shed,
        "n_failed": failed,
        "worker_restarts": 0,
        "breaker": {"state": "closed"},
        "recompiles_steady": engine.tracker.total_compiles() - steady_base,
        "telemetry": telem,
        "scale": {
            "n_replicas": n,
            "killed": killed,
            "n_failovers": router.n_failovers,
            "n_dead_marked": router.n_dead_marked,
            "n_replaced": sup.n_replaced,
            "post_kill_failed": post_kill_failed,
            "post_kill_p95_ms": (None if p95_after is None
                                 else round(p95_after * 1e3, 1)),
            "drain_failures": drain_failures,
            "router": router.stats(),
        },
        "flight_dumps": _scan_flight_dumps(flight_dir),
    }
    out.update(ops)
    return out


def run_serve_processes(args, plan) -> dict:
    """Crashed-PROCESS chaos behind the placement-planned fleet.

    The launcher spawns ``--replicas`` REAL serve.py children against
    one shared artifact dir; routed traffic heats one scene until the
    placement plan replicates it ``hot_width``-wide; then a hot-scene
    child is SIGKILLed at the OS level WITHOUT its registry entry
    knowing (state still says ready — the liar). Recovery requires the
    router to fail the next render over to the surviving planned holder
    (zero post-kill failures), the supervisor's pass to bury the corpse
    and 1:1-respawn through the launcher, the replan to restore the hot
    width, drain-before-retire to fail nothing, and the children to
    report zero steady-state compiles (all-disk warm starts)."""
    import numpy as np

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.fleet import SceneStore
    from nerf_replication_tpu.obs import (
        CapacityLedger,
        configure_tracing,
        init_run,
    )
    from nerf_replication_tpu.resil import (
        FlightRecorder,
        injecting,
        install_flight_recorder,
        uninstall_flight_recorder,
    )
    from nerf_replication_tpu.scale import (
        PlacementExecutor,
        PlacementOptions,
        PlacementPlanner,
        ProcessLauncher,
        ReplicaState,
        Router,
        ScaleOptions,
        Supervisor,
    )
    from nerf_replication_tpu.serve import engine_from_cfg

    # the bench owns the fleet asset builders (child YAML + sharded
    # scene store); chaos reuses them rather than growing a drifting copy
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve_bench as sb

    scene_root = _scene(args.workdir)
    workroot = os.path.join(args.workdir, "fleet")
    os.makedirs(workroot, exist_ok=True)
    store_dir = os.path.join(workroot, "scenes")
    shim = argparse.Namespace(buckets=[256], chunk=64, max_batch_rays=512,
                              max_delay_ms=5.0, shed_depths=[8, 32, 96])
    cfg_path = sb._write_placement_cfg(shim, workroot, scene_root, store_dir)
    cfg = make_cfg(cfg_path, default_task="run")
    telem = os.path.join(args.workdir, "record", "telemetry.jsonl")
    init_run(cfg, component="serve", path=telem)
    flight_dir = os.path.join(args.workdir, "record")
    configure_tracing(enabled=True)
    install_flight_recorder(FlightRecorder(flight_dir))
    incidents, alerts = _ops_attach(flight_dir, with_alerts=args.alerts)
    scene_ids, scene_bytes = sb._build_placement_store(cfg, store_dir, 2)
    hot = scene_ids[0]
    print("chaos: pre-booting parent engine (serializes the shared "
          "artifacts the children warm from)")
    engine_from_cfg(cfg, cfg_file=cfg_path)

    n = max(2, args.replicas)
    ledger = CapacityLedger(replica="router", window_s=600.0)

    def heat_view() -> dict:
        # normalize to the peak scene so the 4:1 hot/cold request ratio —
        # not this host's absolute req/s — decides the hot/cold split
        scenes = ledger.view().get("scenes", {})
        peak = max((s.get("requests_per_s", 0.0) for s in scenes.values()),
                   default=0.0)
        if peak <= 0.0:
            return {"scenes": {}}
        return {"scenes": {
            sid: {"requests_per_s": s.get("requests_per_s", 0.0) / peak}
            for sid, s in scenes.items()}}

    popt = PlacementOptions(enabled=True, hot_width=min(2, n), max_width=n,
                            hot_rps=0.5, width_rps=1e9,
                            replan_every_s=0.0, max_moves_per_step=8)
    planner = PlacementPlanner(SceneStore(store_dir), options=popt,
                               heat_fn=heat_view,
                               scene_bytes_fn=lambda sid: scene_bytes)
    router = Router(heartbeat_timeout_s=5.0)
    router.set_planner(planner)
    launcher = ProcessLauncher(
        cfg_path,
        env={"JAX_PLATFORMS": args.backend.split(":")[0]}
        if args.backend else {},
        ready_timeout_s=600.0, healthz_ttl_s=0.2,
    )
    sup = Supervisor(router, launcher, options=ScaleOptions(
        min_replicas=n, max_replicas=n, cooldown_out_s=1e9,
        cooldown_in_s=1e9, placement=popt),
        planner=planner, placement_executor=PlacementExecutor())
    sup.ensure_min()

    rng = np.random.default_rng(args.seed)
    ok = failed = post_kill_failed = 0

    def one(sid: str, replica=None) -> bool:
        nonlocal ok, failed
        body = {"scene": sid, "theta": float(rng.uniform(0.0, 360.0)),
                "phi": -30.0, "radius": 4.0}
        try:
            if replica is None:
                router.render(body, timeout_s=30.0)
                ledger.note_request(sid, 16 * 16)
            else:
                replica.render(body, timeout_s=30.0)
            ok += 1
            return True
        # graftlint: ok(swallow: counted failure; the recovered gate reads post_kill_failed/n_failed)
        except Exception as exc:
            failed += 1
            print(f"chaos: request for {sid} failed: "
                  f"{type(exc).__name__}: {exc}")
            return False

    def holders() -> list:
        router.sweep()
        view = router.residency_view()
        return [rid for rid in sorted(view)
                if hot in view[rid]["scenes"] or hot in view[rid]["staging"]]

    def tick() -> None:
        """One supervisor pass + realize the plan's lazy prefetches by
        aiming a request at every planned-but-not-resident pair."""
        router.sweep()
        sup.step(1.0, 0.0)
        by_id = {r.replica_id: r for r in router.replicas()}
        view = router.residency_view()
        assignments = (planner.current.assignments
                       if planner.current is not None else {})
        for sid, rids in sorted(assignments.items()):
            for rid in rids:
                st = view.get(rid)
                if st is None or rid not in by_id:
                    continue
                if sid not in st["scenes"] and sid not in st["staging"]:
                    one(sid, replica=by_id[rid])

    killed = None
    t0_run = time.perf_counter()
    with injecting(plan):
        for _ in range(3):  # heat: plan + realize the hot width
            for _ in range(4):
                one(hot)
            one(scene_ids[1])
            tick()
            time.sleep(0.5)
        width_before = len(holders())
        by_id = {r.replica_id: r for r in router.replicas()}
        victim = next((by_id[rid] for rid in holders()
                       if rid in by_id
                       and by_id[rid].state == ReplicaState.READY), None)
        if victim is not None:
            # SIGKILL the OS process; the registry entry still says
            # READY — the liar. The ROUTER must discover the corpse.
            victim.proc.kill()
            victim.proc.wait(timeout=10.0)
            killed = victim.replica_id
            print(f"chaos: SIGKILLed child {killed} (holds {hot})")
            for _ in range(4):
                if not one(hot):
                    post_kill_failed += 1
            sup.replace_dead()  # bury + 1:1 respawn through the launcher
            for _ in range(3):  # replan + realize until width restores
                for _ in range(4):
                    one(hot)
                tick()
                if len(holders()) >= popt.hot_width:
                    break
                time.sleep(0.5)
        if alerts is not None:
            alerts.evaluate()
    wall = time.perf_counter() - t0_run

    child_compiles = 0
    for r in router.replicas():
        if r.accepting():
            try:
                child_compiles += int(
                    r.heartbeat().get("total_compiles", 0))
            # graftlint: ok(swallow: teardown snapshot; a dead child already failed the width gate)
            except Exception:
                pass
    final_width = len(holders())
    drain_failures = 0
    for r in list(router.replicas()):
        if r.accepting():
            drain_failures += int(router.drain(r.replica_id, timeout_s=30.0))
    launcher.shutdown()
    ops = _ops_finish(incidents, alerts)
    uninstall_flight_recorder()
    configure_tracing(enabled=False)
    pstats = planner.stats()
    out = {
        "mode": "serve",
        "completed": True,
        "died": None,
        "wall_s": round(wall, 2),
        "n_ok": ok,
        "n_rejected_503": 0,
        "n_failed": failed,
        "worker_restarts": 0,
        "breaker": {"state": "closed"},
        # children warm from the shared artifact dir: every build any of
        # them did IS a steady-state recompile for the recovered gate
        "recompiles_steady": child_compiles,
        "telemetry": telem,
        "scale": {
            "n_replicas": n,
            "killed": killed,
            "n_failovers": router.n_failovers,
            "n_dead_marked": router.n_dead_marked,
            "n_replaced": sup.n_replaced,
            "post_kill_failed": post_kill_failed,
            "post_kill_p95_ms": None,
            "drain_failures": drain_failures,
            "router": router.stats(),
        },
        "placement": {
            "plan_version": pstats["version"],
            "hot_scene": hot,
            "hot_width_target": popt.hot_width,
            "hot_width_before_kill": width_before,
            "hot_width_achieved": final_width,
            "moves_failed": pstats["n_failed_moves"],
            "children_spawned": launcher.n_spawned,
        },
        "flight_dumps": _scan_flight_dumps(flight_dir),
    }
    out.update(ops)
    return out


def _scan_flight_dumps(flight_dir: str) -> dict:
    """Validate every flight_<reason>.json the run left and extract which
    injected faults its event ring names (the post-mortem must point at
    the cause, not just exist)."""
    from nerf_replication_tpu.resil import validate_flight_dump

    dumps: dict = {}
    for path in sorted(glob.glob(os.path.join(flight_dir, "flight_*.json"))):
        name = os.path.basename(path)
        try:
            with open(path) as fh:
                payload = json.load(fh)
        except ValueError:
            dumps[name] = {"valid": False, "errors": ["unparseable JSON"]}
            continue
        errs = validate_flight_dump(payload)
        dumps[name] = {
            "valid": not errs,
            "errors": errs[:3],
            "reason": payload.get("reason"),
            "detail": payload.get("detail"),
            "n_spans": len(payload.get("spans") or ()),
            "faults_named": sorted({
                f"{e.get('point')}:{e.get('fault')}"
                for e in (payload.get("events") or ())
                if isinstance(e, dict) and e.get("fault")
            }),
        }
    return dumps


def check_flight(outcome: dict, summary: dict, plan) -> tuple[bool, list]:
    """The flight-recorder acceptance: a breaker-open or watchdog-crash
    scenario must leave a valid dump whose event ring names one of the
    injected faults. Scenarios that didn't trip either path pass
    vacuously (nothing crashed, nothing to dump)."""
    problems: list = []
    dumps = outcome.get("flight_dumps") or {}
    injected = {f"{s.point}:{s.kind}" for s in plan.specs}

    def require(name: str) -> None:
        d = dumps.get(name)
        if d is None:
            problems.append(f"{name} missing")
        elif not d.get("valid"):
            problems.append(f"{name} invalid: {d.get('errors')}")
        elif injected and not (set(d.get("faults_named") or ()) & injected):
            problems.append(
                f"{name} names {d.get('faults_named')} — none of the "
                f"injected {sorted(injected)}"
            )

    if summary["breaker_transitions"].get("open"):
        require("flight_breaker_open.json")
    if outcome.get("worker_restarts", 0) > 0:
        require("flight_watchdog_crash.json")
    # a torn/unloadable scene 503s scene-scoped AND leaves a post-mortem
    # whose event ring names the injected fault (the batcher's
    # scene_error dump)
    if outcome.get("n_scene_failed", 0) > 0 and injected:
        require("flight_scene_error.json")
    # multi-tenant: a throttled hot tenant must leave a dump NAMING the
    # tenant — the operator's first question after a 429 storm
    q = outcome.get("qos") or {}
    if q.get("hot_denied", 0) > 0:
        d = dumps.get("flight_tenant_throttled.json")
        if d is None:
            problems.append("flight_tenant_throttled.json missing")
        elif not d.get("valid"):
            problems.append(
                f"flight_tenant_throttled.json invalid: {d.get('errors')}"
            )
        elif "tenant=hot" not in (d.get("detail") or ""):
            problems.append(
                "flight_tenant_throttled.json does not name the hot "
                f"tenant (detail: {d.get('detail')!r})"
            )
    return (not problems, problems)


def check_incidents(outcome: dict, plan, args) -> tuple[bool, list]:
    """The incident acceptance: every injected-fault run must end with a
    schema-valid RESOLVED incident whose fault_points name an injected
    fault; a clean run (no faults, no deliberate tenant flood — whose
    throttle dump legitimately opens one) must leave ZERO incident
    files."""
    from nerf_replication_tpu.obs import validate_incident_dump

    problems: list = []
    info = outcome.get("incidents") or {}
    paths = info.get("paths") or []
    injected = {f"{s.point}:{s.kind}" for s in plan.specs}
    for path in paths:
        errs = validate_incident_dump(path)
        if errs:
            problems.append(f"{os.path.basename(path)} invalid: {errs[:3]}")
    if injected:
        hit = False
        for path in paths:
            try:
                with open(path) as fh:
                    inc = json.load(fh)
            except (OSError, ValueError):
                continue  # already reported invalid above
            if inc.get("status") == "resolved" and \
                    set(inc.get("fault_points") or ()) & injected:
                hit = True
        if not hit:
            problems.append(
                "no RESOLVED incident names an injected fault (injected "
                f"{sorted(injected)}, incidents named "
                f"{info.get('fault_points')})")
    elif args.tenants == 0 and paths:
        problems.append(
            f"clean run left {len(paths)} incident file(s): "
            + ", ".join(os.path.basename(p) for p in paths))
    return (not problems, problems)


def check_alerts(outcome: dict) -> tuple[bool, list]:
    """The --alerts acceptance: the breaker scenario must PAGE
    (``breaker_open`` firing at severity page) on the fast window while
    the circuit was open, and the page must clear after recovery."""
    problems: list = []
    trans = (outcome.get("alerts") or {}).get("transitions") or []
    fired = [t for t in trans
             if t["name"] == "breaker_open" and t["state"] == "firing"]
    cleared = [t for t in trans
               if t["name"] == "breaker_open" and t["state"] == "resolved"]
    if not fired:
        problems.append("breaker_open never fired")
    elif any(t["severity"] != "page" for t in fired):
        problems.append("breaker_open fired below page severity")
    if fired and not cleared:
        problems.append("breaker_open page never cleared after recovery")
    return (not problems, problems)


def summarize_telemetry(path: str) -> dict:
    """fault/retry/breaker row counts from one run's telemetry stream."""
    out = {
        "faults_injected": 0, "faults_detected": 0,
        "faults_by_point": {}, "retries": 0, "retries_exhausted": 0,
        "breaker_transitions": {}, "rows": 0,
    }
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for line in fh:
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn tail line is itself chaos-expected
            out["rows"] += 1
            kind = row.get("kind")
            if kind == "fault":
                key = "faults_injected" if row.get("injected") \
                    else "faults_detected"
                out[key] += 1
                pt = f"{row.get('point')}:{row.get('fault')}"
                out["faults_by_point"][pt] = \
                    out["faults_by_point"].get(pt, 0) + 1
            elif kind == "retry":
                if row.get("status") == "exhausted":
                    out["retries_exhausted"] += 1
                elif row.get("status") == "retry":
                    out["retries"] += 1
            elif kind == "breaker":
                st = row.get("state")
                out["breaker_transitions"][st] = \
                    out["breaker_transitions"].get(st, 0) + 1
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="deterministic chaos runner (docs/robustness.md)"
    )
    p.add_argument("mode", choices=("train", "serve"))
    p.add_argument("--fault", type=parse_fault, action="append", default=[],
                   metavar="point:kind[:after[:times]]")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--scenes", type=int, default=0,
                   help="serve mode: N > 0 runs the stream over an "
                        "N-scene fleet (fleet.load fault coverage)")
    p.add_argument("--tenants", type=int, default=0,
                   help="serve mode: N > 0 adds QoS — one flooding "
                        "'hot' tenant vs N-1 quiet ones; recovery "
                        "requires the quiet tenants un-shed and the "
                        "throttle dump naming the hot tenant")
    p.add_argument("--replicas", type=int, default=0,
                   help="serve mode: N > 1 serves through the scale/ "
                        "front door and kills one replica mid-load; "
                        "recovery requires a router failover, a 1:1 "
                        "supervisor replacement, zero post-kill "
                        "failures, and a clean drain")
    p.add_argument("--processes", action="store_true",
                   help="serve mode, with --replicas: the fleet is REAL "
                        "serve.py child processes (scale/launcher.py) "
                        "behind the placement planner; the kill is a "
                        "SIGKILL of a hot-scene child and recovery "
                        "requires the launcher respawn + plan-restored "
                        "width on top of the --replicas contract")
    p.add_argument("--alerts", action="store_true",
                   help="serve mode: run the chaos-scaled burn-rate "
                        "alert engine — the breaker scenario must PAGE "
                        "(breaker_open) on the fast window while the "
                        "circuit is open and clear after recovery")
    p.add_argument("--backend", default="cpu",
                   help="platform pin ('cpu', 'cpu:8'; '' = inherit)")
    p.add_argument("--workdir",
                   default=os.path.join(_REPO, "data", "chaos_run"))
    p.add_argument("--keep", action="store_true",
                   help="keep the workdir (default: wiped before the run)")
    args = p.parse_args(argv)

    if args.backend:
        from nerf_replication_tpu.utils.platform import (
            force_platform,
            parse_platform_pin,
        )

        force_platform(*parse_platform_pin(args.backend))

    if not args.keep and os.path.isdir(args.workdir):
        shutil.rmtree(args.workdir)
    os.makedirs(args.workdir, exist_ok=True)

    from nerf_replication_tpu.resil import FaultPlan

    plan = FaultPlan(seed=args.seed)
    for kw in args.fault:
        plan.add(**kw)
    specs = [f"{s.point}:{s.kind}(after={s.after}, times={s.times})"
             for s in plan.specs]
    print(f"chaos plan (seed {args.seed}): "
          + ("; ".join(specs) if specs else "no faults (baseline run)"))

    if args.mode == "train":
        runner = run_train
    elif args.replicas > 0 and args.processes:
        runner = run_serve_processes
    elif args.replicas > 0:
        runner = run_serve_replicas
    else:
        runner = run_serve
    outcome = runner(args, plan)
    outcome["faults_injected_by_plan"] = plan.injected()
    summary = summarize_telemetry(outcome["telemetry"])

    qos_out = outcome.get("qos") or {}
    scale_out = outcome.get("scale")
    recovered = bool(
        outcome["completed"]
        and summary["retries_exhausted"] == 0
        and outcome.get("recompiles_steady", 0) == 0
        # fleet mode: a torn scene may 503 scene-scoped, but the stream
        # only counts as recovered if other scenes actually kept serving
        and (args.scenes == 0 or outcome.get("scenes_still_serving", 0) > 0)
        # tenant mode: the hot tenant must have been throttled, every
        # quiet tenant must have kept serving, and none of them may have
        # been shed or denied — the blast radius stayed on the offender
        and (args.tenants == 0 or (
            qos_out.get("hot_denied", 0) > 0
            and qos_out.get("quiet_tenants_served", 0)
            == qos_out.get("quiet_tenants", -1)
            and qos_out.get("quiet_shed", 1) == 0
            and qos_out.get("quiet_denied", 1) == 0
        ))
        # replicas mode: the kill must have been OBSERVED and absorbed —
        # the router failed over at least once, the supervisor replaced
        # the dead replica exactly 1:1, no request after the kill was
        # lost, and drain-before-retire at teardown failed nothing
        and (scale_out is None or (
            scale_out.get("n_failovers", 0) >= 1
            and scale_out.get("n_replaced", 0) == 1
            and scale_out.get("post_kill_failed", 1) == 0
            and scale_out.get("drain_failures", 1) == 0
        ))
        # process mode: the placement plan must have put the hot scene
        # back at full width after the respawn, with zero failed moves
        and (outcome.get("placement") is None or (
            outcome["placement"]["hot_width_achieved"]
            >= outcome["placement"]["hot_width_target"]
            and outcome["placement"]["moves_failed"] == 0
        ))
    )
    flight_ok, flight_problems = check_flight(outcome, summary, plan)
    incidents_ok, incident_problems = check_incidents(outcome, plan, args)
    alerts_ok, alert_problems = ((True, []) if not args.alerts
                                 else check_alerts(outcome))
    print(json.dumps({"outcome": outcome, "telemetry_summary": summary,
                      "recovered": recovered, "flight_ok": flight_ok,
                      "flight_problems": flight_problems,
                      "incidents_ok": incidents_ok,
                      "incident_problems": incident_problems,
                      "alerts_ok": alerts_ok,
                      "alert_problems": alert_problems}, indent=2))
    print(f"chaos: {'RECOVERED' if recovered else 'UNRECOVERED'} — "
          f"{plan.injected()} injected, "
          f"{summary['retries_exhausted']} exhausted retries")
    if not flight_ok:
        print("flight recorder FAILED: " + "; ".join(flight_problems))
    if not incidents_ok:
        print("incident correlation FAILED: "
              + "; ".join(incident_problems))
    if not alerts_ok:
        print("alerting FAILED: " + "; ".join(alert_problems))
    return 0 if (recovered and flight_ok and incidents_ok
                 and alerts_ok) else 1


if __name__ == "__main__":
    raise SystemExit(main())
