"""Inspect a COLMAP sparse model: counts, cameras, track/observation stats.

The capability seat of the reference's vendored visualize_model.py
(src/utils/colmap/visualize_model.py:1-217, matplotlib 3D viewer) in the
form this headless environment can actually use: a terminal summary of
the reconstruction's health — per-camera intrinsics, observation counts,
track-length distribution, mean reprojection error, scene extent.

    python scripts/colmap_stats.py data/scene/sparse [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nerf_replication_tpu.utils.colmap import read_model  # noqa: E402


def model_stats(model_dir: str) -> dict:
    cameras, images, points = read_model(model_dir)
    stats: dict = {
        "model_dir": model_dir,
        "n_cameras": len(cameras),
        "n_images": len(images),
        "n_points3D": len(points),
        "cameras": [
            {
                "id": c.id,
                "model": c.model,
                "size": [c.width, c.height],
                "params": [float(p) for p in c.params],
            }
            for c in cameras.values()
        ],
    }
    # only TRIANGULATED observations count (point3D_id == -1 marks an
    # unmatched keypoint): this is the number that reconciles with the
    # sum of track lengths
    n_obs = [
        int(np.sum(np.asarray(im.point3D_ids) != -1))
        for im in images.values()
    ]
    if n_obs:
        stats["obs_per_image"] = {
            "mean": float(np.mean(n_obs)),
            "min": int(np.min(n_obs)),
            "max": int(np.max(n_obs)),
        }
    if points:
        tracks = np.array([len(p.image_ids) for p in points.values()])
        errors = np.array([p.error for p in points.values()])
        xyz = np.stack([p.xyz for p in points.values()])
        lo, hi = xyz.min(0), xyz.max(0)
        stats["track_length"] = {
            "mean": float(tracks.mean()),
            "median": float(np.median(tracks)),
            "max": int(tracks.max()),
        }
        stats["reprojection_error_px"] = {
            "mean": float(errors.mean()),
            "median": float(np.median(errors)),
        }
        stats["points_bbox"] = {
            "min": [float(v) for v in lo],
            "max": [float(v) for v in hi],
        }
    return stats


def main(argv=None):
    p = argparse.ArgumentParser(
        description="summarize a COLMAP sparse model")
    p.add_argument("model_dir")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (one JSON object)")
    args = p.parse_args(argv)

    s = model_stats(args.model_dir)
    if args.json:
        print(json.dumps(s))
        return

    print(f"model: {s['model_dir']}")
    print(f"  cameras: {s['n_cameras']}  images: {s['n_images']}  "
          f"points3D: {s['n_points3D']}")
    for c in s["cameras"]:
        ps = " ".join(f"{v:.6g}" for v in c["params"])
        print(f"  camera {c['id']}: {c['model']} "
              f"{c['size'][0]}x{c['size'][1]}  [{ps}]")
    if "obs_per_image" in s:
        o = s["obs_per_image"]
        print(f"  observations/image: mean {o['mean']:.1f} "
              f"(min {o['min']}, max {o['max']})")
    if "track_length" in s:
        t, e = s["track_length"], s["reprojection_error_px"]
        print(f"  track length: mean {t['mean']:.2f}  "
              f"median {t['median']:.0f}  max {t['max']}")
        print(f"  reprojection error: mean {e['mean']:.3f} px  "
              f"median {e['median']:.3f} px")
        lo, hi = s["points_bbox"]["min"], s["points_bbox"]["max"]
        print("  points bbox: "
              f"[{lo[0]:.3g}, {lo[1]:.3g}, {lo[2]:.3g}] – "
              f"[{hi[0]:.3g}, {hi[1]:.3g}, {hi[2]:.3g}]")


if __name__ == "__main__":
    main()
