"""Bench sweep: N_rays × compute dtype × remat on the single-chip train step.

Writes one JSON line per point (same schema as bench.py plus the sweep axes)
to stdout and, with --out, to a JSONL file consumed by PERF.md. Run on the
TPU; CPU smoke: BENCH_FORCE_PLATFORM=cpu with tiny axes.

    python scripts/bench_sweep.py [--rays 1024 4096 16384 65536]
        [--dtypes float32 bfloat16] [--remat false true] [--steps 30]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--rays", type=int, nargs="+",
                   default=[1024, 4096, 16384, 65536])
    p.add_argument("--dtypes", nargs="+", default=["float32", "bfloat16"])
    p.add_argument("--remat", nargs="+", default=["false", "true"])
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--out", default="")
    args = p.parse_args(argv)

    results = []
    for n_rays in args.rays:
        for dtype in args.dtypes:
            for remat in args.remat:
                env = dict(
                    os.environ,
                    BENCH_N_RAYS=str(n_rays),
                    BENCH_STEPS=str(args.steps),
                    BENCH_REMAT=remat,
                    BENCH_DTYPE=dtype,
                )
                r = subprocess.run(
                    [sys.executable, os.path.join(_REPO, "bench.py")],
                    env=env, capture_output=True, text=True, timeout=1200,
                )
                line = (r.stdout.strip().splitlines() or ["{}"])[-1]
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    rec = {"error": line or r.stderr[-200:]}
                rec.update(n_rays=n_rays, dtype=dtype, remat=remat == "true")
                results.append(rec)
                print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            for rec in results:
                f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
