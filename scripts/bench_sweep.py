"""Bench sweep: N_rays × compute dtype × remat on the single-chip train step.

Writes one JSON line per point (same schema as bench.py plus the sweep axes)
to stdout and, with --out, to a JSONL file consumed by PERF.md. Run on the
TPU; CPU smoke: BENCH_FORCE_PLATFORM=cpu with tiny axes.

    python scripts/bench_sweep.py [--rays 1024 4096 16384 65536]
        [--dtypes float32 bfloat16] [--remat false true] [--steps 30]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--rays", type=int, nargs="+",
                   default=[1024, 4096, 16384, 65536])
    p.add_argument("--dtypes", nargs="+", default=["float32", "bfloat16"])
    p.add_argument("--remat", nargs="+", default=["false", "true"])
    p.add_argument("--scan_steps", type=int, nargs="+", default=[1],
                   help="K optimizer steps per dispatch (lax.scan burst)")
    p.add_argument("--grad_accum", type=int, nargs="+", default=[1],
                   help="microbatch accumulation (kills the 16k+ hash "
                        "HBM cliff: activation memory bounded by one "
                        "microbatch — PERF.md round 4)")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--point_timeout", type=float, default=1200.0)
    p.add_argument("--config", default="lego.yaml",
                   help="config under configs/nerf/ (e.g. lego_hash.yaml)")
    p.add_argument("--out", default="")
    args = p.parse_args(argv)

    # append mode, opened lazily on first write: a sweep that dies early (or
    # wedges after one point) must never destroy a prior run's records.
    # Downstream, promote_bench_defaults keeps only the LAST record per
    # sweep point (by ts) — a re-measurement replaces its history.
    out_f = None

    def _emit(rec):
        nonlocal out_f
        if args.out:
            if out_f is None:
                out_f = open(args.out, "a")
            out_f.write(json.dumps(rec) + "\n")
            out_f.flush()

    import itertools

    for n_rays, dtype, remat, scan_k, accum in itertools.product(
        args.rays, args.dtypes, args.remat, args.scan_steps,
        args.grad_accum,
    ):
        env = dict(
            os.environ,
            BENCH_N_RAYS=str(n_rays),
            BENCH_STEPS=str(args.steps),
            BENCH_REMAT=remat,
            BENCH_DTYPE=dtype,
            BENCH_CONFIG=args.config,
            BENCH_SCAN_STEPS=str(scan_k),
            # sweep rows are per-point records; the driver-facing NGP
            # companion snapshot would freeze unrelated time-varying data
            # into every appended row
            BENCH_NO_COMPANION="1",
        )
        if accum > 1:
            env["BENCH_GRAD_ACCUM"] = str(accum)
        # the point's init budget must fail LOUDLY (JSON record with an
        # init_trail) inside point_timeout — otherwise a wedged tunnel
        # burns the full point_timeout per point with an opaque kill
        # (docs/operations.md). Leave ≥300 s of the point budget for the
        # measurement itself; an explicit env override still wins.
        env.setdefault(
            "BENCH_INIT_TOTAL_S",
            str(max(60, int(args.point_timeout) - 300)),
        )
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(_REPO, "bench.py")],
                env=env, capture_output=True, text=True,
                timeout=args.point_timeout,
            )
            line = (r.stdout.strip().splitlines() or ["{}"])[-1]
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                rec = {"error": line or r.stderr[-200:]}
        except subprocess.TimeoutExpired:
            # one stuck point (e.g. a long tunnel-recovery wait under
            # a big BENCH_INIT_RETRIES budget) must not abort the
            # sweep and lose every prior record
            rec = {"error": f"point exceeded {args.point_timeout}s"}
        rec.update(n_rays=n_rays, dtype=dtype, remat=remat == "true",
                   scan_steps=scan_k, grad_accum=accum,
                   config=args.config, ts=round(time.time(), 1))
        print(json.dumps(rec), flush=True)
        _emit(rec)  # written per point: a crash keeps prior records
    if out_f:
        out_f.close()


if __name__ == "__main__":
    main()
