#!/bin/bash
# Fetch the NeRF Blender-synthetic test set (parity with the reference's
# scripts/download_blender.sh:1-10 — same Google-Drive archive and cookie
# dance). On an air-gapped machine this fails fast; use the procedural
# generator for a synthetic stand-in scene:
#   python -c "from nerf_replication_tpu.datasets.procedural import generate_scene; \
#              generate_scene('data/nerf_synthetic', scene='lego', H=400, W=400, n_train=100, n_test=8)"
set -e
cd "$(dirname "$0")/.."
data_root="data/nerf_synthetic"
mkdir -p "$data_root"
cd "$data_root"
echo "Getting Blender dataset in $data_root"
fileid="18JxhpWD-4ZmuFKLzKlAw-w5PpzZxXOcG"
wget -q --load-cookies /tmp/cookies.txt \
  "https://docs.google.com/uc?export=download&confirm=$(wget --quiet --save-cookies /tmp/cookies.txt --keep-session-cookies --no-check-certificate "https://docs.google.com/uc?export=download&id=${fileid}" -O- | sed -rn 's/.*confirm=([0-9A-Za-z_]+).*/\1\n/p')&id=${fileid}" \
  -O out.zip || {
    echo "download failed (no network?); see the procedural fallback in this script's header" >&2
    rm -f out.zip /tmp/cookies.txt
    exit 1
  }
rm -f /tmp/cookies.txt
unzip -q out.zip
rm -f out.zip
echo "done"
