"""Validate telemetry/bench JSONL files against the versioned schema.

    python scripts/check_telemetry_schema.py data/record/**/telemetry.jsonl
    python scripts/check_telemetry_schema.py BENCH_*.jsonl PROFILE_STEP.jsonl
    python scripts/check_telemetry_schema.py          # repo-default file set

The default file set covers every committed measurement trail, including
the serving load generator's ``BENCH_SERVE.jsonl`` (family ``serve_mode``)
and its multi-scene fleet trail ``BENCH_FLEET.jsonl`` (family
``fleet_mode``), its multi-tenant QoS trail ``BENCH_QOS.jsonl`` (family
``qos_mode``), its replica scale-out trail ``BENCH_SCALE.jsonl``
(families ``scale_mode`` — one full scale-out/scale-in cycle per row —
and ``placement_mode`` — one placement-planned fleet run per row, plan
version / hot-width attainment / budget compliance / unplanned-dispatch
share; all written by scripts/serve_bench.py), the learned sampler's
``BENCH_SAMPLING.jsonl`` (family ``sampling_mode``, written by
scripts/bench_sampling.py), and the traversal ledger
``BENCH_TRAVERSAL.jsonl`` (families ``traversal_mode`` — flat /
hierarchical / fused mega-kernel arms — and ``shard_mode``, the
model-parallel serving A/B written by ``--mesh-shape``, both from
scripts/bench_traversal.py) via the ``BENCH_*.jsonl`` pattern.

Files named ``telemetry*.jsonl`` are checked row-by-row against the typed
telemetry schema (``obs/schema.py:ROW_KINDS``) — including the fleet-obs
deep checks: span rows' propagated-context fields (alnum trace/span ids;
``remote_parent`` only ever alongside a parent id), ``scale_decision``
rows' ``evidence`` block (attainment series, per-replica queue depths,
deny rate, alnum exemplar trace ids — unknown evidence keys are
errors), and the ops-intelligence rows PR 16 added (``alert`` state/
severity enums, ``incident`` lifecycle status, ``capacity_snapshot``
per-replica ledger commits, and the placement rows this PR added —
``placement_plan`` rows' ``evidence.scene_heat`` block and
``placement_move`` rows' move-kind enum, plus the concurrency-analysis
rows PR 18 added — ``lint_run`` rule-timing/new-count maps and
``lock_order`` rows, whose ``acyclic`` flag must agree with the
presence of a named ``cycle``). Every other JSONL is
checked structurally against the known bench row families — so a bench
script that drifts shape (the pre-PR-1 failure mode: three incompatible
row families grew across ten scripts) fails here instead of silently
producing a fourth. The committed ``graftlint_baseline.json`` (the static
analysis gate's accepted-findings set, docs/static_analysis.md) rides in
the default set too, validated against analysis/baseline.py's schema — a
hand-edited baseline that drops a required field fails here, not at the
next lint run. Flight-recorder dumps (``flight_<reason>.json``, written
by resil/flight.py on breaker-open / watchdog crash / SceneError /
SIGTERM) validate against ``validate_flight_dump`` when passed
explicitly; incident dumps (``incident_<id>.json``, written by
obs/incidents.py when an alert fires / a flight dump lands / a chaos
fault injects) validate against ``validate_incident_dump`` the same
way. Exit code is nonzero on any invalid row; host-only (no JAX
import).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from nerf_replication_tpu.obs.schema import (  # noqa: E402
    validate_bench_row,
    validate_row,
)


def check_baseline_file(path: str) -> list[str]:
    """Errors for a graftlint baseline JSON (whole-file, not JSONL)."""
    from nerf_replication_tpu.analysis.baseline import validate_baseline_data

    try:
        with open(path) as f:
            data = json.load(f)
    except ValueError as e:
        return [f"{path}: unparseable JSON: {e}"]
    return [f"{path}: {e}" for e in validate_baseline_data(data)]


def check_flight_file(path: str) -> list[str]:
    """Errors for a flight-recorder dump (whole-file JSON, not JSONL)."""
    from nerf_replication_tpu.resil.flight import validate_flight_dump

    try:
        with open(path) as f:
            data = json.load(f)
    except ValueError as e:
        return [f"{path}: unparseable JSON: {e}"]
    return [f"{path}: {e}" for e in validate_flight_dump(data)]


def check_incident_file(path: str) -> list[str]:
    """Errors for an incident dump (whole-file JSON, not JSONL)."""
    from nerf_replication_tpu.obs.incidents import validate_incident_dump

    return [f"{path}: {e}" for e in validate_incident_dump(path)]


def check_file(path: str, max_report: int = 5) -> list[str]:
    """Errors for one file (truncated to ``max_report`` rows' worth)."""
    if os.path.basename(path).startswith("graftlint_baseline"):
        return check_baseline_file(path)
    if os.path.basename(path).startswith("flight_"):
        return check_flight_file(path)
    if os.path.basename(path).startswith("incident_") and \
            path.endswith(".json"):
        return check_incident_file(path)
    telemetry = os.path.basename(path).startswith("telemetry")
    validate = validate_row if telemetry else validate_bench_row
    errors: list[str] = []
    bad_rows = 0
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                bad_rows += 1
                if bad_rows <= max_report:
                    errors.append(f"{path}:{i}: unparseable JSON")
                continue
            row_errors = validate(row)
            if row_errors:
                bad_rows += 1
                if bad_rows <= max_report:
                    errors.extend(
                        f"{path}:{i}: {e}" for e in row_errors
                    )
    if bad_rows > max_report:
        errors.append(f"{path}: ... and {bad_rows - max_report} more bad rows")
    return errors


def default_paths() -> list[str]:
    """The repo's committed JSONL measurement trails."""
    pats = ("BENCH_*.jsonl", "PROFILE_STEP.jsonl", "QUALITY*.jsonl",
            "SCALE_CHECK.jsonl", "graftlint_baseline.json")
    paths: list[str] = []
    for pat in pats:
        paths.extend(sorted(glob.glob(os.path.join(_REPO, pat))))
    return paths


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="JSONL schema checker")
    p.add_argument("paths", nargs="*",
                   help="jsonl files (default: the repo's bench trails)")
    args = p.parse_args(argv)
    paths = args.paths or default_paths()
    if not paths:
        print("no files to check")
        return 0
    failed = 0
    for path in paths:
        errors = check_file(path)
        if errors:
            failed += 1
            for e in errors:
                print(e)
        else:
            print(f"{path}: ok")
    if failed:
        print(f"{failed}/{len(paths)} files failed schema validation")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
