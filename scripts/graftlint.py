"""graftlint CLI: the repo's JAX-aware static-analysis gate.

    python scripts/graftlint.py                      # full default scan
    python scripts/graftlint.py nerf_replication_tpu/serve
    python scripts/graftlint.py --format json        # incl. per-rule times
    python scripts/graftlint.py --changed main       # only files in the diff
    python scripts/graftlint.py --write-baseline     # regenerate baseline
    python scripts/graftlint.py --no-baseline        # raw findings, no gate

``--changed [BASE]`` lints only the ``.py`` files ``git diff --name-only
BASE`` reports (default base ``HEAD``) — the inner-loop mode that keeps
the interprocedural concurrency pass (rules R10-R13, which build a
project-wide call graph) off the critical path of a one-file edit. The
project-wide rules still see only the changed files in this mode, so the
full scan (CI / tier-1) remains the authority on cross-module findings.

Exit code is nonzero exactly when there are NEW findings — ones absent
from the committed ``graftlint_baseline.json`` — so CI (tier-1's
tests/test_analysis.py lint gate) fails on a fresh hazard while accepted
legacy findings ride in the baseline until someone fixes them. Rule
catalog + suppression syntax: docs/static_analysis.md.

Every run appends one schema-valid ``lint_run`` telemetry row
(obs/schema.py) to ``logs/graftlint/telemetry.jsonl`` (``--telemetry`` to
redirect, ``--no-telemetry`` to skip); ``scripts/tlm_report.py``
summarizes them next to the training/serving rows. Host-only: no JAX
import anywhere on this path.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from nerf_replication_tpu.analysis import (  # noqa: E402
    BASELINE_FILENAME,
    DEFAULT_SCAN,
    diff_baseline,
    lint_paths,
    load_baseline,
    render_json,
    render_text,
    rule_counts,
    save_baseline,
)

DEFAULT_TELEMETRY = os.path.join("logs", "graftlint", "telemetry.jsonl")


def emit_lint_run(path: str, *, n_findings: int, n_new: int, n_baselined: int,
                  duration_s: float, counts: dict, n_files: int,
                  exit_code: int, baseline_path: str,
                  rule_times_s: dict | None = None,
                  new_rule_counts: dict | None = None) -> None:
    from nerf_replication_tpu.obs.emit import Emitter

    emitter = Emitter(path, chief=True)
    try:
        emitter.emit(
            "lint_run",
            n_findings=n_findings,
            n_new=n_new,
            n_baselined=n_baselined,
            duration_s=duration_s,
            rule_counts=counts,
            n_files=n_files,
            exit_code=exit_code,
            baseline_path=baseline_path,
            rule_times_s={r: round(t, 4)
                          for r, t in (rule_times_s or {}).items()},
            new_rule_counts=dict(new_rule_counts or {}),
        )
    finally:
        emitter.close()


def changed_paths(base: str, repo_root: str) -> list[str]:
    """Absolute paths of changed ``.py`` files inside the default scan
    set, per ``git diff --name-only --diff-filter=d BASE``."""
    import subprocess

    proc = subprocess.run(
        ["git", "diff", "--name-only", "--diff-filter=d", base],
        capture_output=True, text=True, cwd=repo_root, timeout=30,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"git diff --name-only {base} failed: {proc.stderr.strip()}"
        )
    scan_roots = tuple(
        p if p.endswith(".py") else p + "/" for p in DEFAULT_SCAN
    )
    out = []
    for rel in proc.stdout.splitlines():
        rel = rel.strip()
        if not rel.endswith(".py"):
            continue
        if not any(rel == r or rel.startswith(r) for r in scan_roots):
            continue
        path = os.path.join(repo_root, rel)
        if os.path.exists(path):
            out.append(path)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="JAX-aware static analysis gate (rules R1-R8; "
                    "docs/static_analysis.md)"
    )
    p.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: package + scripts + entrypoints)",
    )
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help=f"baseline file (default: <repo>/{BASELINE_FILENAME})",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline: every finding is new (and fails)",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="accept the current findings as the new baseline and exit 0",
    )
    p.add_argument(
        "--rules", default=None, metavar="R1,R2",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--telemetry", default=None, metavar="JSONL",
        help=f"lint_run telemetry sink (default: <repo>/{DEFAULT_TELEMETRY})",
    )
    p.add_argument("--no-telemetry", action="store_true")
    p.add_argument(
        "--changed", nargs="?", const="HEAD", default=None, metavar="BASE",
        help="lint only changed .py files (git diff --name-only BASE; "
             "BASE defaults to HEAD)",
    )
    args = p.parse_args(argv)

    if args.changed is not None and args.write_baseline:
        p.error("--write-baseline needs a full scan; a --changed baseline "
                "would silently drop every finding outside the diff")

    t0 = time.perf_counter()
    if args.changed is not None:
        scan = changed_paths(args.changed, _REPO)
        if not scan:
            print(f"graftlint: no changed .py files vs {args.changed}")
            return 0
    else:
        scan = args.paths or [
            os.path.join(_REPO, p) for p in DEFAULT_SCAN
        ]
    rules = tuple(r.strip() for r in args.rules.split(",")) if args.rules \
        else None
    timings: dict[str, float] = {}
    findings, errors = lint_paths(scan, repo_root=_REPO, rules=rules,
                                  timings=timings)

    baseline_path = args.baseline or os.path.join(_REPO, BASELINE_FILENAME)
    if args.write_baseline:
        save_baseline(baseline_path, findings)
        print(
            f"wrote {len(findings)} finding(s) to {baseline_path}"
        )
        return 0

    baseline: set = set()
    if not args.no_baseline and os.path.exists(baseline_path):
        baseline = load_baseline(baseline_path)
    new, accepted, n_fixed = diff_baseline(findings, baseline)
    if args.changed is not None:
        n_fixed = 0  # a partial scan not observing an entry proves nothing
    duration = time.perf_counter() - t0
    exit_code = 1 if (new or errors) else 0

    if args.format == "json":
        print(render_json(new, accepted, n_fixed, errors=errors,
                          duration_s=duration, rule_times_s=timings))
    else:
        print(render_text(new, accepted, n_fixed, errors=errors))

    if not args.no_telemetry:
        telem = args.telemetry or os.path.join(_REPO, DEFAULT_TELEMETRY)
        try:
            emit_lint_run(
                telem,
                n_findings=len(findings),
                n_new=len(new),
                n_baselined=len(accepted),
                duration_s=duration,
                counts=rule_counts(findings),
                n_files=len({f.path for f in findings}) if findings else 0,
                exit_code=exit_code,
                baseline_path=os.path.relpath(baseline_path, _REPO),
                rule_times_s=timings,
                new_rule_counts=rule_counts(new),
            )
        except OSError as e:  # telemetry must never break the gate
            print(f"warning: lint_run telemetry not written: {e}",
                  file=sys.stderr)

    return exit_code


if __name__ == "__main__":
    raise SystemExit(main())
