"""Run-report CLI over telemetry.jsonl: summarize one run, diff two.

    python scripts/tlm_report.py <run_dir | telemetry.jsonl>
    python scripts/tlm_report.py <run_a> --diff <run_b> [--gate 10]
    python scripts/tlm_report.py <run_dir> --json

Summary: p50/p95/max per-step time, steps/s, compile count (+ total
compile seconds), peak device memory / host RSS, final PSNR, and — when
the run carries resil rows — injected/detected faults, retry-ladder
outcomes, and circuit-breaker opens. Runs that traced (obs/trace.py span
rows) additionally get a per-stage latency breakdown (queue → acquire →
dispatch → device → scatter p50/p95), the queue-wait share of the
stage p95 total, and a fleet-trace block — orphan-span rate,
remote-parent resolution (Traceparent propagation health), and the
per-replica stage breakdown joined on propagated trace ids; fleet runs
add a control-plane block — per-tenant
admit/deny/shed mix, tier occupancy (HBM vs host-RAM staging), the
demote-vs-cold reload split, and publish outcomes. Runs behind the
scale-out front door (serve_bench/chaos_run ``--replicas``) add a
scale section — replica lifecycle mix, supervisor decision mix with
SLO-miss window count, and the router's failover/drain counters; runs
with a placement planner attached add a placement block — replan count,
final plan version, move mix (publish/prefetch/demote) with failures,
convergence p95, and the unplanned-dispatch share.
``--diff`` compares run A (baseline) against run B
(candidate) and flags regressions past ``--gate`` percent (step-time
p50, peak memory, queue-wait p95 share, tenant deny rate, staging
re-promotion share) or any compile-count increase / PSNR drop > 0.1 dB
/ growth in unrecovered faults (exhausted retry ladders), breaker
opens, cold scene loads, failed publishes, fine-MLP evals/ray (the
learned-sampling budget), SLO-miss windows, replica churn, drain-failed
requests, orphan-span rate, evidence-free scale actions, failed
placement moves, or unplanned-dispatch share; with
``--gate`` the exit code is nonzero when
a regression is flagged, so a bench battery can use it as its gate
against a saved baseline run (e.g. the run behind ``BASELINE.json``).

The traversal bench ledger can be fed directly (``tlm_report
BENCH_TRAVERSAL.jsonl``): its flat/hierarchical/fused arm rows summarize
to per-arm rays/s and the carved-regime fused-vs-staged speedup +
intermediate-bytes ratio, and ``--diff`` flags the fused speedup
shrinking past the gate — the fused mega-kernel's regression gate
against a committed baseline ledger.

A file holds every run ever appended to it (one ``run_meta`` row each);
the summary covers the LAST run unless ``--all-runs`` is given. Purely
host-side — no JAX import, safe to run anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def resolve_path(run: str) -> str:
    """Accept a run dir (containing telemetry.jsonl) or a jsonl path."""
    if os.path.isdir(run):
        path = os.path.join(run, "telemetry.jsonl")
        if not os.path.exists(path):
            raise SystemExit(f"no telemetry.jsonl under {run}")
        return path
    if not os.path.exists(run):
        raise SystemExit(f"no such run dir or file: {run}")
    return run


def load_rows(path: str) -> list[dict]:
    rows = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                # a torn final line (crash mid-write) is expected; a torn
                # middle line is worth a warning but not a hard failure
                print(f"warning: {path}:{i}: unparseable row (skipped)",
                      file=sys.stderr)
    return rows


def last_run(rows: list[dict]) -> list[dict]:
    """Rows of the last run segment (from the final run_meta on)."""
    start = 0
    for i, row in enumerate(rows):
        if row.get("kind") == "run_meta":
            start = i
    return rows[start:]


def _percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile (no numpy dependency on purpose)."""
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, int(round(q / 100.0 * (len(ordered) - 1)))))
    return ordered[idx]


def summarize(rows: list[dict]) -> dict:
    """The report's headline numbers for one run's rows."""
    meta = next((r for r in rows if r.get("kind") == "run_meta"), {})
    steps = [r for r in rows if r.get("kind") == "step"]
    compiles = [r for r in rows if r.get("kind") == "compile"]
    memories = [r for r in rows if r.get("kind") == "memory"]
    evals = [r for r in rows if r.get("kind") == "eval"]
    epochs = [r for r in rows if r.get("kind") == "epoch"]

    step_times = [r["step_time_s"] for r in steps if "step_time_s" in r]
    summary = {
        "run_id": meta.get("run_id", ""),
        "config_hash": meta.get("config_hash", ""),
        "platform": meta.get("platform", ""),
        "n_step_rows": len(steps),
        "last_step": max((int(r["step"]) for r in steps), default=0),
        "step_time_p50_s": _percentile(step_times, 50) if step_times else None,
        "step_time_p95_s": _percentile(step_times, 95) if step_times else None,
        "step_time_max_s": max(step_times) if step_times else None,
        "compile_count": (
            # per-name cumulative counters: the last row of each name is
            # its final count
            sum({r["name"]: int(r["n_compiles"]) for r in compiles}.values())
            if compiles else 0
        ),
        "compile_wall_s": sum(float(r.get("wall_s", 0.0)) for r in compiles),
        "peak_device_bytes": None,
        "peak_host_rss_bytes": None,
        "final_psnr": None,
    }
    if epochs:
        rates = [float(r["steps_per_sec"]) for r in epochs
                 if "steps_per_sec" in r]
        summary["steps_per_sec"] = _percentile(rates, 50) if rates else None
    elif step_times:
        summary["steps_per_sec"] = 1.0 / max(_percentile(step_times, 50), 1e-9)
    else:
        summary["steps_per_sec"] = None

    device_peaks = [
        d.get("peak_bytes_in_use")
        for r in memories for d in r.get("devices", [])
        if d.get("peak_bytes_in_use") is not None
    ]
    if device_peaks:
        summary["peak_device_bytes"] = max(device_peaks)
    rss = [r.get("host_rss_bytes") for r in memories
           if r.get("host_rss_bytes") is not None]
    if rss:
        summary["peak_host_rss_bytes"] = max(rss)

    for r in reversed(evals):
        psnr = (r.get("metrics") or {}).get("psnr")
        if psnr is not None:
            summary["final_psnr"] = float(psnr)
            break
    # warmup breakdown: where the run's compile seconds landed.
    # call_index 0 = ahead-of-time builds (compile/registry's note_compile
    # — paid off the hot path, overlapped with setup), call_index 1 =
    # inline first-call builds (the classic warmup tax), >1 = retraces
    # (steady-state shape drift). Eval-cap escalation markers
    # (cap_old/cap_new, train/ngp.py) are accounted separately — they
    # stand for a forced executable rebuild, not a build themselves.
    if compiles:
        caps = [r for r in compiles if r.get("cap_new") is not None]
        builds = [r for r in compiles if r.get("cap_new") is None]
        aot = [r for r in builds if int(r.get("call_index") or 0) == 0]
        inline = [r for r in builds if int(r.get("call_index") or 0) == 1]
        retrace = [r for r in builds if int(r.get("call_index") or 0) > 1]
        wall = lambda rs: sum(float(r.get("wall_s", 0.0)) for r in rs)  # noqa: E731
        summary["warmup_aot_builds"] = len(aot)
        summary["warmup_aot_wall_s"] = wall(aot)
        summary["warmup_inline_builds"] = len(inline)
        summary["warmup_inline_wall_s"] = wall(inline)
        summary["retrace_builds"] = len(retrace)
        summary["retrace_wall_s"] = wall(retrace)
        summary["eval_cap_escalations"] = len(caps)
        if caps:
            summary["eval_cap_final"] = caps[-1].get("cap_new")

    # dispatch/block split (medians): is the loop latency- or
    # compute-bound?
    dispatch = [r["dispatch_s"] for r in steps if r.get("dispatch_s") is not None]
    block = [r["block_s"] for r in steps if r.get("block_s") is not None]
    summary["dispatch_p50_s"] = _percentile(dispatch, 50) if dispatch else None
    summary["block_p50_s"] = _percentile(block, 50) if block else None

    # serving rows (nerf_replication_tpu/serve): request-latency tail,
    # batch occupancy, shed/timeout counts, cache hit rate — keys present
    # only when the run actually served (training runs stay unchanged)
    serve_reqs = [r for r in rows if r.get("kind") == "serve_request"]
    if serve_reqs:
        ok = [r for r in serve_reqs if r.get("status", "ok") == "ok"]
        lats = [float(r["latency_s"]) for r in ok if "latency_s" in r]
        batches = [r for r in rows if r.get("kind") == "serve_batch"]
        occ = [float(r["occupancy"]) for r in batches if "occupancy" in r]
        tiers: dict = {}
        for r in ok:
            tier = r.get("tier", "full")
            tiers[tier] = tiers.get(tier, 0) + 1
        summary["serve_requests"] = len(serve_reqs)
        summary["serve_latency_p50_s"] = _percentile(lats, 50) if lats else None
        summary["serve_latency_p95_s"] = _percentile(lats, 95) if lats else None
        summary["serve_latency_p99_s"] = _percentile(lats, 99) if lats else None
        summary["serve_batches"] = len(batches)
        summary["serve_batch_occupancy"] = (
            sum(occ) / len(occ) if occ else None
        )
        summary["serve_shed_count"] = sum(
            1 for r in rows if r.get("kind") == "serve_shed"
        )
        summary["serve_timeout_count"] = sum(
            1 for r in serve_reqs if r.get("status") == "timeout"
        )
        summary["serve_cache_hit_rate"] = (
            sum(1 for r in ok if r.get("cache_hit")) / len(ok) if ok else None
        )
        summary["serve_tiers"] = tiers

    # fleet residency rows (nerf_replication_tpu/fleet): scene
    # materializations split cold vs prefetched, eviction churn, and the
    # last resident set — keys present only when the run served a
    # multi-scene fleet (single-tenant runs stay unchanged)
    scene_loads = [r for r in rows if r.get("kind") == "scene_load"]
    scene_evicts = [r for r in rows if r.get("kind") == "scene_evict"]
    if scene_loads or scene_evicts:
        cold = sum(1 for r in scene_loads if r.get("source") == "cold")
        pre = sum(1 for r in scene_loads if r.get("source") == "prefetch")
        summary["fleet_scene_loads"] = len(scene_loads)
        summary["fleet_cold_loads"] = cold
        summary["fleet_prefetch_loads"] = pre
        summary["fleet_prefetch_share"] = (
            pre / (pre + cold) if (pre + cold) else None
        )
        summary["fleet_evictions"] = len(scene_evicts)
        # tiered ladder (fleet/ladder.py): scene_load rows whose host
        # arrays came from the staging tier vs a disk read, demotions vs
        # full drops on the eviction side, and the last-seen occupancy of
        # both tiers. The demote/cold split is the ladder's whole point:
        # a working set that cycles through HBM should re-promote from
        # host RAM (device_put only), not re-walk disk + checksums.
        staged = sum(1 for r in scene_loads if r.get("source") == "staging")
        demoted = sum(1 for r in scene_evicts
                      if r.get("reason") == "demoted")
        if staged or demoted or any("tier" in r for r in scene_evicts):
            summary["fleet_staging_loads"] = staged
            summary["fleet_demotions"] = demoted
            summary["fleet_demote_vs_cold"] = (
                staged / (staged + cold) if (staged + cold) else None
            )
            reasons: dict = {}
            for r in scene_evicts:
                k = r.get("reason", "budget")
                reasons[k] = reasons.get(k, 0) + 1
            summary["fleet_evict_reasons"] = reasons
            last_tier = next(
                (r for r in reversed(rows)
                 if r.get("kind") in ("scene_load", "scene_evict")
                 and r.get("staging") is not None),
                None,
            )
            if last_tier is not None:
                summary["fleet_tier_occupancy"] = {
                    "hbm": last_tier.get("resident"),
                    "staging": last_tier.get("staging"),
                }
        summary["fleet_scenes"] = sorted(
            {r.get("scene") for r in scene_loads if r.get("scene")}
        )
        summary["fleet_bytes_loaded"] = sum(
            int(r.get("bytes", 0)) for r in scene_loads
        )
        last = next(
            (r for r in reversed(rows)
             if r.get("kind") in ("scene_load", "scene_evict")),
            None,
        )
        summary["fleet_resident_last"] = (
            last.get("resident") if last else None
        )

    # traversal rows (renderer/packed_march.py hierarchical coarse-DDA):
    # sweep efficiency = occupied samples surviving the fine test per
    # candidate row entering the global sort — the number the mip-pyramid
    # DDA exists to raise. Keys present only when the run marched packed.
    marches = [r for r in rows if r.get("kind") == "march"]
    if marches:
        cand = sum(float(r.get("candidates_in", 0.0)) for r in marches)
        samp = sum(float(r.get("samples_out", 0.0)) for r in marches)
        c_occ = [float(r["coarse_occ"]) for r in marches
                 if r.get("coarse_occ") is not None]
        over = [float(r["overflow_frac"]) for r in marches
                if r.get("overflow_frac") is not None]
        summary["march_rows"] = len(marches)
        summary["march_candidates"] = cand
        summary["march_samples_out"] = samp
        summary["march_sweep_efficiency"] = samp / cand if cand else None
        summary["march_coarse_occ"] = (
            sum(c_occ) / len(c_occ) if c_occ else None
        )
        summary["march_overflow_max"] = max(over) if over else None
        summary["march_modes"] = sorted(
            {r.get("mode", "packed") for r in marches}
        )

    # traversal bench rows (scripts/bench_traversal.py, fed directly:
    # ``tlm_report BENCH_TRAVERSAL.jsonl``): flat / hierarchical / fused
    # arms per occupancy regime. The ledger file appends every run, so
    # the LAST row per (regime, arm) is the current measurement. The
    # headline pair is fused vs staged-hierarchical on the carved regime
    # — the fused mega-kernel's rays/s and peak-intermediate-bytes claims
    # (ops/fused_march.py, docs/traversal.md) — which ``--diff`` gates.
    trav = [r for r in rows if "traversal_mode" in r]
    if trav:
        by_arm: dict = {}
        for r in trav:
            by_arm[(r.get("regime", ""), r["traversal_mode"])] = r
        summary["traversal_arms"] = sorted(
            f"{reg}/{mode}" for reg, mode in by_arm
        )
        for (reg, mode), r in by_arm.items():
            key = f"traversal_{reg}_{mode}"
            summary[f"{key}_rays_per_s"] = r.get("rays_per_s")
            summary[f"{key}_candidates_per_ray"] = r.get(
                "candidates_per_ray"
            )
            if r.get("peak_intermediate_bytes") is not None:
                summary[f"{key}_peak_bytes"] = r["peak_intermediate_bytes"]
        fus = by_arm.get(("carved", "fused"))
        hier = by_arm.get(("carved", "hierarchical"))
        if fus and hier and hier.get("rays_per_s"):
            summary["traversal_fused_speedup_x"] = (
                fus["rays_per_s"] / hier["rays_per_s"]
            )
            if (fus.get("peak_intermediate_bytes")
                    and hier.get("peak_intermediate_bytes")):
                summary["traversal_fused_bytes_x"] = (
                    hier["peak_intermediate_bytes"]
                    / fus["peak_intermediate_bytes"]
                )

    # model-parallel serving A/B rows (bench_traversal.py --mesh-shape,
    # same ledger file): replicated vs sharded params over the same
    # devices. The last row per shard_mode is the current measurement;
    # the per-device param byte reduction is the capacity claim --diff
    # gates on (docs/scaleout.md "Model-parallel serving").
    shard = [r for r in rows if "shard_mode" in r]
    if shard:
        by_mode: dict = {}
        for r in shard:
            by_mode[r["shard_mode"]] = r
        for mode, r in by_mode.items():
            summary[f"shard_{mode}_rays_per_s"] = r.get("rays_per_s")
            summary[f"shard_{mode}_bytes_per_device"] = r.get(
                "param_bytes_per_device"
            )
            summary[f"shard_{mode}_mesh_shape"] = r.get("mesh_shape")
        sh, rep = by_mode.get("sharded"), by_mode.get("replicated")
        if sh is not None:
            red = sh.get("bytes_reduction_x")
            if (red is None and rep is not None
                    and rep.get("param_bytes_per_device")
                    and sh.get("param_bytes_per_device")):
                red = (rep["param_bytes_per_device"]
                       / sh["param_bytes_per_device"])
            summary["shard_bytes_reduction_x"] = red
            summary["shard_allclose"] = sh.get("allclose")

    # learned-sampling rows (renderer/sampling.py proposal resampler):
    # fine-MLP evaluations per ray — the budget the proposal network cuts
    # — next to the PSNR it bought. Keys present only when the run emitted
    # sample rows (validation passes / bench_sampling arms).
    samples = [r for r in rows if r.get("kind") == "sample"]
    if samples:
        last = samples[-1]
        psnrs = [float(r["psnr"]) for r in samples
                 if r.get("psnr") is not None]
        summary["sample_rows"] = len(samples)
        summary["sampling_mode"] = last.get("mode")
        summary["sampling_fine_evals_per_ray"] = last.get(
            "fine_evals_per_ray"
        )
        summary["sampling_n_proposal"] = last.get("n_proposal")
        summary["sampling_n_fine"] = last.get("n_fine")
        summary["sampling_last_psnr"] = psnrs[-1] if psnrs else None

    # QoS rows (fleet/qos.py): per-tenant admission mix and the shed/
    # error attribution — who was throttled, who was degraded, who trips
    # their own breaker. Keys present only when the run metered tenants.
    admits = [r for r in rows if r.get("kind") == "tenant_admit"]
    if admits:
        tenants: dict = {}
        for r in admits:
            t = tenants.setdefault(
                r.get("tenant", "?"), {"admit": 0, "deny": 0, "shed": 0}
            )
            t["admit" if r.get("decision") == "admit" else "deny"] += 1
        for r in rows:
            if r.get("kind") == "serve_shed" and r.get("tenant"):
                if r["tenant"] in tenants:
                    tenants[r["tenant"]]["shed"] += 1
        total_admit = sum(t["admit"] for t in tenants.values())
        total_deny = sum(t["deny"] for t in tenants.values())
        summary["qos_tenants"] = {k: tenants[k] for k in sorted(tenants)}
        summary["qos_admits"] = total_admit
        summary["qos_denies"] = total_deny
        summary["qos_deny_rate"] = (
            total_deny / (total_admit + total_deny)
            if (total_admit + total_deny) else None
        )

    # hot-update rows (fleet/publish.py): publishes by status, drain tail
    publishes = [r for r in rows if r.get("kind") == "scene_publish"]
    if publishes:
        by_status: dict = {}
        for r in publishes:
            k = r.get("status", "?")
            by_status[k] = by_status.get(k, 0) + 1
        drains = [float(r["drain_ms"]) for r in publishes
                  if r.get("status") == "ok" and r.get("drain_ms") is not None]
        summary["publishes"] = by_status
        summary["publish_drain_p95_ms"] = (
            _percentile(drains, 95) if drains else None
        )

    # resilience rows (nerf_replication_tpu/resil): injected vs detected
    # faults, the retry ladder's outcomes, breaker transitions. An
    # ``exhausted`` retry row is an UNRECOVERED fault — the count --diff
    # gates on. Keys present only when the stream carries resil rows.
    faults = [r for r in rows if r.get("kind") == "fault"]
    retries = [r for r in rows if r.get("kind") == "retry"]
    breakers = [r for r in rows if r.get("kind") == "breaker"]
    if faults or retries or breakers:
        by_point: dict = {}
        for r in faults:
            k = f"{r.get('point')}:{r.get('fault')}"
            by_point[k] = by_point.get(k, 0) + 1
        summary["faults_injected"] = sum(
            1 for r in faults if r.get("injected")
        )
        summary["faults_detected"] = sum(
            1 for r in faults if not r.get("injected")
        )
        summary["fault_points"] = by_point
        summary["retry_backoffs"] = sum(
            1 for r in retries if r.get("status") == "retry"
        )
        summary["retry_recovered"] = sum(
            1 for r in retries if r.get("status") == "ok"
        )
        summary["faults_unrecovered"] = sum(
            1 for r in retries if r.get("status") == "exhausted"
        )
        summary["breaker_opens"] = sum(
            1 for r in breakers if r.get("state") == "open"
        )
        summary["breaker_last_state"] = (
            breakers[-1].get("state") if breakers else "closed"
        )

    # request-scoped span rows (obs/trace.py): per-stage latency
    # breakdown of the serve path (queue → acquire → dispatch → device →
    # scatter) and the queue-wait share of the stage tail — keys present
    # only when the run traced (serve.py / serve_bench with tracing on)
    span_rows = [r for r in rows if r.get("kind") == "span"]
    stage_rows = [r for r in span_rows
                  if r.get("stage") and r.get("dur_s") is not None]
    if stage_rows:
        stages: dict = {}
        for r in stage_rows:
            stages.setdefault(r["stage"], []).append(float(r["dur_s"]))
        summary["span_count"] = len(span_rows)
        summary["span_stages"] = {
            st: {
                "n": len(durs),
                "p50_ms": _percentile(durs, 50) * 1e3,
                "p95_ms": _percentile(durs, 95) * 1e3,
            }
            for st, durs in sorted(stages.items())
        }
        # queue share of the summed stage p95s: how much of the tail is
        # waiting for the batcher's cut, not doing work — the scheduling
        # health number --diff gates on
        p95_total = sum(
            v["p95_ms"] for v in summary["span_stages"].values()
        )
        q = summary["span_stages"].get("queue")
        summary["serve_queue_p95_share"] = (
            q["p95_ms"] / p95_total if q and p95_total > 0 else None
        )

    # fleet-trace health (obs/trace.py Traceparent propagation): how well
    # the span tree holds together. An ORPHAN is a span whose parent id
    # appears nowhere in this stream AND that is not remote-parented —
    # remote-parented spans with an absent parent are the expected shape
    # of a single replica's file (the parent lives in the router's file;
    # trace_view --fleet resolves them across files). Keys present only
    # when the run traced.
    if span_rows:
        ids = {r.get("span_id") for r in span_rows}
        orphans = 0
        remote = 0
        remote_resolved = 0
        for r in span_rows:
            parent = r.get("parent_id")
            if r.get("remote_parent"):
                remote += 1
                if parent in ids:
                    remote_resolved += 1
                continue
            if parent is not None and parent not in ids:
                orphans += 1
        summary["trace_orphans"] = orphans
        summary["trace_orphan_rate"] = orphans / len(span_rows)
        summary["trace_remote_parented"] = remote
        summary["trace_remote_resolved"] = remote_resolved
        # per-replica stage breakdown: route.dispatch/route.submit spans
        # carry the replica they landed on; their trace ids attribute the
        # replica-side stage spans of the same request
        trace_replica: dict = {}
        for r in span_rows:
            if r.get("replica") and r.get("trace_id"):
                trace_replica.setdefault(r["trace_id"], str(r["replica"]))
        if trace_replica:
            per_replica: dict = {}
            for r in stage_rows:
                rid = r.get("replica") or trace_replica.get(r.get("trace_id"))
                if rid is None:
                    continue
                per_replica.setdefault(str(rid), []).append(
                    float(r["dur_s"]))
            summary["fleet_stage_by_replica"] = {
                rid: {
                    "n": len(durs),
                    "p50_ms": _percentile(durs, 50) * 1e3,
                    "p95_ms": _percentile(durs, 95) * 1e3,
                }
                for rid, durs in sorted(per_replica.items())
            }

    # replica scale-out rows (nerf_replication_tpu/scale): replica
    # lifecycle events, the router's failover/dead-mark counters, and
    # the supervisor's per-window decisions. ``slo_miss_windows`` counts
    # every observation window that missed the SLO (out actions plus the
    # miss-streak holds building toward one); ``replica_churn`` counts
    # lifecycle transitions (spawn/retire/dead) — the two numbers the
    # --diff gate holds. Keys present only when the stream carries
    # scale rows (serve_bench --replicas / chaos_run --replicas).
    replica_rows = [r for r in rows if r.get("kind") == "replica"]
    router_rows = [r for r in rows if r.get("kind") == "router"]
    decisions = [r for r in rows if r.get("kind") == "scale_decision"]
    if replica_rows or router_rows or decisions:
        by_event: dict = {}
        for r in replica_rows:
            k = r.get("event", "?")
            by_event[k] = by_event.get(k, 0) + 1
        summary["replica_events"] = by_event
        summary["replica_churn"] = (by_event.get("spawn", 0)
                                    + by_event.get("retire", 0)
                                    + by_event.get("dead", 0))
        summary["router_failovers"] = sum(
            1 for r in router_rows if r.get("event") == "failover"
        )
        summary["router_dead_marked"] = sum(
            1 for r in router_rows if r.get("event") == "dead"
        )
        summary["drain_failed_requests"] = sum(
            int(r.get("n_failed") or 0)
            for r in router_rows if r.get("event") == "drain"
        )
        by_action: dict = {}
        for r in decisions:
            k = r.get("action", "?")
            by_action[k] = by_action.get(k, 0) + 1
        summary["scale_decisions"] = by_action
        summary["slo_miss_windows"] = sum(
            1 for r in decisions
            if r.get("reason") in ("slo_miss", "miss_streak")
        )
        peaks = [int(r["n_replicas"]) for r in decisions
                 if r.get("n_replicas") is not None]
        summary["replicas_peak"] = max(peaks) if peaks else None
        summary["replicas_last"] = peaks[-1] if peaks else None
        # evidence linkage: every out/in should carry the metric-window
        # snapshot it acted on (attainment series, queue depths, exemplar
        # trace ids). An EVIDENCE-FREE action is a capacity change the
        # post-mortem cannot reconstruct — the count --diff gates on.
        acted = [r for r in decisions if r.get("action") in ("out", "in")]
        with_ev = [r for r in acted
                   if isinstance(r.get("evidence"), dict)
                   and r["evidence"].get("exemplar_trace_ids")]
        summary["scale_actions"] = len(acted)
        summary["scale_actions_with_evidence"] = len(with_ev)
        summary["scale_actions_evidence_free"] = len(acted) - len(with_ev)

    # placement rows (scale/placement.py): the plan lifecycle — replan
    # count and final version, applied-move mix, convergence wall-time
    # p95, failed moves (a pinned-evict refusal or a failed publish),
    # and the unplanned-dispatch share off the last plan row's router
    # counters. ``placement_failed_moves`` and
    # ``placement_unplanned_share`` are the two numbers the --diff gate
    # holds. Keys present only when the stream carries placement rows
    # (serve_bench --replicas with placement enabled).
    plan_rows = [r for r in rows if r.get("kind") == "placement_plan"]
    move_rows = [r for r in rows if r.get("kind") == "placement_move"]
    if plan_rows or move_rows:
        summary["placement_plans"] = len(plan_rows)
        versions = [int(r.get("version", 0)) for r in plan_rows]
        summary["placement_plan_version"] = max(versions) if versions else 0
        move_mix: dict = {}
        failed = 0
        for r in move_rows:
            k = r.get("move", "?")
            move_mix[k] = move_mix.get(k, 0) + 1
            if not r.get("ok"):
                failed += 1
        summary["placement_move_mix"] = move_mix
        summary["placement_failed_moves"] = failed
        conv = [float(r["convergence_s"]) for r in plan_rows
                if r.get("convergence_s") is not None]
        summary["placement_convergences"] = len(conv)
        summary["placement_convergence_p95_s"] = (
            _percentile(conv, 95) if conv else None)
        counted = [r for r in plan_rows if r.get("planned_hits") is not None
                   or r.get("unplanned") is not None]
        if counted:
            last = counted[-1]
            hits = int(last.get("planned_hits") or 0)
            unplanned = int(last.get("unplanned") or 0)
            total = hits + unplanned
            summary["placement_unplanned_share"] = (
                round(unplanned / total, 4) if total else 0.0)

    # ops-intelligence rows (obs/alerts.py / incidents.py / capacity.py):
    # alert transitions + firing minutes per alert, the incident
    # lifecycle ledger (unresolved count is a --diff gate), and the last
    # capacity_snapshot per replica. Keys present only when the run
    # alerted (serve.py with obs.alerts.enabled / serve_bench / chaos).
    alert_rows = [r for r in rows if r.get("kind") == "alert"]
    if alert_rows:
        fired: dict = {}
        fire_t: dict = {}
        seconds: dict = {}
        pages = 0
        for r in alert_rows:
            name = r.get("name", "?")
            if r.get("state") == "firing":
                fired[name] = fired.get(name, 0) + 1
                fire_t[name] = float(r.get("t", 0.0))
                if r.get("severity") == "page":
                    pages += 1
            elif r.get("state") == "resolved" and name in fire_t:
                seconds[name] = seconds.get(name, 0.0) + (
                    float(r.get("t", 0.0)) - fire_t.pop(name))
        summary["alerts_fired"] = sum(fired.values())
        summary["alert_pages"] = pages
        summary["alerts_by_name"] = {k: fired[k] for k in sorted(fired)}
        summary["alerts_unresolved"] = sorted(fire_t)
        summary["alert_minutes"] = round(
            sum(seconds.values()) / 60.0, 3)
    incident_rows = [r for r in rows if r.get("kind") == "incident"]
    if incident_rows:
        last_status: dict = {}
        triggers: dict = {}
        fault_points: set = set()
        for r in incident_rows:
            last_status[r.get("incident_id", "?")] = r.get("status", "?")
            triggers[r.get("trigger", "?")] = (
                triggers.get(r.get("trigger", "?"), 0) + 1)
            for p in r.get("fault_points") or []:
                fault_points.add(str(p))
        by_status: dict = {}
        for s in last_status.values():
            by_status[s] = by_status.get(s, 0) + 1
        summary["incidents"] = len(last_status)
        summary["incidents_by_status"] = by_status
        summary["incidents_unresolved"] = sum(
            n for s, n in by_status.items() if s != "resolved")
        summary["incident_triggers"] = triggers
        summary["incident_fault_points"] = sorted(fault_points)
    cap_rows = [r for r in rows if r.get("kind") == "capacity_snapshot"]
    if cap_rows:
        last_by_rep: dict = {}
        for r in cap_rows:
            last_by_rep[r.get("replica", "")] = r
        summary["capacity_snapshots"] = len(cap_rows)
        summary["capacity_replicas"] = {
            rep: {
                "hbm_peak_bytes": r.get("hbm_peak_bytes"),
                "staging_peak_bytes": r.get("staging_peak_bytes"),
                "requests_per_s": r.get("requests_per_s"),
                "rays_per_s": r.get("rays_per_s"),
                "cold_loads": r.get("cold_loads"),
                "repromotions": r.get("repromotions"),
            }
            for rep, r in sorted(last_by_rep.items())
        }

    # static-analysis rows (scripts/graftlint.py): the latest run's
    # new-vs-baselined split and rule mix — keys present only when the
    # stream carries lint_run rows (logs/graftlint/telemetry.jsonl)
    lints = [r for r in rows if r.get("kind") == "lint_run"]
    if lints:
        last = lints[-1]
        summary["lint_runs"] = len(lints)
        summary["lint_new"] = last.get("n_new")
        summary["lint_baselined"] = last.get("n_baselined")
        summary["lint_rule_counts"] = last.get("rule_counts") or {}
        summary["lint_duration_s"] = last.get("duration_s")
        summary["lint_new_rule_counts"] = last.get("new_rule_counts") or {}
        summary["lint_rule_times_s"] = last.get("rule_times_s") or {}
        # the interprocedural concurrency rules (R10-R13) reported apart
        # from the per-function tracing rules: a new lock-order or
        # unguarded-shared finding is a deadlock/data-race candidate,
        # not a style regression, and --diff gates on exactly those
        conc = {"lock-order", "unguarded-shared", "blocking-under-lock",
                "thread-hygiene"}
        summary["lint_concurrency_new"] = {
            r: n for r, n in summary["lint_new_rule_counts"].items()
            if r in conc
        }

    # runtime lock-order sanitizer rows (analysis/sanitizer.py)
    lock_rows = [r for r in rows if r.get("kind") == "lock_order"]
    if lock_rows:
        last = lock_rows[-1]
        summary["lock_order_runs"] = len(lock_rows)
        summary["lock_order_acyclic"] = bool(last.get("acyclic"))
        summary["lock_order_edges"] = last.get("n_edges")
        summary["lock_order_cycles"] = sum(
            1 for r in lock_rows if r.get("acyclic") is False)
    return summary


def _fmt_s(v) -> str:
    return "n/a" if v is None else f"{v * 1e3:.2f} ms"


def _fmt_bytes(v) -> str:
    if v is None:
        return "n/a"
    return f"{v / 2**20:.1f} MiB"


def print_summary(summary: dict, label: str = "") -> None:
    head = f"run {summary['run_id']}" + (f" ({label})" if label else "")
    print(head)
    print(f"  config_hash:   {summary['config_hash']}  "
          f"platform: {summary['platform']}")
    print(f"  steps:         {summary['last_step']} "
          f"({summary['n_step_rows']} step rows)")
    print(f"  step time:     p50 {_fmt_s(summary['step_time_p50_s'])}  "
          f"p95 {_fmt_s(summary['step_time_p95_s'])}  "
          f"max {_fmt_s(summary['step_time_max_s'])}")
    sps = summary.get("steps_per_sec")
    print(f"  steps/s:       {sps:.2f}" if sps is not None
          else "  steps/s:       n/a")
    print(f"  dispatch/block p50: {_fmt_s(summary['dispatch_p50_s'])} / "
          f"{_fmt_s(summary['block_p50_s'])}")
    print(f"  compiles:      {summary['compile_count']} "
          f"({summary['compile_wall_s']:.2f}s wall)")
    if summary.get("warmup_aot_builds") is not None:
        print(f"    warmup:      aot {summary['warmup_aot_builds']} "
              f"({summary['warmup_aot_wall_s']:.2f}s)  "
              f"inline {summary['warmup_inline_builds']} "
              f"({summary['warmup_inline_wall_s']:.2f}s)  "
              f"retrace {summary['retrace_builds']} "
              f"({summary['retrace_wall_s']:.2f}s)")
        if summary.get("eval_cap_escalations"):
            print(f"    eval cap:    {summary['eval_cap_escalations']} "
                  f"escalation(s) -> {summary.get('eval_cap_final')}")
    print(f"  peak memory:   device {_fmt_bytes(summary['peak_device_bytes'])}"
          f"  host rss {_fmt_bytes(summary['peak_host_rss_bytes'])}")
    psnr = summary["final_psnr"]
    print(f"  final psnr:    {psnr:.3f}" if psnr is not None
          else "  final psnr:    n/a")
    if summary.get("serve_requests"):
        print(f"  serve:         {summary['serve_requests']} requests in "
              f"{summary['serve_batches']} batches")
        print(f"    latency:     p50 {_fmt_s(summary['serve_latency_p50_s'])}"
              f"  p95 {_fmt_s(summary['serve_latency_p95_s'])}"
              f"  p99 {_fmt_s(summary['serve_latency_p99_s'])}")
        occ = summary.get("serve_batch_occupancy")
        print(f"    occupancy:   "
              + (f"{occ * 100:.1f}%" if occ is not None else "n/a")
              + f"  shed: {summary['serve_shed_count']}"
              f"  timeouts: {summary['serve_timeout_count']}")
        hit = summary.get("serve_cache_hit_rate")
        tiers = " ".join(
            f"{k}:{v}" for k, v in sorted(summary["serve_tiers"].items())
        )
        print(f"    cache hits:  "
              + (f"{hit * 100:.1f}%" if hit is not None else "n/a")
              + f"  tiers: {tiers or 'n/a'}")
    if summary.get("fleet_scene_loads") is not None:
        share = summary.get("fleet_prefetch_share")
        scenes = summary.get("fleet_scenes") or []
        print(f"  fleet:         {summary['fleet_scene_loads']} scene "
              f"load(s) over {len(scenes)} scene(s)  "
              f"({summary['fleet_cold_loads']} cold / "
              f"{summary['fleet_prefetch_loads']} prefetched"
              + (f", {share * 100:.0f}% prefetched" if share is not None
                 else "") + ")")
        print(f"    evictions:   {summary['fleet_evictions']}  "
              f"bytes loaded: {_fmt_bytes(summary['fleet_bytes_loaded'])}  "
              f"resident at end: {summary['fleet_resident_last']}")
        if summary.get("fleet_staging_loads") is not None:
            ratio = summary.get("fleet_demote_vs_cold")
            reasons = " ".join(
                f"{k}:{v}" for k, v in
                sorted((summary.get("fleet_evict_reasons") or {}).items())
            )
            occ = summary.get("fleet_tier_occupancy") or {}
            print(f"    ladder:      {summary['fleet_staging_loads']} "
                  f"staging re-promotion(s) / "
                  f"{summary['fleet_demotions']} demotion(s)"
                  + (f"  ({ratio * 100:.0f}% warm)" if ratio is not None
                     else "")
                  + (f"  evict reasons: {reasons}" if reasons else ""))
            if occ:
                print(f"    tiers at end: hbm {occ.get('hbm')}  "
                      f"staging {occ.get('staging')}")
    if summary.get("qos_tenants"):
        rate = summary.get("qos_deny_rate")
        print(f"  qos:           {summary['qos_admits']} admitted / "
              f"{summary['qos_denies']} denied"
              + (f"  ({rate * 100:.1f}% deny rate)" if rate is not None
                 else ""))
        for name, t in summary["qos_tenants"].items():
            print(f"    {name:<12} admit {t['admit']}  deny {t['deny']}  "
                  f"shed {t['shed']}")
    if summary.get("publishes"):
        mix = " ".join(
            f"{k}:{v}" for k, v in sorted(summary["publishes"].items())
        )
        drain = summary.get("publish_drain_p95_ms")
        print(f"  publishes:     {mix}"
              + (f"  drain p95 {drain:.1f} ms" if drain is not None
                 else ""))
    if summary.get("march_rows"):
        eff = summary.get("march_sweep_efficiency")
        occ = summary.get("march_coarse_occ")
        over = summary.get("march_overflow_max")
        print(f"  march:         {summary['march_rows']} row(s)  "
              f"modes: {','.join(summary['march_modes'])}")
        print(f"    sweep eff:   "
              + (f"{eff * 100:.1f}%" if eff is not None else "n/a")
              + "  coarse occ: "
              + (f"{occ * 100:.1f}%" if occ is not None else "n/a")
              + "  overflow max: "
              + (f"{over * 100:.1f}%" if over is not None else "n/a"))
    if summary.get("traversal_arms"):
        print(f"  traversal:     {', '.join(summary['traversal_arms'])}")
        spd = summary.get("traversal_fused_speedup_x")
        byt = summary.get("traversal_fused_bytes_x")
        if spd is not None:
            print(f"    fused vs staged (carved): {spd:.2f}x rays/s"
                  + (f"  {byt:.2f}x fewer intermediate bytes"
                     if byt is not None else ""))
    if summary.get("shard_sharded_bytes_per_device") is not None:
        shape = summary.get("shard_sharded_mesh_shape")
        red = summary.get("shard_bytes_reduction_x")
        ok = summary.get("shard_allclose")
        print(f"  model-parallel: mesh {shape}  "
              f"bytes/device {_fmt_bytes(summary['shard_sharded_bytes_per_device'])}"
              + (f"  reduction {red:.2f}x" if red is not None else "")
              + ("" if ok is not False else "  ALLCLOSE FAILED"))
    if summary.get("sample_rows"):
        mode = summary.get("sampling_mode") or "n/a"
        fer = summary.get("sampling_fine_evals_per_ray")
        psnr = summary.get("sampling_last_psnr")
        budgets = ""
        if mode == "proposal":
            budgets = (f"  S_p: {summary.get('sampling_n_proposal')}"
                       f"  S_f: {summary.get('sampling_n_fine')}")
        print(f"  sampling:      mode {mode}  fine evals/ray: "
              + (f"{fer:g}" if fer is not None else "n/a") + budgets
              + (f"  psnr: {psnr:.3f}" if psnr is not None else ""))
    if summary.get("fault_points") is not None:
        mix = " ".join(
            f"{k}:{v}" for k, v in sorted(summary["fault_points"].items())
        )
        print(f"  faults:        {summary['faults_injected']} injected / "
              f"{summary['faults_detected']} detected"
              + (f"  ({mix})" if mix else ""))
        print(f"    retries:     {summary['retry_backoffs']} backoffs, "
              f"{summary['retry_recovered']} recovered, "
              f"{summary['faults_unrecovered']} UNRECOVERED")
        print(f"    breaker:     {summary['breaker_opens']} open(s), "
              f"last state {summary['breaker_last_state']}")
    if summary.get("span_stages"):
        mix = "  ".join(
            f"{st} {v['p50_ms']:.2f}/{v['p95_ms']:.2f}"
            for st, v in summary["span_stages"].items()
        )
        print(f"  spans:         {summary['span_count']} row(s)  "
              f"(stage p50/p95 ms: {mix})")
        share = summary.get("serve_queue_p95_share")
        if share is not None:
            print(f"    queue share: {share * 100:.1f}% of the stage "
                  f"p95 total")
    if summary.get("trace_orphan_rate") is not None:
        print(f"  fleet trace:   orphans {summary['trace_orphans']} "
              f"({summary['trace_orphan_rate'] * 100:.1f}% of spans)  "
              f"remote parents {summary['trace_remote_parented']} "
              f"({summary['trace_remote_resolved']} resolved in-stream)")
        by_rep = summary.get("fleet_stage_by_replica") or {}
        for rid, v in by_rep.items():
            print(f"    {rid:<12} {v['n']} stage span(s)  "
                  f"p50 {v['p50_ms']:.2f} ms  p95 {v['p95_ms']:.2f} ms")
    if summary.get("replica_events") is not None or summary.get(
            "scale_decisions") is not None:
        ev_mix = " ".join(
            f"{k}:{v}"
            for k, v in sorted((summary.get("replica_events") or {}).items())
        )
        print(f"  scale-out:     churn {summary.get('replica_churn', 0)}"
              + (f"  ({ev_mix})" if ev_mix else "")
              + f"  peak replicas: "
              + str(summary.get("replicas_peak") or "n/a"))
        act_mix = " ".join(
            f"{k}:{v}"
            for k, v in sorted((summary.get("scale_decisions") or {}).items())
        )
        print(f"    decisions:   {act_mix or 'none'}"
              f"  slo-miss windows: {summary.get('slo_miss_windows', 0)}")
        if summary.get("scale_actions"):
            print(f"    evidence:    "
                  f"{summary['scale_actions_with_evidence']}/"
                  f"{summary['scale_actions']} capacity action(s) carry "
                  f"exemplar-linked evidence "
                  f"({summary['scale_actions_evidence_free']} "
                  f"evidence-free)")
        print(f"    router:      {summary.get('router_failovers', 0)} "
              f"failover(s), {summary.get('router_dead_marked', 0)} dead, "
              f"{summary.get('drain_failed_requests', 0)} drain-failed "
              f"request(s)")
    if summary.get("placement_plans") is not None:
        mix = " ".join(
            f"{k}:{v}"
            for k, v in sorted((summary.get("placement_move_mix")
                                or {}).items())
        )
        conv = summary.get("placement_convergence_p95_s")
        print(f"  placement:     {summary['placement_plans']} replan(s), "
              f"plan v{summary.get('placement_plan_version', 0)}"
              + (f"  moves {mix}" if mix else "  no moves")
              + f"  failed: {summary.get('placement_failed_moves', 0)}")
        share = summary.get("placement_unplanned_share")
        print(f"    convergence: "
              f"{summary.get('placement_convergences', 0)} time(s)"
              + (f", p95 {conv:.3f}s" if conv is not None else "")
              + (f"  unplanned-dispatch share: {share:.2%}"
                 if share is not None else ""))
    if summary.get("alerts_fired") is not None:
        mix = " ".join(
            f"{k}:{v}"
            for k, v in (summary.get("alerts_by_name") or {}).items()
        )
        unres = summary.get("alerts_unresolved") or []
        print(f"  alerts:        {summary['alerts_fired']} fired "
              f"({summary.get('alert_pages', 0)} page(s))"
              + (f"  {mix}" if mix else "")
              + f"  firing-minutes: {summary.get('alert_minutes', 0)}"
              + (f"  STILL FIRING: {','.join(unres)}" if unres else ""))
    if summary.get("incidents") is not None:
        st_mix = " ".join(
            f"{k}:{v}"
            for k, v in sorted(summary["incidents_by_status"].items())
        )
        pts = summary.get("incident_fault_points") or []
        print(f"  incidents:     {summary['incidents']} ({st_mix})  "
              f"unresolved: {summary['incidents_unresolved']}"
              + (f"  fault points: {' '.join(pts)}" if pts else ""))
    if summary.get("capacity_snapshots"):
        print(f"  capacity:      {summary['capacity_snapshots']} "
              f"snapshot(s) over "
              f"{len(summary.get('capacity_replicas') or {})} replica(s)")
        for rep, v in (summary.get("capacity_replicas") or {}).items():
            rps = v.get("requests_per_s")
            print(f"    {rep or '(local)':<12} "
                  f"hbm peak {_fmt_bytes(v.get('hbm_peak_bytes'))}  "
                  f"staging peak {_fmt_bytes(v.get('staging_peak_bytes'))}  "
                  + (f"{rps:.2f} req/s" if rps is not None else "n/a req/s")
                  + f"  cold {v.get('cold_loads', 0)}"
                  f"/repromote {v.get('repromotions', 0)}")
    if summary.get("lint_runs"):
        conc_rules = ("lock-order", "unguarded-shared",
                      "blocking-under-lock", "thread-hygiene")
        counts = summary["lint_rule_counts"] or {}
        rule_mix = " ".join(
            f"{k}:{v}" for k, v in sorted(counts.items())
            if k not in conc_rules
        )
        conc_mix = " ".join(
            f"{k}:{v}" for k, v in sorted(counts.items())
            if k in conc_rules
        )
        dur = summary.get("lint_duration_s")
        print(f"  graftlint:     {summary['lint_new']} new / "
              f"{summary['lint_baselined']} baselined "
              f"({summary['lint_runs']} run(s)"
              + (f", last {dur:.2f}s" if dur is not None else "")
              + (f"; {rule_mix}" if rule_mix else "") + ")")
        times = summary.get("lint_rule_times_s") or {}
        conc_time = sum(times.get(r, 0.0) for r in conc_rules)
        conc_new = summary.get("lint_concurrency_new") or {}
        if conc_new:  # new hazards outrank the (baselined) mix
            conc_mix = "NEW " + " ".join(
                f"{k}:{v}" for k, v in sorted(conc_new.items()))
        if conc_mix or conc_time:
            print("  graftlint-conc:"
                  + (f" {conc_mix}" if conc_mix else " clean")
                  + (f" ({conc_time:.2f}s rule time)" if conc_time else ""))
    if summary.get("lock_order_runs"):
        print(f"  lock-order:    "
              f"{'acyclic' if summary['lock_order_acyclic'] else 'CYCLE'} "
              f"({summary.get('lock_order_edges', 0)} edge(s), "
              f"{summary['lock_order_runs']} run(s), "
              f"{summary.get('lock_order_cycles', 0)} with cycles)")


def diff(base: dict, cand: dict, gate_pct: float) -> list[str]:
    """Regression flags: candidate vs baseline summaries."""
    flags = []

    def pct(a, b):
        return (b - a) / a * 100.0 if a else float("inf")

    a, b = base.get("step_time_p50_s"), cand.get("step_time_p50_s")
    if a is not None and b is not None and pct(a, b) > gate_pct:
        flags.append(
            f"step time p50 regressed {pct(a, b):+.1f}% "
            f"({_fmt_s(a)} -> {_fmt_s(b)})"
        )
    a, b = base.get("compile_count"), cand.get("compile_count")
    if a is not None and b is not None and b > a:
        flags.append(f"compile count grew {a} -> {b} (retrace storm?)")
    a, b = base.get("peak_device_bytes"), cand.get("peak_device_bytes")
    if a and b and pct(a, b) > gate_pct:
        flags.append(
            f"peak device memory grew {pct(a, b):+.1f}% "
            f"({_fmt_bytes(a)} -> {_fmt_bytes(b)})"
        )
    # model-parallel serving: the per-device param byte reduction IS the
    # capacity claim — a candidate sharding less effectively re-inflates
    # every device's resident bytes until big scenes stop fitting; a
    # sharded arm that stopped matching the reference is wrong, not slow
    a = base.get("shard_bytes_reduction_x")
    b = cand.get("shard_bytes_reduction_x")
    if a and b is not None and (a - b) / a * 100.0 > gate_pct:
        flags.append(
            f"model-parallel per-device byte reduction shrank "
            f"{a:.2f}x -> {b:.2f}x"
        )
    if cand.get("shard_allclose") is False:
        flags.append(
            "model-parallel sharded arm diverged from the single-device "
            "reference (allclose failed)"
        )
    a, b = base.get("final_psnr"), cand.get("final_psnr")
    if a is not None and b is not None and b < a - 0.1:
        flags.append(f"final psnr dropped {a:.3f} -> {b:.3f}")
    a, b = base.get("lint_new"), cand.get("lint_new")
    if a is not None and b is not None and b > a:
        flags.append(f"graftlint new findings grew {a} -> {b}")
    # new lock-order / unguarded-shared findings gate unconditionally:
    # each is a deadlock or data-race candidate, not a style drift
    for rule in ("lock-order", "unguarded-shared"):
        a = (base.get("lint_new_rule_counts") or {}).get(rule, 0)
        b_counts = cand.get("lint_new_rule_counts")
        b = (b_counts or {}).get(rule, 0)
        if b_counts is not None and b > a:
            flags.append(
                f"new {rule} findings grew {a} -> {b} "
                f"(concurrency hazard — fix, don't baseline)")
    # a runtime lock-order cycle is an unconditional flag
    if cand.get("lock_order_acyclic") is False:
        flags.append("runtime lock-order sanitizer observed a cycle "
                     "(see lock_order telemetry rows)")
    # an exhausted retry ladder means a load path gave up — a candidate
    # run growing these has faults the resil machinery no longer absorbs
    a = base.get("faults_unrecovered") or 0
    b = cand.get("faults_unrecovered")
    if b is not None and b > a:
        flags.append(f"unrecovered faults grew {a} -> {b} "
                     f"(exhausted retry ladders)")
    a = base.get("breaker_opens") or 0
    b = cand.get("breaker_opens")
    if b is not None and b > a:
        flags.append(f"circuit-breaker opens grew {a} -> {b}")
    # residency churn: a candidate run evicting or cold-missing more than
    # its baseline is thrashing the HBM budget — scenes bounce instead of
    # staying resident, and every bounce is a reload on the request path
    a = base.get("fleet_evictions") or 0
    b = cand.get("fleet_evictions")
    if b is not None and b > a:
        flags.append(f"fleet evictions grew {a} -> {b} "
                     f"(residency budget thrash)")
    a = base.get("fleet_cold_loads") or 0
    b = cand.get("fleet_cold_loads")
    if b is not None and b > a:
        flags.append(f"fleet cold scene loads grew {a} -> {b} "
                     f"(prefetch misses on the request path)")
    # a candidate denying a larger share of tenant admissions is either
    # under-provisioned quota or a fairness regression — both user-facing
    # 429s that never reach the latency histograms
    a = base.get("qos_deny_rate")
    b = cand.get("qos_deny_rate")
    if b is not None and b > (a or 0.0) + 0.02 and (
            a is None or pct(a, b) > gate_pct):
        flags.append(
            f"tenant deny rate grew {(a or 0.0) * 100:.1f}% -> "
            f"{b * 100:.1f}% of admission attempts"
        )
    # the ladder exists to turn evictions into demotions: a candidate
    # re-promoting a SMALLER share of its reloads from staging is paying
    # disk + checksum walks the baseline did not
    a = base.get("fleet_demote_vs_cold")
    b = cand.get("fleet_demote_vs_cold")
    if a and b is not None and (a - b) > 0.02 and (a - b) / a * 100.0 > gate_pct:
        flags.append(
            f"staging re-promotion share dropped {a * 100:.1f}% -> "
            f"{b * 100:.1f}% (ladder misses -> cold disk loads)"
        )
    a = (base.get("publishes") or {}).get("torn", 0) + (
        base.get("publishes") or {}).get("error", 0)
    b_pub = cand.get("publishes")
    if b_pub is not None:
        b = b_pub.get("torn", 0) + b_pub.get("error", 0)
        if b > a:
            flags.append(f"failed scene publishes grew {a} -> {b}")
    # queue-wait share of the stage p95 total growing means the candidate
    # spends more of its tail waiting in the batcher queue instead of
    # doing work — a scheduling regression even when end-to-end p95
    # hasn't moved yet. The 0.02 absolute floor keeps near-zero baselines
    # from flagging on noise.
    a = base.get("serve_queue_p95_share")
    b = cand.get("serve_queue_p95_share")
    if (a is not None and b is not None and (b - a) > 0.02
            and pct(a, b) > gate_pct):
        flags.append(
            f"queue-wait p95 share grew {a * 100:.1f}% -> {b * 100:.1f}% "
            f"of the stage tail"
        )
    # a candidate missing its SLO in more observation windows than the
    # baseline is a capacity or batching regression the supervisor had
    # to paper over with spawns; replica churn growing means the fleet
    # flapped (spawn/retire/dead cycles) where the baseline held steady —
    # each flap costs a warm-start and a drain
    a = base.get("slo_miss_windows") or 0
    b = cand.get("slo_miss_windows")
    if b is not None and b > a:
        flags.append(f"SLO-miss windows grew {a} -> {b}")
    a = base.get("replica_churn") or 0
    b = cand.get("replica_churn")
    if b is not None and b > a:
        flags.append(f"replica churn grew {a} -> {b} "
                     f"(fleet flapping: spawn/retire/dead cycles)")
    a = base.get("drain_failed_requests") or 0
    b = cand.get("drain_failed_requests")
    if b is not None and b > a:
        flags.append(f"drain-failed requests grew {a} -> {b} "
                     f"(retirement dropped in-flight work)")
    # a candidate orphaning a larger share of its spans has broken trace
    # propagation somewhere — requests whose trees no longer reconstruct.
    # The 0.02 absolute floor keeps near-zero baselines from flagging on
    # a single torn span.
    a = base.get("trace_orphan_rate")
    b = cand.get("trace_orphan_rate")
    if (b is not None and (b - (a or 0.0)) > 0.02
            and (not a or pct(a, b) > gate_pct)):
        flags.append(
            f"trace orphan-span rate grew {(a or 0.0) * 100:.1f}% -> "
            f"{b * 100:.1f}% (broken span propagation)"
        )
    # a capacity action without its evidence block is a scale decision
    # the post-mortem cannot tie to the window that caused it — any
    # growth means a supervisor ran detached from the fleet aggregator
    a = base.get("scale_actions_evidence_free") or 0
    b = cand.get("scale_actions_evidence_free")
    if b is not None and b > a:
        flags.append(f"evidence-free scale actions grew {a} -> {b} "
                     f"(decisions detached from the fleet signal)")
    # sweep efficiency DROPPING means the coarse DDA is admitting more
    # dead candidate rows into the sort per useful sample — a traversal
    # regression even when step time hasn't moved yet
    a = base.get("march_sweep_efficiency")
    b = cand.get("march_sweep_efficiency")
    if a and b is not None and (a - b) / a * 100.0 > gate_pct:
        flags.append(
            f"march sweep efficiency dropped {a * 100:.1f}% -> "
            f"{b * 100:.1f}%"
        )
    # the fused mega-kernel's advantage over the staged hierarchical arm
    # SHRINKING past the gate means the fusion stopped paying for itself
    # — a regression in the fused path even if absolute rays/s moved for
    # unrelated machine reasons (the staged arm is the same-run control)
    a = base.get("traversal_fused_speedup_x")
    b = cand.get("traversal_fused_speedup_x")
    if a and b is not None and (a - b) / a * 100.0 > gate_pct:
        flags.append(
            f"fused-vs-staged traversal speedup dropped {a:.2f}x -> "
            f"{b:.2f}x"
        )
    # fine-MLP evals/ray GROWING means the candidate spends more network
    # sweeps per ray than its baseline — the learned sampler's whole win
    # handed back (a config drift or a mode fallback, not a perf bug the
    # step-time gate would necessarily catch at small batches)
    a = base.get("sampling_fine_evals_per_ray")
    b = cand.get("sampling_fine_evals_per_ray")
    if a and b is not None and b > a:
        flags.append(f"fine-MLP evals/ray grew {a:g} -> {b:g}")
    # an incident that never reached ``resolved`` is an open question the
    # run shipped with — any growth over baseline means the candidate's
    # alerts cleared without their incidents closing (or never cleared)
    a = base.get("incidents_unresolved") or 0
    b = cand.get("incidents_unresolved")
    if b is not None and b > a:
        flags.append(f"unresolved incidents grew {a} -> {b} "
                     f"(incident lifecycle left open)")
    # a failed placement move is a plan the executor could not realize
    # (a pinned lease blocking a demote, a publish that raised) — any
    # growth means plans and fleet state are drifting apart
    a = base.get("placement_failed_moves") or 0
    b = cand.get("placement_failed_moves")
    if b is not None and b > a:
        flags.append(f"failed placement moves grew {a} -> {b} "
                     f"(plans the fleet could not realize)")
    # unplanned-dispatch share growing means the router is routing around
    # the plan — placement lagging the heat it is supposed to track. The
    # 0.02 absolute floor keeps near-zero baselines from flagging on a
    # handful of requests landing mid-replan.
    a = base.get("placement_unplanned_share")
    b = cand.get("placement_unplanned_share")
    if (b is not None and (b - (a or 0.0)) > 0.02
            and (not a or pct(a, b) > gate_pct)):
        flags.append(
            f"unplanned-dispatch share grew {(a or 0.0) * 100:.1f}% -> "
            f"{b * 100:.1f}% (router routing around the plan)"
        )
    # alert firing-minutes growing past the gate means the candidate
    # burned its error budget for longer than the baseline did — a
    # reliability regression even when throughput numbers look flat
    a = base.get("alert_minutes")
    b = cand.get("alert_minutes")
    if (b is not None and b > (a or 0.0) + 0.5
            and (not a or pct(a, b) > gate_pct)):
        flags.append(
            f"alert firing-minutes grew {a or 0.0:g} -> {b:g} "
            f"(longer error-budget burn)")
    return flags


def report(run: str, diff_run: str | None = None, gate: float | None = None,
           as_json: bool = False, all_runs: bool = False) -> int:
    rows = load_rows(resolve_path(run))
    if not all_runs:
        rows = last_run(rows)
    summary = summarize(rows)
    if diff_run is None:
        if as_json:
            print(json.dumps(summary, indent=2))
        else:
            print_summary(summary)
        return 0

    rows_b = load_rows(resolve_path(diff_run))
    if not all_runs:
        rows_b = last_run(rows_b)
    summary_b = summarize(rows_b)
    gate_pct = gate if gate is not None else 10.0
    flags = diff(summary, summary_b, gate_pct)
    if as_json:
        print(json.dumps(
            {"baseline": summary, "candidate": summary_b, "flags": flags},
            indent=2,
        ))
    else:
        print_summary(summary, label="baseline")
        print()
        print_summary(summary_b, label="candidate")
        print()
        if flags:
            for f in flags:
                print(f"REGRESSION: {f}")
        else:
            print(f"no regressions past {gate_pct:.0f}% gate")
    return 1 if flags and gate is not None else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="summarize/diff telemetry.jsonl runs"
    )
    p.add_argument("run", help="run dir or telemetry.jsonl path")
    p.add_argument("--diff", default=None, metavar="RUN_B",
                   help="second run to compare (candidate vs baseline)")
    p.add_argument("--gate", type=float, default=None, metavar="PCT",
                   help="regression threshold %%; exit 1 on a flagged "
                        "regression (default report-only at 10%%)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--format", choices=("text", "json"), default=None,
                   help="output format (json = machine-readable summary; "
                        "with --diff the payload carries the gate flags "
                        "under 'flags' — what CI consumes)")
    p.add_argument("--all-runs", action="store_true",
                   help="summarize every appended run, not just the last")
    args = p.parse_args(argv)
    as_json = args.as_json or args.format == "json"
    return report(args.run, diff_run=args.diff, gate=args.gate,
                  as_json=as_json, all_runs=args.all_runs)


if __name__ == "__main__":
    raise SystemExit(main())
