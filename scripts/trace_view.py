"""Export span rows to Chrome-trace JSON (chrome://tracing, Perfetto).

    python scripts/trace_view.py data/record/lego/telemetry.jsonl
    python scripts/trace_view.py flight_breaker_open.json --out trace.json
    python scripts/trace_view.py telemetry.jsonl --trace 00000001

Reads spans from either source — a run's ``telemetry.jsonl`` (rows with
``kind: span``) or a flight-recorder dump (its ``spans`` list) — and
writes the Chrome trace-event format: one complete ("X") event per span
placed on a per-thread track, plus thread-name metadata events, so the
queue → acquire → dispatch → device → scatter stages of each request
render as nested bars across the HTTP, batcher-worker, and prefetch
threads. ``--trace`` filters to one request's trace id.

Span ``start_s`` is on the tracer's clock (perf_counter); the export
rebases to the earliest span so timestamps start at 0 µs. Host-only
(no JAX import).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def load_spans(path: str) -> list[dict]:
    """Span rows from a telemetry JSONL or a flight_<reason>.json dump."""
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "{" and not path.endswith(".jsonl"):
            payload = json.load(f)
            if isinstance(payload, dict) and isinstance(
                    payload.get("spans"), list):
                return [s for s in payload["spans"] if isinstance(s, dict)]
            return []
        spans = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict) and row.get("kind") == "span":
                spans.append(row)
        return spans


def to_chrome(spans: list[dict]) -> dict:
    """Chrome trace-event JSON for a span list (complete events + thread
    name metadata). Nesting is positional: Chrome stacks events on the
    same tid by time containment, which parent/child spans satisfy by
    construction (a child's [start, end) sits inside its parent's)."""
    if not spans:
        return {"traceEvents": []}
    t0 = min(float(s["start_s"]) for s in spans)
    threads: dict[str, int] = {}
    events = []
    for s in spans:
        thread = str(s.get("thread", "main"))
        tid = threads.setdefault(thread, len(threads) + 1)
        args = {
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "parent_id": s.get("parent_id"),
        }
        for k in ("stage", "tier", "scene", "status", "n_rays", "joined",
                  "source", "family", "bucket"):
            if s.get(k) is not None:
                args[k] = s[k]
        events.append({
            "ph": "X",
            "name": str(s.get("name", "span")),
            "cat": str(s.get("stage") or "span"),
            "ts": (float(s["start_s"]) - t0) * 1e6,
            "dur": float(s.get("dur_s", 0.0)) * 1e6,
            "pid": 1,
            "tid": tid,
            "args": args,
        })
    for thread, tid in threads.items():
        events.append({
            "ph": "M", "name": "thread_name", "pid": 1, "tid": tid,
            "args": {"name": thread},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="span rows -> Chrome trace JSON")
    p.add_argument("path", help="telemetry.jsonl or flight_<reason>.json")
    p.add_argument("--out", default=None,
                   help="output path (default: <path stem>_trace.json)")
    p.add_argument("--trace", default=None,
                   help="only spans of this trace_id")
    args = p.parse_args(argv)

    spans = load_spans(args.path)
    if args.trace:
        spans = [s for s in spans if s.get("trace_id") == args.trace]
    if not spans:
        print(f"{args.path}: no span rows"
              + (f" for trace {args.trace}" if args.trace else ""))
        return 1
    out = args.out
    if out is None:
        stem = os.path.splitext(os.path.basename(args.path))[0]
        out = os.path.join(os.path.dirname(args.path) or ".",
                           f"{stem}_trace.json")
    doc = to_chrome(spans)
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    n_threads = len({s.get("thread", "main") for s in spans})
    n_traces = len({s.get("trace_id") for s in spans})
    print(f"{out}: {len(spans)} spans, {n_traces} traces, "
          f"{n_threads} threads")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
