"""Export span rows to Chrome-trace JSON (chrome://tracing, Perfetto).

    python scripts/trace_view.py data/record/lego/telemetry.jsonl
    python scripts/trace_view.py flight_breaker_open.json --out trace.json
    python scripts/trace_view.py telemetry.jsonl --trace 00000001
    python scripts/trace_view.py --fleet router/telemetry.jsonl \
        replica0/telemetry.jsonl replica1/telemetry.jsonl

Reads spans from either source — a run's ``telemetry.jsonl`` (rows with
``kind: span``) or a flight-recorder dump (its ``spans`` list) — and
writes the Chrome trace-event format: one complete ("X") event per span
placed on a per-thread track, plus thread-name metadata events, so the
queue → acquire → dispatch → device → scatter stages of each request
render as nested bars across the HTTP, batcher-worker, and prefetch
threads. ``--trace`` filters to one request's trace id.

``--fleet`` merges SEVERAL files — the router's telemetry plus one per
replica — into one trace with a process lane per file, joined on the
propagated trace/span ids (``obs/trace.py`` Traceparent propagation
keeps ids globally unique via per-replica prefixes). Cross-process
spans carry ``remote_parent``; the merge resolves them against the
whole file set and reports the orphan-span rate (spans whose parent id
appears in NO file) — the health number for fleet-trace propagation.

Span ``start_s`` is on each process's tracer clock (perf_counter);
every file is rebased independently to its earliest span, so lanes
align at 0 but cross-process gaps are approximate (clocks aren't
synchronized — the join is the ids, not the timestamps). Host-only
(no JAX import).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def load_spans(path: str) -> list[dict]:
    """Span rows from a telemetry JSONL or a flight_<reason>.json dump."""
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "{" and not path.endswith(".jsonl"):
            payload = json.load(f)
            if isinstance(payload, dict) and isinstance(
                    payload.get("spans"), list):
                return [s for s in payload["spans"] if isinstance(s, dict)]
            return []
        spans = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict) and row.get("kind") == "span":
                spans.append(row)
        return spans


_EVENT_ARG_KEYS = ("stage", "tier", "scene", "status", "n_rays", "joined",
                   "source", "family", "bucket", "replica", "remote_parent")


def _span_events(spans: list[dict], pid: int,
                 threads: dict[str, int]) -> list[dict]:
    """Complete ("X") events for one process lane, rebased to the lane's
    earliest span. ``threads`` maps thread name -> tid within this pid."""
    if not spans:
        return []
    t0 = min(float(s["start_s"]) for s in spans)
    events = []
    for s in spans:
        thread = str(s.get("thread", "main"))
        tid = threads.setdefault(thread, len(threads) + 1)
        args = {
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "parent_id": s.get("parent_id"),
        }
        for k in _EVENT_ARG_KEYS:
            if s.get(k) is not None:
                args[k] = s[k]
        events.append({
            "ph": "X",
            "name": str(s.get("name", "span")),
            "cat": str(s.get("stage") or "span"),
            "ts": (float(s["start_s"]) - t0) * 1e6,
            "dur": float(s.get("dur_s", 0.0)) * 1e6,
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    for thread, tid in threads.items():
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": thread},
        })
    return events


def to_chrome(spans: list[dict]) -> dict:
    """Chrome trace-event JSON for one span list (complete events +
    thread name metadata). Nesting is positional: Chrome stacks events
    on the same tid by time containment, which parent/child spans
    satisfy by construction (a child's [start, end) sits inside its
    parent's)."""
    if not spans:
        return {"traceEvents": []}
    events = _span_events(spans, pid=1, threads={})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _fleet_labels(paths: list[str]) -> list[str]:
    """One lane label per file: the file stem, disambiguated by its
    parent dir (then an index) when stems repeat — N replicas usually
    all log to ``telemetry.jsonl``."""
    stems = [os.path.splitext(os.path.basename(p))[0] for p in paths]
    labels = []
    for i, (path, stem) in enumerate(zip(paths, stems)):
        if stems.count(stem) > 1:
            parent = os.path.basename(os.path.dirname(os.path.abspath(path)))
            stem = f"{parent}/{stem}" if parent else stem
        labels.append(stem)
    seen: dict[str, int] = {}
    for i, lab in enumerate(labels):
        n = seen.get(lab, 0)
        seen[lab] = n + 1
        if n:
            labels[i] = f"{lab}#{n}"
    return labels


def merge_fleet(paths: list[str], trace: str | None = None
                ) -> tuple[dict, dict]:
    """Merge per-process telemetry files into one Chrome trace (a
    process lane per file) and compute the join stats: orphan spans
    (parent id in NO file), resolved remote parents (the cross-process
    joins propagation exists for), and duplicate span ids (a replica
    missing its id prefix). Returns (chrome_doc, stats)."""
    per_file: list[list[dict]] = []
    for path in paths:
        spans = load_spans(path)
        if trace:
            spans = [s for s in spans if s.get("trace_id") == trace]
        per_file.append(spans)
    all_ids: set[str] = set()
    dup_ids: set[str] = set()
    for spans in per_file:
        for s in spans:
            sid = s.get("span_id")
            if sid in all_ids:
                dup_ids.add(sid)
            all_ids.add(sid)
    labels = _fleet_labels(paths)
    events: list[dict] = []
    n_spans = 0
    n_orphans = 0
    n_remote = 0
    n_remote_resolved = 0
    for pid, (label, spans) in enumerate(zip(labels, per_file), start=1):
        events.extend(_span_events(spans, pid=pid, threads={}))
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
        for s in spans:
            n_spans += 1
            parent = s.get("parent_id")
            if s.get("remote_parent"):
                n_remote += 1
            if parent is None:
                continue
            if parent in all_ids:
                if s.get("remote_parent"):
                    n_remote_resolved += 1
            else:
                n_orphans += 1
    stats = {
        "files": {lab: len(spans) for lab, spans in zip(labels, per_file)},
        "spans": n_spans,
        "traces": len({s.get("trace_id")
                       for spans in per_file for s in spans}),
        "orphans": n_orphans,
        "orphan_rate": round(n_orphans / n_spans, 4) if n_spans else 0.0,
        "remote_parented": n_remote,
        "remote_resolved": n_remote_resolved,
        "duplicate_span_ids": sorted(dup_ids),
    }
    return {"traceEvents": events, "displayTimeUnit": "ms"}, stats


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="span rows -> Chrome trace JSON")
    p.add_argument("paths", nargs="+",
                   help="telemetry.jsonl / flight_<reason>.json "
                        "(several with --fleet)")
    p.add_argument("--out", default=None,
                   help="output path (default: <first path stem>_trace.json)")
    p.add_argument("--trace", default=None,
                   help="only spans of this trace_id")
    p.add_argument("--fleet", action="store_true",
                   help="merge all paths into one trace with a process "
                        "lane per file, joined on propagated ids; "
                        "reports the orphan-span rate")
    args = p.parse_args(argv)

    if len(args.paths) > 1 and not args.fleet:
        p.error("multiple paths require --fleet")

    out = args.out
    if out is None:
        stem = os.path.splitext(os.path.basename(args.paths[0]))[0]
        out = os.path.join(os.path.dirname(args.paths[0]) or ".",
                           f"{stem}_{'fleet_' if args.fleet else ''}"
                           f"trace.json")

    if args.fleet:
        doc, stats = merge_fleet(args.paths, trace=args.trace)
        if not stats["spans"]:
            print(f"{', '.join(args.paths)}: no span rows"
                  + (f" for trace {args.trace}" if args.trace else ""))
            return 1
        with open(out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        lanes = ", ".join(f"{k}: {v}" for k, v in stats["files"].items())
        print(f"{out}: {stats['spans']} spans, {stats['traces']} traces "
              f"across {len(stats['files'])} lanes ({lanes})")
        print(f"orphan spans: {stats['orphans']}/{stats['spans']} "
              f"(rate {stats['orphan_rate']}), remote parents resolved: "
              f"{stats['remote_resolved']}/{stats['remote_parented']}")
        if stats["duplicate_span_ids"]:
            print("WARNING: duplicate span ids across files (missing "
                  f"id_prefix?): {stats['duplicate_span_ids'][:8]}")
        return 0

    spans = load_spans(args.paths[0])
    if args.trace:
        spans = [s for s in spans if s.get("trace_id") == args.trace]
    if not spans:
        print(f"{args.paths[0]}: no span rows"
              + (f" for trace {args.trace}" if args.trace else ""))
        return 1
    doc = to_chrome(spans)
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    n_threads = len({s.get("thread", "main") for s in spans})
    n_traces = len({s.get("trace_id") for s in spans})
    print(f"{out}: {len(spans)} spans, {n_traces} traces, "
          f"{n_threads} threads")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
