"""Hash-encoder shootout: pure-XLA gather vs the Pallas kernel, on device.

VERDICT r1 #5's required measurement: both formulations at the lego_hash
shapes (16 levels, C=2, 2^19 tables, desired_resolution 1024), forward and
forward+backward, at NeRF-step point counts. Prints one JSON line per
(impl, mode, n_points); non-lowerable Pallas (Mosaic rejects the gather) is
caught and reported as {"lowered": false} — that outcome, recorded, is the
evidence for keeping the XLA path.

    python scripts/bench_hash.py [--points 16384 262144] [--steps 30]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--points", type=int, nargs="+", default=[16384, 262144])
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--levels", type=int, default=16)
    p.add_argument("--log2_t", type=int, default=19)
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import setup_backend

    setup_backend(args.force_platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nerf_replication_tpu.models.encoding.hashgrid import level_geometry
    from nerf_replication_tpu.models.encoding.pallas_hash import (
        make_hash_encode_fn,
    )

    # lego_hash geometry: desired_resolution 1024 from base 16 over L levels
    scale = float(2.0 ** (np.log2(1024 / 16) / (args.levels - 1)))
    static = dict(
        input_dim=3, num_levels=args.levels, per_level_scale=scale,
        base_resolution=16, log2_hashmap_size=args.log2_t,
    )
    offsets, _, _, _ = level_geometry(3, args.levels, scale, 16, args.log2_t)
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    table = jax.random.uniform(k1, (offsets[-1], 2), jnp.float32, -1e-4, 1e-4)

    def timed(fn, x, table):
        # perturb x per call: identical-argument loops on the axon tunnel
        # have produced physically impossible timings (see PERF.md round 3
        # and bench_hash_step._timed) — distinct inputs defeat the elision
        out = fn(x, table)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for i in range(args.steps):
            out = fn(x + (i * 1e-7), table)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.steps

    for n in args.points:
        x = jax.random.uniform(k2, (n, 3), jnp.float32)
        for impl in ("xla", "pallas"):
            enc = make_hash_encode_fn(**static, use_pallas=impl == "pallas")
            fwd = jax.jit(enc)

            def loss(x, t):
                return jnp.sum(enc(x, t) ** 2)

            fwdbwd = jax.jit(jax.grad(loss, argnums=1))
            for mode, fn, fargs in (
                ("fwd", fwd, (x, table)),
                ("fwd+bwd", fwdbwd, (x, table)),
            ):
                rec = {"impl": impl, "mode": mode, "n_points": n,
                       "levels": args.levels, "log2_t": args.log2_t}
                try:
                    dt = timed(fn, *fargs)
                    rec.update(
                        lowered=True,
                        ms=round(dt * 1e3, 3),
                        points_per_sec=round(n / dt, 1),
                    )
                except Exception as exc:
                    rec.update(lowered=False,
                               error=f"{type(exc).__name__}: {exc}"[:300])
                print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
