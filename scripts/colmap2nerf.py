"""Convert a COLMAP reconstruction to a NeRF ``transforms.json``.

Capability parity with the reference's vendored instant-ngp script
(scripts/colmap2nerf.py:27-440): optionally run COLMAP (feature extraction,
matching, mapping) on an image folder when the binary is present, then parse
the model — binary cameras.bin/images.bin or text cameras.txt/images.txt —
into camera intrinsics + camera-to-world poses in the NeRF convention,
recentre/rescale the scene, and write transforms.json with per-frame
sharpness scores.

Written from the COLMAP model format specs (qw qx qy qz tx ty tz are
world→camera; binary is little-endian structs with NUL-terminated names);
not a copy of the vendored script (ref read_write_model.py:503 is the
capability being matched).

    python scripts/colmap2nerf.py --images data/scene/images \
        [--run_colmap] [--text data/scene/colmap_text] \
        [--aabb_scale 4] [--out data/scene/transforms.json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import shutil
import subprocess
import sys


sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nerf_replication_tpu.utils import colmap as _cm  # noqa: E402


def _cam_dict(cam) -> dict:
    return {
        "model": cam.model,
        "width": cam.width,
        "height": cam.height,
        "params": [float(p) for p in cam.params],
    }


def _image_tuples(images: dict) -> list:
    return [
        (im.name, im.camera_id, [float(v) for v in im.qvec],
         [float(v) for v in im.tvec])
        for im in images.values()
    ]


qvec2rotmat = _cm.qvec2rotmat


def parse_cameras_txt(path):
    """camera_id → dict(model, width, height, params)."""
    return {
        cid: _cam_dict(cam)
        for cid, cam in _cm.read_cameras_txt(path).items()
    }


def parse_images_txt(path):
    """[(image_name, camera_id, qvec, tvec)] — the converter's pose
    surface; the full reader (incl. the empty-observation-line pairing
    discipline) lives in utils/colmap.read_images_txt."""
    return _image_tuples(_cm.read_images_txt(path, skip_points2D=True))


def parse_cameras_bin(path):
    """camera_id → dict(model, width, height, params), from cameras.bin."""
    return {
        cid: _cam_dict(cam)
        for cid, cam in _cm.read_cameras_bin(path).items()
    }


def parse_images_bin(path):
    """[(image_name, camera_id, qvec, tvec)], from images.bin."""
    return _image_tuples(_cm.read_images_bin(path, skip_points2D=True))


def parse_model(model_dir):
    """(cameras, images) from a COLMAP model dir, binary or text.

    Prefers cameras.bin/images.bin (COLMAP's default export — no
    `colmap model_converter` round-trip needed), falls back to
    cameras.txt/images.txt.
    """
    bin_path = os.path.join(model_dir, "cameras.bin")
    if os.path.exists(bin_path):
        return (
            parse_cameras_bin(bin_path),
            parse_images_bin(os.path.join(model_dir, "images.bin")),
        )
    return (
        parse_cameras_txt(os.path.join(model_dir, "cameras.txt")),
        parse_images_txt(os.path.join(model_dir, "images.txt")),
    )


def intrinsics(cam):
    """(fl_x, fl_y, cx, cy, distortion dict) from a COLMAP camera."""
    p = cam["params"]
    model = cam["model"]
    dist = {"k1": 0.0, "k2": 0.0, "p1": 0.0, "p2": 0.0}
    if model == "SIMPLE_PINHOLE":
        fl_x = fl_y = p[0]
        cx, cy = p[1], p[2]
    elif model == "PINHOLE":
        fl_x, fl_y, cx, cy = p[0], p[1], p[2], p[3]
    elif model == "SIMPLE_RADIAL":
        fl_x = fl_y = p[0]
        cx, cy = p[1], p[2]
        dist["k1"] = p[3]
    elif model == "RADIAL":
        fl_x = fl_y = p[0]
        cx, cy = p[1], p[2]
        dist["k1"], dist["k2"] = p[3], p[4]
    elif model == "OPENCV":
        fl_x, fl_y, cx, cy = p[0], p[1], p[2], p[3]
        dist["k1"], dist["k2"], dist["p1"], dist["p2"] = p[4], p[5], p[6], p[7]
    else:
        raise ValueError(f"unsupported COLMAP camera model {model}")
    return fl_x, fl_y, cx, cy, dist


def sharpness(path) -> float:
    """Variance-of-Laplacian focus score (higher = sharper)."""
    try:
        import cv2

        img = cv2.imread(path, cv2.IMREAD_GRAYSCALE)
        if img is None:
            return 0.0
        return float(cv2.Laplacian(img, cv2.CV_64F).var())
    except Exception:
        return 0.0


def world_to_camera_to_c2w(qvec, tvec):
    """COLMAP stores world→camera; invert and flip into the NeRF/Blender
    convention (x right, y up, camera looks down -z)."""
    import numpy as np

    R = np.asarray(qvec2rotmat(qvec))
    t = np.asarray(tvec).reshape(3, 1)
    w2c = np.concatenate([np.concatenate([R, t], 1), [[0, 0, 0, 1]]], 0)
    c2w = np.linalg.inv(w2c)
    # COLMAP camera: x right, y DOWN, z forward → negate y and z columns
    c2w[0:3, 1] *= -1
    c2w[0:3, 2] *= -1
    return c2w


def recenter_and_scale(c2ws, target_radius: float = 4.0):
    """Translate the camera centroid to the origin and scale the average
    camera distance to target_radius (the Blender-synthetic shell)."""
    import numpy as np

    centers = np.stack([m[:3, 3] for m in c2ws], 0)
    centroid = centers.mean(0)
    for m in c2ws:
        m[:3, 3] -= centroid
    dist = np.mean(np.linalg.norm(centers - centroid, axis=-1))
    if dist > 1e-6:
        s = target_radius / dist
        for m in c2ws:
            m[:3, 3] *= s
    return c2ws


def run_ffmpeg(video_in: str, images_dir: str, fps: float,
               time_slice: str = ""):
    """Extract frames from a video into ``images_dir`` (parity: reference
    colmap2nerf.py:57-120's ffmpeg mode — fps sampling + optional t1,t2
    time slice); requires `ffmpeg` on PATH."""
    if shutil.which("ffmpeg") is None:
        raise SystemExit("ffmpeg binary not found on PATH (drop --video_in)")
    os.makedirs(images_dir, exist_ok=True)
    filters = [f"fps={fps}"]
    cmd = ["ffmpeg", "-y", "-i", video_in]
    if time_slice:
        t1, t2 = (float(t) for t in time_slice.split(","))
        cmd += ["-ss", str(t1), "-to", str(t2)]
    cmd += ["-vf", ",".join(filters), "-qscale:v", "2",
            os.path.join(images_dir, "%04d.jpg")]
    subprocess.run(cmd, check=True)
    return images_dir


def run_colmap(images_dir: str, workspace: str):
    """Drive the COLMAP binary (feature extraction → matching → mapping →
    text export); requires `colmap` on PATH."""
    if shutil.which("colmap") is None:
        raise SystemExit("colmap binary not found on PATH (drop --run_colmap)")
    db = os.path.join(workspace, "database.db")
    sparse = os.path.join(workspace, "sparse")
    text = os.path.join(workspace, "text")
    os.makedirs(sparse, exist_ok=True)
    os.makedirs(text, exist_ok=True)
    steps = [
        ["colmap", "feature_extractor", "--database_path", db,
         "--image_path", images_dir, "--ImageReader.camera_model", "OPENCV",
         "--ImageReader.single_camera", "1"],
        ["colmap", "exhaustive_matcher", "--database_path", db],
        ["colmap", "mapper", "--database_path", db, "--image_path", images_dir,
         "--output_path", sparse],
        ["colmap", "model_converter", "--input_path",
         os.path.join(sparse, "0"), "--output_path", text,
         "--output_type", "TXT"],
    ]
    for cmd in steps:
        subprocess.run(cmd, check=True)
    return text


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--images", required=True, help="image folder")
    parser.add_argument("--text", "--model", dest="text", default=None,
                        help="COLMAP model dir — binary (cameras.bin/"
                             "images.bin) or text (cameras.txt/images.txt)")
    parser.add_argument("--run_colmap", action="store_true")
    parser.add_argument("--video_in", default="",
                        help="extract frames from this video into --images "
                             "first (implies a capture workflow: follow "
                             "with --run_colmap)")
    parser.add_argument("--video_fps", type=float, default=2.0)
    parser.add_argument("--time_slice", default="",
                        help="'t1,t2' seconds window of the video to use")
    parser.add_argument("--aabb_scale", type=int, default=4)
    parser.add_argument("--out", default="transforms.json")
    args = parser.parse_args(argv)

    if args.video_in:
        run_ffmpeg(args.video_in, args.images, args.video_fps,
                   args.time_slice)

    text = args.text
    if args.run_colmap:
        text = run_colmap(args.images, os.path.dirname(args.out) or ".")
    if text is None:
        raise SystemExit("need --text (or --run_colmap)")

    cams, images = parse_model(text)
    if not images:
        raise SystemExit("no registered images in the COLMAP model")

    cam = cams[images[0][1]]
    fl_x, fl_y, cx, cy, dist = intrinsics(cam)
    W, H = cam["width"], cam["height"]

    c2ws = [world_to_camera_to_c2w(q, t) for _, _, q, t in images]
    c2ws = recenter_and_scale(c2ws)

    frames = []
    for (name, _, _, _), c2w in zip(images, c2ws):
        rel = os.path.join(os.path.basename(args.images.rstrip("/")), name)
        frames.append(
            {
                "file_path": rel,
                "sharpness": sharpness(os.path.join(args.images, name)),
                "transform_matrix": [[float(v) for v in row] for row in c2w],
            }
        )
    frames.sort(key=lambda f: f["file_path"])

    out = {
        "camera_angle_x": 2.0 * math.atan(0.5 * W / fl_x),
        "camera_angle_y": 2.0 * math.atan(0.5 * H / fl_y),
        "fl_x": fl_x, "fl_y": fl_y, "cx": cx, "cy": cy,
        "w": W, "h": H,
        **dist,
        "aabb_scale": args.aabb_scale,
        "frames": frames,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {args.out} with {len(frames)} frames")


if __name__ == "__main__":
    sys.exit(main())
