"""Learned-sampling benchmark: coarse+fine baseline vs proposal resampler.

Trains both arms from scratch on the procedural scene with matched step
budgets, then reports per arm:

  - ``fine_evals_per_ray``: fine-MLP evaluations per ray at eval time
    (the quantity the proposal resampler exists to cut),
  - ``psnr``: reconstruction quality on the held-out test rays,
  - ``rays_per_s``: deterministic eval-path throughput.

Timing runs K carry-dependent iterations inside ONE jitted fori_loop
(the elision-immune pattern from bench_traversal.py): each iteration
perturbs the ray origins by ``sum * 1e-12`` so XLA cannot hoist or
elide repeated renders, and compile time is excluded by a warmup call.

Rows append to BENCH_SAMPLING.jsonl (family ``sampling_mode``,
obs/schema.py) — the committed trail `tlm_report --diff` gates on:
a candidate whose fine-evals/ray grows past the baseline's fails CI.

    JAX_PLATFORMS=cpu python scripts/bench_sampling.py --steps 400
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from nerf_replication_tpu.utils.platform import (  # noqa: E402
    enable_compilation_cache,
    setup_backend,
)

# Matched tiny budgets (the test suite's procedural-scene schema): the
# baseline spends N_samples + N_importance = 48 fine-MLP evals per ray;
# the proposal arm resamples n_fine = 24 from a 24-sample proposal
# histogram — a 2x cut at parity PSNR (n_fine 16 gives 3x but lands
# ~0.4 dB under the baseline at this training budget).
_COMMON = [
    "scene", "procedural",
    "train_dataset.H", "16", "train_dataset.W", "16",
    "test_dataset.H", "16", "test_dataset.W", "16",
    "task_arg.N_rays", "256",
    "task_arg.N_samples", "24",
    "task_arg.N_importance", "24",
    "task_arg.chunk_size", "256",
    "task_arg.precrop_iters", "0",
    "network.nerf.W", "64",
    "network.nerf.D", "3",
    "network.nerf.skips", "[1]",
    "network.xyz_encoder.freq", "6",
    "network.dir_encoder.freq", "2",
    "ep_iter", "1000000",
]

ARMS = {
    "coarse_fine": [],
    "proposal": [
        "sampling.mode", "proposal",
        "sampling.n_proposal", "24",
        "sampling.n_fine", "24",
    ],
}


def make_arm_cfg(mode: str, scene_root: str, steps: int):
    from nerf_replication_tpu.config import make_cfg

    extra = list(ARMS[mode])
    if mode == "proposal":
        # anneal over the first half of training, sharp for the rest
        extra += ["sampling.anneal_iters", str(max(1, steps // 2))]
    return make_cfg(
        os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        [
            *_COMMON,
            "train_dataset.data_root", scene_root,
            "test_dataset.data_root", scene_root,
            *extra,
        ],
    )


def run_arm(mode: str, scene_root: str, steps: int, iters: int,
            time_rays: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nerf_replication_tpu.datasets.blender import Dataset
    from nerf_replication_tpu.models import make_network
    from nerf_replication_tpu.renderer.volume import render_rays
    from nerf_replication_tpu.train import (
        Trainer,
        make_loss,
        make_train_state,
    )

    cfg = make_arm_cfg(mode, scene_root, steps)
    net = make_network(cfg)
    loss = make_loss(cfg, net)
    trainer = Trainer(cfg, net, loss)
    state, _ = make_train_state(cfg, net, jax.random.PRNGKey(0))

    ds = Dataset(data_root=scene_root, scene="procedural", split="train",
                 H=16, W=16)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    base_key = jax.random.PRNGKey(1)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, stats = trainer.step(state, bank[0], bank[1], base_key)
    train_psnr = float(stats["psnr"])
    train_s = time.perf_counter() - t0
    params = {"params": state.params}

    # held-out quality: deterministic chunked eval over every test ray
    renderer = loss.renderer
    test_ds = Dataset(data_root=scene_root, scene="procedural", split="test",
                      H=16, W=16)
    test_rays, test_rgbs = (jnp.asarray(a) for a in test_ds.ray_bank())
    out = renderer.render_chunked(
        params,
        {"rays": test_rays, "near": trainer.near, "far": trainer.far},
    )
    pred = out["rgb_map_f"].reshape(-1, 3)[: test_rgbs.shape[0]]
    mse = float(jnp.mean((pred - test_rgbs) ** 2))
    psnr = -10.0 * float(np.log10(max(mse, 1e-12)))

    # eval-path throughput: K chained renders inside one jit
    options = renderer.eval_options
    near, far = trainer.near, trainer.far
    reps = -(-time_rays // int(bank[0].shape[0]))
    r0 = jnp.tile(bank[0], (reps, 1))[:time_rays]

    @jax.jit
    def timed(params, rays0):
        def apply_fn(pts, vd, model):
            return net.apply(params, pts, vd, model=model)

        def body(i, carry):
            s, rays = carry
            o = render_rays(apply_fn, rays, near, far, None, options)
            s = s + jnp.mean(o["rgb_map_f"])
            return s, rays0.at[0, 0].add(s * 1e-12)

        return jax.lax.fori_loop(0, iters, body, (0.0, r0))[0]

    timed(params, r0).block_until_ready()  # compile excluded
    t0 = time.perf_counter()
    timed(params, r0).block_until_ready()
    dt = time.perf_counter() - t0
    rays_per_s = time_rays * iters / dt

    ss = renderer.sampling_stats()
    return {
        "sampling_mode": mode,
        "fine_evals_per_ray": int(options.fine_evals_per_ray),
        "rays_per_s": rays_per_s,
        "psnr": psnr,
        "train_psnr": train_psnr,
        "train_steps": steps,
        "train_s": train_s,
        "n_proposal": ss["n_proposal"],
        "n_fine": ss["n_fine"],
        "timed_rays": time_rays,
        "timed_iters": iters,
        "platform": jax.default_backend(),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default=os.path.join(_REPO,
                                                 "BENCH_SAMPLING.jsonl"))
    p.add_argument("--steps", type=int, default=400,
                   help="train steps per arm (matched)")
    p.add_argument("--iters", type=int, default=4,
                   help="chained renders inside the timing loop")
    p.add_argument("--time_rays", type=int, default=1024,
                   help="rays per timed render")
    p.add_argument("--scene_dir", default="",
                   help="reuse an existing procedural scene dir")
    p.add_argument("--force_platform", default="",
                   help="cpu|tpu|gpu (default: auto)")
    args = p.parse_args(argv)

    setup_backend(args.force_platform)
    enable_compilation_cache()

    from nerf_replication_tpu.datasets.procedural import generate_scene

    scene_root = args.scene_dir
    tmp = None
    if not scene_root:
        tmp = tempfile.TemporaryDirectory(prefix="bench_sampling_")
        scene_root = tmp.name
        generate_scene(scene_root, scene="procedural", H=16, W=16,
                       n_train=6, n_test=2)

    rows = []
    for mode in ("coarse_fine", "proposal"):
        print(f"[bench_sampling] arm={mode} steps={args.steps} ...")
        row = run_arm(mode, scene_root, args.steps, args.iters,
                      args.time_rays)
        rows.append(row)
        print(f"  fine_evals_per_ray={row['fine_evals_per_ray']} "
              f"psnr={row['psnr']:.2f} rays/s={row['rays_per_s']:.0f}")

    base, prop = rows
    reduction = base["fine_evals_per_ray"] / max(1, prop["fine_evals_per_ray"])
    delta_db = prop["psnr"] - base["psnr"]
    prop["fine_eval_reduction_x"] = reduction
    prop["psnr_delta_db"] = delta_db
    print(f"[bench_sampling] proposal vs baseline: {reduction:.1f}x fewer "
          f"fine evals/ray, PSNR delta {delta_db:+.2f} dB, "
          f"throughput {prop['rays_per_s'] / base['rays_per_s']:.2f}x")

    with open(args.out, "a") as f:
        for row in rows:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    print(f"[bench_sampling] appended {len(rows)} rows to {args.out}")

    if tmp is not None:
        tmp.cleanup()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
