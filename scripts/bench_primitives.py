"""Elision-immune primitive rate bench for the hash-grad redesign.

Round 3 proved this machine's axon tunnel produces physically impossible
timings for host-side loops that re-dispatch an executable — even with
perturbed arguments (BENCH_HASH_STEP.jsonl: a 99 MB-gradient fwd+bwd "in
30 us"). Every measurement here therefore runs K dependent iterations
INSIDE one jitted ``lax.fori_loop`` whose carry feeds each iteration from
the last: one dispatch, no host loop, nothing to elide. Rates are
lower bounds (the carry chain serializes iterations — exactly like a real
training loop).

These ten numbers decide the table-gradient mechanism (VERDICT r3 #1):
the reference's CUDA backward is one atomicAdd pass
(hashencoder.cu:254-267); the TPU-native replacement must be built from
whichever of scatter / sort+segment / gather-diff / one-hot-matmul the
hardware actually runs fast.

    python scripts/bench_primitives.py [--rows 2097152] [--iters 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=2 * 1024 * 1024)
    p.add_argument("--table", type=int, default=524288)
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    p.add_argument("--out", default="")
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import (
        enable_compilation_cache,
        setup_backend,
    )

    setup_backend(args.force_platform)
    enable_compilation_cache()

    import jax
    import jax.numpy as jnp
    from jax import lax

    R, T, K = args.rows, args.table, args.iters
    C = 2

    sink = open(args.out, "a") if args.out else None

    def emit(name, seconds, unit_count, unit):
        rec = {"stage": name, "s_per_iter": round(seconds, 6),
               "rate_per_s": round(unit_count / seconds, 1), "unit": unit,
               "rows": R, "table": T, "iters": K,
               "ts": int(time.time())}
        line = json.dumps(rec)
        print(line, flush=True)
        if sink:
            sink.write(line + "\n")
            sink.flush()

    def _sync(x):
        """Force completion: device_get of a scalar CANNOT return before the
        producing computation finishes. On the axon tunnel,
        ``block_until_ready`` returns early for sub-ms programs, so timing
        must end on a host copy, not on a ready-event."""
        leaf = jax.tree_util.tree_leaves(x)[0]
        return float(jnp.ravel(leaf)[0])

    def run(name, body, carry, unit_count, unit):
        """body(i, carry) -> carry; K iterations inside one executable."""

        @jax.jit
        def prog(c):
            return lax.fori_loop(0, K, body, c)

        try:
            out = prog(carry)  # compile + warm
            _sync(out)
            t0 = time.perf_counter()
            out = prog(out)
            _sync(out)
            dt = (time.perf_counter() - t0) / K
            emit(name, dt, unit_count, unit)
        except Exception as exc:  # keep later stages alive
            msg = str(exc).splitlines()[0][:160]
            rec = {"stage": name, "error": msg, "rows": R, "table": T}
            print(json.dumps(rec), flush=True)
            if sink:
                sink.write(json.dumps(rec) + "\n")
                sink.flush()

    key0 = jax.random.PRNGKey(0)

    # fresh indices each iteration (PRNG inside the loop), accumulation
    # carries the dependency chain
    def fresh_idx(i, lo, hi, n):
        return jax.random.randint(jax.random.fold_in(key0, i), (n,), lo, hi)

    # 1. duplicate-index scatter-add (the convicted current lowering)
    def scatter_dup(i, acc):
        idx = fresh_idx(i, 0, T, R)
        upd = jnp.full((R, C), 1e-6, jnp.float32) + acc[0, :1]
        return acc.at[idx].add(upd)

    run("scatter_add_dup", scatter_dup, jnp.zeros((T, C)), R, "rows")

    # 2. duplicate scatter-add, bf16 updates (the training dtype)
    def scatter_dup_bf16(i, acc):
        idx = fresh_idx(i, 0, T, R)
        upd = (jnp.full((R, C), 1e-3, jnp.bfloat16)
               + acc[0, :1].astype(jnp.bfloat16))
        return acc.at[idx].add(upd.astype(jnp.float32))

    run("scatter_add_dup_bf16src", scatter_dup_bf16, jnp.zeros((T, C)),
        R, "rows")

    # 3. SORTED duplicate scatter-add (indices_are_sorted hint)
    def scatter_sorted(i, acc):
        idx = jnp.sort(fresh_idx(i, 0, T, R))
        upd = jnp.full((R, C), 1e-6, jnp.float32) + acc[0, :1]
        dnums = lax.ScatterDimensionNumbers(
            update_window_dims=(1,), inserted_window_dims=(0,),
            scatter_dims_to_operand_dims=(0,))
        return lax.scatter_add(acc, idx[:, None], upd, dnums,
                               indices_are_sorted=True,
                               unique_indices=False)

    run("scatter_add_sorted_dup", scatter_sorted, jnp.zeros((T, C)),
        R, "rows")

    # 4. UNIQUE-index scatter (a permutation write — the radix keystone)
    def scatter_unique(i, acc):
        perm = jax.random.permutation(jax.random.fold_in(key0, i), R)
        upd = jnp.full((R, C), 1e-6, jnp.float32) + acc[0, :1]
        dnums = lax.ScatterDimensionNumbers(
            update_window_dims=(1,), inserted_window_dims=(0,),
            scatter_dims_to_operand_dims=(0,))
        return lax.scatter(acc, perm[:, None], upd, dnums,
                           indices_are_sorted=False, unique_indices=True)

    run("scatter_unique_perm", scatter_unique, jnp.zeros((R, C)), R, "rows")

    # 5. gather R rows from a [T, C] table
    def gather_rows(i, acc):
        idx = fresh_idx(i, 0, T, R)
        vals = jnp.take(acc, idx, axis=0)
        return acc.at[0].add(jnp.sum(vals, axis=0) * 1e-9)

    run("gather_rows", gather_rows, jnp.ones((T, C)), R, "rows")

    # 6. global sort of R int32 keys with int32 payload (argsort-equiv)
    def sort_global(i, acc):
        keys = fresh_idx(i, 0, T, R) + acc[0]
        sk, sv = lax.sort((keys, jnp.arange(R, dtype=jnp.int32)),
                          num_keys=1)
        return acc.at[0].set(sk[0] % 7 + sv[0] % 3)

    run("sort_global_i32_pair", sort_global, jnp.zeros((1,), jnp.int32),
        R, "rows")

    # 7. batched minor-axis sort: [R/4096, 4096] rows sorted independently
    B_rows = R // 4096

    def sort_batched(i, acc):
        keys = jax.random.randint(jax.random.fold_in(key0, i),
                                  (B_rows, 4096), 0, T) + acc[0, 0]
        s = jnp.sort(keys, axis=-1)
        return acc.at[0, 0].set(s[0, 0] % 11)

    run("sort_batched_minor4096", sort_batched,
        jnp.zeros((1, 1), jnp.int32), R, "rows")

    # 8. cumsum over [R, C] f32
    def cumsum_rows(i, acc):
        x = jnp.full((R, C), 1e-7, jnp.float32) + acc[0, :1]
        cs = jnp.cumsum(x, axis=0)
        return acc.at[0].add(cs[-1] * 1e-9)

    run("cumsum_rows", cumsum_rows, jnp.zeros((1, C)), R, "rows")

    # 9. searchsorted: T queries into a sorted R-array
    def searchsorted_t(i, acc):
        hay = jnp.sort(fresh_idx(i, 0, jnp.int32(1 << 30), R))
        pos = jnp.searchsorted(hay, jnp.arange(T, dtype=jnp.int32) + acc[0])
        return acc.at[0].set(pos[0] % 5)

    run("sort_plus_searchsorted", searchsorted_t,
        jnp.zeros((1,), jnp.int32), R, "rows(sort)+T queries")

    # 10. one-hot matmul histogram, level-0 scale (T0=4920): does XLA fuse
    # the iota-compare producer into the matmul, and at what FLOPs?
    T0 = 4920
    R0 = R // 4

    def onehot_hist(i, acc):
        idx = fresh_idx(i, 0, T0, R0)
        upd = jnp.full((R0, C), 1e-6, jnp.float32) + acc[0, :1]
        oh = (idx[:, None] == jnp.arange(T0)[None, :]).astype(jnp.float32)
        return acc + jnp.einsum("nt,nc->tc", oh, upd,
                                preferred_element_type=jnp.float32)

    run("onehot_matmul_hist_T4920", onehot_hist, jnp.zeros((T0, C)),
        R0 * T0 * C * 2, "flops")

    # 11. same but bf16 one-hot (MXU-native dtype)
    def onehot_hist_bf16(i, acc):
        idx = fresh_idx(i, 0, T0, R0)
        upd = (jnp.full((R0, C), 1e-3, jnp.bfloat16)
               + acc[0, :1].astype(jnp.bfloat16))
        oh = (idx[:, None] == jnp.arange(T0)[None, :]).astype(jnp.bfloat16)
        return acc + jnp.einsum("nt,nc->tc", oh, upd,
                                preferred_element_type=jnp.float32)

    run("onehot_matmul_hist_bf16_T4920", onehot_hist_bf16,
        jnp.zeros((T0, C)), R0 * T0 * C * 2, "flops")

    # 12. segment_sum on PRE-SORTED ids (sorted-scatter lowering, no sort
    # in the loop — isolates the segment reduction itself)
    def segsum_sorted(i, acc):
        idx = jnp.sort(fresh_idx(i, 0, T, R))
        upd = jnp.full((R, C), 1e-6, jnp.float32) + acc[0, :1]
        return acc + jax.ops.segment_sum(upd, idx, num_segments=T,
                                         indices_are_sorted=True)

    run("segment_sum_sorted", segsum_sorted, jnp.zeros((T, C)), R, "rows")

    if sink:
        sink.close()


if __name__ == "__main__":
    main()
