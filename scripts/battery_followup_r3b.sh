#!/bin/bash
# Round-3 follow-up battery: the measurements the main battery could not
# deliver because the tunnel's compile helper dies (HTTP 500) on 64k+-ray
# graphs. Everything here uses shapes in the proven-compilable class
# (<= 16384 rays).
#
#   1. hash-config training sweep at 4096/16384, scan on/off — the lego_hash
#      config has no trained-throughput number yet, and the one data point
#      (651 rays/s from bench_ngp) contradicts the encoder microbench by ~50x
#   2. profile of the hash step at the same shape — names the guilty op
#   3. profile of the actual big-MLP headline shape (4096, no remat; the
#      main battery's stage 5 profiled 65536+remat, which cannot compile)
#
# Serialize behind the main battery (monoclient tunnel): run only when no
# other chip job is alive.
set -u
cd "$(dirname "$0")/.."
log() { echo "[followup $(date +%H:%M:%S)] $*"; }

# same watch discipline as tpu_battery.sh: the tunnel wedges after killed
# compiles and recovers on its own; require two consecutive good probes
WATCH_PROBES=${WATCH_PROBES:-60}
PROBE_SLEEP=${PROBE_SLEEP:-300}
probe_once() { timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; }
if [ "$WATCH_PROBES" -gt 0 ]; then
  up=0
  for i in $(seq 1 "$WATCH_PROBES"); do
    if probe_once; then
      log "probe $i: UP — confirming"
      sleep 60
      if probe_once; then log "probe $i: CONFIRMED up"; up=1; break; fi
      log "probe $i: flapped back down"
    else
      log "probe $i: down"
    fi
    sleep "$PROBE_SLEEP"
  done
  [ "$up" -eq 1 ] || { log "tunnel never confirmed up; exiting"; exit 1; }
fi

log "=== f1: lego_hash training sweep (proven-compilable shapes) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 7200 python scripts/bench_sweep.py \
  --config lego_hash.yaml --rays 4096 16384 --dtypes bfloat16 \
  --remat false --scan_steps 1 8 --steps 60 \
  --point_timeout 2400 --out BENCH_SWEEP_HASH.jsonl

log "=== f2: profile the hash step ==="
mkdir -p data/logs
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 2400 python scripts/profile_step.py \
  --config lego_hash.yaml --n_rays 4096 --remat false \
  2>data/logs/profile_hash.err | tee -a PROFILE_STEP.jsonl

log "=== f3: profile the big-MLP headline shape ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 2400 python scripts/profile_step.py \
  --config lego.yaml --n_rays 4096 --remat false \
  2>data/logs/profile_headline.err | tee -a PROFILE_STEP.jsonl

log "=== f1c: big-MLP scan sweep at 8192 rays (fits HBM no-remat; untried) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 5400 python scripts/bench_sweep.py \
  --rays 8192 --dtypes bfloat16 --remat false --scan_steps 8 32 --steps 60 \
  --point_timeout 2400 --out BENCH_SWEEP.jsonl
python scripts/promote_bench_defaults.py \
  BENCH_SWEEP.jsonl BENCH_SWEEP_REMAT.jsonl --config lego.yaml

log "=== f4: scale check 800x800 (battery stage 6 lost to the wedge) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 1800 python scripts/scale_check.py \
  --H 800 2>data/logs/scale_check.err | tee -a SCALE_CHECK.jsonl

log "=== followup-b done ==="
