"""Closed/open-loop load generator for the render-serving engine.

Boots a self-contained tiny scene + randomly-initialized network (no
checkpoint or dataset download needed), a synthetic occupancy grid, and a
full engine + micro-batcher stack, then drives a MIXED-SHAPE request
stream at it:

* **closed loop** — one outstanding request at a time (a single
  well-behaved client): measures the floor latency including the
  batcher's max-delay deadline.
* **open loop** — requests arrive on a fixed-rate pacer regardless of
  completions (heavy-traffic shape): measures coalescing, batch
  occupancy, and the degradation policy under real backlog.

Every run appends one summary row per mode to ``BENCH_SERVE.jsonl``
(family ``serve_mode`` — scripts/check_telemetry_schema.py validates it)
and writes full ``serve_request``/``serve_batch``/``serve_shed`` telemetry
through the obs emitter, so ``scripts/tlm_report.py`` can break the run
down. The headline acceptance number is ``compiles_steady``: the obs
CompileTracker count accumulated AFTER warmup across the whole mixed-shape
stream — it must be zero (the shape buckets absorb every request shape).

``--tracing both`` (the default) runs each mode twice — span tracing off,
then on — and prices the instrumentation itself: the tracing-on row adds
a per-stage latency breakdown from the span stream
(``stage_queue_p50_ms`` … ``stage_scatter_p95_ms``, the obs/trace.py
stage taxonomy), ``stage_wall_ratio`` (how much of each closed-loop
request's wall time the stage spans account for — the tracing acceptance
wants ≥ 0.9), and ``trace_overhead_pct`` (throughput lost vs the
tracing-off arm of the same mode — acceptance wants ≤ 5%).

* **fleet churn** (``--scenes N``) — multi-tenant mode: N synthetic
  scenes (same architecture, perturbed weights) behind a
  :class:`~nerf_replication_tpu.fleet.ResidencyManager`, driven as runs
  of same-scene requests cycling round-robin with the NEXT scene
  prefetched one run ahead. ``--churn`` shrinks the HBM budget to about
  half the fleet so every cycle forces eviction/reload; without it the
  whole fleet stays resident. The summary row (family ``fleet_mode``,
  appended to ``BENCH_FLEET.jsonl``) splits latency into same-scene vs
  scene-switch percentiles — the acceptance number is the prefetched
  switch p95 staying within 2x of the same-scene p95, at
  ``compiles_steady == 0`` across all scene churn.

* **multi-tenant QoS** (``--tenants N``) — one ``hot`` tenant offers
  ``--hot-tenant-share`` of request volume against ``N-1`` quiet
  tenants. Phase 1 measures the quiet tenants' closed-loop p95 alone;
  phase 2 repeats the identical quiet stream under the hot flood, with
  admission quotas (fleet/qos.py token buckets) plus weighted fair
  batching carrying the containment. A third phase prices the residency
  ladder: cold disk load (npz + checksum walk + device_put) vs staging
  re-promotion (device_put only) on a
  :class:`~nerf_replication_tpu.fleet.TieredResidencyManager`. The
  summary row (family ``qos_mode``, appended to ``BENCH_QOS.jsonl``)
  gates on quiet p95 within 15% of solo, re-promotion >= 5x faster than
  cold, and ``compiles_steady == 0`` across throttle + demote churn.

* **replica scale-out** (``--replicas N``) — the scale-out front door
  (nerf_replication_tpu/scale, docs/scaleout.md): an open-loop stream
  through the router + supervisor over in-process replicas that warm
  from a SHARED ``.aot`` artifact dir. A spike phase overloads one
  replica until SLO attainment trips the supervisor's scale-out, then a
  sustain phase idles the fleet back down through drain-before-retire —
  one full scale-out/scale-in cycle per run. The supervisor reads its
  attainment/deny-rate windows off the fleet metrics aggregator
  (``Supervisor.step_from_fleet``) — the same merged signal
  ``GET /fleet/metrics`` exposes — and each out/in decision carries that
  window as evidence. ``--tracing both`` runs the cycle twice (off, then
  on — the shared artifact dir makes the second arm warm) and prices the
  fleet tracing as ``trace_overhead_pct``. The summary row (family
  ``scale_mode``, appended to ``BENCH_SCALE.jsonl``) gates on attainment
  recovering after scale-out, the FRESH replica reporting
  ``warm_source == "disk"`` with zero compiles (artifact warm-start, not
  a recompile), zero drain failures, ``compiles_steady == 0`` across
  the whole cycle, and — with tracing on — every capacity action citing
  >= 1 exemplar trace id. The run also carries the PR 16 ops loop: a
  bench-scaled burn-rate AlertEngine rides the supervisor's merged
  windows and must PAGE during the spike and clear by the end of the
  sustain phase at <= 5% overhead (``alert_page_during_spike`` /
  ``alert_cleared_at_end`` / ``alert_overhead_pct``); the page opens an
  incident whose resolved dump links the scale decisions' exemplar
  trace ids (``incidents_linked``, gated with tracing on); and every
  replica's capacity ledger commits a ``capacity_snapshot`` telemetry
  row at teardown (``capacity_snapshots``).

* **placement-planned process fleet** (``--replicas N --processes``) —
  the real multi-process shape (scale/launcher.py + scale/placement.py):
  a :class:`~nerf_replication_tpu.scale.ProcessLauncher` spawns N
  ``serve.py`` children against ONE shared ``.aot`` artifact dir (the
  parent pre-boots the same config once to pay the compile, so every
  child reports ``warm_source == "disk"`` with zero builds), a 3-scene
  sharded :class:`~nerf_replication_tpu.fleet.SceneStore` backs each
  child's residency ladder, and the router runs with the
  :class:`~nerf_replication_tpu.scale.PlacementPlanner` attached. The
  parent-side capacity ledger measures per-scene heat off the routed
  traffic; the plan must replicate the hot scene ``hot_width``-wide
  under the ladder byte budgets (remote prefetches realize lazily — the
  bench aims one request at every planned-but-not-resident pair, which
  is exactly how plan-steered traffic materializes a copy); then a
  SIGKILLed hot-scene child must be 1:1-replaced by the launcher with
  ZERO failed in-flight requests and the width restored. One summary
  row (family ``placement_mode``, appended to ``BENCH_SCALE.jsonl``)
  gates on plan version/width attainment, zero over-budget replicas,
  the unplanned-dispatch share, a clean kill-repair, and all-disk
  warm-starts with zero child builds.

    python scripts/serve_bench.py --backend cpu
    python scripts/serve_bench.py --backend cpu --mode open --rate 200
    python scripts/serve_bench.py --backend cpu --scenes 3 --churn
    python scripts/serve_bench.py --backend cpu --tenants 3
    python scripts/serve_bench.py --backend cpu --replicas 2 --rate 90 \
        --sustain-rate 20 --slo-ms 200
    python scripts/serve_bench.py --backend cpu --replicas 2 --processes \
        --buckets 512
    python scripts/tlm_report.py data/record/serve_bench
"""

from __future__ import annotations

import argparse
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

NEAR, FAR = 2.0, 6.0


def _bench_cfg(scene_root: str, args, extra=()):
    """A miniature lego-schema config sized for the bench backend."""
    from nerf_replication_tpu.config import make_cfg

    return make_cfg(
        os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        [
            "scene", "procedural",
            "exp_name", "serve_bench",
            "train_dataset.data_root", scene_root,
            "test_dataset.data_root", scene_root,
            "train_dataset.H", "16", "train_dataset.W", "16",
            "test_dataset.H", "16", "test_dataset.W", "16",
            "task_arg.N_samples", "24",
            "task_arg.N_importance", "24",
            "network.nerf.W", "64",
            "network.nerf.D", "3",
            "network.nerf.skips", "[1]",
            "network.xyz_encoder.freq", "6",
            "network.dir_encoder.freq", "2",
            "task_arg.render_step_size", "0.25",
            "task_arg.max_march_samples", "16",
            "task_arg.march_chunk_size", str(args.chunk),
            "serve.buckets", str(list(args.buckets)),
            "serve.max_batch_rays", str(args.max_batch_rays),
            "serve.max_delay_ms", str(args.max_delay_ms),
            "serve.request_timeout_s", "30.0",
            "serve.shed_queue_depths", str(list(args.shed_depths)),
            "record_dir", args.record_dir,
            *extra,
        ],
    )


def _build_stack(args):
    """(engine, batcher) on a procedural scene with a synthetic box grid."""
    import numpy as np

    import jax

    from nerf_replication_tpu.datasets.procedural import generate_scene
    from nerf_replication_tpu.models import init_params_for, make_network
    from nerf_replication_tpu.serve import MicroBatcher, RenderEngine

    scene_root = os.path.join(args.workdir, "scene")
    if not os.path.exists(os.path.join(scene_root, "transforms_train.json")):
        generate_scene(scene_root, scene="procedural", H=16, W=16,
                       n_train=4, n_test=1)
    cfg = _bench_cfg(scene_root, args)
    network = make_network(cfg)
    params = init_params_for(cfg)(network, jax.random.PRNGKey(0))
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)
    grid = np.zeros((16, 16, 16), bool)
    grid[4:12, 4:12, 4:12] = True  # occupied box mid-scene

    from nerf_replication_tpu.obs import init_run

    # explicit path: parse_cfg specializes cfg.record_dir per experiment;
    # the bench wants its telemetry exactly where --record-dir says
    init_run(cfg, component="serve_bench",
             path=os.path.join(args.record_dir, "telemetry.jsonl"))
    t0 = time.perf_counter()
    engine = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                          grid=grid, bbox=bbox)
    warmup_s = time.perf_counter() - t0
    batcher = MicroBatcher(engine)
    return cfg, engine, batcher, warmup_s


def _request_stream(rng, n_requests: int, min_rays: int, max_rays: int):
    """Mixed-shape ray batches: random counts, random view jitter."""
    import numpy as np

    sizes = rng.integers(min_rays, max_rays + 1, size=n_requests)
    for n in sizes:
        d = np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.15, (int(n), 3))
        o = np.tile([0.0, 0.0, 4.0], (int(n), 1))
        yield np.concatenate([o, d], -1).astype(np.float32)


def _build_fleet(engine, args):
    """N synthetic scenes over an in-memory loader: same architecture
    (one executable family serves all), per-scene perturbed weights.

    ``--churn`` sizes the byte budget to about half the fleet, so the
    round-robin stream below forces an eviction/reload every cycle —
    the worst-case residency pattern the bench is meant to price."""
    import numpy as np

    import jax

    from nerf_replication_tpu.fleet import (
        ResidencyManager,
        SceneData,
        SceneRecord,
        SceneRegistry,
    )

    scene_ids = [f"scene{i:02d}" for i in range(args.scenes)]
    datas = {}
    for i, sid in enumerate(scene_ids):
        perturbed = jax.tree.map(
            lambda a, s=1.0 + 0.01 * (i + 1): np.asarray(a) * np.float32(s),
            engine.params,
        )
        datas[sid] = SceneData(scene_id=sid, params=perturbed,
                               grid=np.asarray(engine.grid),
                               bbox=np.asarray(engine.bbox),
                               near=NEAR, far=FAR)
    registry = SceneRegistry(SceneRecord(scene_id=s) for s in scene_ids)
    one = (sum(leaf.nbytes for leaf in jax.tree.leaves(engine.params))
           + engine.grid.nbytes + engine.bbox.nbytes)
    budget_scenes = (
        max(1.5, args.scenes / 2.0) if args.churn else args.scenes + 0.5
    )
    residency = ResidencyManager(
        registry, lambda rec: datas[rec.scene_id],
        budget_bytes=int(one * budget_scenes),
        verify_checksums=False,
    )
    engine.attach_fleet(residency)
    return residency, scene_ids


def _run_fleet(engine, batcher, residency, scene_ids, rng, args) -> dict:
    """Round-robin scene runs with one-run-ahead prefetch.

    Each scene serves ``--run-len`` requests back to back, then the
    stream switches; the upcoming scene's load was issued at the START
    of the previous run, so the switch request finds it resident (or
    joins the in-flight transfer) instead of cold-loading inline."""
    from nerf_replication_tpu.obs import get_tracer

    trs = get_tracer()
    same, switch = [], []
    total = 0
    prev_sid = None
    t_start = time.perf_counter()
    stream = _request_stream(rng, args.requests, args.min_rays,
                             args.max_rays)
    run_idx = 0
    while total < args.requests:
        sid = scene_ids[run_idx % len(scene_ids)]
        residency.prefetch(scene_ids[(run_idx + 1) % len(scene_ids)])
        for i in range(min(args.run_len, args.requests - total)):
            rays = next(stream)
            t0 = time.perf_counter()
            with trs.span("bench.request", parent=None, scene=sid):
                batcher.submit(rays, NEAR, FAR,
                               scene=sid).result(timeout=60.0)
            lat = time.perf_counter() - t0
            total += 1
            # the first request after a scene change pays the switch
            # (residency pin + possible load join); the rest are warm
            (switch if i == 0 and sid != prev_sid else same).append(lat)
        prev_sid = sid
        run_idx += 1
    return {"same_s": same, "switch_s": switch,
            "wall_s": time.perf_counter() - t_start}


def _fleet_row(run: dict, engine, residency, args,
               compiles_steady: int) -> dict:
    stats = residency.stats()
    n = len(run["same_s"]) + len(run["switch_s"])
    return {
        "fleet_mode": "churn" if args.churn else "resident",
        "n_scenes": args.scenes,
        "n_requests": n,
        "run_len": args.run_len,
        "evictions": stats["evictions"],
        "cold_loads": stats["cold_loads"],
        "prefetch_hit_rate": stats["prefetch_hit_rate"],
        "p50_same_ms": (_percentile(run["same_s"], 50) or 0.0) * 1e3,
        "p95_same_ms": (_percentile(run["same_s"], 95) or 0.0) * 1e3,
        "p50_switch_ms": (_percentile(run["switch_s"], 50) or 0.0) * 1e3,
        "p95_switch_ms": (_percentile(run["switch_s"], 95) or 0.0) * 1e3,
        "rps": n / run["wall_s"] if run["wall_s"] else 0.0,
        "budget_bytes": stats["budget_bytes"],
        "bytes_loaded": stats["bytes_loaded"],
        "compiles_warmup": engine.warmup_compiles,
        "compiles_steady": compiles_steady,
        "backend": args.backend,
        "buckets": list(engine.buckets),
        "seed": args.seed,
    }


def _percentile(values, q):
    ordered = sorted(values)
    if not ordered:
        return None
    idx = min(len(ordered) - 1,
              max(0, int(round(q / 100.0 * (len(ordered) - 1)))))
    return ordered[idx]


# -- multi-tenant QoS mode (--tenants) ----------------------------------------


def _build_qos(args):
    """Controller for the fairness phases. The hot tenant's bucket is
    deliberately starved (2 req/s against a flood offering hundreds):
    quiet p95 can only stay within 15% of solo if the flood is absorbed
    at ADMISSION — a single non-preemptive render worker means every hot
    batch that reaches the engine sits directly in some quiet request's
    queue wait, so the bucket (not the scheduler) has to carry the
    containment. The WFQ weights then order whatever trickle is
    admitted: quiet carries 4x hot's share."""
    from nerf_replication_tpu.fleet import QosController, TenantPolicy

    quiet_ids = [f"quiet{i:02d}" for i in range(max(1, args.tenants - 1))]
    qos = QosController(
        [TenantPolicy("hot", rate=2.0, burst=2.0, weight=1.0)]
        + [TenantPolicy(q, rate=2000.0, burst=256.0, weight=4.0)
           for q in quiet_ids],
    )
    return qos, quiet_ids


def _run_quiet_solo(batcher, rng, args, quiet_ids, n_quiet) -> list:
    """Baseline: the quiet tenants alone, closed loop. Their p95 here is
    what the contention phase must stay within 15% of."""
    lats = []
    stream = _request_stream(rng, n_quiet, args.min_rays, args.max_rays)
    for i, rays in enumerate(stream):
        t0 = time.perf_counter()
        batcher.submit(rays, NEAR, FAR,
                       tenant=quiet_ids[i % len(quiet_ids)]).result(60.0)
        lats.append(time.perf_counter() - t0)
    return lats


def _run_quiet_contended(batcher, rng, args, quiet_ids, n_quiet) -> dict:
    """The QoS claim under test: a hot tenant offers
    ``hot_share/(1-hot_share)`` fire-and-forget requests per quiet
    request (75% of offered volume at the default share), and the quiet
    tenants' closed-loop latency must stay near their solo baseline.
    Hot requests are small (<=256 rays) — the realistic abuse shape is
    many cheap calls, and it keeps each leaked hot render brief.

    Admitted hot futures are NOT awaited inline (an open-loop client),
    only windowed so a misconfigured quota can't accumulate unbounded
    futures; denied submits surface here as ``TenantQuotaError``."""
    from collections import deque

    import numpy as np

    from nerf_replication_tpu.fleet import TenantQuotaError

    hot_per_quiet = max(1, round(
        args.hot_tenant_share / max(1e-6, 1.0 - args.hot_tenant_share)
    ))
    hot_stream = _request_stream(
        np.random.default_rng(args.seed + 1),
        n_quiet * hot_per_quiet, args.min_rays,
        min(256, args.max_rays),
    )
    quiet_stream = _request_stream(rng, n_quiet, args.min_rays,
                                   args.max_rays)
    window: deque = deque()
    lats = []
    hot_done = hot_failed = hot_denied = 0

    def harvest(f) -> int:
        try:
            f.result(timeout=60.0)
            return 1
        except Exception:
            return 0

    t_start = time.perf_counter()
    for i, rays_q in enumerate(quiet_stream):
        for _ in range(hot_per_quiet):
            while len(window) >= 16:
                ok = harvest(window.popleft())
                hot_done += ok
                hot_failed += 1 - ok
            try:
                window.append(batcher.submit(next(hot_stream), NEAR, FAR,
                                             tenant="hot"))
            except TenantQuotaError:
                hot_denied += 1
        t0 = time.perf_counter()
        batcher.submit(rays_q, NEAR, FAR,
                       tenant=quiet_ids[i % len(quiet_ids)]).result(60.0)
        lats.append(time.perf_counter() - t0)
    while window:
        ok = harvest(window.popleft())
        hot_done += ok
        hot_failed += 1 - ok
    return {"latencies_s": lats, "hot_done": hot_done,
            "hot_failed": hot_failed, "hot_denied": hot_denied,
            "hot_submitted": n_quiet * hot_per_quiet,
            "hot_per_quiet": hot_per_quiet,
            "wall_s": time.perf_counter() - t_start}


def _build_qos_ladder(engine, args):
    """Disk-backed scenes under a TieredResidencyManager, for pricing a
    cold load (npz read + tree-checksum walk + device_put) against a
    staging re-promotion (device_put only).

    Each scene file carries a 16 MiB ballast array the loader never
    touches: a production checkpoint is tens of MB while the bench MLP is
    not, so the ballast makes the disk + checksum cost representative
    WITHOUT inflating what device_put transfers — the comparison stays
    honest for the claim the ladder actually makes (skip disk, skip
    checksums; the h2d cost is identical on both paths)."""
    import shutil

    import numpy as np

    import jax

    from nerf_replication_tpu.fleet import (
        SceneData,
        SceneRecord,
        SceneRegistry,
        TieredResidencyManager,
    )
    from nerf_replication_tpu.resil import write_tree_checksum

    root = os.path.join(args.workdir, "qos_scenes")
    if os.path.isdir(root):
        shutil.rmtree(root)
    leaves0, treedef = jax.tree_util.tree_flatten(engine.params)
    grid = np.asarray(engine.grid)
    bbox = np.asarray(engine.bbox)
    records = []
    for i in range(3):
        sid = f"ladder{i:02d}"
        d = os.path.join(root, sid)
        os.makedirs(d)
        arrays = {
            f"leaf{j}": np.asarray(l) * np.float32(1.0 + 0.01 * (i + 1))
            for j, l in enumerate(leaves0)
        }
        arrays["ballast"] = np.zeros(1 << 22, np.float32)  # 16 MiB on disk
        np.savez(os.path.join(d, "scene.npz"), **arrays)
        write_tree_checksum(d)
        records.append(SceneRecord(scene_id=sid, checkpoint=d))

    def loader(rec):
        with np.load(os.path.join(rec.checkpoint, "scene.npz")) as z:
            leaves = [z[f"leaf{j}"] for j in range(len(leaves0))]
        return SceneData(
            scene_id=rec.scene_id,
            params=jax.tree_util.tree_unflatten(treedef, leaves),
            grid=grid, bbox=bbox, near=NEAR, far=FAR,
        )

    one = (sum(l.nbytes for l in leaves0) + grid.nbytes + bbox.nbytes)
    residency = TieredResidencyManager(
        SceneRegistry(records), loader,
        budget_bytes=int(one * 8),
        staging_budget_bytes=int(one * 8),
        verify_checksums=True, prefetch=False,
    )
    return residency, [r.scene_id for r in records]


def _time_ladder(residency, scene_ids, rounds: int = 5) -> dict:
    """Median cold-load vs demote->re-promote acquire times (both paths
    end in the same device_put; the delta is disk + checksum walk)."""
    cold, reprom = [], []
    for sid in scene_ids:  # first touch: the true cold path
        t0 = time.perf_counter()
        residency.acquire(sid)
        cold.append(time.perf_counter() - t0)
        residency.release(sid)
    for _ in range(rounds):
        for sid in scene_ids:
            assert residency.evict(sid)  # unpinned: demotes to staging
            t0 = time.perf_counter()
            residency.acquire(sid)
            reprom.append(time.perf_counter() - t0)
            residency.release(sid)
    return {
        "cold_ms": (_percentile(cold, 50) or 0.0) * 1e3,
        "repromote_ms": (_percentile(reprom, 50) or 0.0) * 1e3,
        "n_cold": len(cold),
        "n_repromote": len(reprom),
    }


def _qos_row(solo, cont, ladder, ladder_stats, engine, batcher, args,
             compiles_steady: int) -> dict:
    reprom_ms = ladder["repromote_ms"]
    return {
        "qos_mode": "wfq",
        "tenants": args.tenants,
        "hot_share": args.hot_tenant_share,
        "quiet_p95_ms": (_percentile(cont["latencies_s"], 95) or 0.0) * 1e3,
        "quiet_solo_p95_ms": (_percentile(solo, 95) or 0.0) * 1e3,
        "quiet_p50_ms": (_percentile(cont["latencies_s"], 50) or 0.0) * 1e3,
        "quiet_solo_p50_ms": (_percentile(solo, 50) or 0.0) * 1e3,
        "n_quiet_requests": len(cont["latencies_s"]),
        "hot_submitted": cont["hot_submitted"],
        "hot_denied": cont["hot_denied"],
        "hot_done": cont["hot_done"],
        "hot_failed": cont["hot_failed"],
        "n_quota_denied": batcher.n_quota_denied,
        "cold_load_ms": ladder["cold_ms"],
        "repromote_ms": reprom_ms,
        "repromote_speedup": (
            ladder["cold_ms"] / reprom_ms if reprom_ms else None
        ),
        "n_cold": ladder["n_cold"],
        "n_repromote": ladder["n_repromote"],
        "demotions": ladder_stats["demotions"],
        "manual_evictions": ladder_stats["manual_evictions"],
        "repromotions": ladder_stats["repromotions"],
        "disk_loads": ladder_stats["disk_loads"],
        "compiles_warmup": engine.warmup_compiles,
        "compiles_steady": compiles_steady,
        "backend": args.backend,
        "buckets": list(engine.buckets),
        "seed": args.seed,
    }


def _run_closed(batcher, rng, args) -> dict:
    from nerf_replication_tpu.obs import get_tracer

    trs = get_tracer()
    lats = []
    t_start = time.perf_counter()
    for rays in _request_stream(rng, args.requests, args.min_rays,
                                args.max_rays):
        t0 = time.perf_counter()
        # root span per request: the batcher captures this context at
        # submit, so the queue/scatter records and the worker's batch
        # span (acquire/dispatch/device inside it) land on this trace —
        # _stage_summary divides their sum by this span's duration
        with trs.span("bench.request", parent=None):
            batcher.submit(rays, NEAR, FAR).result(timeout=60.0)
        lats.append(time.perf_counter() - t0)
    wall = time.perf_counter() - t_start
    return {"latencies_s": lats, "wall_s": wall}


def _run_open(batcher, rng, args) -> dict:
    """Fixed-rate arrivals; latency measured submit -> result per request."""
    import threading

    futures, stamps = [], []
    interval = 1.0 / max(args.rate, 1e-6)

    def submitter():
        next_t = time.perf_counter()
        for rays in _request_stream(rng, args.requests, args.min_rays,
                                    args.max_rays):
            now = time.perf_counter()
            if now < next_t:
                time.sleep(next_t - now)
            stamps.append(time.perf_counter())
            futures.append(batcher.submit(rays, NEAR, FAR))
            next_t += interval

    t_start = time.perf_counter()
    th = threading.Thread(target=submitter)
    th.start()
    th.join()
    lats, timeouts = [], 0
    for t0, f in zip(stamps, futures):
        try:
            f.result(timeout=60.0)
            lats.append(time.perf_counter() - t0)
        except TimeoutError:
            timeouts += 1
    wall = time.perf_counter() - t_start
    return {"latencies_s": lats, "wall_s": wall, "timeouts": timeouts}


def _snapshot(engine, batcher) -> dict:
    """Counter snapshot so per-mode rows report deltas, not totals (one
    engine/batcher serves every mode of a run)."""
    return {
        "rendered": engine.n_rays_rendered,
        "pad": engine.n_pad_rays,
        "shed": batcher.n_shed,
        "timeouts": batcher.n_timeouts,
        "batches": batcher.n_batches,
    }


def _summary_row(mode: str, run: dict, engine, batcher, args,
                 compiles_steady: int, warmup_s: float,
                 before: dict) -> dict:
    lats = run["latencies_s"]
    rendered = engine.n_rays_rendered - before["rendered"]
    padded = rendered + engine.n_pad_rays - before["pad"]
    return {
        "serve_mode": mode,
        "n_requests": len(lats),
        "p50_ms": (_percentile(lats, 50) or 0.0) * 1e3,
        "p95_ms": (_percentile(lats, 95) or 0.0) * 1e3,
        "p99_ms": (_percentile(lats, 99) or 0.0) * 1e3,
        "rps": len(lats) / run["wall_s"] if run["wall_s"] else 0.0,
        "occupancy": rendered / padded if padded else 0.0,
        "shed": batcher.n_shed - before["shed"],
        "timeouts": batcher.n_timeouts - before["timeouts"],
        "n_batches": batcher.n_batches - before["batches"],
        "compiles_warmup": engine.warmup_compiles,
        "compiles_steady": compiles_steady,
        "warmup_s": warmup_s,
        "backend": args.backend,
        "buckets": list(engine.buckets),
        "max_batch_rays": args.max_batch_rays,
        "max_delay_ms": args.max_delay_ms,
        "rate": args.rate if mode == "open" else None,
        "seed": args.seed,
    }


def _stage_summary(spans: list[dict]) -> dict:
    """Per-stage latency percentiles and the stage-sum / wall ratio from
    the span rows a run's tracer sink collected.

    ``stage_wall_ratio`` averages, over requests with a ``bench.request``
    root span (closed loop), the trace's summed stage durations divided
    by the root's duration — the acceptance check that the queue →
    acquire → dispatch → device → scatter taxonomy accounts for the
    request's wall time instead of leaving dark gaps. ``scene.load`` is
    excluded from the sum (it nests inside ``scene.acquire``, which is
    already counted). Field names use the ``stage_`` prefix because a
    bare ``stage`` key would reclassify the row into the per-stage bench
    family (obs/schema.py bench_family is first-match)."""
    by_stage: dict[str, list[float]] = {}
    stage_sum: dict[str, float] = {}
    roots: dict[str, float] = {}
    for s in spans:
        st = s.get("stage")
        tid = s.get("trace_id")
        if st:
            by_stage.setdefault(st, []).append(float(s["dur_s"]))
            if s.get("name") != "scene.load":
                stage_sum[tid] = stage_sum.get(tid, 0.0) + float(s["dur_s"])
        elif s.get("name") == "bench.request":
            roots[tid] = float(s["dur_s"])
    out: dict = {}
    for st, durs in sorted(by_stage.items()):
        out[f"stage_{st}_p50_ms"] = (_percentile(durs, 50) or 0.0) * 1e3
        out[f"stage_{st}_p95_ms"] = (_percentile(durs, 95) or 0.0) * 1e3
    ratios = [stage_sum.get(t, 0.0) / d for t, d in roots.items() if d > 0]
    out["stage_wall_ratio"] = (sum(ratios) / len(ratios)) if ratios else None
    return out


# -- replica scale-out mode (--replicas N, docs/scaleout.md) -----------------


def _build_scale_shared(args):
    """(cfg, network, params, grid, bbox): everything replicas share.

    The cfg routes every replica's AOT registry at the SAME artifact dir
    (``compile.dir``) — replica 0 compiles and serializes, every later
    spawn deserializes and boots with zero builds (``warm_source ==
    "disk"``), which is the capacity-in-seconds story the row gates on."""
    import numpy as np

    import jax

    from nerf_replication_tpu.datasets.procedural import generate_scene
    from nerf_replication_tpu.models import init_params_for, make_network
    from nerf_replication_tpu.obs import init_run

    scene_root = os.path.join(args.workdir, "scene")
    if not os.path.exists(os.path.join(scene_root, "transforms_train.json")):
        generate_scene(scene_root, scene="procedural", H=16, W=16,
                       n_train=4, n_test=1)
    aot_dir = os.path.join(args.workdir, "aot_scale")
    cfg = _bench_cfg(scene_root, args,
                     extra=["compile.aot", "True",
                            "compile.artifacts", "True",
                            "compile.dir", aot_dir])
    network = make_network(cfg)
    params = init_params_for(cfg)(network, jax.random.PRNGKey(0))
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)
    grid = np.zeros((16, 16, 16), bool)
    grid[4:12, 4:12, 4:12] = True
    init_run(cfg, component="serve_bench",
             path=os.path.join(args.record_dir, "telemetry.jsonl"))
    return cfg, network, params, grid, bbox


def _make_replica_factory(cfg, shared, fleet: list, ledgers=None,
                          heat_window_s: float = 300.0):
    """spawn_fn(i) for the supervisor: one FULL stack per replica (own
    engine, tracker, AOT registry, batcher) so a kill or drain touches
    nothing the other replicas hold. With ``ledgers`` (a dict), each
    replica also gets its own :class:`~..obs.capacity.CapacityLedger`
    seeded with the shared params' HBM bytes — the per-scene heat /
    watermark accounting the end-of-run ``capacity_snapshot`` rows
    commit."""

    def spawn(i: int):
        import jax

        from nerf_replication_tpu.compile import AOTRegistry
        from nerf_replication_tpu.obs import CompileTracker
        from nerf_replication_tpu.obs.emit import config_hash
        from nerf_replication_tpu.scale import InProcessReplica
        from nerf_replication_tpu.serve import MicroBatcher, RenderEngine

        network, params, grid, bbox = shared
        tracker = CompileTracker()
        aot = AOTRegistry(cache_dir=cfg.compile.dir,
                          config_hash=config_hash(cfg), tracker=tracker,
                          artifacts=True)
        t0 = time.perf_counter()
        engine = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                              grid=grid, bbox=bbox, tracker=tracker,
                              aot=aot)
        capacity = None
        if ledgers is not None:
            from nerf_replication_tpu.obs import CapacityLedger

            capacity = CapacityLedger(replica=f"replica{i}",
                                      window_s=heat_window_s)
            # the shared params are this replica's HBM residency; the
            # grid/bbox ride along in the same watermark
            capacity.note_residency(
                sum(int(leaf.nbytes) for leaf in jax.tree.leaves(params))
                + int(grid.nbytes) + int(bbox.nbytes), 0)
            ledgers[f"replica{i}"] = capacity
        replica = InProcessReplica(f"replica{i}", engine,
                                   MicroBatcher(engine), capacity=capacity)
        replica.boot_s = time.perf_counter() - t0
        fleet.append(replica)
        print(f"  replica{i}: warm_source={replica.warm_source} "
              f"compiles={replica.warm_compiles} "
              f"boot={replica.boot_s:.2f}s")
        return replica

    return spawn


def _drive_window(router, rng, rate: float, window_s: float, slo_s: float,
                  args) -> dict:
    """One open-loop observation window through the front door.

    Requests arrive on a fixed-rate pacer regardless of completions;
    completion times come from polling ``done()`` so a backlogged future
    is measured when IT finishes, not when the harvest loop reaches it.
    Attainment = completed-within-SLO / offered (a shed request — no
    replica accepting — is a miss by definition)."""
    import numpy as np

    from nerf_replication_tpu.scale import NoReplicaAvailableError

    interval = 1.0 / max(rate, 1e-6)
    pending: list = []   # (t_submit, future)
    lats: list = []
    shed = failed = 0
    t_start = time.perf_counter()
    next_t = t_start

    def _harvest(now: float) -> None:
        nonlocal failed
        still = []
        for t0, f in pending:
            if f.done():
                try:
                    f.result(timeout=0)
                    lats.append(now - t0)
                except Exception:
                    failed += 1
            else:
                still.append((t0, f))
        pending[:] = still

    while True:
        now = time.perf_counter()
        if now - t_start >= window_s:
            break
        if now < next_t:
            _harvest(now)
            time.sleep(min(next_t - now, 0.005))
            continue
        n = int(rng.integers(args.min_rays, args.max_rays + 1))
        d = np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.15, (n, 3))
        rays = np.concatenate(
            [np.tile([0.0, 0.0, 4.0], (n, 1)), d], -1).astype(np.float32)
        try:
            pending.append((time.perf_counter(),
                            router.submit(rays, NEAR, FAR)))
        except NoReplicaAvailableError:
            shed += 1
        next_t += interval
    # grace: let the window's backlog land (bounded — an overloaded
    # window reports its misses instead of stalling the bench)
    grace_end = time.perf_counter() + max(2.0 * slo_s, 0.5)
    while pending and time.perf_counter() < grace_end:
        _harvest(time.perf_counter())
        time.sleep(0.002)
    n_late = len(pending)  # never completed inside window + grace: misses
    offered = len(lats) + failed + shed + n_late
    within = sum(1 for l in lats if l <= slo_s)
    return {
        "offered": offered,
        "done": len(lats),
        "within_slo": within,
        "shed": shed,
        "failed": failed,
        "late": n_late,
        "attainment": (within / offered) if offered else None,
        "p95_ms": (_percentile(lats, 95) or 0.0) * 1e3,
    }


def _run_scale(args) -> tuple[dict, bool]:
    """The full scale-out/scale-in cycle; returns (row, failed).

    Phase 1 (spike): ``--rate`` arrivals against ONE replica — attainment
    drops, the supervisor spawns warm-from-disk capacity. Phase 2
    (sustain): the spike subsides to ``--sustain-rate``; sustained
    attainment walks the in-streak until the supervisor drains the extra
    replica back out. The row gates on the cycle actually happening:
    >=1 scale-out, >=1 scale-in, fresh replicas warm from disk with zero
    builds, zero drain failures, zero steady-state recompiles — and the
    PR 16 ops-intelligence contract: the spike PAGES a fast-window
    burn-rate alert, the page clears by the end of the sustain phase at
    <= 5% engine overhead, the paged incident correlates the cycle, and
    every replica commits a ``capacity_snapshot`` row."""
    import numpy as np

    from nerf_replication_tpu.obs import (
        AlertEngine,
        AlertOptions,
        IncidentManager,
    )
    from nerf_replication_tpu.resil.flight import (
        add_dump_listener,
        remove_dump_listener,
    )
    from nerf_replication_tpu.scale import (
        FleetMetricsAggregator,
        Router,
        ScaleOptions,
        Supervisor,
    )

    cfg, network, params, grid, bbox = _build_scale_shared(args)
    fleet: list = []
    ledgers: dict = {}
    spawn = _make_replica_factory(cfg, (network, params, grid, bbox), fleet,
                                  ledgers=ledgers,
                                  heat_window_s=max(4.0 * args.window_s, 5.0))
    opts = ScaleOptions(
        min_replicas=1, max_replicas=max(2, args.replicas),
        out_below=0.90, in_above=0.95, deny_above=1.0,
        out_windows=2, in_windows=3,
        cooldown_out_s=args.window_s, cooldown_in_s=args.window_s,
        drain_timeout_s=60.0,
    )
    router = Router(heartbeat_timeout_s=10.0, clock=time.monotonic)
    slo_s = args.slo_ms / 1e3
    # the supervisor reads the SAME merged signal GET /fleet/metrics
    # shows the operator, and cites it: every out/in decision row carries
    # the aggregator's attainment window + SLO-miss exemplar trace ids
    agg = FleetMetricsAggregator(router, slo_target_s=slo_s)
    # burn-rate alerting scaled to the bench's windows (production runs
    # 5m/1h; here one --window-s observation IS the fast-short window).
    # NOT row-tapped: the engine sees only the supervisor's fleet-merged
    # observe_window feed, so attainment isn't double counted
    alerts = AlertEngine(AlertOptions(
        fast_short_s=args.window_s, fast_long_s=4.0 * args.window_s,
        slow_short_s=2.0 * args.window_s, slow_long_s=8.0 * args.window_s,
        clear_hold_s=0.0,
    ), slo_target_s=slo_s)
    incidents = IncidentManager(args.record_dir).attach()
    alerts.add_listener(incidents.on_alert)
    add_dump_listener(incidents.on_flight_dump)
    page_names = {"slo_burn_page", "deny_burn_page", "breaker_open"}
    sup = Supervisor(router, spawn, options=opts,
                     evidence_source=agg, slo_target_s=slo_s,
                     alerts=alerts)
    print(f"scale: booting replica 0 (cold — compiles + serializes to "
          f"{cfg.compile.dir})")
    sup.ensure_min()
    # prime the delta baseline: the process registry is cumulative across
    # tracing arms, and the first window must not inherit earlier arms
    agg.window()
    sustain_rate = args.sustain_rate or max(1.0, args.rate / 4.0)
    rng = np.random.default_rng(args.seed)
    windows: list = []
    actions: list = []
    first_out_i = None
    spike_paged = False
    t_cycle = time.perf_counter()
    phases = [("spike", args.rate, args.spike_windows),
              ("sustain", sustain_rate, args.sustain_windows)]
    for phase, rate, n_windows in phases:
        for _ in range(n_windows):
            router.sweep()
            w = _drive_window(router, rng, rate, args.window_s, slo_s, args)
            action = sup.step_from_fleet(agg)
            actions.append(action)
            if action == "out" and first_out_i is None:
                first_out_i = len(windows)
            if phase == "spike" and not spike_paged:
                spike_paged = bool(page_names & set(alerts.active()))
            w.update(phase=phase, rate=rate, action=action,
                     n_ready=router.n_ready())
            windows.append(w)
            att = w["attainment"]
            print(f"  [{phase}] offered={w['offered']} "
                  f"attainment={'-' if att is None else f'{att:.3f}'} "
                  f"p95={w['p95_ms']:.0f}ms shed={w['shed']} "
                  f"late={w['late']} -> {action} "
                  f"(replicas={w['n_ready']}, "
                  f"alerts={alerts.active() or '-'})")
    wall_s = time.perf_counter() - t_cycle
    # retire whatever still serves; spawned-but-drained batchers are done
    for r in fleet:
        if r.state in ("starting", "ready"):
            r.drain(timeout_s=30.0)
    # final ops pass: the page must have CLEARED on the fast window now
    # that sustain-phase attainment recovered; the incident the page
    # opened resolves with its full timeline (the out/in decisions and
    # their exemplar trace ids land in the re-assembled dump); every
    # replica commits its capacity_snapshot row
    alerts.evaluate()
    cleared_at_end = not (page_names & set(alerts.active()))
    incidents.resolve_open("bench cycle complete; attainment recovered")
    remove_dump_listener(incidents.on_flight_dump)
    incidents.detach()
    alerts.remove_listener(incidents.on_alert)
    capacity_snaps = [lg.snapshot() for lg in ledgers.values()]
    alert_overhead_pct = (alerts.self_s / wall_s * 100.0) if wall_s else 0.0
    incidents_linked = sum(1 for i in incidents.incidents if i["trace_ids"])
    compiles_steady = sum(
        int(r.engine.tracker.total_compiles()) - r.warm_compiles
        for r in fleet
    )
    fresh = fleet[1:]
    spike_atts = [w["attainment"] for w in windows
                  if w["phase"] == "spike" and w["attainment"] is not None]
    post_atts = ([] if first_out_i is None else
                 [w["attainment"] for w in windows[first_out_i + 1:]
                  if w["attainment"] is not None])
    acted = [d for d in sup.decisions if d["action"] in ("out", "in")]
    with_ev = [d for d in acted
               if (d.get("evidence") or {}).get("exemplar_trace_ids")]
    done_total = sum(w["done"] for w in windows)
    row = {
        "scale_mode": "open_loop",
        "replicas_peak": max(w["n_ready"] for w in windows),
        "attainment_low": min(spike_atts) if spike_atts else None,
        "attainment_recovered": max(post_atts) if post_atts else None,
        "scale_outs": actions.count("out"),
        "scale_ins": actions.count("in"),
        "replaces": actions.count("replace"),
        "drain_failures": sup.drain_failures,
        "n_replicas_spawned": len(fleet),
        "warm_source_first": fleet[0].warm_source,
        "warm_source_fresh": sorted({r.warm_source for r in fresh}),
        "fresh_compiles": sum(r.warm_compiles for r in fresh),
        "first_boot_s": round(fleet[0].boot_s, 3),
        "fresh_boot_s": (round(max(r.boot_s for r in fresh), 3)
                         if fresh else None),
        "compiles_steady": compiles_steady,
        "n_requests": sum(w["offered"] for w in windows),
        "n_shed": sum(w["shed"] for w in windows),
        "n_failed": sum(w["failed"] for w in windows),
        "rps": done_total / wall_s if wall_s else 0.0,
        "actions_with_evidence": len(with_ev),
        "actions_evidence_free": len(acted) - len(with_ev),
        "alerts_fired": sum(1 for t in alerts.transitions
                            if t["state"] == "firing"),
        "alerts_cleared": sum(1 for t in alerts.transitions
                              if t["state"] == "resolved"),
        "alert_page_during_spike": int(spike_paged),
        "alert_cleared_at_end": int(cleared_at_end),
        "alert_overhead_pct": round(alert_overhead_pct, 3),
        "n_incidents": len(incidents.incidents),
        "incidents_linked": incidents_linked,
        "capacity_snapshots": len(capacity_snaps),
        "fleet_scrape_rounds": agg.stats()["n_scrape_rounds"],
        "slo_ms": args.slo_ms,
        "window_s": args.window_s,
        "rate_spike": args.rate,
        "rate_sustain": sustain_rate,
        "windows": [
            {k: w[k] for k in ("phase", "attainment", "n_ready", "action",
                               "offered", "shed", "late")}
            for w in windows
        ],
        "backend": args.backend,
        "seed": args.seed,
    }
    failed = False
    if row["scale_outs"] < 1 or row["scale_ins"] < 1:
        print("WARNING: the run never completed a scale-out/scale-in cycle")
        failed = True
    if fresh and (row["warm_source_fresh"] != ["disk"]
                  or row["fresh_compiles"] != 0):
        print("WARNING: a fresh replica compiled instead of warm-starting "
              f"from disk (sources {row['warm_source_fresh']}, "
              f"{row['fresh_compiles']} builds)")
        failed = True
    if row["drain_failures"]:
        print(f"WARNING: drain-before-retire failed "
              f"{row['drain_failures']} in-flight requests")
        failed = True
    if compiles_steady:
        print(f"WARNING: {compiles_steady} steady-state recompiles across "
              "the replica fleet")
        failed = True
    if (row["attainment_low"] is not None
            and row["attainment_recovered"] is not None
            and row["attainment_recovered"] <= row["attainment_low"]):
        print("WARNING: attainment never recovered after scale-out")
        failed = True
    if not spike_paged:
        print("WARNING: the spike never paged a fast-window burn alert")
        failed = True
    if not cleared_at_end:
        print("WARNING: a page-severity alert was still firing after the "
              "sustain phase")
        failed = True
    if alert_overhead_pct > 5.0:
        print(f"WARNING: alert-engine overhead {alert_overhead_pct:.2f}% "
              "exceeds the 5% budget")
        failed = True
    if len(capacity_snaps) != len(fleet):
        print(f"WARNING: {len(capacity_snaps)} capacity snapshots for "
              f"{len(fleet)} replicas")
        failed = True
    return row, failed


# -- placement-planned process fleet (--replicas N --processes) ---------------


def _write_placement_cfg(args, workroot: str, scene_root: str,
                         store_dir: str) -> str:
    """A self-contained child config at ``workroot/serve_cfg.yaml``.

    ``serve.py`` children get no CLI opts beyond ``--cfg_file/--host/
    --port``, so everything the in-process bench passes as overrides
    must live in the YAML itself (``parent_cfg`` inheritance pulls the
    lego schema). Parent and children ``make_cfg`` the SAME file, so
    ``config_hash`` — and with it the shared AOT artifact key — is
    identical across the fleet: the parent pre-boot pays the one
    compile, every child warms from disk."""
    import yaml

    doc = {
        "parent_cfg": os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        "task": "run",
        "scene": "procedural",
        "exp_name": "placement_bench",
        "train_dataset": {"data_root": scene_root, "H": 16, "W": 16},
        "test_dataset": {"data_root": scene_root, "H": 16, "W": 16},
        "task_arg": {
            "N_samples": 24,
            "N_importance": 24,
            "render_step_size": 0.25,
            "max_march_samples": 16,
            "march_chunk_size": int(args.chunk),
        },
        "network": {
            "nerf": {"W": 64, "D": 3, "skips": [1]},
            "xyz_encoder": {"freq": 6},
            "dir_encoder": {"freq": 2},
        },
        "serve": {
            "buckets": [int(b) for b in args.buckets],
            "max_batch_rays": int(args.max_batch_rays),
            "max_delay_ms": float(args.max_delay_ms),
            "request_timeout_s": 30.0,
            "shed_queue_depths": [int(d) for d in args.shed_depths],
        },
        "record_dir": os.path.join(workroot, "record"),
        "compile": {"aot": True, "artifacts": True,
                    "dir": os.path.join(workroot, "aot")},
        # every child runs the residency LADDER over the shared sharded
        # store: HBM budget + host staging tier, checksums verified —
        # the byte watermarks/budgets ride its /healthz replica block
        # into the planner's residency view
        "fleet": {"store_dir": store_dir, "hbm_budget_mb": 8.0,
                  "staging_mb": 8.0, "verify_checksums": True},
        # cheap children: placement is the parent-side story being
        # priced; the child-side obs loops are covered by their own arms
        "obs": {"trace": False, "alerts": {"enabled": False}},
    }
    path = os.path.join(workroot, "serve_cfg.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(doc, f, sort_keys=True)
    return path


def _build_placement_store(cfg, store_dir: str, n_scenes: int):
    """(scene_ids, scene_bytes): a sharded SceneStore of REAL orbax
    checkpoints (same architecture, per-scene seeds) every child's
    checkpoint_loader can page in — the bench exercises the actual
    disk -> staging -> HBM path, not an in-memory fake."""
    import jax

    from nerf_replication_tpu.fleet import (
        SceneRecord,
        SceneRegistry,
        write_sharded,
    )
    from nerf_replication_tpu.models import make_network
    from nerf_replication_tpu.resil import write_tree_checksum
    from nerf_replication_tpu.train import make_train_state
    from nerf_replication_tpu.train.checkpoint import save_model

    network = make_network(cfg)
    records, scene_bytes = [], 0
    for i in range(n_scenes):
        sid = f"scene{i:02d}"
        state, _ = make_train_state(cfg, network,
                                    jax.random.PRNGKey(100 + i))
        ckpt = os.path.join(store_dir, "ckpt", sid)
        save_model(ckpt, state, 0, None, latest=True)
        write_tree_checksum(ckpt)
        scene_bytes = sum(int(a.nbytes)
                          for a in jax.tree.leaves(state.params))
        records.append(SceneRecord(sid, checkpoint=ckpt))
    write_sharded(SceneRegistry(records), store_dir)
    return [r.scene_id for r in records], scene_bytes


def _run_placement(args) -> tuple[dict, bool]:
    """The placement-planned REAL process fleet; returns (row, failed).

    Boot: parent pre-compiles + serializes the shared artifacts, the
    launcher spawns ``--replicas`` serve.py children (all must warm from
    disk with zero builds). Heat: routed pose traffic makes one scene
    hot; the planner must replicate it ``hot_width``-wide under the
    ladder byte budgets, with lazy remote prefetches realized by aiming
    one request at each planned-but-not-resident pair. Repair: SIGKILL a
    hot-scene child — the router fails over (zero failed requests), the
    supervisor 1:1-replaces through the launcher, the replan restores
    the width. One ``placement_mode`` row summarizes the run."""
    import numpy as np

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.datasets.procedural import generate_scene
    from nerf_replication_tpu.fleet import SceneStore
    from nerf_replication_tpu.obs import CapacityLedger, init_run
    from nerf_replication_tpu.scale import (
        PlacementExecutor,
        PlacementOptions,
        PlacementPlanner,
        ProcessLauncher,
        Router,
        ScaleOptions,
        Supervisor,
    )
    from nerf_replication_tpu.serve import engine_from_cfg

    n = max(2, args.replicas)
    workroot = os.path.join(args.workdir, "placement")
    scene_root = os.path.join(args.workdir, "scene")
    if not os.path.exists(os.path.join(scene_root, "transforms_train.json")):
        generate_scene(scene_root, scene="procedural", H=16, W=16,
                       n_train=4, n_test=1)
    os.makedirs(workroot, exist_ok=True)
    store_dir = os.path.join(workroot, "scenes")
    cfg_path = _write_placement_cfg(args, workroot, scene_root, store_dir)
    cfg = make_cfg(cfg_path, default_task="run")
    init_run(cfg, component="serve_bench",
             path=os.path.join(args.record_dir, "telemetry.jsonl"))
    scene_ids, scene_bytes = _build_placement_store(cfg, store_dir, 3)
    hot, cold = scene_ids[0], scene_ids[1:]

    # parent pre-boot: pay the one cold compile and serialize every
    # executable into the shared artifact dir — the children's all-disk
    # warm-start is the first thing the row gates on
    print(f"placement: pre-booting parent engine (cold — serializes to "
          f"{cfg.compile.dir})")
    t0 = time.perf_counter()
    engine_from_cfg(cfg, cfg_file=cfg_path)
    parent_build_s = time.perf_counter() - t0

    slo_s = args.slo_ms / 1e3
    # window wide enough to cover the whole heat phase: the planner sees
    # the full 6:1 hot/cold request ratio, not a timing-dependent slice
    ledger = CapacityLedger(replica="router", window_s=600.0)
    catalog = SceneStore(store_dir)

    def heat_view() -> dict:
        """Ledger view with rates normalized to the peak scene (peak → 1.0).

        Absolute req/s depends on how fast this host renders; the 6:1
        hot/cold request ratio is fixed by construction, so thresholding
        the *relative* rate keeps the hot/cold split deterministic."""
        scenes = ledger.view().get("scenes", {})
        peak = max((s.get("requests_per_s", 0.0) for s in scenes.values()),
                   default=0.0)
        if peak <= 0.0:
            return {"scenes": {}}
        return {"scenes": {
            sid: {"requests_per_s": s.get("requests_per_s", 0.0) / peak}
            for sid, s in scenes.items()}}

    popt = PlacementOptions(
        enabled=True, hot_width=min(2, n), max_width=n,
        # normalized heat: hot scene sits at 1.0, cold at ~1/6 — threshold
        # between them; width_rps huge pins hot width at hot_width
        hot_rps=0.5, width_rps=1e9,
        replan_every_s=0.0, max_moves_per_step=16,
    )
    planner = PlacementPlanner(catalog, options=popt, heat_fn=heat_view,
                               scene_bytes_fn=lambda sid: scene_bytes)
    executor = PlacementExecutor()  # remote children: prefetches are lazy
    router = Router(heartbeat_timeout_s=max(2.0, args.window_s))
    router.set_planner(planner)
    base_platform = (args.backend or "").split(":")[0]
    launcher = ProcessLauncher(
        cfg_path,
        env={"JAX_PLATFORMS": base_platform} if base_platform else {},
        ready_timeout_s=600.0, healthz_ttl_s=0.2,
    )
    opts = ScaleOptions(
        min_replicas=n, max_replicas=n,  # placement run, not autoscaling
        cooldown_out_s=3600.0, cooldown_in_s=3600.0,
        drain_timeout_s=60.0, placement=popt,
    )
    sup = Supervisor(router, launcher, options=opts, slo_target_s=slo_s,
                     planner=planner, placement_executor=executor)
    rng = np.random.default_rng(args.seed)
    rays_per_req = 16 * 16  # the children's dataset camera
    n_requests = n_failed = 0

    def one(sid: str, replica=None) -> bool:
        """One pose request: through the router (counted into the heat
        ledger), or aimed at one replica (plan realization)."""
        nonlocal n_requests, n_failed
        body = {"scene": sid, "theta": float(rng.uniform(0.0, 360.0)),
                "phi": -30.0, "radius": 4.0}
        try:
            if replica is None:
                router.render(body, timeout_s=30.0)
                ledger.note_request(sid, rays_per_req)
            else:
                replica.render(body, timeout_s=30.0)
            n_requests += 1
            return True
        # graftlint: ok(swallow: counted failure; the kill_repair/n_failed gates below read it)
        except Exception as exc:
            n_failed += 1
            print(f"  request for {sid} failed: "
                  f"{type(exc).__name__}: {exc}")
            return False

    def swept_view() -> dict:
        router.sweep()
        return router.residency_view()

    def holders_of(view: dict, sid: str) -> list:
        return [rid for rid in sorted(view)
                if sid in view[rid]["scenes"] or sid in view[rid]["staging"]]

    def realize_plan(view: dict) -> int:
        """Aim one request at every planned-but-not-resident pair.

        Under closed-loop routing the affinity holder wins WITHIN the
        planned group, so a remote lazy prefetch only materializes when
        traffic actually reaches the planned replica — this is that
        traffic (what plan-steered spillover does at scale)."""
        by_id = {r.replica_id: r for r in router.replicas()}
        assignments = (planner.current.assignments
                       if planner.current is not None else {})
        warmed = 0
        for sid, rids in sorted(assignments.items()):
            for rid in rids:
                st = view.get(rid)
                if st is None or rid not in by_id:
                    continue
                if sid in st["scenes"] or sid in st["staging"]:
                    continue
                if one(sid, replica=by_id[rid]):
                    warmed += 1
        return warmed

    print(f"placement: spawning {n} serve.py children "
          f"(warm from {cfg.compile.dir})")
    t0 = time.perf_counter()
    sup.ensure_min()
    fleet_boot_s = time.perf_counter() - t0
    warm_sources: dict = {}
    for r in router.replicas():
        b = r.heartbeat()
        warm_sources[r.replica_id] = b.get("warm_source")
        print(f"  {r.replica_id}: warm_source={b.get('warm_source')} "
              f"compiles={b.get('total_compiles')} port={r.port}")

    # -- heat phase: make scene00 hot, let the plan widen + realize it
    hot_per_round, heat_rounds = 6, 6
    t_heat = time.perf_counter()
    realized_convergence_s = None
    warm_realizations = 0
    for rnd in range(heat_rounds):
        t_r = time.perf_counter()
        for _ in range(hot_per_round):
            one(hot)
        for sid in cold:
            one(sid)
        router.sweep()
        sup.step(1.0, 0.0)  # healthy window; the placement tick is the point
        warm_realizations += realize_plan(swept_view())
        view = swept_view()
        width = len(holders_of(view, hot))
        plan = planner.current
        print(f"  [heat {rnd}] plan v{0 if plan is None else plan.version} "
              f"hot_width={width}/{popt.hot_width} "
              f"pending_moves={len(planner.pending)}")
        if (realized_convergence_s is None and plan is not None
                and plan.assignments and not planner.pending
                and width >= popt.hot_width
                and all(rid in holders_of(view, sid)
                        for sid, rids in plan.assignments.items()
                        for rid in rids)):
            realized_convergence_s = time.perf_counter() - t_heat
        # steady round cadence; heat_view() normalizes the ledger so the
        # 6/round hot vs 1/round cold split lands at 1.0 vs ~0.17
        dt = time.perf_counter() - t_r
        if dt < args.window_s:
            time.sleep(args.window_s - dt)
    hot_rate = float(ledger.view().get("scenes", {})
                     .get(hot, {}).get("requests_per_s", 0.0))

    # -- kill-repair phase: SIGKILL a hot-scene child, prove failover +
    # 1:1 replacement + width restoration
    view = swept_view()
    by_id = {r.replica_id: r for r in router.replicas()}
    victim_id = next((rid for rid in holders_of(view, hot) if rid in by_id),
                     None)
    kill_failed = 0
    repair_s = None
    if victim_id is None:
        print("WARNING: no hot-scene holder to kill")
    else:
        print(f"  kill: SIGKILL {victim_id} (holds hot scene {hot})")
        t_kill = time.perf_counter()
        by_id[victim_id].kill()
        # outage traffic: the router must fail over to the surviving
        # planned holder — the contract is ZERO failed requests
        for _ in range(4):
            if not one(hot):
                kill_failed += 1
        sup.replace_dead()  # bury + respawn through the launcher, replan
        for _ in range(4):
            for _ in range(hot_per_round):
                one(hot)
            for sid in cold:
                one(sid)
            router.sweep()
            sup.step(1.0, 0.0)
            warm_realizations += realize_plan(swept_view())
            view = swept_view()
            if len(holders_of(view, hot)) >= popt.hot_width:
                repair_s = time.perf_counter() - t_kill
                break
            time.sleep(min(args.window_s, 1.0))

    # -- final child state, then teardown
    child_compiles_total = 0
    for r in router.replicas():
        if not r.accepting():
            continue
        try:
            b = r.heartbeat()
        # graftlint: ok(swallow: teardown snapshot; a just-died child is already visible in the width/warm gates)
        except Exception:
            continue
        warm_sources[r.replica_id] = b.get("warm_source")
        child_compiles_total += int(b.get("total_compiles", 0))
    view = swept_view()
    final_width = len(holders_of(view, hot))
    over_budget = sum(
        1 for st in view.values()
        if (st["hbm_budget_bytes"]
            and st["hbm_bytes"] > st["hbm_budget_bytes"])
        or (st["staging_budget_bytes"]
            and st["staging_bytes"] > st["staging_budget_bytes"]))
    drain_failures = 0
    for r in list(router.replicas()):
        if r.accepting():
            drain_failures += int(router.drain(r.replica_id, timeout_s=60.0))
    launcher.shutdown()
    ledger.snapshot()

    pstats = planner.stats()
    rstats = router.stats()
    counted = rstats["n_planned_hits"] + rstats["n_unplanned"]
    unplanned_share = (rstats["n_unplanned"] / counted) if counted else 0.0
    row = {
        "placement_mode": "process_fleet",
        "plan_version": pstats["version"],
        "n_plans": pstats["n_plans"],
        "hot_scene": hot,
        "hot_rps_measured": round(hot_rate, 3),
        "hot_width_target": popt.hot_width,
        "hot_width_achieved": final_width,
        "over_budget_replicas": over_budget,
        "unplanned_share": round(unplanned_share, 4),
        "planned_hits": rstats["n_planned_hits"],
        "unplanned": rstats["n_unplanned"],
        "n_scenes_catalog": len(scene_ids),
        "n_scenes_planned": pstats["n_assigned_scenes"],
        "moves_planned": pstats["n_moves_planned"],
        "moves_applied": pstats["moves_applied"],
        "moves_failed": pstats["n_failed_moves"],
        "moves_skipped": pstats["n_skipped_moves"],
        "n_convergences": pstats["n_convergences"],
        "realized_convergence_s": (
            None if realized_convergence_s is None
            else round(realized_convergence_s, 3)),
        "warm_realizations": warm_realizations,
        "kill_repair_failed": kill_failed,
        "kill_repair_s": None if repair_s is None else round(repair_s, 3),
        "n_replaced": sup.n_replaced,
        "children_spawned": launcher.n_spawned,
        "warm_sources": sorted({v for v in warm_sources.values() if v}),
        "child_compiles_total": child_compiles_total,
        "drain_failures": drain_failures,
        "parent_build_s": round(parent_build_s, 3),
        "fleet_boot_s": round(fleet_boot_s, 3),
        "n_requests": n_requests,
        "n_failed": n_failed,
        "scene_mb": round(scene_bytes / 2**20, 3),
        "replicas": n,
        "window_s": args.window_s,
        "backend": args.backend,
        "seed": args.seed,
    }
    failed = False
    if final_width < popt.hot_width:
        print(f"WARNING: hot scene {hot} ended {final_width}-wide; the "
              f"plan wants {popt.hot_width}")
        failed = True
    if over_budget:
        print(f"WARNING: {over_budget} replica(s) over their ladder "
              "byte budgets")
        failed = True
    if kill_failed:
        print(f"WARNING: {kill_failed} request(s) failed during the "
              "kill-repair window (failover should absorb all of them)")
        failed = True
    if n_failed > kill_failed:
        print(f"WARNING: {n_failed - kill_failed} request(s) failed "
              "outside the kill window")
        failed = True
    if sup.n_replaced < 1:
        print("WARNING: the killed child was never 1:1-replaced")
        failed = True
    if row["warm_sources"] != ["disk"]:
        print(f"WARNING: child warm sources {row['warm_sources']} "
              "(every child must warm from the shared artifact dir)")
        failed = True
    if child_compiles_total:
        print(f"WARNING: {child_compiles_total} compiles across the "
              "children (warm-start plus steady state must build nothing)")
        failed = True
    if pstats["n_convergences"] < 1 or realized_convergence_s is None:
        print("WARNING: the plan never converged (pending moves or "
              "unrealized assignments at end of heat phase)")
        failed = True
    if pstats["n_failed_moves"]:
        print(f"WARNING: {pstats['n_failed_moves']} placement move(s) "
              "failed")
        failed = True
    if drain_failures:
        print(f"WARNING: drain-before-retire failed {drain_failures} "
              "in-flight requests")
        failed = True
    return row, failed


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="serving-engine load generator")
    p.add_argument("--backend", default="cpu",
                   help="platform pin ('cpu', 'cpu:8', 'tpu'; '' = inherit)")
    p.add_argument("--mode", default="both",
                   choices=("closed", "open", "both"))
    p.add_argument("--requests", type=int, default=80)
    p.add_argument("--rate", type=float, default=100.0,
                   help="open-loop arrivals per second")
    p.add_argument("--min-rays", type=int, default=64)
    p.add_argument("--max-rays", type=int, default=2048)
    p.add_argument("--buckets", type=int, nargs="+", default=[512, 2048])
    p.add_argument("--chunk", type=int, default=256)
    p.add_argument("--max-batch-rays", type=int, default=4096)
    p.add_argument("--max-delay-ms", type=float, default=5.0)
    p.add_argument("--shed-depths", type=int, nargs="+", default=[8, 32, 96])
    p.add_argument("--workdir", default=os.path.join(_REPO, "data",
                                                     "serve_bench"))
    p.add_argument("--record-dir", default=os.path.join(_REPO, "data",
                                                        "record",
                                                        "serve_bench"))
    p.add_argument("--out", default=os.path.join(_REPO, "BENCH_SERVE.jsonl"))
    p.add_argument("--scenes", type=int, default=0,
                   help="N > 0: multi-tenant fleet mode over N synthetic "
                        "scenes (replaces closed/open modes)")
    p.add_argument("--churn", action="store_true",
                   help="shrink the HBM budget to ~half the fleet so "
                        "every scene cycle forces eviction/reload")
    p.add_argument("--run-len", type=int, default=4,
                   help="same-scene requests per run before switching")
    p.add_argument("--out-fleet",
                   default=os.path.join(_REPO, "BENCH_FLEET.jsonl"))
    p.add_argument("--tenants", type=int, default=0,
                   help="N > 0: multi-tenant QoS mode — one hot tenant "
                        "floods N-1 quiet tenants (replaces other modes)")
    p.add_argument("--hot-tenant-share", type=float, default=0.75,
                   help="fraction of OFFERED request volume from the hot "
                        "tenant in the contended phase")
    p.add_argument("--out-qos",
                   default=os.path.join(_REPO, "BENCH_QOS.jsonl"))
    p.add_argument("--replicas", type=int, default=0,
                   help="N > 0: replica scale-out mode — open-loop load "
                        "through the scale/ router across a full "
                        "scale-out/scale-in cycle, max N replicas "
                        "(replaces other modes; docs/scaleout.md)")
    p.add_argument("--processes", action="store_true",
                   help="with --replicas: spawn REAL serve.py child "
                        "processes via the ProcessLauncher and run the "
                        "placement-planned fleet arm (family "
                        "placement_mode; docs/scaleout.md)")
    p.add_argument("--window-s", type=float, default=2.0,
                   help="scale mode: observation-window length (one "
                        "supervisor decision per window)")
    p.add_argument("--slo-ms", type=float, default=400.0,
                   help="scale mode: per-request latency SLO the "
                        "attainment windows are scored against")
    p.add_argument("--spike-windows", type=int, default=4,
                   help="scale mode: overload windows at --rate")
    p.add_argument("--sustain-windows", type=int, default=6,
                   help="scale mode: post-spike windows at --sustain-rate")
    p.add_argument("--sustain-rate", type=float, default=0.0,
                   help="scale mode: arrivals/s after the spike "
                        "(0 = --rate / 4)")
    p.add_argument("--out-scale",
                   default=os.path.join(_REPO, "BENCH_SCALE.jsonl"))
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tracing", default="both",
                   choices=("both", "on", "off"),
                   help="span-tracing arms per mode: 'both' runs each "
                        "mode tracing-off then tracing-on and prices the "
                        "overhead (trace_overhead_pct)")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero if any post-warmup recompile happened")
    args = p.parse_args(argv)

    if args.backend:
        from nerf_replication_tpu.utils.platform import (
            force_platform,
            parse_platform_pin,
        )

        force_platform(*parse_platform_pin(args.backend))

    import numpy as np

    from nerf_replication_tpu.obs import (
        append_jsonl,
        configure_tracing,
        get_emitter,
        get_tracer,
    )

    if args.replicas > 0 and args.processes:
        # the REAL multi-process shape: serve.py children via the
        # ProcessLauncher, placement-planned routing. Single arm —
        # child-side tracing is priced by the in-process scale arms;
        # this one prices the plan (width/budget/repair contracts).
        configure_tracing(enabled=False)
        try:
            row, failed = _run_placement(args)
            append_jsonl(args.out_scale, row)
            print(
                f"placement: plan v{row['plan_version']} "
                f"({row['n_plans']} plans), hot "
                f"{row['hot_width_achieved']}/{row['hot_width_target']}-wide "
                f"@ {row['hot_rps_measured']} req/s, "
                f"over_budget={row['over_budget_replicas']}, "
                f"unplanned_share={row['unplanned_share']}, "
                f"moves={row['moves_applied']} "
                f"(failed={row['moves_failed']}), "
                f"converged={row['realized_convergence_s']}s, "
                f"kill_repair={row['kill_repair_s']}s "
                f"(failed_reqs={row['kill_repair_failed']}, "
                f"replaced={row['n_replaced']}), "
                f"warm={row['warm_sources']} "
                f"({row['child_compiles_total']} child builds, "
                f"boot {row['fleet_boot_s']}s vs parent "
                f"{row['parent_build_s']}s cold)"
            )
        finally:
            get_emitter().close()
        print(f"rows appended to {args.out_scale}; "
              f"telemetry in {args.record_dir}")
        return 1 if (failed and args.strict) else 0

    if args.replicas > 0:
        # tracing arms like the closed/open modes: the off arm prices raw
        # capacity, the on arm prices the fleet-tracing instrumentation
        # (trace_overhead_pct) and must link every capacity action to
        # exemplar evidence. The shared AOT dir persists across arms, so
        # only the first arm's replica 0 pays the cold compile.
        arms = {"both": (False, True), "off": (False,), "on": (True,)}[
            args.tracing]
        failed = False
        rps_off = None
        try:
            for traced in arms:
                configure_tracing(enabled=traced)
                spans: list = []
                if traced:
                    get_tracer().add_sink(spans.append)
                row, arm_failed = _run_scale(args)
                failed = failed or arm_failed
                row["tracing"] = int(traced)
                if traced:
                    row.update(_stage_summary(spans))
                    if rps_off:
                        row["trace_overhead_pct"] = (
                            (rps_off - row["rps"]) / rps_off * 100.0
                        )
                    if row["actions_evidence_free"]:
                        print(f"WARNING: {row['actions_evidence_free']} "
                              "capacity action(s) carried no exemplar "
                              "evidence with tracing on")
                        failed = True
                    if row["n_incidents"] and not row["incidents_linked"]:
                        print("WARNING: no incident links a "
                              "scale_decision exemplar trace id with "
                              "tracing on")
                        failed = True
                else:
                    rps_off = row["rps"]
                append_jsonl(args.out_scale, row)
                extra = ""
                if traced and row.get("trace_overhead_pct") is not None:
                    extra = (f" trace_overhead="
                             f"{row['trace_overhead_pct']:+.1f}%")
                print(
                    f"scale[tracing {'on' if traced else 'off'}]: "
                    f"peak={row['replicas_peak']} replicas, "
                    f"attainment {row['attainment_low']} -> "
                    f"{row['attainment_recovered']}, "
                    f"{row['scale_outs']} out / {row['scale_ins']} in, "
                    f"fresh warm={row['warm_source_fresh']} "
                    f"({row['fresh_compiles']} builds, "
                    f"{row['fresh_boot_s']}s boot vs "
                    f"{row['first_boot_s']}s cold), "
                    f"evidence={row['actions_with_evidence']}/"
                    f"{row['actions_with_evidence'] + row['actions_evidence_free']}, "
                    f"drain_failures={row['drain_failures']}, "
                    f"recompiles_steady={row['compiles_steady']}, "
                    f"alerts={row['alerts_fired']}/{row['alerts_cleared']} "
                    f"(page_in_spike={row['alert_page_during_spike']}, "
                    f"overhead={row['alert_overhead_pct']}%), "
                    f"incidents={row['n_incidents']} "
                    f"(linked={row['incidents_linked']}), "
                    f"capacity_snapshots={row['capacity_snapshots']}"
                    + extra
                )
        finally:
            configure_tracing(enabled=False)
            get_emitter().close()
        print(f"rows appended to {args.out_scale}; "
              f"telemetry in {args.record_dir}")
        return 1 if (failed and args.strict) else 0

    cfg, engine, batcher, warmup_s = _build_stack(args)
    print(f"engine warm: buckets {list(engine.buckets)}, "
          f"{engine.warmup_compiles} executables in {warmup_s:.1f}s")

    failed = False
    if args.tenants > 0:
        try:
            configure_tracing(enabled=False)
            qos, quiet_ids = _build_qos(args)
            batcher.qos = qos
            print(f"qos: 1 hot + {len(quiet_ids)} quiet tenants, "
                  f"hot share {args.hot_tenant_share:.2f} of offered load")
            steady_base = engine.tracker.total_compiles()
            solo = _run_quiet_solo(
                batcher, np.random.default_rng(args.seed), args,
                quiet_ids, args.requests,
            )
            cont = _run_quiet_contended(
                batcher, np.random.default_rng(args.seed), args,
                quiet_ids, args.requests,
            )
            residency, ladder_ids = _build_qos_ladder(engine, args)
            ladder = _time_ladder(residency, ladder_ids)
            compiles_steady = engine.tracker.total_compiles() - steady_base
            row = _qos_row(solo, cont, ladder, residency.stats(), engine,
                           batcher, args, compiles_steady)
            append_jsonl(args.out_qos, row)
            speedup = row["repromote_speedup"]
            print(
                f"qos[wfq]: quiet p95={row['quiet_p95_ms']:.1f}ms "
                f"(solo {row['quiet_solo_p95_ms']:.1f}ms) "
                f"hot {row['hot_done']}/{row['hot_submitted']} served, "
                f"{row['hot_denied']} denied; "
                f"ladder cold={row['cold_load_ms']:.1f}ms "
                f"repromote={row['repromote_ms']:.2f}ms "
                f"({speedup:.1f}x) "
                f"recompiles_after_warmup={compiles_steady}"
            )
            if row["quiet_p95_ms"] > 1.15 * row["quiet_solo_p95_ms"]:
                print("WARNING: quiet p95 drifted >15% over solo "
                      "(the hot flood leaked into quiet latency)")
                failed = True
            if speedup is None or speedup < 5.0:
                print("WARNING: staging re-promotion under 5x faster "
                      "than a cold disk load")
                failed = True
            if compiles_steady:
                print(f"WARNING: {compiles_steady} post-warmup recompiles "
                      "(tenant churn forced a build)")
                failed = True
        finally:
            configure_tracing(enabled=False)
            batcher.close()
            get_emitter().close()
        print(f"row appended to {args.out_qos}; "
              f"telemetry in {args.record_dir}")
        return 1 if (failed and args.strict) else 0

    if args.scenes > 0:
        try:
            residency, scene_ids = _build_fleet(engine, args)
            print(f"fleet: {args.scenes} scenes, budget "
                  f"{residency.budget_bytes / 2**20:.1f} MiB "
                  f"({'churn' if args.churn else 'fully resident'})")
            # fleet mode runs one arm: spans on unless --tracing off (the
            # acquire/load breakdown is the point of tracing churn)
            traced = args.tracing != "off"
            configure_tracing(enabled=traced)
            spans: list = []
            if traced:
                get_tracer().add_sink(spans.append)
            rng = np.random.default_rng(args.seed)
            steady_base = engine.tracker.total_compiles()
            run = _run_fleet(engine, batcher, residency, scene_ids, rng,
                             args)
            compiles_steady = engine.tracker.total_compiles() - steady_base
            row = _fleet_row(run, engine, residency, args, compiles_steady)
            row["tracing"] = int(traced)
            if traced:
                row.update(_stage_summary(spans))
            append_jsonl(args.out_fleet, row)
            print(
                f"fleet[{row['fleet_mode']}]: n={row['n_requests']} "
                f"same p95={row['p95_same_ms']:.1f}ms "
                f"switch p95={row['p95_switch_ms']:.1f}ms "
                f"evictions={row['evictions']} "
                f"prefetch_hit_rate={row['prefetch_hit_rate']:.2f} "
                f"recompiles_after_warmup={compiles_steady}"
            )
            if compiles_steady:
                print(f"WARNING: {compiles_steady} post-warmup recompiles "
                      "(a scene switch forced a build)")
                failed = True
        finally:
            configure_tracing(enabled=False)
            batcher.close()
            get_emitter().close()
        print(f"row appended to {args.out_fleet}; "
              f"telemetry in {args.record_dir}")
        return 1 if (failed and args.strict) else 0

    modes = ("closed", "open") if args.mode == "both" else (args.mode,)
    arms = {"both": (False, True), "off": (False,), "on": (True,)}[
        args.tracing]
    try:
        for mode in modes:
            rps_off = None
            for traced in arms:
                # configure_tracing swaps the global tracer, so the span
                # sink re-registers per arm and dies with it
                configure_tracing(enabled=traced)
                spans: list = []
                if traced:
                    get_tracer().add_sink(spans.append)
                rng = np.random.default_rng(args.seed)
                before = _snapshot(engine, batcher)
                steady_base = engine.tracker.total_compiles()
                run = (_run_closed if mode == "closed" else _run_open)(
                    batcher, rng, args
                )
                compiles_steady = (engine.tracker.total_compiles()
                                   - steady_base)
                row = _summary_row(mode, run, engine, batcher, args,
                                   compiles_steady, warmup_s, before)
                row["tracing"] = int(traced)
                if traced:
                    row.update(_stage_summary(spans))
                    if rps_off:
                        row["trace_overhead_pct"] = (
                            (rps_off - row["rps"]) / rps_off * 100.0
                        )
                else:
                    rps_off = row["rps"]
                append_jsonl(args.out, row)
                extra = ""
                if traced:
                    ratio = row.get("stage_wall_ratio")
                    over = row.get("trace_overhead_pct")
                    extra = (
                        (f" stage_wall_ratio={ratio:.2f}"
                         if ratio is not None else "")
                        + (f" trace_overhead={over:+.1f}%"
                           if over is not None else "")
                    )
                print(
                    f"{mode}[tracing {'on' if traced else 'off'}]: "
                    f"n={row['n_requests']} p50={row['p50_ms']:.1f}ms "
                    f"p95={row['p95_ms']:.1f}ms p99={row['p99_ms']:.1f}ms "
                    f"rps={row['rps']:.1f} occupancy={row['occupancy']:.2f} "
                    f"shed={row['shed']} timeouts={row['timeouts']} "
                    f"recompiles_after_warmup={compiles_steady}" + extra
                )
                if compiles_steady:
                    print(f"WARNING: {compiles_steady} post-warmup "
                          "recompiles (shape escaped the buckets)")
                    failed = True
    finally:
        configure_tracing(enabled=False)
        batcher.close()
        get_emitter().close()
    print(f"rows appended to {args.out}; telemetry in {args.record_dir}")
    return 1 if (failed and args.strict) else 0


if __name__ == "__main__":
    raise SystemExit(main())
