"""Equal-steps quality A/B: corner-shared hashgrid vs cell-packed layout.

The packed layout (models/encoding/packed_hash.py) trades corner sharing
for TPU-fast gathers — the field is piecewise-trilinear per cell. This
script measures what that trade costs in dB: both arms train the SAME
scene, seed, step count, and MLP; only the encoder type differs. Appends
one JSON line per arm to --out.

    python scripts/ab_encoder_quality.py --steps 400 [--H 100]
        [--force_platform cpu] [--out AB_ENCODER.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=400)
    p.add_argument("--H", type=int, default=100)
    p.add_argument("--views", type=int, default=30)
    p.add_argument("--test_views", type=int, default=2)
    p.add_argument("--n_rays", type=int, default=1024)
    p.add_argument("--scene_root", default="data/ab_encoder_scene")
    p.add_argument("--arms", nargs="+",
                   default=["lego_hash.yaml", "lego_hash_packed.yaml"])
    p.add_argument("--out", default="AB_ENCODER.jsonl")
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    p.add_argument("opts", nargs="*", default=[])
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import (
        enable_compilation_cache,
        setup_backend,
    )

    setup_backend(args.force_platform)
    enable_compilation_cache()

    import jax

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.datasets import make_dataset
    from nerf_replication_tpu.datasets.procedural import ensure_scene
    from nerf_replication_tpu.evaluators import make_evaluator
    from nerf_replication_tpu.models import make_network
    from nerf_replication_tpu.train import make_loss, make_train_state
    from nerf_replication_tpu.train.trainer import Trainer

    ensure_scene(args.scene_root, scene="procedural", H=args.H, W=args.H,
                 n_train=args.views, n_test=args.test_views)

    out_f = open(args.out, "a")
    for arm in args.arms:
        cfg = make_cfg(
            os.path.join(_REPO, "configs", "nerf", arm),
            [
                "scene", "procedural",
                "train_dataset.data_root", args.scene_root,
                "test_dataset.data_root", args.scene_root,
                "train_dataset.H", str(args.H), "train_dataset.W", str(args.H),
                "test_dataset.H", str(args.H), "test_dataset.W", str(args.H),
                "test_dataset.cams", "[0, -1, 1]",
                "task_arg.N_rays", str(args.n_rays),
                "task_arg.precrop_iters", "0",
                *args.opts,
            ],
        )
        network = make_network(cfg)
        loss = make_loss(cfg, network)
        evaluator = make_evaluator(cfg)
        trainer = Trainer(cfg, network, loss, evaluator)
        state, _ = make_train_state(cfg, network, jax.random.PRNGKey(0))
        train_ds = make_dataset(cfg, "train")
        test_ds = make_dataset(cfg, "test")
        bank = tuple(jax.device_put(a) for a in train_ds.ray_bank())
        key = jax.random.PRNGKey(1)

        t0 = time.time()
        for _ in range(args.steps):
            state, stats = trainer.step(state, bank[0], bank[1], key)
        jax.block_until_ready(stats)
        dt = time.time() - t0

        result = trainer.val(
            state, epoch=args.steps, test_dataset=test_ds,
            max_images=args.test_views,
        )
        rec = {
            "arm": arm,
            "steps": args.steps,
            "n_rays": args.n_rays,
            "H": args.H,
            "psnr": round(float(result.get("psnr", 0.0)), 3),
            "ssim": round(float(result.get("ssim", 0.0)), 4),
            "train_s": round(dt, 1),
            "ts": round(time.time(), 1),
        }
        print(json.dumps(rec), flush=True)
        out_f.write(json.dumps(rec) + "\n")
        out_f.flush()
    out_f.close()


if __name__ == "__main__":
    main()
