"""Promote the best measured sweep point into BENCH_DEFAULTS.json.

The driver runs a plain `python bench.py` (no envs) at round end; bench.py
reads BENCH_DEFAULTS.json as its fallback defaults, so promoting the winning
(n_rays, dtype, remat) from a sweep makes the driver's headline use the best
known config instead of the conservative 4096-ray default.

    python scripts/promote_bench_defaults.py BENCH_SWEEP_REMAT.jsonl \
        [more.jsonl ...] [--config lego.yaml]

Sweep files are append-only (a crash must not destroy prior records), so a
point may appear many times across runs; only the LAST record per sweep
point counts (the key tuple lives in ONE place —
nerf_replication_tpu/utils/sweeps.py — shared with bench.py's failure
diagnostics) — a re-measured point replaces its stale history instead of
a stale fast record winning forever. Error records are never promoted.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("sweeps", nargs="+", help="sweep .jsonl files to scan")
    p.add_argument("--config", default="lego.yaml",
                   help="only consider points measured on this config")
    p.add_argument("--out", default=os.path.join(_REPO, "BENCH_DEFAULTS.json"))
    args = p.parse_args(argv)

    # last record per sweep point wins (files are append-only across runs);
    # the recency rule lives once in utils/sweeps.py, shared with bench.py
    from nerf_replication_tpu.utils.sweeps import best_point

    best = best_point(args.sweeps, config=args.config)
    if best is None:
        print("promote: no valid points found; leaving defaults untouched")
        return 1

    defaults = {
        "n_rays": int(best["n_rays"]),
        "dtype": best.get("dtype", "bfloat16"),
        "remat": "true" if best.get("remat") else "false",
        "scan_steps": int(best.get("scan_steps", 1)),
        "grad_accum": int(best.get("grad_accum", 1)),
        # free-form cfg overrides (e.g. the fused Pallas trunk) travel
        # with the winning point so the driver's plain bench replays them
        "opts": best.get("opts", ""),
        "config": args.config,
        "measured_rays_per_sec": round(float(best["value"]), 1),
        "source": "scripts/promote_bench_defaults.py",
    }
    with open(args.out, "w") as f:
        json.dump(defaults, f, indent=1)
        f.write("\n")
    print(f"promote: wrote {args.out}: {json.dumps(defaults)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
