"""Cold-start benchmark: process start → first useful unit of work.

The round-5 numbers put 177 s of a 420 s NGP bench window in compile +
warm-up, and every process restart (battery stages, sweep points, serve
redeploys) re-paid it. This bench measures exactly that tax, end to end,
under a COLD vs WARM compile cache:

* ``train`` — fresh interpreter: config → network → datasets → AOT
  registry → **first optimizer step retired**. Wall clock starts before
  the first heavy import (the real "process start").
* ``serve`` — fresh interpreter: config → network → engine warm-up
  (every (bucket, family) executable built or deserialized) → **first
  render response**. The warm run must deserialize the whole executable
  inventory from the artifact store: ``total_compiles`` is asserted into
  the row from the engine's CompileTracker and is 0 on a true warm start.

Each child process prints one JSON result line; the parent wipes (cold)
or keeps (warm) the isolated cache dir between runs and appends one row
per (target, mode) to ``BENCH_COLDSTART.jsonl`` (family ``coldstart``;
``scripts/check_telemetry_schema.py`` validates it).

    python scripts/bench_cold_start.py                    # both targets
    python scripts/bench_cold_start.py --targets serve
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

NEAR, FAR = 2.0, 6.0
RESULT_MARK = "COLDSTART_RESULT "


def _train_cfg(args):
    """Flagship-shaped config, sized so compile dominates a cold start the
    way it does at bench scale (a toy MLP would hide the tax in imports)."""
    from nerf_replication_tpu.config import make_cfg

    return make_cfg(
        os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        [
            "scene", "procedural",
            "exp_name", "bench_cold_start",
            "train_dataset.data_root", args.scene_root,
            "test_dataset.data_root", args.scene_root,
            "train_dataset.H", str(args.H), "train_dataset.W", str(args.H),
            "test_dataset.H", str(args.H), "test_dataset.W", str(args.H),
            "task_arg.N_rays", str(args.n_rays),
            "task_arg.precrop_iters", "0",
            # wide network (the compile cost being measured) with a small
            # sample budget: on CPU a full lego step EXECUTES for ~50 s,
            # which would drown the compile tax the bench isolates
            "network.nerf.W", "448",
            "network.nerf.D", "10",
            "task_arg.N_samples", "24",
            "task_arg.N_importance", "24",
            "compile.dir", os.path.join(args.cache_dir, "aot"),
        ],
    )


def _serve_cfg(args):
    from nerf_replication_tpu.config import make_cfg

    return make_cfg(
        os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        [
            "scene", "procedural",
            "exp_name", "bench_cold_start",
            "train_dataset.data_root", args.scene_root,
            "test_dataset.data_root", args.scene_root,
            "task_arg.N_samples", "48",
            "task_arg.N_importance", "48",
            "task_arg.chunk_size", "1024",
            "serve.buckets", "[1024, 4096]",
            "serve.max_batch_rays", "4096",
            "compile.dir", os.path.join(args.cache_dir, "aot"),
        ],
    )


def _child_setup(args):
    """Backend + caches, called before anything can compile. Returns the
    perf-counter origin (taken before the heavy imports)."""
    from nerf_replication_tpu.utils.platform import (
        enable_compilation_cache,
        setup_backend,
    )

    setup_backend(args.force_platform)
    enable_compilation_cache(os.path.join(args.cache_dir, "xla"))
    import jax

    # persist EVERY executable: the default 0.5 s floor would silently
    # drop bench-scale programs from the cache and fake a cold warm-run
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def child_train(args) -> dict:
    t0 = time.perf_counter()
    _child_setup(args)
    import jax

    from nerf_replication_tpu.compile import registry_from_cfg
    from nerf_replication_tpu.datasets import make_dataset
    from nerf_replication_tpu.models import make_network
    from nerf_replication_tpu.train import make_loss, make_train_state
    from nerf_replication_tpu.train.trainer import Trainer

    cfg = _train_cfg(args)
    network = make_network(cfg)
    loss = make_loss(cfg, network)
    trainer = Trainer(cfg, network, loss, None)
    trainer.aot = registry_from_cfg(cfg, tracker=trainer.tracker)
    state, _ = make_train_state(cfg, network, jax.random.PRNGKey(0))
    train_ds = make_dataset(cfg, "train")
    bank = tuple(jax.device_put(a) for a in train_ds.ray_bank())
    base_key = jax.random.PRNGKey(1)
    trainer.aot_register_steps(state, bank, base_key)
    state, stats = trainer.step(state, bank[0], bank[1], base_key)
    jax.block_until_ready(stats)
    return {
        "wall_s": round(time.perf_counter() - t0, 3),
        "total_compiles": trainer.tracker.total_compiles(),
        "platform": jax.devices()[0].platform,
    }


def child_serve(args) -> dict:
    t0 = time.perf_counter()
    _child_setup(args)
    import numpy as np

    import jax

    from nerf_replication_tpu.compile import registry_from_cfg
    from nerf_replication_tpu.models import init_params_for, make_network
    from nerf_replication_tpu.obs import CompileTracker
    from nerf_replication_tpu.serve import RenderEngine

    cfg = _serve_cfg(args)
    network = make_network(cfg)
    # fresh-init weights: the cold-start question is about executables,
    # not checkpoints (engine_from_cfg additionally overlaps model I/O)
    params = init_params_for(cfg)(network, jax.random.PRNGKey(0))
    tracker = CompileTracker()
    aot = registry_from_cfg(cfg, tracker=tracker)
    engine = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                          tracker=tracker, aot=aot)
    rays = np.concatenate(
        [np.tile([0.0, 0.0, 4.0], (300, 1)),
         np.tile([0.0, 0.0, -1.0], (300, 1))], -1
    ).astype(np.float32)
    out = engine.render_request(rays, NEAR, FAR, emit=False)
    assert "rgb_map_f" in out or "rgb_map_c" in out
    stats = engine.stats()
    return {
        "wall_s": round(time.perf_counter() - t0, 3),
        "total_compiles": stats["total_compiles"],
        "warm_source": stats["warm_source"],
        "warmup_wall_s": stats["warmup_wall_s"],
        "n_executables": len(stats["buckets"]) * 3,
        "platform": jax.devices()[0].platform,
    }


def _spawn(target: str, mode: str, args) -> dict:
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child", target,
        "--cache-dir", args.cache_dir, "--scene-root", args.scene_root,
        "--H", str(args.H), "--n_rays", str(args.n_rays),
        "--force_platform", args.force_platform,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=_REPO)
    result = None
    for line in proc.stdout.splitlines():
        if line.startswith(RESULT_MARK):
            result = json.loads(line[len(RESULT_MARK):])
    if proc.returncode != 0 or result is None:
        tail = "\n".join((proc.stderr or proc.stdout).splitlines()[-15:])
        return {"coldstart": target, "mode": mode,
                "error": f"child failed rc={proc.returncode}: {tail}"}
    return {"coldstart": target, "mode": mode, **result}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="cold vs warm start benchmark")
    p.add_argument("--targets", nargs="+", default=["train", "serve"],
                   choices=("train", "serve"))
    p.add_argument("--H", type=int, default=64)
    p.add_argument("--n_rays", type=int, default=64)
    p.add_argument("--cache-dir", dest="cache_dir",
                   default=os.path.join(_REPO, "data", "bench_cold_start",
                                        "cache"))
    p.add_argument("--scene-root", dest="scene_root",
                   default=os.path.join(_REPO, "data", "bench_cold_start",
                                        "scene"))
    p.add_argument("--out", default=os.path.join(_REPO,
                                                 "BENCH_COLDSTART.jsonl"))
    p.add_argument("--force_platform",
                   default=os.environ.get("BENCH_FORCE_PLATFORM", "cpu"))
    p.add_argument("--child", default="", choices=("", "train", "serve"),
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.child:
        result = (child_train if args.child == "train" else child_serve)(args)
        print(RESULT_MARK + json.dumps(result), flush=True)
        return 0

    from nerf_replication_tpu.datasets.procedural import ensure_scene
    from nerf_replication_tpu.obs import append_jsonl, validate_bench_row

    # scene generated ONCE, outside every timed window — both modes load
    # the same files, so dataset I/O cancels out of the cold/warm delta
    ensure_scene(args.scene_root, scene="procedural", H=args.H, W=args.H,
                 n_train=8, n_test=1)

    rc = 0
    for target in args.targets:
        walls = {}
        for mode in ("cold", "warm"):
            if mode == "cold" and os.path.isdir(args.cache_dir):
                shutil.rmtree(args.cache_dir)
            row = _spawn(target, mode, args)
            errors = validate_bench_row(row)
            if errors:
                raise SystemExit(f"bench row failed schema check: {errors}")
            append_jsonl(args.out, row)
            print(json.dumps(row), flush=True)
            if "error" in row:
                rc = 1
            else:
                walls[mode] = row["wall_s"]
        if "cold" in walls and "warm" in walls and walls["warm"] > 0:
            print(f"{target}: cold {walls['cold']:.1f}s -> warm "
                  f"{walls['warm']:.1f}s "
                  f"({walls['cold'] / walls['warm']:.2f}x)", flush=True)
    print(f"rows appended to {args.out}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
