"""Profile the jitted train step: compiled cost analysis + device trace.

Prints (JSON lines) the XLA-compiled FLOPs/bytes estimates for one train
step and a derived MFU given the measured step time, then optionally writes
a `jax.profiler` trace for XProf/TensorBoard (VERDICT r1 #2's "profile with
device_trace and attack the top op").

    python scripts/profile_step.py [--n_rays 65536] [--remat true]
        [--dtype bfloat16] [--trace_dir /tmp/trace] [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--n_rays", type=int, default=65536)
    p.add_argument("--remat", default="true")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--config", default="lego.yaml")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--trace_dir", default="")
    p.add_argument("--ngp", action="store_true",
                   help="profile the NGP carved-march step instead of the "
                        "hierarchical trainer (grid pre-carved to "
                        "--occupancy; cost analysis is occupancy-"
                        "independent — static shapes)")
    p.add_argument("--occupancy", type=float, default=0.05)
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import setup_backend

    setup_backend(args.force_platform)

    import jax
    import jax.numpy as jnp

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.models.nerf.network import make_network
    from nerf_replication_tpu.train.loss import make_loss
    from nerf_replication_tpu.train.trainer import Trainer, make_train_state
    from nerf_replication_tpu.utils.profiling import device_trace

    cfg = make_cfg(
        os.path.join(_REPO, "configs", "nerf", args.config),
        [
            "task_arg.N_rays", str(args.n_rays),
            "task_arg.precrop_iters", "0",
            "precision.compute_dtype", args.dtype,
            "task_arg.remat", args.remat,
            *os.environ.get("BENCH_OPTS", "").split(),
        ],
    )
    network = make_network(cfg)
    key = jax.random.PRNGKey(0)
    k_init, k_bank, base_key = jax.random.split(key, 3)
    if args.ngp:
        from nerf_replication_tpu.train.ngp import make_ngp_trainer

        trainer = make_ngp_trainer(cfg, network)
        state, _ = trainer.make_state(k_init)
        # pre-carve: the carved executable's OPS are occupancy-independent
        # (static shapes), but a realistic grid keeps the timing honest
        occ_mask = jax.random.bernoulli(
            jax.random.PRNGKey(5), args.occupancy, state.grid_ema.shape
        )
        state = state.replace(
            grid_ema=jnp.where(occ_mask, 2.0 * trainer.threshold, 0.0)
        )
        # skip straight to the carved phase
        trainer._host_step = max(trainer.warmup_steps, int(state.step))
        trainer._last_occ = float(args.occupancy)
    else:
        loss = make_loss(cfg, network)
        trainer = Trainer(cfg, network, loss)
        state, _ = make_train_state(cfg, network, k_init)

    n_bank = 1 << 20
    k1, k2, k3 = jax.random.split(k_bank, 3)
    origins = jax.random.normal(k1, (n_bank, 3)) * 0.5 + jnp.asarray(
        [0.0, 0.0, -4.0]
    )
    dirs = jax.random.normal(k2, (n_bank, 3))
    dirs = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)
    bank_rays = jnp.concatenate([origins, dirs], -1).astype(jnp.float32)
    bank_rgbs = jax.random.uniform(k3, (n_bank, 3), jnp.float32)

    # compiled cost analysis (no execution needed beyond compile)
    if args.ngp:
        step_fn = trainer._jit_step(1, warm=False)
    else:
        step_fn = trainer._build_step(with_pool=False)
    compiled = step_fn.lower(state, bank_rays, bank_rgbs, base_key).compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    flops = float(ca.get("flops", 0.0))
    bytes_hbm = float(ca.get("bytes accessed", 0.0))
    mem = compiled.memory_analysis()
    rec = {
        "config": args.config, "ts": round(time.time(), 1),
        "n_rays": args.n_rays, "dtype": args.dtype, "remat": args.remat,
        "xla_flops_per_step": flops,
        "xla_gbytes_per_step": round(bytes_hbm / 2**30, 3),
        "flops_per_ray": round(flops / args.n_rays, 0) if flops else None,
        "temp_alloc_gb": round(
            getattr(mem, "temp_size_in_bytes", 0) / 2**30, 3
        ) if mem else None,
        "arithmetic_intensity": round(flops / bytes_hbm, 1)
        if bytes_hbm else None,
    }
    print(json.dumps(rec), flush=True)

    # measured step time against the SAME compiled executable (calling
    # step_fn would re-trace and pay the multi-minute compile a second time
    # — jit's dispatch cache doesn't see AOT lower().compile() results)
    state, stats = compiled(state, bank_rays, bank_rgbs, base_key)
    for _ in range(3):
        state, stats = compiled(state, bank_rays, bank_rgbs, base_key)
    jax.block_until_ready(stats)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, stats = compiled(state, bank_rays, bank_rgbs, base_key)
    jax.block_until_ready(stats)
    dt = (time.perf_counter() - t0) / args.steps
    peak_bf16 = 197e12  # TPU v5 lite bf16 peak (PERF.md)
    print(json.dumps({
        "config": args.config, "ts": round(time.time(), 1),
        "s_per_step": round(dt, 4),
        "rays_per_sec": round(args.n_rays / dt, 1),
        "mfu_vs_xla_flops": round(flops / dt / peak_bf16, 3) if flops else None,
        "hbm_gb_per_sec": round(bytes_hbm / dt / 2**30, 1)
        if bytes_hbm else None,
    }), flush=True)

    if args.trace_dir:
        with device_trace(args.trace_dir):
            for _ in range(3):
                state, stats = compiled(state, bank_rays, bank_rgbs, base_key)
            jax.block_until_ready(stats)
        print(json.dumps({"trace_dir": args.trace_dir}), flush=True)


if __name__ == "__main__":
    main()
