#!/bin/bash
# Round-5 chip battery — the performance-and-proof stages VERDICT r4 asks
# for, value-ordered. Run with the host core IDLE (concurrent CPU load
# inflates dispatch timings 3x — PERF.md round 4) and the tunnel CONFIRMED
# up (use tpu_battery.sh's watch loop or /tmp/tunnel_watch_r5.sh).
#
#   1. headline bench (driver insurance: the exact `python bench.py` the
#      driver runs at round end must produce a number NOW)
#   2. NGP A/B: std vs ngp vs ngp_packed (the round-5 packed sample
#      stream), r4's march budget (step 0.01, K 64), equal wall budget
#   3. packed refresh lever: ngp_packed with ngp_grid_update_every 64
#      (the per-step 131k-cell exploration refresh is the #2 roofline
#      term)
#   4. steady-state scale rows (warm executable, steps>=30, tagged) for
#      flagship + packed hash; grad_accum kills the 16k hash HBM cliff
#   5. NGP H=400 quality trail with the DECOUPLED eval march budget
#      (target: >=30 dB where r4 topped at 28.16)
#   6. std quality trail with the eval-fps shootout (accelerated must
#      beat chunked at equal PSNR on the trained net + carved grid)
#   7. hard-scene trail (thin fence + sub-voxel checker)
set -u
cd "$(dirname "$0")/.."
mkdir -p data/logs
log() { echo "[batteryR5 $(date +%H:%M:%S)] $*"; }
export BENCH_INIT_TOTAL_S=${BENCH_INIT_TOTAL_S:-420}

NGP_OPTS="task_arg.render_step_size 0.01 task_arg.max_march_samples 64 \
task_arg.scan_steps 8"

log "stage 1: headline bench (driver replay)"
# rows land in a BENCH_SWEEP*-globbed file so promote_bench_defaults and
# bench.py's failure diagnostics can see them (bench.py emits ts/config)
timeout 1800 python bench.py 2>data/logs/r5_bench.err \
  | tee -a BENCH_SWEEP_FUSED.jsonl | tail -1

log "stage 1b: fused Pallas trunk A/B at the headline shape"
# ops/fused_mlp.py — VMEM-resident MLP chain, backward recomputes in
# VMEM: the direct attack on the 48.8 GB/step activation traffic that
# closed the flagship at 48k rays/s. First Mosaic compile of the kernel
# happens here; a lowering failure is a RECORDED result, not a crash
# (bench.py emits its JSON failure line either way).
for tile in 512 1024; do
  BENCH_OPTS="network.nerf.fused_trunk true network.nerf.fused_tile $tile" \
  timeout 2400 python bench.py 2>data/logs/r5_bench_fused_$tile.err \
    | tee -a BENCH_SWEEP_FUSED.jsonl | tail -1
done
# promote whatever now wins (incl. a fused row's opts) into the
# defaults the driver's plain `python bench.py` replays at round end
python scripts/promote_bench_defaults.py BENCH_SWEEP*.jsonl \
  --config lego.yaml || true

log "stage 2: NGP A/B std vs ngp vs ngp_packed (420 s/arm)"
timeout 3600 python scripts/bench_ngp.py --seconds 420 \
  --config lego_hash_packed.yaml --arms std ngp ngp_packed \
  --out BENCH_NGP.jsonl $NGP_OPTS \
  2>data/logs/r5_ngp_ab.err | tail -4

log "stage 3: packed refresh lever (update_every 64)"
timeout 1800 python scripts/bench_ngp.py --seconds 420 \
  --config lego_hash_packed.yaml --arms ngp_packed \
  --out BENCH_NGP.jsonl $NGP_OPTS task_arg.ngp_grid_update_every 64 \
  2>data/logs/r5_ngp_refresh.err | tail -2

log "stage 3c: packed + bbox-clip + slow refresh (the combined levers)"
# per-ray clipping concentrates the SAME static S inside the bbox span,
# so step 0.015 here has ~the 0.01 unclipped in-bbox resolution with 33%
# fewer phase-1/sort rows; update_every 64 cuts the refresh 4x.
timeout 1800 python scripts/bench_ngp.py --seconds 420 \
  --config lego_hash_packed.yaml --arms ngp_packed \
  --out BENCH_NGP.jsonl task_arg.render_step_size 0.015 \
  task_arg.max_march_samples 64 task_arg.scan_steps 8 \
  task_arg.march_clip_bbox true task_arg.ngp_grid_update_every 64 \
  2>data/logs/r5_ngp_clip.err | tail -2

log "stage 3b: NGP-step cost analysis (validates the PERF.md roofline)"
for MODE in "" "task_arg.ngp_packed_march true"; do
  BENCH_OPTS="task_arg.render_step_size 0.01 task_arg.max_march_samples 64 $MODE" \
  timeout 1800 python scripts/profile_step.py --ngp --n_rays 4096 \
    --remat false --config lego_hash_packed.yaml --steps 20 \
    2>data/logs/r5_ngp_profile.err | tee -a PROFILE_STEP.jsonl | tail -2
done

log "stage 4a: flagship steady-state scale rows (8k/16k/65k)"
BENCH_TAG=steady_state BENCH_OPTS="network.nerf.scan_trunk true" \
timeout 7200 python scripts/bench_sweep.py \
  --rays 8192 16384 65536 --dtypes bfloat16 --remat false \
  --scan_steps 8 --grad_accum 1 8 --steps 40 --point_timeout 2400 \
  --out BENCH_SWEEP.jsonl 2>data/logs/r5_sweep_flagship.err | tail -8

log "stage 4b: packed-hash steady-state scale rows (4k/8k/16k, accum)"
BENCH_TAG=steady_state timeout 5400 python scripts/bench_sweep.py \
  --rays 4096 8192 16384 --dtypes bfloat16 --remat false \
  --scan_steps 8 --grad_accum 1 4 --steps 40 --point_timeout 1800 \
  --config lego_hash_packed.yaml --out BENCH_SWEEP_HASH.jsonl \
  2>data/logs/r5_sweep_hash.err | tail -8

log "stage 5: NGP H=400 quality trail (decoupled eval budget, packed)"
timeout 2700 python scripts/quality_run.py --minutes 25 --H 400 \
  --config lego_hash_packed.yaml --out_prefix QUALITY_NGP_R5 \
  --tag q_ngp_r5 task_arg.ngp_training true \
  task_arg.ngp_packed_march true $NGP_OPTS \
  2>data/logs/r5_quality_ngp.err | tail -6

log "stage 6: std quality trail + eval-fps shootout (lego.yaml)"
timeout 2100 python scripts/quality_run.py --minutes 15 --H 400 \
  --config lego.yaml --out_prefix QUALITY_R5 --tag q_std_r5 \
  2>data/logs/r5_quality_std.err | tail -8

log "stage 7: hard-scene trail (thin fence + checker)"
timeout 2100 python scripts/quality_run.py --minutes 15 --H 400 \
  --scene procedural_hard --config lego_hash_packed.yaml \
  --out_prefix QUALITY_HARD --tag q_hard_r5 \
  task_arg.ngp_training true task_arg.ngp_packed_march true $NGP_OPTS \
  2>data/logs/r5_quality_hard.err | tail -6

log "battery r5 done"
