#!/bin/bash
# Round-3 battery, stage C: runs after battery_followup_r3b.sh releases the
# tunnel (monoclient — wait on its pid or for no live chip job).
#
#   c1. NGP-vs-std training bench — informative now that the hash-encode
#       batch-flattening fix is in (the 651 rays/s anomaly was measured
#       before it); 300 s/arm gives the ngp arm time to carve the grid.
#   c2. Quality run at 800×800 (VERDICT r2 #8: real-scale PSNR + both
#       render paths on chip). 50 views bounds the bank at 1.15 GiB.
#   c3. Promote whatever the sweeps found into BENCH_DEFAULTS.json so the
#       driver's round-end bench.py runs the best measured shape.
set -u
cd "$(dirname "$0")/.."
log() { echo "[batteryC $(date +%H:%M:%S)] $*"; }

WAIT_PID=${WAIT_PID:-}
if [ -n "$WAIT_PID" ]; then
  log "waiting for battery pid $WAIT_PID to release the tunnel"
  while kill -0 "$WAIT_PID" 2>/dev/null; do sleep 60; done
  log "pid $WAIT_PID gone; waiting 120 s for the tunnel to settle"
  sleep 120
fi

log "=== c0: trisect the hash-step anomaly (names the guilty component) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 5400 python scripts/bench_hash_step.py \
  --n_rays 4096 --steps 10 | tee -a BENCH_HASH_STEP.jsonl

log "=== c1: NGP-vs-std with the hash-step fix ==="
# bench_ngp appends its own records to BENCH_NGP.jsonl; don't tee a copy
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 3600 python scripts/bench_ngp.py \
  --seconds 300 --H 200 --views 60 --n_rays 4096

log "=== c2: quality at 800x800 (real-scale PSNR on chip) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 5400 python scripts/quality_run.py \
  --minutes 45 --H 800 --views 50 --test_views 2 --n_rays 4096 \
  --eval_every_s 240 --out_prefix QUALITY_800

log "=== c3: promote best measured defaults ==="
python scripts/promote_bench_defaults.py \
  BENCH_SWEEP.jsonl BENCH_SWEEP_REMAT.jsonl BENCH_SWEEP_HASH.jsonl \
  --config lego.yaml || true

log "=== battery C done ==="
