"""Flat vs hierarchical occupancy traversal — the mip-pyramid DDA's ledger.

The hierarchical march (renderer/packed_march.py::_hierarchical_sweep)
exists to shrink the O(N·S) candidate stream entering the global sort to
the N·K_c·r positions inside occupied COARSE pyramid cells. This bench
measures that claim on a synthetic scene at two occupancy regimes:

* ``carved`` — a ball filling ~5% of the volume, the post-carve steady
  state the NGP trail trains in. The acceptance bar: the hierarchical
  arm admits **>= 2x fewer** candidate samples into the sort than flat.
* ``dense``  — ~50% occupied, the warmup-phase worst case where the
  coarse level admits nearly everything and the DDA must cost ~nothing.

Both arms share one analytic density (no MLP weights — the bench
isolates TRAVERSAL: sweep + sort + compositing, not matmul throughput)
and identical quadrature, so ``samples_out`` must agree exactly; the
rows record candidates/ray, sort rows/s, end-to-end rays/s, and the
hierarchical arm's reduction factor.

Timing runs K carry-dependent iterations inside ONE jitted fori_loop
(the elision-immune pattern from bench_primitives.py — host-side
re-dispatch loops measure impossibly fast on this machine).

    python scripts/bench_traversal.py [--rays 1024] [--iters 4]
        [--out BENCH_TRAVERSAL.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def build_grid(xp, resolution: int, radius: float):
    """Bool [R,R,R] ball of ``radius`` (bbox [-1,1]^3) — spatially
    coherent occupancy like a real carved scene, not salt-and-pepper
    noise (which would defeat ANY coarse level by construction)."""
    c = (xp.arange(resolution) + 0.5) / resolution * 2.0 - 1.0
    x, y, z = xp.meshgrid(c, c, c, indexing="ij")
    return (x * x + y * y + z * z) < radius * radius


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--rays", type=int, default=1024)
    p.add_argument("--resolution", type=int, default=128)
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--coarse_block", type=int, default=8)
    p.add_argument("--cap_avg", type=int, default=96)
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    p.add_argument("--out", default=os.path.join(_REPO,
                                                 "BENCH_TRAVERSAL.jsonl"))
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import (
        enable_compilation_cache,
        setup_backend,
    )

    setup_backend(args.force_platform)
    enable_compilation_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nerf_replication_tpu.renderer.accelerated import MarchOptions
    from nerf_replication_tpu.renderer.packed_march import march_rays_packed

    n_rays, res = args.rays, args.resolution
    near, far, step = 2.0, 6.0, 0.01
    n_steps = int(np.ceil((far - near) / step - 1e-9))
    bbox = jnp.asarray([[-1.0, -1.0, -1.0], [1.0, 1.0, 1.0]], jnp.float32)

    # rays from a ring at z=+4 aimed through the volume: a mix of
    # center-piercing and grazing/missing rays, like a real camera
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    origins = jnp.stack([
        jax.random.uniform(k1, (n_rays,), minval=-0.8, maxval=0.8),
        jax.random.uniform(k2, (n_rays,), minval=-0.8, maxval=0.8),
        jnp.full((n_rays,), 4.0),
    ], axis=-1)
    dirs = jnp.stack([
        jnp.zeros((n_rays,)), jnp.zeros((n_rays,)), -jnp.ones((n_rays,)),
    ], axis=-1)
    rays = jnp.concatenate([origins, dirs], axis=-1).astype(jnp.float32)

    # analytic density: traversal cost only, no network weights
    def apply_fn(pts, viewdirs, model):
        sig = 4.0 * jnp.exp(-4.0 * jnp.sum(pts * pts, axis=-1, keepdims=True))
        rgb = 0.5 * (pts + 1.0)
        return jnp.concatenate([rgb, sig], axis=-1)

    sink = open(args.out, "a")
    platform = jax.devices()[0].platform

    def run_arm(mode, opts, grid, regime, grid_occ):
        fn = jax.jit(
            lambda r, g: march_rays_packed(
                apply_fn, r, near, far, g, bbox, opts,
                cap_avg=args.cap_avg,
            )
        )
        out = jax.block_until_ready(fn(rays, grid))  # compile + diagnostics
        k_iters = args.iters

        @jax.jit
        def timed(r0, g):
            def body(_, carry):
                s, r = carry
                o = march_rays_packed(
                    apply_fn, r, near, far, g, bbox, opts,
                    cap_avg=args.cap_avg,
                )
                s = s + jnp.mean(o["rgb_map_f"])
                # carry-dependent perturbation chains the iterations so
                # nothing can be elided; 1e-12 leaves the march unchanged
                return s, r0.at[0, 0].add(s * 1e-12)

            return jax.lax.fori_loop(
                0, k_iters, body, (jnp.float32(0.0), r0)
            )[0]

        jax.block_until_ready(timed(rays, grid))  # compile the timed loop
        t0 = time.perf_counter()
        jax.block_until_ready(timed(rays, grid))
        dt = time.perf_counter() - t0

        cand = float(out["march_candidates"])
        samp = float(out["march_samples_out"])
        row = {
            "traversal_mode": mode,
            "regime": regime,
            "platform": platform,
            "grid_occ": grid_occ,
            "coarse_occ": float(out["march_coarse_occ"]),
            "candidates_per_ray": cand / n_rays,
            "samples_out_per_ray": samp / n_rays,
            "overflow_frac": float(out["overflow_frac"]),
            "truncated_rays": int(np.asarray(jnp.sum(out["truncated"]))),
            "rays_per_s": n_rays * k_iters / dt,
            "sort_rows_per_s": cand * k_iters / dt,
            "n_rays": n_rays,
            "n_steps": n_steps,
            "coarse_block": opts.coarse_block,
            "cap_avg": args.cap_avg,
        }
        return row

    flat_opts = MarchOptions(
        step_size=step, max_samples=n_steps, white_bkgd=True,
    )
    s_c = -(-n_steps // args.coarse_block)

    # ball radii: volume fraction = (4/3) pi r^3 / 8. The carved arm runs
    # the default K_c = ceil(S_c/4) interval budget (the 4x stream
    # reduction); the dense arm lifts the budget to S_c — at ~50%
    # occupancy the coarse level rightly admits everything, and the claim
    # under test is that the DDA then costs ~nothing, not that it clips
    # content (which would trade PSNR for a fake reduction).
    regimes = (("carved", 0.46, 0), ("dense", 0.98, s_c))
    for regime, radius, k_cap in regimes:
        hier_opts = MarchOptions(
            step_size=step, max_samples=n_steps, white_bkgd=True,
            coarse_block=args.coarse_block, coarse_cap=k_cap,
        )
        grid = jnp.asarray(build_grid(np, res, radius))
        grid_occ = float(jnp.mean(grid.astype(jnp.float32)))
        flat = run_arm("flat", flat_opts, grid, regime, grid_occ)
        hier = run_arm("hierarchical", hier_opts, grid, regime, grid_occ)
        hier["reduction_x"] = (
            flat["candidates_per_ray"] / hier["candidates_per_ray"]
        )
        for row in (flat, hier):
            sink.write(json.dumps(row) + "\n")
            print(
                f"{regime:>6} {row['traversal_mode']:>12}: "
                f"occ {row['grid_occ']:.3f}  "
                f"cand/ray {row['candidates_per_ray']:8.1f}  "
                f"samples/ray {row['samples_out_per_ray']:6.1f}  "
                f"rays/s {row['rays_per_s']:10.0f}"
                + (f"  reduction {row['reduction_x']:.2f}x"
                   if "reduction_x" in row else "")
            )
        # with an unclipped interval budget the coarse level is a strict
        # superset of the fine grid, so the two arms must admit the SAME
        # occupied samples; a clipped ray instead reports truncation
        if (hier["truncated_rays"] == flat["truncated_rays"]
                and flat["samples_out_per_ray"] != hier["samples_out_per_ray"]):
            print(
                f"WARNING: {regime}: samples_out diverged with no "
                f"truncation (flat {flat['samples_out_per_ray']} vs "
                f"hierarchical {hier['samples_out_per_ray']}) — superset "
                "contract broken?"
            )
    sink.close()
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
