"""Flat vs hierarchical occupancy traversal — the mip-pyramid DDA's ledger.

The hierarchical march (renderer/packed_march.py::_hierarchical_sweep)
exists to shrink the O(N·S) candidate stream entering the global sort to
the N·K_c·r positions inside occupied COARSE pyramid cells. This bench
measures that claim on a synthetic scene at two occupancy regimes:

* ``carved`` — a ball filling ~5% of the volume, the post-carve steady
  state the NGP trail trains in. The acceptance bar: the hierarchical
  arm admits **>= 2x fewer** candidate samples into the sort than flat.
* ``dense``  — ~50% occupied, the warmup-phase worst case where the
  coarse level admits nearly everything and the DDA must cost ~nothing.

Both arms share one analytic density (no MLP weights — the bench
isolates TRAVERSAL: sweep + sort + compositing, not matmul throughput)
and identical quadrature, so ``samples_out`` must agree exactly; the
rows record candidates/ray, sort rows/s, end-to-end rays/s, and the
hierarchical arm's reduction factor.

``--fused`` adds a third arm per regime: the fused mega-kernel march
(ops/fused_march.py) on the SAME hierarchical options — per-ray block
traversal with NO global sort and no [N, S] candidate stream. Its rows
additionally carry the modeled peak-intermediate-bytes ledger (every arm
gets one) so the HBM claim is auditable next to the rays/s claim: the
staged arms materialize sort keys over every candidate plus the packed
MLP stream, the fused gather arm only its per-ray [N, K] sample list,
and full fusion only per-block VMEM scratch plus the output maps
(``peak_intermediate_bytes_full_fusion``).

Timing runs K carry-dependent iterations inside ONE jitted fori_loop
(the elision-immune pattern from bench_primitives.py — host-side
re-dispatch loops measure impossibly fast on this machine).

    python scripts/bench_traversal.py [--rays 1024] [--iters 4]
        [--fused] [--out BENCH_TRAVERSAL.jsonl]

``--mesh-shape D,M`` switches the script to the model-parallel serving
A/B instead (``shard_mode`` rows): a replicated arm on a ``(D*M, 1)``
data-only mesh vs the sharded arm on ``(D, M)`` over the same devices,
recording rays/s and the REAL per-device peak param bytes measured from
placement — the capacity claim (docs/scaleout.md) next to the
throughput claim.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def build_grid(xp, resolution: int, radius: float):
    """Bool [R,R,R] ball of ``radius`` (bbox [-1,1]^3) — spatially
    coherent occupancy like a real carved scene, not salt-and-pepper
    noise (which would defeat ANY coarse level by construction)."""
    c = (xp.arange(resolution) + 0.5) / resolution * 2.0 - 1.0
    x, y, z = xp.meshgrid(c, c, c, indexing="ij")
    return (x * x + y * y + z * z) < radius * radius


def _run_shard_bench(args, sink, platform) -> int:
    """The ``--mesh-shape`` arm: model-parallel hash-grid serving A/B.

    The params tree is synthetic but hash-table-dominated with leaf
    names matching ``parallel/sharding.py``'s partition rules, so the
    sharded arm exercises the REAL serve-path collectives: the
    row-sharded table gather at the encoder lookup and the
    column-parallel trunk matmuls. Both arms finalize their executable
    through the production :func:`scale.mesh_dispatch.mesh_jit` — the
    replicated arm takes the collective-free shard_map path (``M=1``),
    the sharded arm the GSPMD path. Per-device peak param bytes are
    measured from placement (largest addressable shard per leaf), not
    modeled."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nerf_replication_tpu.parallel.mesh import make_mesh
    from nerf_replication_tpu.parallel.sharding import tree_shardings
    from nerf_replication_tpu.scale.mesh_dispatch import mesh_jit
    from nerf_replication_tpu.scale.options import parse_mesh_shape

    d, m = parse_mesh_shape(args.mesh_shape)
    n_dev = len(jax.devices())
    if d == -1:
        d = max(1, n_dev // m)
    if d * m > n_dev:
        print(f"error: --mesh-shape {d},{m} needs {d * m} devices, "
              f"only {n_dev} visible", file=sys.stderr)
        return 2

    # hash/embedding table dominates the byte budget (the part model
    # parallelism exists to split); trunk + head ride the same TP rules
    # the engine's real checkpoints hit
    T, F, W = args.table_rows, 8, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 6)
    params = {"params": {
        "table": {"embeddings": jax.random.normal(ks[0], (T, F), jnp.float32)},
        "pts_linear_0": {
            "kernel": jax.random.normal(ks[1], (F + 3, W), jnp.float32) * 0.1,
            "bias": jnp.zeros((W,), jnp.float32)},
        "pts_linear_1": {
            "kernel": jax.random.normal(ks[2], (W, W), jnp.float32) * 0.1,
            "bias": jnp.zeros((W,), jnp.float32)},
        "rgb_linear": {
            "kernel": jax.random.normal(ks[3], (W, 4), jnp.float32) * 0.1,
            "bias": jnp.zeros((4,), jnp.float32)},
    }}
    total_b = int(sum(a.nbytes for a in jax.tree.leaves(params)))

    def body(p, chunks):
        table = p["params"]["table"]["embeddings"]

        def one(ch):  # [chunk, 6] rays -> [chunk, 4] radiance
            pts = ch[:, :3]
            # integer spatial hash on quantized coords (the NGP idiom):
            # float-domain hashes truncate at ~1e5 magnitude where a
            # 1-ulp fusion difference between the sharded and reference
            # lowerings flips the index — integer ops are exact
            xi = jnp.round(pts * 512.0).astype(jnp.int32)
            idx = ((xi[:, 0] * 73856093) ^ (xi[:, 1] * 19349663)
                   ^ (xi[:, 2] * 83492791)) % T
            h = jnp.concatenate([table[idx], pts], axis=-1)
            for name in ("pts_linear_0", "pts_linear_1"):
                lin = p["params"][name]
                h = jax.nn.relu(h @ lin["kernel"] + lin["bias"])
            head = p["params"]["rgb_linear"]
            return h @ head["kernel"] + head["bias"]

        return {"rgb": jax.lax.map(one, chunks)}

    chunk = 64
    group = d * m  # both arms need the chunk count divisible by their D
    n_chunks = max(group, (args.rays // chunk // group) * group)
    n_rays = n_chunks * chunk
    rays = jax.random.normal(ks[4], (n_rays, 6), jnp.float32)
    chunks = np.asarray(rays).reshape(n_chunks, chunk, 6)

    ref = np.asarray(jax.block_until_ready(
        jax.jit(body)(params, jnp.asarray(chunks)))["rgb"])

    def per_device_bytes(tree):
        return int(sum(max(s.data.nbytes for s in leaf.addressable_shards)
                       for leaf in jax.tree.leaves(tree)))

    rows = []
    for mode, shape in (("replicated", (group, 1)), ("sharded", (d, m))):
        mesh = make_mesh(data_axis=shape[0], model_axis=shape[1])
        if shape[1] > 1:
            placed = jax.device_put(params, tree_shardings(params, mesh))
        else:
            placed = jax.device_put(params, NamedSharding(mesh, P()))
        fn = mesh_jit(body, mesh, has_grid=False, params_template=placed)
        ch = jax.device_put(jnp.asarray(chunks),
                            NamedSharding(mesh, P("data")))
        out = jax.block_until_ready(fn(placed, ch))  # compile
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(placed, ch)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        rows.append({
            "shard_mode": mode,
            "mesh_shape": list(shape),
            "rays_per_s": n_rays * args.iters / dt,
            "param_bytes_per_device": per_device_bytes(placed),
            "param_bytes_total": total_b,
            "allclose": bool(np.allclose(np.asarray(out["rgb"]), ref,
                                         atol=1e-5, rtol=1e-5)),
            "platform": platform,
            "n_rays": int(n_rays),
            "table_rows": int(T),
            "iters": int(args.iters),
        })
    rows[1]["bytes_reduction_x"] = (
        rows[0]["param_bytes_per_device"] / rows[1]["param_bytes_per_device"]
    )
    rc = 0
    for row in rows:
        sink.write(json.dumps(row) + "\n")
        print(
            f"{row['shard_mode']:>10} mesh {tuple(row['mesh_shape'])}: "
            f"rays/s {row['rays_per_s']:10.0f}  "
            f"bytes/device {row['param_bytes_per_device']:>9}"
            + (f"  reduction {row['bytes_reduction_x']:.2f}x"
               if "bytes_reduction_x" in row else "")
            + ("" if row["allclose"] else "  ALLCLOSE FAILED")
        )
        if not row["allclose"]:
            rc = 1
    return rc


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--rays", type=int, default=1024)
    p.add_argument("--resolution", type=int, default=128)
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--coarse_block", type=int, default=8)
    p.add_argument("--cap_avg", type=int, default=96)
    p.add_argument("--fused", action="store_true",
                   help="add the fused mega-kernel arm per regime")
    p.add_argument("--fused_block", type=int, default=256)
    p.add_argument("--mesh-shape", dest="mesh_shape", default="",
                   help="D,M: run the model-parallel serving A/B "
                        "(shard_mode rows) instead of the traversal arms")
    p.add_argument("--table-rows", dest="table_rows", type=int,
                   default=1 << 15,
                   help="rows of the synthetic hash table (shard bench)")
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    p.add_argument("--out", default=os.path.join(_REPO,
                                                 "BENCH_TRAVERSAL.jsonl"))
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import (
        enable_compilation_cache,
        setup_backend,
    )

    setup_backend(args.force_platform)
    enable_compilation_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nerf_replication_tpu.ops.fused_march import (
        _statics_for,
        march_rays_fused,
    )
    from nerf_replication_tpu.renderer.accelerated import MarchOptions
    from nerf_replication_tpu.renderer.packed_march import march_rays_packed

    n_rays, res = args.rays, args.resolution
    near, far, step = 2.0, 6.0, 0.01
    n_steps = int(np.ceil((far - near) / step - 1e-9))
    bbox = jnp.asarray([[-1.0, -1.0, -1.0], [1.0, 1.0, 1.0]], jnp.float32)

    # rays from a ring at z=+4 aimed through the volume: a mix of
    # center-piercing and grazing/missing rays, like a real camera
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    origins = jnp.stack([
        jax.random.uniform(k1, (n_rays,), minval=-0.8, maxval=0.8),
        jax.random.uniform(k2, (n_rays,), minval=-0.8, maxval=0.8),
        jnp.full((n_rays,), 4.0),
    ], axis=-1)
    dirs = jnp.stack([
        jnp.zeros((n_rays,)), jnp.zeros((n_rays,)), -jnp.ones((n_rays,)),
    ], axis=-1)
    rays = jnp.concatenate([origins, dirs], axis=-1).astype(jnp.float32)

    # analytic density: traversal cost only, no network weights
    def apply_fn(pts, viewdirs, model):
        sig = 4.0 * jnp.exp(-4.0 * jnp.sum(pts * pts, axis=-1, keepdims=True))
        rgb = 0.5 * (pts + 1.0)
        return jnp.concatenate([rgb, sig], axis=-1)

    sink = open(args.out, "a")
    platform = jax.devices()[0].platform

    if args.mesh_shape:
        rc = _run_shard_bench(args, sink, platform)
        sink.close()
        print(f"wrote {args.out}")
        return rc

    # Modeled peak intermediate bytes (HBM arrays live at once between the
    # admission structure and the composite — NOT weights or outputs):
    #   staged rows: occ(1) + t(4) + dist(4) + sort key/val(8) = 17 B per
    #   candidate entering the global sort, plus the packed MLP stream
    #   (pts 12 + dirs 12 + raw 16 = 40 B per kept row).
    #   fused gather: t_sel(4) + valid(1) + flat_sel(4) = 9 B per [N, K]
    #   slot plus the same 40 B masked-MLP row — no sort, no [N, S].
    #   full fusion: the sample list never reaches HBM; per-block scratch
    #   is blk·K·49 B of VMEM plus the [N] output maps (20 B/ray).
    STREAM_ROW_B, SORT_ROW_B, FUSED_SLOT_B, OUT_ROW_B = 40, 17, 9, 20

    def staged_bytes(cand_rows, m_cap_rows):
        return int(cand_rows * SORT_ROW_B + m_cap_rows * STREAM_ROW_B)

    def run_arm(mode, opts, grid, regime, grid_occ):
        if mode == "fused":
            def march(r, g):
                return march_rays_fused(
                    apply_fn, r, near, far, g, bbox, opts
                )
        else:
            def march(r, g):
                return march_rays_packed(
                    apply_fn, r, near, far, g, bbox, opts,
                    cap_avg=args.cap_avg,
                )

        fn = jax.jit(march)
        out = jax.block_until_ready(fn(rays, grid))  # compile + diagnostics
        k_iters = args.iters

        @jax.jit
        def timed(r0, g):
            def body(_, carry):
                s, r = carry
                o = march(r, g)
                s = s + jnp.mean(o["rgb_map_f"])
                # carry-dependent perturbation chains the iterations so
                # nothing can be elided; 1e-12 leaves the march unchanged
                return s, r0.at[0, 0].add(s * 1e-12)

            return jax.lax.fori_loop(
                0, k_iters, body, (jnp.float32(0.0), r0)
            )[0]

        jax.block_until_ready(timed(rays, grid))  # compile the timed loop
        t0 = time.perf_counter()
        jax.block_until_ready(timed(rays, grid))
        dt = time.perf_counter() - t0

        cand = float(out["march_candidates"])
        samp = float(out["march_samples_out"])
        if mode == "fused":
            from nerf_replication_tpu.renderer.occupancy import (
                PYRAMID_FACTORS,
            )

            st = _statics_for(
                res, res // PYRAMID_FACTORS[-1], near, far, opts
            )
            k = st.k_sel
            peak_b = int(n_rays * k * (FUSED_SLOT_B + STREAM_ROW_B))
            blk = min(opts.fused_block, n_rays)
            peak_b_full = int(
                blk * k * (FUSED_SLOT_B + STREAM_ROW_B)
                + n_rays * OUT_ROW_B
            )
        else:
            m_cap = min(n_rays * args.cap_avg, int(cand))
            peak_b = staged_bytes(cand, m_cap)
            peak_b_full = None
        row = {
            "traversal_mode": mode,
            "regime": regime,
            "platform": platform,
            "grid_occ": grid_occ,
            "coarse_occ": float(out["march_coarse_occ"]),
            "candidates_per_ray": cand / n_rays,
            "samples_out_per_ray": samp / n_rays,
            "overflow_frac": float(out["overflow_frac"]),
            "truncated_rays": int(np.asarray(jnp.sum(out["truncated"]))),
            "rays_per_s": n_rays * k_iters / dt,
            "sort_rows_per_s": cand * k_iters / dt,
            "peak_intermediate_bytes": peak_b,
            "n_rays": n_rays,
            "n_steps": n_steps,
            "coarse_block": opts.coarse_block,
            "cap_avg": args.cap_avg,
        }
        if peak_b_full is not None:
            row["peak_intermediate_bytes_full_fusion"] = peak_b_full
        return row

    flat_opts = MarchOptions(
        step_size=step, max_samples=n_steps, white_bkgd=True,
    )
    s_c = -(-n_steps // args.coarse_block)

    # ball radii: volume fraction = (4/3) pi r^3 / 8. The carved arm runs
    # the default K_c = ceil(S_c/4) interval budget (the 4x stream
    # reduction); the dense arm lifts the budget to S_c — at ~50%
    # occupancy the coarse level rightly admits everything, and the claim
    # under test is that the DDA then costs ~nothing, not that it clips
    # content (which would trade PSNR for a fake reduction).
    regimes = (("carved", 0.46, 0), ("dense", 0.98, s_c))
    for regime, radius, k_cap in regimes:
        hier_opts = MarchOptions(
            step_size=step, max_samples=n_steps, white_bkgd=True,
            coarse_block=args.coarse_block, coarse_cap=k_cap,
        )
        grid = jnp.asarray(build_grid(np, res, radius))
        grid_occ = float(jnp.mean(grid.astype(jnp.float32)))
        flat = run_arm("flat", flat_opts, grid, regime, grid_occ)
        hier = run_arm("hierarchical", hier_opts, grid, regime, grid_occ)
        hier["reduction_x"] = (
            flat["candidates_per_ray"] / hier["candidates_per_ray"]
        )
        rows = [flat, hier]
        if args.fused:
            fused_opts = MarchOptions(
                step_size=step, max_samples=n_steps, white_bkgd=True,
                coarse_block=args.coarse_block,
                coarse_cap=k_cap, fused_block=args.fused_block,
            )
            fused = run_arm("fused", fused_opts, grid, regime, grid_occ)
            # the headline A/B: the fused march against the staged
            # hierarchical arm it replaces, on identical admission
            fused["speedup_vs_staged_x"] = (
                fused["rays_per_s"] / hier["rays_per_s"]
            )
            fused["bytes_vs_staged_x"] = (
                hier["peak_intermediate_bytes"]
                / fused["peak_intermediate_bytes"]
            )
            rows.append(fused)
        for row in rows:
            sink.write(json.dumps(row) + "\n")
            print(
                f"{regime:>6} {row['traversal_mode']:>12}: "
                f"occ {row['grid_occ']:.3f}  "
                f"cand/ray {row['candidates_per_ray']:8.1f}  "
                f"samples/ray {row['samples_out_per_ray']:6.1f}  "
                f"rays/s {row['rays_per_s']:10.0f}"
                + (f"  reduction {row['reduction_x']:.2f}x"
                   if "reduction_x" in row else "")
                + (f"  vs staged {row['speedup_vs_staged_x']:.2f}x "
                   f"({row['bytes_vs_staged_x']:.2f}x fewer bytes)"
                   if "speedup_vs_staged_x" in row else "")
            )
        # with an unclipped interval budget the coarse level is a strict
        # superset of the fine grid, so the two arms must admit the SAME
        # occupied samples; a clipped ray instead reports truncation
        if (hier["truncated_rays"] == flat["truncated_rays"]
                and flat["samples_out_per_ray"] != hier["samples_out_per_ray"]):
            print(
                f"WARNING: {regime}: samples_out diverged with no "
                f"truncation (flat {flat['samples_out_per_ray']} vs "
                f"hierarchical {hier['samples_out_per_ray']}) — superset "
                "contract broken?"
            )
    sink.close()
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
