"""Trisect the hash-config training-step anomaly on chip.

PERF.md round 3: `lego_hash` trains at ~400-650 rays/s while its encoder
microbench (scripts/bench_hash.py) does 1.4 G points/s fwd+bwd and the
big-MLP step does 48k rays/s — the step is ~50x slower than its parts
explain, and the batch-flattening fix did not close the gap. This script
times each third of the step as its own executable at EXACT training shapes:

    enc_coarse / enc_fine   : hash_encode fwd+bwd (grad wrt table)
    enc1_coarse / enc1_fine : candidate reformulation — ALL levels+corners
                              through ONE gather (one scatter in the VJP
                              instead of L*2^D); parity-checked first
    enc2_coarse / enc2_fine : candidate reformulation — per-LEVEL table
                              slices as separate grad leaves (scatters hit
                              small per-level operands, not the 12.4M-row
                              concatenation); parity-checked first
    enc3_coarse / enc3_fine : candidate backward — per-level sort by table
                              index + segment_sum(indices_are_sorted) in
                              place of the scatter lowering; parity-checked
    lossgrad                : full render + MSE value_and_grad (no optimizer)
    lossgrad_frozen_table   : lossgrad with the table excluded from
                              differentiation (scatter-VJP discriminator)
    lossgrad_onegather      : lossgrad with the one-gather encoder patched
                              into the network (fix candidate in context)
    lossgrad_freq           : same rays, frequency encoder + same-size MLP
                              (control: isolates the encoder entirely)
    opt_apply               : apply_gradients alone on precomputed grads
    full_step               : the trainer's fused step

The third that holds the missing seconds names the guilty component.

    python scripts/bench_hash_step.py [--n_rays 4096] [--steps 10]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _timed(fn, args, steps, warmup=2, vary=None):
    """Mean seconds/call over ``steps`` calls.

    ``vary``: index of the positional arg to perturb per call. Round 3 on
    the axon tunnel produced physically impossible timings (786k-point
    fwd+bwd with a 99 MB gradient output "in 20 us") for loops that re-call
    an executable with IDENTICAL arguments — whatever the elision
    mechanism, distinct inputs per call defeat it. Callers must pass
    ``vary`` for any argument-stationary fn; stateful fns that thread their
    own output (train steps) are naturally immune.
    """
    import jax
    import jax.numpy as jnp

    def call(i):
        if vary is None:
            return fn(*args)
        a = list(args)
        if jnp.issubdtype(a[vary].dtype, jnp.unsignedinteger):
            a[vary] = jax.random.fold_in(a[vary], i)  # PRNG key arg
        else:
            a[vary] = a[vary] + jnp.asarray(i * 1e-7, a[vary].dtype)
        return fn(*a)

    out = None
    for i in range(warmup):
        out = call(steps + i)  # positive: fold_in rejects negative data
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for i in range(steps):
        out = call(i)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--n_rays", type=int, default=4096)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--config", default="lego_hash.yaml")
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import (
        enable_compilation_cache,
        setup_backend,
    )

    setup_backend(args.force_platform)
    enable_compilation_cache()

    import jax
    import jax.numpy as jnp

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.models.nerf.network import make_network
    from nerf_replication_tpu.train.loss import make_loss
    from nerf_replication_tpu.train.trainer import Trainer, make_train_state

    def emit(stage, dt, extra=None):
        rec = {"stage": stage, "s_per_call": round(dt, 5),
               "n_rays": args.n_rays, "config": args.config}
        if extra:
            rec.update(extra)
        print(json.dumps(rec), flush=True)

    base_opts = [
        "task_arg.N_rays", str(args.n_rays),
        "task_arg.precrop_iters", "0",
    ]
    cfg = make_cfg(
        os.path.join(_REPO, "configs", "nerf", args.config), base_opts
    )
    n_coarse = int(cfg.task_arg.N_samples)
    n_fine = n_coarse + int(cfg.task_arg.N_importance)

    # --- encoder alone at training scale (grad wrt table) ---------------
    enc_cfg = cfg.network.xyz_encoder
    if enc_cfg.type == "hashgrid":
        from nerf_replication_tpu.models.encoding.hashgrid import (
            hash_encode,
            level_geometry,
        )

        input_dim = int(enc_cfg.input_dim)
        num_levels = int(enc_cfg.num_levels)
        base_res = int(enc_cfg.base_resolution)
        log2_t = int(enc_cfg.log2_hashmap_size)
        # same derivation as HashGridEncoder.scale_factor (hashgrid.py:173-183),
        # incl. the desired_resolution==-1 fallback to per_level_scale
        desired = int(enc_cfg.get("desired_resolution", -1))
        if desired != -1:
            pls = 2.0 ** (math.log2(desired / base_res) / (num_levels - 1))
        else:
            pls = float(enc_cfg.get("per_level_scale", 2.0))
        offsets, scales, resolutions, use_hash = level_geometry(
            input_dim, num_levels, pls, base_res, log2_t
        )
        table = jax.random.uniform(
            jax.random.PRNGKey(0),
            (int(offsets[-1]), int(enc_cfg.level_dim)), jnp.float32,
            -1e-4, 1e-4,
        )

        def enc_loss(x, tab):
            out = hash_encode(
                x, tab, input_dim, num_levels, pls, base_res, log2_t
            )
            return jnp.sum(out * out)

        enc_bwd = jax.jit(jax.grad(enc_loss, argnums=1))
        for name, n_pts in (("enc_coarse", args.n_rays * n_coarse),
                            ("enc_fine", args.n_rays * n_fine)):
            x = jax.random.uniform(jax.random.PRNGKey(1), (n_pts, 3))
            dt = _timed(enc_bwd, (x, table), args.steps, vary=0)
            emit(name, dt, {"n_pts": n_pts,
                            "gpts_per_s": round(n_pts / dt / 1e9, 3)})

        # candidate reformulation: ALL levels x corners through ONE gather,
        # so autodiff emits ONE scatter-add instead of L*2^D of them — if
        # per-scatter-op overhead is what the training step is paying, this
        # variant wins and becomes the production formulation
        from nerf_replication_tpu.models.encoding.hashgrid import (
            _corner_index,
        )

        def hash_encode_onegather(x, tab):
            idx_cols, w_cols = [], []
            for lvl in range(num_levels):
                pos = x * scales[lvl] + 0.5
                pos_grid = jnp.floor(pos)
                frac = pos - pos_grid
                pos_grid = pos_grid.astype(jnp.int32)
                for corner_bits in range(1 << input_dim):
                    sel = [(corner_bits >> dd) & 1
                           for dd in range(input_dim)]
                    corner = pos_grid + jnp.asarray(sel, jnp.int32)
                    w = jnp.ones(x.shape[:-1], x.dtype)
                    for dd in range(input_dim):
                        w = w * (frac[..., dd] if sel[dd]
                                 else 1.0 - frac[..., dd])
                    idx = _corner_index(
                        corner, resolutions[lvl],
                        offsets[lvl + 1] - offsets[lvl], use_hash[lvl],
                    )
                    idx_cols.append(idx + offsets[lvl])
                    w_cols.append(w)
            idx = jnp.stack(idx_cols, axis=-1)  # [N, L*2^D]
            w = jnp.stack(w_cols, axis=-1)
            vals = jnp.take(tab, idx, axis=0)   # ONE gather
            n, c = x.shape[0], tab.shape[-1]
            out = (w[..., None] * vals).reshape(
                n, num_levels, 1 << input_dim, c
            ).sum(axis=2)
            return out.reshape(n, num_levels * c)

        # second candidate: per-LEVEL table slices as separate grad leaves.
        # f2's cost analysis shows ~24.7 TB/step of modeled traffic — if the
        # scatter lowering charges (a multiple of) the full operand, 16
        # small per-level operands instead of one concatenated [12.4M, 2]
        # table cut the traffic ~25x without changing the math
        tables = [
            table[int(offsets[lvl]):int(offsets[lvl + 1])]
            for lvl in range(num_levels)
        ]

        def hash_encode_perlevel(x, tabs):
            outs = []
            for lvl in range(num_levels):
                pos = x * scales[lvl] + 0.5
                pos_grid = jnp.floor(pos)
                frac = pos - pos_grid
                pos_grid = pos_grid.astype(jnp.int32)
                acc = None
                for corner_bits in range(1 << input_dim):
                    sel = [(corner_bits >> dd) & 1
                           for dd in range(input_dim)]
                    corner = pos_grid + jnp.asarray(sel, jnp.int32)
                    w = jnp.ones(x.shape[:-1], x.dtype)
                    for dd in range(input_dim):
                        w = w * (frac[..., dd] if sel[dd]
                                 else 1.0 - frac[..., dd])
                    idx = _corner_index(
                        corner, resolutions[lvl],
                        offsets[lvl + 1] - offsets[lvl], use_hash[lvl],
                    )
                    vals = jnp.take(tabs[lvl], idx, axis=0)
                    contrib = w[..., None] * vals
                    acc = contrib if acc is None else acc + contrib
                outs.append(acc)
            return jnp.concatenate(outs, axis=-1)

        def enc2_loss(x, tabs):
            out = hash_encode_perlevel(x, tabs)
            return jnp.sum(out * out)

        enc2_bwd = jax.jit(jax.grad(enc2_loss, argnums=1))
        for name, n_pts in (("enc2_coarse", args.n_rays * n_coarse),
                            ("enc2_fine", args.n_rays * n_fine)):
            x = jax.random.uniform(jax.random.PRNGKey(1), (n_pts, 3))
            if n_pts == args.n_rays * n_coarse:
                ref = hash_encode(
                    x[:256], table, input_dim, num_levels, pls, base_res,
                    log2_t,
                )
                alt = hash_encode_perlevel(x[:256], tables)
                np.testing.assert_allclose(
                    np.asarray(ref), np.asarray(alt), rtol=1e-5, atol=1e-7
                )
            dt = _timed(enc2_bwd, (x, tables), args.steps, vary=0)
            emit(name, dt, {"n_pts": n_pts,
                            "gpts_per_s": round(n_pts / dt / 1e9, 3)})

        # third candidate: sort rows by table index, then segment_sum with
        # indices_are_sorted=True — replaces the scatter lowering entirely
        # (measured scatter rate ~25M rows/s; a bitonic sort + segmented
        # reduction may beat it at the 33M/100M rows-per-pass scale)
        def table_grad_sorted(x, g):
            grad_slices = []
            c = int(enc_cfg.level_dim)
            for lvl in range(num_levels):
                pos = x * scales[lvl] + 0.5
                pos_grid = jnp.floor(pos)
                frac = pos - pos_grid
                pos_grid = pos_grid.astype(jnp.int32)
                g_lvl = g[:, lvl * c:(lvl + 1) * c]
                n_entries = int(offsets[lvl + 1] - offsets[lvl])
                idx_cols, upd_cols = [], []
                for corner_bits in range(1 << input_dim):
                    sel = [(corner_bits >> dd) & 1
                           for dd in range(input_dim)]
                    corner = pos_grid + jnp.asarray(sel, jnp.int32)
                    w = jnp.ones(x.shape[:-1], x.dtype)
                    for dd in range(input_dim):
                        w = w * (frac[..., dd] if sel[dd]
                                 else 1.0 - frac[..., dd])
                    idx_cols.append(_corner_index(
                        corner, resolutions[lvl], n_entries, use_hash[lvl]
                    ))
                    upd_cols.append(w[:, None] * g_lvl)
                idx_lvl = jnp.concatenate(idx_cols, 0)
                upd_lvl = jnp.concatenate(upd_cols, 0)
                order = jnp.argsort(idx_lvl)
                grad_slices.append(jax.ops.segment_sum(
                    jnp.take(upd_lvl, order, axis=0),
                    jnp.take(idx_lvl, order),
                    num_segments=n_entries, indices_are_sorted=True,
                ))
            return jnp.concatenate(grad_slices, axis=0)

        tg_sorted = jax.jit(table_grad_sorted)
        for name, n_pts in (("enc3_coarse", args.n_rays * n_coarse),
                            ("enc3_fine", args.n_rays * n_fine)):
            x = jax.random.uniform(jax.random.PRNGKey(1), (n_pts, 3))
            g = jax.random.normal(
                jax.random.PRNGKey(2),
                (n_pts, num_levels * int(enc_cfg.level_dim)),
            )
            # parity: sorted-segment table grad == autodiff table grad
            if n_pts == args.n_rays * n_coarse:
                def _lin(tab):
                    return jnp.sum(hash_encode(
                        x[:256], tab, input_dim, num_levels, pls, base_res,
                        log2_t,
                    ) * g[:256])
                ref_g = jax.grad(_lin)(table)
                alt_g = table_grad_sorted(x[:256], g[:256])
                np.testing.assert_allclose(
                    np.asarray(ref_g), np.asarray(alt_g), rtol=2e-4,
                    atol=1e-6,
                )
            dt = _timed(tg_sorted, (x, g), args.steps, vary=0)
            emit(name, dt, {"n_pts": n_pts,
                            "rows": n_pts * (1 << input_dim) * num_levels})

        def enc1_loss(x, tab):
            out = hash_encode_onegather(x, tab)
            return jnp.sum(out * out)

        enc1_bwd = jax.jit(jax.grad(enc1_loss, argnums=1))
        for name, n_pts in (("enc1_coarse", args.n_rays * n_coarse),
                            ("enc1_fine", args.n_rays * n_fine)):
            x = jax.random.uniform(jax.random.PRNGKey(1), (n_pts, 3))
            # parity vs the production formulation before timing it
            if n_pts == args.n_rays * n_coarse:
                ref = hash_encode(
                    x[:256], table, input_dim, num_levels, pls, base_res,
                    log2_t,
                )
                alt = hash_encode_onegather(x[:256], table)
                np.testing.assert_allclose(
                    np.asarray(ref), np.asarray(alt), rtol=1e-5, atol=1e-7
                )
            dt = _timed(enc1_bwd, (x, table), args.steps, vary=0)
            emit(name, dt, {"n_pts": n_pts,
                            "gpts_per_s": round(n_pts / dt / 1e9, 3)})

    # --- full loss value_and_grad (no optimizer) -------------------------
    network = make_network(cfg)
    loss = make_loss(cfg, network)
    state, _ = make_train_state(cfg, network, jax.random.PRNGKey(2))
    near, far = float(cfg.task_arg.near), float(cfg.task_arg.far)
    kb = jax.random.PRNGKey(3)
    rays_o = jax.random.normal(kb, (args.n_rays, 3)) * 0.1
    rays_d = jax.random.normal(jax.random.fold_in(kb, 1), (args.n_rays, 3))
    rays_d = rays_d / jnp.linalg.norm(rays_d, axis=-1, keepdims=True)
    batch = {
        "rays": jnp.concatenate([rays_o, rays_d], -1),
        "rgbs": jnp.full((args.n_rays, 3), 0.5, jnp.float32),
        "near": near, "far": far,
    }

    def make_lossgrad(loss_obj):
        def lossgrad(params, batch, key):
            def f(p):
                _, l, stats = loss_obj(
                    {"params": p}, batch, key=key, train=True
                )
                return l, stats

            (_, stats), grads = jax.value_and_grad(f, has_aux=True)(params)
            return grads, stats

        return jax.jit(lossgrad)

    lg = make_lossgrad(loss)
    grads, _ = lg(state.params, batch, jax.random.PRNGKey(4))
    jax.block_until_ready(grads)
    dt = _timed(lg, (state.params, batch, jax.random.PRNGKey(4)),
                args.steps, vary=2)
    emit("lossgrad", dt, {"rays_per_s": round(args.n_rays / dt, 1)})

    # --- lossgrad with the hash table FROZEN (scatter-VJP discriminator):
    # differentiate only the non-embedding params; the table rides along as
    # a closed-over constant. Fast here + slow above convicts the table-
    # gradient scatter; slow here too exonerates it.
    frozen, trainable = {}, {}
    if "xyz_encoder" in state.params:
        from flax.traverse_util import flatten_dict, unflatten_dict

        flat = flatten_dict(state.params)
        frozen = {k: v for k, v in flat.items() if "embeddings" in k}
        trainable = {k: v for k, v in flat.items() if "embeddings" not in k}
        if not frozen:
            # non-hashgrid encoders name their params differently; an
            # empty frozen set would silently time the same computation as
            # lossgrad — skip the stage (loudly), keep the rest running
            print(json.dumps({"stage": "lossgrad_frozen_table",
                              "skipped": "no 'embeddings' param"}),
                  flush=True)
    if frozen:

        def lossgrad_frozen(tr, fr, batch, key):
            # fr enters as an argument (not a closure) so the 100 MB table
            # isn't baked into the executable as a constant
            def f(tr_):
                p = unflatten_dict({**fr, **tr_})
                _, l, stats = loss({"params": p}, batch, key=key, train=True)
                return l, stats

            (_, stats), g = jax.value_and_grad(f, has_aux=True)(tr)
            return g, stats

        lgf = jax.jit(lossgrad_frozen)
        g3, _ = lgf(trainable, frozen, batch, jax.random.PRNGKey(4))
        jax.block_until_ready(g3)
        dt = _timed(lgf, (trainable, frozen, batch, jax.random.PRNGKey(4)),
                    args.steps, vary=3)
        emit("lossgrad_frozen_table", dt,
             {"rays_per_s": round(args.n_rays / dt, 1)})

    # --- full loss with the ONE-GATHER encoder in context -----------------
    # hashgrid.HashGridEncoder resolves hash_encode through its module
    # global at call time, so patching it swaps the formulation for a
    # freshly built network without touching production code
    if enc_cfg.type == "hashgrid":
        import nerf_replication_tpu.models.encoding.hashgrid as hg_mod

        orig_encode = hg_mod.hash_encode

        def patched(x, tab, input_dim_, num_levels_, pls_, base_res_,
                    log2_t_):
            batch_shape = x.shape[:-1]
            if len(batch_shape) != 1:
                x = x.reshape(-1, x.shape[-1])
            out = hash_encode_onegather(x, tab)
            if len(batch_shape) != 1:
                out = out.reshape(*batch_shape, out.shape[-1])
            return out

        hg_mod.hash_encode = patched
        try:
            network_1g = make_network(cfg)
            loss_1g = make_loss(cfg, network_1g)
            lg1 = make_lossgrad(loss_1g)
            g1g, _ = lg1(state.params, batch, jax.random.PRNGKey(4))
            jax.block_until_ready(g1g)
            dt = _timed(lg1, (state.params, batch, jax.random.PRNGKey(4)),
                        args.steps, vary=2)
            emit("lossgrad_onegather", dt,
                 {"rays_per_s": round(args.n_rays / dt, 1)})
        finally:
            hg_mod.hash_encode = orig_encode

    # --- optimizer alone --------------------------------------------------
    opt = jax.jit(lambda s, g: s.apply_gradients(grads=g))
    # thread the state so every call has distinct inputs (see _timed)
    s_o = opt(state, grads)
    s_o = opt(s_o, grads)
    jax.block_until_ready(jax.tree_util.tree_leaves(s_o.params)[0])
    t0 = time.perf_counter()
    for _ in range(args.steps):
        s_o = opt(s_o, grads)
    jax.block_until_ready(jax.tree_util.tree_leaves(s_o.params)[0])
    dt = (time.perf_counter() - t0) / args.steps
    emit("opt_apply", dt)

    # --- the fused step ---------------------------------------------------
    trainer = Trainer(cfg, network, loss)
    step_fn = trainer._build_step(with_pool=False)
    n_bank = 1 << 18
    bo = jax.random.normal(jax.random.PRNGKey(5), (n_bank, 3)) * 0.1
    bd = jax.random.normal(jax.random.PRNGKey(6), (n_bank, 3))
    bd = bd / jnp.linalg.norm(bd, axis=-1, keepdims=True)
    bank_rays = jnp.concatenate([bo, bd], -1).astype(jnp.float32)
    bank_rgbs = jnp.full((n_bank, 3), 0.5, jnp.float32)
    state2, _ = make_train_state(cfg, network, jax.random.PRNGKey(7))
    # step_fn donates its state argument — thread it instead of reusing
    s2, stats = step_fn(state2, bank_rays, bank_rgbs, jax.random.PRNGKey(8))
    s2, stats = step_fn(s2, bank_rays, bank_rgbs, jax.random.PRNGKey(8))
    jax.block_until_ready(stats)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        s2, stats = step_fn(s2, bank_rays, bank_rgbs, jax.random.PRNGKey(8))
    jax.block_until_ready(stats)
    dt = (time.perf_counter() - t0) / args.steps
    emit("full_step", dt, {"rays_per_s": round(args.n_rays / dt, 1)})

    # --- control: same trunk, frequency encoder ---------------------------
    ctl_cfg = make_cfg(
        os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        base_opts + [
            "network.nerf.W", str(int(cfg.network.nerf.W)),
            "network.nerf.D", str(int(cfg.network.nerf.D)),
            "network.nerf.skips", str(list(cfg.network.nerf.skips)),
        ],
    )
    network_c = make_network(ctl_cfg)
    loss_c = make_loss(ctl_cfg, network_c)
    state_c, _ = make_train_state(ctl_cfg, network_c, jax.random.PRNGKey(9))
    lgc = make_lossgrad(loss_c)
    g2, _ = lgc(state_c.params, batch, jax.random.PRNGKey(10))
    jax.block_until_ready(g2)
    dt = _timed(lgc, (state_c.params, batch, jax.random.PRNGKey(10)),
                args.steps, vary=2)
    emit("lossgrad_freq", dt, {"rays_per_s": round(args.n_rays / dt, 1)})


if __name__ == "__main__":
    main()
