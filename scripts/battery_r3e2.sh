#!/bin/bash
# Round-3 battery, stage E (HISTORICAL): byte-reduction probes for the
# HBM-bound flagship step. OUTCOME (round 4, PERF.md "f3 closure"): remat
# LOSES at the headline shape (41.4k vs 47.8k rays/s — the 2x recompute
# FLOPs cost more than the ~2x byte cut buys at intensity 71), and
# SplitDense left throughput unchanged (XLA had already fused the
# concats). Kept for the record; do not re-run expecting a win.
set -u
cd "$(dirname "$0")/.."
log() { echo "[batteryE $(date +%H:%M:%S)] $*"; }

WAIT_PID=${WAIT_PID:-}
if [ -n "$WAIT_PID" ]; then
  log "waiting for battery pid $WAIT_PID to release the tunnel"
  while kill -0 "$WAIT_PID" 2>/dev/null; do sleep 60; done
  log "pid $WAIT_PID gone; waiting 120 s for the tunnel to settle"
  sleep 120
fi

log "=== e1: remat at the headline shape (4096/8192, scan burst) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 7200 python scripts/bench_sweep.py \
  --rays 4096 8192 --dtypes bfloat16 --remat true --scan_steps 32 --steps 60 \
  --point_timeout 2400 --out BENCH_SWEEP_REMAT.jsonl

log "=== e2: profile the remat step (bytes/step vs the 48.2 GB no-remat) ==="
BENCH_INIT_RETRIES=4 BENCH_INIT_DELAY_S=30 timeout 2400 python scripts/profile_step.py \
  --config lego.yaml --n_rays 4096 --remat true \
  2>data/logs/profile_remat.err | tee -a PROFILE_STEP.jsonl

log "=== e3: promote whatever won ==="
python scripts/promote_bench_defaults.py \
  BENCH_SWEEP.jsonl BENCH_SWEEP_REMAT.jsonl --config lego.yaml || true

log "=== battery E done ==="
