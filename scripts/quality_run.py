"""One-command trained-quality artifact (VERDICT r1 #3).

Generates a procedural Blender-format scene (the air-gapped stand-in for
nerf_synthetic — scripts/download_blender.sh documents the swap), trains the
flagship config under a wall-clock budget with periodic eval, and leaves the
full artifact trail:

* data/record/... PSNR/SSIM trace (QUALITY.jsonl, one line per eval)
* data/result/... summary.json + per-view pred/gt PNGs
* occupancy grid (occupancy_grid.npz) baked from the trained net
* 360° video via the accelerated renderer
* QUALITY.md — the trace table + wall-clock-to-threshold estimates

    python scripts/quality_run.py --minutes 30 [--H 400] [--views 100]
        [--scene_root data/quality_scene] [--target_psnr 21.55]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# BASELINE.md north star: >=30 dB on lego-class scenes
NORTH_STAR_DB = 30.0
sys.path.insert(0, _REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--minutes", type=float, default=30.0)
    p.add_argument("--H", type=int, default=400)
    p.add_argument("--views", type=int, default=100)
    p.add_argument("--test_views", type=int, default=4)
    p.add_argument("--scene_root", default=None,
                   help="default data/quality_scene_h{H} — keyed by "
                        "resolution because ensure_scene rmtree's a root "
                        "whose images mismatch, so two concurrent runs at "
                        "different H sharing a root would destroy each "
                        "other's scene mid-flight")
    p.add_argument("--target_psnr", type=float, default=21.55,
                   help="reference log.txt final PSNR (475 epochs)")
    p.add_argument("--n_rays", type=int, default=4096)
    p.add_argument("--eval_cap", type=int, default=1024,
                   help="preset packed-eval stream cap (samples/ray avg) for "
                        "NGP configs — set from telemetry history so eval "
                        "renders never escalate-recompile mid-run (the "
                        "stage-3c trail settled at 1024; escalations now "
                        "emit a telemetry compile row either way)")
    p.add_argument("--eval_every_s", type=float, default=120.0)
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    p.add_argument("--tag", default=None,
                   help="exp_name; default quality_{config-stem}_{H} so "
                        "concurrent runs get disjoint model/record/result "
                        "dirs (the recorder WIPES its record dir on a "
                        "non-resume start)")
    p.add_argument("--config", default="lego.yaml",
                   help="config under configs/nerf/ (e.g. lego_hash.yaml)")
    p.add_argument("--scene", default="procedural",
                   help="procedural scene variant; 'procedural_hard' adds "
                        "the thin-cylinder fence + sub-voxel checker "
                        "(datasets/procedural.py render_view)")
    p.add_argument("--out_prefix", default="QUALITY",
                   help="repo-root prefix for the .jsonl trace and .md report")
    p.add_argument("opts", nargs="*", default=[],
                   help="trailing cfg key/value overrides (smoke runs)")
    args = p.parse_args(argv)
    if args.scene_root is None:
        # keyed by the FULL scene signature: ensure_scene rmtree's on any
        # resolution OR view-count mismatch, so every parameter it checks
        # must be in the key or concurrent runs can still clobber each other
        args.scene_root = (f"data/quality_scene_h{args.H}"
                           f"_v{args.views}_t{args.test_views}")
    if args.tag is None:
        stem = os.path.splitext(args.config)[0]
        args.tag = f"quality_{stem}_{args.H}"

    from nerf_replication_tpu.utils.platform import (
        enable_compilation_cache,
        setup_backend,
    )

    setup_backend(args.force_platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    enable_compilation_cache()

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.datasets import make_dataset
    from nerf_replication_tpu.evaluators import make_evaluator
    from nerf_replication_tpu.models import make_network
    from nerf_replication_tpu.train import make_loss, make_train_state
    from nerf_replication_tpu.train.checkpoint import save_model
    from nerf_replication_tpu.train.trainer import Trainer

    scene = args.scene
    from nerf_replication_tpu.datasets.procedural import ensure_scene

    ensure_scene(args.scene_root, scene=scene, H=args.H, W=args.H,
                 n_train=args.views, n_test=args.test_views)

    cfg = make_cfg(
        os.path.join(_REPO, "configs", "nerf", args.config),
        [
            "scene", scene,
            "exp_name", args.tag,
            "train_dataset.data_root", args.scene_root,
            "test_dataset.data_root", args.scene_root,
            "train_dataset.H", str(args.H), "train_dataset.W", str(args.H),
            "test_dataset.H", str(args.H), "test_dataset.W", str(args.H),
            "test_dataset.cams", "[0, -1, 1]",
            "task_arg.N_rays", str(args.n_rays),
            # preset the packed-eval cap (NGP trainer reads it; inert
            # elsewhere) instead of paying the escalate-recompile loop's
            # executable rebuilds inside the eval cadence
            "task_arg.ngp_packed_cap_avg_eval", str(args.eval_cap),
            "precision.compute_dtype", "bfloat16",
            *args.opts,
        ],
    )

    ngp = bool(cfg.task_arg.get("ngp_training", False))
    network = make_network(cfg)
    evaluator = make_evaluator(cfg)
    if ngp:
        # occupancy-accelerated training (train/ngp.py): live-grid march,
        # fine network only; eval goes through the march with the live grid
        from nerf_replication_tpu.train.ngp import make_ngp_trainer

        trainer = make_ngp_trainer(cfg, network)
        state, schedule = trainer.make_state(jax.random.PRNGKey(0))
    else:
        loss = make_loss(cfg, network)
        trainer = Trainer(cfg, network, loss, evaluator)
        state, schedule = make_train_state(cfg, network, jax.random.PRNGKey(0))

    def run_val(state, epoch):
        if not ngp:
            return trainer.val(
                state, epoch=epoch, test_dataset=test_ds,
                max_images=args.test_views,
            )
        return trainer.val(
            state, test_ds, evaluator, max_images=args.test_views
        )

    train_ds = make_dataset(cfg, "train")
    test_ds = make_dataset(cfg, "test")
    bank = tuple(jax.device_put(a) for a in train_ds.ray_bank())
    pool = None
    if not ngp and trainer.precrop_iters > 0:
        pool = jax.device_put(
            train_ds.precrop_index_pool(
                float(cfg.task_arg.get("precrop_frac", 0.5))
            )
        )
    base_key = jax.random.PRNGKey(1)

    budget_s = args.minutes * 60.0
    t0 = time.time()
    next_eval = args.eval_every_s
    trace = []
    host_step = 0
    crossed_at = None
    trace_path = os.path.join(_REPO, args.out_prefix + ".jsonl")
    # append, never truncate: a restart with the same prefix must not destroy
    # the previous run's records (two traces were lost that way already); a
    # run-header line marks each run's start so restarts stay attributable
    with open(trace_path, "a") as tf:
        tf.write(json.dumps({
            "run_start": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "config": args.config, "scene": scene, "H": args.H,
            "views": args.views, "n_rays": args.n_rays,
            "minutes": args.minutes,
            "device": jax.devices()[0].device_kind,
        }) + "\n")
        tf.flush()
        while time.time() - t0 < budget_s:
            # ~100 optimizer steps between host syncs, dispatched as
            # scan-bursts (task_arg.scan_steps) — per-dispatch latency on
            # the tunnel is ~0.3-0.4 s, which halved a non-burst packed
            # trail's step rate (PERF.md round 4)
            it = 0
            while it < 100:
                if ngp:
                    state, stats = trainer.multi_step(
                        state, bank[0], bank[1], base_key
                    )
                    it += trainer.last_burst_steps
                    host_step += trainer.last_burst_steps
                else:
                    use_pool = (
                        pool is not None and host_step < trainer.precrop_iters
                    )
                    if use_pool:
                        state, stats = trainer.step(
                            state, bank[0], bank[1], base_key,
                            index_pool=pool,
                        )
                        k = 1
                    else:
                        state, stats = trainer.multi_step(
                            state, bank[0], bank[1], base_key
                        )
                        k = trainer.scan_steps
                    host_step += k
                    it += k
            jax.block_until_ready(stats)
            elapsed = time.time() - t0
            if elapsed >= next_eval or elapsed >= budget_s:
                next_eval = elapsed + args.eval_every_s
                result = run_val(state, host_step)
                rec = {
                    "t_s": round(elapsed, 1), "step": host_step,
                    "loss": float(stats["loss"]), **result,
                }
                trace.append(rec)
                tf.write(json.dumps(rec) + "\n")
                tf.flush()
                print(json.dumps(rec), flush=True)
                if crossed_at is None and result.get("psnr", 0) >= args.target_psnr:
                    crossed_at = rec

    save_model(cfg.trained_model_dir, state, epoch=host_step // 500,
               recorder_state={}, latest=True)

    # artifacts: occupancy grid → accelerated video
    from nerf_replication_tpu.renderer import make_renderer
    from nerf_replication_tpu.renderer.occupancy import (
        bake_occupancy_grid,
        save_occupancy_grid,
    )

    params = {"params": state.params}
    if ngp:
        # NGP mode maintains the grid live during training — save THAT
        # (baking from the coarse net would be garbage: NGP trains fine only)
        grid = np.asarray(state.grid_ema > trainer.threshold)
        thresh = trainer.threshold
    else:
        grid = bake_occupancy_grid(params, network, cfg)
        thresh = float(cfg.task_arg.get("occupancy_grid_threshold", 1.0))
    grid_path = os.path.join(cfg.trained_model_dir, "occupancy_grid.npz")
    bbox = cfg.train_dataset.scene_bbox
    save_occupancy_grid(grid_path, grid, bbox, thresh)
    print(f"occupancy grid: {grid_path} "
          f"({100.0 * float(np.asarray(grid).mean()):.1f}% occupied)")

    import render_video

    renderer = make_renderer(cfg, network)
    renderer.load_occupancy_grid(grid_path)

    # eval-fps shootout (VERDICT r4 #3): the accelerated marcher must be
    # SHOWN faster than the chunked path at equal PSNR on the trained net
    # with the carved grid — not assumed. Runs BEFORE the video stage's
    # budget doubling so it measures the EVAL operating point. Protocol =
    # the reference's run.py:73-87: per-image wall clock ended on a
    # device→host copy; image 0 is rendered once untimed (compile warmup)
    # so every timed render — including single-test-view runs — is warm.
    from nerf_replication_tpu.evaluators.nerf import psnr as _psnr
    from nerf_replication_tpu.evaluators.nerf import ssim as _ssim

    fps_rows = []

    def _fps_path(tag, render_fn):
        times, ps, ss = [], [], []
        n_img = min(test_ds.n_images, max(int(args.test_views), 1))
        np.asarray(render_fn(test_ds.image_batch(0))["rgb_map_f"])  # warm
        for i in range(n_img):
            b = test_ds.image_batch(i)
            t0i = time.perf_counter()
            out = render_fn(b)
            pred = np.asarray(out["rgb_map_f"])  # device→host sync
            times.append(time.perf_counter() - t0i)
            meta = b["meta"]
            pred = np.clip(
                pred.reshape(meta["H"], meta["W"], 3), 0.0, 1.0
            )
            gt = np.asarray(b["rgbs"]).reshape(meta["H"], meta["W"], 3)
            ps.append(float(_psnr(pred, gt)))
            ss.append(float(_ssim(pred, gt)))
        mean_s = float(np.mean(times))
        rec = {
            "eval_fps_path": tag,
            "s_per_image": round(mean_s, 4),
            "fps": round(1.0 / mean_s, 3),
            "psnr": round(float(np.mean(ps)), 3),
            "ssim": round(float(np.mean(ss)), 4),
            "n_images": n_img,
            "H": args.H,
        }
        fps_rows.append(rec)
        print(json.dumps(rec), flush=True)
        with open(trace_path, "a") as tfa:
            tfa.write(json.dumps(rec) + "\n")
        return rec

    if ngp:
        # chunked coarse+fine is meaningless here (NGP trains fine only);
        # the march with the live grid IS the fast path — measure it
        _fps_path(
            "ngp_march",
            lambda b: trainer.render_image(state, {"rays": b["rays"]}),
        )
    else:
        _fps_path(
            "render_chunked",
            lambda b: renderer.render_chunked(params, {
                "rays": jnp.asarray(b["rays"]),
                "near": float(b["near"]), "far": float(b["far"]),
            }),
        )
        _fps_path(
            "render_accelerated",
            lambda b: renderer.render_accelerated(params, {
                "rays": jnp.asarray(b["rays"]),
                "near": float(b["near"]), "far": float(b["far"]),
            }),
        )
        # drain the on-device truncation counter so the video stage's
        # report attributes only ITS truncated rays, not the shootout's
        renderer.report_truncation(
            log=lambda m: print(f"[fps shootout] {m}")
        )

    # the renderer takes the eval march budget when the config defines it
    # (task_arg.eval_max_march_samples — MarchOptions.eval_from_cfg). For
    # configs without eval keys, keep the measured video margin: at the
    # shared K=192 the chip quality run truncated ~2.3% of spiral rays
    # while still transparent, so offline video doubles the budget —
    # AFTER the fps shootout, which must measure the eval operating point.
    if "eval_max_march_samples" not in cfg.task_arg:
        from dataclasses import replace as _dc_replace

        renderer.march_options = _dc_replace(
            renderer.march_options,
            max_samples=2 * renderer.march_options.max_samples,
        )
    frames = render_video.spiral_frames(
        renderer, params, H=min(args.H, 200), W=min(args.H, 200),
        focal=test_ds.focal * min(args.H, 200) / args.H,
        near=float(cfg.task_arg.near), far=float(cfg.task_arg.far),
        n_frames=60,
    )
    os.makedirs(cfg.result_dir, exist_ok=True)
    video_path = render_video._write_video(
        os.path.join(cfg.result_dir, "video"), frames
    )
    print(f"video: {video_path}")

    # QUALITY.md
    best = max(trace, key=lambda r: r.get("psnr", 0), default=None)
    lines = [
        "# QUALITY — trained artifact trace",
        "",
        f"Scene: {scene} {args.H}²×{args.views} views; config {args.config} "
        f"(N_rays={args.n_rays}, bf16); budget {args.minutes:.0f} min on "
        f"`{jax.devices()[0].device_kind}`.",
        "",
        "| t (s) | step | loss | PSNR | SSIM |",
        "|---|---|---|---|---|",
    ]
    for r in trace:
        lines.append(
            f"| {r['t_s']} | {r['step']} | {r['loss']:.4f} | "
            f"{r.get('psnr', float('nan')):.2f} | "
            f"{r.get('ssim', float('nan')):.3f} |"
        )
    lines.append("")
    if crossed_at:
        lines.append(
            f"**Crossed the reference's {args.target_psnr} dB at "
            f"t={crossed_at['t_s']} s (step {crossed_at['step']})** — the "
            f"reference took 237k steps / ~14.6 h at 0.222 s/iter to get "
            f"there (log.txt)."
        )
    elif best:
        lines.append(
            f"Best PSNR {best.get('psnr', 0):.2f} dB at t={best['t_s']} s; "
            f"did not cross {args.target_psnr} dB in budget."
        )
    # state the BASELINE.md north star (≥30 dB) outcome explicitly either
    # way, not just when it is missed (VERDICT r2 weak #7)
    north = next((r for r in trace if r.get("psnr", 0) >= NORTH_STAR_DB), None)
    if north:
        lines.append(
            f"\n**North star (BASELINE.md ≥{NORTH_STAR_DB:g} dB): crossed at "
            f"t={north['t_s']} s (step {north['step']}).**"
        )
    elif best:
        lines.append(
            f"\nNorth star (BASELINE.md ≥{NORTH_STAR_DB:g} dB): NOT reached — best "
            f"{best.get('psnr', 0):.2f} dB; gap "
            f"{NORTH_STAR_DB - best.get('psnr', 0):.2f} dB."
        )
    if len(trace) >= 2 and best and best.get("psnr", 0) > 0:
        # crude wall-clock-to-30dB estimate from the tail slope
        a, b = trace[-2], trace[-1]
        dpsnr = b.get("psnr", 0) - a.get("psnr", 0)
        if dpsnr > 1e-3:
            eta = (NORTH_STAR_DB - b["psnr"]) * (b["t_s"] - a["t_s"]) / dpsnr
            lines.append(
                f"\nTail slope {dpsnr:.2f} dB / {b['t_s'] - a['t_s']:.0f} s "
                f"⇒ naive wall-clock-to-north-star ≈ {b['t_s'] + max(eta, 0):.0f} s "
                "(log-shaped convergence makes this a lower bound)."
            )
    if fps_rows:
        lines.append("\n## Eval fps (ref run.py:73-87 protocol: first "
                     "image excluded, timed to a device→host copy)\n")
        lines.append("| path | s/image | fps | PSNR | SSIM |")
        lines.append("|---|---|---|---|---|")
        for r in fps_rows:
            lines.append(
                f"| {r['eval_fps_path']} | {r['s_per_image']} | "
                f"{r['fps']} | {r['psnr']:.2f} | {r['ssim']:.3f} |"
            )
    with open(os.path.join(_REPO, args.out_prefix + ".md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {args.out_prefix}.md")


if __name__ == "__main__":
    main()
