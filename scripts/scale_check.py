"""Full-scale eval exercise (VERDICT r1 #7): one real 800×800 image through
both eval paths, with the memory/compile diagnostics that small tests miss.

A real Blender eval is 640k rays × 256 samples through `render_chunked`, and
640k × K=192 compacted march points through `render_accelerated` — regimes
no unit test reaches (tests render ≤64²). This script renders one synthetic
800×800 view with a randomly-initialized flagship network and reports, as
JSON lines:

* wall time + rays/s for each path (post-compile),
* peak device memory (``memory_stats``) after each path,
* the accelerated path's truncation count at the K budget
  (``Renderer.report_truncation``), against a half-occupied grid,
* the executable-cache sizes (``_chunked_fns``/``_march_fns``) after
  rendering at two different (near, far) bounds — bounding the LRU growth
  the round-1 review flagged (renderer/volume.py:383-404).

    python scripts/scale_check.py [--H 800] [--chunk 8192] [--grid 128]
        [--force_platform cpu]  (CPU smoke: --H 64 --chunk 2048 --grid 32)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _peak_mb():
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return round(stats["peak_bytes_in_use"] / 2**20, 1)
    except Exception:
        pass
    return None


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--H", type=int, default=800)
    p.add_argument("--chunk", type=int, default=8192)
    p.add_argument("--grid", type=int, default=128)
    p.add_argument("--force_platform", default=os.environ.get(
        "BENCH_FORCE_PLATFORM", ""))
    p.add_argument("opts", nargs="*", default=[],
                   help="trailing cfg key/value overrides (CPU smoke: tiny net)")
    args = p.parse_args(argv)

    from nerf_replication_tpu.utils.platform import setup_backend

    setup_backend(args.force_platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.datasets.rays import get_rays_np
    from nerf_replication_tpu.models import init_params_for, make_network
    from nerf_replication_tpu.renderer import make_renderer

    H = W = args.H
    cfg = make_cfg(
        os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        [
            "task_arg.chunk_size", str(args.chunk),
            "task_arg.march_chunk_size", str(args.chunk),
            "task_arg.occupancy_grid_res", str(args.grid),
            "precision.compute_dtype", "bfloat16",
            *args.opts,
        ],
    )
    network = make_network(cfg)
    params = init_params_for(cfg)(network, jax.random.PRNGKey(0))
    renderer = make_renderer(cfg, network)

    # one real-scale view: pinhole rays from a lego-style pose
    focal = 0.5 * W / np.tan(0.5 * 0.6911)
    c2w = np.eye(4, dtype=np.float32)
    c2w[2, 3] = 4.0
    o, d = get_rays_np(H, W, focal, c2w)
    rays = jnp.asarray(
        np.concatenate([o.reshape(-1, 3), d.reshape(-1, 3)], -1), jnp.float32
    )
    n = rays.shape[0]
    print(f"scale_check: {H}x{W} = {n} rays, chunk {args.chunk}, "
          f"platform {jax.devices()[0].platform}", file=sys.stderr)

    def run(tag, fn, drain=None, **extra):
        out = fn()
        jax.block_until_ready(out["rgb_map_f"])
        if drain is not None:
            drain()  # e.g. reset the truncation accumulator after warmup
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out["rgb_map_f"])
        dt = time.perf_counter() - t0
        assert out["rgb_map_f"].shape == (n, 3)
        assert bool(jnp.isfinite(out["rgb_map_f"]).all())
        rec = {"path": tag, "s_per_image": round(dt, 3),
               "rays_per_sec": round(n / dt, 1), "peak_mb": _peak_mb(),
               **extra}
        print(json.dumps(rec), flush=True)
        return out

    batch = {"rays": rays, "near": 2.0, "far": 6.0}
    run("render_chunked", lambda: renderer.render_chunked(params, batch))

    # accelerated path against a half-occupied grid (random init has no
    # learned geometry — a random grid exercises compaction + ERT masking)
    grid = np.asarray(
        jax.random.uniform(jax.random.PRNGKey(1),
                           (args.grid,) * 3) < 0.5
    )
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)
    renderer.occupancy_grid = jnp.asarray(grid)
    renderer.grid_bbox = jnp.asarray(bbox)
    run("render_accelerated",
        lambda: renderer.render_accelerated(params, batch),
        drain=lambda: renderer.report_truncation(log=lambda *_: None))
    n_trunc = renderer.report_truncation(log=lambda *_: None)
    print(json.dumps({
        "path": "render_accelerated", "n_truncated": int(n_trunc),
        "k_budget": renderer.march_options.max_samples,
        "truncated_pct": round(100.0 * n_trunc / n, 2),
    }), flush=True)

    # executable-cache bound: render at a second (near, far); the march cache
    # is keyed on bounds and must stay within its LRU cap
    batch2 = {"rays": rays, "near": 2.5, "far": 5.5}
    renderer.render_accelerated(params, batch2)
    print(json.dumps({
        "chunked_fns": len(renderer._chunked_fns),
        "march_fns": len(renderer._march_fns),
        "march_fns_cap": renderer._march_fns_cap,
    }), flush=True)
    assert len(renderer._march_fns) <= renderer._march_fns_cap


if __name__ == "__main__":
    main()
