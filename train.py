"""Training entry point.

Parity with the reference's `train.py:31-135`: build everything from the YAML
config, resume from the latest checkpoint, run the epoch loop with periodic
save/eval, with multi-process (multi-host) support. The process topology is
JAX's (`jax.distributed` + mesh collectives) rather than NCCL/DDP; there is no
`kill -9` self-termination (reference train.py:131) because there are no
dataloader workers to orphan.

Usage:
    python train.py --cfg_file configs/nerf/lego.yaml [key value ...]
    python train.py --cfg_file configs/nerf/lego.yaml --test   # eval only
"""

from __future__ import annotations


def main():
    from nerf_replication_tpu.config import cfg_from_args, make_parser

    args = make_parser().parse_args()
    cfg = cfg_from_args(args)
    from nerf_replication_tpu.utils.setup import configure_runtime

    configure_runtime(cfg)

    if args.test:
        from run import run_evaluate

        run_evaluate(cfg, args)
        return

    from nerf_replication_tpu.train.trainer import fit

    fit(cfg)


if __name__ == "__main__":
    main()
