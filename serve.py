"""HTTP render-serving entrypoint over the batched serving engine.

Loads a trained experiment ONCE (checkpoint + baked occupancy grid),
pre-warms the shape-bucketed executables, and serves render requests over
plain stdlib HTTP — concurrent requests coalesce through the micro-batcher
and share warm executables; repeated views hit the pose cache; backlog is
shed to degraded tiers instead of timing out (docs/serving.md).

    python serve.py --cfg_file configs/nerf/lego.yaml --port 8008
    curl -s localhost:8008/render -d '{"theta": 40, "phi": -30, "radius": 4}'
    curl -s localhost:8008/stats

API (all JSON):

* ``POST /render`` — body carries a spherical pose (``theta``/``phi``/
  ``radius`` degrees, degrees, world units) OR a ``c2w`` 3x4/4x4 matrix;
  optional ``H``/``W``/``focal`` override the dataset camera; optional
  ``scene`` names a registry scene when the ``fleet:`` block is
  configured (absent = the engine's own checkpoint, API-compatible);
  optional ``tenant`` meters the request through that tenant's QoS token
  bucket when ``fleet.qos.enabled`` (over-quota -> 429 + ``Retry-After``;
  a tenant whose batches keep failing trips only its OWN breaker).
  Response: ``{h, w, tier, cache_hit, latency_ms, rgb_b64}`` with
  ``rgb_b64`` the base64 of the raw uint8 [h, w, 3] buffer.
* ``GET /stats`` — engine + batcher + cache counters (compile inventory,
  occupancy, shed/timeout counts, queue depth) plus, multi-scene, the
  ``fleet`` residency block (resident set, evictions, prefetch hits).
* ``GET /healthz`` — supervision view: queue depth, last-dispatch age,
  circuit-breaker state, worker liveness/restarts, plus the ``slo`` block
  (latency attainment vs. ``obs.slo_target_ms``, shed/timeout/error/
  breaker rates from the live metrics registry) and the ``replica``
  block (id, warm-start source, compile count, resident scenes) that
  scale-out heartbeats read (docs/scaleout.md). 200 while healthy,
  503 when the breaker is open or the worker cannot be kept alive.
* ``POST /drain`` — drain-before-retire: stop admitting, render
  everything queued, reply ``{drained, n_failed}`` (scale/replica.py).
* ``GET /metrics`` — Prometheus text exposition of the live counters/
  gauges/histograms (obs/metrics.py): request counts by status and tier,
  queue depth, per-stage latency histograms fed by the span tracer.

With ``obs.trace`` enabled (the default), every request runs under a
root span whose children — queue wait, scene acquire, dispatch, device
block, scatter — land in ``telemetry.jsonl`` as ``span`` rows
(``scripts/trace_view.py`` exports them to chrome://tracing), and a
crash/breaker-open/SIGTERM dumps the recent-span ring to
``flight_<reason>.json`` (docs/observability.md).

Errors are structured JSON, never stack traces (docs/robustness.md):
bad pose / out-of-bounds request → 400, unknown scene → 404, over-quota
tenant → 429 with a ``Retry-After`` header, batcher
timeout → 504, breaker open → 503 with a ``Retry-After`` header, a
torn/unloadable/over-budget scene → 503 for THAT scene only (every other
resident scene keeps serving), anything else → 500
``{"error": "internal error"}``.
"""

from __future__ import annotations

import argparse
import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _resolve_pose(body: dict):
    """c2w matrix from a request body (spherical pose or explicit matrix)."""
    import numpy as np

    from nerf_replication_tpu.datasets.rays import pose_spherical

    if "c2w" in body:
        c2w = np.asarray(body["c2w"], np.float32)
        if c2w.shape not in ((3, 4), (4, 4)):
            raise ValueError(f"c2w must be 3x4 or 4x4, got {c2w.shape}")
        return c2w
    try:
        return pose_spherical(
            float(body["theta"]), float(body.get("phi", -30.0)),
            float(body.get("radius", 4.0)),
        )
    except KeyError:
        raise ValueError(
            "request must carry either 'c2w' or a spherical pose "
            "('theta' [, 'phi', 'radius'])"
        ) from None


def render_pose(engine, batcher, body: dict) -> dict:
    """One request: pose -> cached or batch-rendered image -> JSON fields."""
    camera = dict(engine.default_camera or {"H": 400, "W": 400, "focal": 555.0})
    H = int(body.get("H", camera["H"]))
    W = int(body.get("W", camera["W"]))
    focal = float(body.get("focal", camera["focal"]))
    scene = body.get("scene")
    scene = None if scene is None else str(scene)
    tenant = body.get("tenant")
    tenant = None if tenant is None else str(tenant)
    c2w = _resolve_pose(body)

    timeout = engine.options.request_timeout_s + 30.0  # queue + render slack
    via = None
    if batcher is not None:
        via = lambda rays, near, far: (  # noqa: E731
            batcher.submit(rays, near, far, scene=scene,
                           tenant=tenant).result(timeout)
        )
    t0 = time.perf_counter()
    image, info = engine.render_view(c2w, H, W, focal, via=via, scene=scene)
    out = {
        "h": H,
        "w": W,
        "tier": info["tier"],
        "cache_hit": bool(info["cache_hit"]),
        "latency_ms": (time.perf_counter() - t0) * 1e3,
        "rgb_b64": base64.b64encode(image.tobytes()).decode("ascii"),
    }
    if scene is not None:
        out["scene"] = scene
    return out


def make_server(engine, batcher, host: str = "127.0.0.1",
                port: int = 8008,
                slo_target_ms: float = 100.0,
                alerts=None,
                slo_window_s: float | None = None) -> ThreadingHTTPServer:
    """A ready-to-serve ThreadingHTTPServer (port 0 = ephemeral, tests).

    ``alerts`` (an ``obs.alerts.AlertEngine``) adds ``GET /alerts`` and
    an ``alerts`` block to /healthz; ``slo_window_s`` switches the
    /healthz SLO view from whole-lifetime rates to a sliding window (so
    a long-idle replica can't mask a fresh regression)."""
    from nerf_replication_tpu.fleet import (
        ResidencyOverloadError,
        SceneError,
        TenantQuotaError,
        UnknownSceneError,
    )
    from nerf_replication_tpu.obs import get_metrics, get_tracer
    from nerf_replication_tpu.obs.trace import TRACE_HEADER, SpanContext
    from nerf_replication_tpu.resil import BreakerOpenError, report
    from nerf_replication_tpu.serve.batcher import ServeTimeoutError

    slo_target_s = float(slo_target_ms) / 1e3

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet: telemetry is the record
            pass

        def _reply(self, code: int, payload: dict,
                   headers: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                health = batcher.health() if batcher is not None else {"ok": True}
                health["slo"] = get_metrics().slo_view(
                    slo_target_s, window_s=slo_window_s)
                if alerts is not None:
                    health["alerts"] = alerts.healthz_block()
                # replica block: what scale/replica.py's ProcessReplica
                # heartbeat reads (id from the supervisor's spawn env,
                # warm-start provenance, resident scenes for affinity)
                import os

                stats = engine.stats() if hasattr(engine, "stats") else {}
                trs = get_tracer().stats()
                fleet = getattr(engine, "fleet", None)
                fs = fleet.stats() if fleet is not None else {}
                health["replica"] = {
                    "id": os.environ.get("SCALE_REPLICA_ID", ""),
                    "warm_source": stats.get("warm_source"),
                    "total_compiles": stats.get("total_compiles", 0),
                    "scenes": (engine.resident_scenes()
                               if hasattr(engine, "resident_scenes") else []),
                    # full residency state for the placement planner
                    # (scale/placement.py): staging-tier scene ids plus
                    # HBM/staging byte watermarks and ladder budgets —
                    # a remote process exposes what an in-process
                    # replica's heartbeat reads off its ladder directly
                    "staging": list(fs.get("staging", [])),
                    "hbm_bytes": int(fs.get("resident_bytes", 0)),
                    "staging_bytes": int(fs.get("staging_bytes", 0)),
                    "hbm_budget_bytes": int(fs.get("budget_bytes", 0)),
                    "staging_budget_bytes": int(
                        fs.get("staging_budget_bytes", 0)),
                    "param_shards": int(fs.get("param_shards", 1)),
                    # tracing health, surfaced to the router's heartbeat:
                    # spans emitted, sink drops, and how many spans
                    # parented under a propagated (router) ctx
                    "trace": {
                        "enabled": trs["enabled"],
                        "spans": trs["spans"],
                        "dropped_sink": trs["dropped_sink"],
                        "remote_parented": trs["remote_parented"],
                    },
                }
                return self._reply(200 if health["ok"] else 503, health)
            if self.path == "/stats":
                stats = engine.stats()
                if batcher is not None:
                    stats["batcher"] = batcher.stats()
                return self._reply(200, stats)
            if self.path == "/alerts":
                if alerts is None:
                    return self._reply(
                        200, {"enabled": False, "firing": [], "alerts": []})
                return self._reply(200, alerts.status())
            if self.path == "/metrics":
                data = get_metrics().render_prometheus().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            return self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path == "/drain":
                # drain-before-retire entry (scale/replica.py): stop NEW
                # admissions, render everything queued, report failures.
                # Retirement (process exit) stays with the supervisor.
                if batcher is None:
                    return self._reply(200, {"drained": True, "n_failed": 0})
                # the drain is traced too: a propagated header parents it
                # under the router's retirement flow
                with get_tracer().span(
                    "serve.drain",
                    parent=SpanContext.from_header(
                        self.headers.get(TRACE_HEADER)),
                ) as sp:
                    before = (batcher.n_timeouts + batcher.n_dispatch_errors
                              + batcher.n_scene_errors)
                    batcher.close(drain=True)
                    failed = (batcher.n_timeouts + batcher.n_dispatch_errors
                              + batcher.n_scene_errors) - before
                    sp.set(detail=f"drain_failed={int(failed)}")
                return self._reply(200, {"drained": True,
                                         "n_failed": int(failed)})
            if self.path != "/render":
                return self._reply(404, {"error": f"no route {self.path}"})
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                scene = body.get("scene")
                tenant = body.get("tenant")
                # the REQUEST's root span: a propagated Traceparent
                # header (the router's dispatch span) parents this
                # process's whole tree under the router's trace — one
                # routed request, ONE trace. Without the header,
                # parent=None starts a fresh trace as before; either
                # way the batcher submit captures the ctx into the
                # queue entry, making every downstream stage
                # (worker/prefetch threads included) a descendant.
                with get_tracer().span(
                    "serve.request",
                    parent=SpanContext.from_header(
                        self.headers.get(TRACE_HEADER)),
                    scene=None if scene is None else str(scene),
                    tenant=None if tenant is None else str(tenant),
                ):
                    out = render_pose(engine, batcher, body)
                return self._reply(200, out)
            except TenantQuotaError as err:
                # over-quota tenant: typed 429 with the bucket's own
                # refill horizon — scoped to the tenant, not the server
                return self._reply(
                    429, {"error": str(err), "tenant": err.tenant,
                          "retry_after_s": err.retry_after_s},
                    headers={"Retry-After": str(max(1, round(err.retry_after_s)))},
                )
            except BreakerOpenError as err:
                return self._reply(
                    503, {"error": str(err),
                          "retry_after_s": err.retry_after_s},
                    headers={"Retry-After": str(max(1, round(err.retry_after_s)))},
                )
            except UnknownSceneError as err:
                return self._reply(
                    404, {"error": str(err), "scene": err.scene_id})
            except ResidencyOverloadError as err:
                # every resident scene is pinned: momentary, retryable
                return self._reply(
                    503, {"error": str(err), "scene": err.scene_id},
                    headers={"Retry-After": "1"},
                )
            except SceneError as err:
                # torn/unloadable scene: 503 for THIS scene only — the
                # fault row is already in telemetry, other scenes serve on
                return self._reply(
                    503, {"error": str(err), "scene": err.scene_id})
            except (ServeTimeoutError, TimeoutError) as err:
                return self._reply(
                    504, {"error": str(err) or "render timed out"})
            except (ValueError, KeyError) as err:
                # BakedBoundsError is a ValueError: caller asked for a view
                # outside the baked near/far — a client error, not ours
                return self._reply(400, {"error": str(err)})
            except Exception as err:
                # structured 500: the detail goes to telemetry, never to
                # the client (no stack traces on the wire)
                report("serve.request", "error",
                       detail=f"{type(err).__name__}: {err}"[:200])
                return self._reply(500, {"error": "internal error"})

    return ThreadingHTTPServer((host, port), Handler)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="NeRF render-serving endpoint")
    p.add_argument("--cfg_file", default="configs/nerf/lego.yaml")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8008)
    p.add_argument("opts", default=[], nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.obs import configure_tracing, get_metrics, init_run
    from nerf_replication_tpu.serve import MicroBatcher, engine_from_cfg
    from nerf_replication_tpu.utils.setup import configure_runtime

    cfg = make_cfg(args.cfg_file, args.opts or (), default_task="run")
    configure_runtime(cfg)
    emitter = init_run(cfg, component="serve")

    # observability wiring (cfg.obs): request tracing + the crash flight
    # recorder come up BEFORE the engine so warm-up and the first request
    # are both on the record
    from nerf_replication_tpu.resil import (
        CircuitBreaker,
        FlightRecorder,
        PreemptionGuard,
        install_flight_recorder,
    )

    o = cfg.get("obs", {})
    trace_on = bool(o.get("trace", True))
    trace_ring = int(o.get("trace_ring", 256))
    flight_dir = str(o.get("flight_dir", "")) or str(
        cfg.get("record_dir", "."))
    slo_target_ms = float(o.get("slo_target_ms", 100.0))
    # a replica's span ids carry its id as a prefix, so a --fleet merge
    # of several replicas' telemetry joins on globally-unique ids
    import os

    replica_id = os.environ.get("SCALE_REPLICA_ID", "")
    configure_tracing(enabled=trace_on, id_prefix=replica_id)
    install_flight_recorder(FlightRecorder(flight_dir, capacity=trace_ring))

    # ops intelligence (cfg.obs.alerts): the burn-rate alert engine, the
    # incident correlator (alert fires / flight dumps open incidents next
    # to the telemetry), and the capacity/heat ledger — all fed from the
    # emitter's row-tap bus, evaluated in the poll loop below
    alerts = incidents = capacity = None
    slo_window_s = None
    capacity_every_s = 30.0
    if bool(cfg.obs.alerts.enabled):
        from nerf_replication_tpu.obs import (
            AlertEngine,
            AlertOptions,
            CapacityLedger,
            IncidentManager,
        )
        from nerf_replication_tpu.resil.flight import add_dump_listener

        slo_window_s = float(cfg.obs.alerts.view_window_s)
        capacity_every_s = float(cfg.obs.alerts.capacity_every_s)
        alerts = AlertEngine(AlertOptions.from_cfg(cfg),
                             slo_target_s=slo_target_ms / 1e3,
                             replica=replica_id).attach()
        incidents = IncidentManager(flight_dir, replica=replica_id).attach()
        alerts.add_listener(incidents.on_alert)
        add_dump_listener(incidents.on_flight_dump)
        capacity = CapacityLedger(replica=replica_id,
                                  window_s=slo_window_s).attach()
    # SIGTERM: the guard's handler dumps the flight ring, then the poll
    # loop below drains and exits cleanly (a preempted replica leaves a
    # post-mortem AND closes its telemetry)
    guard = PreemptionGuard.install()

    from nerf_replication_tpu.fleet.qos import QosController

    engine = engine_from_cfg(cfg, cfg_file=args.cfg_file)
    batcher = MicroBatcher(engine, breaker=CircuitBreaker.from_cfg(cfg),
                           qos=QosController.from_cfg(cfg))
    server = make_server(engine, batcher, host=args.host, port=args.port,
                         slo_target_ms=slo_target_ms, alerts=alerts,
                         slo_window_s=slo_window_s)
    print(
        f"serving on http://{args.host}:{server.server_address[1]} "
        f"(buckets {list(engine.buckets)}, "
        f"{'grid' if engine.use_grid else 'volume'} path, "
        f"{engine.warmup_compiles} executables warm, "
        f"tracing {'on' if trace_on else 'off'})"
    )
    try:
        if guard is None and alerts is None:
            server.serve_forever()
        else:
            t = threading.Thread(target=server.serve_forever, daemon=True)
            t.start()
            last_cap = time.monotonic()
            while t.is_alive() and not (guard is not None and guard.triggered):
                t.join(timeout=0.5)
                # the alerting tick: transitions emit alert rows, open/
                # mitigate incidents, and refresh the /alerts view; a
                # capacity_snapshot row commits on its own cadence
                if alerts is not None:
                    alerts.evaluate()
                    incidents.sweep()
                    if time.monotonic() - last_cap >= capacity_every_s:
                        capacity.snapshot()
                        last_cap = time.monotonic()
            server.shutdown()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        batcher.close()
        if capacity is not None:
            capacity.snapshot()  # final ledger state is on the record
        if alerts is not None:
            alerts.evaluate()
            incidents.sweep()
            alerts.detach()
            incidents.detach()
            capacity.detach()
        snap = get_metrics().snapshot()
        snap["slo"] = get_metrics().slo_view(slo_target_ms / 1e3)
        emitter.emit("metrics_snapshot", **snap)
        emitter.close()
        if guard is not None:
            guard.uninstall()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
