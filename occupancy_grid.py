"""Bake the occupancy grid for accelerated rendering.

Parity with the reference's `occupancy_grid.py:15-82`: load the trained
network, sweep an R³ voxel grid of the scene bbox (2×2×2 sub-samples per
voxel) through the coarse density head, threshold, and save the bool grid to
``logs/<config_name>/occupancy_grid.npz``.

    python occupancy_grid.py --cfg_file configs/nerf/lego.yaml
"""

from __future__ import annotations


def main():
    from nerf_replication_tpu.config import cfg_from_args, make_parser
    from nerf_replication_tpu.renderer.occupancy import (
        bake_occupancy_grid,
        default_grid_path,
        occupancy_stats,
        save_occupancy_grid,
    )
    from nerf_replication_tpu.utils.setup import load_trained_network

    parser = make_parser()
    args = parser.parse_args()
    cfg = cfg_from_args(args)
    from nerf_replication_tpu.utils.setup import configure_runtime

    configure_runtime(cfg)

    network, params, _ = load_trained_network(cfg)
    grid = bake_occupancy_grid(params, network, cfg)
    stats = occupancy_stats(grid)
    print(
        f"grid {stats['shape']}: {stats['occupied']}/{stats['total']} occupied "
        f"({stats['occupancy_pct']:.2f}%)"
    )

    path = default_grid_path(args.cfg_file)
    save_occupancy_grid(
        path,
        grid,
        cfg.train_dataset.scene_bbox,
        float(cfg.task_arg.occupancy_grid_threshold),
    )
    print(f"Saving occupancy grid to: {path}")
    print("Done.")


if __name__ == "__main__":
    main()
