"""Stage-by-stage debug/eval CLI.

Parity with the reference's `run.py:5-91` — the operational smoke-test
workflow (SURVEY.md §4): each subcommand exercises one pipeline stage
end-to-end.

    python run.py --type dataset  --cfg_file configs/nerf/lego.yaml
    python run.py --type network  --cfg_file configs/nerf/lego.yaml
    python run.py --type evaluate --cfg_file configs/nerf/lego.yaml

* ``dataset``: iterate the loader contract (timed batch draws).
* ``network``: timed full-image forward over the test set.
* ``evaluate``: render + PSNR/SSIM metrics + per-image net_time / fps report,
  using the occupancy-accelerated renderer when a baked grid exists
  (reference run.py:64-67; missing grid falls back to the vanilla path).
"""

from __future__ import annotations

import time

import numpy as np


def _load_eval_setup(cfg):
    """network + params (from the trained checkpoint) + renderer + test set."""
    from nerf_replication_tpu.datasets import make_dataset
    from nerf_replication_tpu.renderer import make_renderer
    from nerf_replication_tpu.utils.setup import load_trained_network

    network, params, _ = load_trained_network(cfg)
    renderer = make_renderer(cfg, network)
    test_ds = make_dataset(cfg, "test")
    return network, params, renderer, test_ds


def run_dataset(cfg, args=None):
    """Iterate the train loader contract (reference run.py:5-12): the full
    sampler → collate → prefetch pipeline, capped at 1000 batches."""
    from tqdm import tqdm

    from nerf_replication_tpu.datasets import make_data_loader

    loader = make_data_loader(cfg, "train", max_iter=1000)
    t0 = time.time()
    n = 0
    for _ in tqdm(loader):
        n += 1
    dt = time.time() - t0
    print(f"iterated {n} batches in {dt:.2f}s ({n / dt:.1f} it/s)")


def _full_image_render_fn(cfg, network, renderer, test_ds, use_grid=False):
    """Whole-image renderer for the eval CLIs — the shared render gate
    (renderer/gate.py): single-device chunked by default, sequence-parallel
    over the mesh's data axis under ``eval.sharded: true``."""
    from nerf_replication_tpu.renderer.gate import full_image_render_fn

    return full_image_render_fn(cfg, network, renderer, test_ds, use_grid)


def run_network(cfg, args=None):
    """Timed full-image network forward over the test set (run.py:15-40)."""
    import jax

    from tqdm import tqdm

    network, params, renderer, test_ds = _load_eval_setup(cfg)
    render = _full_image_render_fn(cfg, network, renderer, test_ds)
    total_time, net_times = 0.0, []
    for i in tqdm(range(len(test_ds))):
        batch = test_ds.image_batch(i)
        t0 = time.time()
        out = render(params, batch)
        jax.block_until_ready(out)
        net_times.append(time.time() - t0)
        total_time += net_times[-1]
    # first image excluded: it pays compilation (reference excludes it too,
    # run.py:82-87, there for cache warmup)
    times = net_times[1:] if len(net_times) > 1 else net_times
    print(
        f"mean net_time: {np.mean(times):.4f}s  fps: {1.0 / np.mean(times):.3f}"
    )
    from nerf_replication_tpu.obs import init_run

    emitter = init_run(cfg, component="eval_network")
    emitter.emit(
        "eval",
        prefix="network",
        metrics={},
        n_images=len(net_times),
        mean_net_time_s=float(np.mean(times)),
        fps=float(1.0 / np.mean(times)),
    )
    emitter.close()


def run_evaluate(cfg, args=None):
    """Full metric run: render every test view, PSNR/SSIM, summary.json
    (reference run.py:43-87)."""
    import jax

    from tqdm import tqdm

    from nerf_replication_tpu.evaluators import make_evaluator
    from nerf_replication_tpu.renderer.occupancy import default_grid_path

    network, params, renderer, test_ds = _load_eval_setup(cfg)
    evaluator = make_evaluator(cfg)

    accelerated = bool(cfg.task_arg.get("accelerated_renderer", False))
    grid_loaded = False
    if accelerated:
        grid_path = default_grid_path(getattr(args, "cfg_file", "config"))
        grid_loaded = renderer.load_occupancy_grid(grid_path)
    # one gate for all four combinations: (grid?, sharded?) — the march
    # paths when a grid loaded, the vanilla chunked/sequence paths otherwise
    render = _full_image_render_fn(
        cfg, network, renderer, test_ds, use_grid=grid_loaded
    )

    net_times = []
    for i in tqdm(range(len(test_ds))):
        batch = test_ds.image_batch(i)
        t0 = time.time()
        out = render(params, batch)
        jax.block_until_ready(out)
        net_times.append(time.time() - t0)
        out = {k: np.asarray(v) for k, v in out.items()}
        evaluator.evaluate(out, batch)

    result = evaluator.summarize()
    renderer.report_truncation()
    times = net_times[1:] if len(net_times) > 1 else net_times
    print(
        f"mean net_time: {np.mean(times):.4f}s  fps: {1.0 / np.mean(times):.3f}"
    )
    print(result)
    # telemetry: the eval CLI emits a typed row instead of only printing,
    # so quality regressions are diffable by tlm_report like step-time ones
    from nerf_replication_tpu.obs import init_run

    emitter = init_run(cfg, component="evaluate")
    emitter.emit(
        "eval",
        prefix="evaluate",
        metrics={k: float(v) for k, v in (result or {}).items()},
        n_images=len(net_times),
        mean_net_time_s=float(np.mean(times)),
        fps=float(1.0 / np.mean(times)),
    )
    emitter.close()
    return result


def run_mesh(cfg, args=None):
    """Extract the density iso-surface to a PLY mesh (the reference's
    mesh_utils capability, driven by cfg.level / cfg.resolution)."""
    from nerf_replication_tpu.utils.mesh import extract_mesh
    from nerf_replication_tpu.utils.setup import load_trained_network

    network, params, _ = load_trained_network(cfg)
    path = extract_mesh(params, network, cfg)
    print(f"mesh saved to {path}")
    return path


def main():
    from nerf_replication_tpu.config import cfg_from_args, make_parser

    args = make_parser().parse_args()
    cfg = cfg_from_args(args)
    from nerf_replication_tpu.utils.setup import configure_runtime

    configure_runtime(cfg)
    fn = globals().get("run_" + args.type)
    if fn is None:
        known = sorted(
            n[len("run_"):] for n in globals() if n.startswith("run_")
        )
        raise SystemExit(f"unknown --type {args.type!r}; choose from {known}")
    fn(cfg, args)


if __name__ == "__main__":
    main()
