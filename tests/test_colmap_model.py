"""COLMAP model I/O (utils/colmap.py): binary/text round trips and the
quaternion helpers.

The reference vendors COLMAP's read_write_model.py with self-tests that
no runner executes (src/utils/colmap/test_read_write_model.py:37-118 —
SURVEY.md §4 "vendored self-tests"); these are the wired equivalent for
the independent implementation.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nerf_replication_tpu.utils.colmap import (
    Camera,
    Image,
    Point3D,
    qvec2rotmat,
    read_model,
    rotmat2qvec,
    write_model,
)


def _model():
    rng = np.random.default_rng(7)
    cameras = {
        1: Camera(1, "PINHOLE", 640, 480,
                  np.array([500.0, 505.0, 320.0, 240.0])),
        2: Camera(2, "SIMPLE_RADIAL", 800, 600,
                  np.array([450.0, 400.0, 300.0, -0.03])),
    }
    images = {}
    for iid in (1, 2, 3):
        q = rng.normal(size=4)
        q /= np.linalg.norm(q)
        if q[0] < 0:
            q = -q
        m = int(rng.integers(0, 5))
        images[iid] = Image(
            iid, q, rng.normal(size=3), 1 + iid % 2, f"frame_{iid:04d}.png",
            rng.uniform(0, 640, (m, 2)),
            rng.integers(-1, 50, m).astype(np.int64),
        )
    points = {}
    for pid in (10, 11):
        t = int(rng.integers(1, 4))
        points[pid] = Point3D(
            pid, rng.normal(size=3),
            rng.integers(0, 256, 3).astype(np.uint8),
            float(rng.uniform(0, 2)),
            rng.integers(1, 4, t).astype(np.int32),
            rng.integers(0, 5, t).astype(np.int32),
        )
    return cameras, images, points


def _assert_models_equal(a, b):
    for (ca, ia, pa), (cb, ib, pb) in [(a, b)]:
        assert ca.keys() == cb.keys()
        for k in ca:
            x, y = ca[k], cb[k]
            assert (x.model, x.width, x.height) == (y.model, y.width,
                                                   y.height)
            np.testing.assert_array_equal(x.params, y.params)
        assert ia.keys() == ib.keys()
        for k in ia:
            x, y = ia[k], ib[k]
            assert (x.camera_id, x.name) == (y.camera_id, y.name)
            np.testing.assert_array_equal(x.qvec, y.qvec)
            np.testing.assert_array_equal(x.tvec, y.tvec)
            np.testing.assert_array_equal(x.xys, y.xys)
            np.testing.assert_array_equal(x.point3D_ids, y.point3D_ids)
        assert pa.keys() == pb.keys()
        for k in pa:
            x, y = pa[k], pb[k]
            np.testing.assert_array_equal(x.xyz, y.xyz)
            np.testing.assert_array_equal(x.rgb, y.rgb)
            assert x.error == y.error
            np.testing.assert_array_equal(x.image_ids, y.image_ids)
            np.testing.assert_array_equal(x.point2D_idxs, y.point2D_idxs)


@pytest.mark.parametrize("ext", [".bin", ".txt"])
def test_model_roundtrip(tmp_path, ext):
    """write → read is the identity in both encodings (text uses repr
    floats, so even the f64 bits survive)."""
    model = _model()
    d = str(tmp_path / "sparse")
    write_model(*model, d, ext=ext)
    got = read_model(d, ext=ext)
    _assert_models_equal(model, got)
    # auto-detect finds the same files
    _assert_models_equal(model, read_model(d))


def test_cross_format_equivalence(tmp_path):
    """bin→read→write txt→read lands on the same model (the
    model_converter guarantee the reference gets from COLMAP itself)."""
    model = _model()
    d_bin, d_txt = str(tmp_path / "b"), str(tmp_path / "t")
    write_model(*model, d_bin, ext=".bin")
    via = read_model(d_bin)
    write_model(*via, d_txt, ext=".txt")
    _assert_models_equal(model, read_model(d_txt))


def test_missing_points3D_reads_empty(tmp_path):
    cams, ims, pts = _model()
    d = str(tmp_path / "sparse")
    write_model(cams, ims, pts, d, ext=".bin")
    os.remove(os.path.join(d, "points3D.bin"))
    c2, i2, p2 = read_model(d)
    assert p2 == {}
    assert c2.keys() == cams.keys() and i2.keys() == ims.keys()


def test_quaternion_helpers_roundtrip():
    rng = np.random.default_rng(3)
    for _ in range(20):
        q = rng.normal(size=4)
        q /= np.linalg.norm(q)
        if q[0] < 0:
            q = -q
        R = qvec2rotmat(q)
        # R must be a proper rotation
        np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-12)
        np.testing.assert_allclose(np.linalg.det(R), 1.0, atol=1e-12)
        np.testing.assert_allclose(rotmat2qvec(R), q, atol=1e-12)


def test_colmap_stats_cli(tmp_path, capsys):
    """scripts/colmap_stats.py summarizes a model in both output modes
    (the headless seat of the reference's visualize_model.py)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "colmap_stats",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "colmap_stats.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    model = _model()
    # plant an unmatched keypoint deterministically so the -1 filter in
    # model_stats is actually exercised (the random ids may all be >= 0)
    im3 = model[1][3]
    if len(im3.point3D_ids) == 0:
        im3.xys = np.array([[1.0, 2.0]])
        im3.point3D_ids = np.array([-1], np.int64)
    else:
        im3.point3D_ids[0] = -1
    d = str(tmp_path / "sparse")
    write_model(*model, d, ext=".bin")

    s = mod.model_stats(d)
    assert s["n_cameras"] == 2 and s["n_images"] == 3
    assert s["n_points3D"] == 2
    assert s["track_length"]["max"] <= 3
    assert all(
        a <= b
        for a, b in zip(s["points_bbox"]["min"], s["points_bbox"]["max"])
    )
    # triangulated-only observation count: the fixture plants -1 ids
    n_valid = sum(
        int(np.sum(im.point3D_ids != -1)) for im in model[1].values()
    )
    n_all = sum(len(im.point3D_ids) for im in model[1].values())
    assert s["obs_per_image"]["mean"] * s["n_images"] == n_valid
    assert n_all > n_valid  # the planted -1 really is in the model
    assert s["obs_per_image"]["mean"] * s["n_images"] < n_all

    mod.main([d, "--json"])
    out = capsys.readouterr().out
    assert json.loads(out)["n_images"] == 3
    mod.main([d])
    assert "reprojection error" in capsys.readouterr().out


def test_colmap2nerf_reads_written_models(tmp_path):
    """The converter consumes models this module writes — the same
    parse path run_colmap feeds (scripts/colmap2nerf.py parse_model)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "colmap2nerf",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "colmap2nerf.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    model = _model()
    for ext in (".bin", ".txt"):
        d = str(tmp_path / ext.lstrip("."))
        write_model(*model, d, ext=ext)
        cams, ims = mod.parse_model(d)
        # converter-facing surface: cams dict + [(name, cam_id, q, t)]
        assert set(cams) == set(model[0])
        assert cams[1]["model"] == "PINHOLE"
        by_name = {t[0]: t for t in ims}
        assert set(by_name) == {im.name for im in model[1].values()}
        name, cam_id, qvec, tvec = by_name["frame_0002.png"]
        assert cam_id == model[1][2].camera_id
        np.testing.assert_allclose(qvec, model[1][2].qvec)
        np.testing.assert_allclose(tvec, model[1][2].tvec)
