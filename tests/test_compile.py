"""AOT compile registry (nerf_replication_tpu/compile/) — the warmup-tax
subsystem.

Three invariants the cold-start work rests on:

* **zero retrace on dispatch** — executables built (or deserialized) by
  the registry never count as compiles when dispatched through a
  CompileTracker wrap; the ONLY compile accounting is the registry's own
  ``note_compile`` per actual build;
* **artifact round-trip** — a ``serialize=True`` entry persists to the
  repo-anchored cache and a second registry (same config hash, same
  shapes) resolves it from disk with zero builds, reporting
  ``warm_source() == "disk"``;
* **graceful degradation** — unknown names, a disabled registry, and
  failed builds all return None from ``take`` so callers keep their lazy
  jit path.

scripts/bench_cold_start.py asserts the same invariants end-to-end across
process boundaries; this file pins them at unit scale inside tier-1.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from nerf_replication_tpu.compile import (
    AOTRegistry,
    abstract_like,
    artifact_key,
)
from nerf_replication_tpu.obs import CompileTracker


def _registry(tmp_path, tracker, **kw):
    return AOTRegistry(cache_dir=str(tmp_path / "aot"), tracker=tracker,
                       config_hash="test", **kw)


def test_aot_registry_builds_then_zero_retrace_dispatch(tmp_path):
    tracker = CompileTracker()
    reg = _registry(tmp_path, tracker)
    x = jnp.ones((8, 8), jnp.float32)
    f = jax.jit(lambda a: a * 2.0 + 1.0)
    g = jax.jit(lambda a, b: (a @ b).sum())
    reg.register("f", f, (abstract_like(x),))
    reg.register("g", g, (abstract_like(x), abstract_like(x)))
    reg.compile_all(wait=True)

    # exactly one note_compile per AOT build, nothing else
    assert tracker.total_compiles() == 2
    summary = reg.summary()
    assert summary["entries"] == 2 and summary["sources"] == {"compiled": 2}

    pf = tracker.wrap("f", reg.take("f"))
    pg = tracker.wrap("g", reg.take("g"))
    for _ in range(3):
        jax.block_until_ready(pf(x))
        jax.block_until_ready(pg(x, x))
    # dispatching a precompiled executable is never a build
    assert tracker.total_compiles() == 2
    np.testing.assert_allclose(np.asarray(pf(x)), np.asarray(x) * 2.0 + 1.0)
    np.testing.assert_allclose(
        float(pg(x, x)), float((np.asarray(x) @ np.asarray(x)).sum())
    )


def test_aot_abstract_like_passthrough_and_shapes():
    x = jnp.ones((4, 3), jnp.float32)
    sds = jax.ShapeDtypeStruct((2,), jnp.int32)
    tree = abstract_like({"x": x, "s": sds, "k": jnp.zeros((), jnp.uint32)})
    assert tree["s"] is sds  # already-abstract leaves pass through
    assert tree["x"].shape == (4, 3) and tree["x"].dtype == jnp.float32


def test_aot_artifact_roundtrip_second_registry_warm_from_disk(tmp_path):
    x = jnp.ones((16, 6), jnp.float32)
    sig = (abstract_like(x),)

    t1 = CompileTracker()
    r1 = _registry(tmp_path, t1)
    r1.register("render", jax.jit(lambda a: jnp.tanh(a).sum(-1)), sig,
                serialize=True)
    r1.compile_all(wait=True)
    assert t1.total_compiles() == 1
    assert r1.warm_source() == "compiled"
    ref = np.asarray(r1.take("render")(x))

    # fresh registry, same cache dir + config hash: a process restart
    t2 = CompileTracker()
    r2 = _registry(tmp_path, t2)
    r2.register("render", jax.jit(lambda a: jnp.tanh(a).sum(-1)), sig,
                serialize=True)
    r2.compile_all(wait=True)
    assert t2.total_compiles() == 0  # zero builds: deserialized
    assert r2.warm_source() == "disk"
    assert r2.summary()["sources"] == {"disk": 1}
    np.testing.assert_allclose(np.asarray(r2.take("render")(x)), ref)


def test_aot_artifact_key_separates_config_and_shapes():
    sig_a = (jax.ShapeDtypeStruct((8,), jnp.float32),)
    sig_b = (jax.ShapeDtypeStruct((16,), jnp.float32),)
    assert artifact_key("f", sig_a, extra="h1") == artifact_key(
        "f", sig_a, extra="h1"
    )
    # shape, name, and config hash each invalidate the artifact
    assert artifact_key("f", sig_a, "h1") != artifact_key("f", sig_b, "h1")
    assert artifact_key("f", sig_a, "h1") != artifact_key("g", sig_a, "h1")
    assert artifact_key("f", sig_a, "h1") != artifact_key("f", sig_a, "h2")


def test_aot_take_degrades_to_lazy_path(tmp_path):
    tracker = CompileTracker()
    reg = _registry(tmp_path, tracker)
    assert reg.take("never_registered") is None

    # a build failure is captured, not raised: take -> None, callers jit
    bad_sig = (abstract_like(jnp.ones((7,), jnp.float32)),)
    reg.register("bad", jax.jit(lambda a: a.reshape(3, 3)), bad_sig)
    reg.compile_all(wait=True)
    assert reg.take("bad") is None
    assert reg.summary()["errors"] == ["bad"]
    assert tracker.total_compiles() == 0

    off = AOTRegistry(cache_dir=str(tmp_path / "aot"), enabled=False)
    off.register("f", jax.jit(lambda a: a), bad_sig)
    off.compile_all(wait=True)
    assert off.take("f") is None
