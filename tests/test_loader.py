"""Host-side loader stack: samplers, collator registry, DataLoader factory,
Keras weight import — the reference's data/loader machinery surface
(make_dataset.py:13-100, samplers.py:10-131, collate_batch.py:4-12,
network.py:76-123)."""

import numpy as np
import pytest

from nerf_replication_tpu.datasets.collate import (
    default_collate,
    make_collator,
    register_collator,
)
from nerf_replication_tpu.datasets.samplers import (
    BatchSampler,
    DistributedSampler,
    ImageSizeBatchSampler,
    IterationBasedBatchSampler,
    RandomSampler,
    SequentialSampler,
)


def test_random_sampler_epoch_seeding():
    s = RandomSampler(10, seed=3)
    s.set_epoch(0)
    a = list(s)
    s.set_epoch(1)
    b = list(s)
    s.set_epoch(0)
    assert list(s) == a  # deterministic per epoch
    assert a != b  # re-shuffled across epochs
    assert sorted(a) == list(range(10))


def test_distributed_sampler_partitions():
    world = 4
    per_rank = [list(DistributedSampler(10, r, world, seed=1)) for r in range(world)]
    # pad-to-divisible: every rank gets ceil(10/4)=3, union covers all 10
    assert all(len(p) == 3 for p in per_rank)
    covered = set(i for p in per_rank for i in p)
    assert covered == set(range(10))


def test_distributed_sampler_more_ranks_than_items():
    # world > 2·n: padding must tile, not slice — every rank still gets
    # ceil(n/world) items (a short/empty high-rank slice would hang lockstep
    # collectives while low ranks proceed)
    world = 4
    per_rank = [list(DistributedSampler(1, r, world, seed=0)) for r in range(world)]
    assert all(p == [0] for p in per_rank)
    per_rank = [list(DistributedSampler(3, r, world, seed=0)) for r in range(world)]
    assert all(len(p) == 1 for p in per_rank)
    assert set(i for p in per_rank for i in p) == {0, 1, 2}


def test_batch_sampler_shapes():
    bs = BatchSampler(SequentialSampler(10), 4)
    batches = list(bs)
    assert [len(b) for b in batches] == [4, 4, 2]
    bs = BatchSampler(SequentialSampler(10), 4, drop_last=True)
    assert [len(b) for b in list(bs)] == [4, 4]


def test_image_size_batch_sampler_buckets():
    s = ImageSizeBatchSampler(
        SequentialSampler(20), 5, min_hw=(100, 200), max_hw=(200, 300),
        divisor=32, seed=0,
    )
    out = list(s)
    assert len(out) == 4
    for batch in out:
        assert len(batch) == 5
        idxs = [i for i, h, w in batch]
        hws = {(h, w) for i, h, w in batch}
        assert len(hws) == 1  # one size per batch, attached per entry
        (h, w) = hws.pop()
        assert h % 32 == 0 and w % 32 == 0
        assert 100 <= h <= 200 and 200 <= w <= 300
    # fresh sizes on the next epoch (instance RNG stream, not reseeded)
    sizes_ep0 = [b[0][1:] for b in out]
    sizes_ep1 = [b[0][1:] for b in list(s)]
    assert sizes_ep0 != sizes_ep1 or len(set(sizes_ep0)) == 1


def test_image_size_sampler_epoch_reshuffle():
    # IterationBased must reach the sampler through .sampler on the
    # image_size kind too (epoch reshuffling regressed silently before)
    rs = RandomSampler(8, seed=0)
    s = ImageSizeBatchSampler(rs, 4, min_hw=(32, 32), max_hw=(64, 64))
    it = IterationBasedBatchSampler(s, 4)  # 2 epochs of 2 batches
    batches = list(it)
    ep0 = [i for b in batches[:2] for (i, h, w) in b]
    ep1 = [i for b in batches[2:] for (i, h, w) in b]
    assert sorted(ep0) == sorted(ep1) == list(range(8))
    assert ep0 != ep1  # different permutation per epoch


def test_iteration_based_sampler_caps_and_extends():
    base = BatchSampler(SequentialSampler(4), 2)  # 2 batches/pass
    it = IterationBasedBatchSampler(base, 5)
    batches = list(it)
    assert len(batches) == 5  # re-iterates past one epoch
    it2 = IterationBasedBatchSampler(base, 1)
    assert len(list(it2)) == 1


def test_iteration_based_sampler_empty_inner_raises():
    empty = BatchSampler(SequentialSampler(0), 2)
    with pytest.raises(ValueError, match="no batches"):
        list(IterationBasedBatchSampler(empty, 3))


def test_default_collate_meta_exemption():
    items = [
        {"rays": np.zeros((8, 6)), "i": k, "meta": {"H": 4, "W": 2}}
        for k in range(3)
    ]
    out = default_collate(items)
    assert out["rays"].shape == (3, 8, 6)
    assert out["i"].tolist() == [0, 1, 2]
    assert isinstance(out["meta"], list) and out["meta"][0] == {"H": 4, "W": 2}
    # no batch-size type fork: a single item still collates meta to a list
    one = default_collate(items[:1])
    assert isinstance(one["meta"], list) and one["meta"][0]["H"] == 4


def test_collator_registry(tmp_path):
    from nerf_replication_tpu.config import make_cfg
    import os

    @register_collator("test_only_collator")
    def swap(items):
        return {"n": len(items)}

    cfg = make_cfg(
        os.path.join(os.path.dirname(__file__), "..", "configs", "nerf",
                     "lego.yaml"),
        ["train.collator", "test_only_collator"],
    )
    assert make_collator(cfg, "train")([1, 2]) == {"n": 2}
    assert make_collator(cfg, "test") is default_collate
    cfg2 = make_cfg(
        os.path.join(os.path.dirname(__file__), "..", "configs", "nerf",
                     "lego.yaml"),
        ["train.collator", "no_such"],
    )
    with pytest.raises(KeyError):
        make_collator(cfg2, "train")


def test_make_data_loader_end_to_end(tmp_path):
    import os

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.datasets import make_data_loader
    from nerf_replication_tpu.datasets.procedural import generate_scene

    root = str(tmp_path / "scene")
    generate_scene(root, scene="procedural", H=8, W=8, n_train=5, n_test=2)
    cfg = make_cfg(
        os.path.join(os.path.dirname(__file__), "..", "configs", "nerf",
                     "lego.yaml"),
        [
            "scene", "procedural",
            "train_dataset.data_root", root,
            "test_dataset.data_root", root,
            "train_dataset.H", "8", "train_dataset.W", "8",
            "test_dataset.H", "8", "test_dataset.W", "8",
            "train.num_workers", "2",  # thread prefetch path
        ],
    )
    loader = make_data_loader(cfg, "train", max_iter=3)
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0]["rays"].shape[-1] == 6

    test_loader = make_data_loader(cfg, "test")
    tb = next(iter(test_loader))
    assert tb["rays"].shape == (1, 64, 6)  # one 8x8 image per batch
    assert tb["meta"][0]["H"] == 8


def test_keras_weight_import():
    import jax
    import jax.numpy as jnp

    from nerf_replication_tpu.models.encoding.freq import frequency_encoder
    from nerf_replication_tpu.models.nerf.network import (
        Network,
        init_params,
        load_weights_from_keras,
    )

    xyz, xyz_dim = frequency_encoder(3, 4)
    dirs, dirs_dim = frequency_encoder(3, 2)
    net = Network(
        D=3, W=16, skips=(1,), use_viewdirs=True,
        xyz_encoder=xyz, dir_encoder=dirs,
        input_ch=xyz_dim, input_ch_views=dirs_dim,
    )
    params = init_params(net, jax.random.PRNGKey(0))

    # synthesize a keras-style flat list FROM the coarse branch, then load it
    # into the fine branch: layouts must line up exactly
    rng = np.random.default_rng(0)
    src = params["params"]["coarse"]
    weights = []
    for i in range(3):
        weights += [np.asarray(src[f"pts_linear_{i}"]["kernel"]) + 1.0,
                    np.asarray(src[f"pts_linear_{i}"]["bias"]) + 1.0]
    for name in ("feature_linear", "views_linear_0", "rgb_linear",
                 "alpha_linear"):
        weights += [np.asarray(src[name]["kernel"]) + 1.0,
                    np.asarray(src[name]["bias"]) + 1.0]
    # keras order: feature at 2D, views at 2D+2, rgb at 2D+4, alpha at 2D+6
    # (we appended views/rgb/alpha in that order after feature — matches)

    new = load_weights_from_keras(params, weights, model="fine")
    got = new["params"]["fine"]
    np.testing.assert_array_equal(
        np.asarray(got["pts_linear_0"]["kernel"]),
        np.asarray(src["pts_linear_0"]["kernel"]) + 1.0,
    )
    np.testing.assert_array_equal(
        np.asarray(got["alpha_linear"]["bias"]),
        np.asarray(src["alpha_linear"]["bias"]) + 1.0,
    )
    # untouched branch preserved
    np.testing.assert_array_equal(
        np.asarray(new["params"]["coarse"]["pts_linear_0"]["kernel"]),
        np.asarray(src["pts_linear_0"]["kernel"]),
    )
    # wrong shape → loud error
    bad = list(weights)
    bad[0] = np.zeros((2, 2), np.float32)
    with pytest.raises(ValueError):
        load_weights_from_keras(params, bad, model="fine")


def test_image_size_sampler_with_img_fit_dataset(tmp_path):
    """The (index, h, w) dataset contract end-to-end: image_size batch
    sampler -> img_fit dataset resize -> collate. The reference exercises
    this via its light-stage datasets; img_fit is our in-tree example."""
    import os

    from nerf_replication_tpu.config import make_cfg
    from nerf_replication_tpu.datasets import make_data_loader
    from nerf_replication_tpu.datasets.procedural import generate_scene

    root = str(tmp_path)
    generate_scene(root, scene="procedural", H=48, W=48, n_train=2, n_test=1)
    cfg = make_cfg(
        os.path.join(os.path.dirname(__file__), "..", "configs", "img_fit",
                     "lego_view0.yaml"),
        ["scene", "procedural",
         "train_dataset.data_root", root,
         "test_dataset.data_root", root,
         "task_arg.N_pixels", "64",
         "train.batch_sampler", "image_size",
         "train.sampler_meta", "{'min_hw': [16, 16], 'max_hw': [32, 32], 'strides': 16}",
         "ep_iter", "4"],
    )
    loader = make_data_loader(cfg, "train")
    batches = list(loader)
    assert len(batches) == 4
    for b in batches:
        h, w = int(b["meta"][0]["H"]), int(b["meta"][0]["W"])
        assert h in (16, 32) and w in (16, 32)
        assert b["uv"].shape[1] == min(64, h * w)
