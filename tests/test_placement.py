"""Placement subsystem (scale/placement.py + scale/launcher.py): the
planner's determinism / budget packing / heat-driven width / move
ordering, the router's plan-beats-affinity promotion and its bitwise
empty-plan fallback, the capability filter's typed no-capable error,
the supervisor's replan-on-death, and — marked ``slow`` — one REAL
two-process ``serve.py`` fleet through the ProcessLauncher. Everything
tier-1 runs on fake clocks/replicas; no processes, no chips."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nerf_replication_tpu.obs import validate_row
from nerf_replication_tpu.scale import (
    NoCapableReplicaError,
    PlacementExecutor,
    PlacementOptions,
    PlacementPlan,
    PlacementPlanner,
    ReplicaState,
    Router,
    ScaleOptions,
    Supervisor,
    merge_heat,
)

_REPO = os.path.join(os.path.dirname(__file__), "..")


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeCatalog:
    """The SceneStore surface the planner reads (ids + record get)."""

    def __init__(self, *ids):
        self._ids = list(ids)

    def ids(self):
        return list(self._ids)

    def get(self, sid):
        return {"scene_id": sid}


class FakeReplica:
    """The replica surface with scripted load/scenes/liveness."""

    def __init__(self, rid, load=0, scenes=()):
        self.replica_id = str(rid)
        self.state = ReplicaState.READY
        self._load = load
        self.scenes = list(scenes)
        self.beat_ok = True
        self.submits = []

    def accepting(self):
        return self.state == ReplicaState.READY

    def load(self):
        return self._load

    def heartbeat(self):
        if not self.beat_ok:
            raise RuntimeError("beat down")
        return {"replica": self.replica_id, "state": self.state, "ok": True,
                "load": self._load, "scenes": self.scenes,
                "warm_source": "disk", "total_compiles": 0}

    def submit(self, rays, near, far, scene=None, tenant=None):
        self.submits.append(scene)
        return f"future:{self.replica_id}"

    def drain(self, timeout_s=60.0):
        self.state = ReplicaState.RETIRED
        return 0

    def kill(self):
        self.state = ReplicaState.DEAD


class PoseOnlyReplica(FakeReplica):
    """An HTTP-shaped replica: whole poses only, no ray-level submit."""

    capabilities = ("pose",)


def _state(scenes=(), staging=(), hbm_budget=0, staging_budget=0,
           hbm_bytes=0, staging_bytes=0):
    return {"scenes": list(scenes), "staging": list(staging),
            "hbm_bytes": hbm_bytes, "staging_bytes": staging_bytes,
            "hbm_budget_bytes": hbm_budget,
            "staging_budget_bytes": staging_budget}


def _heat(**rps):
    return {s: {"requests_per_s": float(r), "rays_per_s": 0.0}
            for s, r in rps.items()}


def _router(clock, *replicas, timeout_s=10.0):
    r = Router(heartbeat_timeout_s=timeout_s, clock=clock)
    for rep in replicas:
        r.register(rep)
    r.sweep()  # populate beats (affinity reads the last beat)
    return r


def _planner(catalog, scene_bytes_fn=None, **opts):
    merged = dict(enabled=True, hot_width=2, max_width=4,
                  hot_rps=1.0, width_rps=2.0)
    merged.update(opts)
    return PlacementPlanner(catalog, options=PlacementOptions(**merged),
                            scene_bytes_fn=scene_bytes_fn,
                            clock=FakeClock())


# -- planner: determinism ----------------------------------------------------


def test_plan_is_deterministic_and_version_stable():
    planner = _planner(FakeCatalog("a", "b"))
    states = {"r0": _state(scenes=["a"]), "r1": _state()}
    heat = _heat(a=3.0, b=0.1)
    p1 = planner.plan(dict(states), dict(heat))
    p2 = planner.plan(dict(states), dict(heat))
    # identical inputs: identical assignment AND no version bump
    assert p2.assignments == p1.assignments
    assert p2.moves == p1.moves
    assert p2.version == p1.version
    assert planner.n_version_bumps == 1
    # a changed input (the hot scene cools) changes the assignment and
    # bumps exactly once
    p3 = planner.plan(states, _heat(a=0.0, b=0.1))
    assert p3.assignments != p1.assignments
    assert p3.version == p1.version + 1


def test_only_catalog_scenes_are_planned():
    planner = _planner(FakeCatalog("a"))
    plan = planner.plan({"r0": _state(scenes=["a", "ghost"])},
                        _heat(a=0.2, phantom=9.0))
    # "ghost" (resident but uncataloged) and "phantom" (heat with no
    # record) have nothing to prefetch from: unplannable
    assert set(plan.assignments) == {"a"}


# -- planner: budget packing -------------------------------------------------


def test_budget_packing_never_overfills_a_replica():
    planner = _planner(FakeCatalog("a", "b", "c", "d", "e"),
                       scene_bytes_fn=lambda sid: 100)
    states = {"r0": _state(hbm_budget=250), "r1": _state(hbm_budget=250)}
    plan = planner.plan(states, _heat(a=0.1, b=0.1, c=0.1, d=0.1, e=0.1))
    packed = {"r0": 0, "r1": 0}
    for sid, rids in plan.assignments.items():
        for r in rids:
            packed[r] += 100
    assert all(v <= 250 for v in packed.values())
    # two fit per replica; the fifth scene fits nowhere and stays
    # unassigned — the router falls back to passive dispatch for it
    assert len(plan.assignments) == 4
    assert planner.planned_replicas("e") == ()


def test_ladder_tiers_pool_into_one_planning_budget():
    planner = _planner(FakeCatalog("a", "b"),
                       scene_bytes_fn=lambda sid: 100)
    # 60 HBM + 60 staging = 120 pooled: one scene fits, two don't
    plan = planner.plan({"r0": _state(hbm_budget=60, staging_budget=60)},
                        _heat(a=0.2, b=0.1))
    assert plan.assignments == {"a": ("r0",)}


# -- planner: heat-driven width ----------------------------------------------


def test_heat_drives_replication_width():
    states = {f"r{i}": _state() for i in range(4)}
    cold = _planner(FakeCatalog("a"), max_width=3).plan(
        dict(states), _heat(a=0.5))
    assert len(cold.assignments["a"]) == 1  # below hot_rps: single holder
    hot = _planner(FakeCatalog("a"), max_width=3).plan(
        dict(states), _heat(a=1.0))
    assert len(hot.assignments["a"]) == 2  # at hot_rps: hot_width
    hotter = _planner(FakeCatalog("a"), max_width=3).plan(
        dict(states), _heat(a=3.0))
    assert len(hotter.assignments["a"]) == 3  # +1 per width_rps of heat
    capped = _planner(FakeCatalog("a"), max_width=3).plan(
        dict(states), _heat(a=99.0))
    assert len(capped.assignments["a"]) == 3  # max_width caps the fan-out


def test_width_never_exceeds_the_fleet():
    plan = _planner(FakeCatalog("a"), max_width=8).plan(
        {"r0": _state(), "r1": _state()}, _heat(a=50.0))
    assert len(plan.assignments["a"]) == 2


# -- planner: move ordering --------------------------------------------------


def test_moves_order_publish_then_prefetch_then_demote():
    planner = _planner(FakeCatalog("a"))
    planner.note_publish("a")
    plan = planner.plan({"r0": _state(scenes=["a", "stale"]),
                         "r1": _state()}, _heat(a=2.0))
    kinds = [m.kind for m in plan.moves]
    # hot "a" widens onto r1; uncataloged "stale" leaves r0; the publish
    # lands first and every new copy lands before any old one is demoted
    assert kinds == sorted(kinds, key=("publish", "prefetch",
                                       "demote").index)
    assert ("prefetch", "a", "r1") in [(m.kind, m.scene, m.replica)
                                       for m in plan.moves]
    demotes = [m for m in plan.moves if m.kind == "demote"]
    assert [(m.scene, m.replica) for m in demotes] == [("stale", "r0")]
    assert max(i for i, m in enumerate(plan.moves)
               if m.kind == "prefetch") < plan.moves.index(demotes[0])


def test_prefetch_moves_are_hottest_first():
    plan = _planner(FakeCatalog("a", "b")).plan(
        {"r0": _state()}, _heat(a=0.2, b=0.9))
    prefetches = [m.scene for m in plan.moves if m.kind == "prefetch"]
    assert prefetches == ["b", "a"]  # b is hotter: its copy lands first


def test_executor_lazy_skips_remote_replicas_and_converges():
    clock = FakeClock()
    planner = _planner(FakeCatalog("a"))
    planner.clock = clock
    plan = planner.plan({"r0": _state()}, _heat(a=2.0))
    assert not plan.converged
    clock.advance(3.0)
    out = PlacementExecutor().execute(planner)  # no residency_of: remote
    assert out == {"applied": 0, "failed": 0,
                   "skipped": len(plan.moves), "remaining": 0}
    assert planner.stats()["n_convergences"] == 1
    assert planner.stats()["convergence_s_last"] == pytest.approx(3.0)


def test_executor_counts_pinned_demote_as_failed_move():
    class PinnedLadder:
        def prefetch(self, sid):
            return True

        def evict(self, sid):
            return False  # the lease is pinned: evict() REFUSES

    planner = _planner(FakeCatalog("a"))
    planner.plan({"r0": _state(scenes=["stale", "a"])}, _heat(a=0.2))
    executor = PlacementExecutor(residency_of=lambda rid: PinnedLadder())
    out = executor.execute(planner)
    assert out["failed"] == 1  # the refused demote, counted not forced
    assert planner.stats()["n_failed_moves"] == 1


# -- router: plan consult ----------------------------------------------------


def test_router_plan_beats_passive_affinity():
    clock = FakeClock()
    holder = FakeReplica("r0", load=0, scenes=["lego"])
    other = FakeReplica("r1", load=5)
    router = _router(clock, holder, other)
    assert router.pick("lego") is holder  # passive: affinity wins
    planner = _planner(FakeCatalog("lego"), scene_bytes_fn=lambda sid: 100)
    router.set_planner(planner)
    # the plan moves lego off the over-budget holder; the router follows
    planner.plan({"r0": _state(scenes=["lego"], hbm_budget=1),
                  "r1": _state(hbm_budget=1000)}, _heat(lego=0.2))
    assert planner.current.assignments == {"lego": ("r1",)}
    assert router.pick("lego") is other
    router.submit(None, 2.0, 6.0, scene="lego")
    router.submit(None, 2.0, 6.0, scene="ship")  # no plan entry: passive
    assert router.n_planned_hits == 1
    assert router.n_unplanned == 1


def test_empty_or_disabled_plan_is_bitwise_passive_dispatch():
    def fleet():
        return (FakeReplica("r0", load=2, scenes=["lego"]),
                FakeReplica("r1", load=0),
                FakeReplica("r2", load=0, scenes=["ship"]))

    def order(router, scene):
        return [c[:3] for c in router._candidates(scene)]

    clock = FakeClock()
    bare = _router(clock, *fleet())
    no_plan = _router(clock, *fleet())
    no_plan.set_planner(_planner(FakeCatalog("lego", "ship")))
    disabled = _router(clock, *fleet())
    off = PlacementPlanner(FakeCatalog("lego", "ship"),
                           options=PlacementOptions(enabled=False))
    off.plan({"r0": _state(scenes=["lego"])}, _heat(lego=9.0))
    disabled.set_planner(off)
    empty = _router(clock, *fleet())
    hollow = _planner(FakeCatalog())
    hollow.plan({"r0": _state()}, {})  # plans, but assigns nothing
    empty.set_planner(hollow)
    for scene in (None, "lego", "ship", "unknown"):
        want = order(bare, scene)
        assert order(no_plan, scene) == want
        assert order(disabled, scene) == want
        assert order(empty, scene) == want


def test_planned_candidates_keep_passive_order_within_group():
    clock = FakeClock()
    a = FakeReplica("a", load=9, scenes=["lego"])
    b = FakeReplica("b", load=0)
    c = FakeReplica("c", load=0)
    router = _router(clock, a, b, c)
    planner = _planner(FakeCatalog("lego"))
    planner.current = PlacementPlan(version=1,
                                    assignments={"lego": ("a", "c")})
    router.set_planner(planner)
    # planned group first (affinity beats load within it: a before c),
    # unplanned group keeps its passive order behind them
    assert [r.replica_id for *_, r in router._candidates("lego")] \
        == ["a", "c", "b"]


# -- router: capability filter -----------------------------------------------


def test_capability_mismatch_is_a_filter_not_a_failover():
    clock = FakeClock()
    pose_only = PoseOnlyReplica("http0")
    router = _router(clock, pose_only)
    with pytest.raises(NoCapableReplicaError):
        router.submit(None, 2.0, 6.0)  # ray submit vs a pose-only fleet
    assert pose_only.state == ReplicaState.READY  # healthy, just filtered
    assert router.n_failovers == 0


def test_capable_replica_is_picked_over_filtered_one():
    clock = FakeClock()
    pose_only = PoseOnlyReplica("http0", load=0)
    universal = FakeReplica("ray0", load=9)
    router = _router(clock, pose_only, universal)
    router.submit(None, 2.0, 6.0)
    assert universal.submits == [None]  # the idle pose replica never saw it


# -- supervisor: plan lifecycle ----------------------------------------------


def _placement_supervisor(clock, heat):
    popt = PlacementOptions(enabled=True, hot_width=2, max_width=4,
                            hot_rps=0.5, width_rps=2.0,
                            replan_every_s=1e9, max_moves_per_step=8)
    planner = PlacementPlanner(FakeCatalog("lego"), options=popt,
                               heat_fn=lambda: {"scenes": heat},
                               clock=clock)
    router = Router(heartbeat_timeout_s=10.0, clock=clock)
    spawned = []

    def spawn_fn(i):
        r = FakeReplica(f"s{i}", scenes=["lego"] if i == 0 else [])
        spawned.append(r)
        return r

    sup = Supervisor(router, spawn_fn,
                     options=ScaleOptions(min_replicas=2, max_replicas=3,
                                          placement=popt),
                     clock=clock, planner=planner,
                     placement_executor=PlacementExecutor())
    sup.ensure_min()
    router.sweep()
    return sup, router, planner, spawned


def test_supervisor_replans_on_replica_death():
    clock = FakeClock()
    heat = _heat(lego=5.0)
    sup, router, planner, spawned = _placement_supervisor(clock, heat)
    sup.step(1.0, 0.0)  # first healthy step: the boot plan
    assert planner.current.assignments["lego"] == ("s0", "s1")
    v_before = planner.current.version
    spawned[0].kill()
    assert sup.replace_dead() == 1
    plan = planner.current
    # the death triggered an immediate replan (no cadence wait): the
    # corpse is out of every assignment, the replacement is in
    assert plan.reason == "replace"
    assert plan.version == v_before + 1
    assert all("s0" not in rids for rids in plan.assignments.values())
    assert plan.assignments["lego"] == ("s1", "s2")
    assert sup.n_replaced == 1


def test_supervisor_replans_on_pending_publish():
    clock = FakeClock()
    sup, router, planner, _ = _placement_supervisor(clock, _heat(lego=5.0))
    sup.step(1.0, 0.0)
    sup.note_publish("lego")
    sup.step(1.0, 0.0)  # cadence is 1e9 s away: only the publish triggers
    assert planner.current.reason == "publish"
    assert [m.kind for m in planner.current.moves][:2] \
        == ["publish", "publish"]  # one per assigned replica, first


def test_placement_rows_validate_against_the_schema():
    rows = []
    clock = FakeClock()
    planner = _planner(FakeCatalog("a"))
    planner.clock = clock
    plan = planner.plan({"r0": _state()}, _heat(a=2.0),
                        dispatch_counters={"planned_hits": 3,
                                           "unplanned": 1})
    for move in plan.moves:
        planner.note_move(move, True, "", skipped=True)
    base = {"v": 1, "t": 0.0}
    rows.append({**base, "kind": "placement_plan", "version": plan.version,
                 "reason": plan.reason, "n_scenes": len(plan.assignments),
                 "n_replicas": 1, "n_moves": len(plan.moves),
                 "moves_by_kind": plan.moves_by_kind(), "converged": False,
                 "planned_hits": 3, "unplanned": 1,
                 "evidence": {"scene_heat": plan.scene_heat}})
    for m in plan.moves:
        rows.append({**base, "kind": "placement_move",
                     "version": plan.version, "move": m.kind,
                     "scene": m.scene, "replica": m.replica, "ok": True})
    for row in rows:
        assert validate_row(row) == [], row


def test_merge_heat_sums_rates_across_ledger_views():
    merged = merge_heat(
        {"scenes": {"a": {"requests_per_s": 1.0, "rays_per_s": 10.0}}},
        {"a": {"requests_per_s": 2.0}, "b": {"requests_per_s": 0.5}},
        None,
    )
    assert merged["a"]["requests_per_s"] == pytest.approx(3.0)
    assert merged["a"]["rays_per_s"] == pytest.approx(10.0)
    assert merged["b"]["requests_per_s"] == pytest.approx(0.5)


# -- the real thing: two serve.py children ------------------------------------


@pytest.mark.slow
def test_launcher_spawns_two_real_children_ready_drain_exit(tmp_path):
    """spawn -> ready -> render -> drain -> exit on REAL serve.py
    children (the ProcessLauncher contract end-to-end). Slow: two child
    engine boots; excluded from tier-1."""
    import yaml

    from nerf_replication_tpu.datasets.procedural import generate_scene
    from nerf_replication_tpu.scale import ProcessLauncher

    scene_root = str(tmp_path / "scene")
    generate_scene(scene_root, scene="procedural", H=16, W=16,
                   n_train=2, n_test=1)
    doc = {
        "parent_cfg": os.path.join(_REPO, "configs", "nerf", "lego.yaml"),
        "task": "run",
        "scene": "procedural",
        "exp_name": "launcher_test",
        "train_dataset": {"data_root": scene_root, "H": 16, "W": 16},
        "test_dataset": {"data_root": scene_root, "H": 16, "W": 16},
        "task_arg": {"N_samples": 24, "N_importance": 24,
                     "render_step_size": 0.25, "max_march_samples": 16,
                     "march_chunk_size": 64},
        "network": {"nerf": {"W": 64, "D": 3, "skips": [1]},
                    "xyz_encoder": {"freq": 6},
                    "dir_encoder": {"freq": 2}},
        "serve": {"buckets": [256], "max_batch_rays": 256,
                  "max_delay_ms": 5.0, "request_timeout_s": 30.0},
        "record_dir": str(tmp_path / "record"),
        # one shared artifact dir: child 0 pays the compile, child 1
        # must warm from its serialized executables
        "compile": {"aot": True, "artifacts": True,
                    "dir": str(tmp_path / "aot")},
        "obs": {"trace": False, "alerts": {"enabled": False}},
    }
    cfg_path = str(tmp_path / "serve_cfg.yaml")
    with open(cfg_path, "w") as f:
        yaml.safe_dump(doc, f, sort_keys=True)
    launcher = ProcessLauncher(cfg_path, env={"JAX_PLATFORMS": "cpu"},
                               ready_timeout_s=600.0)
    try:
        r0 = launcher(0)
        r1 = launcher(1)
        assert r0.port != r1.port  # two live sockets, two processes
        b0, b1 = r0.heartbeat(), r1.heartbeat()
        assert b0["ok"] and b1["ok"]
        assert r0.state == ReplicaState.READY
        assert b1["warm_source"] == "disk"  # second child: zero builds
        assert b1["total_compiles"] == 0
        out = r0.render({"theta": 30.0, "phi": -30.0, "radius": 4.0},
                        timeout_s=120.0)
        assert out["h"] > 0 and out["rgb_b64"]
        assert launcher.retire(r1, timeout_s=60.0) == 0  # clean drain
        assert r1.state == ReplicaState.RETIRED
        assert r1.proc.poll() is not None  # the child actually exited
        assert launcher.stats()["n_spawned"] == 2
    finally:
        launcher.shutdown()
