"""Fleet-wide observability tests (PR 15): Traceparent propagation
across the router -> ProcessReplica HTTP boundary, the fleet metrics
aggregator (merge, dead-replica skips, exemplar harvesting), evidence-
linked scale decisions, and the cross-replica trace assembly tooling
(trace_view --fleet, tlm_report's fleet-trace section and --diff gates).

The headline test is a REAL two-process run: a serve.py-shaped child
process (stdlib HTTP server over a fake engine) is spawned, one pose
request is routed through Router.render with tracing on, and the child's
span tree must parent under the router's dispatch span via the
propagated header — reconstructing >= 95% of the routed wall time with
zero orphan spans in the merged Chrome trace.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from nerf_replication_tpu.obs import (  # noqa: E402
    SCHEMA_VERSION,
    TRACE_HEADER,
    SpanContext,
    configure_tracing,
    get_metrics,
    get_tracer,
    reset_metrics,
    trace_headers,
    validate_row,
)
from nerf_replication_tpu.obs import emit as emit_mod  # noqa: E402
from nerf_replication_tpu.obs.schema import validate_bench_row  # noqa: E402
from nerf_replication_tpu.resil import (  # noqa: E402
    FlightRecorder,
    install_flight_recorder,
    uninstall_flight_recorder,
)
from nerf_replication_tpu.scale import (  # noqa: E402
    FleetMetricsAggregator,
    ProcessReplica,
    ReplicaState,
    Router,
    ScaleOptions,
    Supervisor,
    make_fleet_server,
    merge_scrapes,
)
from nerf_replication_tpu.scale.fleet_metrics import (  # noqa: E402
    relabel_sample,
)


def _load_script(name):
    path = os.path.join(_REPO, "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def telem(tmp_path, monkeypatch):
    """Route the process emitter at a scratch JSONL; yields its path."""
    path = str(tmp_path / "telemetry.jsonl")
    em = emit_mod.Emitter(path, chief=True)
    monkeypatch.setattr(emit_mod, "_active", em)
    yield path
    em.close()


@pytest.fixture
def traced(telem):
    """Tracing ON with a span-collecting sink; resets after."""
    reset_metrics()
    configure_tracing(enabled=True)
    spans = []
    get_tracer().add_sink(spans.append)
    yield spans
    configure_tracing(enabled=False)
    reset_metrics()


# -- header propagation (SpanContext.to_header/from_header) -------------------


def test_span_context_header_round_trip():
    ctx = SpanContext("r0abc123", "00000007")
    restored = SpanContext.from_header(ctx.to_header())
    assert restored is not None
    assert restored.trace_id == "r0abc123"
    assert restored.span_id == "00000007"
    # a restored ctx is marked remote: children record remote_parent so
    # the fleet merge can tell propagated parents from torn ones
    assert restored.remote is True
    assert ctx.remote is False


@pytest.mark.parametrize("bad", [
    None, "", "nodashhere", "-abc", "abc-", "a b-c", "abc-d!f",
    "tr@ce-span", 42,
])
def test_from_header_rejects_malformed(bad):
    assert SpanContext.from_header(bad) is None


def test_trace_headers_ambient_and_explicit(traced):
    # outside any span there is nothing to propagate
    assert trace_headers() == {}
    explicit = SpanContext("t1", "s1")
    assert trace_headers(explicit) == {TRACE_HEADER: "t1-s1"}
    with get_tracer().span("outer", parent=None) as sp:
        hdrs = trace_headers()
        assert hdrs == {TRACE_HEADER: sp.ctx.to_header()}


def test_remote_parented_span_row_validates(traced):
    trs = get_tracer()
    ctx = SpanContext.from_header("parenttrace-parentspan")
    with trs.span("serve.request", parent=ctx):
        pass
    row = traced[-1]
    assert row["remote_parent"] is True
    assert row["trace_id"] == "parenttrace"
    assert row["parent_id"] == "parentspan"
    full = {"v": SCHEMA_VERSION, "kind": "span", "t": 0.0, **row}
    assert validate_row(full) == []


def test_replica_id_prefix_keeps_span_ids_unique(telem):
    configure_tracing(enabled=True, id_prefix="rep-0")  # dash stripped
    rows = []
    get_tracer().add_sink(rows.append)
    with get_tracer().span("x", parent=None):
        pass
    configure_tracing(enabled=False)
    assert rows[0]["span_id"].startswith("rep0")
    assert rows[0]["span_id"].isalnum()


# -- fleet metrics: merge + aggregator ----------------------------------------


_PROM_A = """\
# TYPE serve_request_latency_seconds histogram
serve_request_latency_seconds_bucket{le="0.1"} 8
serve_request_latency_seconds_bucket{le="0.25"} 8
serve_request_latency_seconds_bucket{le="1"} 10 # {trace_id="slowA1"} 0.7
serve_request_latency_seconds_bucket{le="+Inf"} 10
serve_request_latency_seconds_sum 2.4
serve_request_latency_seconds_count 10
# TYPE serve_requests_total counter
serve_requests_total{status="ok"} 10
# TYPE scale_router_dispatch_total counter
scale_router_dispatch_total{replica="r1"} 3
"""

_PROM_B = """\
# TYPE serve_request_latency_seconds histogram
serve_request_latency_seconds_bucket{le="0.1"} 5
serve_request_latency_seconds_bucket{le="0.25"} 5
serve_request_latency_seconds_bucket{le="1"} 5
serve_request_latency_seconds_bucket{le="+Inf"} 5
serve_request_latency_seconds_sum 0.2
serve_request_latency_seconds_count 5
# TYPE serve_requests_total counter
serve_requests_total{status="ok"} 5
"""


def test_relabel_sample_injects_and_renames_collision():
    out = relabel_sample('serve_requests_total{status="ok"} 10', "rep0")
    assert out == 'serve_requests_total{replica="rep0",status="ok"} 10'
    # a pre-existing replica label (the router talking about OTHER
    # replicas) is renamed, not clobbered — the federation collision rule
    out = relabel_sample('scale_router_dispatch_total{replica="r1"} 3',
                         "router")
    assert 'exported_replica="r1"' in out
    assert 'replica="router"' in out
    # exemplar suffixes ride along untouched
    out = relabel_sample(
        'x_bucket{le="1"} 2 # {trace_id="t9"} 0.7', "rep1")
    assert out.endswith('# {trace_id="t9"} 0.7')
    assert 'replica="rep1"' in out


def test_merge_scrapes_groups_series_per_source():
    merged = merge_scrapes({"rep0": _PROM_A, "rep1": _PROM_B})
    # one TYPE line per metric, not one per source
    assert merged.count("# TYPE serve_requests_total counter") == 1
    assert merged.count(
        "# TYPE serve_request_latency_seconds histogram") == 1
    assert 'serve_requests_total{replica="rep0",status="ok"} 10' in merged
    assert 'serve_requests_total{replica="rep1",status="ok"} 5' in merged
    # the exemplar survived the merge with its source's replica label
    assert '# {trace_id="slowA1"} 0.7' in merged


class _ScrapeReplica:
    """Replica double wearing only the fleet-metrics surface."""

    def __init__(self, replica_id, text, state=ReplicaState.READY,
                 source_id=None, load=0):
        self.replica_id = replica_id
        self.text = text
        self.state = state
        self._source_id = source_id or replica_id
        self._load = load

    def accepting(self):
        return self.state == ReplicaState.READY

    def metrics_source_id(self):
        return self._source_id

    def scrape_metrics(self):
        if isinstance(self.text, Exception):
            raise self.text
        return self.text

    def load(self):
        return self._load

    def heartbeat(self):
        return {"replica": self.replica_id, "state": self.state,
                "ok": True, "load": self._load, "scenes": []}

    def drain(self, timeout_s=60.0):
        self.state = ReplicaState.RETIRED
        return 0


@pytest.fixture
def metrics_reset():
    reset_metrics()
    yield
    reset_metrics()


def test_aggregator_skips_dead_and_dedups_shared_registry(metrics_reset):
    router = Router()
    router.register(_ScrapeReplica("rep0", _PROM_A))
    router.register(_ScrapeReplica("rep1", _PROM_B,
                                   state=ReplicaState.DEAD))
    router.register(_ScrapeReplica("rep2", _PROM_B,
                                   state=ReplicaState.RETIRED))
    router.register(_ScrapeReplica("rep3", RuntimeError("conn refused")))
    # two in-process replicas sharing one registry: one scrape, not two
    router.register(_ScrapeReplica("rep4", _PROM_B, source_id="process"))
    router.register(_ScrapeReplica("rep5", _PROM_B, source_id="process"))
    agg = FleetMetricsAggregator(router, slo_target_s=0.25)
    scrapes = agg.scrape()
    assert sorted(scrapes) == ["process", "rep0"]
    reasons = {s["replica"]: s["reason"] for s in agg.skipped}
    assert reasons["rep1"] == ReplicaState.DEAD
    assert reasons["rep2"] == ReplicaState.RETIRED
    assert reasons["rep3"].startswith("unreachable")
    assert agg.stats()["n_scrape_failures"] == 1


def test_aggregator_slo_view_and_window_deltas(metrics_reset):
    router = Router()
    rep = _ScrapeReplica("rep0", _PROM_A)
    router.register(rep)
    agg = FleetMetricsAggregator(router, slo_target_s=0.25)
    view = agg.slo_view()
    assert view["replicas_scraped"] == 1
    assert view["attainment"] == pytest.approx(0.8)  # 8 of 10 under 250 ms
    assert view["requests"] == 10

    # first window diffs against zeros: cumulative-since-start
    w1 = agg.window()
    assert w1["attainment"] == pytest.approx(0.8)
    assert w1["requests"] == 10
    # the SLO-missing bucket's exemplar is the window's evidence join key
    assert w1["exemplar_trace_ids"] == ["slowA1"]

    # next window: 10 more requests, all fast -> delta attainment 1.0
    rep.text = _PROM_A.replace(
        'le="0.1"} 8', 'le="0.1"} 18').replace(
        'le="0.25"} 8', 'le="0.25"} 18').replace(
        'le="1"} 10', 'le="1"} 20').replace(
        'le="+Inf"} 10', 'le="+Inf"} 20').replace(
        '_count 10', '_count 20').replace(
        'serve_requests_total{status="ok"} 10',
        'serve_requests_total{status="ok"} 20')
    w2 = agg.window()
    assert w2["attainment"] == pytest.approx(1.0)
    assert w2["requests"] == 10


def test_aggregator_exemplars_match_registry_duck_type(metrics_reset):
    """The Supervisor calls ``slo_miss_exemplars(target_s)`` on whatever
    evidence source it holds — the aggregator must accept the positional
    target like MetricsRegistry does (a target of 0.25 must not be
    swallowed as the ``limit``)."""
    router = Router()
    router.register(_ScrapeReplica("rep0", _PROM_A))
    agg = FleetMetricsAggregator(router, slo_target_s=0.25)
    agg.scrape()
    assert agg.slo_miss_exemplars(0.25) == ["slowA1"]
    assert agg.slo_miss_exemplars() == ["slowA1"]
    # a target above every harvested edge filters the pool empty
    assert agg.slo_miss_exemplars(5.0) == []


def test_registry_exemplar_sampling_and_miss_pool(metrics_reset):
    mx = get_metrics()
    mx.observe("serve_request_latency_seconds", 0.01, trace_id="fast1")
    mx.observe("serve_request_latency_seconds", 0.6, trace_id="slow1")
    mx.observe("serve_request_latency_seconds", 1.8, trace_id="slow2")
    mx.observe("serve_request_latency_seconds", 0.02)  # no trace: no exemplar
    # misses only, slowest first
    assert mx.slo_miss_exemplars(0.25) == ["slow2", "slow1"]
    # nothing missed the (huge) target with an exemplar -> fall back to
    # the slowest seen so evidence is never empty on an observed fleet
    assert mx.slo_miss_exemplars(100.0) == ["slow2", "slow1", "fast1"]
    text = mx.render_prometheus()
    assert '# {trace_id="slow1"} 0.6' in text
    assert '# {trace_id="slow2"} 1.8' in text


def test_fleet_server_endpoints(metrics_reset):
    router = Router()
    router.register(_ScrapeReplica("rep0", _PROM_A))
    agg = FleetMetricsAggregator(router, slo_target_s=0.25)
    server = make_fleet_server(agg, port=0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet/metrics", timeout=5) as r:
            body = r.read().decode()
            assert r.status == 200
        assert 'replica="rep0"' in body
        assert '# {trace_id="slowA1"} 0.7' in body
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet/slo", timeout=5) as r:
            slo = json.loads(r.read().decode())
        assert slo["attainment"] == pytest.approx(0.8)
        assert slo["replicas_scraped"] == 1
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet/nope", timeout=5)
        assert exc.value.code == 404
    finally:
        server.shutdown()
        server.server_close()


# -- evidence-linked scale decisions ------------------------------------------


def test_supervisor_decision_carries_fleet_evidence(telem, tmp_path,
                                                    metrics_reset):
    router = Router()
    router.register(_ScrapeReplica("rep0", _PROM_A, load=3))
    agg = FleetMetricsAggregator(router, slo_target_s=0.25)
    spawned = []

    def spawn(i):
        r = _ScrapeReplica(f"fresh{i}", _PROM_B)
        spawned.append(r)
        return r

    opts = ScaleOptions(min_replicas=1, max_replicas=3, out_below=0.9,
                        out_windows=1, cooldown_out_s=0.0)
    sup = Supervisor(router, spawn, options=opts, evidence_source=agg,
                     slo_target_s=0.25)
    install_flight_recorder(FlightRecorder(str(tmp_path), capacity=64))
    try:
        router.sweep()  # populate beats so load_view has depths
        action = sup.step_from_fleet(agg)  # attainment 0.8 < 0.9 -> out
    finally:
        uninstall_flight_recorder()
    assert action == "out"
    assert len(spawned) == 1
    decision = sup.decisions[-1]
    ev = decision["evidence"]
    assert ev["exemplar_trace_ids"] == ["slowA1"]
    assert ev["attainment_series"][-1] == pytest.approx(0.8)
    assert ev["queue_depths"] == {"rep0": 3}
    assert isinstance(ev["deny_rate"], float)
    # the emitted telemetry row passes the deep evidence checks
    full = {"v": SCHEMA_VERSION, "kind": "scale_decision", "t": 0.0,
            **decision}
    assert validate_row(full) == []
    # ... and the scale-out dumped a flight snapshot naming the evidence
    dump_path = tmp_path / "flight_scale_out.json"
    assert dump_path.exists()
    dump = json.loads(dump_path.read_text())
    assert "slowA1" in dump["detail"]


def test_wedged_fleet_window_scales_out_not_in(telem, metrics_reset):
    """Attainment None with queued work is overload, not idleness."""
    router = Router()
    rep = _ScrapeReplica("rep0", "", load=9)  # nothing completing, deep queue
    router.register(rep)
    agg = FleetMetricsAggregator(router, slo_target_s=0.25)
    opts = ScaleOptions(min_replicas=1, max_replicas=3, out_windows=1,
                        cooldown_out_s=0.0)
    sup = Supervisor(router, lambda i: _ScrapeReplica(f"f{i}", ""),
                     options=opts, evidence_source=agg)
    router.sweep()
    assert sup.step_from_fleet(agg) == "out"


def test_scale_bench_row_shape_stays_in_family():
    """The serve_bench --replicas row with the PR-15 tracing/evidence
    fields must still land in the scale_mode bench family (no key may
    collide with an earlier first-match discriminator)."""
    row = {
        "scale_mode": True, "tracing": "on", "rps": 42.0,
        "replicas_peak": 2, "attainment_low": 0.6,
        "attainment_recovered": 0.99, "scale_outs": 1, "scale_ins": 1,
        "actions_with_evidence": 2, "actions_evidence_free": 0,
        "fleet_scrape_rounds": 12, "trace_overhead_pct": 1.5,
        "compiles_steady": 0,
    }
    assert validate_bench_row(row) == []


# -- trace assembly tooling ---------------------------------------------------


def _write_spans(path, spans):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for s in spans:
            f.write(json.dumps(
                {"v": SCHEMA_VERSION, "kind": "span", "t": 0.0, **s}) + "\n")


def _span(trace_id, span_id, name, start_s, dur_s, parent_id=None, **attrs):
    return {"trace_id": trace_id, "span_id": span_id, "name": name,
            "start_s": start_s, "dur_s": dur_s, "parent_id": parent_id,
            "thread": "main", **attrs}


def test_trace_view_fleet_merge_stats(tmp_path):
    router_path = str(tmp_path / "router" / "telemetry.jsonl")
    rep_path = str(tmp_path / "rep0" / "telemetry.jsonl")
    _write_spans(router_path, [
        _span("t1", "r1", "route.submit", 0.0, 0.5, stage="route"),
        _span("t1", "r2", "route.dispatch", 0.01, 0.48, parent_id="r1",
              stage="route", replica="rep0"),
    ])
    _write_spans(rep_path, [
        _span("t1", "rep0a", "serve.request", 100.0, 0.4, parent_id="r2",
              remote_parent=True),
        _span("t1", "rep0b", "serve.dispatch", 100.1, 0.2,
              parent_id="rep0a", stage="dispatch"),
        _span("t1", "rep0c", "orphaned", 100.3, 0.1, parent_id="gone"),
    ])
    tv = _load_script("trace_view")
    doc, stats = tv.merge_fleet([router_path, rep_path])
    assert stats["spans"] == 5
    assert stats["traces"] == 1
    assert stats["orphans"] == 1  # only the torn parent, not the remote one
    assert stats["remote_parented"] == 1
    assert stats["remote_resolved"] == 1
    assert stats["duplicate_span_ids"] == []
    # one process lane per file, labeled by parent dir (stems collide)
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e["name"] == "process_name"}
    assert names == {"router/telemetry", "rep0/telemetry"}
    # each lane rebased independently to its earliest span
    starts = [e["ts"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert min(starts) == 0.0
    assert max(starts) < 1e6  # the 100 s clock offset did not survive

    # duplicate span ids across files (a replica missing its id_prefix)
    dup_path = str(tmp_path / "rep1" / "telemetry.jsonl")
    _write_spans(dup_path, [_span("t2", "r1", "serve.request", 0.0, 0.1)])
    _, stats = tv.merge_fleet([router_path, rep_path, dup_path])
    assert stats["duplicate_span_ids"] == ["r1"]


def test_trace_view_fleet_cli_and_trace_filter(tmp_path, capsys):
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    _write_spans(a, [_span("t1", "s1", "route.submit", 0.0, 0.2),
                     _span("t9", "s9", "stray", 0.0, 0.1)])
    _write_spans(b, [_span("t1", "s2", "serve.request", 0.0, 0.15,
                           parent_id="s1", remote_parent=True)])
    tv = _load_script("trace_view")
    out = str(tmp_path / "fleet.json")
    rc = tv.main([a, b, "--fleet", "--trace", "t1", "--out", out])
    assert rc == 0
    doc = json.loads(open(out).read())
    x_events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(x_events) == 2  # the stray t9 span was filtered out
    printed = capsys.readouterr().out
    assert "orphan spans: 0/2" in printed
    # multiple paths without --fleet is an argparse error
    with pytest.raises(SystemExit):
        tv.main([a, b])


def test_tlm_report_fleet_trace_section_and_diff_gates(tmp_path):
    tlm = _load_script("tlm_report")
    clean_rows = [
        _row("span", **_span("t1", "r1", "route.submit", 0.0, 0.5,
                             stage="route", replica="rep0")),
        _row("span", **_span("t1", "s1", "serve.dispatch", 0.1, 0.3,
                             parent_id="r1", stage="dispatch")),
        _row("scale_decision", action="out", reason="slo_miss",
             n_replicas=2, streak=2, evidence={
                 "attainment_series": [0.7], "queue_depths": {"rep0": 4},
                 "deny_rate": 0.0, "exemplar_trace_ids": ["t1"]}),
    ]
    base = tlm.summarize(clean_rows)
    assert base["trace_orphans"] == 0
    assert base["trace_remote_parented"] == 0
    assert base["scale_actions"] == 1
    assert base["scale_actions_with_evidence"] == 1
    assert base["scale_actions_evidence_free"] == 0
    # the propagated-trace join attributes replica-side stages
    assert base["fleet_stage_by_replica"]["rep0"]["n"] == 2

    broken_rows = [
        _row("span", **_span("t1", "r1", "route.submit", 0.0, 0.5,
                             stage="route")),
        _row("span", **_span("t1", "s1", "serve.dispatch", 0.1, 0.3,
                             parent_id="gone", stage="dispatch")),
        _row("scale_decision", action="out", reason="slo_miss",
             n_replicas=2, streak=2),
    ]
    cand = tlm.summarize(broken_rows)
    assert cand["trace_orphans"] == 1
    assert cand["scale_actions_evidence_free"] == 1
    flags = tlm.diff(base, cand, gate_pct=5.0)
    assert any("orphan-span rate grew" in f for f in flags)
    assert any("evidence-free scale actions grew" in f for f in flags)
    # no self-regression: a run diffed against itself stays silent
    assert not any("orphan" in f or "evidence" in f
                   for f in tlm.diff(base, base, gate_pct=5.0))


def _row(kind, **fields):
    return {"v": SCHEMA_VERSION, "kind": kind, "t": 0.0, **fields}


# -- the two-process propagation test -----------------------------------------

# A serve.py-shaped child: the REAL make_server/render_pose HTTP stack
# over a fake engine whose render emits dispatch/device stage spans
# around sleeps — so the parent can assert the child's tree parents
# under the router's propagated ctx and covers >= 95% of the request.
_SERVE_CHILD = """\
import json, os, sys, time

repo_dir, telem_path = sys.argv[1:3]
sys.path.insert(0, repo_dir)

import numpy as np

from nerf_replication_tpu.obs import configure_tracing, get_tracer
from nerf_replication_tpu.obs import emit as emit_mod

emit_mod._active = emit_mod.Emitter(telem_path, chief=True)
configure_tracing(enabled=True,
                  id_prefix=os.environ.get("SCALE_REPLICA_ID", ""))

import serve as serve_cli


class Options:
    request_timeout_s = 5.0


class Engine:
    default_camera = {"H": 8, "W": 8, "focal": 10.0}
    options = Options()

    def stats(self):
        return {"warm_source": "disk", "total_compiles": 0}

    def render_view(self, c2w, H, W, focal, via=None, scene=None):
        trs = get_tracer()
        with trs.span("serve.dispatch", stage="dispatch"):
            time.sleep(0.4)
        with trs.span("serve.device", stage="device"):
            time.sleep(0.4)
        return np.zeros((H, W, 3), np.uint8), {"tier": "full",
                                               "cache_hit": False}


serve_cli._resolve_pose({"theta": 0.0})  # imports paid before the timed path
server = serve_cli.make_server(Engine(), None, port=0, slo_target_ms=100.0)
print(json.dumps({"port": server.server_address[1]}), flush=True)
server.serve_forever()
"""


def _read_child_port(proc, timeout_s=240.0):
    """First stdout line (the child's port report) with a watchdog."""
    out = {}

    def read():
        out["line"] = proc.stdout.readline()

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    return out.get("line", "")


def _load_spans(path):
    spans = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                row = json.loads(line)
                if row.get("kind") == "span":
                    spans.append(row)
    return spans


def test_two_process_trace_spans_router_and_replica(tmp_path, monkeypatch):
    """The tentpole acceptance: ONE routed request is ONE trace across
    two real processes. The child's serve.request parents under the
    router's route.dispatch via the propagated Traceparent header; both
    sides reconstruct >= 95% of their wall time; the merged fleet trace
    has zero orphan spans."""
    router_telem = str(tmp_path / "router" / "telemetry.jsonl")
    os.makedirs(os.path.dirname(router_telem), exist_ok=True)
    em = emit_mod.Emitter(router_telem, chief=True)
    monkeypatch.setattr(emit_mod, "_active", em)
    reset_metrics()
    configure_tracing(enabled=True, id_prefix="router")

    child_telem = str(tmp_path / "rep0" / "telemetry.jsonl")
    os.makedirs(os.path.dirname(child_telem), exist_ok=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu", SCALE_REPLICA_ID="rep0")
    err_path = tmp_path / "child_stderr.txt"
    with open(err_path, "w") as errf:
        proc = subprocess.Popen(
            [sys.executable, "-c", _SERVE_CHILD, _REPO, child_telem],
            stdout=subprocess.PIPE, stderr=errf, text=True, env=env,
            cwd=_REPO,
        )
    try:
        line = _read_child_port(proc)
        assert line, ("child never reported a port; stderr:\n"
                      + err_path.read_text()[-2000:])
        port = json.loads(line)["port"]

        rep = ProcessReplica("rep0", cfg_file="unused.yaml",
                             host="127.0.0.1", port=port)
        rep.proc = proc
        router = Router(heartbeat_timeout_s=30.0)
        router.register(rep)
        router.sweep()
        assert rep.state == ReplicaState.READY

        out = router.render({"theta": 40.0, "phi": -30.0, "radius": 4.0,
                             "H": 8, "W": 8, "focal": 10.0},
                            timeout_s=60.0)
        assert out["h"] == 8 and out["tier"] == "full"

        # the /healthz replica block surfaces tracing health: the child
        # counted its serve.request as remote-parented
        beat = rep.heartbeat()
        assert beat["trace"]["enabled"] is True
        assert beat["trace"]["remote_parented"] >= 1
        assert beat["trace"]["spans"] >= 3

        # fleet metrics aggregate the child's registry over HTTP with a
        # replica label (serve_stage_seconds fed by its stage spans)
        agg = FleetMetricsAggregator(router, slo_target_s=0.25)
        merged = agg.render()
        assert 'replica="rep0"' in merged
        assert "serve_stage_seconds" in merged

        # drain-before-retire shuts the child down cleanly
        assert router.drain("rep0", timeout_s=30.0) == 0
        assert rep.state == ReplicaState.RETIRED
        proc.wait(timeout=15)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        proc.stdout.close()
        configure_tracing(enabled=False)
        reset_metrics()
    em.close()

    router_spans = _load_spans(router_telem)
    child_spans = _load_spans(child_telem)
    submits = [s for s in router_spans if s["name"] == "route.submit"]
    dispatches = [s for s in router_spans if s["name"] == "route.dispatch"]
    requests = [s for s in child_spans if s["name"] == "serve.request"]
    assert len(submits) == 1 and len(dispatches) == 1 and len(requests) == 1
    submit, dispatch, request = submits[0], dispatches[0], requests[0]

    # the propagated header made the child's root a child of the
    # router's dispatch span — one request, one trace, two processes
    assert request["remote_parent"] is True
    assert request["parent_id"] == dispatch["span_id"]
    assert request["trace_id"] == submit["trace_id"]
    assert dispatch["parent_id"] == submit["span_id"]
    # per-process id prefixes keep the merged id space collision-free
    assert request["span_id"].startswith("rep0")
    assert submit["span_id"].startswith("router")

    stages = {s["name"]: s for s in child_spans
              if s["name"] in ("serve.dispatch", "serve.device")}
    assert set(stages) == {"serve.dispatch", "serve.device"}
    for s in stages.values():
        assert s["parent_id"] == request["span_id"]
        assert s["trace_id"] == request["trace_id"]

    # >= 95% of the routed wall time reconstructs on both sides
    stage_sum = sum(s["dur_s"] for s in stages.values())
    assert stage_sum >= 0.95 * request["dur_s"]
    assert dispatch["dur_s"] >= 0.95 * submit["dur_s"]

    # every emitted row (spans, replica lifecycle, router events) is
    # schema-clean end to end
    for path in (router_telem, child_telem):
        with open(path) as f:
            for line in f:
                row = json.loads(line)
                assert validate_row(row) == [], row

    # the merged fleet trace joins the two files with zero orphans
    tv = _load_script("trace_view")
    doc, stats = tv.merge_fleet([router_telem, child_telem])
    assert stats["orphans"] == 0
    assert stats["remote_parented"] >= 1
    assert stats["remote_resolved"] == stats["remote_parented"]
    assert stats["duplicate_span_ids"] == []
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e["name"] == "process_name"}
    assert lanes == {"router/telemetry", "rep0/telemetry"}
