"""Training subsystem tests: schedules, optimizer, jitted step, end-to-end
learning on the procedural scene, checkpoint round-trip."""

import os

import chex
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nerf_replication_tpu.config import make_cfg
from nerf_replication_tpu.datasets.blender import Dataset
from nerf_replication_tpu.datasets.procedural import generate_scene
from nerf_replication_tpu.models import make_network
from nerf_replication_tpu.train import (
    Trainer,
    make_loss,
    make_lr_schedule,
    make_optimizer,
    make_train_state,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_cfg(scene_root, extra=()):
    """A miniature lego-schema config that compiles fast on 1-core CPU."""
    return make_cfg(
        os.path.join(ROOT, "configs", "nerf", "lego.yaml"),
        [
            "scene", "procedural",
            "train_dataset.data_root", str(scene_root),
            "test_dataset.data_root", str(scene_root),
            "train_dataset.H", "16", "train_dataset.W", "16",
            "test_dataset.H", "16", "test_dataset.W", "16",
            "task_arg.N_rays", "128",
            "task_arg.N_samples", "24",
            "task_arg.N_importance", "24",
            "task_arg.chunk_size", "256",
            "task_arg.precrop_iters", "0",
            "network.nerf.W", "64",
            "network.nerf.D", "3",
            "network.nerf.skips", "[1]",
            "network.xyz_encoder.freq", "6",
            "network.dir_encoder.freq", "2",
            "ep_iter", "25",
            *extra,
        ],
    )


@pytest.fixture(scope="module")
def scene_root(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("scene"))
    generate_scene(root, scene="procedural", H=16, W=16, n_train=6, n_test=2)
    return root


def test_exponential_schedule_matches_reference_formula(scene_root):
    cfg = tiny_cfg(scene_root)
    sched = make_lr_schedule(cfg)
    lr0 = float(cfg.train.lr)
    gamma, decay_epochs, ep_iter = 0.1, 500.0, 25
    for step in (0, 25, 1000):
        epoch = step / ep_iter
        expected = lr0 * gamma ** (epoch / decay_epochs)
        np.testing.assert_allclose(float(sched(step)), expected, rtol=1e-6)


def test_multistep_schedule(scene_root):
    cfg = tiny_cfg(
        scene_root,
        ["train.scheduler.type", "multi_step",
         "train.scheduler.milestones", "[2, 4]",
         "train.scheduler.gamma", "0.5"],
    )
    sched = make_lr_schedule(cfg)  # ep_iter=25 → boundaries at 50, 100
    lr0 = float(cfg.train.lr)
    assert np.isclose(float(sched(0)), lr0)
    assert np.isclose(float(sched(60)), lr0 * 0.5)
    assert np.isclose(float(sched(120)), lr0 * 0.25)


def test_grad_clip_by_value():
    import optax

    from nerf_replication_tpu.train.optim import GRAD_CLIP_VALUE

    assert GRAD_CLIP_VALUE == 40.0
    clip = optax.clip(GRAD_CLIP_VALUE)
    g = {"w": jnp.array([100.0, -100.0, 3.0])}
    out, _ = clip.update(g, clip.init(g))
    np.testing.assert_allclose(out["w"], [40.0, -40.0, 3.0])


def test_train_step_runs_and_descends(scene_root):
    cfg = tiny_cfg(scene_root)
    net = make_network(cfg)
    loss = make_loss(cfg, net)
    trainer = Trainer(cfg, net, loss)
    state, schedule = make_train_state(cfg, net, jax.random.PRNGKey(0))

    ds = Dataset(
        data_root=scene_root, scene="procedural", split="train", H=16, W=16
    )
    bank_rays, bank_rgbs = (jnp.asarray(a) for a in ds.ray_bank())
    base_key = jax.random.PRNGKey(1)

    losses = []
    for _ in range(30):
        state, stats = trainer.step(state, bank_rays, bank_rgbs, base_key)
        losses.append(float(stats["loss"]))
    assert int(state.step) == 30
    assert np.all(np.isfinite(losses))
    # loss should clearly descend on this easy scene
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.8


def test_end_to_end_learning_psnr_climbs(scene_root):
    """The minimum end-to-end slice (SURVEY.md §7 step 5): PSNR improves."""
    cfg = tiny_cfg(scene_root)
    net = make_network(cfg)
    loss = make_loss(cfg, net)
    trainer = Trainer(cfg, net, loss)
    state, schedule = make_train_state(cfg, net, jax.random.PRNGKey(0))
    ds = Dataset(
        data_root=scene_root, scene="procedural", split="train", H=16, W=16
    )
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    base_key = jax.random.PRNGKey(1)

    psnr_first = None
    for i in range(150):
        state, stats = trainer.step(state, bank[0], bank[1], base_key)
        if i == 0:
            psnr_first = float(stats["psnr"])
    psnr_last = float(stats["psnr"])
    assert psnr_last > psnr_first + 3.0, (psnr_first, psnr_last)
    assert psnr_last > 12.0


def test_epoch_iters_sentinel(scene_root):
    """ep_iter=-1 must mean one natural pass over the bank, never 0 steps."""
    cfg = tiny_cfg(scene_root, ["ep_iter", "-1"])
    net = make_network(cfg)
    trainer = Trainer(cfg, net, make_loss(cfg, net))
    assert trainer.epoch_iters(bank_size=1536) == 1536 // 128
    assert trainer.epoch_iters(bank_size=10) == 1  # never zero
    cfg2 = tiny_cfg(scene_root)
    trainer2 = Trainer(cfg2, net, make_loss(cfg2, net))
    assert trainer2.epoch_iters(bank_size=10**9) == 25


def test_step_rng_distinct_across_processes(scene_root):
    """Data-parallel processes must draw different ray batches."""
    cfg = tiny_cfg(scene_root)
    net = make_network(cfg)
    loss = make_loss(cfg, net)
    ds = Dataset(
        data_root=scene_root, scene="procedural", split="train", H=16, W=16
    )
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(0)

    t0 = Trainer(cfg, net, loss)
    t1 = Trainer(cfg, net, loss)
    t1.process_index = 1  # simulate rank 1
    state0, _ = make_train_state(cfg, net, jax.random.PRNGKey(0))
    state1, _ = make_train_state(cfg, net, jax.random.PRNGKey(0))
    _, s0 = t0.step(state0, bank[0], bank[1], key)
    _, s1 = t1.step(state1, bank[0], bank[1], key)
    # same params, same step, different process → different batch → loss
    assert float(s0["loss"]) != float(s1["loss"])


def test_precrop_pool_step_variant(scene_root):
    cfg = tiny_cfg(scene_root, ["task_arg.precrop_iters", "10"])
    net = make_network(cfg)
    loss = make_loss(cfg, net)
    trainer = Trainer(cfg, net, loss)
    state, _ = make_train_state(cfg, net, jax.random.PRNGKey(0))
    ds = Dataset(
        data_root=scene_root, scene="procedural", split="train", H=16, W=16
    )
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    pool = jnp.asarray(ds.precrop_index_pool(0.5))
    state, stats = trainer.step(
        state, bank[0], bank[1], jax.random.PRNGKey(1), index_pool=pool
    )
    assert np.isfinite(float(stats["loss"]))


def test_checkpoint_roundtrip(scene_root, tmp_path):
    from nerf_replication_tpu.train.checkpoint import (
        load_model,
        load_network,
        save_model,
    )

    cfg = tiny_cfg(scene_root)
    net = make_network(cfg)
    state, _ = make_train_state(cfg, net, jax.random.PRNGKey(0))
    state = state.replace(step=123)
    model_dir = str(tmp_path / "ckpt")

    save_model(model_dir, state, epoch=7, recorder_state={"step": 123}, latest=True)
    save_model(model_dir, state, epoch=7, recorder_state={"step": 123})

    state2, _ = make_train_state(cfg, net, jax.random.PRNGKey(42))
    restored, begin_epoch, rec = load_model(model_dir, state2)
    assert begin_epoch == 8
    assert int(restored.step) == 123
    assert rec["step"] == 123
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b),
        restored.params, state.params,
    )

    params_only, picked = load_network(model_dir, {"params": state2.params})
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b),
        params_only["params"], state.params,
    )


def test_checkpoint_retention(scene_root, tmp_path):
    from nerf_replication_tpu.train.checkpoint import KEEP_EPOCHS, save_model

    cfg = tiny_cfg(scene_root)
    net = make_network(cfg)
    state, _ = make_train_state(cfg, net, jax.random.PRNGKey(0))
    model_dir = str(tmp_path / "ckpt")
    for ep in range(8):
        save_model(model_dir, state, epoch=ep)
    kept = sorted(
        int(d) for d in os.listdir(model_dir) if d.isdigit()
    )
    assert kept == list(range(8 - KEEP_EPOCHS, 8))


def test_recorder_smoothing_and_console(tmp_path):
    from nerf_replication_tpu.config import ConfigNode
    from nerf_replication_tpu.train.recorder import Recorder, SmoothedValue

    sv = SmoothedValue(window_size=3)
    for v in (1.0, 2.0, 9.0):
        sv.update(v)
    assert sv.median == 2.0
    assert np.isclose(sv.avg, 4.0)
    assert np.isclose(sv.global_avg, 4.0)
    sv.update(2.0)  # window drops 1.0
    assert sv.median == 2.0
    assert np.isclose(sv.global_avg, 3.5)

    cfg = ConfigNode({"record_dir": str(tmp_path / "rec"), "resume": False})
    rec = Recorder(cfg)
    rec.update_loss_stats({"loss": 0.5, "psnr": 20.0})
    rec.step = 10
    line = rec.console_line(epoch=1, it=5, max_iter=25, lr=5e-4)
    for token in ("eta:", "epoch: 1", "step: 10", "loss:", "psnr:", "lr: 0.000500"):
        assert token in line


def test_multi_step_scan_matches_sequential_steps(scene_root):
    """task_arg.scan_steps runs K optimizer steps inside one lax.scan
    dispatch; the step body derives its RNG from state.step exactly like
    the single-step path, so a K-burst must reproduce K sequential calls
    step-for-step (same final params, same final stats)."""
    cfg = tiny_cfg(scene_root)
    net = make_network(cfg)
    loss = make_loss(cfg, net)
    ds = Dataset(
        data_root=scene_root, scene="procedural", split="train", H=16, W=16
    )
    bank_rays, bank_rgbs = (jnp.asarray(a) for a in ds.ray_bank())
    base_key = jax.random.PRNGKey(1)

    # arm A: 6 sequential single steps
    trainer_a = Trainer(cfg, net, loss)
    state_a, _ = make_train_state(cfg, net, jax.random.PRNGKey(0))
    for _ in range(6):
        state_a, stats_a = trainer_a.step(
            state_a, bank_rays, bank_rgbs, base_key
        )

    # arm B: one 4-burst + one clamped 2-burst (the epoch-tail case)
    trainer_b = Trainer(cfg, net, loss)
    state_b, _ = make_train_state(cfg, net, jax.random.PRNGKey(0))
    state_b, _ = trainer_b.multi_step(
        state_b, bank_rays, bank_rgbs, base_key, k_steps=4
    )
    state_b, stats_b = trainer_b.multi_step(
        state_b, bank_rays, bank_rgbs, base_key, k_steps=2
    )

    assert int(state_a.step) == int(state_b.step) == 6
    chex.assert_trees_all_close(
        state_a.params, state_b.params, rtol=1e-5, atol=1e-6
    )
    assert np.isclose(
        float(stats_a["loss"]), float(stats_b["loss"]), rtol=1e-5, atol=1e-7
    )
    # k_steps=1 must reuse the plain step path (no scan executable)
    assert trainer_b.multi_step(
        state_b, bank_rays, bank_rgbs, base_key, k_steps=1
    ) is not None


def test_train_epoch_with_scan_steps_bursts(scene_root):
    """train_epoch under scan_steps>1: same step count per epoch, logging
    cadence preserved at burst granularity, and the precrop pool window
    still single-steps (bursts would straddle the precrop boundary)."""
    from nerf_replication_tpu.train.recorder import Recorder

    cfg = tiny_cfg(scene_root, [
        "task_arg.scan_steps", "4", "ep_iter", "10", "log_interval", "5",
    ])
    net = make_network(cfg)
    loss = make_loss(cfg, net)
    trainer = Trainer(cfg, net, loss)
    state, schedule = make_train_state(cfg, net, jax.random.PRNGKey(0))
    ds = Dataset(
        data_root=scene_root, scene="procedural", split="train", H=16, W=16
    )
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    rec = Recorder(cfg)
    lines = []
    state, stats = trainer.train_epoch(
        state, 0, bank, jax.random.PRNGKey(1), rec, schedule,
        log=lines.append,
    )
    assert int(state.step) == 10  # 4 + 4 + clamped 2
    assert float(stats["loss"]) == float(stats["loss"])  # finite, present
    assert lines  # console cadence still produces output


def test_grad_accum_matches_full_batch_memory_shape(scene_root):
    """grad_accum=A must produce the mean-of-microbatch gradients: equal to
    a full batch over the union of the A microbatch draws. (The HBM lever
    for past-roofline batches — PERF.md round 4: 65,536 rays OOM as one
    batch, fit as 4 x 16,384.)"""
    import jax.numpy as jnp

    from nerf_replication_tpu.datasets.blender import Dataset
    from nerf_replication_tpu.train.step_core import sampled_grad_step

    cfg = tiny_cfg(scene_root)
    from nerf_replication_tpu.models import make_network
    from nerf_replication_tpu.train import make_loss, make_train_state

    network = make_network(cfg)
    loss = make_loss(cfg, network)
    state, _ = make_train_state(cfg, network, jax.random.PRNGKey(0))
    ds = Dataset(
        data_root=scene_root, scene="procedural", split="train", H=16, W=16
    )
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    near, far = float(cfg.task_arg.near), float(cfg.task_arg.far)
    ks, kr = jax.random.split(jax.random.PRNGKey(3))

    g_acc, stats_acc = jax.jit(
        lambda p: sampled_grad_step(
            loss, p, bank[0], bank[1], 128, near, far, ks, kr, grad_accum=4
        )
    )(state.params)

    # reference: mean of the 4 microbatch grads computed independently
    kss = jax.random.split(ks, 4)
    krs = jax.random.split(kr, 4)
    gs = []
    for i in range(4):
        g_i, _ = sampled_grad_step(
            loss, state.params, bank[0], bank[1], 32, near, far,
            kss[i], krs[i],
        )
        gs.append(g_i)
    g_ref = jax.tree_util.tree_map(
        lambda *a: sum(a) / 4.0, *gs
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(g_acc), jax.tree_util.tree_leaves(g_ref)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )
    assert np.isfinite(float(stats_acc["loss"]))
    # psnr must be the psnr OF THE AVERAGED mse, not the average of the
    # per-microbatch psnrs (nonlinear — round-4 advisor: logged metrics
    # shifted with grad_accum even though the gradient is exact)
    from nerf_replication_tpu.train.loss import mse_to_psnr

    base = stats_acc.get("loss_f", stats_acc.get("loss_c"))
    np.testing.assert_allclose(
        float(stats_acc["psnr"]), float(mse_to_psnr(base)), rtol=1e-6
    )
