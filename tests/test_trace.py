"""Observability tentpole tests (obs/trace.py, obs/metrics.py,
resil/flight.py): cross-thread span parentage through the micro-batcher
and the fleet prefetch seam, the bounded flight-recorder ring and its
deterministic dumps, Prometheus /metrics rendering + the HTTP endpoint,
the Chrome-trace exporter round-trip, zero steady-state recompiles with
tracing ON, and flight-dump validation through the schema checker."""

import importlib.util
import json
import os
import re
import sys
import threading

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from nerf_replication_tpu.obs import (  # noqa: E402
    configure_tracing,
    current_ctx,
    get_metrics,
    get_tracer,
    reset_metrics,
    validate_row,
)
from nerf_replication_tpu.obs import emit as emit_mod  # noqa: E402
from nerf_replication_tpu.resil import (  # noqa: E402
    FlightRecorder,
    install_flight_recorder,
    uninstall_flight_recorder,
    validate_flight_dump,
)


def _load_script(name):
    path = os.path.join(_REPO, "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _rays(n, seed=0):
    rng = np.random.default_rng(seed)
    d = np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.1, (n, 3))
    return np.concatenate(
        [np.tile([0.0, 0.0, 4.0], (n, 1)), d], -1
    ).astype(np.float32)


@pytest.fixture
def telem(tmp_path, monkeypatch):
    """Route the process emitter at a scratch JSONL; yields its path."""
    path = str(tmp_path / "telemetry.jsonl")
    em = emit_mod.Emitter(path, chief=True)
    monkeypatch.setattr(emit_mod, "_active", em)
    yield path
    em.close()


@pytest.fixture
def traced(telem):
    """Tracing ON with a span-collecting sink; resets tracer + metrics
    after, so other tests see the disabled default. Yields the list the
    sink appends finished span rows to."""
    reset_metrics()
    configure_tracing(enabled=True)
    spans = []
    get_tracer().add_sink(spans.append)
    yield spans
    configure_tracing(enabled=False)
    reset_metrics()


def _by_name(spans):
    out = {}
    for s in spans:
        out.setdefault(s["name"], []).append(s)
    return out


# -- span basics -------------------------------------------------------------


def test_span_nesting_ids_and_error_status(traced):
    trs = get_tracer()
    with trs.span("outer", parent=None) as outer:
        with trs.span("inner") as inner:
            assert inner.ctx.trace_id == outer.ctx.trace_id
            assert current_ctx().span_id == inner.ctx.span_id
        assert current_ctx().span_id == outer.ctx.span_id
    with pytest.raises(ValueError):
        with trs.span("boom", parent=None):
            raise ValueError("x")
    rows = _by_name(traced)
    assert rows["inner"][0]["parent_id"] == outer.ctx.span_id
    assert rows["outer"][0]["parent_id"] is None
    assert rows["boom"][0]["status"] == "error:ValueError"
    # ids are counter-based 8-hex — deterministic per configure
    assert re.fullmatch(r"[0-9a-f]{8}", rows["outer"][0]["span_id"])


def test_disabled_tracer_is_inert(telem):
    configure_tracing(enabled=False)
    trs = get_tracer()
    with trs.span("nope", parent=None) as sp:
        assert sp.ctx is None
        sp.set(tier="full")  # absorbed, never raises
    trs.record("nope2", start_s=0.0, dur_s=1.0)
    assert current_ctx() is None


# -- cross-thread propagation through the micro-batcher ----------------------


def test_batcher_spans_parent_the_submitting_request(traced):
    """The HTTP-shaped seam: a root span on the submitting thread must
    become the parent of the queue record (cut time), the worker's batch
    span, and the scatter record — one joinable trace across threads."""
    from test_resil import FakeEngine

    from nerf_replication_tpu.serve import MicroBatcher

    engine = FakeEngine()
    batcher = MicroBatcher(engine)
    trs = get_tracer()
    try:
        with trs.span("serve.request", parent=None) as root:
            root_ctx = root.ctx
            out = batcher.submit(_rays(8), 2.0, 6.0).result(timeout=5.0)
        assert out["rgb_map_f"].shape == (8, 3)
    finally:
        batcher.close()
    rows = _by_name(traced)
    queue = rows["serve.queue"][0]
    batch = rows["serve.batch"][0]
    scatter = rows["serve.scatter"][0]
    for row in (queue, batch, scatter):
        assert row["trace_id"] == root_ctx.trace_id
        assert row["parent_id"] == root_ctx.span_id
    assert queue["stage"] == "queue" and scatter["stage"] == "scatter"
    # the batch ran on the worker thread, the root on this one
    assert batch["thread"] != rows["serve.request"][0]["thread"]
    assert batch["n_requests"] == 1
    # every span row the pipeline emitted validates against the schema
    with open(emit_mod._active.path) as f:
        emitted = [json.loads(line) for line in f if line.strip()]
    span_rows = [r for r in emitted if r.get("kind") == "span"]
    assert len(span_rows) == len(traced)
    for r in span_rows:
        assert validate_row(r) == [], r


def test_prefetch_load_attributed_to_issuing_request(traced):
    """The fleet seam: a prefetch issued under request A runs on its own
    thread but its scene.load span must land in A's trace; a request B
    joining that in-flight load gets ``joined: prefetch`` on its acquire
    span — who paid vs who rode, disentangled."""
    from nerf_replication_tpu.fleet import (
        ResidencyManager,
        SceneData,
        SceneRecord,
        SceneRegistry,
    )

    data = SceneData(scene_id="a",
                     params={"w": np.zeros(64, np.float32)})
    started, release = threading.Event(), threading.Event()

    def loader(rec):
        started.set()
        assert release.wait(5.0)
        return data

    mgr = ResidencyManager(
        SceneRegistry([SceneRecord(scene_id="a")]), loader,
        budget_bytes=1 << 20, verify_checksums=False,
    )
    trs = get_tracer()
    with trs.span("origin.prefetch", parent=None) as op:
        op_ctx = op.ctx
        assert mgr.prefetch("a")
    assert started.wait(5.0)
    timer = threading.Timer(0.05, release.set)
    timer.start()
    try:
        with trs.span("origin.request", parent=None) as rq:
            rq_ctx = rq.ctx
            assert mgr.acquire("a").scene_id == "a"
        mgr.release("a")
    finally:
        timer.join()
    rows = _by_name(traced)
    load = rows["scene.load"][0]
    assert load["source"] == "prefetch"
    assert load["trace_id"] == op_ctx.trace_id
    assert load["parent_id"] == op_ctx.span_id
    assert load["thread"].startswith("fleet-prefetch")
    acquire = rows["scene.acquire"][0]
    assert acquire["trace_id"] == rq_ctx.trace_id
    assert acquire["joined"] == "prefetch"


def test_zero_steady_state_recompiles_with_tracing_on(traced):
    """The invariant the whole module is built around: tracing is
    host-side only, so a warm mixed-shape stream with tracing ON must
    not move the CompileTracker total."""
    from test_resil import FakeEngine

    from nerf_replication_tpu.serve import MicroBatcher

    engine = FakeEngine()
    batcher = MicroBatcher(engine)
    try:
        batcher.submit(_rays(8), 2.0, 6.0).result(timeout=5.0)  # warmup
        before = engine.tracker.total_compiles()
        for n in (4, 8, 16, 32, 64):
            out = batcher.submit(_rays(n), 2.0, 6.0).result(timeout=5.0)
            assert out["rgb_map_f"].shape == (n, 3)
        assert engine.tracker.total_compiles() == before
    finally:
        batcher.close()
    assert any(s["name"] == "serve.batch" for s in traced)  # it DID trace


# -- flight recorder ---------------------------------------------------------


def test_flight_ring_bounded_and_dump_deterministic(tmp_path):
    clk = FakeClock(100.0)
    rec = FlightRecorder(str(tmp_path), capacity=4, clock=clk)
    for i in range(10):
        rec.record({"trace_id": "t0", "span_id": f"{i:08x}", "name": "s",
                    "start_s": float(i), "dur_s": 0.5})
    rec.note(point="serve.flush", fault="kill")
    stats = rec.stats()
    assert stats["spans"] == 4 and stats["capacity"] == 4
    path = rec.dump("breaker_open", detail="threshold=2")
    assert os.path.basename(path) == "flight_breaker_open.json"
    with open(path) as f:
        payload = json.load(f)
    assert validate_flight_dump(payload) == []
    # ring keeps exactly the LAST capacity spans, in finish order
    assert [s["span_id"] for s in payload["spans"]] == [
        f"{i:08x}" for i in range(6, 10)
    ]
    assert payload["events"][0]["fault"] == "kill"
    assert payload["t"] == 100.0
    # frozen clock + same ring state -> byte-identical re-dump
    first = open(path, "rb").read()
    rec.dump("breaker_open", detail="threshold=2")
    assert open(path, "rb").read() == first


def test_dump_reason_sanitized_and_noop_when_uninstalled(tmp_path):
    from nerf_replication_tpu.resil import dump_flight

    assert dump_flight("anything") is None  # no recorder installed
    rec = FlightRecorder(str(tmp_path))
    path = rec.dump("scene error: a/b!")
    assert os.path.basename(path) == "flight_scene_error_a_b_.json"


def test_breaker_open_triggers_flight_dump(tmp_path, telem):
    from nerf_replication_tpu.resil import CircuitBreaker

    reset_metrics()
    install_flight_recorder(FlightRecorder(str(tmp_path)))
    try:
        br = CircuitBreaker(threshold=2, cooldown_s=60.0)
        br.record_failure()
        br.record_failure()
        dump = os.path.join(str(tmp_path), "flight_breaker_open.json")
        assert os.path.exists(dump)
        with open(dump) as f:
            payload = json.load(f)
        assert validate_flight_dump(payload) == []
        assert "threshold" not in payload["reason"]  # reason is the kind
        assert payload["reason"] == "breaker_open"
        # the transition also landed in the live metrics
        snap = get_metrics().snapshot()
        assert any("serve_breaker_transitions_total" in k
                   and 'state="open"' in k for k in snap["counters"])
    finally:
        uninstall_flight_recorder()
        reset_metrics()


def test_installed_recorder_rings_tracer_spans(tmp_path, traced):
    rec = install_flight_recorder(FlightRecorder(str(tmp_path)))
    try:
        with get_tracer().span("req", parent=None, stage="dispatch"):
            pass
        assert rec.stats()["spans"] == 1
        payload = json.load(open(rec.dump("sigterm")))
        assert payload["spans"][0]["name"] == "req"
    finally:
        uninstall_flight_recorder()


# -- live metrics ------------------------------------------------------------


def test_metrics_prometheus_rendering_parses(tmp_path):
    reset_metrics()
    try:
        mx = get_metrics()
        mx.counter("serve_requests_total", status="ok", tier="full")
        mx.counter("serve_requests_total", 2, status="timeout", tier="none")
        mx.gauge("serve_queue_depth", 3)
        for v in (0.004, 0.02, 0.02):
            mx.observe("serve_request_latency_seconds", v, tier="full")
        # the slow observe carries a trace id -> its bucket renders an
        # OpenMetrics exemplar suffix (` # {trace_id="..."} <value>`)
        mx.observe("serve_request_latency_seconds", 0.7, trace_id="t0042",
                   tier="full")
        text = mx.render_prometheus()
        line_re = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+-]+(e[+-]?\d+)?"
            r'( # \{trace_id="[a-zA-Z0-9]+"\} [0-9.e+-]+)?$'
        )
        for line in text.strip().splitlines():
            assert line.startswith("# TYPE") or line_re.match(line), line
        assert 'serve_requests_total{status="ok",tier="full"} 1' in text
        assert '# {trace_id="t0042"} 0.7' in text
        # histogram: cumulative buckets end at +Inf == _count
        bucket_vals = [
            float(m.group(1)) for m in re.finditer(
                r'serve_request_latency_seconds_bucket\{[^}]*\} ([0-9.]+)',
                text)
        ]
        assert bucket_vals == sorted(bucket_vals)  # cumulative, monotonic
        assert 'le="+Inf"' in text
        assert "serve_request_latency_seconds_count" in text
        view = mx.slo_view(0.1)
        assert view["requests"] == 3  # counter total, not histogram count
        assert view["attainment"] == pytest.approx(3 / 4)
        assert view["timeout_rate"] == pytest.approx(2 / 3, abs=1e-3)
    finally:
        reset_metrics()


def test_http_metrics_and_healthz_slo(traced):
    import http.client

    import serve as serve_cli
    from test_resil import FakeEngine

    from nerf_replication_tpu.serve import MicroBatcher

    engine = FakeEngine()
    batcher = MicroBatcher(engine)
    server = serve_cli.make_server(engine, batcher, port=0,
                                   slo_target_ms=100.0)
    port = server.server_address[1]
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        batcher.submit(_rays(8), 2.0, 6.0).result(timeout=5.0)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        assert "# TYPE serve_requests_total counter" in body
        assert 'serve_requests_total{status="ok",tier="full"} 1' in body
        assert "serve_stage_seconds_bucket" in body  # span-fed histogram
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["ok"] and health["slo"]["requests"] == 1
        assert health["slo"]["target_ms"] == 100.0
        conn.close()
    finally:
        server.shutdown()
        server.server_close()
        batcher.close()


# -- Chrome-trace export -----------------------------------------------------


def test_trace_view_chrome_roundtrip(tmp_path, telem):
    """Golden round-trip: spans under a fake clock -> JSONL -> Chrome
    trace JSON that loads, nests by time containment, and carries the
    thread-name metadata chrome://tracing groups tracks by."""
    clk = FakeClock(10.0)
    configure_tracing(enabled=True, clock=clk)
    spans = []
    get_tracer().add_sink(spans.append)
    trs = get_tracer()
    try:
        with trs.span("serve.request", parent=None):
            clk.advance(0.001)
            with trs.span("serve.dispatch", stage="dispatch"):
                clk.advance(0.002)
            clk.advance(0.001)
    finally:
        configure_tracing(enabled=False)
        reset_metrics()
    jsonl = tmp_path / "spans.jsonl"
    with open(jsonl, "w") as f:
        for s in spans:
            f.write(json.dumps({"kind": "span", **s}) + "\n")
        f.write("not json\n")  # exporter must tolerate torn lines
    tv = _load_script("trace_view")
    out = tmp_path / "trace.json"
    assert tv.main([str(jsonl), "--out", str(out)]) == 0
    doc = json.load(open(out))
    xs = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    assert set(xs) == {"serve.request", "serve.dispatch"}
    parent, child = xs["serve.request"], xs["serve.dispatch"]
    assert parent["ts"] == 0.0  # rebased to the earliest span
    assert child["dur"] == pytest.approx(2000.0)  # 2 ms in µs
    assert parent["ts"] <= child["ts"]
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + 1e-6
    assert child["args"]["parent_id"] == parent["args"]["span_id"]
    assert child["cat"] == "dispatch"
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert metas and all(e["name"] == "thread_name" for e in metas)
    # flight dumps are a second legal source for the same exporter
    rec = FlightRecorder(str(tmp_path), clock=FakeClock(1.0))
    for s in spans:
        rec.record(s)
    dump = rec.dump("sigterm")
    assert tv.load_spans(dump) == spans
    # filtering to an unknown trace id is a clean nonzero exit
    assert tv.main([str(jsonl), "--trace", "ffffffff",
                    "--out", str(out)]) == 1


# -- tlm_report: span section + queue-share diff gate ------------------------


def test_tlm_report_span_section_and_queue_share_gate(tmp_path):
    def write_run(path, queue_ms):
        rows = [{"kind": "run_meta", "run_id": "r", "t": 0.0}]
        for i in range(20):
            for stage, ms in (("queue", queue_ms), ("device", 30.0)):
                rows.append({
                    "kind": "span", "trace_id": f"{i:08x}",
                    "span_id": f"{i:08x}", "name": f"serve.{stage}",
                    "start_s": float(i), "dur_s": ms / 1e3, "stage": stage,
                })
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    tlm = _load_script("tlm_report")
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    write_run(a, queue_ms=3.0)   # ~9% of the stage p95 total
    write_run(b, queue_ms=20.0)  # 40%: waiting, not working
    sa = tlm.summarize(tlm.load_rows(a))
    sb = tlm.summarize(tlm.load_rows(b))
    assert sa["span_stages"]["queue"]["p50_ms"] == pytest.approx(3.0)
    assert sa["serve_queue_p95_share"] == pytest.approx(3 / 33, abs=1e-3)
    flags = tlm.diff(sa, sb, gate_pct=10.0)
    assert any("queue-wait p95 share" in f for f in flags)
    assert tlm.diff(sa, sa, gate_pct=10.0) == []  # self-diff is clean


# -- schema checker handles flight dumps -------------------------------------


def test_check_telemetry_schema_validates_flight_dumps(tmp_path):
    rec = FlightRecorder(str(tmp_path), clock=FakeClock(5.0))
    rec.record({"trace_id": "t0", "span_id": "00000001", "name": "req",
                "start_s": 0.0, "dur_s": 0.1})
    rec.note(point="fleet.load", fault="io_error")
    path = rec.dump("scene_error", detail="scene=a")
    chk = _load_script("check_telemetry_schema")
    assert chk.check_file(path) == []
    payload = json.load(open(path))
    del payload["reason"]
    payload["spans"].append({"name": 3})
    bad = tmp_path / "flight_bad.json"
    with open(bad, "w") as f:
        json.dump(payload, f)
    errors = chk.check_file(str(bad))
    assert any("reason" in e for e in errors)
    assert any("spans[1]" in e for e in errors)
