"""img_fit task end-to-end: the 2-D image-regression warm-up must train
through the SAME generic trainer/fit pipeline as NeRF (the reference ships
this task broken — missing loss module and dead imports; SURVEY.md §2.1)."""

import os

import jax
import numpy as np
import pytest

from nerf_replication_tpu.config import make_cfg
from nerf_replication_tpu.datasets.procedural import generate_scene

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def scene_root(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("scene_imgfit"))
    generate_scene(root, scene="procedural", H=24, W=24, n_train=3, n_test=1)
    return root


def imgfit_cfg(scene_root, tmp_path, extra=()):
    return make_cfg(
        os.path.join(ROOT, "configs", "img_fit", "lego_view0.yaml"),
        [
            "scene", "procedural",
            "train_dataset.data_root", str(scene_root),
            "test_dataset.data_root", str(scene_root),
            "test_dataset.input_ratio", "1.0",
            "task_arg.N_pixels", "256",
            "network.W", "32", "network.D", "2",
            "network.uv_encoder.freq", "4",
            "ep_iter", "50",
            "train.epoch", "4",
            "eval_ep", "4",
            "save_latest_ep", "100",
            "log_interval", "25",
            "result_dir", str(tmp_path / "result"),
            "trained_model_dir", str(tmp_path / "model"),
            "trained_config_dir", str(tmp_path / "config"),
            "record_dir", str(tmp_path / "record"),
            *extra,
        ],
    )


def test_img_fit_trains_and_evaluates(scene_root, tmp_path):
    from nerf_replication_tpu.train.trainer import fit

    cfg = imgfit_cfg(scene_root, tmp_path)
    logs = []
    state = fit(cfg, log=logs.append)
    assert int(state.step) == 200

    # the last validation PSNR must beat a flat-gray baseline on this image
    val_lines = [l for l in logs if l.startswith("val epoch")]
    assert val_lines, f"no validation ran; logs: {logs[-3:]}"
    psnr = float(val_lines[-1].split("psnr:")[1].split()[0])
    assert psnr > 10.0

    result_dir = cfg.result_dir
    assert os.path.exists(os.path.join(result_dir, "metrics.json"))
    assert os.path.exists(os.path.join(result_dir, "vis", "res.png"))


def test_img_fit_network_module_contract():
    """The network plugin exposes make_network + init_params and maps
    [..., 2] uv → [..., 3] rgb in (0, 1)."""
    from nerf_replication_tpu.config.node import ConfigNode
    from nerf_replication_tpu.models.img_fit.network import (
        init_params,
        make_network,
    )

    cfg = ConfigNode(
        {
            "network": {
                "W": 16,
                "D": 2,
                "uv_encoder": {"type": "frequency", "input_dim": 2, "freq": 3},
            }
        }
    )
    net = make_network(cfg)
    params = init_params(net, jax.random.PRNGKey(0))
    uv = jax.numpy.asarray(
        np.random.default_rng(0).uniform(0, 1, (5, 2)), jax.numpy.float32
    )
    rgb = net.apply(params, uv)
    assert rgb.shape == (5, 3)
    out = np.asarray(rgb)
    assert (out > 0).all() and (out < 1).all()
