"""Model tests: frequency encoding math, NeRF MLP shapes/params, factory."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nerf_replication_tpu.config import make_cfg
from nerf_replication_tpu.models import make_network
from nerf_replication_tpu.models.encoding import get_encoder
from nerf_replication_tpu.models.encoding.freq import frequency_encoder
from nerf_replication_tpu.models.nerf.network import init_params


def _lego_cfg(tmp_path):
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return make_cfg(os.path.join(root, "configs", "nerf", "lego.yaml"))


def test_frequency_encoder_dims_and_values():
    enc, out_dim = frequency_encoder(3, 10)
    assert out_dim == 3 * (1 + 2 * 10) == 63
    x = jnp.array([[0.5, -0.25, 1.0]])
    y = enc(x)
    assert y.shape == (1, 63)
    # identity part first
    np.testing.assert_allclose(y[0, :3], x[0], rtol=1e-6)
    # band 0: sin(x), cos(x)
    np.testing.assert_allclose(y[0, 3:6], np.sin(x[0]), rtol=1e-6)
    np.testing.assert_allclose(y[0, 6:9], np.cos(x[0]), rtol=1e-6)
    # band k uses frequency 2^k: last band sin(2^9 x)
    np.testing.assert_allclose(y[0, 3 + 9 * 6 : 6 + 9 * 6], np.sin(512 * x[0]), rtol=1e-5)


def test_frequency_encoder_dir_dims():
    enc, out_dim = frequency_encoder(3, 4)
    assert out_dim == 27
    assert enc(jnp.zeros((7, 3))).shape == (7, 27)


def test_get_encoder_dispatch(tmp_path):
    cfg = _lego_cfg(tmp_path)
    enc, dim = get_encoder(cfg.network.xyz_encoder)
    assert dim == 63
    enc_d, dim_d = get_encoder(cfg.network.dir_encoder)
    assert dim_d == 27
    bad = cfg.network.xyz_encoder.clone()
    bad.type = "not_a_real_encoder"
    with pytest.raises(NotImplementedError):
        get_encoder(bad)


def test_network_forward_shapes(tmp_path):
    cfg = _lego_cfg(tmp_path)
    net = make_network(cfg)
    params = init_params(net, jax.random.PRNGKey(0))
    pts = jnp.ones((8, 16, 3)) * 0.1
    dirs = jnp.ones((8, 3)) / np.sqrt(3)
    raw_c = net.apply(params, pts, dirs, model="coarse")
    raw_f = net.apply(params, pts, dirs, model="fine")
    assert raw_c.shape == (8, 16, 4)
    assert raw_f.shape == (8, 16, 4)
    # coarse and fine are independently initialized → different outputs
    assert not np.allclose(raw_c, raw_f)
    assert np.all(np.isfinite(raw_c))


def test_network_param_structure(tmp_path):
    cfg = _lego_cfg(tmp_path)
    net = make_network(cfg)
    params = init_params(net, jax.random.PRNGKey(0))["params"]
    assert set(params.keys()) == {"coarse", "fine"}
    coarse = params["coarse"]
    # 8 trunk layers + alpha + feature + views + rgb
    assert "pts_linear_0" in coarse and "pts_linear_7" in coarse
    assert coarse["pts_linear_0"]["kernel"].shape == (63, 256)
    # skip at layer 4 → layer 5 input is W + input_ch
    assert coarse["pts_linear_5"]["kernel"].shape == (256 + 63, 256)
    assert coarse["alpha_linear"]["kernel"].shape == (256, 1)
    assert coarse["views_linear_0"]["kernel"].shape == (256 + 27, 128)
    assert coarse["rgb_linear"]["kernel"].shape == (128, 3)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    # ~1.2M params for the pair (595k each)
    assert 1_000_000 < n_params < 1_400_000


def test_network_viewdir_dependence(tmp_path):
    cfg = _lego_cfg(tmp_path)
    net = make_network(cfg)
    params = init_params(net, jax.random.PRNGKey(1))
    pts = jnp.ones((4, 8, 3)) * 0.3
    d1 = jnp.tile(jnp.array([[1.0, 0, 0]]), (4, 1))
    d2 = jnp.tile(jnp.array([[0, 1.0, 0]]), (4, 1))
    r1 = net.apply(params, pts, d1, model="coarse")
    r2 = net.apply(params, pts, d2, model="coarse")
    # rgb depends on direction, sigma does not (viewdirs branch after alpha head)
    assert not np.allclose(r1[..., :3], r2[..., :3])
    np.testing.assert_allclose(r1[..., 3], r2[..., 3], rtol=1e-5)


def test_network_bfloat16_compute(tmp_path):
    cfg = _lego_cfg(tmp_path).clone()
    cfg.defrost()
    cfg.precision.compute_dtype = "bfloat16"
    net = make_network(cfg)
    params = init_params(net, jax.random.PRNGKey(0))
    # params stay float32
    assert params["params"]["coarse"]["pts_linear_0"]["kernel"].dtype == jnp.float32
    raw = net.apply(params, jnp.ones((2, 4, 3)), jnp.ones((2, 3)), model="coarse")
    assert raw.dtype == jnp.float32  # heads cast back to f32
    assert np.all(np.isfinite(raw))


def test_split_dense_equals_concat_dense():
    """The skip/view concat-split (SplitDense) must be numerically
    identical to Dense-over-concat with the SAME param tree (names,
    shapes, init values) — the [N, S, 319]/[N, S, 283] buffers it removes
    are the f3 roofline's top byte producers (PERF.md round 4)."""
    import flax.linen as nn
    from flax.traverse_util import flatten_dict

    from nerf_replication_tpu.models.nerf.network import NeRFMLP

    class ConcatMLP(nn.Module):
        D: int = 8
        W: int = 64
        input_ch: int = 63
        input_ch_views: int = 27
        skips: tuple = (4,)

        @nn.compact
        def __call__(self, embedded):
            dense = lambda f, n: nn.Dense(f, name=n)  # noqa: E731
            input_pts = embedded[..., : self.input_ch]
            input_views = embedded[..., self.input_ch:]
            h = input_pts
            for i in range(self.D):
                h = nn.relu(dense(self.W, f"pts_linear_{i}")(h))
                if i in self.skips:
                    h = jnp.concatenate([input_pts, h], -1)
            alpha = nn.Dense(1, name="alpha_linear")(h)
            feature = dense(self.W, "feature_linear")(h)
            h = jnp.concatenate([feature, input_views], -1)
            h = nn.relu(dense(self.W // 2, "views_linear_0")(h))
            rgb = nn.Dense(3, name="rgb_linear")(h)
            return jnp.concatenate([rgb, alpha], -1)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(128, 90)), jnp.float32)
    new = NeRFMLP(D=8, W=64, input_ch=63, input_ch_views=27, skips=(4,))
    old = ConcatMLP()
    p_new = new.init(jax.random.PRNGKey(0), x)
    p_old = old.init(jax.random.PRNGKey(0), x)
    fa = {"/".join(k): v for k, v in flatten_dict(p_new["params"]).items()}
    fb = {"/".join(k): v for k, v in flatten_dict(p_old["params"]).items()}
    assert set(fa) == set(fb)
    for k in fa:
        np.testing.assert_array_equal(
            np.asarray(fa[k]), np.asarray(fb[k]), err_msg=k
        )
    np.testing.assert_allclose(
        np.asarray(new.apply(p_old, x)), np.asarray(old.apply(p_old, x)),
        rtol=1e-5, atol=1e-5,
    )

    # no concatenate op with the skip width survives in the compiled fwd+bwd
    def loss(p):
        return jnp.sum(new.apply(p, x) ** 2)

    hlo = jax.jit(jax.grad(loss)).lower(p_old).compile().as_text()
    assert "f32[128,127]" not in hlo, "skip concat buffer still materializes"


def test_scan_trunk_matches_unrolled():
    """scan_trunk=True (stacked trunk params, lax.scan) must compute the
    same function as the unrolled trunk when the per-layer params are
    packed into the stack — the compile-time dedup must not change math."""
    from flax.traverse_util import flatten_dict, unflatten_dict

    from nerf_replication_tpu.models.nerf.network import NeRFMLP

    kwargs = dict(D=8, W=32, input_ch=21, input_ch_views=9, skips=(4,))
    unrolled = NeRFMLP(**kwargs)
    scanned = NeRFMLP(**kwargs, scan_trunk=True)

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(64, 30)), jnp.float32)
    p_u = unrolled.init(jax.random.PRNGKey(0), x)

    # pack pts_linear_{1..4} and {6..7} into the scan stacks
    flat = flatten_dict(p_u["params"], sep="/")
    packed = {}
    for start, length in ((1, 4), (6, 2)):
        packed[f"trunk_scan_{start}"] = jnp.stack(
            [flat[f"pts_linear_{i}/kernel"]
             for i in range(start, start + length)]
        )
        packed[f"trunk_scan_{start}_bias"] = jnp.stack(
            [flat[f"pts_linear_{i}/bias"]
             for i in range(start, start + length)]
        )
        for i in range(start, start + length):
            del flat[f"pts_linear_{i}/kernel"]
            del flat[f"pts_linear_{i}/bias"]
    flat.update(packed)
    p_s = {"params": unflatten_dict(flat, sep="/")}

    # the scanned module's own init produces exactly this tree structure
    p_init = scanned.init(jax.random.PRNGKey(0), x)
    assert jax.tree_util.tree_structure(p_init) == \
        jax.tree_util.tree_structure(p_s)

    o_u = np.asarray(unrolled.apply(p_u, x))
    o_s = np.asarray(scanned.apply(p_s, x))
    np.testing.assert_allclose(o_s, o_u, rtol=1e-5, atol=1e-5)

    # gradients agree too (the scan differentiates to a scan)
    g_u = jax.grad(lambda p: jnp.sum(unrolled.apply(p, x) ** 2))(p_u)
    g_s = jax.grad(lambda p: jnp.sum(scanned.apply(p, x) ** 2))(p_s)
    gu = flatten_dict(g_u["params"], sep="/")
    gs = flatten_dict(g_s["params"], sep="/")
    np.testing.assert_allclose(
        np.asarray(gs["trunk_scan_1"][2]),
        np.asarray(gu["pts_linear_3/kernel"]), rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(gs["alpha_linear/kernel"]),
        np.asarray(gu["alpha_linear/kernel"]), rtol=1e-4, atol=1e-5,
    )
