"""Occupancy-accelerated training (train/ngp.py — the instant-ngp speed
lever the reference lacks: its grid is baked once post-training and used
only at eval, occupancy_grid.py). The live-grid step must train, the grid
must actually carve out empty space, and eval must render through the march
with the live grid."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from test_train import tiny_cfg

from nerf_replication_tpu.datasets.blender import Dataset
from nerf_replication_tpu.datasets.procedural import generate_scene
from nerf_replication_tpu.models import make_network
from nerf_replication_tpu.train.ngp import NGPTrainState, make_ngp_trainer

NGP_EXTRA = (
    "train_dataset.H", "32", "train_dataset.W", "32",
    "test_dataset.H", "32", "test_dataset.W", "32",
    "task_arg.N_rays", "256",
    "task_arg.render_step_size", "0.08",
    "task_arg.max_march_samples", "24",
    "task_arg.march_chunk_size", "512",
    "task_arg.ngp_grid_res", "32",
    "task_arg.ngp_training", "true",
)


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("ngp_scene"))
    generate_scene(root, scene="procedural", H=32, W=32, n_train=8, n_test=2)
    cfg = tiny_cfg(root, NGP_EXTRA)
    net = make_network(cfg)
    return root, cfg, net


def test_ngp_trains_and_carves_occupancy(setup):
    root, cfg, net = setup
    trainer = make_ngp_trainer(cfg, net)
    state, _ = trainer.make_state(jax.random.PRNGKey(0))
    assert isinstance(state, NGPTrainState)
    # warm start: everything occupied ⇒ dense march with gradients everywhere
    assert float(jnp.mean(state.grid_ema > trainer.threshold)) == 1.0

    ds = Dataset(data_root=root, scene="procedural", split="train", H=32, W=32)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(1)

    losses, occs = [], []
    for i in range(600):
        state, stats = trainer.step(state, bank[0], bank[1], key)
        if i % 50 == 0 or i == 599:
            losses.append(float(stats["loss"]))
            occs.append(float(stats["occupancy"]))
    assert np.all(np.isfinite(losses))
    # learning: loss clearly descends
    assert losses[-1] < losses[0] * 0.5
    # the speed lever: the live grid has carved out real empty space
    assert occs[0] == 1.0 and occs[-1] < 0.9

    # eval through the march with the live grid
    tds = Dataset(data_root=root, scene="procedural", split="test", H=32, W=32)
    b = tds.image_batch(0)
    out = trainer.render_image(state, {"rays": b["rays"]})
    rgb = np.asarray(out["rgb_map_f"])
    assert rgb.shape == (32 * 32, 3) and np.isfinite(rgb).all()
    # trained output beats an untrained render on PSNR
    fresh, _ = trainer.make_state(jax.random.PRNGKey(2))
    rgb0 = np.asarray(trainer.render_image(fresh, {"rays": b["rays"]})["rgb_map_f"])
    gt = np.asarray(b["rgbs"])
    mse_t = float(np.mean((rgb - gt) ** 2))
    mse_0 = float(np.mean((rgb0 - gt) ** 2))
    assert mse_t < mse_0 * 0.5


def test_ngp_grid_update_is_densitydriven(setup):
    """Cells the network marks empty must decay below the threshold while
    cells over real content stay occupied (scatter-max vs decay race)."""
    root, cfg, net = setup
    trainer = make_ngp_trainer(cfg, net)
    state, _ = trainer.make_state(jax.random.PRNGKey(0))
    ds = Dataset(data_root=root, scene="procedural", split="train", H=32, W=32)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(1)
    for _ in range(600):
        state, _ = trainer.step(state, bank[0], bank[1], key)
    grid = np.asarray(state.grid_ema > trainer.threshold)
    # the procedural scene's content (sphere r=1.1 + box) fills well under
    # 100% of the [-1.5, 1.5]^3 bbox but is not empty either
    occ = grid.mean()
    assert 0.05 < occ < 0.9
    # the bbox center (inside the sphere) must remain occupied
    c = trainer.grid_res // 2
    assert grid[c - 1 : c + 1, c - 1 : c + 1, c - 1 : c + 1].any()


def test_fit_refuses_ngp_config(setup):
    """The epoch-loop entry must refuse an ngp_training config loudly
    instead of silently training the hierarchical path under it."""
    from nerf_replication_tpu.train.trainer import fit

    _, cfg, net = setup
    with pytest.raises(NotImplementedError, match="ngp_training"):
        fit(cfg, network=net, log=lambda *a, **k: None)
