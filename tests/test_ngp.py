"""Occupancy-accelerated training (train/ngp.py — the instant-ngp speed
lever the reference lacks: its grid is baked once post-training and used
only at eval, occupancy_grid.py). The live-grid step must train, the grid
must actually carve out empty space, and eval must render through the march
with the live grid."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from test_train import tiny_cfg

from nerf_replication_tpu.datasets.blender import Dataset
from nerf_replication_tpu.datasets.procedural import generate_scene
from nerf_replication_tpu.models import make_network
from nerf_replication_tpu.train.ngp import NGPTrainState, make_ngp_trainer

NGP_EXTRA = (
    "train_dataset.H", "32", "train_dataset.W", "32",
    "test_dataset.H", "32", "test_dataset.W", "32",
    "task_arg.N_rays", "256",
    "task_arg.render_step_size", "0.08",
    "task_arg.max_march_samples", "24",
    "task_arg.march_chunk_size", "512",
    "task_arg.ngp_grid_res", "32",
    "task_arg.ngp_training", "true",
)


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("ngp_scene"))
    generate_scene(root, scene="procedural", H=32, W=32, n_train=8, n_test=2)
    cfg = tiny_cfg(root, NGP_EXTRA)
    net = make_network(cfg)
    return root, cfg, net


@pytest.mark.slow  # multi-minute compile+train on CPU: keeps tier-1
# inside its 870 s budget (a timeout kill mid-run tears the shared XLA
# cache -- docs/operations.md); run with `-m slow` or no marker filter
def test_ngp_trains_and_carves_occupancy(setup):
    root, cfg, net = setup
    trainer = make_ngp_trainer(cfg, net)
    state, _ = trainer.make_state(jax.random.PRNGKey(0))
    assert isinstance(state, NGPTrainState)
    # warm start: everything occupied ⇒ dense march with gradients everywhere
    assert float(jnp.mean(state.grid_ema > trainer.threshold)) == 1.0

    ds = Dataset(data_root=root, scene="procedural", split="train", H=32, W=32)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(1)

    losses, occs = [], []
    for i in range(600):
        state, stats = trainer.step(state, bank[0], bank[1], key)
        if i % 50 == 0 or i == 599:
            losses.append(float(stats["loss"]))
            occs.append(float(stats["occupancy"]))
    assert np.all(np.isfinite(losses))
    # learning: loss clearly descends
    assert losses[-1] < losses[0] * 0.5
    # the speed lever: the live grid has carved out real empty space
    assert occs[0] == 1.0 and occs[-1] < 0.9

    # eval through the march with the live grid
    tds = Dataset(data_root=root, scene="procedural", split="test", H=32, W=32)
    b = tds.image_batch(0)
    out = trainer.render_image(state, {"rays": b["rays"]})
    rgb = np.asarray(out["rgb_map_f"])
    assert rgb.shape == (32 * 32, 3) and np.isfinite(rgb).all()
    # trained output beats an untrained render on PSNR
    fresh, _ = trainer.make_state(jax.random.PRNGKey(2))
    rgb0 = np.asarray(trainer.render_image(fresh, {"rays": b["rays"]})["rgb_map_f"])
    gt = np.asarray(b["rgbs"])
    mse_t = float(np.mean((rgb - gt) ** 2))
    mse_0 = float(np.mean((rgb0 - gt) ** 2))
    assert mse_t < mse_0 * 0.5


def test_ngp_eval_cap_escalates_on_overflow(setup):
    """A dense-phase grid overflowing the packed eval stream cap must NOT
    silently truncate (understated PSNR): render_image doubles the cap,
    recompiles, and re-renders; the raised cap persists on the trainer."""
    from test_train import tiny_cfg

    root, _, _ = setup
    cfg = tiny_cfg(root, NGP_EXTRA + (
        "task_arg.ngp_packed_march", "true",
        "task_arg.ngp_packed_cap_avg", "2",
        "task_arg.ngp_packed_cap_avg_eval", "2",
    ))
    net = make_network(cfg)
    trainer = make_ngp_trainer(cfg, net)
    state, _ = trainer.make_state(jax.random.PRNGKey(0))
    # fresh state: fully-dense grid, cap_avg 2 ≪ samples/ray ⇒ overflow
    tds = Dataset(data_root=root, scene="procedural", split="test",
                  H=32, W=32)
    b = tds.image_batch(0)
    out = trainer.render_image(state, {"rays": b["rays"]})
    assert np.isfinite(np.asarray(out["rgb_map_f"])).all()
    assert trainer.packed_cap_avg_eval > 2  # escalated at least once


@pytest.mark.slow  # multi-minute compile+train on CPU: keeps tier-1
# inside its 870 s budget (a timeout kill mid-run tears the shared XLA
# cache -- docs/operations.md); run with `-m slow` or no marker filter
def test_ngp_grid_update_is_densitydriven(setup):
    """Cells the network marks empty must decay below the threshold while
    cells over real content stay occupied (scatter-max vs decay race)."""
    root, cfg, net = setup
    trainer = make_ngp_trainer(cfg, net)
    state, _ = trainer.make_state(jax.random.PRNGKey(0))
    ds = Dataset(data_root=root, scene="procedural", split="train", H=32, W=32)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(1)
    for _ in range(600):
        state, _ = trainer.step(state, bank[0], bank[1], key)
    grid = np.asarray(state.grid_ema > trainer.threshold)
    # the procedural scene's content (sphere r=1.1 + box) fills well under
    # 100% of the [-1.5, 1.5]^3 bbox but is not empty either
    occ = grid.mean()
    assert 0.05 < occ < 0.9
    # the bbox center (inside the sphere) must remain occupied
    c = trainer.grid_res // 2
    assert grid[c - 1 : c + 1, c - 1 : c + 1, c - 1 : c + 1].any()


@pytest.mark.slow  # multi-minute compile+train on CPU: keeps tier-1
# inside its 870 s budget (a timeout kill mid-run tears the shared XLA
# cache -- docs/operations.md); run with `-m slow` or no marker filter
def test_ngp_carves_fast_from_sampled_densities(setup):
    """VERDICT r3 #5: the round-4 warmup (ray-sampled scatter-max + low
    warm factor) must carve occupancy while PSNR rises and the K-budget
    truncation diagnostic falls — round 3's random-cell-only EMA sat at
    occupancy 1.0 forever. At this test's scale (256 rays/step, 16x less
    signal than the chip's 4096) the measured trajectory crosses 0.478 at
    step 1000 (probe, round 4); the chip A/B pins the <0.5-in-500 form."""
    root, cfg, net = setup
    trainer = make_ngp_trainer(cfg, net)
    assert trainer.warm_factor <= 2.0
    state, _ = trainer.make_state(jax.random.PRNGKey(0))
    ds = Dataset(data_root=root, scene="procedural", split="train", H=32, W=32)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(1)
    psnr0 = occ_mid = None
    for i in range(1000):
        state, stats = trainer.step(state, bank[0], bank[1], key)
        if i == 0:
            psnr0 = float(stats["psnr"])
            # warmup phase: stratified sampling cannot truncate
            assert float(stats["truncated_frac"]) == 0.0
        if i == 599:
            occ_mid = float(stats["occupancy"])
    occ = float(stats["occupancy"])
    # with the bake-aligned threshold (σ from occupancy_grid_threshold,
    # round 4) the grid carves DEEP by mid-run and then fluctuates at the
    # floor as the network refines — assert the deep carve, not
    # monotonicity at the floor (chip A/B: 1.0 → 0.047)
    assert occ_mid < 0.3, occ_mid
    assert occ < 0.3, occ
    assert float(stats["psnr"]) > psnr0 + 3.0


def test_ngp_multi_step_burst_matches_single_steps(setup):
    """A K-step scan burst must land on the same state as K single calls
    (same key threading via state.step inside the scan).

    Retry discipline (PR 3 triage, docs/operations.md): on this host
    (XLA:CPU, jax 0.4.37) donated step executables intermittently
    corrupted the step scalar — garbage ints (1073528057) or a lost
    increment, ~1/5 runs, REPRODUCED WITH A VIRGIN compilation cache.
    PR 5 root-caused it: XLA:CPU's input-output aliasing under the
    forced-device-count test topology frees donated buffers while aliased
    outputs still reference them, so donation is now gated off on the cpu
    backend entirely (utils/platform.py donation_argnums). The retry
    stays as a cheap backstop on the same corruption signature (insane
    step counters); the burst-vs-single numerics assertions — the point
    of the test — are never retried around."""
    root, cfg, net = setup
    ds = Dataset(data_root=root, scene="procedural", split="train", H=32, W=32)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(1)

    for attempt in range(3):
        trainer_a = make_ngp_trainer(cfg, net)
        trainer_b = make_ngp_trainer(cfg, net)
        sa, _ = trainer_a.make_state(jax.random.PRNGKey(0))
        for _ in range(4):
            sa, stats_a = trainer_a.step(sa, bank[0], bank[1], key)

        sb, _ = trainer_b.make_state(jax.random.PRNGKey(0))
        sb, stats_b = trainer_b.multi_step(sb, bank[0], bank[1], key,
                                           k_steps=4)
        if int(sa.step) == int(sb.step) == 4:
            break
        print(f"attempt {attempt}: corrupted step counters "
              f"(a={int(sa.step)}, b={int(sb.step)}), retrying")
    else:
        raise AssertionError(
            f"step counters corrupted on 3 consecutive attempts "
            f"(a={int(sa.step)}, b={int(sb.step)})"
        )

    np.testing.assert_allclose(
        np.asarray(sa.grid_ema), np.asarray(sb.grid_ema), rtol=1e-5,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        float(stats_a["loss"]), float(stats_b["loss"]), rtol=1e-4
    )


def test_ngp_occupancy_resyncs_past_warmup_max(setup):
    """Past ngp_warmup_max the per-burst occupancy sync is throttled to
    every ``ngp_occ_resync_bursts`` bursts — NOT skipped forever (round-4
    advisor: a frozen _last_occ could never re-engage warm mode if the
    grid re-densified)."""
    root, cfg, net = setup
    extra = (
        "task_arg.ngp_warmup_steps", "1",
        "task_arg.ngp_warmup_max", "2",
        "task_arg.ngp_warmup_exit_occ", "1.1",  # never blocks the exit
        "task_arg.ngp_occ_resync_bursts", "2",
    )
    cfg2 = tiny_cfg(root, NGP_EXTRA + extra)
    trainer = make_ngp_trainer(cfg2, net)
    ds = Dataset(data_root=root, scene="procedural", split="train", H=32, W=32)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(1)

    state, _ = trainer.make_state(jax.random.PRNGKey(0))
    # burn past warmup_max
    for _ in range(3):
        state, _ = trainer.multi_step(state, bank[0], bank[1], key, 1)
    assert trainer._host_step >= trainer.warmup_max

    # un-synced burst: _last_occ must NOT move on an off-cadence burst...
    trainer._bursts = 0  # next burst -> _bursts 1 (odd: no sync)
    trainer._last_occ = -123.0
    state, _ = trainer.multi_step(state, bank[0], bank[1], key, 1)
    assert trainer._last_occ == -123.0
    # ...and MUST re-sync on the cadence burst (_bursts 2)
    state, stats = trainer.multi_step(state, bank[0], bank[1], key, 1)
    assert trainer._last_occ == pytest.approx(float(stats["occupancy"]))

    # a re-densified grid RE-ENGAGES warm mode (the resync exists so this
    # can happen late in training), bounded by cumulative warm steps
    assert trainer._warm_steps_total < trainer.warmup_max
    trainer._last_occ = 2.0  # "grid re-densified"
    state, _ = trainer.multi_step(state, bank[0], bank[1], key, 1)
    assert trainer.last_burst_warm
    # cumulative cap: once warm steps reach warmup_max, warm cannot
    # re-engage no matter how dense the grid reads
    trainer._warm_steps_total = trainer.warmup_max
    trainer._last_occ = 2.0
    state, _ = trainer.multi_step(state, bank[0], bank[1], key, 1)
    assert not trainer.last_burst_warm

    # ngp_occ_resync_bursts = 0 disables the resync without crashing
    trainer.occ_resync_bursts = 0
    trainer._last_occ = -77.0
    trainer._bursts = 0
    state, _ = trainer.multi_step(state, bank[0], bank[1], key, 1)
    assert trainer._last_occ == -77.0


def test_fit_ngp_trains_over_the_mesh(setup, tmp_path):
    """With 8 devices visible, fit_ngp builds the DP mesh: per-shard ray
    sampling, pmean'd grads, pmax-merged live grid — and the epoch loop
    still checkpoints and validates."""
    from nerf_replication_tpu.train.ngp import fit_ngp

    root, _, _ = setup
    cfg = tiny_cfg(
        root,
        NGP_EXTRA + (
            "ep_iter", "6",
            "train.epoch", "1",
            "eval_ep", "1",
            "save_ep", "100",
            "save_latest_ep", "1",
            "log_interval", "3",
            "result_dir", str(tmp_path / "result"),
            "trained_model_dir", str(tmp_path / "model"),
            "trained_config_dir", str(tmp_path / "config"),
            "record_dir", str(tmp_path / "record"),
        ),
    )
    logs = []
    state = fit_ngp(cfg, log=logs.append)
    assert any(str(l).startswith("ngp training over mesh") for l in logs)
    assert int(state.step) == 6
    grid = np.asarray(state.grid_ema)
    assert np.all(np.isfinite(grid))
    leaf = np.asarray(jax.tree_util.tree_leaves(state.params)[0])
    assert np.all(np.isfinite(leaf))
    assert any("latest" in n for n in os.listdir(cfg.trained_model_dir))

    # TP is genuinely unsupported — still refused loudly
    with pytest.raises(NotImplementedError, match="model_axis"):
        fit_ngp(
            tiny_cfg(root, NGP_EXTRA + ("parallel.model_axis", "2")),
            log=lambda *a, **k: None,
        )


def test_fit_trains_ngp_config_end_to_end(setup, tmp_path):
    """train.py's entry now routes ngp_training through fit_ngp: epoch
    loop, checkpoint, live-grid validation (VERDICT r3 #5 wiring)."""
    from nerf_replication_tpu.train.trainer import fit

    root, _, _ = setup
    cfg = tiny_cfg(
        root,
        NGP_EXTRA + (
            "parallel.data_axis", "1",
            "ep_iter", "30",
            "train.epoch", "2",
            "eval_ep", "2",
            "save_ep", "100",
            "save_latest_ep", "2",
            "log_interval", "10",
            "task_arg.scan_steps", "5",
            "result_dir", str(tmp_path / "result"),
            "trained_model_dir", str(tmp_path / "model"),
            "trained_config_dir", str(tmp_path / "config"),
            "record_dir", str(tmp_path / "record"),
        ),
    )
    logs = []
    state = fit(cfg, log=logs.append)
    assert isinstance(state, NGPTrainState)
    assert int(state.step) == 60
    assert any(str(l).startswith("ngp val") for l in logs)
    assert any("latest" in n for n in os.listdir(cfg.trained_model_dir))
    # resume restores the grid alongside params
    state2 = fit(cfg, log=lambda *a, **k: None)
    assert int(state2.step) == 60  # epochs exhausted; nothing retrains


def test_ngp_warm_start_resume_bitwise_parity(setup, tmp_path):
    """Kill/resume must land bitwise on the uninterrupted trajectory: the
    grid EMA + optimizer state ride the checkpoint bundle and the phase
    sidecar re-enters the carved phase directly — a resumed run must not
    replay grid warm-up (the round-5 warmup tax) or fork numerically."""
    from nerf_replication_tpu.train.checkpoint import (
        load_model,
        load_phase_state,
        save_model,
    )

    root, _, _ = setup
    # warmup_max == warmup_steps == 2: burst 1 (k=2) is the whole warm
    # phase, burst 2 runs carved — the phase switch sits inside the run
    cfg = tiny_cfg(root, NGP_EXTRA + (
        "task_arg.ngp_warmup_steps", "2",
        "task_arg.ngp_warmup_max", "2",
    ))
    net = make_network(cfg)
    ds = Dataset(data_root=root, scene="procedural", split="train",
                 H=32, W=32)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(1)

    tr_a = make_ngp_trainer(cfg, net)
    sa, _ = tr_a.make_state(jax.random.PRNGKey(0))
    sa, _ = tr_a.multi_step(sa, bank[0], bank[1], key, k_steps=2)
    assert tr_a.last_burst_warm
    sa, _ = tr_a.multi_step(sa, bank[0], bank[1], key, k_steps=2)
    assert not tr_a.last_burst_warm  # carved phase reached
    # full-state sync before checkpointing and the bitwise reads below:
    # bursts dispatch async (and donate their input state on accelerator
    # backends — utils/platform.py donation_argnums)
    jax.block_until_ready(sa)

    model_dir = str(tmp_path / "ckpt")
    save_model(model_dir, sa, 0, None, latest=True,
               phase_state=tr_a.phase_state())

    tr_b = make_ngp_trainer(cfg, net)
    template, _ = tr_b.make_state(jax.random.PRNGKey(3))
    sb, begin_epoch, _ = load_model(model_dir, template)
    assert begin_epoch == 1
    phase = load_phase_state(model_dir)
    assert phase is not None
    assert tr_b.restore_phase(phase, expect_step=int(sb.step))
    assert tr_b.phase_state() == phase  # counters round-trip the sidecar

    # the full bundle (params, optimizer moments, grid EMA) round-trips
    # bitwise through the checkpoint — compared BEFORE the continuation
    # bursts below consume (on accelerators: donate) both input states
    for a, b in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    # uninterrupted continuation vs resumed continuation; sync each burst
    # (state AND stats) before reading — np.asarray on CPU is a zero-copy
    # view of the device buffer
    sa2, stats_a = tr_a.multi_step(sa, bank[0], bank[1], key, k_steps=2)
    jax.block_until_ready((sa2, stats_a))
    sb2, stats_b = tr_b.multi_step(sb, bank[0], bank[1], key, k_steps=2)
    jax.block_until_ready((sb2, stats_b))
    # resume re-enters the carved phase directly: no warm-up replay
    assert not tr_b.last_burst_warm
    for a, b in zip(jax.tree.leaves(sa2), jax.tree.leaves(sb2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert float(stats_a["loss"]) == float(stats_b["loss"])


def test_ngp_resume_rejects_torn_phase_sidecar(setup, tmp_path):
    """A phase sidecar whose host_step disagrees with the restored bundle
    (torn save pair) must be rejected — the occupancy heuristic takes
    over instead of pinning the trainer to a stale phase."""
    root, cfg, net = setup
    trainer = make_ngp_trainer(cfg, net)
    assert not trainer.restore_phase(None)
    assert not trainer.restore_phase({}, expect_step=0)
    good = {"host_step": 4, "last_occ": 0.5, "warm_steps_total": 2,
            "bursts": 2, "trunc_warned": False}
    assert not trainer.restore_phase(good, expect_step=8)  # mismatch
    assert trainer.restore_phase(good, expect_step=4)
    assert trainer._host_step == 4 and trainer._warm_steps_total == 2
