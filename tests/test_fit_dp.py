"""fit() drives the mesh DP step when multiple devices are visible.

VERDICT r3 #2: the shard_map DP builder was a tested library that no
production entry point called. These tests pin the integration: on the
8-device CPU emulation, ``train.py``'s `fit()` path must build the mesh,
shard the bank, and train THROUGH `build_dp_step` end-to-end — epoch loop,
precrop pool, scan bursts, checkpointing, validation. Parity seat:
reference train.py:116-120 + trainer.py:17-22 (distribution is on by
default in the entry point, not a separate driver).
"""

import os

import jax
import numpy as np
import pytest

from nerf_replication_tpu.config import make_cfg
from nerf_replication_tpu.datasets.procedural import generate_scene

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def scene_root(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("scene_dpfit"))
    generate_scene(root, scene="procedural", H=16, W=16, n_train=6, n_test=2)
    return root


def dp_cfg(scene_root, tmp_path, extra=()):
    return make_cfg(
        os.path.join(ROOT, "configs", "nerf", "lego.yaml"),
        [
            "scene", "procedural",
            "train_dataset.data_root", str(scene_root),
            "test_dataset.data_root", str(scene_root),
            "train_dataset.H", "16", "train_dataset.W", "16",
            "test_dataset.H", "16", "test_dataset.W", "16",
            "task_arg.N_rays", "128",
            "task_arg.N_samples", "16",
            "task_arg.N_importance", "16",
            "task_arg.chunk_size", "256",
            "task_arg.precrop_iters", "0",
            "network.nerf.W", "32",
            "network.nerf.D", "2",
            "network.nerf.skips", "[1]",
            "network.xyz_encoder.freq", "4",
            "network.dir_encoder.freq", "2",
            "ep_iter", "4",
            "train.epoch", "2",
            "eval_ep", "2",
            "save_ep", "100",
            "save_latest_ep", "2",
            "log_interval", "2",
            "result_dir", str(tmp_path / "result"),
            "trained_model_dir", str(tmp_path / "model"),
            "trained_config_dir", str(tmp_path / "config"),
            "record_dir", str(tmp_path / "record"),
            *extra,
        ],
    )


def test_fit_trains_through_dp_step(scene_root, tmp_path, monkeypatch):
    """End-to-end: fit() on 8 virtual devices goes through build_dp_step
    (counted), finishes the epoch loop, checkpoints, and validates."""
    import nerf_replication_tpu.parallel.step as pstep
    from nerf_replication_tpu.train.trainer import fit

    assert jax.device_count() == 8, "conftest must pin the 8-device CPU mesh"

    calls = []
    orig = pstep.build_dp_step

    def counting(*args, **kwargs):
        calls.append(kwargs.get("k_steps", 1))
        return orig(*args, **kwargs)

    monkeypatch.setattr(pstep, "build_dp_step", counting)

    cfg = dp_cfg(scene_root, tmp_path)
    logs = []
    state = fit(cfg, log=logs.append)

    assert calls, "fit() never built the mesh DP step"
    assert int(state.step) == 8  # 2 epochs x ep_iter 4
    assert any(l.startswith("training over mesh") for l in logs)

    # replicated state came back finite after pmean'd grads
    leaf = np.asarray(jax.tree_util.tree_leaves(state.params)[0])
    assert np.all(np.isfinite(leaf))

    # checkpoint (latest) written through the DP path
    assert os.path.isdir(cfg.trained_model_dir)
    assert any("latest" in n for n in os.listdir(cfg.trained_model_dir))

    # validation rendered and reported
    assert any(l.startswith("val epoch") for l in logs)


def test_fit_dp_precrop_and_bursts(scene_root, tmp_path, monkeypatch):
    """Precrop pool (sharded local indices) and scan-burst variants both
    run under the mesh, and the pooled variant retires after precrop."""
    import nerf_replication_tpu.parallel.step as pstep
    from nerf_replication_tpu.train.trainer import fit

    built = []
    orig = pstep.build_dp_step

    def recording(*args, **kwargs):
        built.append((kwargs.get("k_steps", 1), kwargs.get("with_pool", False)))
        return orig(*args, **kwargs)

    monkeypatch.setattr(pstep, "build_dp_step", recording)

    cfg = dp_cfg(
        scene_root, tmp_path,
        ["task_arg.precrop_iters", "2",
         "task_arg.precrop_frac", "0.5",
         "task_arg.scan_steps", "2",
         "eval_ep", "100", "save_latest_ep", "100"],
    )
    state = fit(cfg, log=lambda *a, **k: None)
    assert int(state.step) == 8
    assert (1, True) in built, "precrop steps never used the pooled DP step"
    assert any(k == 2 for k, _ in built), "bursts never used the DP scan step"


def test_fit_tp_routes_to_gspmd(scene_root, tmp_path, monkeypatch):
    """parallel.model_axis: 2 must engage the GSPMD dp×tp builder (params
    column-sharded), not the pure-DP shard_map body that would replicate
    the model axis."""
    import nerf_replication_tpu.parallel.step as pstep
    from nerf_replication_tpu.train.trainer import fit

    gspmd_calls = []
    orig = pstep.build_gspmd_step

    def counting(*args, **kwargs):
        gspmd_calls.append(kwargs.get("k_steps", 1))
        return orig(*args, **kwargs)

    monkeypatch.setattr(pstep, "build_gspmd_step", counting)

    def boom(*args, **kwargs):  # pragma: no cover - fails the test if hit
        raise AssertionError("pure-DP step built despite model_axis: 2")

    monkeypatch.setattr(pstep, "build_dp_step", boom)

    cfg = dp_cfg(
        scene_root, tmp_path,
        ["parallel.model_axis", "2", "eval_ep", "100",
         "save_ep", "100", "save_latest_ep", "100", "train.epoch", "1"],
    )
    state = fit(cfg, log=lambda *a, **k: None)
    assert gspmd_calls, "fit() never built the GSPMD step"
    assert int(state.step) == 4
    leaf = np.asarray(jax.tree_util.tree_leaves(state.params)[0])
    assert np.all(np.isfinite(leaf))


def test_fit_single_device_opt_out(scene_root, tmp_path, monkeypatch):
    """parallel.data_axis: 1 keeps the single-chip step even with 8
    devices visible."""
    import nerf_replication_tpu.parallel.step as pstep
    from nerf_replication_tpu.train.trainer import fit

    def boom(*args, **kwargs):  # pragma: no cover - fails the test if hit
        raise AssertionError("DP step built despite parallel.data_axis: 1")

    monkeypatch.setattr(pstep, "build_dp_step", boom)

    cfg = dp_cfg(
        scene_root, tmp_path,
        ["parallel.data_axis", "1", "eval_ep", "100",
         "save_latest_ep", "100", "train.epoch", "1"],
    )
    state = fit(cfg, log=lambda *a, **k: None)
    assert int(state.step) == 4
