"""Scale-out subsystem (nerf_replication_tpu/scale): the supervisor's
decision table on synthetic metrics (scale-out on missed SLO, scale-in on
sustained attainment, hysteresis band, cooldowns, dead-replica repair),
the router's scene-affinity + least-loaded + failover behavior, the
drain-before-retire zero-failure contract on a REAL MicroBatcher, and
mesh-sharded dispatch bitwise parity on a forced size-1 mesh. All CPU,
fake clocks/replicas — no processes, no real chips."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

from test_train import tiny_cfg

from nerf_replication_tpu.datasets.procedural import generate_scene
from nerf_replication_tpu.obs import validate_row
from nerf_replication_tpu.scale import (
    InProcessReplica,
    MeshDispatchError,
    NoReplicaAvailableError,
    ReplicaState,
    ReplicaUnavailableError,
    Router,
    ScaleOptions,
    Supervisor,
    mesh_from_scale_cfg,
    validate_mesh_buckets,
)

NEAR, FAR = 2.0, 6.0


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeReplica:
    """The replica surface with scripted load/scenes/liveness."""

    def __init__(self, rid, load=0, scenes=()):
        self.replica_id = str(rid)
        self.state = ReplicaState.READY
        self._load = load
        self.scenes = list(scenes)
        self.beat_ok = True
        self.fail_submit = False  # accepting but dies mid-submit
        self.submits = []
        self.drains = 0
        self.drain_failures = 0

    def accepting(self):
        return self.state == ReplicaState.READY

    def load(self):
        return self._load

    def heartbeat(self):
        if not self.beat_ok:
            raise RuntimeError("beat down")
        return {"replica": self.replica_id, "state": self.state, "ok": True,
                "load": self._load, "scenes": self.scenes,
                "warm_source": "disk", "total_compiles": 0}

    def submit(self, rays, near, far, scene=None, tenant=None):
        if not self.accepting() or self.fail_submit:
            raise ReplicaUnavailableError(f"{self.replica_id} {self.state}")
        self.submits.append(scene)
        return f"future:{self.replica_id}"

    def drain(self, timeout_s=60.0):
        self.state = ReplicaState.RETIRED
        self.drains += 1
        return self.drain_failures

    def kill(self):
        self.state = ReplicaState.DEAD


def _router(clock, *replicas, timeout_s=10.0):
    r = Router(heartbeat_timeout_s=timeout_s, clock=clock)
    for rep in replicas:
        r.register(rep)
    r.sweep()  # populate beats (affinity reads the last beat)
    return r


# -- options -----------------------------------------------------------------


def test_scale_options_from_cfg_reads_the_scale_block(tmp_path):
    root = str(tmp_path / "scene")
    generate_scene(root, scene="procedural", H=16, W=16, n_train=2, n_test=1)
    cfg = tiny_cfg(root, ["scale.max_replicas", "7",
                          "scale.out_below", "0.8",
                          "scale.mesh", "auto"])
    opt = ScaleOptions.from_cfg(cfg)
    assert opt.max_replicas == 7
    assert opt.out_below == pytest.approx(0.8)
    assert opt.mesh == "auto"
    # untouched knobs keep the documented defaults
    assert opt.in_above == pytest.approx(0.98)
    assert opt.out_windows == 2


# -- router: affinity, load, failover, heartbeats ----------------------------


def test_router_prefers_scene_affinity_over_load():
    clock = FakeClock()
    busy_with_scene = FakeReplica("r0", load=9, scenes=["lego"])
    idle_without = FakeReplica("r1", load=0)
    router = _router(clock, busy_with_scene, idle_without)
    assert router.pick("lego") is busy_with_scene
    # no affinity anywhere -> least-loaded wins
    assert router.pick("ship") is idle_without
    assert router.pick(None) is idle_without


def test_router_least_loaded_then_id_order():
    clock = FakeClock()
    a = FakeReplica("a", load=3)
    b = FakeReplica("b", load=1)
    c = FakeReplica("c", load=1)
    router = _router(clock, a, b, c)
    assert router.pick() is b  # load ties break on id


def test_router_failover_skips_dying_replica_and_delivers():
    clock = FakeClock()
    first = FakeReplica("r0", load=0)
    second = FakeReplica("r1", load=5)
    router = _router(clock, first, second)
    first.fail_submit = True  # dies between the pick and the submit
    fut = router.submit(np.zeros((4, 6), np.float32), NEAR, FAR)
    assert fut == "future:r1"
    assert second.submits == [None]
    assert router.n_failovers == 1
    assert first.state == ReplicaState.DEAD  # marked at the failed submit


def test_router_raises_when_every_replica_is_gone():
    clock = FakeClock()
    only = FakeReplica("r0")
    router = _router(clock, only)
    only.kill()
    with pytest.raises(NoReplicaAvailableError):
        router.submit(np.zeros((4, 6), np.float32), NEAR, FAR)


def test_router_heartbeat_timeout_has_hysteresis():
    clock = FakeClock()
    rep = FakeReplica("r0")
    router = _router(clock, rep, timeout_s=10.0)
    rep.beat_ok = False
    clock.advance(5.0)
    out = router.sweep()  # inside the window: transient, NOT dead
    assert out["dead"] == []
    assert rep.state == ReplicaState.READY
    clock.advance(6.0)
    out = router.sweep()  # 11 s of failed beats: dead
    assert out["dead"] == ["r0"]
    assert rep.state == ReplicaState.DEAD


def test_router_does_not_mark_draining_replica_dead_on_submit():
    clock = FakeClock()
    draining = FakeReplica("r0")
    other = FakeReplica("r1")
    router = _router(clock, draining, other)
    draining.state = ReplicaState.DRAINING
    router.submit(np.zeros((4, 6), np.float32), NEAR, FAR)
    assert draining.state == ReplicaState.DRAINING  # retirement, not death


# -- supervisor: the decision table ------------------------------------------


def _supervisor(clock, n_start=1, **overrides):
    opts = ScaleOptions(**{**dict(
        min_replicas=1, max_replicas=4, out_below=0.90, in_above=0.98,
        deny_above=0.05, out_windows=2, in_windows=3,
        cooldown_out_s=30.0, cooldown_in_s=60.0,
    ), **overrides})
    router = Router(heartbeat_timeout_s=10.0, clock=clock)
    spawned = []

    def spawn_fn(i):
        r = FakeReplica(f"s{i}")
        spawned.append(r)
        return r

    sup = Supervisor(router, spawn_fn, options=opts, clock=clock)
    for _ in range(n_start):
        sup._spawn("test_boot")
    return sup, router, spawned


def test_scale_out_needs_consecutive_miss_windows():
    clock = FakeClock()
    sup, router, spawned = _supervisor(clock)
    assert sup.step(0.5) == "hold"          # first miss: streak 1 of 2
    assert router.n_ready() == 1
    clock.advance(10.0)
    assert sup.step(0.5) == "out"           # second consecutive miss
    assert router.n_ready() == 2
    assert sup.decisions[-1]["reason"] == "slo_miss"


def test_single_miss_between_good_windows_never_scales():
    clock = FakeClock()
    sup, router, _ = _supervisor(clock)
    for att in (0.99, 0.5, 0.99, 0.5, 0.99):
        sup.step(att)
        clock.advance(10.0)
    assert router.n_ready() == 1  # streak never reached out_windows


def test_scale_out_cooldown_blocks_back_to_back_spawns():
    clock = FakeClock()
    sup, router, _ = _supervisor(clock, cooldown_out_s=100.0)
    sup.step(0.5)
    assert sup.step(0.5) == "out"
    assert router.n_ready() == 2
    # still missing, streak re-fills, but the cooldown gate holds
    assert sup.step(0.5) == "hold"
    assert sup.step(0.5) == "hold"
    assert router.n_ready() == 2
    clock.advance(101.0)
    assert sup.step(0.5) == "out"
    assert router.n_ready() == 3


def test_scale_out_respects_max_replicas():
    clock = FakeClock()
    sup, router, _ = _supervisor(clock, max_replicas=2, cooldown_out_s=0.0)
    sup.step(0.5)
    assert sup.step(0.5) == "out"
    assert router.n_ready() == 2
    sup.step(0.5)
    assert sup.step(0.5) == "hold"  # at max: no spawn
    assert router.n_ready() == 2


def test_deny_rate_alone_triggers_scale_out():
    clock = FakeClock()
    sup, router, _ = _supervisor(clock)
    sup.step(0.99, deny_rate=0.2)  # attainment fine, tenants denied
    assert sup.step(0.99, deny_rate=0.2) == "out"
    assert sup.decisions[-1]["reason"] == "deny_rate"


def test_scale_in_on_sustained_attainment_drains_least_loaded():
    clock = FakeClock()
    sup, router, spawned = _supervisor(clock, n_start=2, in_windows=3,
                                       cooldown_in_s=0.0)
    spawned[0]._load = 4
    spawned[1]._load = 0
    sup.step(0.99)
    sup.step(0.99)
    assert sup.step(0.99) == "in"
    assert spawned[1].state == ReplicaState.RETIRED  # least-loaded victim
    assert spawned[0].state == ReplicaState.READY
    assert spawned[1].drains == 1
    assert sup.drain_failures == 0
    assert router.n_ready() == 1


def test_scale_in_respects_min_replicas():
    clock = FakeClock()
    sup, router, _ = _supervisor(clock, n_start=1, in_windows=2,
                                 cooldown_in_s=0.0)
    sup.step(0.99)
    assert sup.step(0.99) == "hold"  # at min: the fleet never empties
    assert router.n_ready() == 1


def test_idle_fleet_counts_toward_scale_in():
    clock = FakeClock()
    sup, router, _ = _supervisor(clock, n_start=2, in_windows=2,
                                 cooldown_in_s=0.0)
    sup.step(None)  # no traffic at all
    assert sup.step(None) == "in"
    assert router.n_ready() == 1


def test_hysteresis_band_resets_both_streaks():
    clock = FakeClock()
    sup, router, _ = _supervisor(clock, n_start=2, in_windows=2,
                                 cooldown_in_s=0.0, cooldown_out_s=0.0)
    sup.step(0.5)    # miss streak 1
    sup.step(0.94)   # in the band (0.90..0.98): both streaks reset
    assert sup.step(0.5) == "hold"   # miss streak restarts at 1
    sup.step(0.99)   # good streak 1
    sup.step(0.94)   # band again
    assert sup.step(0.99) == "hold"  # good streak restarts at 1
    assert router.n_ready() == 2     # nothing ever fired


def test_replace_dead_runs_outside_cooldowns():
    clock = FakeClock()
    sup, router, spawned = _supervisor(clock, n_start=2,
                                       cooldown_out_s=1e9)
    victim = spawned[0]
    victim.beat_ok = False
    clock.advance(11.0)  # past heartbeat_timeout_s
    assert sup.step(0.99) == "replace"
    assert sup.n_replaced == 1
    assert router.n_ready() == 2  # 1:1 replacement despite the cooldown
    assert victim.replica_id not in [
        r.replica_id for r in router.replicas()]


def test_supervisor_decisions_are_valid_telemetry_rows():
    clock = FakeClock()
    sup, _, _ = _supervisor(clock)
    sup.step(0.5)
    sup.step(0.5)
    sup.step(0.99)
    for d in sup.decisions:
        row = {"v": 1, "kind": "scale_decision", "t": 0.0, **d}
        assert validate_row(row) == [], row


# -- drain-before-retire on a REAL batcher -----------------------------------


@pytest.fixture(scope="module")
def serve_stack(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("scene_scale"))
    generate_scene(root, scene="procedural", H=16, W=16, n_train=4, n_test=1)
    cfg = tiny_cfg(
        root,
        ["task_arg.render_step_size", "0.25",
         "task_arg.max_march_samples", "16",
         "task_arg.march_chunk_size", "64",
         "serve.buckets", "[128]",
         "serve.max_batch_rays", "128",
         "serve.max_delay_ms", "40.0",
         "serve.request_timeout_s", "5.0",
         "compile.aot", "False"],
    )
    from nerf_replication_tpu.models import make_network
    from nerf_replication_tpu.models.nerf.network import init_params

    network = make_network(cfg)
    params = init_params(network, jax.random.PRNGKey(0))
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)
    grid = np.zeros((16, 16, 16), bool)
    grid[4:12, 4:12, 4:12] = True
    return cfg, network, params, grid, bbox


def _rays(n: int, seed: int = 7) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return np.concatenate(
        [np.tile([0.0, 0.0, 4.0], (n, 1)),
         np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.15, (n, 3))],
        -1,
    ).astype(np.float32)


def test_drain_before_retire_fails_zero_in_flight(serve_stack):
    from nerf_replication_tpu.serve import MicroBatcher, RenderEngine

    cfg, network, params, grid, bbox = serve_stack
    clock = FakeClock()
    engine = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                          grid=grid, bbox=bbox)
    batcher = MicroBatcher(engine, clock=clock, start=False)
    replica = InProcessReplica("r0", engine, batcher, clock=clock)
    futures = [replica.submit(_rays(32, seed=i), NEAR, FAR)
               for i in range(4)]
    assert replica.load() == 4
    clock.advance(1.0)  # past the delay edge so pump() cuts immediately
    failed = replica.drain(timeout_s=30.0)
    assert failed == 0                       # the contract
    assert replica.state == ReplicaState.RETIRED
    for f in futures:
        out = f.result(timeout=0.1)          # every queued request rendered
        assert out["rgb_map_f"].shape == (32, 3)
    with pytest.raises(ReplicaUnavailableError):
        replica.submit(_rays(8), NEAR, FAR)  # no admissions after retire


def test_killed_replica_fails_queued_and_router_fails_over(serve_stack):
    from nerf_replication_tpu.serve import MicroBatcher, RenderEngine
    from nerf_replication_tpu.serve.batcher import ServeTimeoutError

    cfg, network, params, grid, bbox = serve_stack
    clock = FakeClock()
    engine = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                          grid=grid, bbox=bbox)
    doomed = InProcessReplica(
        "doomed", engine, MicroBatcher(engine, clock=clock, start=False),
        clock=clock)
    backup = InProcessReplica(
        "backup", engine, MicroBatcher(engine, clock=clock, start=False),
        clock=clock)
    router = Router(heartbeat_timeout_s=10.0, clock=clock)
    router.register(doomed)
    router.register(backup)
    router.sweep()
    fut = doomed.submit(_rays(16), NEAR, FAR)
    doomed.kill()
    with pytest.raises(ServeTimeoutError):
        fut.result(timeout=0.1)  # queued work fails AT the kill, not later
    fut2 = router.submit(_rays(16), NEAR, FAR)  # front door fails over
    clock.advance(1.0)
    backup.batcher.pump()
    assert fut2.result(timeout=0.1)["rgb_map_f"].shape == (16, 3)
    assert router.n_failovers == 0  # dead replica never entered candidates
    assert backup.stats()["n_submitted"] == 1


# -- mesh-sharded dispatch ---------------------------------------------------


def test_validate_mesh_buckets_rejects_indivisible_layouts():
    class FakeMesh:
        def __init__(self, n):
            self.shape = {"data": n, "model": 1}

    validate_mesh_buckets([128, 256], 64, FakeMesh(1))  # size 1: all fine
    validate_mesh_buckets([128, 256], 16, FakeMesh(8))  # 8, 16 chunks % 8
    with pytest.raises(MeshDispatchError):
        validate_mesh_buckets([128, 256], 64, FakeMesh(3))  # 2, 4 chunks


def test_mesh_mode_off_and_auto_single_device_disable_the_mesh(serve_stack):
    cfg, *_ = serve_stack
    root = cfg.train_dataset.data_root
    assert mesh_from_scale_cfg(tiny_cfg(root)) is None  # default off
    if len(jax.devices()) == 1:
        assert mesh_from_scale_cfg(
            tiny_cfg(root, ["scale.mesh", "auto"])) is None
    with pytest.raises(MeshDispatchError):
        mesh_from_scale_cfg(tiny_cfg(root, ["scale.mesh", "sideways"]))


def test_mesh_forced_render_is_bitwise_equal_to_single_device(serve_stack):
    """The acceptance contract: the SAME request through the mesh-sharded
    executables composites bitwise-identically to the plain-jit path.
    Under conftest's 8-device CPU emulation this is a real 8-way shard —
    chunk 16 so the 128-ray bucket holds 8 chunks, one per device."""
    from nerf_replication_tpu.models import make_network
    from nerf_replication_tpu.models.nerf.network import init_params
    from nerf_replication_tpu.serve import RenderEngine

    base_cfg, _, _, grid, bbox = serve_stack
    cfg = tiny_cfg(
        base_cfg.train_dataset.data_root,
        ["task_arg.render_step_size", "0.25",
         "task_arg.max_march_samples", "16",
         "task_arg.march_chunk_size", "16",
         "serve.buckets", "[128]",
         "serve.max_batch_rays", "128",
         "compile.aot", "False",
         "scale.mesh", "force"],
    )
    network = make_network(cfg)
    params = init_params(network, jax.random.PRNGKey(0))
    mesh = mesh_from_scale_cfg(cfg)
    assert mesh is not None and int(mesh.size) == len(jax.devices())
    plain = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                         grid=grid, bbox=bbox)
    sharded = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                           grid=grid, bbox=bbox, mesh=mesh)
    assert sharded.stats()["mesh"]["devices"] == len(jax.devices())
    for n in (37, 100, 128):
        rays = _rays(n)
        for tier in ("full", "bf16", "reduced_k", "coarse"):
            a = plain.render_request(rays, NEAR, FAR, tier=tier, emit=False)
            b = sharded.render_request(rays, NEAR, FAR, tier=tier,
                                       emit=False)
            for k in ("rgb_map_f", "depth_map_f", "acc_map_f"):
                assert np.array_equal(np.asarray(a[k]),
                                      np.asarray(b[k])), (tier, k, n)
    # zero steady-state recompiles through the mesh path
    before = sharded.tracker.total_compiles()
    for n in (1, 64, 128, 200):
        rays = np.tile(_rays(1), (n, 1))
        sharded.render_request(rays, NEAR, FAR, tier="full", emit=False)
    assert sharded.tracker.total_compiles() == before


# -- telemetry schema --------------------------------------------------------


def test_scale_row_kinds_validate():
    rows = [
        {"v": 1, "kind": "replica", "t": 0.0, "replica": "r0",
         "event": "spawn", "state": "ready", "warm_source": "disk",
         "total_compiles": 0, "scenes": ["lego"], "n_ready": 2},
        {"v": 1, "kind": "router", "t": 0.0, "event": "failover",
         "replica": "r0", "scene": "lego", "n_candidates": 1},
        {"v": 1, "kind": "router", "t": 0.0, "event": "drain",
         "replica": "r1", "load": 3, "n_failed": 0},
        {"v": 1, "kind": "scale_decision", "t": 0.0, "action": "out",
         "reason": "slo_miss", "n_replicas": 2, "attainment": 0.8,
         "deny_rate": 0.0, "streak": 2, "replica": "s1"},
    ]
    for row in rows:
        assert validate_row(row) == [], row
    bad = {"v": 1, "kind": "scale_decision", "t": 0.0, "action": "out"}
    assert validate_row(bad) != []  # reason + n_replicas are required


def test_scale_mode_bench_family_validates():
    from nerf_replication_tpu.obs.schema import validate_bench_row

    row = {"scale_mode": "open_loop", "replicas_peak": 3,
           "attainment_low": 0.6, "attainment_recovered": 0.97,
           "scale_outs": 2, "scale_ins": 1}
    assert validate_bench_row(row) == [], row


# -- runtime lock-order sanitizer on the LIVE stack (PR 18) -------------------


def test_lock_order_sanitizer_live_stack(serve_stack):
    """PR 18's acceptance gate: a worker-threaded batcher (real clock, real
    thread), a tiered residency ladder, and a router instrumented in ONE
    process — steady state serves at zero recompiles AND the observed
    lock-acquisition order is acyclic."""
    from nerf_replication_tpu.analysis import LockOrderRecorder, sanitizer
    from nerf_replication_tpu.fleet import (
        SceneData,
        SceneRecord,
        SceneRegistry,
        TieredResidencyManager,
    )
    from nerf_replication_tpu.obs import CompileTracker
    from nerf_replication_tpu.serve import MicroBatcher, RenderEngine

    cfg, network, params, grid, bbox = serve_stack
    tracker = CompileTracker()
    engine = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                          grid=grid, bbox=bbox, tracker=tracker)
    batcher = MicroBatcher(engine)
    replica = InProcessReplica("r0", engine, batcher)
    router = Router(heartbeat_timeout_s=30.0)
    router.register(replica)
    router.sweep()

    def loader(record):
        return SceneData(scene_id=record.scene_id,
                         params={"w": np.full((256,), 1.0, np.float32)})

    registry = SceneRegistry(SceneRecord(scene_id=s) for s in ("a", "b", "c"))
    mgr = TieredResidencyManager(
        registry, loader, budget_bytes=3000, staging_budget_bytes=8192,
        verify_checksums=False)

    recorder = LockOrderRecorder()
    recorder.instrument(batcher, "_cond")
    recorder.instrument(mgr, "_cond")
    recorder.instrument(router, "_lock")

    try:
        # warm the single 128-ray bucket OUTSIDE the guarded region
        router.submit(_rays(64), NEAR, FAR).result(timeout=60.0)
        with sanitizer(tracker, transfers=None) as probe:
            futs = [router.submit(_rays(48, seed=i), NEAR, FAR)
                    for i in range(6)]
            mgr.prefetch("b")
            for i in range(4):
                data = mgr.acquire("a" if i % 2 else "c")
                mgr.release(data.scene_id)
            mgr.sweep()
            router.sweep()
            for f in futs:
                assert f.result(timeout=60.0)["rgb_map_f"].shape == (48, 3)
        assert probe.compiles == 0           # zero steady-state recompiles
    finally:
        batcher.close(drain=False)

    recorder.assert_acyclic()                # the lock-order gate

    class _Tap:
        def __init__(self):
            self.rows = []

        def emit(self, kind, **fields):
            self.rows.append({"kind": kind, **fields})

    tap = _Tap()
    row = recorder.emit(emitter=tap, source="tier1")
    assert row["acyclic"] is True and row["n_locks"] >= 3
    assert {"MicroBatcher._cond", "TieredResidencyManager._cond",
            "Router._lock"} <= set(row["locks"])
    assert validate_row({"v": 1, "t": 0.0, **tap.rows[0]}) == [], row
