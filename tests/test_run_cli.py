"""The eval CLI's unified render gate: all four (grid, sharded) paths
resolve, and the sharded paths reject per-batch bounds that differ from the
baked ones instead of silently rendering the wrong depth range."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import run as run_cli
from test_train import tiny_cfg

from nerf_replication_tpu.datasets.procedural import generate_scene
from nerf_replication_tpu.models import make_network
from nerf_replication_tpu.models.nerf.network import init_params
from nerf_replication_tpu.renderer import make_renderer


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("scene_cli"))
    generate_scene(root, scene="procedural", H=8, W=8, n_train=2, n_test=1)
    cfg = tiny_cfg(
        root,
        ["task_arg.march_chunk_size", "32",
         "task_arg.max_march_samples", "8",
         "task_arg.render_step_size", "0.5",
         "task_arg.chunk_size", "32",
         "train_dataset.H", "8", "train_dataset.W", "8",
         "test_dataset.H", "8", "test_dataset.W", "8"],
    )
    network = make_network(cfg)
    params = init_params(network, jax.random.PRNGKey(0))
    renderer = make_renderer(cfg, network)
    from nerf_replication_tpu.datasets import make_dataset

    test_ds = make_dataset(cfg, "test")
    return cfg, network, params, renderer, test_ds


def _batch(test_ds, near=None, far=None):
    b = test_ds.image_batch(0)
    if near is not None:
        b = dict(b, near=np.float32(near), far=np.float32(far))
    return b


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device CPU mesh")
@pytest.mark.parametrize("sharded", [False, True])
@pytest.mark.parametrize("use_grid", [False, True])
def test_gate_resolves_and_renders(setup, sharded, use_grid):
    cfg, network, params, renderer, test_ds = setup
    cfg = cfg.clone()
    cfg.defrost()
    cfg.eval = {"sharded": sharded}
    cfg.freeze()
    if use_grid:
        rng = np.random.default_rng(0)
        renderer.occupancy_grid = jnp.asarray(rng.random((8, 8, 8)) < 0.5)
        renderer.grid_bbox = jnp.asarray(
            cfg.train_dataset.scene_bbox, jnp.float32
        )
    render = run_cli._full_image_render_fn(
        cfg, network, renderer, test_ds, use_grid=use_grid
    )
    out = render(params, _batch(test_ds))
    rgb = np.asarray(out["rgb_map_f"])
    assert rgb.shape == (64, 3) and np.isfinite(rgb).all()


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device CPU mesh")
def test_sharded_gate_accepts_non_dyadic_matching_bounds(setup):
    # near=0.1 is not exactly float32-representable: the batch carries
    # np.float32(0.1) while the dataset holds the python float 0.1 — equal
    # bounds must pass the gate (ADVICE r2: exact != compared f64 vs f32)
    import types

    cfg, network, params, renderer, test_ds = setup
    cfg = cfg.clone()
    cfg.defrost()
    cfg.eval = {"sharded": True}
    cfg.freeze()
    ds = types.SimpleNamespace(near=0.1, far=0.3)
    render = run_cli._full_image_render_fn(
        cfg, network, renderer, ds, use_grid=False
    )
    out = render(params, _batch(test_ds, near=0.1, far=0.3))
    assert np.isfinite(np.asarray(out["rgb_map_f"])).all()


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device CPU mesh")
def test_sharded_gate_rejects_mismatched_bounds(setup):
    cfg, network, params, renderer, test_ds = setup
    cfg = cfg.clone()
    cfg.defrost()
    cfg.eval = {"sharded": True}
    cfg.freeze()
    render = run_cli._full_image_render_fn(
        cfg, network, renderer, test_ds, use_grid=False
    )
    with pytest.raises(ValueError, match="baked bounds"):
        render(params, _batch(test_ds, near=1.0, far=3.0))
