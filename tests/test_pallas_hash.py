"""Pallas hash-grid forward vs the pure-XLA oracle (SURVEY.md §7 step 8).

Interpret mode on CPU checks the kernel's semantics — layout packing, the
dense/hash index select, corner weights, block padding — against
``hash_encode``, the formulation already gradient-tested in
test_hashgrid.py. The TPU lowering + speed verdict lives in PERF.md.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nerf_replication_tpu.models.encoding.hashgrid import (
    hash_encode,
    level_geometry,
)
from nerf_replication_tpu.models.encoding.pallas_hash import (
    make_hash_encode_fn,
    pack_table,
    pallas_hash_encode,
)

CASES = [
    # (D, L, C, scale, base_res, log2_T) — small tables. NOTE the
    # reference's round-DOWN-to-8 slice sizing (hashgrid.py:171) makes
    # (res+1)^D exceed the slice on nearly every level, so almost
    # everything takes the XOR-hash path; dense indexing survives only
    # when (res+1)^D is itself a multiple of 8 (e.g. res+1 = 2, D = 3).
    (3, 4, 2, 1.5, 4, 8),
    (3, 6, 2, 1.39, 16, 11),  # lego_hash geometry, shrunk table
    (2, 3, 4, 2.0, 4, 9),
    (4, 2, 2, 1.5, 3, 10),
    (3, 2, 2, 2.0, 1, 8),  # level 0 is dense: (1+1)^3 = 8 = slice size
]


def _setup(d, lvls, c, scale, base, log2_t, n=300, seed=0):
    offsets, _, _, use_hash = level_geometry(d, lvls, scale, base, log2_t)
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    table = jax.random.uniform(k1, (offsets[-1], c), jnp.float32, -1.0, 1.0)
    x = jax.random.uniform(k2, (n, d), jnp.float32)
    return x, table, use_hash


@pytest.mark.parametrize("case", CASES)
def test_pallas_matches_xla_oracle(case):
    d, lvls, c, scale, base, log2_t = case
    x, table, use_hash = _setup(*case)
    assert any(use_hash)
    if case == CASES[-1]:
        assert not use_hash[0], "dense-mode coverage case regressed"

    ref = hash_encode(x, table, d, lvls, scale, base, log2_t)
    got = pallas_hash_encode(
        x, table, d, lvls, scale, base, log2_t,
        block_size=128, interpret=True,
    )
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_block_padding_edge():
    # n not divisible by block_size: padded points must not leak into output
    case = CASES[0]
    x, table, _ = _setup(*case, n=130)
    d, lvls, c, scale, base, log2_t = case
    ref = hash_encode(x, table, d, lvls, scale, base, log2_t)
    got = pallas_hash_encode(
        x, table, d, lvls, scale, base, log2_t,
        block_size=64, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_pack_table_roundtrip():
    d, lvls, c, scale, base, log2_t = CASES[0]
    offsets, _, _, _ = level_geometry(d, lvls, scale, base, log2_t)
    table = jnp.arange(offsets[-1] * c, dtype=jnp.float32).reshape(-1, c)
    pages = pack_table(table, offsets)
    # entry e of level l, feature f -> pages[l, e//128, f*128 + e%128]
    for lvl in range(lvls):
        size = offsets[lvl + 1] - offsets[lvl]
        for e in (0, 1, size - 1):
            for f in range(c):
                expect = table[offsets[lvl] + e, f]
                got = pages[lvl, e // 128, f * 128 + e % 128]
                assert float(got) == float(expect)


def test_custom_vjp_grads_match_xla():
    """Pallas forward + XLA backward == XLA forward + XLA backward."""
    case = CASES[1]
    d, lvls, c, scale, base, log2_t = case
    x, table, _ = _setup(*case, n=64)

    f_xla = make_hash_encode_fn(d, lvls, scale, base, log2_t, use_pallas=False)
    f_pal = make_hash_encode_fn(
        d, lvls, scale, base, log2_t, use_pallas=True, interpret=True
    )

    def loss_of(f):
        return lambda x, t: jnp.sum(jnp.sin(f(x, t) * 3.0))

    v_ref, g_ref = jax.value_and_grad(loss_of(f_xla), argnums=(0, 1))(x, table)
    v_pal, g_pal = jax.value_and_grad(loss_of(f_pal), argnums=(0, 1))(x, table)
    assert float(v_pal) == pytest.approx(float(v_ref), rel=1e-5)
    for a, b in zip(g_pal, g_ref):
        # the two paths accumulate the scatter-add in different orders, so
        # f32 grads can disagree by a few ulps amplified through the chain
        # rule (observed rel ~5e-4 on one element of 192 on this host)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-3, rtol=1e-3
        )
