"""Globally-packed occupancy march (renderer/packed_march.py): exact
compositing parity with the per-ray [N, K] march when neither truncates,
global-overflow semantics, differentiability, and the NGP trainer's packed
mode end to end."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from test_train import tiny_cfg

from nerf_replication_tpu.datasets.blender import Dataset
from nerf_replication_tpu.datasets.procedural import generate_scene
from nerf_replication_tpu.models import make_network
from nerf_replication_tpu.models.nerf.network import init_params
from nerf_replication_tpu.renderer.accelerated import (
    MarchOptions,
    march_rays_accelerated,
)
from nerf_replication_tpu.renderer.packed_march import march_rays_packed


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("scene_packed"))
    generate_scene(root, scene="procedural", H=16, W=16, n_train=4, n_test=1)
    cfg = tiny_cfg(
        root,
        ["task_arg.render_step_size", "0.25",
         "task_arg.max_march_samples", "16",
         "task_arg.march_chunk_size", "64"],
    )
    network = make_network(cfg)
    params = init_params(network, jax.random.PRNGKey(0))

    def apply_fn(pts, dirs, model):
        return network.apply(params, pts, dirs, model=model)

    rng = np.random.default_rng(7)
    n = 64
    rays = np.concatenate(
        [
            np.tile([0.0, 0.0, 4.0], (n, 1)),
            np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.15, (n, 3)),
        ],
        -1,
    ).astype(np.float32)

    bbox = jnp.asarray(cfg.train_dataset.scene_bbox, jnp.float32)
    # a carved grid with structure: occupied box in the middle
    grid = np.zeros((16, 16, 16), bool)
    grid[4:12, 4:12, 4:12] = True
    return cfg, apply_fn, jnp.asarray(rays), jnp.asarray(grid), bbox


def test_packed_matches_per_ray_march_when_neither_truncates(setup):
    """With generous budgets on both sides the packed stream must composite
    EXACTLY like the [N, K] march: 1−α = exp(−σδ) makes the log-space
    segmented transmittance the same number as the cumprod, so rgb/depth/
    acc agree to float tolerance, per ray."""
    cfg, apply_fn, rays, grid, bbox = setup
    options = MarchOptions(
        step_size=0.25, max_samples=16, white_bkgd=True, chunk_size=64
    )
    a = march_rays_accelerated(
        apply_fn, rays, 2.0, 6.0, grid, bbox, options
    )
    p = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox, options, cap_avg=16
    )
    assert not bool(a["truncated"].any())
    assert float(p["overflow_frac"]) == 0.0
    for k in ("rgb_map_f", "depth_map_f", "acc_map_f"):
        np.testing.assert_allclose(
            np.asarray(p[k]), np.asarray(a[k]), rtol=2e-4, atol=2e-5,
            err_msg=k,
        )
    assert not bool(p["truncated"].any())


def test_packed_ert_matches_reference_semantics(setup):
    """A high transmittance threshold exercises early termination: both
    formulations must zero the same samples' weights."""
    cfg, apply_fn, rays, grid, bbox = setup
    options = MarchOptions(
        step_size=0.25, max_samples=16, white_bkgd=True, chunk_size=64,
        transmittance_threshold=0.5,
    )
    a = march_rays_accelerated(apply_fn, rays, 2.0, 6.0, grid, bbox, options)
    p = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox, options, cap_avg=16
    )
    for k in ("rgb_map_f", "acc_map_f"):
        np.testing.assert_allclose(
            np.asarray(p[k]), np.asarray(a[k]), rtol=2e-4, atol=2e-5,
            err_msg=k,
        )


def test_packed_global_overflow_reports_and_truncates_tail_rays(setup):
    """A starved global cap must (a) report the dropped-occupied fraction,
    (b) flag truncated only for rays whose samples fell off the stream
    while still transparent — rays early in the batch keep full budgets."""
    cfg, apply_fn, rays, grid, bbox = setup
    options = MarchOptions(
        step_size=0.25, max_samples=16, white_bkgd=True, chunk_size=64
    )
    full = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox, options, cap_avg=16
    )
    starved = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox, options, cap_avg=2
    )
    assert float(starved["overflow_frac"]) > 0.0
    trunc = np.asarray(starved["truncated"])
    assert trunc.any()
    # rays BEFORE the overflow point are untouched: their maps equal the
    # generous-cap render
    first_bad = int(np.argmax(trunc))
    assert first_bad > 0
    np.testing.assert_allclose(
        np.asarray(starved["rgb_map_f"][:first_bad]),
        np.asarray(full["rgb_map_f"][:first_bad]),
        rtol=2e-4, atol=2e-5,
    )


def test_packed_pad_rays_and_fully_dropped_segments(setup):
    """Zero-direction padding rays must consume no stream budget and not
    inflate overflow_frac; a ray whose WHOLE segment falls past the cap
    must still be flagged truncated (its transmittance is trivially 1 —
    it must not read another ray's tau through the clamped gather)."""
    cfg, apply_fn, rays, grid, bbox = setup
    options = MarchOptions(
        step_size=0.25, max_samples=16, white_bkgd=True, chunk_size=64
    )
    pads = jnp.zeros((32, 6), jnp.float32)
    padded = jnp.concatenate([rays, pads], axis=0)
    base = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox, options, cap_avg=16
    )
    with_pads = march_rays_packed(
        apply_fn, padded, 2.0, 6.0, grid, bbox, options, cap_avg=16
    )
    # pads add stream capacity (M = N*cap) but zero occupied samples
    assert float(with_pads["overflow_frac"]) == 0.0
    np.testing.assert_allclose(
        np.asarray(with_pads["rgb_map_f"][: rays.shape[0]]),
        np.asarray(base["rgb_map_f"]),
        rtol=2e-4, atol=2e-5,
    )
    assert not bool(with_pads["truncated"][rays.shape[0]:].any())

    # cap_avg=1 on the unpadded batch: late rays lose their ENTIRE
    # segment; every sample-losing, still-transparent ray must be flagged
    starved = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox, options, cap_avg=1
    )
    trunc = np.asarray(starved["truncated"])
    acc = np.asarray(starved["acc_map_f"])
    # rays that composited nothing but DO cross occupied space: truncated
    fully_dropped = (acc < 1e-6) & (np.asarray(base["acc_map_f"]) > 1e-3)
    assert fully_dropped.any()
    assert trunc[fully_dropped].all()


def test_hierarchical_march_matches_flat_when_no_block_clips(setup):
    """coarse_block > 0 inserts the coarse-DDA stage; because the pyramid
    level is an any-reduce (strict superset) of the fine grid, admitting
    only the positions with occupied parents must composite IDENTICALLY to
    the flat packed march whenever K_c covers every occupied block — while
    genuinely shrinking the candidate stream entering the global sort."""
    import dataclasses

    cfg, apply_fn, rays, grid, bbox = setup
    options = MarchOptions(
        step_size=0.25, max_samples=16, white_bkgd=True, chunk_size=64
    )
    # S=16, r=4 ⇒ S_c=4 blocks; the box [4:12]³ spans at most 3 of them
    # for any ray in this batch, so K_c=3 never clips
    hier_opt = dataclasses.replace(options, coarse_block=4, coarse_cap=3)
    flat = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox, options, cap_avg=16
    )
    hier = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox, hier_opt, cap_avg=16
    )
    assert float(hier["overflow_frac"]) == 0.0
    assert not bool(hier["truncated"].any())
    for k in ("rgb_map_f", "depth_map_f", "acc_map_f"):
        np.testing.assert_allclose(
            np.asarray(hier[k]), np.asarray(flat[k]), rtol=2e-4, atol=2e-5,
            err_msg=k,
        )
    # the same occupied samples composite on both sides...
    assert float(hier["march_samples_out"]) == float(flat["march_samples_out"])
    # ...from a smaller candidate stream (K_c·r = 12 < S = 16)
    assert float(hier["march_candidates"]) < float(flat["march_candidates"])
    assert 0.0 < float(hier["march_coarse_occ"]) < 1.0
    assert float(flat["march_coarse_occ"]) == 1.0


def test_hierarchical_coarse_clip_reports_truncation(setup):
    """Satellite fix: a ray whose occupied coarse blocks were CLIPPED by
    K_c lost samples the stream never saw — the global-overflow test alone
    cannot observe that, so ``truncated`` must still fire. Unclipped rays
    must be untouched."""
    import dataclasses

    cfg, apply_fn, rays, grid, bbox = setup
    options = MarchOptions(
        step_size=0.25, max_samples=16, white_bkgd=True, chunk_size=64
    )
    full = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox,
        dataclasses.replace(options, coarse_block=4, coarse_cap=3),
        cap_avg=16,
    )
    clipped = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox,
        dataclasses.replace(options, coarse_block=4, coarse_cap=1),
        cap_avg=16,
    )
    # the stream itself never overflowed: every reported truncation below
    # comes from the coarse clip, not the global cap
    assert float(clipped["overflow_frac"]) == 0.0
    trunc = np.asarray(clipped["truncated"])
    assert trunc.any()
    # unflagged rays composite exactly like the uncapped run (either they
    # fit in one block, or their clipped tail was already ERT-dead)
    ok = ~trunc
    for k in ("rgb_map_f", "depth_map_f", "acc_map_f"):
        np.testing.assert_allclose(
            np.asarray(clipped[k])[ok], np.asarray(full[k])[ok],
            rtol=2e-4, atol=2e-5, err_msg=k,
        )
    # flagged rays lost tail samples: they can only composite LESS opacity
    acc_c = np.asarray(clipped["acc_map_f"])
    acc_f = np.asarray(full["acc_map_f"])
    assert (acc_c[trunc] <= acc_f[trunc] + 1e-5).all()


def test_hierarchical_march_is_differentiable(setup):
    """Grads must flow through the coarse-DDA stage (block selection is a
    constant gather; only the candidate set changes) and stay finite."""
    import dataclasses

    cfg, apply_fn, rays, grid, bbox = setup
    network = make_network(cfg)
    params = init_params(network, jax.random.PRNGKey(0))
    options = dataclasses.replace(
        MarchOptions(
            step_size=0.25, max_samples=16, white_bkgd=True, chunk_size=64
        ),
        coarse_block=4, coarse_cap=3,
    )
    gt = jnp.ones((rays.shape[0], 3)) * 0.5

    def loss_fn(p):
        out = march_rays_packed(
            lambda pts, d, m: network.apply(p, pts, d, model=m),
            rays, 2.0, 6.0, grid, bbox, options, cap_avg=8,
        )
        return jnp.mean((out["rgb_map_f"] - gt) ** 2)

    g = jax.grad(loss_fn)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert leaves and all(bool(jnp.isfinite(leaf).all()) for leaf in leaves)
    assert sum(float(jnp.abs(leaf).sum()) for leaf in leaves) > 0.0


def test_packed_march_is_differentiable(setup):
    """Grads must flow through the packed stream (sort indices are
    constant; gather/cumsum/segment_sum all differentiate) and be finite."""
    cfg, apply_fn, rays, grid, bbox = setup
    network = make_network(cfg)
    params = init_params(network, jax.random.PRNGKey(0))
    options = MarchOptions(
        step_size=0.25, max_samples=16, white_bkgd=True, chunk_size=64
    )
    gt = jnp.ones((rays.shape[0], 3)) * 0.5

    def loss_fn(p):
        out = march_rays_packed(
            lambda pts, d, m: network.apply(p, pts, d, model=m),
            rays, 2.0, 6.0, grid, bbox, options, cap_avg=8,
        )
        return jnp.mean((out["rgb_map_f"] - gt) ** 2)

    g = jax.grad(loss_fn)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert leaves and all(
        bool(jnp.isfinite(leaf).all()) for leaf in leaves
    )
    # the fine trunk actually receives signal
    total = sum(float(jnp.abs(leaf).sum()) for leaf in leaves)
    assert total > 0.0


def test_packed_return_samples_feed_grid_maintenance(setup):
    """return_samples must expose [M] flat voxel ids / sigmas / valid mask
    (the NGP live-grid scatter-max consumes them flattened)."""
    cfg, apply_fn, rays, grid, bbox = setup
    options = MarchOptions(
        step_size=0.25, max_samples=16, white_bkgd=True, chunk_size=64
    )
    out = march_rays_packed(
        apply_fn, rays, 2.0, 6.0, grid, bbox, options, cap_avg=8,
        return_samples=True,
    )
    m = rays.shape[0] * 8
    assert out["sample_flat"].shape == (m,)
    assert out["sample_sigma"].shape == (m,)
    assert out["sample_valid"].shape == (m,)
    flat = np.asarray(out["sample_flat"])
    valid = np.asarray(out["sample_valid"]) > 0
    # every VALID sample's voxel is occupied in the grid
    assert np.asarray(grid).reshape(-1)[flat[valid]].all()


def test_packed_clip_bbox_concentrates_the_budget(setup):
    """march_clip_bbox: the same static S covers only each ray's bbox
    span — misses render pure background with zero stream use, hits
    composite ≈ the unclipped march (same integral, finer quadrature),
    and per-ray spans are genuine bbox intersections."""
    import dataclasses

    from nerf_replication_tpu.renderer.packed_march import _ray_bbox_spans

    cfg, apply_fn, rays, grid, bbox = setup
    # fine steps: with a constant-density field both quadratures converge
    # to the same optical-depth integral (boundary samples carry t ~ s·d,
    # so the disagreement shrinks linearly with the step)
    options = MarchOptions(
        step_size=0.05, max_samples=80, white_bkgd=True, chunk_size=64
    )
    clipped = dataclasses.replace(options, clip_bbox=True)

    def const_apply(pts, dirs, model):
        shape = pts.shape[:-1] + (4,)
        return jnp.concatenate(
            [jnp.full(pts.shape[:-1] + (3,), 0.3),
             jnp.full(pts.shape[:-1] + (1,), 2.0)], -1
        ).reshape(shape)

    a = march_rays_packed(
        const_apply, rays, 2.0, 6.0, grid, bbox, options, cap_avg=80
    )
    c = march_rays_packed(
        const_apply, rays, 2.0, 6.0, grid, bbox, clipped, cap_avg=80
    )
    np.testing.assert_allclose(
        np.asarray(c["rgb_map_f"]), np.asarray(a["rgb_map_f"]),
        atol=0.05,
    )

    # rays that MISS the bbox: pure background, no budget consumed
    miss = jnp.asarray(
        np.concatenate(
            [np.tile([0.0, 0.0, 4.0], (8, 1)),
             np.tile([0.0, 0.0, 1.0], (8, 1))],  # pointing away
            -1,
        ), jnp.float32,
    )
    m = march_rays_packed(
        const_apply, miss, 2.0, 6.0, grid, bbox, clipped, cap_avg=4
    )
    np.testing.assert_allclose(np.asarray(m["rgb_map_f"]), 1.0, atol=1e-6)
    assert float(m["overflow_frac"]) == 0.0

    # span math: inside [near, far], within the bbox diameter
    t0, t1 = _ray_bbox_spans(rays[:, :3], rays[:, 3:6], bbox, 2.0, 6.0)
    span = np.asarray(t1 - t0)
    assert (span >= 0).all() and (np.asarray(t0) >= 2.0 - 1e-6).all()
    diag = float(jnp.linalg.norm(bbox[1] - bbox[0]))
    assert (span <= diag + 1e-5).all()
    assert span.max() > 0  # at least one ray crosses the bbox


def test_ngp_trainer_packed_mode_trains_and_carves(setup):
    """ngp_packed_march: true routes the march loss through the packed
    stream; training must reduce loss and keep the live grid finite, and
    eval must render finite images through the packed path."""
    cfg, apply_fn, rays, grid, bbox = setup
    root = cfg.train_dataset.data_root
    extra = [
        "task_arg.N_rays", "128",
        "task_arg.ngp_training", "true",
        "task_arg.ngp_grid_res", "16",
        "task_arg.ngp_packed_march", "true",
        "task_arg.ngp_packed_cap_avg", "8",
        "task_arg.ngp_warmup_steps", "4",
        "task_arg.ngp_warmup_samples", "16",
        "task_arg.ngp_warmup_exit_occ", "1.1",
        "task_arg.render_step_size", "0.25",
        "task_arg.max_march_samples", "16",
        "task_arg.march_chunk_size", "64",
    ]
    cfg2 = tiny_cfg(root, extra)
    from nerf_replication_tpu.train.ngp import make_ngp_trainer

    net = make_network(cfg2)
    trainer = make_ngp_trainer(cfg2, net)
    assert trainer.packed_march
    ds = Dataset(data_root=root, scene="procedural", split="train", H=16,
                 W=16)
    bank = tuple(jnp.asarray(a) for a in ds.ray_bank())
    key = jax.random.PRNGKey(1)

    state, _ = trainer.make_state(jax.random.PRNGKey(0))
    losses = []
    for _ in range(20):
        state, stats = trainer.step(state, bank[0], bank[1], key)
        losses.append(float(stats["loss"]))
    assert np.isfinite(losses).all()
    # signal reaches the params through the packed march (per-step noise
    # from the masked loss over a still-dense grid is expected — demand
    # improvement, not monotonicity)
    assert min(losses[5:]) < losses[0]
    # past warmup the packed stats surface the overflow diagnostic
    assert not trainer.last_burst_warm
    assert "overflow_frac" in stats
    assert bool(jnp.isfinite(state.grid_ema).all())

    out = trainer.render_image(state, {"rays": bank[0][:128]})
    assert np.isfinite(np.asarray(out["rgb_map_f"])).all()
