"""Recorder/SmoothedValue parity surface: median/global-avg math, eta
formatting in console_line, and checkpointable state that preserves the
smoothing totals across a resume (the reference resets eta to zero on
resume — fixed here, PR 1 satellite)."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nerf_replication_tpu.config import ConfigNode  # noqa: E402
from nerf_replication_tpu.train.recorder import (  # noqa: E402
    Recorder,
    SmoothedValue,
)


def _recorder(tmp_path, **extra):
    cfg = ConfigNode(
        {"record_dir": str(tmp_path / "rec"), "resume": False, **extra}
    )
    return Recorder(cfg)


def test_smoothed_value_window_math():
    sv = SmoothedValue(window_size=4)
    for v in (1.0, 3.0, 5.0, 7.0):
        sv.update(v)
    assert sv.median == 4.0        # even window: mean of middle pair
    assert np.isclose(sv.avg, 4.0)
    assert np.isclose(sv.global_avg, 4.0)
    sv.update(9.0)                 # 1.0 falls out of the window...
    assert sv.median == 6.0
    assert np.isclose(sv.avg, 6.0)
    # ...but stays in the global average
    assert np.isclose(sv.global_avg, 5.0)
    assert sv.count == 5 and sv.total == 25.0


def test_smoothed_value_empty_defaults():
    sv = SmoothedValue()
    assert sv.median == 0.0 and sv.avg == 0.0 and sv.global_avg == 0.0
    assert str(sv) == "0.0000 (0.0000)"


def test_smoothed_value_state_roundtrip():
    sv = SmoothedValue(window_size=3)
    for v in (2.0, 4.0, 6.0, 8.0):
        sv.update(v)
    sv2 = SmoothedValue(window_size=3)
    sv2.load_state_dict(sv.state_dict())
    assert sv2.total == sv.total and sv2.count == sv.count
    assert list(sv2.deque) == list(sv.deque)
    assert sv2.median == sv.median and sv2.global_avg == sv.global_avg


def test_console_line_eta_formatting(tmp_path):
    rec = _recorder(tmp_path)
    rec.step = 40
    rec.batch_time.update(2.0)     # global_avg 2 s/step
    rec.data_time.update(0.25)
    rec.update_loss_stats({"loss": 0.125})
    # eta = 2.0 * (3700 - 100) = 7200 s = 2:00:00
    line = rec.console_line(epoch=3, it=100, max_iter=3700, lr=1.25e-3)
    assert "eta: 2:00:00" in line
    assert "epoch: 3" in line and "step: 40" in line
    assert "loss: 0.1250 (0.1250)" in line
    assert "lr: 0.001250" in line
    assert "data: 0.2500" in line and "batch: 2.0000" in line
    assert "max_mem" not in line
    assert "max_mem: 123" in rec.console_line(0, 0, 1, 1e-3, max_mem_mb=123.4)


def test_console_line_eta_seconds_and_minutes(tmp_path):
    rec = _recorder(tmp_path)
    rec.batch_time.update(0.5)
    line = rec.console_line(epoch=0, it=0, max_iter=125, lr=1e-3)
    assert "eta: 0:01:02" in line  # 62.5 s floors to 0:01:02


def test_recorder_state_dict_preserves_smoothing(tmp_path):
    """A resumed recorder must continue eta/global averages, not reset."""
    rec = _recorder(tmp_path)
    rec.step, rec.epoch = 500, 3
    for i in range(30):
        rec.batch_time.update(0.1 + 0.001 * i)
        rec.data_time.update(0.01)
        rec.update_loss_stats({"loss": 1.0 / (i + 1), "psnr": 20.0 + i})

    state = rec.state_dict()
    rec2 = _recorder(tmp_path, record_dir=str(tmp_path / "rec2"))
    rec2.load_state_dict(state)

    assert rec2.step == 500 and rec2.epoch == 3
    assert rec2.batch_time.count == 30
    assert np.isclose(rec2.batch_time.global_avg, rec.batch_time.global_avg)
    assert np.isclose(rec2.batch_time.median, rec.batch_time.median)
    assert set(rec2.loss_stats) == {"loss", "psnr"}
    assert rec2.loss_stats["psnr"].count == 30
    # the console line (eta + global averages) is bit-identical
    assert rec2.console_line(3, 10, 100, 5e-4) == rec.console_line(
        3, 10, 100, 5e-4
    )


def test_recorder_legacy_state_dict_still_loads(tmp_path):
    """Pre-PR-1 checkpoints carry only {step, epoch} — they must load."""
    rec = _recorder(tmp_path)
    rec.load_state_dict({"step": 42, "epoch": 7})
    assert rec.step == 42 and rec.epoch == 7
    assert rec.batch_time.count == 0


def test_checkpoint_recorder_sidecar_roundtrip(tmp_path):
    """save_model/load_model carry the FULL recorder state through the
    sidecar while the orbax bundle keeps its fixed schema."""
    import jax

    from test_train import tiny_cfg
    from nerf_replication_tpu.datasets.procedural import generate_scene
    from nerf_replication_tpu.models import make_network
    from nerf_replication_tpu.train import make_train_state
    from nerf_replication_tpu.train.checkpoint import load_model, save_model

    root = str(tmp_path / "scene")
    generate_scene(root, scene="procedural", H=16, W=16, n_train=2, n_test=1)
    cfg = tiny_cfg(root)
    net = make_network(cfg)
    state, _ = make_train_state(cfg, net, jax.random.PRNGKey(0))
    state = state.replace(step=77)

    rec = _recorder(tmp_path)
    rec.step, rec.epoch = 77, 2
    for _ in range(5):
        rec.batch_time.update(0.2)
        rec.update_loss_stats({"loss": 0.5})

    model_dir = str(tmp_path / "ckpt")
    save_model(model_dir, state, epoch=2, recorder_state=rec.state_dict(),
               latest=True)
    assert os.path.exists(os.path.join(model_dir, "latest_recorder.json"))

    state2, _ = make_train_state(cfg, net, jax.random.PRNGKey(1))
    _, begin_epoch, rec_state = load_model(model_dir, state2)
    assert begin_epoch == 3
    rec2 = _recorder(tmp_path, record_dir=str(tmp_path / "rec3"))
    rec2.load_state_dict(rec_state)
    assert rec2.step == 77
    assert rec2.batch_time.count == 5
    assert np.isclose(rec2.batch_time.global_avg, 0.2)
    assert rec2.loss_stats["loss"].count == 5
