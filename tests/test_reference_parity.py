"""Direct numerical parity against the reference implementation.

The strongest behavioral-parity evidence available: run the reference's own
PyTorch modules (read-only from /root/reference, CPU) as oracles against this
framework's JAX implementations — identical weights, identical inputs,
outputs must match to float32 tolerance. Covers the four math surfaces every
PSNR depends on: the frequency encoder, the NeRF MLP forward, volume
compositing (raw2outputs), and deterministic inverse-CDF sampling
(sample_pdf).

Skipped wholesale when the reference tree or torch is unavailable.
"""

import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_REF = "/root/reference"

torch = pytest.importorskip("torch")
pytestmark = pytest.mark.skipif(
    not os.path.isdir(os.path.join(_REF, "src")),
    reason="reference tree not present",
)


@pytest.fixture(scope="module")
def ref():
    """Import the reference's modules with their import-time quirks tamed:
    src.config parses sys.argv and loads configs/default.yaml relative to
    the CWD at import, and volume_renderer imports ipdb unconditionally."""
    old_argv, old_cwd = sys.argv[:], os.getcwd()
    sys.argv = ["parity", "--cfg_file", "configs/nerf/lego.yaml"]
    os.chdir(_REF)
    sys.path.insert(0, _REF)
    if "ipdb" not in sys.modules:
        sys.modules["ipdb"] = types.ModuleType("ipdb")  # debug-only import
    if "imp" not in sys.modules:
        # `imp` was removed in Python 3.12; the reference only uses it in
        # make_network (plugin loading), which these oracle tests never call
        sys.modules["imp"] = types.ModuleType("imp")
    try:
        import src.models.encoding as ref_encoding
        import src.models.nerf.network as ref_network
        import src.models.nerf.renderer.volume_renderer as ref_renderer

        yield types.SimpleNamespace(
            encoding=ref_encoding,
            network=ref_network,
            renderer=ref_renderer,
        )
    finally:
        sys.argv = old_argv
        os.chdir(old_cwd)
        # don't leak the reference tree onto sys.path for later modules
        try:
            sys.path.remove(_REF)
        except ValueError:
            pass


def test_frequency_encoder_matches_reference(ref):
    """Same xyz → identical embedding, including the interleaved
    sin/cos-per-frequency ordering (ref freq.py:23-26)."""
    from nerf_replication_tpu.models.encoding.freq import frequency_encoder

    enc_t, out_dim_t = (
        lambda eo: (lambda x: eo.embed(x), eo.out_dim)
    )(ref.encoding.FreqEncoder(
        include_input=True, input_dims=3, max_freq_log2=9, num_freqs=10,
        log_sampling=True, periodic_fns=[torch.sin, torch.cos],
    ))
    enc_j, out_dim_j = frequency_encoder(
        input_dim=3, n_freqs=10, include_input=True, log_sampling=True
    )
    assert out_dim_j == out_dim_t == 63

    x = np.random.default_rng(0).uniform(-1.5, 1.5, (64, 3)).astype(np.float32)
    out_t = enc_t(torch.from_numpy(x)).numpy()
    out_j = np.asarray(enc_j(x))
    np.testing.assert_allclose(out_j, out_t, rtol=1e-6, atol=1e-6)


def _copy_torch_weights_to_flax(ref_mlp, D):
    """torch Linear [out, in] weights → flax kernel [in, out] param tree."""
    def pair(linear):
        return {
            "kernel": linear.weight.detach().numpy().T,
            "bias": linear.bias.detach().numpy(),
        }

    params = {
        f"pts_linear_{i}": pair(ref_mlp.pts_linears[i]) for i in range(D)
    }
    params["feature_linear"] = pair(ref_mlp.feature_linear)
    params["alpha_linear"] = pair(ref_mlp.alpha_linear)
    params["views_linear_0"] = pair(ref_mlp.views_linears[0])
    params["rgb_linear"] = pair(ref_mlp.rgb_linear)
    return params


def test_nerf_mlp_forward_matches_reference(ref):
    """The flagship MLP (D=8, W=256, skip at 4, viewdirs) with the
    reference's own randomly-initialized weights copied over: identical
    embedded inputs → identical raw (rgb, sigma) outputs. Output ordering
    (rgb first, alpha last — ref network.py:69-70) included."""
    import jax

    from nerf_replication_tpu.models.nerf.network import NeRFMLP

    D, W, in_ch, in_ch_views = 8, 256, 63, 27
    torch.manual_seed(0)
    ref_mlp = ref.network.NeRF(
        D=D, W=W, input_ch=in_ch, input_ch_views=in_ch_views,
        skips=[4], use_viewdirs=True,
    ).eval()

    ours = NeRFMLP(
        D=D, W=W, input_ch=in_ch, input_ch_views=in_ch_views,
        skips=(4,), use_viewdirs=True,
    )
    params = {"params": _copy_torch_weights_to_flax(ref_mlp, D)}

    x = np.random.default_rng(1).normal(
        size=(128, in_ch + in_ch_views)
    ).astype(np.float32)
    with torch.no_grad():
        out_t = ref_mlp(torch.from_numpy(x)).numpy()
    out_j = np.asarray(ours.apply(params, x))
    assert out_j.shape == out_t.shape == (128, 4)
    np.testing.assert_allclose(out_j, out_t, rtol=1e-5, atol=1e-5)


def test_raw2outputs_matches_reference(ref):
    """Identical raw network outputs + z_vals + ray dirs → identical
    composited rgb/depth/acc/weights (ref volume_renderer.py:20-81),
    both with and without the white background."""
    from nerf_replication_tpu.renderer.volume import raw2outputs

    rng = np.random.default_rng(2)
    n_rays, n_samples = 64, 48
    raw = rng.normal(size=(n_rays, n_samples, 4)).astype(np.float32)
    z_vals = np.sort(
        rng.uniform(2.0, 6.0, (n_rays, n_samples)).astype(np.float32), -1
    )
    rays_d = rng.normal(size=(n_rays, 3)).astype(np.float32)

    for white_bkgd in (False, True):
        rgb_t, depth_t, acc_t, w_t = ref.renderer.Renderer.raw2outputs(
            None,
            torch.from_numpy(raw),
            torch.from_numpy(z_vals),
            torch.from_numpy(rays_d),
            raw_noise_std=0,
            white_bkgd=white_bkgd,
        )
        rgb_j, depth_j, acc_j, w_j = raw2outputs(
            raw, z_vals, rays_d, key=None, raw_noise_std=0.0,
            white_bkgd=white_bkgd,
        )
        np.testing.assert_allclose(
            np.asarray(rgb_j), rgb_t.numpy(), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(depth_j), depth_t.numpy(), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(acc_j), acc_t.numpy(), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(w_j), w_t.numpy(), rtol=1e-5, atol=1e-6
        )


def test_sample_pdf_det_matches_reference(ref):
    """Deterministic (det=True) hierarchical sampling: same bins/weights →
    the same fine z samples (ref volume_renderer.py:82-151). The stochastic
    path cannot be compared directly (different RNGs); det exercises the
    whole CDF-inversion pipeline."""
    from nerf_replication_tpu.renderer.volume import sample_pdf

    rng = np.random.default_rng(3)
    n_rays, n_bins, n_fine = 32, 63, 128
    bins = np.sort(
        rng.uniform(2.0, 6.0, (n_rays, n_bins)).astype(np.float32), -1
    )
    weights = rng.uniform(0.0, 1.0, (n_rays, n_bins - 1)).astype(np.float32)

    out_t = ref.renderer.Renderer.sample_pdf(
        None, torch.from_numpy(bins), torch.from_numpy(weights),
        n_fine, det=True,
    ).numpy()
    out_j = np.asarray(sample_pdf(None, bins, weights, n_fine, det=True))
    np.testing.assert_allclose(out_j, out_t, rtol=1e-4, atol=1e-4)
