"""Ops intelligence (nerf_replication_tpu/obs: alerts, incidents,
capacity): multi-window burn-rate math is deterministic under a fake
clock, alerts hold through hysteresis instead of flapping, the incident
correlator assembles causal timelines (with exemplar-trace joins) from
synthetic telemetry, the capacity ledger's watermarks/rates match
hand-computed values, windowed SLO reads don't let lifetime history
dilute a fresh regression, and a live serve run with the full ops loop
attached stays at zero steady-state recompiles."""

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

from test_train import tiny_cfg

from nerf_replication_tpu.datasets.procedural import generate_scene
from nerf_replication_tpu.models import make_network
from nerf_replication_tpu.models.nerf.network import init_params
from nerf_replication_tpu.obs import (
    AlertEngine,
    AlertOptions,
    CapacityLedger,
    IncidentManager,
    MetricsRegistry,
    get_metrics,
    init_run,
    reset_metrics,
    validate_incident_dump,
    validate_row,
)
from nerf_replication_tpu.obs.emit import add_row_tap, remove_row_tap
from nerf_replication_tpu.serve import MicroBatcher, RenderEngine

NEAR, FAR = 2.0, 6.0


class FakeClock:
    """Injectable clock shared by engine/manager/ledger under test."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _rays(n: int, seed: int = 7) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return np.concatenate(
        [
            np.tile([0.0, 0.0, 4.0], (n, 1)),
            np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.15, (n, 3)),
        ],
        -1,
    ).astype(np.float32)


def _opts(**kw) -> AlertOptions:
    """Second-scale windows so one test drives whole alert lifetimes."""
    base = dict(fast_short_s=60.0, fast_long_s=600.0, slow_short_s=120.0,
                slow_long_s=1200.0, clear_hold_s=120.0)
    base.update(kw)
    return AlertOptions(**base)


# -- burn-rate math ----------------------------------------------------------


def test_burn_page_fires_on_both_windows_and_clears_after_hold():
    """20% error rate against a 99% objective burns at 20x: over the page
    threshold in BOTH fast windows -> page fires. Once traffic ages out
    of the windows the alert clears only after clear_hold_s of the
    condition staying false — and exactly once (no flap)."""
    clock = FakeClock(10_000.0)
    eng = AlertEngine(_opts(), clock=clock)
    eng.observe_window(attainment=0.8, deny_rate=0.0, n=100)
    view = eng.evaluate()
    assert "slo_burn_page" in view["firing"]
    assert "slo_burn_ticket" in view["firing"]  # 20x >= the 6x ticket bar
    assert "deny_burn_page" not in view["firing"]
    fire = next(t for t in eng.transitions
                if t["name"] == "slo_burn_page" and t["state"] == "firing")
    assert fire["severity"] == "page"
    assert fire["burn_fast"] == pytest.approx(20.0)
    assert fire["burn_slow"] == pytest.approx(20.0)
    assert fire["value"] == pytest.approx(0.2)

    # age everything out of the fast windows: condition false, but the
    # alert must HOLD for clear_hold_s before resolving
    clock.advance(700.0)
    eng.evaluate()
    assert "slo_burn_page" in eng.active()
    clock.advance(60.0)  # 60 < 120 hold
    eng.evaluate()
    assert "slo_burn_page" in eng.active()
    clock.advance(61.0)  # 121 >= 120 hold
    eng.evaluate()
    assert "slo_burn_page" not in eng.active()
    page_ts = [t["state"] for t in eng.transitions
               if t["name"] == "slo_burn_page"]
    assert page_ts == ["firing", "resolved"]
    # alert_seconds spans first-fire -> resolve (10000 -> 10821)
    assert eng.alert_seconds["slo_burn_page"] == pytest.approx(821.0)


def test_long_window_guards_against_a_short_blip():
    """A burst that saturates the short window but is diluted by healthy
    history in the long window must NOT page — the multi-window contract
    (one bad GC pause is not an incident)."""
    clock = FakeClock(50_000.0)
    eng = AlertEngine(_opts(), clock=clock)
    eng.observe_window(attainment=1.0, deny_rate=0.0, n=1000)
    clock.advance(550.0)  # healthy history leaves the 60s short window
    eng.observe_window(attainment=0.0, deny_rate=0.0, n=50)
    view = eng.evaluate()
    # short window burns at 100x, long at 50/1050/0.01 ~ 4.8x < 14.4x
    cond = next(a for a in view["alerts"] if a["name"] == "slo_burn_page")
    assert cond["burn_fast"] == pytest.approx(100.0)
    assert cond["burn_slow"] < 14.4
    assert "slo_burn_page" not in view["firing"]
    assert "slo_burn_ticket" not in view["firing"]


def test_deny_burn_is_a_separate_signal():
    clock = FakeClock(1_000.0)
    eng = AlertEngine(_opts(), clock=clock)
    eng.observe_window(attainment=1.0, deny_rate=0.5, n=100)
    view = eng.evaluate()
    assert "deny_burn_page" in view["firing"]
    assert "slo_burn_page" not in view["firing"]


def test_no_traffic_no_alert():
    """min_count: empty windows never fire (0/0 is not an outage)."""
    eng = AlertEngine(_opts(), clock=FakeClock(1_000.0))
    assert eng.evaluate()["firing"] == []


# -- direct conditions -------------------------------------------------------


def test_breaker_row_pages_and_clears_with_listener_and_rows():
    """A breaker-open telemetry row pages immediately (naming the point),
    a closed row clears it; both transitions emit schema-valid alert
    rows and reach listeners."""
    clock = FakeClock(2_000.0)
    eng = AlertEngine(_opts(clear_hold_s=0.0), clock=clock)
    events, rows = [], []
    eng.add_listener(events.append)
    add_row_tap(rows.append)
    try:
        eng._on_row({"kind": "breaker", "point": "serve.dispatch",
                     "state": "open", "failures": 5})
        view = eng.evaluate()
        assert "breaker_open" in view["firing"]
        fire = next(t for t in eng.transitions
                    if t["name"] == "breaker_open")
        assert fire["severity"] == "page"
        assert fire["detail"] == "serve.dispatch"
        assert eng.healthz_block()["n_firing"] == 1
        eng._on_row({"kind": "breaker", "point": "serve.dispatch",
                     "state": "closed", "failures": 0})
        eng.evaluate()
        assert "breaker_open" not in eng.active()
    finally:
        remove_row_tap(rows.append)
        eng.remove_listener(events.append)
    alert_rows = [r for r in rows if r.get("kind") == "alert"]
    assert [r["state"] for r in alert_rows] == ["firing", "resolved"]
    for r in alert_rows:
        assert validate_row(r) == [], r
    assert [e["state"] for e in events] == ["firing", "resolved"]


def test_orphan_span_rate_tickets_after_grace():
    """A child whose parent never arrives is judged an orphan only after
    the grace period; a parent that does arrive never counts."""
    clock = FakeClock(3_000.0)
    eng = AlertEngine(_opts(orphan_grace_s=10.0, clear_hold_s=0.0),
                      clock=clock)
    eng._on_row({"kind": "span", "span_id": "c1", "parent_id": "gone1"})
    eng._on_row({"kind": "span", "span_id": "c2", "parent_id": "p1"})
    eng._on_row({"kind": "span", "span_id": "p1"})  # parent arrives
    view = eng.evaluate()
    assert "orphan_spans" not in view["firing"]  # still inside grace
    clock.advance(10.0)
    view = eng.evaluate()
    assert "orphan_spans" in view["firing"]
    cond = next(a for a in view["alerts"] if a["name"] == "orphan_spans")
    assert cond["value"] == pytest.approx(0.5)  # 1 orphan of 2 judged
    clock.advance(70.0)  # judged counts age out of the 60s window
    eng.evaluate()
    assert "orphan_spans" not in eng.active()


def test_staging_thrash_tickets_on_demote_repromote_churn():
    clock = FakeClock(4_000.0)
    eng = AlertEngine(_opts(thrash_per_min_max=6.0, clear_hold_s=0.0),
                      clock=clock)
    for _ in range(7):
        eng._on_row({"kind": "scene_evict", "scene": "lego",
                     "reason": "demoted"})
        eng._on_row({"kind": "scene_load", "scene": "lego",
                     "source": "staging"})
    view = eng.evaluate()
    assert "staging_thrash" in view["firing"]  # min(7,7)/1min = 7 >= 6
    clock.advance(120.0)
    eng.evaluate()
    assert "staging_thrash" not in eng.active()


def test_alert_options_from_cfg_roundtrip():
    class _NS:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    cfg = _NS(obs=_NS(alerts=_NS(
        slo_objective=0.95, deny_objective=0.9, fast_burn=10.0,
        slow_burn=5.0, fast_short_s=10.0, fast_long_s=20.0,
        slow_short_s=30.0, slow_long_s=40.0, clear_hold_s=7.0,
        orphan_grace_s=3.0, orphan_rate_max=0.1, thrash_per_min_max=2.0)))
    opt = AlertOptions.from_cfg(cfg)
    assert opt.slo_objective == 0.95
    assert opt.fast_burn == 10.0
    assert opt.clear_hold_s == 7.0
    assert opt.thrash_per_min_max == 2.0


# -- incident correlation ----------------------------------------------------


def _feed_timeline(mgr: IncidentManager, t0: float) -> None:
    """Synthetic telemetry: a fault, its retry/breaker fallout, an
    evidence-linked scale decision, residency moves, a tenant denial —
    plus spans for the exemplar trace and for an unrelated one."""
    mgr._on_row({"kind": "fault", "point": "serve.flush",
                 "fault": "io_error", "mode": "count", "t": t0})
    mgr._on_row({"kind": "retry", "point": "serve.flush", "attempt": 1,
                 "outcome": "success", "t": t0 + 1})
    mgr._on_row({"kind": "breaker", "point": "serve.dispatch",
                 "state": "open", "failures": 5, "t": t0 + 2})
    mgr._on_row({"kind": "span", "trace_id": "abc123", "span_id": "s1",
                 "name": "render", "dur_s": 0.31, "status": "ok",
                 "t": t0 + 3})
    mgr._on_row({"kind": "span", "trace_id": "zzz999", "span_id": "s2",
                 "name": "render", "dur_s": 0.02, "status": "ok",
                 "t": t0 + 3})
    mgr._on_row({"kind": "scale_decision", "action": "scale_out",
                 "reason": "attainment", "n_replicas": 2,
                 "attainment": 0.5,
                 "evidence": {"exemplar_trace_ids": ["abc123"]},
                 "t": t0 + 4})
    mgr._on_row({"kind": "scene_load", "scene": "lego", "source": "disk",
                 "load_s": 0.2, "t": t0 + 5})
    mgr._on_row({"kind": "tenant_admit", "tenant": "bronze",
                 "decision": "deny", "reason": "rate", "t": t0 + 6})


def test_incident_assembles_timeline_faults_and_exemplar_traces(tmp_path):
    clock = FakeClock(5_000.0)
    mgr = IncidentManager(str(tmp_path), clock=clock, lookback_s=300.0,
                          coalesce_s=30.0, quiet_s=60.0)
    _feed_timeline(mgr, clock.t)
    clock.advance(10.0)
    mgr.on_alert({"name": "slo_burn_page", "state": "firing",
                  "severity": "page", "value": 0.2, "threshold": 14.4})
    assert len(mgr.incidents) == 1
    inc = mgr.incidents[0]
    assert inc["status"] == "open"
    assert inc["trigger"] == "alert"
    assert inc["alerts"] == ["slo_burn_page"]
    assert inc["fault_points"] == ["serve.flush:io_error"]
    assert inc["trace_ids"] == ["abc123"]
    kinds = {e["kind"] for e in inc["timeline"]}
    assert {"fault", "retry", "breaker", "scale_decision", "scene_load",
            "tenant_admit", "span"} <= kinds
    # only the exemplar trace's spans make the timeline
    span_evs = [e for e in inc["timeline"] if e["kind"] == "span"]
    assert [e["trace_id"] for e in span_evs] == ["abc123"]
    ts = [e["t"] for e in inc["timeline"]]
    assert ts == sorted(ts)
    # atomic dumps: schema-valid json + human-readable markdown
    assert validate_incident_dump(inc["path"]) == []
    md = inc["path"][:-len(".json")] + ".md"
    assert "serve.flush:io_error" in open(md).read()


def test_incident_lifecycle_coalesce_mitigate_resolve(tmp_path):
    """Two alerts inside coalesce_s are ONE incident; the last alert
    clearing mitigates it; a quiet period resolves it. Every transition
    emits a schema-valid incident row."""
    clock = FakeClock(5_000.0)
    rows = []
    add_row_tap(rows.append)
    try:
        mgr = IncidentManager(str(tmp_path), clock=clock, lookback_s=300.0,
                              coalesce_s=30.0, quiet_s=60.0)
        mgr.on_alert({"name": "slo_burn_page", "state": "firing",
                      "severity": "page", "value": 0.2, "threshold": 14.4})
        clock.advance(20.0)
        mgr.on_alert({"name": "deny_burn_page", "state": "firing",
                      "severity": "page", "value": 0.5, "threshold": 14.4})
        assert len(mgr.incidents) == 1  # coalesced, not a second incident
        inc = mgr.incidents[0]
        assert inc["alerts"] == ["slo_burn_page", "deny_burn_page"]
        clock.advance(10.0)
        mgr.on_alert({"name": "slo_burn_page", "state": "resolved"})
        assert inc["status"] == "open"  # deny still firing
        mgr.on_alert({"name": "deny_burn_page", "state": "resolved"})
        assert inc["status"] == "mitigated"
        assert inc["mitigated_t"] == pytest.approx(clock.t)
        clock.advance(60.0)
        mgr.sweep()
        assert inc["status"] == "resolved"
        assert inc["resolved_t"] == pytest.approx(clock.t)
        assert validate_incident_dump(inc["path"]) == []
    finally:
        remove_row_tap(rows.append)
    inc_rows = [r for r in rows if r.get("kind") == "incident"]
    assert [r["status"] for r in inc_rows] == ["open", "mitigated",
                                               "resolved"]
    for r in inc_rows:
        assert validate_row(r) == [], r


def test_open_on_fault_and_force_resolve(tmp_path):
    """The chaos harness contract: injected fault rows open incidents
    themselves (coalescing a storm into one), and resolve_open closes
    whatever recovery checks left behind."""
    clock = FakeClock(7_000.0)
    mgr = IncidentManager(str(tmp_path), clock=clock, coalesce_s=30.0,
                          quiet_s=60.0, open_on_fault=True)
    mgr._on_row({"kind": "fault", "point": "serve.flush",
                 "fault": "io_error", "t": clock.t})
    clock.advance(5.0)
    mgr._on_row({"kind": "fault", "point": "data.load",
                 "fault": "io_error", "t": clock.t})
    assert len(mgr.incidents) == 1  # storm coalesced
    inc = mgr.incidents[0]
    assert inc["trigger"] == "fault"
    assert set(inc["fault_points"]) == {"serve.flush:io_error",
                                        "data.load:io_error"}
    assert mgr.resolve_open("recovery checks passed") == 1
    assert inc["status"] == "resolved"
    assert "recovery checks passed" in inc["detail"]
    assert validate_incident_dump(inc["path"]) == []
    assert mgr.resolve_open() == 0  # idempotent


def test_quiet_sweep_automation(tmp_path):
    """An alertless open incident mitigates after quiet_s and resolves
    after another — no operator in the loop."""
    clock = FakeClock(8_000.0)
    mgr = IncidentManager(str(tmp_path), clock=clock, coalesce_s=5.0,
                          quiet_s=60.0, open_on_fault=True)
    mgr._on_row({"kind": "fault", "point": "p", "fault": "f",
                 "t": clock.t})
    inc = mgr.incidents[0]
    clock.advance(59.0)
    mgr.sweep()
    assert inc["status"] == "open"
    clock.advance(1.0)
    mgr.sweep()
    assert inc["status"] == "mitigated"
    clock.advance(60.0)
    mgr.sweep()
    assert inc["status"] == "resolved"


def test_lookback_bounds_the_timeline(tmp_path):
    clock = FakeClock(9_000.0)
    mgr = IncidentManager(str(tmp_path), clock=clock, lookback_s=50.0)
    mgr._on_row({"kind": "fault", "point": "old", "fault": "f",
                 "t": clock.t})
    clock.advance(100.0)
    mgr._on_row({"kind": "fault", "point": "new", "fault": "f",
                 "t": clock.t})
    mgr.on_alert({"name": "a", "state": "firing", "severity": "page"})
    inc = mgr.incidents[0]
    assert inc["fault_points"] == ["new:f"]  # the stale fault aged out
    assert all(e["t"] >= clock.t - 50.0 for e in inc["timeline"])


# -- capacity ledger ---------------------------------------------------------


def test_capacity_ledger_matches_hand_computed_accounting():
    clock = FakeClock(1_000.0)
    lg = CapacityLedger(replica="r0", window_s=100.0, clock=clock)
    # watermarks: peaks latch, currents track the last report
    lg.note_residency(100, 200)
    lg.note_residency(50, 75)
    # heat: 4 lego requests x 512 rays, 2 ship x 256
    for _ in range(4):
        lg.note_request("lego", 512)
    for _ in range(2):
        lg.note_request("ship", 256)
    # churn + row-carried residency fallback
    lg._on_row({"kind": "scene_load", "scene": "ship", "source": "staging"})
    lg._on_row({"kind": "scene_load", "scene": "fern", "source": "disk",
                "resident_bytes": 80, "staging_bytes": 75})
    lg._on_row({"kind": "span", "stage": "device", "family": "nerf",
                "dur_s": 3.0})
    lg._on_row({"kind": "span", "stage": "device", "name": "prop",
                "dur_s": 1.0})
    lg._on_row({"kind": "span", "stage": "host", "name": "ignored",
                "dur_s": 99.0})
    v = lg.view()
    assert v["hbm_bytes"] == 80 and v["hbm_peak_bytes"] == 100
    assert v["staging_bytes"] == 75 and v["staging_peak_bytes"] == 200
    assert v["scenes"]["lego"]["requests_per_s"] == pytest.approx(0.04)
    assert v["scenes"]["lego"]["rays_per_s"] == pytest.approx(20.5)
    assert v["scenes"]["ship"]["requests_per_s"] == pytest.approx(0.02)
    assert v["scenes"]["ship"]["rays_per_s"] == pytest.approx(5.1)
    assert v["scenes"]["ship"]["repromotions"] == 1
    assert v["scenes"]["fern"]["cold_loads"] == 1
    assert v["requests_per_s"] == pytest.approx(0.06)
    assert v["rays_per_s"] == pytest.approx(25.6)
    assert v["device_share"] == {"nerf": 0.75, "prop": 0.25}
    # rates age out of the window; counters and peaks persist
    clock.advance(150.0)
    v2 = lg.view()
    assert v2["requests_per_s"] == 0.0
    assert v2["scenes"]["fern"]["cold_loads"] == 1
    assert v2["hbm_peak_bytes"] == 100


def test_capacity_snapshot_row_and_gauges(tmp_path):
    reset_metrics()
    clock = FakeClock(2_000.0)
    lg = CapacityLedger(replica="r7", window_s=100.0, clock=clock)
    lg.note_residency(1234, 0)
    lg.note_request("lego", 128)
    rows = []
    add_row_tap(rows.append)
    try:
        lg.snapshot()
    finally:
        remove_row_tap(rows.append)
    snap = next(r for r in rows if r.get("kind") == "capacity_snapshot")
    assert validate_row(snap) == [], snap
    assert snap["replica"] == "r7"
    assert snap["hbm_peak_bytes"] == 1234
    assert "lego" in snap["scenes"]
    text = get_metrics().render_prometheus()
    assert 'capacity_scene_requests_per_s{scene="lego"}' in text
    assert "capacity_hbm_peak_bytes 1234" in text
    # no local replica label — the fleet merge injects it
    assert 'replica="' not in text
    assert lg.n_snapshots == 1


# -- windowed SLO reads (the dilution fix) -----------------------------------


def test_windowed_slo_view_does_not_dilute_a_fresh_regression():
    clock = FakeClock(0.0)
    reg = MetricsRegistry(clock=clock)
    clock.t = 1_000.0
    for _ in range(200):
        reg.observe("serve_request_latency_seconds", 0.05)
    reg.counter("serve_requests_total", 200.0, status="ok")
    reg.counter("serve_breaker_transitions_total", 1.0, state="open")
    clock.t = 1_400.0
    for _ in range(50):
        reg.observe("serve_request_latency_seconds", 0.9)
    reg.counter("serve_requests_total", 50.0, status="ok")
    reg.counter("serve_requests_total", 10.0, status="timeout")

    life = reg.slo_view(0.25)
    win = reg.slo_view(0.25, window_s=60.0)
    # lifetime read: 200 healthy obs dilute the regression to 0.8
    assert life["attainment"] == pytest.approx(0.8)
    # windowed read sees only the last minute: total outage, no dilution
    assert win["attainment"] == pytest.approx(0.0)
    assert win["window_s"] == 60.0
    assert win["requests"] == 60
    assert win["timeout_rate"] == pytest.approx(round(10 / 60, 4))
    # the hour-old breaker open is lifetime history, not current state
    assert life["breaker_opens"] == 1
    assert win["breaker_opens"] == 0
    # windowed counter primitive agrees
    assert reg.window_counter("serve_requests_total", 60.0,
                              status="ok") == 50.0
    assert reg.window_counter("serve_requests_total", 10_000.0) == 260.0


# -- live serve run with the full ops loop -----------------------------------


@pytest.fixture(scope="module")
def ops_setup(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("scene_alerts"))
    generate_scene(root, scene="procedural", H=16, W=16, n_train=4, n_test=1)
    cfg = tiny_cfg(
        root,
        ["task_arg.render_step_size", "0.25",
         "task_arg.max_march_samples", "16",
         "task_arg.march_chunk_size", "64",
         "serve.buckets", "[128]",
         "serve.max_batch_rays", "128",
         "serve.max_delay_ms", "5.0",
         "serve.request_timeout_s", "5.0"],
    )
    network = make_network(cfg)
    params = init_params(network, jax.random.PRNGKey(0))
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)
    grid = np.zeros((16, 16, 16), bool)
    grid[4:12, 4:12, 4:12] = True
    engine = RenderEngine(cfg, network, params, near=NEAR, far=FAR,
                          grid=grid, bbox=bbox)
    return cfg, engine


def test_serve_with_ops_loop_zero_steady_recompiles(ops_setup, tmp_path):
    """The ops loop (alert engine + incident correlator + capacity
    ledger, all row-tapped) rides a live serve stream without triggering
    a single steady-state recompile; a clean run fires no alerts and
    opens no incidents, and every emitted row validates."""
    cfg, engine = ops_setup
    assert AlertOptions.from_cfg(cfg).fast_burn == 14.4  # cfg block wired
    engine.render_request(_rays(64), NEAR, FAR, emit=False)  # warm
    path = str(tmp_path / "telemetry.jsonl")
    emitter = init_run(cfg, component="serve_ops_test", path=path)
    alerts = AlertEngine(_opts(clear_hold_s=0.0), slo_target_s=30.0,
                         replica="r0").attach()
    incidents = IncidentManager(str(tmp_path)).attach()
    alerts.add_listener(incidents.on_alert)
    capacity = CapacityLedger(replica="r0", window_s=60.0).attach()
    before = engine.tracker.total_compiles()
    try:
        clock = FakeClock()
        batcher = MicroBatcher(engine, clock=clock, start=False)
        futures = [batcher.submit(_rays(30 + 7 * i), NEAR, FAR)
                   for i in range(5)]
        while batcher.queue_depth():
            clock.advance(1.0)
            batcher.pump()
            alerts.evaluate()
        for f in futures:
            f.result(timeout=5.0)
        capacity.snapshot()
        alerts.evaluate()
    finally:
        capacity.detach()
        alerts.remove_listener(incidents.on_alert)
        incidents.detach()
        alerts.detach()
        emitter.close()
        init_run(cfg, component="noop", path=str(tmp_path / "t2.jsonl")).close()
    assert engine.tracker.total_compiles() == before
    # clean run: nothing fired, nothing opened, overhead accounted
    assert alerts.active() == []
    assert alerts.transitions == []
    assert incidents.incidents == []
    assert alerts.self_s < 1.0
    # the ledger saw the request stream through its row tap
    v = capacity.view(now=time.monotonic())
    assert v["requests_per_s"] > 0.0
    assert v["rays_per_s"] > 0.0
    rows = [json.loads(line) for line in open(path)]
    kinds = {r["kind"] for r in rows}
    assert "serve_request" in kinds
    assert "capacity_snapshot" in kinds
    for r in rows:
        assert validate_row(r) == [], r
