"""Occupancy-grid subsystem tests: bake math, artifact round-trip, world→voxel
indexing, and equivalence of the accelerated (ESS+ERT) renderer against both
an all-occupied dense march and the reference's sequential compositing
semantics."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nerf_replication_tpu.datasets.procedural import generate_scene
from nerf_replication_tpu.models import make_network
from nerf_replication_tpu.models.nerf.network import init_params
from nerf_replication_tpu.renderer import make_renderer
from nerf_replication_tpu.renderer.accelerated import (
    MarchOptions,
    march_rays_accelerated,
)
from nerf_replication_tpu.renderer.occupancy import (
    PYRAMID_FACTORS,
    PYRAMID_VERSION,
    bake_occupancy_grid,
    build_pyramid,
    coarse_from_grid,
    load_occupancy_grid,
    load_occupancy_pyramid,
    occupancy_stats,
    pyramid_stats,
    save_occupancy_grid,
    voxel_sample_points,
    world_to_voxel,
)

from test_train import tiny_cfg

pytestmark = []


@pytest.fixture(scope="module")
def scene_root(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("scene_occ"))
    generate_scene(root, scene="procedural", H=16, W=16, n_train=4, n_test=2)
    return root


@pytest.fixture(scope="module")
def setup(scene_root):
    cfg = tiny_cfg(
        scene_root,
        ["task_arg.occupancy_grid_res", "16",
         "task_arg.occupancy_grid_batch_size", "512",
         "task_arg.occupancy_grid_threshold", "0.5",
         "task_arg.render_step_size", "0.25",
         "task_arg.max_march_samples", "16",
         "task_arg.march_chunk_size", "64"],
    )
    network = make_network(cfg)
    params = init_params(network, jax.random.PRNGKey(0))
    return cfg, network, params


def test_voxel_sample_points_geometry():
    bbox = np.array([[-1.0, -1.0, -1.0], [1.0, 1.0, 1.0]], np.float32)
    pts = voxel_sample_points(bbox, 4)
    assert pts.shape == (64, 8, 3)
    # first voxel's first sub-sample is the bbox corner; its last sub-sample
    # spans exactly one voxel
    np.testing.assert_allclose(pts[0, 0], [-1.0, -1.0, -1.0])
    np.testing.assert_allclose(pts[0, -1], [-0.5, -0.5, -0.5])
    # last voxel's last sub-sample reaches the opposite corner
    np.testing.assert_allclose(pts[-1, -1], [1.0, 1.0, 1.0])


def test_world_to_voxel_clamps_and_indexes():
    bbox = jnp.asarray([[-1.0, -1.0, -1.0], [1.0, 1.0, 1.0]], jnp.float32)
    pts = jnp.asarray(
        [[-1.0, -1.0, -1.0], [1.0, 1.0, 1.0], [5.0, -5.0, 0.0], [0.0, 0.0, 0.0]]
    )
    idx = np.asarray(world_to_voxel(pts, bbox, 8))
    np.testing.assert_array_equal(idx[0], [0, 0, 0])
    np.testing.assert_array_equal(idx[1], [7, 7, 7])
    np.testing.assert_array_equal(idx[2], [7, 0, 4])  # clamped then scaled
    # center point lands in the voxel whose bake-layout cell contains it
    np.testing.assert_array_equal(idx[3], [4, 4, 4])
    assert (idx >= 0).all() and (idx < 8).all()


def test_world_to_voxel_aligns_with_bake_layout():
    """A point inside baked voxel i (cell [lo + i·vs, lo + (i+1)·vs)) must
    map back to index i — the misalignment the reference inherits from
    scaling by resolution-1 (volume_renderer.py:264) is fixed here."""
    bbox_np = np.array([[-1.5, -1.5, -1.5], [1.5, 1.5, 1.5]], np.float32)
    bbox = jnp.asarray(bbox_np)
    res = 16
    vs = (bbox_np[1] - bbox_np[0]) / res
    rng = np.random.default_rng(0)
    ijk = rng.integers(0, res, (50, 3))
    pts = bbox_np[0] + (ijk + rng.uniform(0.01, 0.99, (50, 3))) * vs
    idx = np.asarray(world_to_voxel(jnp.asarray(pts, jnp.float32), bbox, res))
    np.testing.assert_array_equal(idx, ijk)


def test_bake_and_roundtrip(tmp_path, setup):
    cfg, network, params = setup
    grid = bake_occupancy_grid(params, network, cfg)
    assert grid.shape == (16, 16, 16) and grid.dtype == np.bool_

    stats = occupancy_stats(grid)
    assert 0 <= stats["occupancy_pct"] <= 100

    path = str(tmp_path / "grid.npz")
    save_occupancy_grid(path, grid, cfg.train_dataset.scene_bbox, 0.5)
    loaded, bbox = load_occupancy_grid(path)
    np.testing.assert_array_equal(loaded, grid)
    assert bbox.shape == (2, 3)


def test_pyramid_roundtrip_and_legacy_flat_upgrade(tmp_path, setup):
    """The versioned artifact round-trips its baked coarse levels; a legacy
    flat-grid .npz (no ``pyramid_version`` key) upgrades transparently by
    rebuilding the pyramid from the fine grid, and the flat reader keeps
    working against both layouts."""
    cfg, network, params = setup
    grid = bake_occupancy_grid(params, network, cfg)
    bbox = cfg.train_dataset.scene_bbox

    path = str(tmp_path / "pyr.npz")
    save_occupancy_grid(path, grid, bbox, 0.5)
    with np.load(path) as z:
        assert int(z["pyramid_version"]) == PYRAMID_VERSION
        assert tuple(np.asarray(z["pyramid_factors"])) == PYRAMID_FACTORS
    levels, bbox_l = load_occupancy_pyramid(path)
    assert len(levels) == 1 + len(PYRAMID_FACTORS)
    np.testing.assert_array_equal(levels[0], grid)
    assert bbox_l.shape == (2, 3)
    for f, lv in zip(PYRAMID_FACTORS, levels[1:]):
        assert lv.shape == tuple(-(-s // f) for s in grid.shape)
        assert lv.dtype == np.bool_
        # baked coarse levels are bit-identical to the in-graph derivation
        # the march executables run (coarse_from_grid) — the parity
        # contract between artifact and live-NGP traversal
        np.testing.assert_array_equal(
            np.asarray(coarse_from_grid(jnp.asarray(grid), f)), lv
        )

    # legacy layout: the exact keys pre-pyramid save_occupancy_grid wrote
    legacy = str(tmp_path / "legacy.npz")
    np.savez_compressed(
        legacy, grid=grid, bbox=np.asarray(bbox, np.float32),
        threshold=np.float32(0.5),
    )
    levels2, _ = load_occupancy_pyramid(legacy)
    for a, b in zip(levels2, levels):
        np.testing.assert_array_equal(a, b)
    g_flat, _ = load_occupancy_grid(legacy)
    np.testing.assert_array_equal(g_flat, grid)
    g_flat2, _ = load_occupancy_grid(path)
    np.testing.assert_array_equal(g_flat2, grid)

    stats = pyramid_stats(levels)
    # any-reduce can only grow the occupied fraction per level
    assert (
        stats["level_0_occ"]
        <= stats["level_1_occ"]
        <= stats["level_2_occ"]
    )


def test_pyramid_superset_holds_for_nondivisible_resolution():
    """R=9 doesn't divide either factor: the False-pad must land past the
    +bbox face, never stealing a real voxel's parent — every fine-occupied
    voxel's parent cell stays occupied, and no parent is occupied without
    at least one occupied child."""
    rng = np.random.default_rng(0)
    g = rng.random((9, 9, 9)) < 0.2
    levels = build_pyramid(g)
    assert levels[1].shape == (5, 5, 5) and levels[2].shape == (3, 3, 3)
    occ = np.argwhere(g)
    for f, lv in zip(PYRAMID_FACTORS, levels[1:]):
        parents = occ // f
        assert lv[parents[:, 0], parents[:, 1], parents[:, 2]].all()
        # tightness: occupied parents are exactly the occupied children's
        n_parents = len(np.unique(parents, axis=0))
        assert int(lv.sum()) == n_parents


def test_bake_matches_direct_density_query(setup):
    """Golden: a voxel is occupied iff ANY of its 2x2x2 sub-sample densities
    (coarse head, zero viewdirs) exceeds the threshold."""
    cfg, network, params = setup
    grid = bake_occupancy_grid(params, network, cfg)
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)
    pts = voxel_sample_points(bbox, 16)

    rng = np.random.default_rng(1)
    for flat_idx in rng.choice(16**3, 20, replace=False):
        p = jnp.asarray(pts[flat_idx])[None]  # [1, 8, 3]
        raw = network.apply(params, p, jnp.zeros((1, 3)), model="coarse")
        sigma = np.asarray(jax.nn.relu(raw[..., 3]))
        expected = bool((sigma > 0.5).any())
        i, j, k = np.unravel_index(flat_idx, (16, 16, 16))
        assert grid[i, j, k] == expected


def _sequential_march_reference(apply_fn, rays, near, far, grid, bbox, opt):
    """Literal NumPy transcription of the reference's per-step compositing
    loop (volume_renderer.py:298-341) as a correctness oracle."""
    rays_o, rays_d = np.asarray(rays[:, :3]), np.asarray(rays[:, 3:])
    n = rays_o.shape[0]
    res = grid.shape[0]
    rgb_map = np.zeros((n, 3))
    depth = np.zeros(n)
    acc = np.zeros(n)
    trans = np.ones(n)
    alive = np.ones(n, bool)
    grid_np = np.asarray(grid)

    t = near
    while t < far - 1e-9:
        pts = rays_o + t * rays_d
        idx = np.asarray(world_to_voxel(jnp.asarray(pts), bbox, res))
        occ = grid_np[idx[:, 0], idx[:, 1], idx[:, 2]] & alive
        if occ.any():
            vd = rays_d / np.linalg.norm(rays_d, axis=-1, keepdims=True)
            raw = np.asarray(
                apply_fn(jnp.asarray(pts[occ])[:, None, :], jnp.asarray(vd[occ]),
                         "fine")
            )[:, 0]
            rgb = 1.0 / (1.0 + np.exp(-raw[:, :3]))
            sigma = np.maximum(raw[:, 3], 0.0)
            dists = opt.step_size * np.linalg.norm(rays_d[occ], axis=-1)
            alpha = 1.0 - np.exp(-sigma * dists)
            T = trans[occ]
            rgb_map[occ] += (T * alpha)[:, None] * rgb
            acc[occ] += T * alpha
            depth[occ] += T * alpha * t
            trans[occ] *= 1.0 - alpha
            newly_dead = trans < opt.transmittance_threshold
            alive &= ~newly_dead
        t += opt.step_size

    if opt.white_bkgd:
        rgb_map += (1.0 - acc)[:, None]
    return rgb_map, depth, acc


@pytest.mark.parametrize("step_size", [0.25, 0.3])
def test_accelerated_march_matches_sequential_reference(setup, step_size):
    """The static-shape two-phase march must reproduce the reference's
    sequential alive-ray loop bit-for-bit in float tolerance (K large enough
    to hold every occupied step). step 0.3 doesn't divide [2, 6] — covers
    the ceil step-count semantics of torch.arange(near, far, step)."""
    cfg, network, params = setup
    bbox = jnp.asarray(cfg.train_dataset.scene_bbox, jnp.float32)
    rng = np.random.default_rng(2)
    grid = jnp.asarray(rng.random((16, 16, 16)) < 0.3)

    opt = MarchOptions(
        step_size=step_size, transmittance_threshold=1e-4, max_samples=17,
        white_bkgd=True, chunk_size=64,
    )
    # rays through the volume from the procedural camera distance
    n = 32
    origins = np.tile([0.0, 0.0, 4.0], (n, 1)) + rng.normal(0, 0.1, (n, 3))
    dirs = np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.15, (n, 3))
    rays = jnp.asarray(
        np.concatenate([origins, dirs], -1).astype(np.float32)
    )

    apply_fn = lambda p, v, model: network.apply(params, p, v, model=model)  # noqa: E731
    out = march_rays_accelerated(apply_fn, rays, 2.0, 6.0, grid, bbox, opt)
    ref_rgb, ref_depth, ref_acc = _sequential_march_reference(
        apply_fn, np.asarray(rays), 2.0, 6.0, grid, bbox, opt
    )

    np.testing.assert_allclose(np.asarray(out["rgb_map_f"]), ref_rgb, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out["acc_map_f"]), ref_acc, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out["depth_map_f"]), ref_depth, atol=2e-3)


def test_renderer_accelerated_fallback_and_grid_path(tmp_path, setup):
    """Renderer API: no grid → vanilla fallback; with grid → accelerated
    output keys; both full-image entry points produce finite images."""
    cfg, network, params = setup
    renderer = make_renderer(cfg, network)
    rng = np.random.default_rng(3)
    rays = np.concatenate(
        [
            np.tile([0.0, 0.0, 4.0], (100, 1)),
            np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.1, (100, 3)),
        ],
        -1,
    ).astype(np.float32)
    batch = {"rays": jnp.asarray(rays), "near": 2.0, "far": 6.0}

    assert not renderer.load_occupancy_grid(str(tmp_path / "missing.npz"))
    out_slow = renderer.render_accelerated(params, batch)
    assert "rgb_map_f" in out_slow  # fell back to the full coarse+fine path

    grid = bake_occupancy_grid(params, network, cfg)
    path = str(tmp_path / "grid.npz")
    save_occupancy_grid(path, grid, cfg.train_dataset.scene_bbox, 0.5)
    assert renderer.load_occupancy_grid(path)
    out_fast = renderer.render_accelerated(params, batch)
    assert out_fast["rgb_map_f"].shape == (100, 3)
    assert np.isfinite(np.asarray(out_fast["rgb_map_f"])).all()


def test_eval_march_budget_decouples_from_training(setup):
    """``task_arg.eval_render_step_size`` / ``eval_max_march_samples``
    override the shared march keys for EVAL executables only (VERDICT r4
    #3: the NGP H=400 trail was quality-capped by rendering through the
    training budget), falling back to the training values when unset."""
    from nerf_replication_tpu.renderer.accelerated import MarchOptions

    cfg, network, params = setup
    base = MarchOptions.from_cfg(cfg)
    assert MarchOptions.eval_from_cfg(cfg) == base  # unset ⇒ fallback

    cfg2 = cfg.clone()
    cfg2.defrost()
    cfg2.task_arg.render_step_size = 0.01
    cfg2.task_arg.max_march_samples = 64
    cfg2.task_arg.eval_render_step_size = 0.005
    cfg2.task_arg.eval_max_march_samples = 256
    cfg2.freeze()
    train_opts = MarchOptions.from_cfg(cfg2)
    eval_opts = MarchOptions.eval_from_cfg(cfg2)
    assert (train_opts.step_size, train_opts.max_samples) == (0.01, 64)
    assert (eval_opts.step_size, eval_opts.max_samples) == (0.005, 256)

    # the NGP trainer trains on the former and evals on the latter
    from nerf_replication_tpu.train.ngp import NGPTrainer

    trainer = NGPTrainer(cfg2, network)
    assert trainer.march == train_opts
    assert trainer.eval_march == eval_opts

    # the Renderer's accelerated path only serves eval: it must pick the
    # eval budget
    r = make_renderer(cfg2, network)
    assert r.march_options == eval_opts


def test_march_executable_cache_is_bounded(tmp_path, setup):
    """Per-frame-varying (near, far) must not grow the compiled-executable
    cache without bound (VERDICT r1 weak #5): the LRU cap holds and the
    most-recently-used entries survive."""
    cfg, network, params = setup
    renderer = make_renderer(cfg, network)
    grid = bake_occupancy_grid(params, network, cfg)
    path = str(tmp_path / "grid_lru.npz")
    save_occupancy_grid(path, grid, cfg.train_dataset.scene_bbox, 0.5)
    assert renderer.load_occupancy_grid(path)

    rays = jnp.asarray(
        np.concatenate(
            [np.tile([0.0, 0.0, 4.0], (8, 1)),
             np.tile([0.0, 0.0, -1.0], (8, 1))], -1
        ).astype(np.float32)
    )
    cap = renderer._march_fns_cap
    for k in range(cap + 4):  # more distinct bounds than the cap
        near = 2.0 + 0.01 * k
        renderer.render_accelerated(
            params, {"rays": rays, "near": near, "far": 6.0}
        )
        assert len(renderer._march_fns) <= cap
    # most recent entry is retained (LRU, not clear-on-full); the key
    # carries march_options so budget changes can't hit stale executables
    assert (
        1, 8, 2.0 + 0.01 * (cap + 3), 6.0, renderer.march_options
    ) in renderer._march_fns


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device CPU mesh")
def test_sequence_parallel_march_matches_single_device(setup):
    """The sharded ESS+ERT march (replicated grid, ray axis over the data
    axis, in-shard chunking) must reproduce the single-device march."""
    from nerf_replication_tpu.parallel.mesh import make_mesh
    from nerf_replication_tpu.parallel.sequence import (
        build_sequence_parallel_march,
    )

    cfg, network, params = setup
    bbox = jnp.asarray(cfg.train_dataset.scene_bbox, jnp.float32)
    rng = np.random.default_rng(5)
    grid = jnp.asarray(rng.random((16, 16, 16)) < 0.3)
    opt = MarchOptions(
        step_size=0.25, transmittance_threshold=1e-4, max_samples=16,
        white_bkgd=True, chunk_size=64,
    )

    n = 37  # deliberately non-divisible by 8 shards
    origins = np.tile([0.0, 0.0, 4.0], (n, 1)) + rng.normal(0, 0.1, (n, 3))
    dirs = np.array([0.0, 0.0, -1.0]) + rng.normal(0, 0.15, (n, 3))
    rays = jnp.asarray(np.concatenate([origins, dirs], -1).astype(np.float32))

    apply_fn = lambda p, v, model: network.apply(params, p, v, model=model)  # noqa: E731
    ref = march_rays_accelerated(apply_fn, rays, 2.0, 6.0, grid, bbox, opt)

    mesh = make_mesh(model_axis=1)
    march = build_sequence_parallel_march(
        mesh, network, opt, 2.0, 6.0, chunk_size=3
    )
    out = march(params, rays, grid, bbox)
    for k in ("rgb_map_f", "depth_map_f", "acc_map_f"):
        np.testing.assert_allclose(
            np.asarray(out[k]), np.asarray(ref[k]), rtol=2e-5, atol=1e-5
        )
    # pad rows are sliced off before the sum, so the sharded diagnostic
    # equals the single-device per-ray count exactly
    assert int(out["n_truncated"]) == int(jnp.sum(ref["truncated"]))


def test_accelerated_march_rejects_time_conditioned_rays(tmp_path, setup):
    """An occupancy grid is a static-geometry bake: marching 7-column
    (time-conditioned) rays against it would skip space that is empty in
    one frame and occupied in another — the march must refuse loudly and
    point at the chunked volume path."""
    cfg, network, params = setup
    renderer = make_renderer(cfg, network)
    grid = bake_occupancy_grid(params, network, cfg)
    path = str(tmp_path / "grid_t.npz")
    save_occupancy_grid(path, grid, cfg.train_dataset.scene_bbox, 0.5)
    assert renderer.load_occupancy_grid(path)

    rays7 = jnp.asarray(
        np.concatenate(
            [np.tile([0.0, 0.0, 4.0], (8, 1)),
             np.tile([0.0, 0.0, -1.0], (8, 1)),
             np.zeros((8, 1))], -1
        ).astype(np.float32)
    )
    with pytest.raises(ValueError, match="static"):
        renderer.render_accelerated(
            params, {"rays": rays7, "near": 2.0, "far": 6.0}
        )
